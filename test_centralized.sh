#!/usr/bin/env bash
# E2E test: centralized fleet (capability of the reference's
# test_centralized.sh — build, FIFO-driven manager, N dumb agents, warmup,
# task dispatch, CSV + summary harvest including avg task latency).
#
# Usage: ./test_centralized.sh [NUM_AGENTS] [DURATION_SECS]
# Env:   MAPD_SOLVER=cpu|tpu  (tpu additionally launches the JAX solverd)
set -u

NUM_AGENTS=${1:-3}
DURATION=${2:-60}
PORT=${MAPD_BUS_PORT:-7422}
SOLVER=${MAPD_SOLVER:-cpu}
ROOT="$(cd "$(dirname "$0")" && pwd)"
BUILD="$ROOT/cpp/build"
OUT="$ROOT/results/centralized_$(date +%Y%m%d_%H%M%S)"
mkdir -p "$OUT"

cmake -S "$ROOT/cpp" -B "$BUILD" -G Ninja >/dev/null
ninja -C "$BUILD" >/dev/null || { echo "build failed"; exit 1; }

PIDS=()
cleanup() {
  for p in "${PIDS[@]:-}"; do kill "$p" 2>/dev/null; done
  wait 2>/dev/null
}
trap cleanup EXIT

"$BUILD/mapd_bus" "$PORT" >"$OUT/bus.log" 2>&1 &
PIDS+=($!)
sleep 0.3

if [ "$SOLVER" = "tpu" ]; then
  echo "🧮 launching solverd (JAX)..."
  PYTHONPATH="$ROOT" python -m p2p_distributed_tswap_tpu.runtime.solverd \
    --port "$PORT" >"$OUT/solverd.log" 2>&1 &
  PIDS+=($!)
  sleep 10   # accelerator init + first-compile headroom
fi

FIFO="$OUT/mgr_in"
mkfifo "$FIFO"
TASK_CSV_PATH="$OUT/task_metrics.csv" PATH_CSV_PATH="$OUT/path_metrics.csv" \
  "$BUILD/mapd_manager_centralized" --port "$PORT" --solver "$SOLVER" \
  >"$OUT/manager.log" 2>&1 <"$FIFO" &
MGR_PID=$!
PIDS+=($MGR_PID)
exec 3>"$FIFO"
sleep 0.5

for i in $(seq 1 "$NUM_AGENTS"); do
  "$BUILD/mapd_agent_centralized" --port "$PORT" --seed "$i" \
    >"$OUT/agent_$i.log" 2>&1 &
  PIDS+=($!)
  sleep 0.2
done

WARMUP=$((5 + NUM_AGENTS / 5))
echo "⏳ warmup ${WARMUP}s..."
sleep "$WARMUP"

echo "🚀 dispatching tasks for ${DURATION}s..."
echo "tasks $NUM_AGENTS" >&3
END=$(($(date +%s) + DURATION))
while [ "$(date +%s)" -lt "$END" ]; do
  echo "task" >&3
  sleep 2
done

echo "metrics" >&3
sleep 1
echo "quit" >&3
exec 3>&-
for _ in $(seq 1 10); do kill -0 $MGR_PID 2>/dev/null || break; sleep 1; done

SUMMARY="$OUT/test_summary.txt"
{
  echo "test: centralized solver=$SOLVER agents=$NUM_AGENTS duration=${DURATION}s"
  if [ -f "$OUT/task_metrics.csv" ]; then
    COMPLETED=$(awk -F, 'NR>1 && $10=="completed"' "$OUT/task_metrics.csv" | wc -l)
    TOTAL=$(awk 'NR>1' "$OUT/task_metrics.csv" | wc -l)
    echo "tasks_completed: $COMPLETED / $TOTAL"
    echo "throughput_tasks_per_sec: $(awk -v c="$COMPLETED" -v d="$DURATION" 'BEGIN{printf "%.3f", c/d}')"
    awk -F, 'NR>1 && $7!="" {s+=$7; n++} END{if(n) printf "avg_task_latency_s: %.2f\n", s/n/1000}' "$OUT/task_metrics.csv"
  fi
  if [ -f "$OUT/path_metrics.csv" ]; then
    awk -F, 'NR>1 {s+=$2; n++} END{if(n) printf "avg_plan_time_ms: %.3f (n=%d)\n", s/n/1000, n}' "$OUT/path_metrics.csv"
  fi
} | tee "$SUMMARY"
echo "📁 results in $OUT"
