#!/usr/bin/env python
"""CI mesh-solverd smoke (ISSUE 13), SLO-gate pattern: both halves run
every time.

1. **mesh == flat digest gate** (in-process, no C++ needed): the same
   packed request stream through a flat and a 2-way virtual-mesh
   TickRunner must produce byte-identical responses and equal
   mirror/device/fields audit digests every tick — and with
   JG_SOLVER_MESH unset the resolved service must be the flat path
   (``service.mesh is None``), pinning the kill-switch contract.
2. **live fleet through a mesh solverd** (skipped without the C++
   runtime): busd + the C++ centralized manager --solver tpu + solverd
   --mesh 2 on virtual CPU devices; every dispatched task must
   complete, and the solverd log must show the mesh banner.

Exit 0 = both halves green (or the live half explicitly SKIPPED).
"""

from __future__ import annotations

import os
import shutil
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from p2p_distributed_tswap_tpu.parallel.virtual_mesh import (  # noqa: E402
    force_virtual_cpu_devices)

force_virtual_cpu_devices(2)

import numpy as np  # noqa: E402


def digest_gate() -> None:
    from p2p_distributed_tswap_tpu.core.grid import Grid
    from p2p_distributed_tswap_tpu.obs import audit as au
    from p2p_distributed_tswap_tpu.parallel.solver_mesh import (
        SolverMesh, mesh_spec_from_env)
    from p2p_distributed_tswap_tpu.runtime import plan_codec as pc
    from p2p_distributed_tswap_tpu.runtime.solverd import (PlanService,
                                                           TickRunner)

    leaked = os.environ.get("JG_SOLVER_MESH")
    assert not leaked, \
        f"JG_SOLVER_MESH={leaked!r} leaked into the smoke env"
    # the kill-switch pin: an unset env resolves to NO mesh
    assert mesh_spec_from_env(leaked) is None
    grid = Grid.from_ascii("\n".join(["." * 16] * 16) + "\n")
    flat_svc = PlanService(grid, capacity_min=4)
    assert flat_svc.mesh is None  # unset env = the flat path, pinned
    flat_svc.defer_fields = False
    mesh_svc = PlanService(grid, capacity_min=4, mesh=SolverMesh(2))
    mesh_svc.defer_fields = False
    flat, mesh = TickRunner(flat_svc, grid), TickRunner(mesh_svc, grid)
    enc_f = pc.PackedFleetEncoder(snapshot_every=4)
    enc_m = pc.PackedFleetEncoder(snapshot_every=4)

    rng = np.random.default_rng(3)
    free = np.flatnonzero(np.asarray(grid.free).reshape(-1)).astype(int)
    cells = rng.choice(free, size=12, replace=False)
    fleet = {f"p{k}": [int(cells[k]), int(cells[6 + k])] for k in range(6)}

    for seq in range(1, 7):
        items = [(n, p, g) for n, (p, g) in sorted(fleet.items())]

        def req(enc):
            return {"type": "plan_request", "seq": seq,
                    "codec": pc.CODEC_NAME, "caps": [pc.CODEC_NAME],
                    "data": pc.encode_b64(enc.encode_tick(seq, items))}

        rf, rm = flat.handle(req(enc_f)), mesh.handle(req(enc_m))
        assert rm["data"] == rf["data"], f"wire diverged at seq {seq}"
        df = (au.lane_digest(*flat_svc.audit_views("mirror")),
              au.lane_digest(*flat_svc.audit_views("device")))
        dm = (au.lane_digest(*mesh_svc.audit_views("mirror")),
              au.lane_digest(*mesh_svc.audit_views("device")))
        assert df == dm, f"audit digests diverged at seq {seq}"
        rp = pc.decode_b64(rf["data"])
        for lane, c, g in zip(rp.idx, rp.pos, rp.goal):
            fleet[flat.packed.name_of(int(lane))] = [int(c), int(g)]
        fleet[f"p{int(rng.integers(6))}"][1] = int(rng.choice(free))
    per = mesh_svc.resident_shard_bytes()
    assert len(per) == 2, per
    print(f"mesh smoke: digest gate OK (6 ticks byte-identical, "
          f"per-shard bytes {sorted(per.values())})", flush=True)


def live_gate(log_dir: str) -> bool:
    if not (ROOT / "cpp" / "build" / "mapd_bus").exists() \
            and (shutil.which("cmake") is None
                 or shutil.which("ninja") is None):
        print("mesh smoke: live half SKIPPED (no C++ runtime)",
              flush=True)
        return True
    from p2p_distributed_tswap_tpu.runtime.fleet import Fleet

    mapf = Path(log_dir) / "t12.map.txt"
    mapf.parent.mkdir(parents=True, exist_ok=True)
    mapf.write_text("\n".join(["." * 12] * 12) + "\n")
    with Fleet("centralized", num_agents=2, port=7491,
               map_file=str(mapf), solver="tpu", log_dir=log_dir,
               solverd_args=["--cpu", "--mesh", "2"]) as fleet:
        time.sleep(4)
        fleet.command("tasks 2")
        deadline = time.monotonic() + 90
        done = 0
        while time.monotonic() < deadline:
            done = sum(f.read_text(errors="ignore").count("DONE")
                       for f in Path(log_dir).glob("agent_*.log"))
            if done >= 2:
                break
            time.sleep(1)
        fleet.quit()
    solverd_log = (Path(log_dir) / "solverd.log").read_text(
        errors="ignore")
    assert "mesh=2x1" in solverd_log, "solverd did not build the mesh"
    assert done >= 2, "live mesh fleet did not complete its tasks"
    print("mesh smoke: live half OK (2 tasks completed through a "
          "2-way mesh solverd)", flush=True)
    return True


def main(argv=None) -> int:
    log_dir = "/tmp/jg_mesh_smoke_logs"
    if argv and len(argv) >= 2 and argv[0] == "--log-dir":
        log_dir = argv[1]
    digest_gate()
    live_gate(log_dir)
    print("mesh smoke OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
