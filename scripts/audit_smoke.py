"""Audit-plane CI smoke (ISSUE 10, scripts/ci.sh) — the SLO-gate
discipline applied to state consistency:

1. **clean gate** — a tiny live fleet (busd + C++ centralized manager
   --solver tpu + solverd + wire-faithful sim pool) runs tasks under
   fast digest beacons; the auditor must observe beacons from every
   stateful role, join manager↔solverd watermarks, and end with ZERO
   confirmed divergences (exit 1 otherwise — a fleet that cannot prove
   itself consistent fails CI);
2. **corruption drill** — the run then flips one device lane via the
   ``audit_corrupt`` test hook and the SAME auditor must (a) confirm a
   roster divergence within the detection budget and (b) bisect it to
   the EXACT injected lane and field via the drill protocol.  A gate
   that cannot trip is no gate: both halves run every time.

Usage:  JAX_PLATFORMS=cpu python scripts/audit_smoke.py
        [--agents 4] [--side 16] [--log-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.obs import audit as au  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=4)
    ap.add_argument("--side", type=int, default=16)
    ap.add_argument("--log-dir", default=None)
    ap.add_argument("--clean-window", type=float, default=6.0,
                    help="seconds the clean gate observes the fleet")
    ap.add_argument("--detect-budget", type=float, default=15.0,
                    help="corruption -> confirmed-roster budget (s)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write a JSON artifact (clean-gate stats + "
                         "detection latency + drill cost) — the bench "
                         "audit axis parses it")
    args = ap.parse_args()

    from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient
    from p2p_distributed_tswap_tpu.runtime.fleet import (
        BUILD_DIR, ensure_built, wait_for_log)
    from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool

    ensure_built()
    log_dir = Path(args.log_dir or tempfile.mkdtemp(prefix="jg_audit_ci_"))
    log_dir.mkdir(parents=True, exist_ok=True)
    mapf = log_dir / "smoke.map.txt"
    mapf.write_text("\n".join(["." * args.side] * args.side) + "\n")

    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {**os.environ, "JG_AUDIT_TEST_HOOKS": "1",
           "JG_AUDIT_INTERVAL_MS": "400", "JG_AUDIT_INTERVAL_S": "0.4"}
    procs = []
    pool = None
    try:
        bus = subprocess.Popen([str(BUILD_DIR / "mapd_bus"), str(port)],
                               stdout=subprocess.DEVNULL)
        procs.append(bus)
        time.sleep(0.3)
        sd_log = log_dir / "solverd.log"
        # --warm: first-use JAX compiles (capacity-16 step program, field
        # chunk programs) stall solverd's loop for seconds on a small
        # host; uncompiled they land inside the clean-gate window and
        # read as a `silent` divergence of the beacon — warm them out
        # before the gate starts instead of widening the gate
        sd = subprocess.Popen(
            [sys.executable, "-m",
             "p2p_distributed_tswap_tpu.runtime.solverd",
             "--port", str(port), "--cpu", "--map", str(mapf),
             "--warm", str(max(args.agents, 4))],
            stdout=open(sd_log, "w"), stderr=subprocess.STDOUT, env=env)
        procs.append(sd)
        if not wait_for_log(sd_log, "solverd up", 120, proc=sd):
            print("audit smoke: solverd never came up", file=sys.stderr)
            return 1
        mgr = subprocess.Popen(
            [str(BUILD_DIR / "mapd_manager_centralized"),
             "--port", str(port), "--map", str(mapf), "--solver", "tpu",
             "--planning-interval-ms", "250"],
            stdin=subprocess.PIPE, stdout=subprocess.DEVNULL, env=env)
        procs.append(mgr)
        time.sleep(0.5)

        pool = SimAgentPool(args.agents, args.side, port=port, seed=11)
        pool.heartbeat_all()
        pool.pump(1.5)
        mgr.stdin.write(f"tasks {args.agents}\n".encode())
        mgr.stdin.flush()
        deadline = time.monotonic() + 45
        while pool.adopted < args.agents and time.monotonic() < deadline:
            pool.pump(0.5)
        if pool.adopted < args.agents:
            print(f"audit smoke: tasks never adopted ({pool.stats()})",
                  file=sys.stderr)
            return 1

        cli = BusClient(port=port, peer_id="audit-smoke")
        cli.subscribe(au.AUDIT_TOPIC, raw=True)
        joiner = au.AuditJoiner(
            record_path=str(log_dir / "auditor.audit.jsonl"))

        def pump(seconds: float) -> list:
            end = time.monotonic() + seconds
            confirmed = []
            while time.monotonic() < end:
                pool.pump(0.15)
                f = cli.recv(timeout=0.2)
                if f and f.get("op") == "msg":
                    joiner.ingest(f.get("data") or {})
                confirmed += joiner.evaluate()
            return confirmed

        # ---- 1. clean gate -------------------------------------------
        confirmed = pump(args.clean_window)
        st = joiner.status()
        procs_seen = {e["proc"] for e in st["epochs"].values()}
        red = [d for d in confirmed if d["class"] in au.RED_CLASSES]
        if red or st["verdict"] == "red":
            print(f"audit smoke FAIL: red divergence in a healthy "
                  f"fleet: {red or st['active']}", file=sys.stderr)
            return 1
        if confirmed:
            # amber (view/epoch) advisories can surface on a healthy
            # fleet's propagation windows — visible, not gating
            print(f"audit smoke note: amber advisories: "
                  f"{[d['class'] for d in confirmed]}")
        if st["joins"] < 1 or not {"manager_centralized",
                                   "solverd"} <= procs_seen:
            print(f"audit smoke FAIL: no digest joins "
                  f"(beacons={st['beacons']}, joins={st['joins']}, "
                  f"procs={sorted(procs_seen)})", file=sys.stderr)
            return 1
        print(f"audit clean gate OK: {st['peers']} peer(s), "
              f"{st['joins']} join(s), verdict {st['verdict']}")

        # ---- 2. the drill must trip ----------------------------------
        t0 = time.monotonic()
        cli.publish(au.AUDIT_TOPIC, {"type": "audit_corrupt", "lane": 1,
                                     "field": "goal", "delta": 1,
                                     "view": "both"}, raw=True)
        confirmed = []
        while not any(d["class"] == "roster" for d in confirmed):
            if time.monotonic() - t0 > args.detect_budget:
                print(f"audit smoke FAIL: corruption not confirmed "
                      f"within {args.detect_budget}s "
                      f"({joiner.status()})", file=sys.stderr)
                return 1
            confirmed += pump(0.4)
        detect_s = time.monotonic() - t0
        driller = au.AuditDriller(bus=cli, timeout=5.0)
        res = driller.drill_lanes("manager_centralized", "shadow",
                                  "solverd", "mirror", span=1 << 10)
        goal_f = [f for f in res.get("findings") or []
                  if f["field"] == "goal" and f["lane"] == 1]
        if len(goal_f) != 1:
            print(f"audit smoke FAIL: drill did not localize lane 1 "
                  f"goal: {res}", file=sys.stderr)
            return 1
        print(f"audit drill OK: confirmed in {detect_s:.1f}s, "
              f"{res['requests']} drill request(s) -> "
              + au.render_finding(goal_f[0], width=args.side,
                                  side_a="manager", side_b="solverd"))
        if args.out:
            # the FLEET's digest interval (set via env for the spawned
            # processes) — au.interval_s() here would read this
            # process's default and misstate detect_intervals
            interval = float(env["JG_AUDIT_INTERVAL_S"])
            with open(args.out, "w") as f:
                json.dump({
                    "agents": args.agents, "side": args.side,
                    "interval_s": interval,
                    "clean": {"peers": st["peers"], "joins": st["joins"],
                              "beacons": st["beacons"],
                              "verdict": st["verdict"]},
                    "drill": {
                        "detect_s": round(detect_s, 3),
                        "detect_intervals": round(detect_s / interval, 2),
                        "requests": res["requests"],
                        "elapsed_s": res.get("elapsed_s"),
                        "finding": goal_f[0],
                    },
                }, f, indent=2)
        cli.close()
        return 0
    finally:
        if pool is not None:
            pool.close()
        for p in reversed(procs):
            p.terminate()


if __name__ == "__main__":
    sys.exit(main())
