#!/usr/bin/env python
"""Field-repair fuzz gate (scripts/ci.sh, ISSUE 9): random obstacle
toggle sequences through ops/field_repair.py must stay BIT-IDENTICAL to
a full recompute — distances AND derived direction codes — across
chained repairs (each event repairs the previous event's output, so any
drift compounds and trips).  Covers the targeted edges too: the
ROI-overflow fallback (must return None, never a wrong field), the
freed-door long-range decrease (window growth), and multi-cluster
batches (a wall reopening far from where one closes).

Runs in ~30 s on the CPU backend; scripts/ci.sh invokes it next to the
codec fuzz gate.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.ops import field_repair  # noqa: E402
from p2p_distributed_tswap_tpu.ops.distance import (  # noqa: E402
    distance_fields,
    directions_from_distance,
)


def _full(free_np, goal):
    d = distance_fields(jnp.asarray(free_np),
                        jnp.asarray([goal], np.int32))
    # writable copies: the fuzz loop patches the dirs band in place
    return (np.array(d)[0],
            np.array(directions_from_distance(
                d, jnp.asarray(free_np)))[0])


def fuzz_seed(seed: int, events: int) -> int:
    """One chained toggle sequence on one random world; returns the
    number of exact (non-fallback) repairs."""
    rng = np.random.default_rng(seed)
    kind = seed % 3
    if kind == 0:
        free = rng.random((24, 24)) > 0.25
    elif kind == 1:
        free = np.asarray(Grid.warehouse(32, 32).free).copy()
    else:
        free = np.ones((16, 48), np.bool_)
    h, w = free.shape
    flat = free.reshape(-1)
    goal = int(rng.choice(np.flatnonzero(flat)))
    dist, dirs = _full(free, goal)
    repaired = 0
    prev_batch: list = []
    for _ in range(events):
        # sliding batches: reopen the previous cells AND close fresh
        # ones in one update — the multi-cluster shape
        toggles = list(prev_batch)
        fresh = [int(c) for c in rng.integers(0, h * w, size=3)
                 if c != goal and flat[c]][:2]
        toggles += fresh
        for c in prev_batch:
            flat[c] = True  # reopen
        for c in fresh:
            flat[c] = False
        prev_batch = fresh
        res = field_repair.repair_field(dist, free, toggles)
        ref_d, ref_dirs = _full(free, goal)
        if res is None:
            dist, dirs = ref_d, ref_dirs  # the caller's fallback
            continue
        new_dist, (y0, y1, x0, x1) = res
        assert np.array_equal(new_dist, ref_d), \
            f"seed {seed}: repaired distances diverged"
        b0, b1 = max(0, y0 - 1), min(h, y1 + 1)
        if b1 > b0:
            dirs[b0:b1] = field_repair.directions_np(new_dist, free,
                                                     b0, b1)
        assert np.array_equal(dirs, ref_dirs), \
            f"seed {seed}: band-derived directions diverged"
        assert np.array_equal(
            field_repair.pack_rows_np(dirs.reshape(-1)),
            field_repair.pack_rows_np(ref_dirs.reshape(-1)))
        dist = new_dist
        repaired += 1
    return repaired


def edge_cases() -> None:
    # ROI overflow must refuse, never mis-repair
    free = np.ones((16, 16), np.bool_)
    dist, _ = _full(free, 0)
    free[1, :] = False
    assert field_repair.repair_field(
        dist, free, [16 + x for x in range(16)], max_dirty=4) is None
    # freed door: far half re-routes through window growth, still exact
    free = np.ones((24, 24), np.bool_)
    free[:, 12] = False
    goal = 24 * 5 + 2
    dist, _ = _full(free, goal)
    free[8, 12] = True
    res = field_repair.repair_field(dist, free, [8 * 24 + 12],
                                    max_window=24 * 24)
    ref_d, _ = _full(free, goal)
    assert res is not None and np.array_equal(res[0], ref_d), \
        "freed-door growth diverged"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=4)
    ap.add_argument("--events", type=int, default=5)
    args = ap.parse_args()
    t0 = time.perf_counter()
    total = 0
    for seed in range(args.seeds):
        total += fuzz_seed(seed, args.events)
    assert total > 0, "no toggle event exercised the exact-repair path"
    edge_cases()
    print(f"field-repair fuzz gate OK: {args.seeds} seeds x "
          f"{args.events} chained events, {total} exact repairs, "
          f"overflow + freed-door edges, {time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
