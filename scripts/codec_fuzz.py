"""Codec fuzz gate (scripts/ci.sh): random fleet evolutions through BOTH
plan codecs must yield identical decoded plans.

Four properties per seed:
1. wire fuzz — random fleet scripts (joins/leaves/moves/goal churn)
   through PackedFleetEncoder -> bytes -> PackedStateDecoder reconstruct
   the exact fleet state every tick;
2. golden fuzz — the native encoder (cpp/build/mapd_codec_golden, built
   on demand with bare g++) emits byte-identical packets for the same
   scripts (skipped with a warning when no C++ toolchain exists);
3. pos1 fuzz — random position beacons round-trip through the py pos1
   codec, the native encoder is byte-identical, the native decoder
   round-trips py bytes, and truncated/corrupted packets are rejected on
   both sides (ISSUE 4);
4. plan fuzz — a TickRunner fed packed deltas (device-resident state)
   returns the same moves as one fed legacy JSON full-fleet requests;
5. agg1 + shm fuzz (ISSUE 18) — random beacon aggregates round-trip the
   py agg1 codec byte-identical to the native one with malformed packets
   rejected on both sides, and random frame streams through a SHL1 ring
   stay FIFO-exact while corrupted lane headers are refused at attach.

Runs in ~30 s on the CPU backend; scripts/ci.sh invokes it before the
tier-1 suite.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402

from p2p_distributed_tswap_tpu.runtime import plan_codec as pc  # noqa: E402


def fleet_script(rng, ticks, grid_cells, start_agents):
    fleet = {}
    nid = 0
    for _ in range(start_agents):
        fleet[f"ag{nid:04d}"] = [int(rng.integers(grid_cells)),
                                 int(rng.integers(grid_cells))]
        nid += 1
    out = []
    for seq in range(1, ticks + 1):
        for name in list(fleet):
            if rng.random() < 0.5:
                fleet[name][0] = int(rng.integers(grid_cells))
            if rng.random() < 0.2:
                fleet[name][1] = int(rng.integers(grid_cells))
        if rng.random() < 0.3 and len(fleet) > 2:
            fleet.pop(sorted(fleet)[int(rng.integers(len(fleet)))])
        if rng.random() < 0.4:
            fleet[f"ag{nid:04d}"] = [int(rng.integers(grid_cells)),
                                     int(rng.integers(grid_cells))]
            nid += 1
        out.append((seq, [(n, p, g) for n, (p, g) in sorted(fleet.items())]))
    return out


def wire_fuzz(seed: int, ticks: int, snapshot_every: int) -> list:
    rng = np.random.default_rng(seed)
    # odd seeds run in the narrow (u16) regime, even seeds force wide i32
    cells = 4096 if seed % 2 else 1 << 17
    script = fleet_script(rng, ticks, grid_cells=cells,
                          start_agents=int(rng.integers(3, 20)))
    enc = pc.PackedFleetEncoder(snapshot_every=snapshot_every)
    dec = pc.PackedStateDecoder()
    lines = []
    for seq, fleet in script:
        pkt = enc.encode_tick(seq, fleet)
        # ~half the packets carry a trace1 context (ISSUE 5); ids stay
        # under 2^53 — the JSON wire (and the golden probe's JSON parse)
        # carries numbers as doubles
        trace = None
        if rng.random() < 0.5:
            trace = pc.TraceCtx(int(rng.integers(1, 1 << 52)),
                                int(rng.integers(0, 1 << 16)),
                                int(rng.integers(1, 1 << 44)))
            pkt.trace = trace
        b64 = pc.encode_b64(pkt)
        lines.append((seq, fleet, trace, b64))
        back = pc.decode_b64(b64)
        assert back.trace == trace, f"seed {seed} seq {seq}: trace diverged"
        dec.apply(back)
        got = {dec.name_of(k): list(v) for k, v in dec.state.items()}
        want = {n: [p, g] for n, p, g in fleet}
        assert got == want, f"seed {seed} seq {seq}: decoder diverged"
    return lines


def _golden_binary():
    from p2p_distributed_tswap_tpu.runtime.fleet import build_single_tu

    return build_single_tu("mapd_codec_golden",
                           "cpp/probes/codec_golden.cpp")


def pos1_fuzz(seed: int, count: int = 200) -> bool:
    """Random pos1 beacons: py round-trip, py<->cpp byte identity, and
    malformed-packet rejection.  Returns False when the golden binary is
    unavailable (pure-python checks still ran)."""
    rng = np.random.default_rng(seed)
    cases = []
    for _ in range(count):
        hi = 1 << 20 if rng.random() < 0.4 else 65536
        pos, goal = int(rng.integers(hi)), int(rng.integers(hi))
        task = int(rng.integers(1 << 40)) if rng.random() < 0.5 else None
        trace = None
        if rng.random() < 0.5:  # trace1 ext (ISSUE 5), ids under 2^53
            trace = pc.TraceCtx(int(rng.integers(1, 1 << 52)),
                                int(rng.integers(0, 1 << 16)),
                                int(rng.integers(1, 1 << 44)))
        cases.append((pos, goal, task, trace))
        blob = pc.encode_pos1(pos, goal, task, trace)
        assert pc.decode_pos1_full(blob) == (pos, goal, task, trace), \
            f"pos1 seed {seed}: py round-trip diverged"
        # truncation and magic corruption must raise, never mis-decode
        for bad in (blob[:-1], b"\xff" + blob[1:], blob + b"\x00"):
            try:
                pc.decode_pos1(bad)
            except pc.CodecError:
                continue
            raise AssertionError(f"pos1 seed {seed}: bad packet accepted")
    binary = _golden_binary()
    if binary is None:
        return False
    py_lines = [pc.encode_pos1_b64(p, g, t, tr) for p, g, t, tr in cases]
    feed = "\n".join(
        '{"pos":%d,"goal":%d%s%s}' % (
            p, g,
            ',"task":%d' % t if t is not None else "",
            "" if tr is None else
            ',"trace":[%d,%d,%d]' % (tr.trace_id, tr.hop, tr.send_ms))
        for p, g, t, tr in cases) + "\n"
    out = subprocess.run([str(binary), "--pos1-encode"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    assert out.stdout.split() == py_lines, \
        f"pos1 seed {seed}: cpp encoder bytes diverged"
    out = subprocess.run([str(binary), "--pos1-decode"],
                         input="\n".join(py_lines) + "\n",
                         capture_output=True, text=True, check=True,
                         timeout=120)
    import json as _json
    for (p, g, t, tr), line in zip(cases, out.stdout.splitlines()):
        d = _json.loads(line)
        assert (d["pos"], d["goal"], d["task"]) == (p, g, t), \
            f"pos1 seed {seed}: cpp decoder diverged"
        want_tr = None if tr is None else [tr.trace_id, tr.hop, tr.send_ms]
        assert d.get("trace") == want_tr, \
            f"pos1 seed {seed}: cpp trace decode diverged"
    return True


def world_fuzz(seed: int, count: int = 100) -> bool:
    """Random world1 toggle batches (ISSUE 9): py round-trip, py<->cpp
    byte identity (narrow + wide + trace1 composition), and decode_world
    rejection of non-world kinds.  Returns False when the golden binary
    is unavailable (pure-python checks still ran)."""
    rng = np.random.default_rng(seed)
    cases = []
    for k in range(count):
        hi = 1 << 20 if rng.random() < 0.4 else 65536  # wide vs narrow
        n = int(rng.integers(1, 12))
        cells = [int(c) for c in rng.integers(0, hi, size=n)]
        blocked = [int(b) for b in rng.integers(0, 2, size=n)]
        trace = None
        if rng.random() < 0.5:
            trace = pc.TraceCtx(int(rng.integers(1, 1 << 52)),
                                int(rng.integers(0, 1 << 16)),
                                int(rng.integers(1, 1 << 44)))
        pkt = pc.encode_world(k + 1, cells, blocked, trace=trace)
        b64 = pc.encode_b64(pkt)
        back = pc.decode_b64(b64)
        assert back.kind == pc.KIND_WORLD and back.seq == k + 1
        assert back.trace == trace, f"world seed {seed}: trace diverged"
        assert pc.decode_world(back) == \
            [(c, bool(b)) for c, b in zip(cells, blocked)], \
            f"world seed {seed}: round-trip diverged"
        cases.append((cells, blocked, trace, b64))
    try:
        pc.decode_world(pc.Packet(kind=pc.KIND_DELTA, seq=1))
        raise AssertionError("decode_world accepted a delta packet")
    except pc.CodecError:
        pass
    binary = _golden_binary()
    if binary is None:
        return False
    feed = "\n".join(
        '{"seq":%d,"cells":[%s],"blocked":[%s]%s}' % (
            k + 1, ",".join(map(str, cells)), ",".join(map(str, blocked)),
            "" if tr is None else
            ',"trace":[%d,%d,%d]' % (tr.trace_id, tr.hop, tr.send_ms))
        for k, (cells, blocked, tr, _) in enumerate(cases)) + "\n"
    out = subprocess.run([str(binary), "--world-encode"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    assert out.stdout.split() == [b64 for _, _, _, b64 in cases], \
        f"world seed {seed}: cpp encoder bytes diverged"
    return True


def audit_fuzz(seed: int, count: int = 80) -> bool:
    """Random audit-plane inputs (ISSUE 10): the py digest canon
    (lane/ledger/view/cells FNV-1a chains) and the audit1 beacon blob
    must be byte-identical py<->cpp, and malformed blobs must be
    rejected on both sides.  Returns False when the golden binary is
    unavailable (pure-python checks still ran)."""
    import json as _json

    from p2p_distributed_tswap_tpu.obs import audit as au

    rng = np.random.default_rng(seed)
    digest_cases = []  # (script line, py digest hex, py count)
    for _ in range(count):
        kind = int(rng.integers(4))
        if kind == 0:
            n = int(rng.integers(0, 20))
            lanes = rng.choice(1 << 16, size=n, replace=False).astype(int)
            pos = rng.integers(0, 1 << 20, size=n)
            goal = rng.integers(0, 1 << 20, size=n)
            d, c = au.lane_digest(lanes, pos, goal)
            line = _json.dumps({"lanes": [[int(l), int(p), int(g)]
                                          for l, p, g in
                                          zip(lanes, pos, goal)]})
        elif kind == 1:
            n = int(rng.integers(0, 20))
            tasks = [(int(rng.integers(1, 1 << 44)),
                      int(rng.integers(0, 3)),
                      int(rng.integers(-1, 1 << 20)),
                      int(rng.integers(-1, 1 << 20)))
                     for _ in range(n)]
            d, c = au.ledger_digest(tasks)
            line = _json.dumps({"ledger": [list(t) for t in tasks]})
        elif kind == 2:
            ids = [int(t) for t in rng.integers(1, 1 << 44,
                                                size=rng.integers(0, 30))]
            d, c = au.view_digest(ids)
            line = _json.dumps({"view": ids})
        else:
            cells = [int(t) for t in rng.integers(0, 1 << 20,
                                                  size=rng.integers(0, 30))]
            d, c = au.cells_digest(cells)
            line = _json.dumps({"cells": cells})
        digest_cases.append((line, au.digest_hex(d), c))

    blob_cases = []  # (entries, py b64)
    for _ in range(count // 2):
        entries = [au.AuditEntry(int(rng.integers(1, 7)),
                                 int(rng.integers(0, 1 << 31)),
                                 int(rng.integers(0, 1 << 44)),
                                 int(rng.integers(0, 1 << 31)),
                                 int(rng.integers(0, 1 << 64,
                                                  dtype=np.uint64)))
                   for _ in range(int(rng.integers(0, 7)))]
        b64 = au.encode_audit_b64(entries)
        assert au.decode_audit_b64(b64) == entries, \
            f"audit seed {seed}: py round-trip diverged"
        raw = au.encode_audit(entries)
        for bad in (raw[:-1], b"\xff" + raw[1:], raw + b"\x00", b""):
            try:
                au.decode_audit(bad)
            except au.AuditCodecError:
                continue
            raise AssertionError(f"audit seed {seed}: bad blob accepted")
        blob_cases.append((entries, b64))

    binary = _golden_binary()
    if binary is None:
        return False
    feed = "\n".join(line for line, _, _ in digest_cases) + "\n"
    out = subprocess.run([str(binary), "--audit-digest"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    for (line, hexd, c), got in zip(digest_cases, out.stdout.splitlines()):
        g = _json.loads(got)
        assert (g["digest"], g["count"]) == (hexd, c), \
            f"audit seed {seed}: cpp digest diverged on {line}"
    feed = "\n".join(
        _json.dumps({"entries": [[e.section, e.count, e.seq, e.epoch,
                                  au.digest_hex(e.digest)]
                                 for e in entries]})
        for entries, _ in blob_cases) + "\n"
    out = subprocess.run([str(binary), "--audit-encode"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    assert out.stdout.split() == [b64 for _, b64 in blob_cases], \
        f"audit seed {seed}: cpp audit1 encoder bytes diverged"
    out = subprocess.run([str(binary), "--audit-decode"],
                         input="\n".join(b64 for _, b64 in blob_cases)
                         + "\n",
                         capture_output=True, text=True, check=True,
                         timeout=120)
    for (entries, _), got in zip(blob_cases, out.stdout.splitlines()):
        g = _json.loads(got)
        want = [[e.section, e.count, e.seq, e.epoch,
                 au.digest_hex(e.digest)] for e in entries]
        assert g and g["entries"] == want, \
            f"audit seed {seed}: cpp audit1 decoder diverged"
    return True


def ledger_fuzz(seed: int, ticks: int = 24) -> bool:
    """Random replication streams (ISSUE 15 "ledger1"): py round-trip,
    the replica applies + digest-verifies every record, malformed blobs
    are rejected on both sides, and the native encoder emits
    byte-identical records for the same ledger evolution.  Returns
    False when the golden binary is unavailable (pure-python checks
    still ran)."""
    import json as _json

    from p2p_distributed_tswap_tpu.runtime import ha

    rng = np.random.default_rng(seed)
    enc = ha.LedgerEncoder(incarnation=int(rng.integers(1, 1 << 44)),
                           snapshot_every=3 + seed % 5)
    rep = ha.LedgerReplica()
    tasks = {}
    world = {}
    outbox = {}  # (dst, seq) -> HandoffOut: the unacked handoff view
    hseq = 0
    nid = 1
    script, py_out = [], []
    for tick in range(1, ticks + 1):
        # evolve the handoff outbox: sends and acks (ISSUE 15 — the
        # replicated retransmit state a promoted standby resumes)
        if rng.random() < 0.25:
            hseq += 1
            dst = int(rng.integers(0, 4))
            outbox[(dst, hseq)] = ha.HandoffOut(
                dst, hseq, int(rng.integers(1, 1 << 44)),
                f"hpeer{int(rng.integers(1, 9))}",
                int(rng.integers(0, 1 << 16)),
                int(rng.integers(0, 1 << 16)),
                int(rng.integers(0, 3)),
                int(rng.integers(1, 1 << 40))
                if rng.random() < 0.8 else None,
                int(rng.integers(0, 1 << 16)),
                int(rng.integers(0, 1 << 16)))
        if outbox and rng.random() < 0.3:
            outbox.pop(sorted(outbox)[int(rng.integers(len(outbox)))])
        # evolve the ledger: births, state moves, completions, toggles
        for _ in range(int(rng.integers(0, 3))):
            tasks[nid] = ha.LedgerTask(
                nid, int(rng.integers(0, 3)),
                int(rng.integers(0, 1 << 17)),
                int(rng.integers(0, 1 << 17)),
                f"peer{int(rng.integers(1, 9))}"
                if rng.random() < 0.7 else "")
            nid += 1
        for tid in list(tasks):
            r = rng.random()
            if r < 0.15:
                del tasks[tid]
            elif r < 0.4:
                t = tasks[tid]
                tasks[tid] = ha.LedgerTask(
                    tid, int(rng.integers(0, 3)), t.pickup, t.delivery,
                    t.peer)
        if rng.random() < 0.3:
            world[int(rng.integers(0, 1 << 16))] = int(rng.integers(0, 2))
        force = rng.random() < 0.1
        if force:
            enc.request_snapshot()
        # pending entries carry no peer on the real wire
        cur = [ha.LedgerTask(t.task_id, t.state, t.pickup, t.delivery,
                             "" if t.state == ha.TASK_PENDING else t.peer)
               for t in tasks.values()]
        script.append({"plan": tick, "world_seq": len(world),
                       "next": nid, "force_snapshot": force,
                       "tasks": [[t.task_id, t.state, t.pickup,
                                  t.delivery, t.peer] for t in cur],
                       "world": sorted([c, b] for c, b in world.items()),
                       "handoffs": [[h.dst, h.seq, h.epoch, h.peer,
                                     h.pos, h.goal, h.phase, h.task_id,
                                     h.pickup, h.delivery]
                                    for h in outbox.values()]})
        rec = enc.encode_tick(tick, len(world), nid, cur, world,
                              outbox.values())
        if rec is None:
            py_out.append("null")
            continue
        b64 = ha.encode_ledger_b64(rec)
        py_out.append(b64)
        back = ha.decode_ledger_b64(b64)
        assert ha.encode_ledger_b64(back) == b64, \
            f"ledger seed {seed} tick {tick}: py round-trip diverged"
        # the replica applies the stream and must digest-verify: the
        # record's full-ledger digests equal its own recomputation
        assert rep.apply(back) is True, \
            f"ledger seed {seed} tick {tick}: replica digest diverged"
        assert sorted(rep.tasks) == sorted(t.task_id for t in cur), \
            f"ledger seed {seed} tick {tick}: replica ledger diverged"
        raw = ha.encode_ledger(rec)
        for bad in (raw[:13], b"\xff" + raw[1:], raw + b"\x00",
                    raw[:-1], b""):
            try:
                ha.decode_ledger(bad)
            except ha.HaCodecError:
                continue
            raise AssertionError(
                f"ledger seed {seed} tick {tick}: bad blob accepted")
    binary = _golden_binary()
    if binary is None:
        return False
    script[0]["inc"] = enc.incarnation
    script[0]["snapshot_every"] = enc.snapshot_every
    feed = "\n".join(_json.dumps(line) for line in script) + "\n"
    out = subprocess.run([str(binary), "--ledger-encode"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    assert out.stdout.split() == py_out, \
        f"ledger seed {seed}: cpp encoder bytes diverged"
    # native decode round-trips py bytes; malformed b64 reads null
    real = [b for b in py_out if b != "null"]
    out = subprocess.run([str(binary), "--ledger-decode"],
                         input="\n".join(real + ["AAAA"]) + "\n",
                         capture_output=True, text=True, check=True,
                         timeout=120)
    lines = out.stdout.splitlines()
    assert lines[-1] == "null", \
        f"ledger seed {seed}: cpp accepted a malformed blob"
    for b64, got in zip(real, lines):
        g = _json.loads(got)
        back = ha.decode_ledger_b64(b64)
        assert g["seq"] == back.seq and g["snapshot"] == back.snapshot \
            and g["tasks"] == [[t.task_id, t.state, t.pickup, t.delivery,
                                t.peer] for t in back.tasks] \
            and g["removed"] == back.removed \
            and g["world"] == [list(w) for w in back.world] \
            and g["handoffs"] == [[h.dst, h.seq, h.epoch, h.peer, h.pos,
                                   h.goal, h.phase, h.task_id, h.pickup,
                                   h.delivery]
                                  for h in back.handoffs], \
            f"ledger seed {seed}: cpp decoder diverged"
    return True


def agg1_fuzz(seed: int, count: int = 80) -> bool:
    """Random agg1 beacon aggregates (ISSUE 18): py round-trip, py<->cpp
    byte identity (outer trace1 + inner blobs passed through VERBATIM),
    and malformed-packet rejection on both sides.  Returns False when
    the golden binary is unavailable (pure-python checks still ran)."""
    import base64 as _b64
    import json as _json

    rng = np.random.default_rng(seed)
    cases = []  # (entries, trace, py b64)
    for _ in range(count):
        entries = []
        for _k in range(int(rng.integers(0, 9))):
            name = f"ag{int(rng.integers(1 << 20)):x}"
            if rng.random() < 0.8:
                tr = None
                if rng.random() < 0.5:  # each sender's own trace1 block
                    tr = pc.TraceCtx(int(rng.integers(1, 1 << 52)),
                                     int(rng.integers(0, 1 << 16)),
                                     int(rng.integers(1, 1 << 44)))
                blob = pc.encode_pos1(
                    int(rng.integers(1 << 20)), int(rng.integers(1 << 20)),
                    int(rng.integers(1 << 40))
                    if rng.random() < 0.5 else None, tr)
            else:  # the aggregate never re-encodes: any bytes pass through
                blob = rng.integers(0, 256, size=int(rng.integers(0, 40)),
                                    dtype=np.uint8).tobytes()
            entries.append((name, blob))
        trace = None
        if rng.random() < 0.5:  # the aggregate's own span
            trace = pc.TraceCtx(int(rng.integers(1, 1 << 52)),
                                int(rng.integers(0, 1 << 16)),
                                int(rng.integers(1, 1 << 44)))
        b64 = pc.encode_agg1_b64(entries, trace)
        assert pc.decode_agg1_b64(b64) == (entries, trace), \
            f"agg1 seed {seed}: py round-trip diverged"
        raw = pc.encode_agg1(entries, trace)
        for bad in (raw[:-1], b"\xff" + raw[1:], raw + b"\x00",
                    raw[:4] + b"\x07" + raw[5:], b""):
            try:
                pc.decode_agg1(bad)
            except pc.CodecError:
                continue
            raise AssertionError(f"agg1 seed {seed}: bad packet accepted")
        cases.append((entries, trace, b64))
    binary = _golden_binary()
    if binary is None:
        return False
    feed = "\n".join(
        _json.dumps(dict(
            entries=[[n, _b64.b64encode(b).decode()] for n, b in entries],
            **({} if tr is None
               else {"trace": [tr.trace_id, tr.hop, tr.send_ms]})))
        for entries, tr, _ in cases) + "\n"
    out = subprocess.run([str(binary), "--agg1-encode"], input=feed,
                         capture_output=True, text=True, check=True,
                         timeout=120)
    assert out.stdout.split() == [b64 for _, _, b64 in cases], \
        f"agg1 seed {seed}: cpp encoder bytes diverged"
    out = subprocess.run([str(binary), "--agg1-decode"],
                         input="\n".join([b64 for _, _, b64 in cases]
                                         + ["AAAA"]) + "\n",
                         capture_output=True, text=True, check=True,
                         timeout=120)
    lines = out.stdout.splitlines()
    assert lines[-1] == "null", \
        f"agg1 seed {seed}: cpp accepted a malformed blob"
    for (entries, tr, _), got in zip(cases, lines):
        g = _json.loads(got)
        want = [[n, _b64.b64encode(b).decode()] for n, b in entries]
        want_tr = None if tr is None else [tr.trace_id, tr.hop, tr.send_ms]
        assert g["entries"] == want and g.get("trace") == want_tr, \
            f"agg1 seed {seed}: cpp decoder diverged"
    return True


def shm_fuzz(seed: int, steps: int = 400) -> None:
    """shm-lane handshake fuzz (ISSUE 18): random frame streams through
    a SHL1 ring stay FIFO-exact in BOTH directions under arbitrary
    push/pop interleavings, a detached lane refuses every send, and a
    corrupted lane header must be rejected by attach_lane — never a
    crash or a half-attach of the hub."""
    import tempfile

    from p2p_distributed_tswap_tpu.runtime import shmlane

    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory(prefix="jg_shmfuzz_") as td:
        lane_path = Path(td) / "fuzz.shl"
        client = shmlane.create_lane(lane_path, slot_size=256, nslots=16)
        hub = shmlane.attach_lane(lane_path)
        tx = {"c2s": client, "s2c": hub}
        rx = {"c2s": hub, "s2c": client}
        flights = {"c2s": [], "s2c": []}
        for _ in range(steps):
            d = "c2s" if rng.random() < 0.5 else "s2c"
            if rng.random() < 0.6:
                frame = rng.integers(0, 256,
                                     size=int(rng.integers(1, 200)),
                                     dtype=np.uint8).tobytes()
                if tx[d].send(frame):
                    flights[d].append(frame)
                else:  # full ring is the TCP-fallback signal, never a drop
                    assert len(flights[d]) >= client.nslots - 1, \
                        f"shm seed {seed}: ring refused below capacity"
            else:
                got = rx[d].recv()
                if flights[d]:
                    assert got == flights[d].pop(0), \
                        f"shm seed {seed}: ring reordered frames"
                else:
                    assert got is None
        for d in ("c2s", "s2c"):
            while flights[d]:
                assert rx[d].recv() == flights[d].pop(0), \
                    f"shm seed {seed}: drain reordered frames"
            assert rx[d].recv() is None
        hub.detach()
        assert client.send(b"x") is False and hub.send(b"x") is False, \
            f"shm seed {seed}: detached lane accepted a frame"
        good = lane_path.read_bytes()
        hub.close()
        client.close(unlink=True)

        bad_path = Path(td) / "bad.shl"
        muts = [good[:100],                              # below header
                b"\x00\x00\x00\x00" + good[4:],          # bad magic
                good[:4] + b"\x63\x00" + good[6:],       # version 99
                good[:8] + b"\x00\x00\x00\x00" + good[12:],   # slot 0
                good[:12] + b"\x03\x00\x00\x00" + good[16:],  # nslots !pow2
                good[:5000]]                             # < geometry
        for off in rng.integers(0, 6, size=4):           # magic/version
            off = int(off)
            flip = bytes([good[off] ^ 0xFF])
            muts.append(good[:off] + flip + good[off + 1:])
        for mut in muts:
            bad_path.write_bytes(mut)
            try:
                shmlane.attach_lane(bad_path)
            except shmlane.LaneError:
                continue
            raise AssertionError(
                f"shm seed {seed}: malformed lane header attached")


def golden_fuzz(lines_by_seed: dict) -> bool:
    binary = _golden_binary()
    if binary is None:
        return False
    for seed, (snapshot_every, lines) in lines_by_seed.items():
        feed = "\n".join(
            '{"seq":%d,"snapshot_every":%d,"fleet":[%s]%s}' % (
                seq, snapshot_every,
                ",".join('["%s",%d,%d]' % (n, p, g) for n, p, g in fleet),
                "" if trace is None else
                ',"trace":[%d,%d,%d]' % (trace.trace_id, trace.hop,
                                         trace.send_ms))
            for seq, fleet, trace, _ in lines) + "\n"
        out = subprocess.run([str(binary), "--encode"], input=feed,
                             capture_output=True, text=True, check=True,
                             timeout=120)
        cpp = out.stdout.split()
        py = [b64 for _, _, _, b64 in lines]
        assert cpp == py, f"seed {seed}: cpp encoder bytes diverged"
    return True


def plan_fuzz(seed: int, ticks: int) -> None:
    from p2p_distributed_tswap_tpu.core.grid import Grid
    from p2p_distributed_tswap_tpu.runtime.solverd import (
        PlanService, TickRunner)

    grid = Grid.default()
    w = grid.width
    rng = np.random.default_rng(seed)
    free = np.flatnonzero(np.asarray(grid.free).reshape(-1)).astype(int)
    n = int(rng.integers(4, 10))
    cells = rng.choice(free, size=2 * n, replace=False)
    fleet = {f"p{k}": [int(cells[k]), int(cells[n + k])] for k in range(n)}
    run_j = TickRunner(PlanService(grid, capacity_min=4), grid)
    run_p = TickRunner(PlanService(grid, capacity_min=4), grid)
    run_p.service.defer_fields = False  # step equivalence needs inline rows
    enc = pc.PackedFleetEncoder(snapshot_every=5)
    for seq in range(1, ticks + 1):
        items = [(nm, p, g) for nm, (p, g) in sorted(fleet.items())]
        resp_j = run_j.handle({"type": "plan_request", "seq": seq,
                               "agents": [{"peer_id": nm,
                                           "pos": [p % w, p // w],
                                           "goal": [g % w, g // w]}
                                          for nm, p, g in items]})
        resp_p = run_p.handle({"type": "plan_request", "seq": seq,
                               "codec": pc.CODEC_NAME,
                               "caps": [pc.CODEC_NAME],
                               "data": pc.encode_b64(
                                   enc.encode_tick(seq, items))})
        jm = {m["peer_id"]: (m["next_pos"], m["goal"])
              for m in resp_j["moves"]}
        rp = pc.decode_b64(resp_p["data"])
        pm = {run_p.packed.name_of(int(l)):
              ([int(c) % w, int(c) // w], [int(g) % w, int(g) // w])
              for l, c, g in zip(rp.idx, rp.pos, rp.goal)}
        for nm, p, g in items:
            want = pm.get(nm, ([p % w, p // w], [g % w, g // w]))
            assert jm[nm] == want, \
                f"seed {seed} seq {seq} {nm}: plans diverged"
        for m in resp_j["moves"]:
            x, y = m["next_pos"]
            gx, gy = m["goal"]
            fleet[m["peer_id"]] = [y * w + x, gy * w + gx]
        if rng.random() < 0.5:
            k = sorted(fleet)[int(rng.integers(len(fleet)))]
            fleet[k][1] = int(rng.choice(free))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=4)
    ap.add_argument("--ticks", type=int, default=10)
    ap.add_argument("--skip-plans", action="store_true",
                    help="wire/golden fuzz only (no jax import)")
    args = ap.parse_args()

    lines_by_seed = {}
    for seed in range(args.seeds):
        snapshot_every = 3 + seed % 6
        lines_by_seed[seed] = (snapshot_every,
                               wire_fuzz(seed, args.ticks, snapshot_every))
    print(f"wire fuzz: {args.seeds} seeds x {args.ticks} ticks OK")
    if golden_fuzz(lines_by_seed):
        print("golden fuzz: cpp encoder byte-identical")
    else:
        print("golden fuzz: SKIPPED (no g++/binary)", file=sys.stderr)
    pos1_native = all([pos1_fuzz(seed) for seed in range(args.seeds)])
    if pos1_native:
        print(f"pos1 fuzz: {args.seeds} seeds round-trip, cpp "
              "byte-identical, malformed rejected")
    else:
        print("pos1 fuzz: py round-trip OK; cpp SKIPPED (no g++/binary)",
              file=sys.stderr)
    world_native = all([world_fuzz(seed) for seed in range(args.seeds)])
    if world_native:
        print(f"world1 fuzz: {args.seeds} seeds round-trip, cpp "
              "byte-identical")
    else:
        print("world1 fuzz: py round-trip OK; cpp SKIPPED (no g++/binary)",
              file=sys.stderr)
    audit_native = all([audit_fuzz(seed) for seed in range(args.seeds)])
    if audit_native:
        print(f"audit1 fuzz: {args.seeds} seeds digests + blobs "
              "byte-identical, malformed rejected")
    else:
        print("audit1 fuzz: py round-trip OK; cpp SKIPPED (no g++/binary)",
              file=sys.stderr)
    for seed in range(args.seeds):
        shm_fuzz(seed)
    print(f"shm-lane fuzz: {args.seeds} seeds FIFO-exact both ways, "
          "malformed headers rejected")
    agg1_native = all([agg1_fuzz(seed) for seed in range(args.seeds)])
    if agg1_native:
        print(f"agg1 fuzz: {args.seeds} seeds round-trip, cpp "
              "byte-identical, malformed rejected")
    else:
        print("agg1 fuzz: py round-trip OK; cpp SKIPPED (no g++/binary)",
              file=sys.stderr)
    ledger_native = all([ledger_fuzz(seed) for seed in range(args.seeds)])
    if ledger_native:
        print(f"ledger1 fuzz: {args.seeds} seeds replica-verified, cpp "
              "byte-identical, malformed rejected")
    else:
        print("ledger1 fuzz: py round-trip OK; cpp SKIPPED "
              "(no g++/binary)", file=sys.stderr)
    if not args.skip_plans:
        for seed in range(2):
            plan_fuzz(seed, ticks=6)
        print("plan fuzz: resident packed == stateless JSON")
    print("codec fuzz gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
