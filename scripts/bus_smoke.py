#!/usr/bin/env python
"""busd relay micro-smoke (scripts/ci.sh, ISSUE 4): N-client fanout
sanity under the fast framing.

Builds ``mapd_bus`` with a bare g++ if absent (single translation unit —
no cmake needed; SKIPs with a warning when no toolchain exists), then:

- 6 subscribers (half fast-framed, half legacy JSON) on one topic plus a
  ``mapd.pos.*`` wildcard watcher;
- a fast publisher sends 200 sequenced frames on the topic and 50 pos1
  beacons across several region topics;
- every subscriber must receive every sequenced frame in order, the
  wildcard watcher every region beacon, and the hub's own metrics beacon
  must report the fan-out.

Exit 0 on success; ~5 s end to end.
"""

from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.runtime import plan_codec as pc  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.fleet import build_single_tu  # noqa: E402


def free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    binary = build_single_tu("mapd_bus", "cpp/busd/main.cpp")
    if binary is None:
        print("bus smoke: SKIPPED (no g++/binary)", file=sys.stderr)
        return 0
    port = free_port()
    bus = subprocess.Popen([str(binary), str(port)],
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
    subs = []
    try:
        time.sleep(0.3)
        for k in range(6):
            c = BusClient(port=port, peer_id=f"sub{k}", fastframe=k % 2 == 0)
            c.subscribe("smoke")
            subs.append(c)
        wild = BusClient(port=port, peer_id="wild")
        wild.subscribe("mapd.pos.*")
        pub = BusClient(port=port, peer_id="pub")
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and pub.hub_caps is None:
            pub.recv(timeout=0.2)
        assert pub.fast_hub, "hub did not negotiate the relay1 fast framing"
        time.sleep(0.3)

        n_seq, n_pos = 200, 50
        for k in range(n_seq):
            pub.publish("smoke", {"seq": k})
        for k in range(n_pos):
            pub.publish(f"mapd.pos.{k % 5}.{k % 3}",
                        {"type": "pos1",
                         "data": pc.encode_pos1_b64(k, k + 1, k * 7)})

        for c in subs:
            got = []
            t_end = time.monotonic() + 10
            while time.monotonic() < t_end and len(got) < n_seq:
                f = c.recv(timeout=0.5)
                if f and f.get("op") == "msg" and f["topic"] == "smoke":
                    got.append(f["data"]["seq"])
            assert got == list(range(n_seq)), (
                f"{c.peer_id}: fanout lost/reordered frames "
                f"({len(got)}/{n_seq})")
        beacons = []
        t_end = time.monotonic() + 10
        while time.monotonic() < t_end and len(beacons) < n_pos:
            f = wild.recv(timeout=0.5)
            if f and f.get("op") == "msg" \
                    and f["topic"].startswith("mapd.pos."):
                p, g, t = pc.decode_pos1_b64(f["data"]["data"])
                beacons.append((p, g, t))
        assert len(beacons) == n_pos, (
            f"wildcard watcher saw {len(beacons)}/{n_pos} region beacons")
        assert beacons[7] == (7, 8, 49), beacons[7]

        # the hub's own beacon reports the fan-out it relayed
        watch = BusClient(port=port, peer_id="watch")
        watch.subscribe("mapd.metrics")
        counters = None
        t_end = time.monotonic() + 6
        while time.monotonic() < t_end and counters is None:
            f = watch.recv(timeout=0.5)
            if (f and f.get("op") == "msg"
                    and (f.get("data") or {}).get("proc") == "busd"):
                counters = (f["data"]["metrics"] or {}).get("counters") or {}
        assert counters and \
            counters.get('bus.fanout_msgs{topic="smoke"}', 0) \
            == n_seq * len(subs), counters
        assert counters.get("bus.relay_fast_frames", 0) >= n_seq, counters
        watch.close()
        for c in subs + [wild, pub]:
            c.close()
        print(f"bus smoke OK: {n_seq} frames x {len(subs)} subscribers "
              f"(fast+legacy), {n_pos} wildcard region beacons, hub "
              f"counters consistent")
        return 0
    finally:
        bus.terminate()


if __name__ == "__main__":
    sys.exit(main())
