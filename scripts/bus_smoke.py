#!/usr/bin/env python
"""busd relay micro-smoke (scripts/ci.sh, ISSUE 4): N-client fanout
sanity under the fast framing.

Builds ``mapd_bus`` with a bare g++ if absent (single translation unit —
no cmake needed; SKIPs with a warning when no toolchain exists), then:

- 6 subscribers (half fast-framed, half legacy JSON) on one topic plus a
  ``mapd.pos.*`` wildcard watcher;
- a fast publisher sends 200 sequenced frames on the topic and 50 pos1
  beacons across several region topics;
- every subscriber must receive every sequenced frame in order, the
  wildcard watcher every region beacon, and the hub's own metrics beacon
  must report the fan-out.

``--shm`` (ISSUE 18) runs the SHM-LANE smoke instead: one busd with
same-host shared-memory lanes on and a 10 ms beacon-aggregation window;
an shm publisher and an agg1-capable shm subscriber must negotiate
lanes, every beacon must cross the rings (zero TCP fallbacks), busd
must coalesce the region fanout >= 4x into agg1 frames the subscriber
transparently explodes back into singles, and closing the clients must
leave the lane directory empty (no ring-file litter).

``--shards 3`` (ISSUE 6) runs the FEDERATED-POOL smoke instead: a
3-shard busd pool with peering links, a shard-aware publisher spraying
region beacons across every owning shard, a shard-aware wildcard watcher
(must see each beacon exactly once — the duplicate-suppression rule), a
LEGACY client parked on a non-home shard (must still see control-plane
frames via peering), then one non-home shard is hard-killed: the
surviving shards must keep relaying and the control plane must stay up
(the one-dead-shard degradation contract).

Exit 0 on success; ~5 s (single) / ~10 s (pool) end to end.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.runtime import plan_codec as pc  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import shardmap  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.buspool import (  # noqa: E402
    BusPool, free_port)
from p2p_distributed_tswap_tpu.runtime.fleet import build_single_tu  # noqa: E402


def _drain(client, seconds: float, sink):
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        f = client.recv(timeout=0.1)
        if f and f.get("op") == "msg":
            sink.append((f["topic"], f.get("data") or {}))


def shm_smoke(binary) -> int:
    import os
    import tempfile

    from p2p_distributed_tswap_tpu.obs import registry as _reg
    from p2p_distributed_tswap_tpu.runtime import shmlane

    n_pos, n_regions = 240, 4
    with tempfile.TemporaryDirectory(prefix="jg_bus_smoke_shm_") as td:
        saved = {k: os.environ.get(k)
                 for k in (shmlane.SHM_DIR_ENV, "JG_BUS_AGG_MS")}
        os.environ[shmlane.SHM_DIR_ENV] = td
        os.environ["JG_BUS_AGG_MS"] = "10"
        port = free_port()
        bus = subprocess.Popen(
            [str(binary), str(port)],
            env=dict(os.environ, JG_BUS_SHM="1"),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            time.sleep(0.3)
            r_sub, r_pub = _reg.Registry(), _reg.Registry()
            sub = BusClient(port=port, peer_id="shm-sub", shm=True,
                            registry=r_sub)
            sub.subscribe("mapd.pos.*")
            pub = BusClient(port=port, peer_id="shm-pub", shm=True,
                            registry=r_pub)
            for c in (sub, pub):
                deadline = time.monotonic() + 3
                while time.monotonic() < deadline and c.hub_caps is None:
                    c.recv(timeout=0.2)
                assert c.hub_caps and "shm1" in c.hub_caps, \
                    f"{c.peer_id}: hub did not negotiate the shm lane"
            time.sleep(0.2)

            for k in range(n_pos):
                pub.publish(f"mapd.pos.{k % n_regions}.0",
                            {"type": "pos1",
                             "data": pc.encode_pos1_b64(k, k + 1)})
            got = []
            t_end = time.monotonic() + 10
            while time.monotonic() < t_end and len(got) < n_pos:
                f = sub.recv(timeout=0.2)
                if f and f.get("op") == "msg" \
                        and f["topic"].startswith("mapd.pos."):
                    got.append(pc.decode_pos1_b64(f["data"]["data"])[0])
            assert sorted(got) == list(range(n_pos)), (
                f"shm subscriber saw {len(got)}/{n_pos} beacons "
                f"(losses or dupes across the rings)")

            cp = r_pub.snapshot()["counters"]
            cs = r_sub.snapshot()["counters"]
            assert cp.get("bus.shm_tx_frames", 0) == n_pos, cp
            assert cp.get("bus.shm_fallbacks", 0) == 0, cp
            assert cs.get("bus.shm_rx_frames", 0) >= 1, cs
            assert cs.get("bus.agg_rx_entries", 0) == n_pos, cs
            frames = cs.get("bus.agg_rx_frames", 0)
            assert 0 < frames <= n_pos // 4, (
                f"agg1 fanout cut below 4x: {n_pos} beacons arrived as "
                f"{frames} frames")

            sub.close()
            pub.close()
            leftovers = sorted(Path(td).glob("*.shl"))
            assert not leftovers, f"lane files not reclaimed: {leftovers}"
            print(f"bus smoke OK (shm): {n_pos} beacons over rings "
                  f"(0 TCP fallbacks), agg1 coalesced {n_pos} -> {frames} "
                  f"frames ({n_pos / frames:.1f}x fanout cut), lane dir "
                  f"clean after close")
            return 0
        finally:
            bus.terminate()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


def sharded_smoke(binary, num_shards: int) -> int:
    with BusPool(binary, num_shards=num_shards, settle_s=1.0) as pool:
        ports = pool.ports
        # shard-aware wildcard watcher + control subscriber
        watch = BusClient(port=ports[0], peer_id="watch",
                          shard_ports=ports)
        watch.subscribe("mapd.pos.*")
        watch.subscribe("smoke")
        # legacy single-connection client parked on a NON-home shard:
        # control frames must reach it over the peering links
        legacy = BusClient(port=ports[-1], peer_id="legacy")
        legacy.subscribe("smoke")
        pub = BusClient(port=ports[0], peer_id="pub", shard_ports=ports)
        time.sleep(0.5)

        n_pos, n_ctl = 60, 20
        topics = [f"mapd.pos.{k % 7}.{k % 5}" for k in range(n_pos)]
        owners = {shardmap.shard_of(t, num_shards) for t in topics}
        assert len(owners) > 1, (
            f"shardmap degenerated: all region topics on one shard "
            f"({owners})")
        for k, t in enumerate(topics):
            pub.publish(t, {"type": "pos1", "seq": k})
        for k in range(n_ctl):
            pub.publish("smoke", {"seq": k})

        got_watch, got_legacy = [], []
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and (
                sum(1 for t, _ in got_watch if t != "smoke") < n_pos
                or sum(1 for t, _ in got_watch if t == "smoke") < n_ctl
                or len(got_legacy) < n_ctl):
            _drain(watch, 0.2, got_watch)
            _drain(legacy, 0.2, got_legacy)
        pos_seqs = sorted(d["seq"] for t, d in got_watch if t != "smoke")
        ctl_seqs = [d["seq"] for t, d in got_watch if t == "smoke"]
        assert pos_seqs == list(range(n_pos)), (
            f"wildcard watcher across {num_shards} shards saw "
            f"{len(pos_seqs)}/{n_pos} beacons (dupes or losses): "
            f"{pos_seqs[:20]}...")
        assert ctl_seqs == list(range(n_ctl)), ctl_seqs
        legacy_seqs = [d["seq"] for _, d in got_legacy]
        assert legacy_seqs == list(range(n_ctl)), (
            f"legacy client on shard {num_shards - 1} missed control "
            f"frames via peering: {legacy_seqs}")

        # kill one NON-home shard: its regions go dark, everything else
        # must keep flowing (and nothing crashes)
        dead = next(s for s in sorted(owners) if s != 0)
        pool.kill_shard(dead)
        time.sleep(1.0)
        survivors = [t for t in topics
                     if shardmap.shard_of(t, num_shards) != dead]
        for k, t in enumerate(survivors):
            pub.publish(t, {"type": "pos1", "seq": 1000 + k})
        for k in range(5):
            pub.publish("smoke", {"seq": 1000 + k})
        got_watch.clear()
        got_legacy.clear()
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and (
                sum(1 for t, _ in got_watch if t != "smoke")
                < len(survivors)
                or len(got_legacy) < 5):
            _drain(watch, 0.2, got_watch)
            _drain(legacy, 0.2, got_legacy)
        pos2 = sorted(d["seq"] for t, d in got_watch if t != "smoke")
        assert pos2 == [1000 + k for k in range(len(survivors))], (
            f"surviving shards degraded after shard {dead} kill: "
            f"{len(pos2)}/{len(survivors)}")
        assert [d["seq"] for _, d in got_legacy] \
            == [1000 + k for k in range(5)], (
            "control plane lost frames after a region shard died")
        for c in (watch, legacy, pub):
            c.close()
        print(f"bus smoke OK (sharded): {num_shards}-shard pool, {n_pos} "
              f"cross-shard beacons seen exactly once, {n_ctl} control "
              f"frames via peering, shard-{dead} kill degraded only its "
              f"regions")
        return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=1,
                    help="run the federated-pool smoke with this many "
                         "busd shards (default: single-hub smoke)")
    ap.add_argument("--shm", action="store_true",
                    help="run the shm-lane + agg1 smoke (ISSUE 18)")
    args = ap.parse_args()
    if args.shm:
        binary = build_single_tu("mapd_bus", "cpp/busd/main.cpp")
        if binary is None:
            print("bus smoke: SKIPPED (no g++/binary)", file=sys.stderr)
            return 0
        return shm_smoke(binary)
    if args.shards > 1:
        binary = build_single_tu("mapd_bus", "cpp/busd/main.cpp")
        if binary is None:
            print("bus smoke: SKIPPED (no g++/binary)", file=sys.stderr)
            return 0
        return sharded_smoke(binary, args.shards)
    binary = build_single_tu("mapd_bus", "cpp/busd/main.cpp")
    if binary is None:
        print("bus smoke: SKIPPED (no g++/binary)", file=sys.stderr)
        return 0
    port = free_port()
    bus = subprocess.Popen([str(binary), str(port)],
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
    subs = []
    try:
        time.sleep(0.3)
        for k in range(6):
            c = BusClient(port=port, peer_id=f"sub{k}", fastframe=k % 2 == 0)
            c.subscribe("smoke")
            subs.append(c)
        wild = BusClient(port=port, peer_id="wild")
        wild.subscribe("mapd.pos.*")
        pub = BusClient(port=port, peer_id="pub")
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and pub.hub_caps is None:
            pub.recv(timeout=0.2)
        assert pub.fast_hub, "hub did not negotiate the relay1 fast framing"
        time.sleep(0.3)

        n_seq, n_pos = 200, 50
        for k in range(n_seq):
            pub.publish("smoke", {"seq": k})
        for k in range(n_pos):
            pub.publish(f"mapd.pos.{k % 5}.{k % 3}",
                        {"type": "pos1",
                         "data": pc.encode_pos1_b64(k, k + 1, k * 7)})

        for c in subs:
            got = []
            t_end = time.monotonic() + 10
            while time.monotonic() < t_end and len(got) < n_seq:
                f = c.recv(timeout=0.5)
                if f and f.get("op") == "msg" and f["topic"] == "smoke":
                    got.append(f["data"]["seq"])
            assert got == list(range(n_seq)), (
                f"{c.peer_id}: fanout lost/reordered frames "
                f"({len(got)}/{n_seq})")
        beacons = []
        t_end = time.monotonic() + 10
        while time.monotonic() < t_end and len(beacons) < n_pos:
            f = wild.recv(timeout=0.5)
            if f and f.get("op") == "msg" \
                    and f["topic"].startswith("mapd.pos."):
                p, g, t = pc.decode_pos1_b64(f["data"]["data"])
                beacons.append((p, g, t))
        assert len(beacons) == n_pos, (
            f"wildcard watcher saw {len(beacons)}/{n_pos} region beacons")
        assert beacons[7] == (7, 8, 49), beacons[7]

        # the hub's own beacon reports the fan-out it relayed
        watch = BusClient(port=port, peer_id="watch")
        watch.subscribe("mapd.metrics")
        counters = None
        t_end = time.monotonic() + 6
        while time.monotonic() < t_end and counters is None:
            f = watch.recv(timeout=0.5)
            if (f and f.get("op") == "msg"
                    and (f.get("data") or {}).get("proc") == "busd"):
                counters = (f["data"]["metrics"] or {}).get("counters") or {}
        assert counters and \
            counters.get('bus.fanout_msgs{topic="smoke"}', 0) \
            == n_seq * len(subs), counters
        assert counters.get("bus.relay_fast_frames", 0) >= n_seq, counters
        watch.close()
        for c in subs + [wild, pub]:
            c.close()
        print(f"bus smoke OK: {n_seq} frames x {len(subs)} subscribers "
              f"(fast+legacy), {n_pos} wildcard region beacons, hub "
              f"counters consistent")
        return 0
    finally:
        bus.terminate()


if __name__ == "__main__":
    sys.exit(main())
