#!/usr/bin/env python
"""Sector-planner fuzz gate (scripts/ci.sh, ISSUE 19): seeded random
worlds + chained toggles through ops/sector.py must keep all three
contracts the serving path relies on:

  1. route validity — the corridor's packed field strictly descends:
     a walk from every planned start reaches the goal in exactly
     corridor-distance steps over free cells, never reading STAY
     (unreachable starts must read STAY and must NOT demand re-entry);
  2. bounded suboptimality — corridor distance at each start is within
     EPS (0.05, the committed bound) of the true shortest path;
  3. repair == recompute — after every block/unblock batch,
     apply_toggles leaves the portal graph + intra tables equal to a
     from-scratch SectorPlanner on the final mask, and re-plans on the
     repaired graph are again exact per (1) and (2).

Also exercises corridor re-entry: off-corridor cells must either fold
into a replanned corridor or be provably unreachable.

Runs in a few seconds on the CPU backend; scripts/ci.sh invokes it
next to the field-repair fuzz gate.
"""

from __future__ import annotations

import argparse
import sys
import time
from collections import deque
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402

from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.ops import distance, sector  # noqa: E402

EPS = 0.05  # the committed bound (results/sector_r20.json)


def _bfs_dist(free: np.ndarray, goal: int) -> np.ndarray:
    """Reference full-grid BFS, independent of the planner."""
    h, w = free.shape
    d = np.full(h * w, int(sector.INF), np.int64)
    fr = free.reshape(-1)
    if fr[goal]:
        d[goal] = 0
        dq = deque([goal])
        while dq:
            c = dq.popleft()
            y, x = divmod(c, w)
            for dy, dx in ((0, 1), (1, 0), (0, -1), (-1, 0)):
                ny, nx = y + dy, x + dx
                if 0 <= ny < h and 0 <= nx < w:
                    nc = ny * w + nx
                    if fr[nc] and d[nc] > d[c] + 1:
                        d[nc] = d[c] + 1
                        dq.append(nc)
    return d


def _check_descent(pl, free, gl, st, fd, tag) -> float:
    """Walk the corridor from st; returns the measured epsilon."""
    w = free.shape[1]
    plan = pl.plan_goal(gl, [st], keep_dist=True)
    assert plan is not None, tag
    if fd[st] >= int(sector.INF):
        # unreachable: STAY, and no re-entry churn
        assert pl.code_at(gl, st) == int(distance.DIR_STAY), tag
        assert not pl.needs_reentry(gl, st), tag
        return 0.0
    cd = int(plan.dist.reshape(-1)[st])
    assert cd >= int(fd[st]), (tag, cd, int(fd[st]))
    eps = (cd - int(fd[st])) / max(1, int(fd[st]))
    assert eps <= EPS, (tag, eps)
    c, steps = st, 0
    while c != gl and steps <= cd:
        code = pl.code_at(gl, c)
        assert code != int(distance.DIR_STAY), (tag, c)
        dx, dy = distance.DIR_DXDY[code]
        y, x = divmod(c, w)
        c = (y + dy) * w + (x + dx)
        assert free.reshape(-1)[c], (tag, c)
        steps += 1
    assert c == gl and steps == cd, (tag, steps, cd)
    return eps


def fuzz_seed(seed: int, trials: int) -> tuple:
    """One world, `trials` goal/start pairs + one block/unblock toggle
    round each; returns (reachable pairs checked, max epsilon seen)."""
    rng = np.random.default_rng(seed)
    kind = seed % 3
    if kind == 0:
        free = rng.random((64, 64)) > 0.2
    elif kind == 1:
        free = np.asarray(Grid.warehouse(64, 64).free).copy()
    else:
        free = rng.random((48, 80)) > 0.3
    s = (16, 32)[seed % 2]
    pl = sector.SectorPlanner(free, s=s, use_jit=False)
    flat = free.reshape(-1)
    eps_max, checked = 0.0, 0
    for t in range(trials):
        cells = np.flatnonzero(flat)
        st, gl = (int(c) for c in rng.choice(cells, 2, replace=False))
        fd = _bfs_dist(free, gl)
        eps_max = max(eps_max,
                      _check_descent(pl, free, gl, st, fd, (seed, t)))
        if fd[st] < int(sector.INF):
            checked += 1
        # corridor re-entry: an off-corridor free cell folds in exactly
        q = int(rng.choice(cells))
        if q != gl and pl.needs_reentry(gl, q):
            eps_max = max(eps_max, _check_descent(
                pl, free, gl, q, fd, (seed, t, "reenter")))
        # chained toggles: block a batch, verify repair == recompute,
        # re-plan exact on the repaired graph, then unblock and re-check
        batch = [int(c) for c in rng.choice(cells, 6, replace=False)
                 if c != gl and c != st][:4]
        for c in batch:
            flat[c] = False
        pl.apply_toggles(batch)
        assert pl.graph_state() == sector.SectorPlanner(
            free, s=s, use_jit=False).graph_state(), (seed, t, "block")
        pl.forget(gl)
        eps_max = max(eps_max, _check_descent(
            pl, free, gl, st, _bfs_dist(free, gl), (seed, t, "post")))
        for c in batch:
            flat[c] = True
        pl.apply_toggles(batch)
        assert pl.graph_state() == sector.SectorPlanner(
            free, s=s, use_jit=False).graph_state(), (seed, t, "unblock")
    return checked, eps_max


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=6)
    ap.add_argument("--trials", type=int, default=4)
    args = ap.parse_args()
    t0 = time.perf_counter()
    total, eps_max = 0, 0.0
    for seed in range(args.seeds):
        n, e = fuzz_seed(seed, args.trials)
        total += n
        eps_max = max(eps_max, e)
    assert total >= args.seeds, \
        "too few reachable pairs exercised the corridor path"
    print(f"sector fuzz gate OK: {args.seeds} seeds x {args.trials} "
          f"trials, {total} reachable pairs, eps_max={eps_max:.4f} "
          f"(bound {EPS}), repair==recompute on every toggle round, "
          f"{time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
