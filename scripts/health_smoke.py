#!/usr/bin/env python
"""Health-plane smoke (ISSUE 16, scripts/ci.sh): the live alerting proof.

Brings up a real fleet (busd + open-loop C++ manager + sim agents) with
an in-process :class:`HealthWatcher` (obs/health.py — the healthd body)
under JG_HEALTH=1 and judges BOTH acceptance halves:

- **clean** — steady achievable load for a full evaluation window must
  record ZERO alerts (no confirmed breach, no forecast: a flat fleet
  has no trend to extrapolate);
- **ramp** — a diurnal-ramp overload (analysis/fleetsim.py
  ``shape_rate``, the ``--shape ramp`` generator) drives the fleet's
  completion ratio into a smooth monotone decline; the watcher must
  emit a **forecast alert ≥ 2 evaluation intervals BEFORE the breach
  confirms**, the confirmed page must **attribute** the breach to the
  overloaded manager peer (backlog growth) with a ``shed_load``
  recommendation, the page must carry an **auto-captured** replayable
  ``capture1`` artifact, and the ``alert1`` frames must actually land
  on the raw ``mapd.alert`` wire (a tap subscriber counts them).

``--out FILE`` writes a JSON artifact (+ a ``.md`` sibling) — bench.py's
``health`` axis and ``results/health_r17.json(.md)`` consume it.

Usage:
  JAX_PLATFORMS=cpu python scripts/health_smoke.py
  JAX_PLATFORMS=cpu python scripts/health_smoke.py --out /tmp/h.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.obs import events as _events  # noqa: E402
from p2p_distributed_tswap_tpu.obs import flightrec as _flightrec  # noqa: E402,E501
from p2p_distributed_tswap_tpu.obs import health as _health  # noqa: E402
from p2p_distributed_tswap_tpu.obs import registry as _reg  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import buspool  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built)
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501

from analysis.fleetsim import shape_rate  # noqa: E402

CLEAN_SPEC = {
    "name": "health-smoke-clean",
    "slos": [
        # min well below the steady fleet's ratio: a clean run that
        # still alerts is exactly the false-positive the judge rejects
        {"name": "completion", "signal": "fleet.completion_ratio",
         "min": 0.3},
    ],
}

RAMP_SPEC = {
    "name": "health-smoke-ramp",
    "slos": [
        # dispatch is capacity-gated, so an overload surfaces as queue
        # depth (manager.tasks_pending, ISSUE 16) — under the ramp the
        # backlog climbs smoothly, which is exactly the monotone trend
        # the slope forecaster must catch BEFORE this bound breaks
        {"name": "backlog", "signal": "fleet.tasks_pending",
         "max": 40.0},
    ],
}


def write_md(path: Path, doc: dict) -> None:
    r = doc["ramp"]
    c = doc["clean"]
    fc = (r.get("forecast") or {}).get("forecast") or {}
    att = (r.get("breach") or {}).get("attribution") or {}
    reco = (r.get("breach") or {}).get("recommendation") or {}
    lines = [
        "# Health-plane smoke (ISSUE 16): forecast-before-breach "
        "on a diurnal ramp",
        "",
        f"- verdict: **{'PASS' if doc['ok'] else 'FAIL'}**",
        f"- fleet: {doc['agents']} sim agents, "
        f"{doc['side']}x{doc['side']} map, 1 busd shard, "
        f"open-loop C++ manager",
        f"- evaluation interval: {doc['interval_s']} s "
        f"(the beacon cadence)",
        "",
        "## Clean phase (steady achievable load)",
        "",
        f"- beats: {c['beats']}, alerts: **{c['alerts']}** "
        f"(must be 0 — no confirmed breach, no forecast)",
        "",
        "## Ramp phase (diurnal overload via `--shape ramp`)",
        "",
        f"- injection: {r['base_rate']} → {r['peak_rate']} tasks/s "
        f"over {r['period_s']} s (`shape_rate('ramp', ...)`)",
        f"- forecast: `{(r.get('forecast') or {}).get('signal')}` crosses "
        f"its SLO in ~{fc.get('eta_s')} s "
        f"({fc.get('eta_intervals')} intervals, "
        f"confidence {fc.get('confidence')})",
        f"- forecast → confirmed breach lead: "
        f"**{r.get('lead_intervals')} evaluation interval(s)** "
        f"(acceptance: ≥ 2)",
        f"- attribution: {att.get('kind')} `{att.get('id')}` "
        f"(proc {att.get('proc')}) — {att.get('detail')}",
        f"- recommendation: `{reco.get('actuator')}"
        f"({reco.get('target')})` direction={reco.get('direction')}",
        f"- auto-capture: `{(r.get('breach') or {}).get('capture')}` "
        f"(replayable capture1)",
        f"- alert1 frames observed on the raw `mapd.alert` wire: "
        f"{r['alerts_on_wire']}",
        "",
    ]
    path.write_text("\n".join(lines))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--agents", type=int, default=6)
    ap.add_argument("--side", type=int, default=16)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--clean-s", type=float, default=24.0,
                    help="steady-phase watch window")
    ap.add_argument("--ramp-peak", type=float, default=8.0,
                    help="ramp peak injection rate tasks/s")
    ap.add_argument("--ramp-period-s", type=float, default=40.0)
    ap.add_argument("--ramp-max-s", type=float, default=90.0,
                    help="ramp-phase budget (forecast + confirm must "
                         "land inside it)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the artifact JSON here (+ .md sibling)")
    ap.add_argument("--log-dir", default="/tmp/jg_health_smoke")
    args = ap.parse_args(argv)

    ensure_built()
    side = args.side
    map_file = f"/tmp/health_smoke_{side}.map.txt"
    Path(map_file).write_text("\n".join(["." * side] * side) + "\n")
    log_dir = Path(args.log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    port = buspool.free_port()
    saved_env = dict(os.environ)
    procs, logs = [], []
    import subprocess

    def spawn(name, cmd, stdin=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ))
        procs.append(p)
        return p

    pool = sim = tap = None
    watcher = None
    _reg.get_registry().clear()
    try:
        pool = buspool.BusPool(BUILD_DIR / "mapd_bus", num_shards=1,
                               home_port=port, spawn=spawn)
        time.sleep(0.3)
        os.environ.update(pool.env())
        os.environ["JG_HEALTH"] = "1"
        # capture evidence (ISSUE 11): the sim pool's capture.meta /
        # task.spec events ride THIS process's flight ring — bind it
        # before the pool exists so the page's auto-capture can rebuild
        # a replayable window from our own dump
        os.environ["JG_FLIGHT_DIR"] = str(log_dir)
        _events.configure("health_smoke")
        mgr = spawn("manager", [
            str(BUILD_DIR / "mapd_manager_centralized"),
            "--port", str(port), "--map", map_file,
            "--solver", "cpu", "--planning-interval-ms", "150",
            "--seed", str(args.seed), "--open-loop",
        ], stdin=subprocess.PIPE)
        time.sleep(0.8)
        sim = SimAgentPool(args.agents, side, port=port, seed=args.seed,
                           heartbeat_s=1.0)
        # the wire proof: alert1 frames must actually land on the raw
        # mapd.alert topic, not just in the watcher's own lists
        tap = BusClient(port=port, peer_id="health-smoke-tap")
        tap.subscribe(_health.ALERT_TOPIC, raw=True)
        sim.heartbeat_all()
        sim.pump(2.0)

        wire = {"alert1": 0, "health_beacon": 0}

        def pump_tap():
            while True:
                f = tap.recv(timeout=0.01)
                if not f:
                    return
                if f.get("op") != "msg":
                    continue
                t = (f.get("data") or {}).get("type")
                if t in wire:
                    wire[t] += 1

        def inject(k):
            mgr.stdin.write(f"tasks {k}\n".encode())
            mgr.stdin.flush()

        def drive(watcher, seconds, rate_fn=None):
            """Pump sim + watcher + tap for ``seconds``, injecting
            ``rate_fn(t)`` tasks once per second (None = no injection).
            Returns alerts emitted during the drive."""
            out = []
            t0 = time.monotonic()
            next_inject = t0
            end = t0 + seconds
            while time.monotonic() < end:
                now = time.monotonic()
                if rate_fn is not None and now >= next_inject:
                    next_inject = now + 1.0
                    k = int(round(rate_fn(now - t0)))
                    if k > 0:
                        inject(k)
                sim.pump(0.25)
                pump_tap()
                out.extend(watcher.pump(0.25))
            return out

        def capture_dump():
            # in-process evidence: the sim pool (and its capture.meta /
            # task.spec events) live in THIS process, so the auditor's
            # bus-wide flight_dump request would miss them — dump our
            # own ring straight into the record dir instead
            rec = _flightrec.get_recorder()
            _flightrec.dump(str(log_dir / f"{rec.proc}-{rec.pid}"
                                          ".flight.jsonl"),
                            reason="health_alert")

        # --- phase 1: settle, then a steady clean window -----------------
        # let the first injected tasks complete BEFORE the engine starts
        # sampling: the cold-start ratio (dispatched>0, completed=0) is
        # startup, not an SLO story
        settle_watch = _health.HealthWatcher(
            BusClient(port=port, peer_id="healthd-settle"),
            _health.HealthEngine(spec=CLEAN_SPEC),
            publish=False)
        drive(settle_watch, 10.0, rate_fn=lambda t: 1.0)
        settle_watch.bus.close()

        clean_watch = _health.HealthWatcher(
            BusClient(port=port, peer_id="healthd-clean"),
            _health.HealthEngine(spec=CLEAN_SPEC),
            record_dir=str(log_dir), capture_dump=capture_dump)
        clean_alerts = drive(clean_watch, args.clean_s,
                             rate_fn=lambda t: 1.0)
        clean_beats = clean_watch.engine.seq
        clean_ratio = (clean_watch.agg.rollup()["fleet"]
                       ["completion_ratio"])
        clean_watch.bus.close()
        print(f"health_smoke: clean phase — {clean_beats} beat(s), "
              f"{len(clean_alerts)} alert(s), "
              f"completion_ratio={clean_ratio}", flush=True)

        # --- phase 2: diurnal ramp overload ------------------------------
        ramp_base = 1.0
        ramp_watch = _health.HealthWatcher(
            BusClient(port=port, peer_id="healthd-ramp"),
            _health.HealthEngine(spec=RAMP_SPEC),
            record_dir=str(log_dir), capture_dump=capture_dump)
        ramp_alerts = []
        deadline = time.monotonic() + args.ramp_max_s
        t_ramp0 = time.monotonic()

        def ramp_rate(_t):
            return shape_rate("ramp", time.monotonic() - t_ramp0,
                              ramp_base, args.ramp_peak,
                              args.ramp_period_s)

        while time.monotonic() < deadline:
            ramp_alerts.extend(drive(ramp_watch, 2.0,
                                     rate_fn=ramp_rate))
            if os.environ.get("JG_HEALTH_SMOKE_DEBUG"):
                ru = ramp_watch.agg.rollup()["fleet"]
                st = ramp_watch.engine._states.get("backlog")
                fcst = st.forecaster if st else None
                print(f"  t={time.monotonic() - t_ramp0:5.1f}s "
                      f"pending={ru['tasks_pending']} "
                      f"disp={ru['tasks_dispatched']} "
                      f"done={ru['tasks_completed']} "
                      f"slope={getattr(fcst, 'slope', None)} "
                      f"conf={fcst.confidence() if fcst else None}",
                      flush=True)
            if any(a["kind"] == "breach" and a["state"] == "confirmed"
                   for a in ramp_alerts):
                break
        pump_tap()
        for a in ramp_alerts:
            print("health_smoke: " + _health.render_alert(a),
                  flush=True)
        ramp_watch.bus.close()

        forecast = next((a for a in ramp_alerts
                         if a["kind"] == "forecast"), None)
        breach = next((a for a in ramp_alerts
                       if a["kind"] == "breach"
                       and a["state"] == "confirmed"), None)
        interval = ramp_watch.engine.interval_s
        lead_intervals = None
        if forecast and breach:
            lead_intervals = round(
                (breach["ts_ms"] - forecast["ts_ms"]) / 1000.0
                / interval, 1)
        att = (breach or {}).get("attribution") or {}
        reco = (breach or {}).get("recommendation") or {}
        capture_path = (breach or {}).get("capture")
        capture_ok = bool(capture_path
                          and Path(capture_path).exists())
        attribution_ok = (att.get("kind") == "peer"
                          and str(att.get("proc") or ""
                                  ).startswith("manager"))
        alerts_jsonl = log_dir / "healthd.alerts.jsonl"

        ok = (len(clean_alerts) == 0
              and clean_beats >= 8
              and forecast is not None and breach is not None
              and lead_intervals is not None and lead_intervals >= 2
              and attribution_ok
              and reco.get("actuator") in _health.ACTUATORS
              and capture_ok
              and wire["alert1"] >= 2
              and alerts_jsonl.exists())

        doc = {
            "experiment": "health-plane smoke (ISSUE 16)",
            "agents": args.agents,
            "side": side,
            "interval_s": interval,
            "clean": {
                "beats": clean_beats,
                "alerts": len(clean_alerts),
                "completion_ratio": clean_ratio,
                "spec": CLEAN_SPEC,
            },
            "ramp": {
                "base_rate": ramp_base,
                "peak_rate": args.ramp_peak,
                "period_s": args.ramp_period_s,
                "spec": RAMP_SPEC,
                "forecast": forecast,
                "breach": breach,
                "lead_intervals": lead_intervals,
                "alerts_on_wire": wire["alert1"],
                "health_beacons_on_wire": wire["health_beacon"],
            },
            "attribution_ok": attribution_ok,
            "capture_ok": capture_ok,
            "alerts_jsonl": str(alerts_jsonl),
            "ok": ok,
        }
        print("health_smoke: " + json.dumps(
            {k: doc[k] for k in ("ok", "attribution_ok", "capture_ok")}
            | {"clean_alerts": len(clean_alerts),
               "lead_intervals": lead_intervals,
               "alerts_on_wire": wire["alert1"]}), flush=True)
        if args.out:
            out = Path(args.out)
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_text(json.dumps(doc, indent=2) + "\n")
            write_md(out.with_suffix(".md"), doc)
        if not ok:
            print("health_smoke FAILED", file=sys.stderr)
        return 0 if ok else 1
    finally:
        if sim is not None:
            sim.close()
        if tap is not None:
            tap.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        os.environ.clear()
        os.environ.update(saved_env)
        _events.configure("health_smoke")


if __name__ == "__main__":
    sys.exit(main())
