"""Chaos matrix on top of deterministic replay (ISSUE 11, scripts/ci.sh).

Replay (``analysis/fleetsim.py --replay``) makes the LOAD deterministic;
this gate schedules faults ON TOP of it and judges each run with the
audit plane (ISSUE 10) and the SLO engine (ISSUE 7) — chaos engineering
with a reproducible trigger and an automated judge:

- ``clean`` — no fault: the replay itself must complete every captured
  task exactly once with zero confirmed RED divergences (the control
  row, and — run twice — the DETERMINISM PROOF: identical completed-task
  sets and equal audit ledger/view digests at the final watermark);
- ``bus_shard_kill`` — hard-kill a non-home busd pool member mid-window
  (runtime/buspool.py kill_shard): a dead shard must cost its regions,
  not the fleet (PR 6 contract, now chaos-gated on every run);
- ``solverd_sigkill`` — SIGKILL solverd mid-window (mid-dynamic-world
  when the capture carries toggles) and respawn it: the auditor must
  DETECT the gap (a confirmed ``silent`` record naming solverd — the
  localization), and the restarted daemon's snapshot+world-replay
  resync must reconverge with nothing lost or duplicated;
- ``manager_sigstop`` — SIGSTOP the manager past its audit cadence
  (several claim windows), then SIGCONT: detected as a manager
  ``silent`` episode, healed after resume, outcome intact;
- ``peer_partition`` — SIGSTOP a busd pool member (a link partition:
  the process lives, its traffic stalls), then SIGCONT: the fleet rides
  through on the surviving shards + reconnects.
- ``shm_peer_kill`` — replay with the zero-copy lanes armed
  (JG_BUS_SHM=1, ISSUE 18), spawn a dedicated shm-lane beacon peer,
  SIGKILL it mid-window: busd must reap the dead peer's ring (lane file
  unlinked with the TCP session), the surviving lane users keep
  flowing, and the replay stays divergence-free.

Verdict per fault: ``green`` iff the outcome ledger is intact (every
captured task completed exactly once), any required detection fired AND
named the faulted role, no RED divergence is still active at the final
watermark, and the SLO engine passes the replay signals.  A gate that
cannot trip is no gate: ``--ci`` runs the determinism pair AND the
solverd kill, and fails unless the kill is detected + localized.

Usage:
  JAX_PLATFORMS=cpu python scripts/chaos_gate.py \
      --capture results/captures/ci_small.capture.json --ci
  python scripts/chaos_gate.py --capture C --faults \
      clean,bus_shard_kill,solverd_sigkill,manager_sigstop,peer_partition \
      --out results/replay_r13.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from pathlib import Path
from typing import Optional

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.obs import audit as au  # noqa: E402
from p2p_distributed_tswap_tpu.obs import capture as _capture  # noqa: E402
from p2p_distributed_tswap_tpu.obs import slo as _slo  # noqa: E402


# ---------------------------------------------------------------------------
# fault scripts — polled by fleetsim.run_replay with (ctx, t_rel_s)
# ---------------------------------------------------------------------------

class Fault:
    """Base fault: fires once at ``at_s`` into the replay.  Subclasses
    implement :meth:`fire` (and optionally :meth:`recover` at
    ``at_s + recover_after_s``)."""

    kind = "clean"
    needs_solverd = False
    needs_shards = 1
    needs_shm = False
    extra_drain_s = 0.0

    def __init__(self, at_s: float = 0.0, recover_after_s: float = 0.0):
        self.at_s = at_s
        self.recover_after_s = recover_after_s
        self.fired_at = None
        self.recovered_at = None

    def fire(self, ctx) -> None:  # pragma: no cover - overridden
        pass

    def recover(self, ctx) -> None:
        pass

    def poll(self, ctx, t_s: float) -> None:
        if self.fired_at is None and t_s >= self.at_s:
            self.fired_at = round(t_s, 2)
            self.fire(ctx)
        if self.fired_at is not None and self.recovered_at is None \
                and self.recover_after_s \
                and t_s >= self.at_s + self.recover_after_s:
            self.recovered_at = round(t_s, 2)
            self.recover(ctx)

    def summary(self) -> dict:
        return {"kind": self.kind, "at_s": self.at_s,
                "fired_at_s": self.fired_at,
                "recovered_at_s": self.recovered_at}


class CleanFault(Fault):
    kind = "clean"

    def poll(self, ctx, t_s: float) -> None:
        pass


class BusShardKill(Fault):
    kind = "bus_shard_kill"
    needs_shards = 2
    extra_drain_s = 20.0

    def __init__(self, at_s: float, shard: int = 1):
        super().__init__(at_s)
        self.shard = shard

    def fire(self, ctx) -> None:
        ctx.pool.kill_shard(self.shard)
        ctx.note(f"killed bus shard {self.shard} at t={self.fired_at}s")

    def summary(self) -> dict:
        return {**super().summary(), "shard": self.shard}


class SolverdSigkill(Fault):
    kind = "solverd_sigkill"
    needs_solverd = True
    # the respawned daemon re-warms JAX programs before planning resumes
    extra_drain_s = 90.0

    def __init__(self, at_s: float, restart_after_s: float = 3.0):
        super().__init__(at_s, recover_after_s=restart_after_s)

    def fire(self, ctx) -> None:
        ctx.solverd.send_signal(signal.SIGKILL)
        try:
            ctx.solverd.wait(timeout=10)
        except Exception:
            pass
        ctx.note(f"SIGKILLed solverd at t={self.fired_at}s")

    def recover(self, ctx) -> None:
        ctx.restart_solverd(wait=False)
        ctx.note(f"respawned solverd at t={self.recovered_at}s "
                 "(non-blocking; resync heals it)")


class ManagerSigstop(Fault):
    kind = "manager_sigstop"
    extra_drain_s = 25.0

    def __init__(self, at_s: float, stop_s: float = 4.0):
        super().__init__(at_s, recover_after_s=stop_s)

    def fire(self, ctx) -> None:
        os.kill(ctx.manager.pid, signal.SIGSTOP)
        ctx.note(f"SIGSTOPped manager at t={self.fired_at}s")

    def recover(self, ctx) -> None:
        os.kill(ctx.manager.pid, signal.SIGCONT)
        ctx.note(f"SIGCONTed manager at t={self.recovered_at}s")


class ManagerHandoffKill(Fault):
    """ISSUE 14: SIGKILL region 1's manager mid-window on a FEDERATED
    (2x1) replay — by then agents are crossing the border, so the kill
    lands mid-handoff-traffic.  The contract: the auditor must DETECT
    the silent manager, the surviving neighbor must NOT double-dispatch
    (no uncaptured completion, no ledger overcount — the handoff dedup
    guard under fire), and the handoff protocol must actually have been
    exercised (handoffs_sent >= 1).

    Verdict modes (ISSUE 15): without a standby (``ha=False``, the
    JG_HA=0 legacy row) tasks whose region of record died MAY strand —
    the row demands detection only.  With a standby configured the row
    is RECOVERY-REQUIRED: the dead region's warm standby must promote
    (digest-equal takeover watermark) and every captured task must
    complete exactly once — zero lost, zero duplicated."""

    kind = "manager_handoff_kill"
    needs_regions = "2x1"
    extra_drain_s = 25.0

    def __init__(self, at_s: float, ha: bool = False):
        super().__init__(at_s)
        self.ha = ha
        if ha:
            # the promoted standby needs the lease-expiry window plus a
            # sweep-hold before re-queued tasks can finish
            self.extra_drain_s = 45.0

    def fire(self, ctx) -> None:
        victim = ctx.managers[1]
        victim.send_signal(signal.SIGKILL)
        try:
            victim.wait(timeout=10)
        except Exception:
            pass
        ctx.note(f"SIGKILLed region-1 manager at t={self.fired_at}s")


class ManagerKillFailover(Fault):
    """ISSUE 15: SIGKILL the (flat fleet's) active manager mid-window
    with a warm standby configured.  The contract is full recovery:
    the auditor must confirm the silent active (detection), the standby
    must promote inside one claim window announcing ledger/view digests
    EQUAL to the active's last shipped ones (the takeover watermark
    proof), and every captured task must complete exactly once — zero
    lost, zero duplicated (the promoted manager's restore-hold +
    unknown-done dedup under fire)."""

    kind = "manager_kill_failover"
    ha = True
    extra_drain_s = 45.0

    def fire(self, ctx) -> None:
        ctx.manager.send_signal(signal.SIGKILL)
        try:
            ctx.manager.wait(timeout=10)
        except Exception:
            pass
        ctx.note(f"SIGKILLed the active manager at t={self.fired_at}s "
                 "(warm standby must take over)")


class PeerPartition(Fault):
    kind = "peer_partition"
    needs_shards = 2
    extra_drain_s = 20.0

    def __init__(self, at_s: float, stop_s: float = 4.0, shard: int = 1):
        super().__init__(at_s, recover_after_s=stop_s)
        self.shard = shard

    def fire(self, ctx) -> None:
        os.kill(ctx.pool.procs[self.shard].pid, signal.SIGSTOP)
        ctx.note(f"partitioned bus shard {self.shard} (SIGSTOP) at "
                 f"t={self.fired_at}s")

    def recover(self, ctx) -> None:
        os.kill(ctx.pool.procs[self.shard].pid, signal.SIGCONT)
        ctx.note(f"healed partition of shard {self.shard} at "
                 f"t={self.recovered_at}s")

    def summary(self) -> dict:
        return {**super().summary(), "shard": self.shard}


class ShmPeerKill(Fault):
    """ISSUE 18: the replay runs with the zero-copy lanes armed
    (JG_BUS_SHM=1 — the sim pool itself rides rings), a dedicated
    shm-lane beacon peer is spawned at ``at_s`` and SIGKILLed a few
    seconds later.  The contract: busd reaps the dead peer's ring with
    its TCP session (the lane FILE is unlinked — nothing stale
    survives), the surviving lane users keep flowing, and the replay
    outcome stays intact with no RED divergence."""

    kind = "shm_peer_kill"
    needs_shm = True
    extra_drain_s = 15.0

    def __init__(self, at_s: float, kill_after_s: float = 4.0):
        super().__init__(at_s, recover_after_s=kill_after_s)
        self.victim = None
        self.lane_negotiated = None
        self.reaped = None

    def _lane_path(self):
        from p2p_distributed_tswap_tpu.runtime import shmlane
        return shmlane.lane_path_for("shm-victim", 0)

    def fire(self, ctx) -> None:
        import subprocess
        code = (
            "import sys, time, base64\n"
            f"sys.path.insert(0, {str(ROOT)!r})\n"
            "from p2p_distributed_tswap_tpu.obs import registry as reg\n"
            "from p2p_distributed_tswap_tpu.runtime import plan_codec\n"
            "from p2p_distributed_tswap_tpu.runtime.bus_client import "
            "BusClient\n"
            f"c = BusClient(port={ctx.pool.home_port}, "
            "peer_id='shm-victim', shm=True, registry=reg.Registry())\n"
            "c.subscribe('mapd.pos.*')\n"
            "beat = {'type': 'pos1', 'data': base64.b64encode("
            "plan_codec.encode_pos1(66, 66)).decode()}\n"
            "while True:\n"
            "    c.publish('mapd.pos.66.66', beat)\n"
            "    c.recv(timeout=0.02)\n")
        self.victim = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        # the lane file appearing proves the ring pair was offered;
        # busd's welcome echo arms it moments later
        path, end = self._lane_path(), time.monotonic() + 5.0
        while not path.exists() and time.monotonic() < end:
            time.sleep(0.1)
        self.lane_negotiated = path.exists()
        ctx.note(f"spawned shm-lane victim (pid {self.victim.pid}, "
                 f"lane {'up' if self.lane_negotiated else 'MISSING'}) "
                 f"at t={self.fired_at}s")

    def recover(self, ctx) -> None:
        self.victim.send_signal(signal.SIGKILL)
        try:
            self.victim.wait(timeout=10)
        except Exception:
            pass
        ctx.note(f"SIGKILLed shm-lane victim at t={self.recovered_at}s")
        # busd sees the TCP session die in its next poll cycle and must
        # unlink the ring + doorbells — nothing stale survives
        path, end = self._lane_path(), time.monotonic() + 5.0
        while path.exists() and time.monotonic() < end:
            time.sleep(0.1)
        self.reaped = not path.exists()
        ctx.note("shm lane reaped (ring file unlinked)" if self.reaped
                 else f"shm lane NOT reaped: {path} survived the kill")

    def summary(self) -> dict:
        return {**super().summary(),
                "lane_negotiated": self.lane_negotiated,
                "reaped": self.reaped}


FAULT_KINDS = ("clean", "bus_shard_kill", "solverd_sigkill",
               "manager_sigstop", "peer_partition", "shm_peer_kill",
               "manager_handoff_kill", "manager_kill_failover")


def build_fault(kind: str, capture: dict,
                ha: Optional[bool] = None) -> Fault:
    """Instantiate a fault scheduled relative to the capture's own
    duration (mid-window: the fleet is busiest there).  ``ha`` arms the
    warm-standby rows; for ``manager_handoff_kill`` it defaults to the
    JG_HA env so the same row name upgrades from detection-only to
    recovery-required when a standby is configured (ISSUE 15)."""
    dur_s = capture["duration_ms"] / 1000.0
    mid = max(1.0, 0.4 * dur_s)
    if kind == "clean":
        return CleanFault()
    if kind == "bus_shard_kill":
        return BusShardKill(at_s=mid)
    if kind == "solverd_sigkill":
        return SolverdSigkill(at_s=mid)
    if kind == "manager_sigstop":
        return ManagerSigstop(at_s=mid)
    if kind == "peer_partition":
        return PeerPartition(at_s=mid)
    if kind == "shm_peer_kill":
        return ShmPeerKill(at_s=mid)
    if kind == "manager_handoff_kill":
        if ha is None:
            ha = os.environ.get("JG_HA", "") not in ("", "0")
        return ManagerHandoffKill(at_s=mid, ha=ha)
    if kind == "manager_kill_failover":
        return ManagerKillFailover(at_s=mid)
    raise SystemExit(f"unknown fault {kind!r} (one of {FAULT_KINDS})")


# ---------------------------------------------------------------------------
# judges
# ---------------------------------------------------------------------------

# faults whose detection by the auditor is REQUIRED for a green verdict
# (the faulted role goes silent while its peers keep beaconing); the bus
# faults are judged on outcome + reconvergence — busd holds no audited
# fleet state to go silent with
_DETECTION_REQUIRED = {"solverd_sigkill": "solverd",
                       "manager_sigstop": "manager"}

CHAOS_SPEC = {
    "name": "chaos-replay",
    "slos": [
        {"name": "completion_ratio", "signal": "replay.completion_ratio",
         "min": 1.0},
        {"name": "tasks_missing", "signal": "replay.missing", "max": 0},
        # duplication is judged at the SYSTEM-OF-RECORD level: the
        # manager's dedup-guarded completion counter exceeding the
        # captured task count, or an id the capture never issued
        # completing.  Pool-side double-deliveries (the positional-done
        # / goal-exchange race) are reference behavior and ride the
        # artifact as evidence only.
        {"name": "ledger_overcount", "signal": "replay.ledger_overcount",
         "max": 0},
        {"name": "uncaptured_completions",
         "signal": "replay.extra_done", "max": 0},
    ],
}


def _proc_of(res: dict, peer: str) -> str:
    return ((res["audit"].get("epochs") or {}).get(peer) or {}).get(
        "proc") or ""


def _ha_takeover_checks(res: dict, reasons: list) -> dict:
    """Shared failover evidence (ISSUE 15): exactly >= 1 takeover must
    have been announced, and the promoted standby's ledger/view digests
    must equal the failed active's last shipped ones at the takeover
    watermark.  Appends failures to ``reasons``; returns the evidence."""
    takeovers = (res.get("ha") or {}).get("takeovers") or []
    if not takeovers:
        reasons.append("no ha_takeover was ever announced — the "
                       "standby never promoted")
        return {"takeovers": 0, "digests_equal": None,
                "takeover_latency_s": None}
    bad = [t for t in takeovers if not t["digests_equal"]]
    if bad:
        reasons.append("takeover watermark digests DIFFER from the "
                       f"failed active's last shipped ones: {bad}")
    fired = (res.get("chaos") or {}).get("fired_at_s")
    latency = None
    if fired is not None:
        latency = round(min(t["t_rel_s"] for t in takeovers) - fired, 2)
    return {"takeovers": len(takeovers),
            "digests_equal": not bad,
            "takeover_latency_s": latency}


def classify_kill_failover(res: dict) -> dict:
    """The warm-standby failover verdict (ISSUE 15): full recovery —
    detection (silent manager confirmed), digest-equal takeover, and
    the exact-once outcome ledger (zero lost, zero duplicated)."""
    reasons = []
    confirmed = res["audit"]["confirmed"]
    overcount = max(0, res.get("mgr_completed", 0) - res["expected"])
    if res["missing"]:
        reasons.append(f"{len(res['missing'])} task(s) lost across the "
                       f"failover: {res['missing'][:8]}")
    if res["extra_done"]:
        reasons.append(f"uncaptured task id(s) completed: "
                       f"{res['extra_done'][:8]}")
    if overcount:
        reasons.append(f"manager ledger double-counted {overcount} "
                       "completion(s) across the takeover")
    silent_mgr = [d for d in confirmed if d["class"] == "silent"
                  and _proc_of(res, d.get("peer_a") or "").startswith(
                      "manager")]
    detected = bool(silent_mgr)
    if not detected:
        reasons.append("auditor never confirmed the silent active — "
                       "the kill went undetected")
    ha_ev = _ha_takeover_checks(res, reasons)
    return {"fault": "manager_kill_failover",
            "verdict": "green" if not reasons else "red",
            "outcome_ok": not res["missing"] and not res["extra_done"]
            and not overcount,
            "healed": bool(ha_ev["takeovers"]),
            "detected": detected, "localized": detected,
            "ha": ha_ev,
            "confirmed_divergences": confirmed,
            "slo": {"ok": not reasons, "failed": []},
            "reasons": reasons}


def classify(kind: str, res: dict) -> dict:
    """The chaos verdict for one replayed fault: green iff the outcome
    ledger is intact, required detection fired and NAMED the faulted
    role (localization), no RED divergence is still active at the final
    watermark (reconvergence), and the SLO engine passes."""
    if kind == "manager_handoff_kill":
        return classify_handoff_kill(res)
    if kind == "manager_kill_failover":
        return classify_kill_failover(res)
    reasons = []
    confirmed = res["audit"]["confirmed"]
    red_confirmed = [d for d in confirmed
                     if d["class"] in au.RED_CLASSES]
    active_red = [d for d in res["audit"]["active"]
                  if d["class"] in au.RED_CLASSES]
    healed = not active_red
    outcome_ok = res["ok"]
    overcount = max(0, res.get("mgr_completed", 0) - res["expected"])
    if res["missing"]:
        reasons.append(f"{len(res['missing'])} task(s) lost: "
                       f"{res['missing'][:8]}")
    if res["extra_done"]:
        reasons.append(f"uncaptured task id(s) completed: "
                       f"{res['extra_done'][:8]}")
    if overcount:
        reasons.append(f"manager ledger double-counted {overcount} "
                       "completion(s)")
    if not healed:
        reasons.append("RED divergence still active at the final "
                       f"watermark: {active_red}")
    signals = {"replay.completion_ratio": res["completion_ratio"],
               "replay.ledger_overcount": overcount,
               "replay.extra_done": len(res["extra_done"]),
               "replay.missing": len(res["missing"])}
    slo = _slo.evaluate(CHAOS_SPEC, signals)
    if not slo["ok"]:
        reasons.append(f"SLO breach: {slo['failed'] + slo['unknown']}")

    detected = localized = None
    want = _DETECTION_REQUIRED.get(kind)
    if want is not None:
        hits = [d for d in confirmed if d["class"] == "silent"
                and _proc_of(res, d.get("peer_a") or "").startswith(want)]
        detected = bool(hits)
        localized = detected  # a silent record NAMES the quiet peer
        if not detected:
            reasons.append(f"auditor never confirmed a silent {want} "
                           "episode — the fault went undetected")
    elif kind == "clean":
        if red_confirmed:
            reasons.append("clean replay confirmed RED divergence(s): "
                           f"{red_confirmed}")
    elif kind == "shm_peer_kill":
        # the lane-hygiene contract (ISSUE 18): the victim's ring must
        # have been negotiated AND unlinked by busd after the kill
        notes = res.get("chaos_notes") or []
        if not any("lane up" in n for n in notes):
            reasons.append("victim never negotiated an shm lane — the "
                           "kill tested nothing")
        if not any("shm lane reaped" in n for n in notes):
            reasons.append("victim's ring file survived the kill — "
                           "busd never reaped the lane")
    verdict = "green" if not reasons else "red"
    return {"fault": kind, "verdict": verdict,
            "outcome_ok": outcome_ok, "healed": healed,
            "detected": detected, "localized": localized,
            "confirmed_divergences": confirmed,
            "slo": {"ok": slo["ok"], "failed": slo["failed"]},
            "reasons": reasons}


def classify_handoff_kill(res: dict) -> dict:
    """The federated-kill verdict (ISSUE 14 + ISSUE 15):

    - the auditor must confirm a silent MANAGER episode (detection +
      localization; the dead peer never heals, so that record staying
      active at the final watermark is the expected end state);
    - the surviving neighbor must not double-dispatch: no uncaptured id
      completes, the dedup-guarded ledger never overcounts;
    - the handoff protocol must actually have been exercised
      (handoffs_sent >= 1 — a kill before any border crossing tests
      nothing) and the surviving region must still complete tasks.

    Without a standby (JG_HA=0) a dead region manager may strand ITS
    OPEN tasks and the row stays detection-only.  With a standby
    configured the row is RECOVERY-REQUIRED: the dead region's warm
    standby must promote with a digest-equal takeover watermark and
    every captured task must complete exactly once."""
    reasons = []
    confirmed = res["audit"]["confirmed"]
    overcount = max(0, res.get("mgr_completed", 0) - res["expected"])
    fed = res.get("federation") or {}
    ha_on = bool((res.get("ha") or {}).get("enabled"))
    ha_ev = None
    if res["extra_done"]:
        reasons.append(f"uncaptured task id(s) completed: "
                       f"{res['extra_done'][:8]}")
    if overcount:
        reasons.append(f"manager ledger double-counted {overcount} "
                       "completion(s)")
    if not fed.get("handoffs_sent"):
        reasons.append("no handoff ever fired — the kill tested nothing")
    if res["completed"] < 1:
        reasons.append("the surviving region completed no task at all")
    if ha_on:
        # recovery-required (ISSUE 15): the dead region's open tasks
        # must complete via the promoted manager — zero lost
        if res["missing"]:
            reasons.append(f"{len(res['missing'])} task(s) lost despite "
                           f"a standby: {res['missing'][:8]}")
        ha_ev = _ha_takeover_checks(res, reasons)
    silent_mgr = [d for d in confirmed if d["class"] == "silent"
                  and _proc_of(res, d.get("peer_a") or "").startswith(
                      "manager")]
    detected = bool(silent_mgr)
    if not detected:
        reasons.append("auditor never confirmed a silent manager "
                       "episode — the dead region went undetected")
    # reconvergence judged on every OTHER divergence: the killed
    # manager's own silence is the detection, not a failure to heal
    other_red = [d for d in res["audit"]["active"]
                 if d["class"] in au.RED_CLASSES
                 and not (d["class"] == "silent"
                          and _proc_of(res, d.get("peer_a") or ""
                                       ).startswith("manager"))]
    if other_red:
        reasons.append("RED divergence beyond the killed manager still "
                       f"active at the final watermark: {other_red}")
    return {"fault": "manager_handoff_kill",
            "verdict": "green" if not reasons else "red",
            "outcome_ok": not res["extra_done"] and not overcount
            and (not ha_on or not res["missing"]),
            "healed": not other_red,
            "detected": detected, "localized": detected,
            "ha": ha_ev,
            "recovery_required": ha_on,
            "handoffs_sent": fed.get("handoffs_sent"),
            "handoffs_dup_dropped": fed.get("handoffs_dup_dropped"),
            "confirmed_divergences": confirmed,
            "slo": {"ok": not reasons, "failed": []},
            "reasons": reasons}


def determinism_verdict(a: dict, b: dict) -> dict:
    """The replay determinism proof (ISSUE 11 acceptance): two replays
    of one capture must complete the IDENTICAL task-id set and land
    EQUAL audit ledger/view digests at the final (drained) watermark.
    Lane digests (positions) are compared informationally only — the
    planner's assignment interleaving is live by design."""
    completed_equal = a["completed_ids"] == b["completed_ids"]
    digests = {}
    proof_ok = completed_equal
    for key in ("ledger", "view", "view_agents", "lanes"):
        da, db = a["digests"].get(key), b["digests"].get(key)
        if da is None and db is None:
            # never beaconed on this solver path: absent, not unequal —
            # but for the PROOF sections absence still fails (a proof
            # needs evidence)
            equal = None
        else:
            equal = (da is not None and db is not None
                     and da["digest"] == db["digest"]
                     and da["count"] == db["count"])
        digests[key] = {
            "a": None if da is None else f"{da['digest']}/{da['count']}",
            "b": None if db is None else f"{db['digest']}/{db['count']}",
            "equal": equal}
        if key in ("ledger", "view"):
            proof_ok = proof_ok and equal is True
    return {"completed_equal": completed_equal,
            "completed": len(a["completed_ids"]),
            "digests": digests,
            "both_outcomes_ok": a["ok"] and b["ok"],
            "ok": proof_ok and a["ok"] and b["ok"]}


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def run_matrix(capture: dict, faults, log_dir, no_trace: bool,
               drain_s=None, solver_override=None,
               ha_kinds=()) -> dict:
    from analysis import fleetsim

    rows = []
    for i, kind in enumerate(faults):
        fault = build_fault(kind, capture,
                            ha=True if kind in ha_kinds else None)
        solver = (solver_override or capture["fleet"].get("solver")
                  or "native")
        if fault.needs_solverd:
            solver = "tpu"
        shards = max(int(capture["fleet"].get("shards") or 1),
                     fault.needs_shards)
        regions = getattr(fault, "needs_regions", None)
        # the warm-standby rows (ISSUE 15) replay with JG_HA=1 pairs
        ha = bool(getattr(fault, "ha", False))
        print(f"chaos_gate: [{i + 1}/{len(faults)}] fault={kind} "
              f"solver={solver} shards={shards}"
              + (f" regions={regions}" if regions else "")
              + (" ha" if ha else ""), flush=True)
        t0 = time.monotonic()
        res = fleetsim.run_replay(
            capture, log_dir, solver=solver, shards=shards,
            no_trace=no_trace, drain_s=drain_s,
            chaos=None if kind == "clean" else fault,
            label=f"{i}_{kind}", regions=regions, ha=ha)
        verdict = classify(kind, res)
        verdict["fault_detail"] = fault.summary()
        verdict["elapsed_s"] = round(time.monotonic() - t0, 1)
        verdict["replay"] = {k: res[k] for k in
                             ("completed", "expected", "missing",
                              "extra_done", "done_dups",
                              "mgr_completed", "window_tasks_per_s",
                              "drift", "wall_s", "digests",
                              "federation", "ha", "chaos_notes")}
        rows.append((verdict, res))
        print(f"chaos_gate: {kind} -> {verdict['verdict'].upper()}"
              + (f" ({'; '.join(verdict['reasons'])})"
                 if verdict["reasons"] else ""), flush=True)
    return rows


def write_artifact(out: Path, doc: dict) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=2) + "\n")
    md = ["# replay + chaos matrix", "",
          f"capture: `{doc['capture']}` "
          f"({doc['capture_tasks']} task(s), "
          f"{doc['capture_world_events']} world event(s), "
          f"{doc['capture_duration_s']}s)", ""]
    det = doc.get("determinism")
    if det:
        md.append(f"## determinism proof — "
                  f"{'**PASS**' if det['ok'] else '**FAIL**'}")
        md.append("")
        md.append(f"- completed-task sets identical: "
                  f"{det['completed_equal']} "
                  f"({det['completed']} tasks)")
        for k, v in det["digests"].items():
            state = ("absent (not beaconed)" if v["equal"] is None
                     else "equal" if v["equal"] else "DIFFER")
            md.append(f"- {k} digest: `{v['a']}` vs `{v['b']}` — "
                      + state
                      + (" (informational)" if k in ("lanes",)
                         else ""))
        md.append("")
    if doc.get("matrix"):
        md += ["## chaos matrix", "",
               "| fault | verdict | detected | localized | healed "
               "| completed | dups | tasks/s drift |",
               "|---|---|---|---|---|---|---|---|"]
        for v in doc["matrix"]:
            r = v["replay"]
            drift = (r.get("drift") or {}).get("tasks_per_s_pct")
            md.append(
                f"| {v['fault']} | {v['verdict'].upper()} "
                f"| {v.get('detected')} | {v.get('localized')} "
                f"| {v['healed']} "
                f"| {r['completed']}/{r['expected']} "
                f"| {r['done_dups']} "
                f"| {drift if drift is not None else '-'}% |")
        md.append("")
    out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--capture", required=True,
                    help="capture1 file to replay (fleetsim --capture / "
                         "blackbox --capture / auditor auto-dump)")
    ap.add_argument("--faults", default="clean",
                    help=f"comma list of {', '.join(FAULT_KINDS)}")
    ap.add_argument("--ci", action="store_true",
                    help="the CI gate: clean determinism PAIR (two "
                         "replays must agree on completed sets + "
                         "ledger/view digests) then an injected "
                         "solverd SIGKILL that MUST be detected + "
                         "localized by the audit plane")
    ap.add_argument("--determinism", action="store_true",
                    help="run the clean replay twice and add the "
                         "determinism verdict to the artifact")
    ap.add_argument("--trace", action="store_true",
                    help="run replays under JG_TRACE=1 (phase-drift "
                         "fidelity lands in the artifact; slower)")
    ap.add_argument("--solver", choices=["native", "tpu"], default=None,
                    help="override the capture's solver (e.g. drive a "
                         "native capture through a mesh solverd: --solver "
                         "tpu + JG_SOLVER_MESH=2)")
    ap.add_argument("--drain-s", type=float, default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--log-dir", default="/tmp/jg_chaos_logs")
    args = ap.parse_args(argv)

    try:
        capture = _capture.load(args.capture)
    except _capture.CaptureError as e:
        print(f"chaos_gate: bad capture {args.capture}: {e}",
              file=sys.stderr)
        return 2

    faults = [f.strip() for f in args.faults.split(",") if f.strip()]
    ha_kinds = set()
    if args.ci:
        # the CI matrix (ISSUE 11 + 14 + 15): determinism pair, the
        # solverd kill that MUST be detected, the flat SIGKILL-the-
        # active failover that MUST recover (warm standby, digest-equal
        # takeover, exact-once), and the federated manager kill —
        # recovery-required too now that every region pair has a
        # standby
        faults = ["clean", "clean", "solverd_sigkill",
                  "manager_kill_failover", "manager_handoff_kill"]
        ha_kinds = {"manager_kill_failover", "manager_handoff_kill"}
    elif args.determinism:
        faults = ["clean"] + faults

    rows = run_matrix(capture, faults, args.log_dir,
                      no_trace=not args.trace, drain_s=args.drain_s,
                      solver_override=args.solver, ha_kinds=ha_kinds)

    determinism = None
    clean_results = [res for v, res in rows if v["fault"] == "clean"]
    if len(clean_results) >= 2:
        determinism = determinism_verdict(clean_results[0],
                                          clean_results[1])
        print("chaos_gate: determinism proof "
              + ("PASS" if determinism["ok"] else "FAIL")
              + f" — completed sets equal={determinism['completed_equal']}"
              + ", " + ", ".join(
                  f"{k}={'absent' if v['equal'] is None else '==' if v['equal'] else '!='}"
                  for k, v in determinism["digests"].items()),
              flush=True)

    doc = {
        "experiment": "deterministic replay + audit-judged chaos matrix",
        "capture": str(args.capture),
        "solver_override": args.solver,
        "solver_mesh": os.environ.get("JG_SOLVER_MESH") or None,
        "capture_tasks": len(capture["tasks"]),
        "capture_world_events": len(capture.get("world") or []),
        "capture_duration_s": round(capture["duration_ms"] / 1000.0, 1),
        "baseline": capture.get("baseline"),
        "determinism": determinism,
        "matrix": [v for v, _ in rows],
    }
    if args.out:
        write_artifact(Path(args.out), doc)

    ok = all(v["verdict"] == "green" for v, _ in rows)
    if determinism is not None:
        ok = ok and determinism["ok"]
    if args.ci:
        kill = next(v for v, _ in rows if v["fault"] == "solverd_sigkill")
        ok = ok and kill["detected"] and kill["localized"]
        hk = next(v for v, _ in rows
                  if v["fault"] == "manager_handoff_kill")
        ok = ok and hk["detected"] and bool(hk.get("handoffs_sent"))
        # the failover acceptance (ISSUE 15): takeover announced with
        # digest-equal watermark AND nothing lost or duplicated
        fo = next(v for v, _ in rows
                  if v["fault"] == "manager_kill_failover")
        ok = ok and fo["detected"] and fo["outcome_ok"] \
            and bool((fo.get("ha") or {}).get("digests_equal"))
    print(json.dumps({"faults": faults,
                      "verdicts": {v["fault"]: v["verdict"]
                                   for v, _ in rows},
                      "determinism_ok": (determinism or {}).get("ok"),
                      "ok": ok}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
