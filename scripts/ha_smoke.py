#!/usr/bin/env python
"""HA failover smoke (ISSUE 15, scripts/ci.sh): the live takeover proof.

Brings up a fleet with a warm standby per region (active manager(s)
shipping the ledger1 replication stream under JG_HA=1), drives explicit
open-loop tasks through it, SIGKILLs an active MID-FLIGHT, and judges
the takeover:

- **exact-once**: every injected task completes (zero lost — tasks in
  flight at the kill survive through the promoted standby's restore
  hold), no uncaptured id completes, and the managers' dedup-guarded
  completion counters never exceed the injected count (zero
  duplicated);
- **digest-equal takeover watermark**: the promoted standby's
  ``ha_takeover`` announcement must carry ledger/view digests EQUAL to
  the failed active's last shipped ones (the audit-canon equality the
  acceptance is judged on);
- **inside one claim window**: kill -> takeover announcement must land
  within ``--claim-window-s`` (default 5 s, the task-resend grace);
- **detection**: the auditor must confirm the silent active.

``--regions 2x1`` runs the federated variant: two (manager, standby)
pairs, world-spanning tasks, region 1's active killed — the dead
region's open tasks must complete via its promoted standby.

``--out FILE`` writes a JSON artifact (takeover latency, replication
stream overhead bytes/s, outcome ledger) — bench.py's ``ha`` axis and
``results/ha_failover_r16.json`` consume it.

Usage:
  JAX_PLATFORMS=cpu python scripts/ha_smoke.py
  JAX_PLATFORMS=cpu python scripts/ha_smoke.py --regions 2x1
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.obs import audit as _audit  # noqa: E402
from p2p_distributed_tswap_tpu.obs import registry as _reg  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import buspool  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import ha as _ha  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import region as regionlib  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built)
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501

from analysis.fleetsim import MetricsWindow  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--regions", default="1",
                    help="'1' = flat (kill THE active); 'CxR' = "
                         "federated (kill the last region's active)")
    ap.add_argument("--agents", type=int, default=6)
    ap.add_argument("--side", type=int, default=16)
    ap.add_argument("--tasks", type=int, default=8)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--claim-window-s", type=float, default=5.0,
                    help="the takeover budget: kill -> ha_takeover "
                         "(one task-resend claim window)")
    ap.add_argument("--drain-s", type=float, default=90.0)
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the artifact JSON here")
    ap.add_argument("--log-dir", default="/tmp/jg_ha_smoke")
    args = ap.parse_args(argv)

    ensure_built()
    cols, rows = regionlib.fed_parse_spec(args.regions)
    total = cols * rows
    side = args.side
    map_file = f"/tmp/ha_smoke_{side}.map.txt"
    Path(map_file).write_text("\n".join(["." * side] * side) + "\n")
    log_dir = Path(args.log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    port = buspool.free_port()
    saved_env = dict(os.environ)
    procs, logs = [], []

    def spawn(name, cmd, stdin=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ))
        procs.append(p)
        return p

    pool = watch = sim = tap = None
    _reg.get_registry().clear()
    try:
        pool = buspool.BusPool(BUILD_DIR / "mapd_bus", num_shards=1,
                               home_port=port, spawn=spawn)
        time.sleep(0.3)
        os.environ.update(pool.env())
        os.environ["JG_HA"] = "1"
        # fast audit cadence: the silent-active detection must land
        # inside the smoke budget
        os.environ.setdefault("JG_AUDIT_INTERVAL_MS", "500")
        os.environ.setdefault("JG_AUDIT_INTERVAL_S", "0.5")
        mgrs, stbys = [], []
        for rid in range(total):
            tag = f"_r{rid}" if total > 1 else ""
            cmd = [str(BUILD_DIR / "mapd_manager_centralized"),
                   "--port", str(port), "--map", map_file,
                   "--solver", "cpu", "--planning-interval-ms", "150",
                   *regionlib.fed_cli_args(rid, cols, rows, "manager"),
                   "--seed", str(args.seed + rid),
                   "--open-loop", "--ha", "1"]
            mgrs.append(spawn(f"manager{tag}", cmd,
                              stdin=subprocess.PIPE))
            stbys.append(spawn(f"standby{tag}", cmd + ["--standby"],
                               stdin=subprocess.PIPE))
        time.sleep(0.8)
        sim = SimAgentPool(args.agents, side, port=port, seed=args.seed,
                           heartbeat_s=1.0)
        watch = MetricsWindow(port, audit=True)
        # the smoke's own HA tap: takeover announcements + replication
        # stream accounting (frame sizes -> bytes/s overhead)
        tap = BusClient(port=port, peer_id="ha-smoke-tap")
        tap.subscribe(_ha.HA_TOPIC, raw=True)
        sim.heartbeat_all()
        sim.pump(2.0)
        watch.pump(0.5)

        # explicit task set: ids + endpoints, spread over the world (in
        # a federated run: tasks whose pickup the victim region owns
        # MUST survive its death)
        tasks = []
        for k in range(args.tasks):
            px = 1 + (k * 3) % (side - 2)
            py = 1 + (k * 5) % (side - 2)
            dx = side - 2 - (k * 3) % (side - 3)
            dy = side - 2 - (k * 7) % (side - 3)
            rid = regionlib.fed_region_of(px, py, cols, rows, side, side)
            tasks.append((1000 + k, rid, px, py, dx, dy))
        expected = {t[0] for t in tasks}

        takeovers = []
        repl = {"records": 0, "bytes": 0, "first_ms": None,
                "last_ms": None}

        def pump_tap():
            while True:
                f = tap.recv(timeout=0.01)
                if not f:
                    return
                if f.get("op") != "msg":
                    continue
                d = f.get("data") or {}
                if d.get("type") == "ha_takeover":
                    d["_seen_s"] = time.monotonic()
                    takeovers.append(d)
                elif d.get("type") == "ledger1":
                    now_ms = time.monotonic() * 1000.0
                    repl["records"] += 1
                    repl["bytes"] += len(d.get("data") or "")
                    if repl["first_ms"] is None:
                        repl["first_ms"] = now_ms
                    repl["last_ms"] = now_ms

        def pump(seconds):
            end = time.monotonic() + seconds
            last_eval = 0.0
            while time.monotonic() < end:
                sim.pump(0.2)
                watch.pump(0.05)
                pump_tap()
                if time.monotonic() - last_eval >= 0.5:
                    last_eval = time.monotonic()
                    watch.agg.audit.evaluate()

        for tid, rid, px, py, dx, dy in tasks:
            mgrs[rid].stdin.write(
                f"taskat {px} {py} {dx} {dy} {tid}\n".encode())
            mgrs[rid].stdin.flush()
            pump(0.25)

        # mid-flight kill: the LAST region's active (flat: the only
        # one) — its standby must take over inside one claim window
        victim = total - 1
        pump(1.0)
        kill_t = time.monotonic()
        mgrs[victim].send_signal(signal.SIGKILL)
        try:
            mgrs[victim].wait(timeout=10)
        except Exception:
            pass
        print(f"ha_smoke: SIGKILLed region-{victim} active", flush=True)

        deadline = time.monotonic() + args.drain_s
        while time.monotonic() < deadline \
                and not expected <= sim.done_ids:
            pump(0.3)
        pump(2.5)  # final watermark: drained beacons + auditor rounds
        watch.pump(1.0)
        watch.agg.audit.evaluate()

        mgr_proc = "manager_centralized"
        mgr_completed = int(watch.delta(mgr_proc,
                                        "manager.tasks_completed"))
        missing = sorted(expected - sim.done_ids)
        extra = sorted(sim.done_ids - expected)
        takeover = takeovers[0] if takeovers else None
        latency_s = (round(takeover["_seen_s"] - kill_t, 2)
                     if takeover else None)
        digests_equal = bool(takeover
                             and _ha.takeover_digests_equal(takeover))
        silent_mgr = [
            d for d in watch.agg.audit.divergences
            if d["class"] == "silent"
            and ((watch.agg.audit._peers.get(d.get("peer_a") or "")
                  or type("x", (), {"proc": ""})).proc
                 ).startswith("manager")]
        repl_span_s = (max(1e-9, (repl["last_ms"] - repl["first_ms"])
                           / 1000.0)
                       if repl["first_ms"] is not None else None)
        ok = (not missing and not extra
              and mgr_completed <= len(expected)
              and takeover is not None and digests_equal
              and latency_s is not None
              and latency_s <= args.claim_window_s
              and bool(silent_mgr))
        doc = {
            "experiment": "HA failover smoke (ISSUE 15)",
            "regions": f"{cols}x{rows}",
            "agents": args.agents,
            "injected": len(expected),
            "completed": len(sim.done_ids & expected),
            "missing": missing,
            "extra_done": extra,
            "done_dups": sim.done_dups,
            "mgr_completed": mgr_completed,
            "claim_window_s": args.claim_window_s,
            "takeover_latency_s": latency_s,
            "takeover": None if takeover is None else {
                k: takeover.get(k) for k in
                ("peer_id", "ns", "why", "repl_seq", "pending",
                 "inflight", "ledger_digest", "active_ledger_digest",
                 "view_digest", "active_view_digest")},
            "digests_equal": digests_equal,
            "silent_active_detected": bool(silent_mgr),
            "replication": {
                "records": repl["records"],
                "b64_bytes": repl["bytes"],
                "bytes_per_s": (round(repl["bytes"] / repl_span_s, 1)
                                if repl_span_s else None),
            },
            "ok": ok,
        }
        print("ha_smoke: " + json.dumps(doc), flush=True)
        if args.out:
            out = Path(args.out)
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_text(json.dumps(doc, indent=2) + "\n")
        if not ok:
            print("ha_smoke FAILED", file=sys.stderr)
        return 0 if ok else 1
    finally:
        for obj in (sim, watch):
            if obj is not None:
                obj.close()
        if tap is not None:
            tap.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        os.environ.clear()
        os.environ.update(saved_env)


if __name__ == "__main__":
    sys.exit(main())
