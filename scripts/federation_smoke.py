#!/usr/bin/env python
"""Federation smoke (ISSUE 14, scripts/ci.sh): the live 2-region proof.

Brings up a 2x1 federated world — two (manager [, solverd]) pairs on one
busd pool, one wire-faithful sim fleet spanning both rectangles — and
drives an EXPLICIT world-spanning task set through it (open-loop
``taskat``, so the ledger is exact):

- half the tasks live entirely inside one region, half CROSS the border
  (pickup in region 0, delivery in region 1 and vice versa), so at
  least one agent is handed off mid-route;
- every injected task must complete EXACTLY ONCE: the pool's done-id
  ledger must equal the injected set (zero lost), no uncaptured id may
  complete and the managers' dedup-guarded completion counters must not
  exceed the injected count (zero duplicated);
- the handoff protocol must actually run: handoffs sent >= 1 AND acked
  >= 1 across the pair (a smoke that never crosses the border proves
  nothing);
- per-region ledger digests must reconcile at the drained watermark:
  each region pair's audit join must be free of RED divergence and both
  managers' in-flight views must be EMPTY (count 0) — everything that
  entered a ledger left it through a completion.

Usage:
  JAX_PLATFORMS=cpu python scripts/federation_smoke.py
  python scripts/federation_smoke.py --solver tpu   # per-region solverd
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.obs import audit as _audit  # noqa: E402
from p2p_distributed_tswap_tpu.obs import registry as _reg  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import buspool  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import region as regionlib  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built, wait_for_log)
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501

from analysis.fleetsim import MetricsWindow  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--solver", choices=["native", "tpu"],
                    default="native",
                    help="native = the fast CI smoke; tpu adds one "
                         "solverd per region (the full pair "
                         "architecture)")
    ap.add_argument("--agents", type=int, default=8)
    ap.add_argument("--side", type=int, default=20)
    ap.add_argument("--tasks", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--drain-s", type=float, default=90.0)
    ap.add_argument("--log-dir", default="/tmp/jg_federation_smoke")
    args = ap.parse_args(argv)

    ensure_built()
    cols, rows = 2, 1
    total = cols * rows
    side = args.side
    map_file = f"/tmp/federation_smoke_{side}.map.txt"
    Path(map_file).write_text("\n".join(["." * side] * side) + "\n")
    log_dir = Path(args.log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    port = buspool.free_port()
    saved_env = dict(os.environ)
    procs, logs = [], []

    def spawn(name, cmd, stdin=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ))
        procs.append(p)
        return p

    pool = watch = sim = None
    _reg.get_registry().clear()
    try:
        pool = buspool.BusPool(BUILD_DIR / "mapd_bus", num_shards=1,
                               home_port=port, spawn=spawn)
        time.sleep(0.3)
        os.environ.update(pool.env())
        # fast audit cadence: per-region digests must land inside the
        # smoke budget
        os.environ.setdefault("JG_AUDIT_INTERVAL_MS", "500")
        os.environ.setdefault("JG_AUDIT_INTERVAL_S", "0.5")
        if args.solver == "tpu":
            for rid in range(total):
                p = spawn(f"solverd_r{rid}",
                          [sys.executable, "-m",
                           "p2p_distributed_tswap_tpu.runtime.solverd",
                           "--port", str(port), "--map", map_file,
                           "--warm", str(args.agents), "--cpu",
                           *regionlib.fed_cli_args(rid, cols, rows,
                                                   "solverd")])
                if not wait_for_log(log_dir / f"solverd_r{rid}.log",
                                    "solverd up", 900, proc=p):
                    raise RuntimeError(f"solverd_r{rid} never ready")
        mgrs = []
        for rid in range(total):
            mgrs.append(spawn(
                f"manager_r{rid}",
                [str(BUILD_DIR / "mapd_manager_centralized"),
                 "--port", str(port), "--map", map_file,
                 "--solver", "cpu" if args.solver == "native" else "tpu",
                 "--planning-interval-ms", "150",
                 *regionlib.fed_cli_args(rid, cols, rows, "manager"),
                 "--seed", str(args.seed + rid),
                 "--open-loop"],
                stdin=subprocess.PIPE))
        time.sleep(0.6)
        sim = SimAgentPool(args.agents, side, port=port, seed=args.seed,
                           heartbeat_s=1.0)
        watch = MetricsWindow(port, audit=True)
        sim.heartbeat_all()
        sim.pump(2.0)
        watch.pump(0.5)

        # world-spanning task set: explicit endpoints, explicit ids —
        # half CROSS-REGION (both directions: the handoff path must
        # carry real ledger entries over the border both ways), half
        # IN-REGION (a purely local task must also complete exactly
        # once while federation machinery runs around it)
        tasks = []
        for k in range(args.tasks):
            tid = 1000 + k
            if k % 4 == 0:    # cross: r0 pickup -> r1 delivery
                px, py = 2 + (k % 3), 2 + k % (side - 4)
                dx, dy = side - 3, 2 + (k * 3) % (side - 4)
            elif k % 4 == 2:  # cross the other way
                px, py = side - 3 - (k % 3), 1 + k % (side - 4)
                dx, dy = 1 + (k % 3), side - 3 - k % (side - 4)
            elif k % 4 == 1:  # in-region, region 0
                px, py = 1 + (k % 3), 2 + k % (side - 4)
                dx, dy = 4, side - 3 - k % (side - 4)
            else:             # in-region, region 1
                px, py = side - 2 - (k % 3), 2 + k % (side - 4)
                dx, dy = side - 5, side - 3 - k % (side - 4)
            rid = regionlib.fed_region_of(px, py, cols, rows, side, side)
            tasks.append((tid, rid, px, py, dx, dy))
        expected = {t[0] for t in tasks}
        cross = sum(1 for t in tasks
                    if regionlib.fed_region_of(t[2], t[3], cols, rows,
                                               side, side)
                    != regionlib.fed_region_of(t[4], t[5], cols, rows,
                                               side, side))
        for tid, rid, px, py, dx, dy in tasks:
            mgrs[rid].stdin.write(
                f"taskat {px} {py} {dx} {dy} {tid}\n".encode())
            mgrs[rid].stdin.flush()
            sim.pump(0.3)
            watch.pump(0.05)

        deadline = time.monotonic() + args.drain_s
        last_eval = 0.0
        while time.monotonic() < deadline \
                and not expected <= sim.done_ids:
            sim.pump(0.3)
            watch.pump(0.05)
            if time.monotonic() - last_eval >= 0.5:
                last_eval = time.monotonic()
                watch.agg.audit.evaluate()
        # final watermark: let every role beacon its drained digests
        end = time.monotonic() + 2.5
        while time.monotonic() < end:
            sim.pump(0.2)
            watch.pump(0.1)
            watch.agg.audit.evaluate()
        watch.pump(1.0)

        rollup = watch.agg.rollup()
        fed = rollup.get("federation") or {}
        mgr_proc = "manager_centralized"
        mgr_completed = int(watch.delta(mgr_proc,
                                        "manager.tasks_completed"))
        handoffs_sent = int(watch.delta(mgr_proc,
                                        "manager.handoffs_sent"))
        handoffs_acked = int(watch.delta(mgr_proc,
                                         "manager.handoffs_acked"))
        missing = sorted(expected - sim.done_ids)
        extra = sorted(sim.done_ids - expected)
        # per-region ledger reconciliation at the drained watermark:
        # every region manager's newest VIEW digest must count 0
        # in-flight tasks, and the audit joiner must hold no RED
        views = {}
        for name, st in watch.agg.audit._peers.items():
            if not st.proc.startswith("manager"):
                continue
            e = st.latest.get(_audit.SEC_VIEW)
            if e is not None:
                views[f"{st.ns or name}"] = {
                    "digest": _audit.digest_hex(e.digest),
                    "inflight": e.count}
        red = [d for d in watch.agg.audit.active()
               if d["class"] in _audit.RED_CLASSES]
        views_drained = bool(views) and all(
            v["inflight"] == 0 for v in views.values())
        # with per-region solverd pairs, the daemons must have admitted
        # handed-off lanes through the re-admission path (the
        # cause=handoff attribution the managers flag on plan_request)
        lanes_admitted = {}
        for peer, p in rollup["peers"].items():
            for cause, v in (p.get("lanes_admitted") or {}).items():
                lanes_admitted[cause] = lanes_admitted.get(cause, 0) + v
        solverd_ok = (args.solver != "tpu"
                      or lanes_admitted.get("handoff", 0) >= 1)

        ok = (not missing and not extra
              and mgr_completed <= len(expected)
              and handoffs_sent >= 1 and handoffs_acked >= 1
              and 1 <= cross < len(expected)  # mixed: both task kinds ran
              and not red and views_drained
              and solverd_ok)
        print("federation smoke: " + json.dumps({
            "injected": len(expected),
            "cross_region_tasks": cross,
            "completed": len(sim.done_ids & expected),
            "missing": missing,
            "extra_done": extra,
            "done_dups": sim.done_dups,
            "mgr_completed": mgr_completed,
            "handoffs_sent": handoffs_sent,
            "handoffs_acked": handoffs_acked,
            "per_region": fed.get("per_region"),
            "region_views": views,
            "views_drained": views_drained,
            "lanes_admitted": lanes_admitted or None,
            "active_red": red,
            "ok": ok}), flush=True)
        if not ok:
            print("federation smoke FAILED", file=sys.stderr)
        return 0 if ok else 1
    finally:
        for obj in (sim, watch):
            if obj is not None:
                obj.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        os.environ.clear()
        os.environ.update(saved_env)


if __name__ == "__main__":
    sys.exit(main())
