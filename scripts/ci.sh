#!/usr/bin/env bash
# Single CI entrypoint (ISSUE 2 satellite): syntax gate + tier-1 suite.
#
#   scripts/ci.sh            # everything
#   scripts/ci.sh --syntax   # compileall gate only (seconds)
#
# The pytest invocation is the ROADMAP tier-1 command plus --strict-markers
# (unknown @pytest.mark.* names fail fast instead of silently never
# deselecting; known markers are declared in pyproject.toml).  The registry/
# beacon/aggregator tests (tests/test_fleet_metrics.py) and the obs unit
# tests ride inside the tier-1 run — they are Python-only and never skip.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== syntax gate (compileall) =="
# whole trees plus the new entry points by name, so a rename/removal of a
# gated file fails the gate instead of silently shrinking it
python -m compileall -q -f \
    p2p_distributed_tswap_tpu \
    analysis \
    analysis/fleet_top.py \
    analysis/bus_scaling.py \
    analysis/task_timeline.py \
    analysis/blackbox.py \
    p2p_distributed_tswap_tpu/obs/registry.py \
    p2p_distributed_tswap_tpu/obs/beacon.py \
    p2p_distributed_tswap_tpu/obs/events.py \
    p2p_distributed_tswap_tpu/obs/flightrec.py \
    p2p_distributed_tswap_tpu/obs/fleet_aggregator.py \
    p2p_distributed_tswap_tpu/runtime/region.py \
    p2p_distributed_tswap_tpu/runtime/shardmap.py \
    p2p_distributed_tswap_tpu/runtime/buspool.py \
    p2p_distributed_tswap_tpu/runtime/simagent.py \
    p2p_distributed_tswap_tpu/runtime/busns.py \
    p2p_distributed_tswap_tpu/runtime/solverd.py \
    p2p_distributed_tswap_tpu/ops/field_repair.py \
    p2p_distributed_tswap_tpu/ops/field_fused.py \
    p2p_distributed_tswap_tpu/ops/sector.py \
    scripts/sector_fuzz.py \
    analysis/sector_bench.py \
    tests/test_sector.py \
    p2p_distributed_tswap_tpu/obs/slo.py \
    p2p_distributed_tswap_tpu/obs/audit.py \
    scripts/audit_smoke.py \
    scripts/chaos_gate.py \
    p2p_distributed_tswap_tpu/runtime/ha.py \
    scripts/ha_smoke.py \
    p2p_distributed_tswap_tpu/obs/health.py \
    scripts/health_smoke.py \
    p2p_distributed_tswap_tpu/obs/capture.py \
    analysis/fleetsim.py \
    analysis/tenant_scaling.py \
    analysis/field_bench.py \
    p2p_distributed_tswap_tpu/parallel/mesh.py \
    p2p_distributed_tswap_tpu/parallel/solver_mesh.py \
    p2p_distributed_tswap_tpu/parallel/virtual_mesh.py \
    p2p_distributed_tswap_tpu/parallel/sharded.py \
    p2p_distributed_tswap_tpu/parallel/sharded2d.py \
    p2p_distributed_tswap_tpu/ops/tiled_distance.py \
    analysis/mesh_bench.py \
    scripts/mesh_smoke.py \
    scripts/bus_smoke.py \
    scripts/trace_smoke.py \
    scripts/field_fuzz.py \
    scripts/federation_smoke.py \
    p2p_distributed_tswap_tpu/runtime/fleet.py \
    p2p_distributed_tswap_tpu/runtime/plan_codec.py \
    p2p_distributed_tswap_tpu/runtime/shmlane.py \
    p2p_distributed_tswap_tpu/runtime/bus_client.py \
    bench.py
echo "syntax OK"

if [[ "${1:-}" == "--syntax" ]]; then
    exit 0
fi

echo "== codec fuzz gate =="
# random fleets through both plan codecs (ISSUE 3 satellite): py/cpp
# packed encoders must be byte-identical and resident packed planning
# must equal stateless JSON planning; plus pos1 beacon fuzz (ISSUE 4)
JAX_PLATFORMS=cpu python scripts/codec_fuzz.py

echo "== field-repair fuzz gate =="
# ISSUE 9: random obstacle-toggle sequences through the bounded-region
# repair must stay bit-identical to full recompute (chained, so drift
# compounds), incl. ROI-overflow fallback + freed-door window growth
JAX_PLATFORMS=cpu python scripts/field_fuzz.py

echo "== sector planner fuzz gate =="
# ISSUE 19: seeded random worlds + chained toggles through the
# hierarchical sector planner — corridor descent valid from every
# start, suboptimality <= the committed 0.05 bound, and apply_toggles
# == from-scratch rebuild after every block/unblock batch
JAX_PLATFORMS=cpu python scripts/sector_fuzz.py

echo "== busd relay micro-smoke =="
# N-client fanout sanity under the fast relay framing (ISSUE 4): fast +
# legacy subscribers, wildcard region watcher, hub fanout counters
JAX_PLATFORMS=cpu python scripts/bus_smoke.py

echo "== busd shard-pool smoke =="
# federated 3-shard pool (ISSUE 6): cross-shard publish, wildcard
# spanning without duplicates, peering to a legacy client, and the
# one-shard-kill degradation contract
JAX_PLATFORMS=cpu python scripts/bus_smoke.py --shards 3

echo "== busd shm-lane smoke =="
# zero-copy same-host lanes + per-region beacon aggregation (ISSUE 18):
# shm1 negotiation, every beacon over the rings with zero TCP fallbacks,
# >= 4x agg1 fanout cut, lane files reclaimed on close
JAX_PLATFORMS=cpu python scripts/bus_smoke.py --shm

echo "== trace smoke =="
# ISSUE 5: a tiny live fleet under JG_TRACE=1 JG_TRACE_SAMPLE=1.0 must
# reconstruct >= 1 fully-attributed task timeline (task_timeline.py
# --once --json) — proof the trace context propagates on the real wire
JAX_PLATFORMS=cpu python scripts/trace_smoke.py

echo "== fleetsim SLO gate =="
# ISSUE 7: scaled-down production-load rehearsal — a tiny wire-faithful
# sim fleet over a live 2-shard busd pool + centralized manager, judged
# against the relaxed CI spec (deterministic seed).  Any SLO breach OR a
# signal gone dark (exit 2) fails CI.  The breach drill then re-judges
# the SAME measured signals against a known-breaching spec and demands
# exit 1 — proof the gate can actually trip, every run.
if [[ -x cpp/build/mapd_bus ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python analysis/fleetsim.py \
        --agents 24 --side 24 --tick-ms 250 --shards 2 \
        --settle 14 --window 12 --seed 1 \
        --spec scripts/fleetsim_ci.spec.json \
        --out /tmp/jg_fleetsim_ci.json \
        --log-dir /tmp/jg_fleetsim_ci_logs
    drill=0
    JAX_PLATFORMS=cpu python -m p2p_distributed_tswap_tpu.obs.slo \
        --signals /tmp/jg_fleetsim_ci.json \
        --spec scripts/fleetsim_ci.breach.json >/dev/null || drill=$?
    if [[ "$drill" != 1 ]]; then
        echo "fleetsim gate did not trip on the breaching spec" \
             "(exit $drill)" >&2
        exit 1
    fi
    echo "fleetsim gate OK (breach drill tripped as expected)"
else
    echo "fleetsim gate SKIPPED (no C++ toolchain / binaries)"
fi

echo "== dynamic-world smoke =="
# ISSUE 9: a live fleet (busd + manager --solver tpu + solverd + sim
# pool) with walls closing every few seconds via world_update_request;
# the incremental field repairs must route the fleet around them —
# completion ratio 1.0 and >= 1 accepted toggle, judged from the
# artifact the run writes.
if [[ -x cpp/build/mapd_bus ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    # fresh artifact every run: a stale file from a previous invocation
    # must never pass the gate for a build whose run crashed early
    rm -f /tmp/jg_dynworld_ci.json
    JAX_PLATFORMS=cpu python analysis/fleetsim.py \
        --agents 12 --side 24 --tick-ms 250 --solver tpu \
        --settle 12 --window 15 --seed 1 --no-trace \
        --world-toggle-cells 5 --world-toggle-every 5 \
        --spec scripts/fleetsim_ci.spec.json \
        --out /tmp/jg_dynworld_ci.json \
        --log-dir /tmp/jg_dynworld_ci_logs || true
    JAX_PLATFORMS=cpu python - <<'PY'
import json, sys
r = json.load(open("/tmp/jg_dynworld_ci.json"))["rungs"][0]
sig = r["signals"]
world = r.get("world") or {}
ok = (sig.get("fleet.completion_ratio") == 1.0
      and world.get("toggles_accepted", 0) >= 1
      and world.get("updates_seen", 0) >= 1)
print("dynamic-world smoke:", json.dumps({
    "completion": sig.get("fleet.completion_ratio"),
    "world": world}))
sys.exit(0 if ok else 1)
PY
    echo "dynamic-world smoke OK"
    # ISSUE 9 satellite (ROADMAP item 2 headroom): N tenants admitted
    # LIVE through solver.admit tenant_hello — exit 0 iff every tenant
    # is welcomed and completes >= 1 task
    JAX_PLATFORMS=cpu python analysis/fleetsim.py --tenants 2 \
        --agents 4 --side 24 --settle 10 --window 25 \
        --log-dir /tmp/jg_dynworld_ci_logs
else
    echo "dynamic-world smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== audit smoke =="
# ISSUE 10: state-consistency gate, both halves every run — a tiny live
# fleet must end with ZERO confirmed divergences (a fleet that cannot
# prove itself consistent fails CI), then the injected-corruption drill
# must confirm a roster divergence and bisect it to the exact lane +
# field (a gate that cannot trip is no gate)
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python scripts/audit_smoke.py \
        --log-dir /tmp/jg_audit_ci_logs
else
    echo "audit smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== replay + chaos gate =="
# ISSUE 11: the committed capture must replay deterministically — two
# clean replays completing the identical task-id set with equal audit
# ledger/view digests at the final watermark — and then an injected
# solverd SIGKILL mid-replay MUST be detected and localized by the
# audit plane (a confirmed silent record naming solverd) with zero
# tasks lost or duplicated.  A chaos gate that cannot trip is no gate.
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python scripts/chaos_gate.py \
        --capture results/captures/ci_small.capture.json --ci \
        --log-dir /tmp/jg_chaos_ci_logs
    # schema versioning is load-bearing: a future/unknown capture
    # version must be REJECTED (exit 2), never half-replayed
    rej=0
    python - >/dev/null 2>&1 <<'PY' || rej=$?
import json, sys, tempfile, os
sys.path.insert(0, os.getcwd())
doc = json.load(open("results/captures/ci_small.capture.json"))
doc["version"] = "capture999"
p = tempfile.mktemp(suffix=".json")
json.dump(doc, open(p, "w"))
sys.path.insert(0, "scripts")
import chaos_gate
sys.exit(chaos_gate.main(["--capture", p]))
PY
    if [[ "$rej" != 2 ]]; then
        echo "chaos gate accepted an unknown capture version" \
             "(exit $rej)" >&2
        exit 1
    fi
    echo "replay + chaos gate OK (determinism pair held, solverd kill" \
         "detected + localized, unknown version rejected)"
else
    echo "replay + chaos gate SKIPPED (no C++ toolchain / binaries)"
fi

echo "== HA failover smoke =="
# ISSUE 15: a live fleet with a warm standby — SIGKILL the active
# mid-flight; the standby must promote inside one claim window with
# ledger/view digests EQUAL to the active's last shipped ones, the
# auditor must confirm the silent active, and every injected task must
# complete exactly once (zero lost, zero duplicated).  The federated
# variant (2x1: kill one region's active) rides the chaos gate above
# as the recovery-required manager_handoff_kill row.
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python scripts/ha_smoke.py \
        --log-dir /tmp/jg_ha_ci_logs
else
    echo "HA failover smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== health plane smoke =="
# ISSUE 16: the continuous watcher over a live fleet — a steady clean
# run must record ZERO alerts (false-alert gate), then a diurnal-ramp
# overload must be FORECAST >= 2 evaluation intervals before the
# confirmed hard breach, attributed to the overloaded manager with an
# actuator recommendation, and the page must ship an auto-captured
# replayable capture1 artifact.  An alerting plane that cries wolf, or
# one that only confirms after the outage, both fail CI.
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python scripts/health_smoke.py \
        --log-dir /tmp/jg_health_ci_logs
else
    echo "health plane smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== federation smoke =="
# ISSUE 14: a live 2x1 federated world — two (manager, solverd-less)
# region pairs on one bus, explicit world-spanning tasks (half cross
# the border), every task must complete EXACTLY once, the handoff
# protocol must fire and ack, and each region's ledger digests must
# reconcile drained at the final watermark
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python scripts/federation_smoke.py \
        --log-dir /tmp/jg_federation_ci_logs
else
    echo "federation smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== mesh-solverd smoke =="
# ISSUE 13: the mesh==flat digest gate runs unconditionally (byte-
# identical packed responses + audit digests over a 2-way virtual
# mesh, JG_SOLVER_MESH-unset flat-path pin); the live half (tiny fleet
# served BY a mesh solverd, every task completes) self-skips without
# the C++ runtime
JAX_PLATFORMS=cpu python scripts/mesh_smoke.py \
    --log-dir /tmp/jg_mesh_ci_logs

echo "== multi-tenant smoke =="
# ISSUE 8: two namespaced fleets (real C++ managers behind JG_BUS_NS +
# wire-faithful sim pools) on ONE busd + ONE multi-tenant solverd.
# Asserts both tenants complete tasks through the shared device
# super-batch with zero cross-tenant resyncs/evictions — cross-talk on
# the namespaced wire would stall a fleet and fail the gate.
if [[ -x cpp/build/mapd_bus && -x cpp/build/mapd_manager_centralized ]] \
        || { command -v cmake >/dev/null && command -v ninja >/dev/null; }
then
    JAX_PLATFORMS=cpu python analysis/tenant_scaling.py --smoke \
        --log-dir /tmp/jg_tenant_ci_logs
else
    echo "multi-tenant smoke SKIPPED (no C++ toolchain / binaries)"
fi

echo "== tier-1 suite =="
rm -f /tmp/_t1.log
# `|| rc=$?` keeps set -e from aborting before the DOTS_PASSED diagnostic
# below — which matters exactly when tests fail (pipefail makes $? pytest's
# exit status, not tee's)
rc=0
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --strict-markers \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log || rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit "$rc"
