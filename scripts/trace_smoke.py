#!/usr/bin/env python
"""CI trace smoke (ISSUE 5 satellite): a tiny live fleet under
``JG_TRACE=1 JG_TRACE_SAMPLE=1.0`` must reconstruct at least one
fully-attributed task timeline via ``analysis/task_timeline.py --once``.

This is the end-to-end proof that the trace context actually propagates
across the wire in a running fleet — the unit/golden tests prove the
codecs, this proves the plumbing.  Exits 0 on success, 0 with a SKIP
notice when the C++ runtime cannot be built (no toolchain), non-zero on a
real propagation failure.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from p2p_distributed_tswap_tpu.runtime.fleet import BUILD_DIR, Fleet  # noqa: E402


def main() -> int:
    if not (BUILD_DIR / "mapd_bus").exists() and (
            shutil.which("cmake") is None or shutil.which("ninja") is None):
        print("trace smoke: SKIPPED (no C++ toolchain / binaries)",
              file=sys.stderr)
        return 0
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    tmp = Path(tempfile.mkdtemp(prefix="jg-trace-smoke-"))
    trace_dir = tmp / "trace"
    tiny = tmp / "tiny.map.txt"
    tiny.write_text("\n".join(["." * 12] * 12) + "\n")
    env = {"JG_TRACE": "1", "JG_TRACE_DIR": str(trace_dir),
           "JG_TRACE_SAMPLE": "1.0"}
    with Fleet("centralized", num_agents=2, port=port, map_file=str(tiny),
               log_dir=str(tmp / "logs"), env=env) as fleet:
        time.sleep(4)
        fleet.command("tasks 2")
        log_dir = tmp / "logs"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(f.read_text(errors="ignore").count("DONE")
                   for f in log_dir.glob("agent_*.log")) >= 2:
                break
            time.sleep(1)
        time.sleep(2)  # acks settle
        fleet.quit()
    out = subprocess.run(
        [sys.executable, str(ROOT / "analysis" / "task_timeline.py"),
         "--dir", str(trace_dir), "--once", "--json"],
        capture_output=True, text=True, cwd=str(ROOT),
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    summary = json.loads(out.stdout) if out.stdout.strip() else {}
    complete = summary.get("tasks_complete", 0)
    orphans = summary.get("orphans", -1)
    print(f"trace smoke: {complete} fully-attributed task(s), "
          f"{orphans} orphan trace(s), "
          f"coverage {summary.get('coverage')}")
    if out.returncode != 0 or complete < 1:
        print(out.stdout[-2000:], file=sys.stderr)
        print(out.stderr[-2000:], file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
