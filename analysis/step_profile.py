"""Flagship step-cost profile by ablation (VERDICT r2 item 6).

The axon tunnel has a ~100-130 ms dispatch+fetch floor per synchronous
round-trip and no working device profiler, so per-phase microbenchmarks
mostly measure the floor.  Instead each variant runs the SAME pipelined
K-step loop (per-step dispatch, one value fetch at the end — the bench.py
pattern) with one phase ablated; the phase's cost is the difference from
the full step.  Trajectories diverge slightly once a phase is ablated
(stale fields change behavior), so differences are estimates of cost
structure, not exact decompositions — good enough to decide where a Pallas
kernel would (or would not) pay.

Variants:
  full         — the shipped mapd_step
  no_replan    — replan_fn = identity (fields go stale; sweeps ablated)
  no_swap      — swap_rounds = 0 (Rule 3/4 goal exchange ablated)
  kernel_only  — step_parallel alone on frozen fields (no transitions /
                 assignment / replan): the TSWAP rules + movement cascade
  dispatch     — jitted identity on the same state pytree: the tunnel floor

Usage: python analysis/step_profile.py [--rung flagship] [--steps 25]
Prints a markdown table; paste into SCALING.md.
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from p2p_distributed_tswap_tpu.models import scenarios
from p2p_distributed_tswap_tpu.solver import mapd
from p2p_distributed_tswap_tpu.solver.step import step_parallel

WARMUP = 8


def _timed_loop(fn, s, steps, *args):
    for _ in range(WARMUP):
        s = fn(s, *args)
    int(jax.tree.leaves(s)[0].ravel()[0])  # force (axon: fetch, not block)
    t0 = time.perf_counter()
    for _ in range(steps):
        s = fn(s, *args)
    int(jax.tree.leaves(s)[0].ravel()[0])
    return 1000.0 * (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", default="flagship",
                    choices=["ref", "small", "medium", "flagship",
                             "extreme_lite"])
    ap.add_argument("--steps", type=int, default=25)
    args = ap.parse_args()

    scn = {"ref": scenarios.REFERENCE_DEMO, "small": scenarios.SMALL,
           "medium": scenarios.MEDIUM, "flagship": scenarios.FLAGSHIP,
           "extreme_lite": scenarios.EXTREME_LITE}[args.rung]
    grid, starts, tasks, cfg = scn.build(seed=0)
    cfg = dataclasses.replace(cfg, record_paths=False)
    starts_j = jnp.asarray(starts, jnp.int32)
    tasks_j = jnp.asarray(tasks, jnp.int32)
    free_j = jnp.asarray(grid.free)

    huge = cfg.num_cells >= 2048 * 2048
    if huge:
        s0, tasks_j = jax.jit(functools.partial(
            mapd.prepare_state_unprimed, cfg))(starts_j, tasks_j)
        s0 = mapd.host_prime_fields(cfg, s0, free_j)
    else:
        s0, tasks_j = jax.jit(functools.partial(mapd.prepare_state, cfg))(
            starts_j, tasks_j, free_j)
    jax.block_until_ready(s0.pos)

    rows = []

    def run(name, fn, *extra):
        ms = _timed_loop(fn, s0, args.steps, *extra)
        rows.append((name, ms))
        print(f"# {name}: {ms:.1f} ms/step", flush=True)
        return ms

    full = run("full", jax.jit(functools.partial(mapd.mapd_step, cfg)),
               tasks_j, free_j)
    no_replan = run(
        "no_replan",
        jax.jit(functools.partial(mapd.mapd_step, cfg,
                                  replan_fn=lambda c, s, f: s)),
        tasks_j, free_j)
    cfg_ns = dataclasses.replace(cfg, swap_rounds=0)
    no_swap = run("no_swap",
                  jax.jit(functools.partial(mapd.mapd_step, cfg_ns)),
                  tasks_j, free_j)

    def kernel(s, tasks, free):
        pos, goal, slot = step_parallel(cfg, s.pos, s.goal, s.slot, s.dirs)
        return s.replace(pos=pos, goal=goal, slot=slot, t=s.t + 1)

    kern = run("kernel_only", jax.jit(kernel), tasks_j, free_j)
    disp = run("dispatch", jax.jit(lambda s, tasks, free: s),
               tasks_j, free_j)

    print()
    print(f"| phase (ablation) | ms/step | share of full |")
    print(f"|---|---|---|")
    print(f"| full step | {full:.1f} | 100% |")
    print(f"| replan sweeps (full - no_replan) | {full - no_replan:.1f} "
          f"| {100 * (full - no_replan) / full:.0f}% |")
    print(f"| swap phase (full - no_swap) | {full - no_swap:.1f} "
          f"| {100 * (full - no_swap) / full:.0f}% |")
    print(f"| TSWAP kernel alone (rules + movement) | {kern:.1f} "
          f"| {100 * kern / full:.0f}% |")
    print(f"| transitions + assignment (no_replan - kernel) "
          f"| {no_replan - kern:.1f} | {100 * (no_replan - kern) / full:.0f}% |")
    print(f"| dispatch floor (jitted identity) | {disp:.1f} "
          f"| {100 * disp / full:.0f}% |")


if __name__ == "__main__":
    main()
