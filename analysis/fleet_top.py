#!/usr/bin/env python
"""fleet_top — live fleet-wide metrics view over ``mapd.metrics`` beacons.

Every process in a running fleet (solverd, the C++ managers/agents, busd)
publishes its live-metrics registry snapshot on bus topic ``mapd.metrics``
every ~2 s (obs/beacon.py and the cpp/common mirror).  This tool subscribes,
merges the beacons with obs/fleet_aggregator.py, and renders the rollup:
per-peer tick p50/p95 vs the 500 ms planning budget, wire-byte bandwidth,
field-cache hit rate, task-latency percentiles, and last-seen staleness
(dead or wedged peers surface as STALE).

Usage:
    python analysis/fleet_top.py [--port 7400] [--host 127.0.0.1]
        [--interval 2.0]          # live view, ANSI-refreshed (watch-able)
    python analysis/fleet_top.py --once [--json] [--wait 5.0]
        # collect beacons for --wait seconds, print one rollup, exit 0
        # (exit 1 if no beacon arrived) — the harness/CI entry point
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.obs import audit as _audit  # noqa: E402
from p2p_distributed_tswap_tpu.obs import health as _health  # noqa: E402
from p2p_distributed_tswap_tpu.obs import slo as _slo  # noqa: E402
from p2p_distributed_tswap_tpu.obs.beacon import METRICS_TOPIC  # noqa: E402
from p2p_distributed_tswap_tpu.obs.fleet_aggregator import (  # noqa: E402
    FleetAggregator,
)
from p2p_distributed_tswap_tpu.runtime import ha as _ha  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402


def _fmt(v, spec: str = "", dash: str = "-") -> str:
    return dash if v is None else format(v, spec)


def render(rollup: dict, spec=None, color: bool = False) -> str:
    """Plain-text table over the rollup (the live view body)."""
    f = rollup["fleet"]
    lines = [
        f"fleet_top — {f['peers']} peer(s), {f['stale_peers']} stale, "
        f"{rollup['beacons_ingested']} beacon(s); "
        f"ticks {f['ticks']} ({f['ticks_over_budget']} over "
        f"{rollup['budget_ms']:.0f} ms budget)",
        f"{'PEER':<28} {'PROC':<20} {'AGE':>5} {'TICKp50':>8} "
        f"{'TICKp95':>8} {'OVER':>5} {'TX kbps':>8} {'RX kbps':>8} "
        f"{'CACHE%':>7} {'RECOMP':>6} {'TASKS':>6} {'TASKp95':>8}",
    ]
    for peer, p in rollup["peers"].items():
        t, c, k = p["tick"], p["cache"], p["tasks"]
        bw = p["bandwidth"]
        age = f"{p['age_s']:.0f}s" + ("!" if p["stale"] else "")
        proc = p["proc"]
        if p.get("shard") is not None:  # busd pool member (ISSUE 6)
            proc = f"{proc}[s{p['shard']}]"
        lines.append(
            f"{peer[:28]:<28} {proc[:20]:<20} {age:>5} "
            f"{_fmt(t and t['p50_ms'], '.1f'):>8} "
            f"{_fmt(t and t['p95_ms'], '.1f'):>8} "
            f"{_fmt(t and t['over_budget']):>5} "
            f"{bw['sent_kbps']:>8.1f} {bw['recv_kbps']:>8.1f} "
            f"{_fmt(c and round(100 * c['hit_rate'], 1), '.1f'):>7} "
            f"{_fmt(c and c['recompiles']):>6} "
            f"{_fmt(k and k['completed']):>6} "
            f"{_fmt(k and k['latency_p95_ms'], '.0f'):>8}")
    # per-shard bus health (busd rows with a `bus` section): relay
    # fanout, queue depth, peering links + traffic — the live view of
    # each pool member's load
    bus_rows = [(peer, p) for peer, p in rollup["peers"].items()
                if p.get("bus")]
    if bus_rows:
        lines.append("BUS " + " | ".join(
            f"{(('s' + str(p['shard'])) if p.get('shard') is not None else peer)}:"
            f" {p['bus']['fanout_kbps']:.0f}kbps"
            f" q={p['bus']['queued_bytes']}B"
            f" cl={p['bus']['clients']}"
            f" links={p['bus']['peer_links']}"
            f" peer rx/tx={p['bus']['peer_rx_msgs']}/{p['bus']['peer_tx_msgs']}"
            f" drops={p['bus']['slow_consumer_drops']}"
            f" shm={p['bus'].get('shm_lanes', 0)}l"
            f"/{p['bus'].get('shm_rx_frames', 0)}rx"
            f"/{p['bus'].get('shm_fallbacks', 0)}fb"
            f" agg={p['bus'].get('agg_entries', 0)}"
            f"/{p['bus'].get('agg_flushes', 0)}f"
            for peer, p in bus_rows))
    # field-engine health (ISSUE 9): per-cause sweeps, repair counters,
    # queue depth + starvation age, dynamic-world seq — solverd rows
    field_rows = [(peer, p) for peer, p in rollup["peers"].items()
                  if p.get("field")]
    if field_rows:
        def _field_cell(peer, p):
            f = p["field"]
            s = f["sweeps"]
            cell = (f"{peer[:16]}: q={f['queue']} age={f['max_age']}"
                    f" sweeps f/p/r={s.get('fresh_goal', 0)}"
                    f"/{s.get('prime', 0)}/{s.get('repair', 0)}"
                    f" repairs={f['repairs']}"
                    f"(+{f['repair_fallbacks']} full)")
            if f.get("world_seq"):
                cell += f" world_seq={f['world_seq']}"
            if f.get("promotions"):
                cell += f" promoted={f['promotions']}"
            if f.get("mirror_evictions"):
                cell += f" mev={f['mirror_evictions']}"
            sec = f.get("sector")
            if sec:
                cell += (f" sector r/e/f={sec['routes']}"
                         f"/{sec['reentries']}/{sec['fallbacks']}")
            return cell

        lines.append("FIELD " + " | ".join(
            _field_cell(peer, p) for peer, p in field_rows))
    # mesh-sharded solverd (ISSUE 13): mesh shape + per-shard resident
    # MB — the live proof the planning plane actually spans the mesh
    mesh_rows = [(peer, p) for peer, p in rollup["peers"].items()
                 if p.get("mesh")]
    if mesh_rows:
        def _mesh_cell(peer, p):
            msh = p["mesh"]
            per = msh.get("resident_bytes") or {}
            # the aggregator emits shards in numeric order (and dict /
            # JSON round-trips preserve it) — render as-is
            mb = "/".join(f"{v / 2**20:.1f}" for v in per.values())
            return (f"{peer[:16]}: {msh.get('shape') or '?'}"
                    f" dev={msh['devices']}"
                    + (f" resident={mb}MB" if per else ""))

        lines.append("MESH " + " | ".join(
            _mesh_cell(peer, p) for peer, p in mesh_rows))
    # federated world regions (ISSUE 14): per-region tasks/s + the
    # handoff ledger — the live proof every region pair pulls its
    # weight and nothing is stuck mid-transfer
    fed = rollup.get("federation")
    if fed:
        cells = []
        for rname, r in (fed.get("per_region") or {}).items():
            tps = r.get("tasks_per_s")
            cell = (f"{rname}{'!' if r.get('stale') else ''}:"
                    f" {_fmt(tps, '.2f')}/s"
                    f" hs={r['handoffs_sent']}/{r['handoffs_acked']}")
            if r.get("pending_handoffs"):
                cell += f" pend={r['pending_handoffs']}!"
            if r.get("handoffs_dup_dropped"):
                cell += f" dup={r['handoffs_dup_dropped']}"
            if r.get("mirrors"):
                cell += f" mir={r['mirrors']}"
            cells.append(cell)
        lines.append(f"REGIONS {fed['regions']} "
                     f"({fed['managers']} mgr) " + " | ".join(cells))
    # control-plane HA (ISSUE 15): live role census, replica lag, and
    # the last takeover — the operator's one-line answer to "who is the
    # system of record right now, and did a failover happen?"
    ha = rollup.get("ha")
    if ha:
        def _names(peers):
            return ",".join(p[:16] for p in peers) or "-"

        line = (f"HA active={_names(ha['active'])}"
                f" standby={_names(ha['standby'])}"
                f" lag={ha['replica_lag']}")
        if ha.get("takeovers"):
            line += (f" takeovers={ha['takeovers']}"
                     f" lease_expiries={ha['lease_expiries']}")
        if ha.get("demotions"):
            line += f" demotions={ha['demotions']}"
        last = ha.get("last_takeover")
        if last:
            # the ONE digest-equality rule (runtime/ha.py): a
            # cold-start takeover shipped no active digests — there is
            # nothing to compare, which must not render as an alarm
            eq = _ha.takeover_digests_equal(last)
            tag = ("n/a" if eq is None
                   else "EQUAL" if eq else "DIFFER!")
            line += (f" last={str(last.get('peer_id'))[:16]}"
                     f"@{last.get('repl_seq')}"
                     f" digests={tag}")
        lines.append(line)
    # health plane (ISSUE 16): healthd's heartbeat + one ALERT line per
    # active confirmed breach — severity, burning signal, forecast
    # lead, attribution, and the recommended actuator
    health = rollup.get("health")
    if health:
        hb = health.get("beacon")
        line = "HEALTH"
        if hb:
            line += (f" spec={hb.get('spec')}"
                     f" seq={hb.get('seq')}"
                     f" active={hb.get('active')}"
                     f" alerts={hb.get('alerts')}")
            if health.get("stale"):
                line += " STALE!"
        else:
            line += f" alerts={health.get('alerts')}"
        if color and health.get("active"):
            line = f"\x1b[31m{line}\x1b[0m"
        lines.append(line)
        for a in health.get("active") or []:
            al = (f"ALERT {str(a.get('severity')).upper()}"
                  f" [{a.get('name')}] {a.get('signal')}"
                  f"={_fmt(a.get('observed'))}")
            burn = a.get("burn") or {}
            if burn:
                al += (f" burn={_fmt(burn.get('fast'))}"
                       f"/{_fmt(burn.get('slow'))}")
            fc = a.get("forecast")
            if fc:
                al += (f" eta={_fmt(fc.get('eta_s'))}s"
                       f" ({_fmt(fc.get('eta_intervals'))} ivl)")
            att = a.get("attribution")
            if att:
                al += f" ← {att.get('kind')} {att.get('id')}"
            reco = a.get("recommendation")
            if reco:
                al += (f" ⇒ {reco.get('actuator')}"
                       f"({reco.get('target')})")
            if a.get("capture"):
                al += " 📼"
            lines.append(al)
    # world-epoch tracking (ISSUE 10 satellite): every peer carrying a
    # world_seq gauge, plus the audit beacons' per-tenant epochs — a
    # dynamic-world-OFF peer in a toggling fleet renders "OFF!", the
    # visible form of the PR 9 silent-divergence caveat
    world_rows = [(peer, p) for peer, p in rollup["peers"].items()
                  if p.get("world")]
    audit_st = rollup.get("audit")
    if world_rows or (audit_st and audit_st.get("epochs")):
        cells = []
        for peer, p in world_rows:
            w = p["world"]
            dyn = w.get("dynamic")
            tag = "" if dyn is None else (" dyn" if dyn else " OFF!")
            cells.append(f"{peer[:16]}@{w['seq']}{tag}")
        seen = {peer for peer, _ in world_rows}
        for peer, e in ((audit_st or {}).get("epochs") or {}).items():
            if peer in seen:
                continue
            ns = f" ns={e['ns']}" if e.get("ns") else ""
            dyn = e.get("dynamic")
            tag = "" if dyn is None else (" dyn" if dyn else " OFF!")
            cells.append(f"{peer[:16]}@{e['epoch']}{tag}{ns}")
        lines.append("WORLD " + " | ".join(cells))
    # the AUDIT verdict line (ISSUE 10): state-consistency judgment from
    # the embedded auditor — green/amber/red plus the active divergences
    if audit_st:
        head = (f"AUDIT {audit_st['verdict'].upper()}"
                f" peers={audit_st['peers']}"
                f" joins={audit_st['joins']}"
                f" div={audit_st['divergences']}")
        if color:
            tint = {"green": "\x1b[32m", "amber": "\x1b[33m",
                    "red": "\x1b[31m"}[audit_st["verdict"]]
            head = f"{tint}{head}\x1b[0m"
        for d in audit_st.get("active") or []:
            head += (f"  [{d['class']}] {d['peer_a']}"
                     + (f"↔{d['peer_b']}" if d.get("peer_b") else "")
                     + f": {d['detail']}")
        lines.append(head)
    # replay drift (ISSUE 11): the replay driver's progress beacons —
    # injection progress, completions, duplicates, and tasks/s vs the
    # captured original (the live answer to "is this replay faithful?")
    rp = rollup.get("replay")
    if rp:
        line = (f"REPLAY [{rp.get('capture_source') or '?'}] "
                f"inj {_fmt(rp.get('injected'))}/{_fmt(rp.get('total'))}"
                f" done {_fmt(rp.get('done'))}")
        if rp.get("done_dups"):
            line += f" DUPS {rp['done_dups']}!"
        if rp.get("world_injected"):
            line += f" world {rp['world_injected']}"
        line += f"  tasks/s {_fmt(rp.get('tasks_per_s'))}"
        if rp.get("tasks_per_s_delta") is not None:
            line += (f" vs orig {_fmt(rp.get('orig_tasks_per_s'))}"
                     f" (Δ{rp['tasks_per_s_delta']:+g})")
        if rp.get("drift_pct") is not None:
            line += f" drift {rp['drift_pct']:+g}%"
        if rp.get("phase_p95_delta_ms"):
            line += " Δp95 " + " ".join(
                f"{ph}{v:+g}ms"
                for ph, v in sorted(rp["phase_p95_delta_ms"].items()))
        if rp.get("final"):
            line += " (final)"
        lines.append(line)
    # fleet task throughput (ISSUE 7): manager done-counter derivations
    if f.get("tasks_dispatched") is not None:
        ratio = f.get("completion_ratio")
        lines.append(
            f"TASKS fleet {_fmt(f.get('tasks_per_s'), '.2f')}/s"
            f"  completion "
            f"{_fmt(None if ratio is None else 100 * ratio, '.1f')}%"
            f"  dispatched {f['tasks_dispatched']}"
            f"  done {f['tasks_completed']}")
    # live SLO verdicts from the active spec (rollup-resolvable signals
    # only — phase-attribution SLOs read unknown without an event dir,
    # which is the honest live answer, never a silent pass)
    if spec is not None:
        result = _slo.evaluate(spec, _slo.signals_from_rollup(rollup))
        lines.append(_slo.render_line(result, color=color))
    return "\n".join(lines)


def collect(agg: FleetAggregator, bus: BusClient, duration: float) -> int:
    """Pump beacons into the aggregator for ``duration`` seconds; returns
    the number ingested."""
    n = 0
    deadline = time.monotonic() + duration
    last_eval = 0.0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return n
        # judge the embedded auditor DURING the window, not only in the
        # post-collect rollup(): confirm streaks need >= 2 evaluation
        # rounds on fresh evidence, so a single end-of-wait evaluate
        # (--once mode) could never turn divergent beacons into a red
        # verdict no matter how long --wait is
        now = time.monotonic()
        if agg.audit.beacons and now - last_eval > 0.5:
            last_eval = now
            agg.audit.evaluate()
        frame = bus.recv(timeout=min(0.5, remaining))
        if not frame or frame.get("op") != "msg":
            continue
        if frame.get("topic") not in (METRICS_TOPIC, _audit.AUDIT_TOPIC,
                                      _ha.HA_TOPIC,
                                      _health.ALERT_TOPIC):
            continue
        if agg.ingest(frame.get("data") or {}):
            n += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7400)
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-view refresh cadence (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="collect for --wait seconds, print once, exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the raw rollup JSON")
    ap.add_argument("--wait", type=float, default=5.0,
                    help="--once collection window (seconds; spans at "
                         "least two 2 s beacon intervals by default)")
    ap.add_argument("--budget-ms", type=float, default=500.0)
    ap.add_argument("--slo-spec", default=None, metavar="FILE",
                    help="SLO spec JSON to judge the live rollup against "
                         "('default' = the built-in rated-load spec); "
                         "adds a red/green verdict line per SLO")
    args = ap.parse_args(argv)

    spec = None
    if args.slo_spec is not None:
        spec = _slo.load_spec(
            None if args.slo_spec == "default" else args.slo_spec)

    try:
        bus = BusClient(host=args.host, port=args.port, peer_id="fleet_top",
                        reconnect=not args.once)
    except OSError as e:
        print(f"fleet_top: cannot reach bus at {args.host}:{args.port} "
              f"({e})", file=sys.stderr)
        return 1
    bus.subscribe(METRICS_TOPIC)
    # sustained divergence in the live view: pull the fleet's black
    # boxes (throttled) so the moments before the state fork survive
    # for blackbox --audit
    agg = FleetAggregator(budget_ms=args.budget_ms,
                          on_divergence=None if args.once
                          else _audit.flight_dump_trigger(bus))
    if _audit.enabled():
        # the embedded auditor's feed (ISSUE 10); raw — audit beacons
        # ride the un-namespaced operator plane like mapd.metrics
        bus.subscribe(_audit.AUDIT_TOPIC, raw=True)
    if _ha.enabled():
        # takeover announcements (ISSUE 15) feed the HA line's
        # digest-equality tag; subscribed only when the HA plane is on
        bus.subscribe(_ha.HA_TOPIC, raw=True)
    if _health.enabled():
        # healthd's alert1 records + heartbeat (ISSUE 16) feed the
        # HEALTH/ALERT lines; JG_HEALTH unset keeps the wire
        # byte-identical (the pin test in tests/test_health.py)
        bus.subscribe(_health.ALERT_TOPIC, raw=True)

    if args.once:
        collect(agg, bus, args.wait)
        rollup = agg.rollup()
        if not rollup["peers"]:
            print("fleet_top: no metrics beacons observed "
                  f"within {args.wait:.1f}s", file=sys.stderr)
            return 1
        if args.json:
            if spec is not None:
                # the JSON consumer gets the verdicts too — --slo-spec
                # must never be silently ignored by an output mode
                rollup["slo"] = _slo.evaluate(
                    spec, _slo.signals_from_rollup(rollup))
            print(json.dumps(rollup, indent=2))
        else:
            print(render(rollup, spec=spec))
        return 0

    try:
        while True:
            collect(agg, bus, args.interval)
            # ANSI clear + home: a poor man's curses, pipe-safe
            out = render(agg.rollup(), spec=spec,
                         color=sys.stdout.isatty())
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(out, flush=True)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
