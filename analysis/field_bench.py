#!/usr/bin/env python
"""Field-engine bench (ISSUE 9): full resweep vs bounded-region repair
vs the multi-field fused kernel, plus the dynamic-obstacle fleet rung.

Three measured sections feed ``results/field_engine_r11.json``:

1. ``repair_vs_full`` — ms/field of a FULL fixpoint resweep (the jitted
   sweep->extract->pack pipeline solverd's chunk-of-1 pays) against the
   incremental path (ops/field_repair.py repair + band direction
   re-derivation + host repack) for localized obstacle toggles on the
   flagship-style grid, bit-identity asserted per event;
2. ``multi_field`` — the 8-fields-per-program Pallas kernel against the
   XLA doubling-scan baseline.  ON-CHIP ONLY: without a TPU the section
   records interpreter bit-identity plus an explicit NO-GO-by-default
   verdict (the kernel stays opt-in via MAPD_FUSED=1 until a real-step
   win is measured) and the recipe to re-measure;
3. ``fleet`` — a dynamic-obstacle fleetsim rung (walls closing mid-run
   via world_update_request) whose completion ratio must stay 1.0.

Usage:
  python analysis/field_bench.py --out results/field_engine_r11.json
  python analysis/field_bench.py --quick          # CI-scale settings
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.ops import field_fused  # noqa: E402
from p2p_distributed_tswap_tpu.ops import field_repair  # noqa: E402
from p2p_distributed_tswap_tpu.ops.distance import (  # noqa: E402
    direction_fields,
    directions_from_distance,
    distance_fields,
    pack_directions,
)


def _grid(kind: str, side: int, seed: int) -> Grid:
    if kind == "warehouse":
        return Grid.warehouse(side, side)
    if kind == "obstacles":
        return Grid.random_obstacles(side, side, 0.15, seed)
    free = np.ones((side, side), np.bool_)
    return Grid(free)


def bench_repair(side: int, kind: str, events: int, toggle_cells: int,
                 repeats: int, seed: int) -> dict:
    grid = _grid(kind, side, seed)
    free = np.asarray(grid.free).copy()
    rng = np.random.default_rng(seed)
    h, w = free.shape
    free_flat = free.reshape(-1)
    goal = int(rng.choice(np.flatnonzero(free_flat)))

    # the full pipeline one cached field costs solverd (sweep fixpoint ->
    # direction extraction -> nibble pack), jitted exactly like _fields
    full = jax.jit(lambda fr, gl: pack_directions(
        direction_fields(fr, gl).reshape(1, -1)))
    gvec = jnp.asarray([goal], jnp.int32)
    full(jnp.asarray(free), gvec).block_until_ready()  # compile
    full_ms = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        full(jnp.asarray(free), gvec).block_until_ready()
        full_ms.append(1000.0 * (time.perf_counter() - t0))

    dist = np.asarray(distance_fields(jnp.asarray(free), gvec))[0]
    dirs = field_repair.directions_np(dist, free)

    def random_wall():
        """A localized vertical wall of free cells away from the goal."""
        for _ in range(200):
            x = int(rng.integers(1, w - 1))
            y = int(rng.integers(0, max(1, h - toggle_cells)))
            cells = [(y + i) * w + x for i in range(toggle_cells)]
            if goal in cells or not all(free_flat[c] for c in cells):
                continue
            return cells
        return []

    repair_ms = []
    fallbacks = 0
    prev_wall: list = []
    identical = True
    event = 0
    while event < events:
        toggles = list(prev_wall)  # reopen the previous wall
        wall = random_wall()
        if not wall:
            break
        toggles += wall
        for c in prev_wall:
            free_flat[c] = True
        for c in wall:
            free_flat[c] = False
        prev_wall = wall
        t0 = time.perf_counter()
        res = field_repair.repair_field(dist, free, toggles)
        if res is None:
            fallbacks += 1
            dist = np.asarray(distance_fields(jnp.asarray(free), gvec))[0]
            dirs = field_repair.directions_np(dist, free)
            event += 1
            continue
        new_dist, (y0, y1, x0, x1) = res
        b0, b1 = max(0, y0 - 1), min(h, y1 + 1)
        if b1 > b0:
            dirs[b0:b1] = field_repair.directions_np(new_dist, free,
                                                     b0, b1)
        packed = field_repair.pack_rows_np(dirs.reshape(-1))
        ms = 1000.0 * (time.perf_counter() - t0)
        dist = new_dist
        if event > 0:  # event 0 warms the windowed-fixpoint programs
            repair_ms.append(ms)
        # exactness against the ground truth, every event
        ref_d = np.asarray(distance_fields(jnp.asarray(free), gvec))[0]
        ref_p = np.asarray(pack_directions(directions_from_distance(
            jnp.asarray(ref_d)[None],
            jnp.asarray(free)).reshape(1, -1)))[0]
        if not (np.array_equal(dist, ref_d)
                and np.array_equal(packed, ref_p)):
            identical = False
        event += 1

    full_mean = float(np.mean(full_ms))
    repair_mean = float(np.mean(repair_ms)) if repair_ms else None
    return {
        "grid": f"{side}x{side} {kind}",
        "toggle_cells": toggle_cells,
        "events": events,
        "repeats": repeats,
        "full_resweep_ms": [round(v, 2) for v in full_ms],
        "full_resweep_ms_mean": round(full_mean, 2),
        "repair_ms": [round(v, 2) for v in repair_ms],
        "repair_ms_mean": (round(repair_mean, 2)
                           if repair_mean is not None else None),
        "repair_fallbacks": fallbacks,
        "speedup_vs_full": (round(full_mean / repair_mean, 1)
                            if repair_mean else None),
        "bit_identical_to_full_recompute": identical,
    }


def bench_multi_field(repeats: int) -> dict:
    """The multi-field kernel vs the XLA pipeline.  A trustworthy
    measurement needs the compiled TPU path; everywhere else the section
    records interpreter bit-identity and the explicit NO-GO-by-default
    decision."""
    backend = jax.default_backend()
    out: dict = {"backend": backend}
    h, w, g = 64, 128, 16
    rng = np.random.default_rng(0)
    free_np = rng.random((h, w)) > 0.25
    free = jnp.asarray(free_np)
    goals = jnp.asarray(rng.choice(np.flatnonzero(free_np.reshape(-1)),
                                   g, replace=False), jnp.int32)
    ref = np.asarray(directions_from_distance(distance_fields(free, goals),
                                              free))
    if backend == "tpu":
        multi = jax.jit(field_fused.multi_direction_fields)
        xla = jax.jit(lambda fr, gl: directions_from_distance(
            distance_fields(fr, gl), fr))
        multi(free, goals).block_until_ready()
        xla(free, goals).block_until_ready()
        ms_multi, ms_xla = [], []
        for _ in range(repeats):
            t0 = time.perf_counter()
            multi(free, goals).block_until_ready()
            ms_multi.append(1000.0 * (time.perf_counter() - t0) / g)
            t0 = time.perf_counter()
            xla(free, goals).block_until_ready()
            ms_xla.append(1000.0 * (time.perf_counter() - t0) / g)
        out["ms_per_field_multi"] = round(float(np.mean(ms_multi)), 3)
        out["ms_per_field_xla"] = round(float(np.mean(ms_xla)), 3)
        win = out["ms_per_field_multi"] < out["ms_per_field_xla"]
        out["verdict"] = "GO" if win else "NO-GO"
        out["decision"] = (
            "multi-field kernel wins the micro-measure — confirm on real "
            "steps (bench.py medium/flagship with MAPD_FUSED=1) before "
            "flipping the default" if win else
            "multi-field kernel loses the on-chip micro-measure; stays "
            "opt-in (MAPD_FUSED=1)")
        return out
    # no TPU: interpreter identity is the gate, default stays off
    field_fused.INTERPRET = True
    try:
        t0 = time.perf_counter()
        got = np.asarray(field_fused.multi_direction_fields(free, goals))
        interp_s = time.perf_counter() - t0
    finally:
        field_fused.INTERPRET = False
    out["interpreter_bit_identical"] = bool(np.array_equal(got, ref))
    out["interpreter_batch_s"] = round(interp_s, 1)
    out["verdict"] = "NO-GO (unmeasured)"
    out["decision"] = (
        "no TPU attached to this container: the 8-fields-per-program "
        "kernel (grid (G/8,), fields on sublanes — the layout the "
        "round-3/4 roofline named as the GO signal) is verified "
        "bit-identical in interpreter mode but its on-chip win cannot "
        "be measured here, so it stays OPT-IN (MAPD_FUSED=1; =single "
        "keeps the round-3 one-field baseline).  Re-measure on a TPU "
        "host with: MAPD_FUSED=1 python bench.py (medium + flagship "
        "rungs) and python analysis/field_bench.py — default-on only "
        "if it wins real steps.")
    return out


def bench_fleet(args) -> dict:
    """Dynamic-obstacle fleetsim rung: walls close mid-run, completion
    ratio must hold 1.0 (acceptance (c))."""
    root = Path(__file__).resolve().parents[1]
    from p2p_distributed_tswap_tpu.runtime.fleet import BUILD_DIR
    import shutil

    if not (BUILD_DIR / "mapd_bus").exists() \
            and (shutil.which("cmake") is None
                 or shutil.which("ninja") is None):
        return {"skipped": "C++ runtime unavailable"}
    out = Path("/tmp/jg_field_bench_fleet.json")
    out.unlink(missing_ok=True)
    cmd = [sys.executable, str(root / "analysis" / "fleetsim.py"),
           "--agents", str(args.fleet_agents), "--side", "24",
           "--tick-ms", "250", "--settle", "14",
           "--window", str(args.fleet_window), "--seed", "1",
           "--solver", "tpu", "--world-toggle-cells", "6",
           "--world-toggle-every", "5", "--no-trace",
           "--log-dir", "/tmp/jg_field_bench_fleet_logs",
           "--out", str(out)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=600,
                              env=dict(os.environ, JAX_PLATFORMS="cpu"),
                              cwd=root)
    except subprocess.TimeoutExpired:
        return {"error": "fleetsim timeout"}
    if not out.exists():
        return {"error": (proc.stderr or proc.stdout or "no output")[-300:]}
    rung = json.loads(out.read_text())["rungs"][0]
    sig = rung.get("signals") or {}
    return {
        "agents": rung.get("agents"),
        "tick_ms": rung.get("tick_ms"),
        "world": rung.get("world"),
        "tasks_per_s": sig.get("fleet.tasks_per_s"),
        "completion_ratio": sig.get("fleet.completion_ratio"),
        "completion_ratio_is_1": sig.get("fleet.completion_ratio") == 1.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--side", type=int, default=1024)
    ap.add_argument("--map", choices=["warehouse", "obstacles", "empty"],
                    default="warehouse")
    ap.add_argument("--events", type=int, default=8,
                    help="toggle events (event 0 warms the jitted "
                         "window programs and is not timed)")
    ap.add_argument("--toggle-cells", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--quick", action="store_true",
                    help="CI-scale: 256^2, 4 events, 1 repeat, no fleet")
    ap.add_argument("--no-fleet", action="store_true")
    ap.add_argument("--fleet-agents", type=int, default=12)
    ap.add_argument("--fleet-window", type=float, default=25.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.quick:
        args.side, args.events, args.repeats = 256, 4, 1
        args.no_fleet = True

    doc = {
        "experiment": "incremental field engine: full resweep vs "
                      "bounded-region repair vs multi-field kernel "
                      "(ISSUE 9)",
        "backend": jax.default_backend(),
        "host_note": "CPU-container numbers bound the DISPATCH/HOST "
                     "cost shape, not on-chip ms (SCALING.md quotes "
                     "~2.5-3.3 ms/field on a v5e); the repair-vs-full "
                     "RATIO is the portable claim.",
    }
    print(f"field_bench: repair vs full @ {args.side}^2 {args.map}",
          flush=True)
    doc["repair_vs_full"] = bench_repair(args.side, args.map, args.events,
                                         args.toggle_cells, args.repeats,
                                         args.seed)
    print(json.dumps(doc["repair_vs_full"]), flush=True)
    print("field_bench: multi-field kernel", flush=True)
    doc["multi_field"] = bench_multi_field(args.repeats)
    print(json.dumps(doc["multi_field"]), flush=True)
    if not args.no_fleet:
        print("field_bench: dynamic-obstacle fleet rung", flush=True)
        doc["fleet"] = bench_fleet(args)
        print(json.dumps(doc["fleet"]), flush=True)

    r = doc["repair_vs_full"]
    ok = bool(r["bit_identical_to_full_recompute"]
              and (r["speedup_vs_full"] or 0) >= 5.0)
    doc["acceptance"] = {
        "repair_ge_5x_cheaper": (r["speedup_vs_full"] or 0) >= 5.0,
        "repair_bit_identical": r["bit_identical_to_full_recompute"],
        "multi_field_verdict": doc["multi_field"]["verdict"],
        "fleet_completion_1": (doc.get("fleet") or {}).get(
            "completion_ratio_is_1"),
    }
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2) + "\n")
        md = [
            "# field_engine — incremental repair + multi-field kernel",
            "",
            f"- grid: {r['grid']}, backend {doc['backend']}",
            f"- full resweep: **{r['full_resweep_ms_mean']} ms/field**",
            f"- bounded-region repair: **{r['repair_ms_mean']} ms** per "
            f"localized {r['toggle_cells']}-cell wall event "
            f"(**{r['speedup_vs_full']}x** cheaper; "
            f"{r['repair_fallbacks']} fallback(s); bit-identical: "
            f"{r['bit_identical_to_full_recompute']})",
            f"- multi-field kernel: {doc['multi_field']['verdict']} — "
            f"{doc['multi_field']['decision']}",
        ]
        if doc.get("fleet") and not doc["fleet"].get("skipped"):
            f = doc["fleet"]
            md.append(f"- dynamic-obstacle fleet rung: {f['agents']} "
                      f"agents, {(f.get('world') or {}).get('requests')} "
                      f"wall event(s), completion ratio "
                      f"{f['completion_ratio']} "
                      f"(1.0: {f['completion_ratio_is_1']})")
        out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")
    print(json.dumps({"acceptance": doc["acceptance"]}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
