"""Live-fleet bus-fanout scaling harness (ISSUE 4 acceptance).

The reference's scalability post-mortem names the O(N²) position broadcast
as its first wall and proposes — but never builds — geographic topic
partitioning (DECENTRALIZED_ISSUES.md:62-96).  This harness measures what
the built version buys, on a REAL fleet: busd + the decentralized manager
+ N real ``mapd_agent_decentralized`` processes closing the task loop at a
fast decision tick.  Three variants, worst first:

- ``flat-json``  — JG_REGION_GOSSIP=0 + JG_BUS_FASTFRAME=0: the pre-ISSUE-4
  wire (flat topic, JSON beacons, JSON-parsing relay) — the baseline.
- ``flat``       — region gossip still off, but the busd relay fast path on
  (topic-peek framing + coalesced writev): isolates the hub-side win on
  identical traffic.
- ``region``     — the defaults: pos1 beacons on mapd.pos.<rx>.<ry> region
  topics, 3x3 neighborhood subscriptions, manager on the wildcard.

``--shards`` (ISSUE 6) sweeps the FEDERATED BUS POOL on top of the region
variant: ``--shards 1,3`` runs the single hub and a 3-shard pool on
identical traffic.  Pool rows carry aggregate AND per-shard numbers
(fanout, CPU, peering traffic) from each shard's own beacon
(peer "busd-s<i>"), plus summed /proc CPU across the pool — the
acceptance metric is aggregate hub CPU per message and per-shard peak
fanout vs the single-hub baseline, at no tasks/s regression.

All numbers come from the processes' own ``mapd.metrics`` beacons (busd's
per-topic ``bus.fanout_msgs/bytes`` registry counters, diffed across the
measurement window) plus busd's /proc CPU clock — no instrumentation is
added for the benchmark.  For the flat variants the position share of the
mixed "mapd" topic is sampled by a short-lived spy BEFORE the window (the
spy disconnects first, so it never inflates the measured fanout).

Usage:
  python analysis/bus_scaling.py --out results/bus_scaling.json
  python analysis/bus_scaling.py --agents 10 --window 10   # smoke

Defaults match the SCALING.md rung: 50 agents / 20 ms tick / the 100x100
reference map, with JG_REGION_CELLS=16 (on a 100² map the 32-cell default
makes one 3x3 neighborhood span nearly the whole grid; 16 matches the
radius-15 view — big maps keep the 32 default).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.core.config import RuntimeConfig  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import region  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.buspool import (  # noqa: E402
    free_port as _free_port)
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    Fleet, ensure_built)

VARIANTS = {
    "flat-json": {"JG_REGION_GOSSIP": "0", "JG_BUS_FASTFRAME": "0"},
    "flat": {"JG_REGION_GOSSIP": "0"},
    "region": {},
    # ISSUE 18: region wire + same-host shared-memory rings — identical
    # traffic, the droppable class moves out of the TCP stack entirely
    "shm": {"JG_BUS_SHM": "1"},
    # rings + per-region beacon coalescing (one agg1 frame per window)
    "shm-agg": {"JG_BUS_SHM": "1", "JG_BUS_AGG_MS": "10"},
}


def _proc_cpu_s(pid: int) -> float:
    """utime+stime of a pid, seconds (Linux /proc)."""
    parts = Path(f"/proc/{pid}/stat").read_text().rsplit(") ", 1)[1].split()
    hz = os.sysconf("SC_CLK_TCK")
    return (int(parts[11]) + int(parts[12])) / hz


class BeaconWatch:
    """Collect mapd.metrics beacons per process name."""

    def __init__(self, port: int):
        self.bus = BusClient(port=port, peer_id="beaconwatch")
        self.bus.subscribe("mapd.metrics")
        self.samples = {}  # proc -> list of (mono_t, metrics)

    def pump(self, budget_s: float):
        end = time.monotonic() + budget_s
        while True:
            now = time.monotonic()
            if now >= end:
                return
            f = self.bus.recv(timeout=min(0.2, end - now))
            if not f or f.get("op") != "msg":
                continue
            d = f.get("data") or {}
            if d.get("type") == "metrics_beacon":
                # busd pool members are distinct peers ("busd-s<i>"): key
                # them by peer_id so per-shard windows don't interleave
                key = d.get("proc")
                if key == "busd":
                    key = d.get("peer_id") or key
                self.samples.setdefault(key, []).append(
                    (time.monotonic(), d.get("metrics") or {}))

    def window(self, proc: str):
        s = self.samples.get(proc) or []
        if len(s) < 2:
            return None
        return s[0][1], s[-1][1]

    def busd_keys(self):
        """Every busd peer seen ("busd" single hub / "busd-s<i>" pool)."""
        return sorted(k for k in self.samples if str(k).startswith("busd"))

    def close(self):
        self.bus.close()


def _counter_delta(first, last, name, topic_prefix=None, topic=None):
    """Sum of `name{topic=...}` deltas, filtered by exact topic or
    prefix (None = all labels)."""
    total = 0.0
    for key, v in (last.get("counters") or {}).items():
        if not (key == name or key.startswith(name + "{")):
            continue
        if topic is not None and f'topic="{topic}"' not in key:
            continue
        if topic_prefix is not None \
                and f'topic="{topic_prefix}' not in key:
            continue
        total += v - (first.get("counters") or {}).get(key, 0.0)
    return total


def _sample_pos_share(port: int, seconds: float) -> dict:
    """Byte/message share of position traffic on the flat 'mapd' topic,
    from a short-lived spy (closed before the measurement window)."""
    spy = BusClient(port=port, peer_id="pos-share-spy")
    spy.subscribe("mapd")
    by = {"pos_bytes": 0, "other_bytes": 0, "pos_msgs": 0, "other_msgs": 0}
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        f = spy.recv(timeout=0.2)
        if not f or f.get("op") != "msg" or f.get("topic") != "mapd":
            continue
        d = f.get("data") or {}
        size = len(json.dumps(d))
        if d.get("type") in ("position", "position_update", "pos1"):
            by["pos_bytes"] += size
            by["pos_msgs"] += 1
        else:
            by["other_bytes"] += size
            by["other_msgs"] += 1
    spy.close()
    tot = by["pos_bytes"] + by["other_bytes"]
    by["pos_byte_share"] = round(by["pos_bytes"] / tot, 4) if tot else 0.0
    return by


def _pool_cpu_s(pids) -> float:
    """Summed utime+stime across the busd pool (a dead pid counts 0)."""
    total = 0.0
    for pid in pids:
        try:
            total += _proc_cpu_s(pid)
        except (OSError, IndexError, ValueError):
            pass
    return total


def _busd_delta(watch: BeaconWatch, name: str, **kw) -> float:
    """Counter delta summed across every busd pool member's window."""
    total = 0.0
    for key in watch.busd_keys():
        win = watch.window(key)
        if win:
            total += _counter_delta(win[0], win[1], name, **kw)
    return total


def run_variant(variant: str, args, map_file: str, tick_ms: int,
                shards: int = 1) -> dict:
    port = _free_port()
    env = dict(VARIANTS[variant])
    env["JG_REGION_CELLS"] = str(args.region_cells)
    if shards > 1:
        env["JG_BUS_SHARDS"] = str(shards)
    if args.cpu_affinity:
        # per-shard relay pinning (ISSUE 8 satellite / ROADMAP item 1
        # headroom): each busd shard owns a core on many-core hosts
        env["JG_BUS_CPU_AFFINITY"] = args.cpu_affinity
    cfg = RuntimeConfig(decision_interval_ms=tick_ms)
    log_dir = Path(args.log_dir) \
        / f"{variant}_s{shards}_{args.agents}_{tick_ms}"
    watch = None
    with Fleet("decentralized", num_agents=args.agents, port=port,
               map_file=map_file, log_dir=str(log_dir), env=env,
               config=cfg, bus_shards=shards) as fleet:
        try:
            busd_pids = [p.pid for p in fleet.bus_pool.procs]
            time.sleep(3 + args.agents * 0.05)  # discovery + initial pos
            fleet.command(f"tasks {args.agents}")
            watch = BeaconWatch(port)
            t_end = time.monotonic() + args.settle
            next_tasks = 0.0
            while time.monotonic() < t_end:
                watch.pump(0.5)
                if time.monotonic() >= next_tasks:
                    next_tasks = time.monotonic() + 3.0
                    fleet.command(f"tasks {args.agents}")
            # flat variants: sample the position share of the mixed topic
            # BEFORE the window; the spy disconnects so the measured
            # fanout never includes it
            pos_share = None
            if variant in ("flat-json", "flat"):
                pos_share = _sample_pos_share(port, 2.0)
            watch.samples.clear()
            cpu0 = _pool_cpu_s(busd_pids)
            t0 = time.monotonic()
            t_end = t0 + args.window
            while time.monotonic() < t_end:
                watch.pump(0.5)
                if time.monotonic() >= next_tasks:
                    next_tasks = time.monotonic() + 3.0
                    fleet.command(f"tasks {args.agents}")
            cpu1 = _pool_cpu_s(busd_pids)
            wall = time.monotonic() - t0
            busd_keys = [k for k in watch.busd_keys()
                         if watch.window(k) is not None]
            if not busd_keys:
                # the fleet collapsed under this wire (e.g. the flat JSON
                # broadcast at 50 agents / 20 ms saturates the host: the
                # scheduler starves even the hub's 2 s beacon) — that IS
                # the measurement: this variant's ceiling is below the
                # rung.  Record the collapse instead of crashing.
                fleet.quit()
                return {
                    "variant": variant,
                    "shards": shards,
                    "agents": args.agents,
                    "tick_ms": tick_ms,
                    "window_s": round(wall, 1),
                    "collapsed": True,
                    "busd_cpu_pct": round(100 * (cpu1 - cpu0) / wall, 1),
                    "note": "no busd beacons landed in the window; fleet "
                            "unsustainable at this rung on this host",
                }
            fan_msgs = _busd_delta(watch, "bus.fanout_msgs")
            fan_bytes = _busd_delta(watch, "bus.fanout_bytes")
            if variant not in ("flat-json", "flat"):
                pos_fan_bytes = _busd_delta(
                    watch, "bus.fanout_bytes",
                    topic_prefix=region.POS_TOPIC_PREFIX)
                pos_fan_msgs = _busd_delta(
                    watch, "bus.fanout_msgs",
                    topic_prefix=region.POS_TOPIC_PREFIX)
            else:
                first, last = watch.window(busd_keys[0])
                share = pos_share["pos_byte_share"]
                pos_fan_bytes = _counter_delta(
                    first, last, "bus.fanout_bytes", topic="mapd") * share
                pos_fan_msgs = _counter_delta(
                    first, last, "bus.fanout_msgs", topic="mapd") \
                    * (pos_share["pos_msgs"]
                       / max(1, pos_share["pos_msgs"]
                             + pos_share["other_msgs"]))
            # task completions observed by the manager in the window
            mgr = watch.window("manager_decentralized")
            tasks_done = 0
            if mgr is not None:
                h0 = (mgr[0].get("hists") or {}).get("task.total_time_ms")
                h1 = (mgr[1].get("hists") or {}).get("task.total_time_ms")
                tasks_done = (h1 or {}).get("count", 0) \
                    - (h0 or {}).get("count", 0)
            row = {
                "variant": variant,
                "shards": shards,
                "agents": args.agents,
                "tick_ms": tick_ms,
                "window_s": round(wall, 1),
                "relayed_msgs_per_s": round(fan_msgs / wall, 1),
                "relayed_kb_per_s": round(fan_bytes / wall / 1024, 1),
                "pos_fanout_bytes_per_peer_per_s": round(
                    pos_fan_bytes / wall / args.agents, 1),
                "pos_fanout_msgs_per_s": round(pos_fan_msgs / wall, 1),
                "busd_cpu_pct": round(100 * (cpu1 - cpu0) / wall, 1),
                "busd_cpu_us_per_msg": round(
                    1e6 * (cpu1 - cpu0) / max(fan_msgs, 1), 2),
                "slow_consumer_drops": int(_busd_delta(
                    watch, "bus.slow_consumer_drops")),
                "tasks_done_in_window": int(tasks_done),
            }
            if variant.startswith("shm"):
                # lane-plane evidence: how much of the fanout actually
                # rode the rings, and whether overflow fallbacks fired
                row["shm_tx_frames_per_s"] = round(
                    _busd_delta(watch, "bus.shm_tx_frames") / wall, 1)
                row["shm_rx_frames_per_s"] = round(
                    _busd_delta(watch, "bus.shm_rx_frames") / wall, 1)
                row["shm_fallbacks"] = int(_busd_delta(
                    watch, "bus.shm_fallbacks"))
            if variant == "shm-agg":
                row["agg_flushes_per_s"] = round(
                    _busd_delta(watch, "bus.agg_flushes") / wall, 1)
                row["agg_entries_per_flush"] = round(
                    _busd_delta(watch, "bus.agg_entries")
                    / max(1.0, _busd_delta(watch, "bus.agg_flushes")), 1)
            if shards > 1:
                # per-shard breakdown: peak fanout (the new headroom
                # metric), CPU share, and the peering tax
                per_shard = {}
                for key in busd_keys:
                    w = watch.window(key)
                    per_shard[key] = {
                        "fanout_kb_per_s": round(_counter_delta(
                            w[0], w[1], "bus.fanout_bytes") / wall / 1024,
                            1),
                        "fanout_msgs_per_s": round(_counter_delta(
                            w[0], w[1], "bus.fanout_msgs") / wall, 1),
                        "peer_rx_msgs_per_s": round(_counter_delta(
                            w[0], w[1], "bus.peer_rx_msgs") / wall, 1),
                        "peer_tx_msgs_per_s": round(_counter_delta(
                            w[0], w[1], "bus.peer_tx_msgs") / wall, 1),
                    }
                row["per_shard"] = per_shard
                row["peak_shard_fanout_kb_per_s"] = max(
                    (v["fanout_kb_per_s"] for v in per_shard.values()),
                    default=0.0)
            if pos_share is not None:
                row["pos_byte_share_sampled"] = pos_share["pos_byte_share"]
            fleet.quit()
            return row
        finally:
            if watch is not None:
                watch.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=50)
    ap.add_argument("--ticks", default="50,20",
                    help="decision-tick ladder (ms), heaviest-sustainable "
                         "first; the flat variants may collapse at the "
                         "fastest rungs — that is recorded, not fatal")
    ap.add_argument("--side", type=int, default=100,
                    help="map side (default: the 100x100 reference map)")
    ap.add_argument("--region-cells", type=int, default=16,
                    help="JG_REGION_CELLS for the fleet (16 matches the "
                         "radius-15 view on a 100² map)")
    ap.add_argument("--variants", default="flat-json,flat,region")
    ap.add_argument("--shards", default="1",
                    help="busd pool sizes to sweep on the region variant "
                         "(comma list, e.g. 1,3); the flat variants always "
                         "run the single hub")
    ap.add_argument("--cpu-affinity", default="",
                    help="pin busd shard i to cpu list[i %% len] "
                         "('0,1,2' or 'auto'; needs a many-core host "
                         "to show the pool's aggregate CPU win)")
    ap.add_argument("--settle", type=float, default=8.0)
    ap.add_argument("--window", type=float, default=20.0)
    ap.add_argument("--log-dir", default="/tmp/bus_scaling_logs")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    ensure_built()

    map_file = f"/tmp/bus_scaling_{args.side}.map.txt"
    Path(map_file).write_text(
        "\n".join(["." * args.side] * args.side) + "\n")

    shard_sweep = [int(s) for s in args.shards.split(",")]
    rows = []
    for tick_ms in [int(t) for t in args.ticks.split(",")]:
        for variant in args.variants.split(","):
            # the shard sweep applies to the region variant (the pool
            # routes region topics); flat variants stay single-hub
            for shards in (shard_sweep if variant == "region" else [1]):
                row = run_variant(variant, args, map_file, tick_ms, shards)
                rows.append(row)
                print(json.dumps(row), flush=True)
                time.sleep(2)  # let the previous fleet's ports drain

    by_tick = {}
    for r in rows:
        key = r["variant"] if r.get("shards", 1) <= 1 \
            else f"{r['variant']}-s{r['shards']}"
        by_tick.setdefault(r["tick_ms"], {})[key] = r
    result = {
        "experiment": "live-fleet bus fanout: region gossip + pos1 + busd "
                      "fast path vs the flat JSON wire",
        "map": f"{args.side}x{args.side} empty",
        "agents": args.agents,
        "ticks_ms": sorted(by_tick),
        "region_cells": args.region_cells,
        "note": ("pos fanout for the flat variants = busd "
                 "fanout{topic=mapd} x the spy-sampled position byte "
                 "share; region = the mapd.pos.* topics exactly.  "
                 "flat-json is the pre-ISSUE-4 baseline (JSON relay, "
                 "flat topic); flat isolates the busd relay fast path; "
                 "region adds interest-scoped fanout + packed pos1."),
        "rows": rows,
    }
    ratios = {}
    for tick_ms, by in sorted(by_tick.items()):
        fj, rg = by.get("flat-json", {}), by.get("region", {})
        if fj.get("pos_fanout_bytes_per_peer_per_s") \
                and rg.get("pos_fanout_bytes_per_peer_per_s"):
            ratios[str(tick_ms)] = round(
                fj["pos_fanout_bytes_per_peer_per_s"]
                / rg["pos_fanout_bytes_per_peer_per_s"], 1)
        if fj.get("collapsed") and not rg.get("collapsed"):
            result.setdefault("ceiling", {})[str(tick_ms)] = (
                "flat JSON wire collapses at this rung on this host; "
                "region gossip sustains it "
                f"({rg.get('tasks_done_in_window')} tasks in the window)")
        if "busd_cpu_us_per_msg" in fj \
                and "busd_cpu_us_per_msg" in by.get("flat", {}):
            result.setdefault(
                "busd_cpu_us_per_msg_flatjson_vs_fast", {})[
                str(tick_ms)] = [fj["busd_cpu_us_per_msg"],
                                 by["flat"]["busd_cpu_us_per_msg"]]
    if ratios:
        result["pos_fanout_bytes_ratio_flatjson_over_region"] = ratios
    # shm-lane comparison (ISSUE 18 acceptance: µs/msg strictly below
    # the TCP region wire on identical traffic)
    for tick_ms, by in sorted(by_tick.items()):
        rg = by.get("region", {})
        for key in ("shm", "shm-agg"):
            r = by.get(key, {})
            if rg.get("busd_cpu_us_per_msg") is None \
                    or r.get("busd_cpu_us_per_msg") is None:
                continue
            result.setdefault("busd_cpu_us_per_msg_region_vs_" + key,
                              {})[str(tick_ms)] = [
                rg["busd_cpu_us_per_msg"], r["busd_cpu_us_per_msg"]]
    # shard-pool vs single-hub comparison at each rung (ISSUE 6
    # acceptance: aggregate CPU/msg and per-shard peak fanout improve,
    # tasks/s holds)
    for tick_ms, by in sorted(by_tick.items()):
        single = by.get("region", {})
        for key, r in by.items():
            if r.get("shards", 1) <= 1 or r.get("collapsed") \
                    or single.get("collapsed") or not single:
                continue
            cmp = {
                "busd_cpu_us_per_msg": [single.get("busd_cpu_us_per_msg"),
                                        r.get("busd_cpu_us_per_msg")],
                "peak_shard_fanout_kb_per_s": [
                    single.get("relayed_kb_per_s"),
                    r.get("peak_shard_fanout_kb_per_s")],
                "tasks_done_in_window": [
                    single.get("tasks_done_in_window"),
                    r.get("tasks_done_in_window")],
            }
            result.setdefault("pool_vs_single_hub", {}).setdefault(
                str(tick_ms), {})[key] = cmp
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(result, indent=2))
        md = ["| variant | shards | tick | relayed msg/s | relayed KB/s "
              "| pos B/peer/s | busd CPU % | CPU µs/msg | drops "
              "| tasks done | peak shard KB/s |",
              "|---|---|---|---|---|---|---|---|---|---|---|"]
        for r in rows:
            if r.get("collapsed"):
                md.append(f"| {r['variant']} | {r.get('shards', 1)} | "
                          f"{r['tick_ms']} ms | "
                          f"COLLAPSED (fleet unsustainable) | | | "
                          f"{r['busd_cpu_pct']} | | | 0 | |")
                continue
            md.append(
                f"| {r['variant']} | {r.get('shards', 1)} | "
                f"{r['tick_ms']} ms | "
                f"{r['relayed_msgs_per_s']} | "
                f"{r['relayed_kb_per_s']} | "
                f"{r['pos_fanout_bytes_per_peer_per_s']} | "
                f"{r['busd_cpu_pct']} | {r['busd_cpu_us_per_msg']} | "
                f"{r['slow_consumer_drops']} | "
                f"{r['tasks_done_in_window']} | "
                f"{r.get('peak_shard_fanout_kb_per_s', '')} |")
        for tick, ratio in (result.get(
                "pos_fanout_bytes_ratio_flatjson_over_region") or {}).items():
            md.append(f"\nper-peer position fanout bytes at {tick} ms: "
                      f"flat-json / region = **{ratio}x**")
        for tick, note in (result.get("ceiling") or {}).items():
            md.append(f"\nceiling at {tick} ms: {note}")
        Path(str(args.out) + ".md").write_text("\n".join(md) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
