"""North-star deployment on REAL hardware (VERDICT r3 weak #2): the C++
centralized fleet ticking through solverd on the actual TPU.

BASELINE.json's ``--solver=tpu`` path (C++ manager -> bus -> solverd ->
accelerator) had only ever been e2e-tested with ``--cpu``; the real chip had
only been driven by bench.py's offline solves.  This script runs the full
fleet — busd + solverd (TPU backend) + centralized manager + N agents — for
several minutes of continuous task injection, then commits the artifacts the
deployment claim needs: task-metrics CSV with completions, path-metrics CSV
(per-tick plan time through the daemon), the solverd log proving the TPU
backend planned the moves, and a summary JSON.

Usage:
  python analysis/tpu_fleet_run.py --agents 50 --duration 300 \
      --out results/tpu_fleet_r04
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.runtime.fleet import Fleet  # noqa: E402


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=50)
    ap.add_argument("--duration", type=int, default=300)
    ap.add_argument("--inject-every", type=float, default=5.0)
    ap.add_argument("--out", default="results/tpu_fleet_r04")
    ap.add_argument("--cpu", action="store_true",
                    help="debug: run solverd on CPU instead")
    ap.add_argument("--planning-interval-ms", type=int, default=500,
                    help="manager tick; the reference is pinned at 500 by "
                         "its ~180 ms plan time — sub-ms planning unlocks "
                         "50 (VERDICT r4 item 2)")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip solverd pre-warm (reproduces the r4 "
                         "startup-stall behavior)")
    args = ap.parse_args()

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    log_dir = out / "logs"
    t_start = time.time()

    from p2p_distributed_tswap_tpu.core.config import RuntimeConfig
    cfg = RuntimeConfig(
        planning_interval_ms=args.planning_interval_ms,
        # agent heartbeat tracks the tick (position refresh cadence); the
        # floor keeps idle chatter bounded
        heartbeat_ms=max(250, args.planning_interval_ms))
    sd_args = ["--cpu"] if args.cpu else []
    if not args.no_warm:
        # pre-warm kills the r4 77 s capacity-recompile stall: the step
        # program is compiled at the fleet's capacity before the manager
        # even starts (solverd --warm)
        sd_args += ["--warm", str(args.agents),
                    "--capacity-min", str(args.agents)]

    with Fleet("centralized", num_agents=args.agents, port=_free_port(),
               solver="tpu", log_dir=str(log_dir), config=cfg,
               solverd_args=sd_args) as fleet:
        # mesh/registration warmup: agents broadcast 3x at startup, manager
        # needs them all registered before dispatching (test_centralized.sh
        # uses N*2/10 + 30 s; the loopback bus needs far less)
        warmup = 5 + args.agents * 0.1
        print(f"# warmup {warmup:.0f}s", flush=True)
        time.sleep(warmup)
        t_end = time.time() + args.duration
        while time.time() < t_end:
            fleet.command(f"tasks {args.agents}")
            time.sleep(args.inject_every)
        fleet.command("metrics")
        time.sleep(1)
        task_csv = out / "task_metrics.csv"
        path_csv = out / "path_metrics.csv"
        fleet.command(f"save {task_csv}")
        time.sleep(1)
        fleet.command(f"save path {path_csv}")
        time.sleep(2)
        fleet.quit()

    # --- summarize ---
    completed = 0
    dispatched = 0
    if task_csv.exists():
        rows = task_csv.read_text().splitlines()[1:]
        dispatched = len(rows)
        completed = sum(1 for r in rows if r.rstrip().endswith("completed"))
    plan_ms = None
    plan_ticks = 0
    if path_csv.exists():
        # schema: sample_index,duration_micros,duration_millis[,timestamp_ms]
        us = [float(r.split(",")[1])
              for r in path_csv.read_text().splitlines()[1:] if "," in r]
        plan_ticks = len(us)
        if us:
            plan_ms = round(sum(us) / len(us) / 1000.0, 3)
    solverd_log = (log_dir / "solverd.log").read_text(errors="ignore") \
        if (log_dir / "solverd.log").exists() else ""
    tpu_line = next((ln for ln in solverd_log.splitlines()
                     if "solverd up" in ln), "")
    warm_line = next((ln for ln in solverd_log.splitlines()
                      if "pre-warmed" in ln), "")
    # count stalls AFTER the readiness banner only: the --warm compile
    # itself prints a recompile line before "solverd up" by design.  No
    # banner = the daemon never became ready; report None, not a count
    # that would misattribute the warm compile as a runtime stall.
    if "solverd up" in solverd_log:
        recompile_stalls = solverd_log.split("solverd up", 1)[1].count(
            "recompiled step program")
    else:
        recompile_stalls = None
    mgr_log = (log_dir / "manager.log").read_text(errors="ignore") \
        if (log_dir / "manager.log").exists() else ""
    failed_over = "planning natively" in mgr_log

    # task latency (sent -> completed) from the CSV, for the tick-speed row
    lat_s = None
    if task_csv.exists():
        lats = []
        for r in task_csv.read_text().splitlines()[1:]:
            parts = r.split(",")
            # schema: task_id,peer_id,sent,received,start,completion,
            #         total_time_ms,processing,startup,status
            if parts and parts[-1] == "completed" and len(parts) >= 7:
                try:
                    lats.append(float(parts[6]) / 1000.0)
                except ValueError:
                    pass
        if lats:
            lat_s = round(sum(lats) / len(lats), 2)

    summary = {
        "experiment": "centralized fleet --solver=tpu on real hardware",
        "agents": args.agents,
        "duration_s": args.duration,
        "planning_interval_ms": args.planning_interval_ms,
        "prewarmed": not args.no_warm,
        "wallclock_s": round(time.time() - t_start, 1),
        "tasks_dispatched": dispatched,
        "tasks_completed": completed,
        "throughput_tasks_per_s": round(completed / args.duration, 3),
        "avg_task_latency_s": lat_s,
        "plan_ticks_recorded": plan_ticks,
        "avg_plan_ms_via_solverd": plan_ms,
        "solverd_recompile_stalls": recompile_stalls,
        "solverd_warm_line": warm_line.strip(),
        "solverd_backend_line": tpu_line.strip(),
        "manager_failed_over_to_native": failed_over,
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    print(json.dumps(summary, indent=2))
    if completed == 0:
        print("!! zero completions — inspect logs", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
