"""Explain the 4096² est_ratio gap (VERDICT r4 item 4, second half).

At the flagship (10k/1024²) the solve BEATS the swap-free routing
estimate (est_ratio ~0.75–0.86); at 512 agents on 4096² it lands at 1.80.
Hypothesis: routing_est charges each task the distance from the NEAREST
agent start to its pickup (min over ALL agents) — at 10k agents that min
is a good proxy for whoever actually goes, but at 512 agents on 16.7M
cells agents are ~180 cells apart and tasks outnumber nearby agents, so
the ASSIGNED agent's journey is much longer than the nearest agent's.

This script recomputes the estimate ASSIGNMENT-AWARE: a greedy nearest-
pickup matching (the solver's own assignment policy, solver/mapd._assign)
over exact BFS start->pickup distances, then
  assigned_est = max_i  bfs(start_assigned(i) -> pickup_i) + bfs(pickup_i
                 -> delivery_i).
If assigned_est lands near the measured makespan, the 1.80 is assignment
geometry, not solve slack.

Usage: python analysis/quality_gap.py --out results/quality_gap_r05.json
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from p2p_distributed_tswap_tpu.models import scenarios  # noqa: E402
from p2p_distributed_tswap_tpu.ops.distance import (  # noqa: E402
    INF, distance_fields)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", default="extreme_lite_full")
    ap.add_argument("--measured-makespan", type=int, default=12782)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    scn = getattr(scenarios, args.rung.upper())
    grid, starts, tasks, cfg = scn.build(seed=0)
    starts = np.asarray(starts)
    tasks = np.asarray(tasks)
    n, t = len(starts), len(tasks)
    free_j = jnp.asarray(grid.free)

    starts_j = jnp.asarray(starts, jnp.int32)

    @functools.partial(jax.jit, static_argnums=3)
    def chunk_bfs_gather(free, goals, dl, r):
        # gather ON DEVICE: returning full (r, 16.7M) fields would drag
        # ~536 MB/chunk through the tunnel and dominate the run
        f = distance_fields(free, goals,
                            max_rounds=cfg.max_sweep_rounds).reshape(r, -1)
        return f[:, starts_j], f[jnp.arange(r), dl]

    # exact BFS start->pickup for ALL (agent, task) pairs, and pickup->
    # delivery per task, from pickup-sourced fields (chunked to bound
    # device memory at 4096²)
    r = args.chunk
    d_sp = np.zeros((t, n), np.int64)   # task x agent
    d_pd = np.zeros(t, np.int64)
    for o in range(0, t, r):
        sel = np.clip(np.arange(o, o + r), 0, t - 1)
        sp, pd = chunk_bfs_gather(
            free_j, jnp.asarray(tasks[sel, 0], jnp.int32),
            jnp.asarray(tasks[sel, 1], jnp.int32), r)
        d_sp[sel] = np.asarray(sp)
        d_pd[sel] = np.asarray(pd)
        print(f"# fields {min(o + r, t)}/{t}", flush=True)

    # the solver's greedy policy: agents in slot order take the nearest
    # unused pickup (mirrors solver/mapd._assign's parallel chunked greedy
    # closely enough for an estimate)
    unused = np.ones(t, bool)
    assigned = np.full(t, -1)
    for a in range(n):
        cand = np.where(unused)[0]
        if not len(cand):
            break
        best = cand[np.argmin(d_sp[cand, a])]
        assigned[best] = a
        unused[best] = False

    m = assigned >= 0
    per_task_assigned = d_sp[np.arange(t)[m], assigned[m]] + d_pd[m]
    per_task_nearest = d_sp[m].min(axis=1) + d_pd[m]
    valid = per_task_assigned < int(INF)
    result = {
        "rung": scn.name, "agents": n, "tasks": t,
        "measured_makespan": args.measured_makespan,
        "routing_est_nearest_start": int(per_task_nearest[valid].max()),
        "routing_est_assigned": int(per_task_assigned[valid].max()),
        "assigned_over_measured": round(
            float(per_task_assigned[valid].max())
            / args.measured_makespan, 3),
        "measured_over_assigned": round(
            args.measured_makespan
            / float(per_task_assigned[valid].max()), 3),
        "mean_start_pickup_assigned": round(
            float(d_sp[np.arange(t)[m], assigned[m]][valid].mean()), 1),
        "mean_start_pickup_nearest": round(
            float(d_sp[m].min(axis=1)[valid].mean()), 1),
    }
    print(json.dumps(result, indent=2))
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
