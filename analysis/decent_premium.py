"""Decentralized-mode step-cost ablation at the flagship rung (VERDICT r3
weak #5: decent cost 42.95 ms/step vs 21.87 centralized with no profile row
isolating the 2x premium).

Variants (each a FULL fused solve on the real chip, the same measurement
that produced the shipped numbers):

  cent          — FLAGSHIP (global view)
  decent        — FLAGSHIP_DECENT (radius-15 fresh mask) with the round-4
                  fused member_scan (round 3 ran membership + initiator as
                  two separate scan chains)
  decent_nomask — same config but _within_radius patched to all-true:
                  keeps every scan chain and both extra passes, ablates
                  only the pairwise Manhattan arithmetic.  (Behavior
                  changes — swaps ignore distance — so makespan may drift;
                  the number is a cost-structure probe, not a benchmark.)
  stale         — FLAGSHIP_DECENT_STALE (round-4 stale/async semantics:
                  ONE decision round instead of swap_rounds x (Rule3+Rule4),
                  which is why it is CHEAPER than the fresh mask)

Each variant runs in a FRESH SUBPROCESS: flagship programs hold ~5 GB of
field buffers, and several variants resident in one process poison the
later measurements (first in-process attempt read 163 ms/step for a
variant that measures 40 in isolation).

Usage: python analysis/decent_premium.py [--rung flagship]
Prints a markdown table for SCALING.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

VARIANTS = ("cent", "decent", "decent_nomask", "stale")


def run_variant(rung: str, variant: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_distributed_tswap_tpu.models import scenarios
    from p2p_distributed_tswap_tpu.solver import mapd, step as step_mod

    base = {"medium": scenarios.MEDIUM,
            "flagship": scenarios.FLAGSHIP}[rung]
    scn = {"cent": base,
           "decent": base.decentralized(),
           "decent_nomask": base.decentralized(),
           "stale": base.stale()}[variant]
    # fresh jit per call + finally-restore: 'decent' and 'decent_nomask'
    # share an identical static cfg, so the shared _run_mapd_jit cache
    # would silently serve one variant's trace to the other if anything
    # ever runs two variants in one process (main() subprocesses them, but
    # the guard belongs here, not implicitly in the caller)
    run = jax.jit(mapd.run_mapd, static_argnums=0)
    orig_wr = step_mod._within_radius
    try:
        if variant == "decent_nomask":
            step_mod._within_radius = (
                lambda cfg, pos, i_idx, j_idx: jnp.ones_like(i_idx, bool))
        grid, starts, tasks, cfg = scn.build(seed=0)
        args = (cfg, jnp.asarray(starts, jnp.int32),
                jnp.asarray(tasks, jnp.int32), jnp.asarray(grid.free))
        final = run(*args)
        jax.block_until_ready(final)
        t0 = time.perf_counter()
        final = run(*args)
        jax.block_until_ready(final)
        steps = int(final.t)
    finally:
        step_mod._within_radius = orig_wr
    return {"variant": variant,
            "ms_per_step": round(1000.0 * (time.perf_counter() - t0) / steps,
                                 2),
            "makespan": steps,
            "completed": bool(np.asarray(final.task_used).all())}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", default="flagship",
                    choices=["medium", "flagship"])
    ap.add_argument("--variant", default=None, help="(internal) child mode")
    args = ap.parse_args()

    if args.variant:
        print(json.dumps(run_variant(args.rung, args.variant)), flush=True)
        return

    rows = []
    for v in VARIANTS:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--rung",
                 args.rung, "--variant", v],
                capture_output=True, text=True, timeout=3600)
        except subprocess.TimeoutExpired:
            print(f"# {v}: FAILED (timeout 3600s)", file=sys.stderr)
            continue
        out = None
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                out = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        if out is None:
            print(f"# {v}: FAILED\n{(proc.stderr or '')[-400:]}",
                  file=sys.stderr)
            continue
        rows.append(out)
        print(f"# {v}: {out['ms_per_step']} ms/step, makespan "
              f"{out['makespan']}, completed={out['completed']}", flush=True)

    if not rows or rows[0]["variant"] != "cent":
        sys.exit(1)
    cent_ms = rows[0]["ms_per_step"]
    print("\n| variant | ms/step | makespan | vs cent |")
    print("|---|---|---|---|")
    for r in rows:
        note = "" if r["completed"] else " (horizon)"
        print(f"| {r['variant']} | {r['ms_per_step']} "
              f"| {r['makespan']}{note} | "
              f"{r['ms_per_step'] / cent_ms:.2f}x |")


if __name__ == "__main__":
    main()
