"""Decentralized-mode step-cost ablation at the flagship rung (VERDICT r3
weak #5: decent cost 42.95 ms/step vs 21.87 centralized with no profile row
isolating the 2x premium).

Variants (each a FULL fused solve on the real chip, the same measurement
that produced the shipped numbers):

  cent          — FLAGSHIP (global view)
  decent        — FLAGSHIP_DECENT (radius-15 fresh mask) with the round-4
                  fused member_scan (round 3 ran membership + initiator as
                  two separate scan chains)
  decent_nomask — same config but _within_radius patched to all-true:
                  keeps every scan chain and both extra passes, ablates
                  only the pairwise Manhattan arithmetic.  (Behavior
                  changes — swaps ignore distance — so makespan may drift;
                  the number is a cost-structure probe, not a benchmark.)
  stale         — FLAGSHIP_DECENT_STALE (round-4 stale/async semantics)

Usage: python analysis/decent_premium.py [--rung flagship]
Prints a markdown table for SCALING.md.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.models import scenarios
from p2p_distributed_tswap_tpu.solver import mapd, step as step_mod


def solve_ms(scn):
    grid, starts, tasks, cfg = scn.build(seed=0)
    args = (cfg, jnp.asarray(starts, jnp.int32), jnp.asarray(tasks, jnp.int32),
            jnp.asarray(grid.free))
    run = jax.jit(mapd.run_mapd, static_argnums=0)  # fresh jit per variant:
    final = run(*args)                              # monkeypatches must not
    jax.block_until_ready(final)                    # hit a stale cache
    t0 = time.perf_counter()
    final = run(*args)
    jax.block_until_ready(final)
    steps = int(final.t)
    completed = bool(np.asarray(final.task_used).all())
    return 1000.0 * (time.perf_counter() - t0) / steps, steps, completed


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", default="flagship",
                    choices=["medium", "flagship"])
    args = ap.parse_args()
    base = {"medium": scenarios.MEDIUM,
            "flagship": scenarios.FLAGSHIP}[args.rung]

    rows = []

    def run(name, scn):
        ms, steps, done = solve_ms(scn)
        rows.append((name, ms, steps, done))
        print(f"# {name}: {ms:.2f} ms/step, makespan {steps}, "
              f"completed={done}", flush=True)

    run("cent", base)
    run("decent", base.decentralized())

    orig_wr = step_mod._within_radius
    try:
        step_mod._within_radius = (
            lambda cfg, pos, i_idx, j_idx: jnp.ones_like(i_idx, bool))
        run("decent_nomask", base.decentralized())
    finally:
        step_mod._within_radius = orig_wr

    run("stale", base.stale())

    cent_ms = rows[0][1]
    print("\n| variant | ms/step | makespan | vs cent |")
    print("|---|---|---|---|")
    for name, ms, steps, done in rows:
        note = "" if done else " (horizon)"
        print(f"| {name} | {ms:.2f} | {steps}{note} | "
              f"{ms / cent_ms:.2f}x |")


if __name__ == "__main__":
    main()
