#!/usr/bin/env python
"""Fleetsim: saturation-aware load generation + SLO verdicts (ISSUE 7).

The crossover harness (analysis/solver_crossover.py) proved that
simulated agents can close the task loop over the real wire; this
harness grows that into the production traffic rehearsal ROADMAP item 4
names: a load generator that multiplexes thousands of wire-faithful
agents in one process (runtime/simagent.py — pos1 region beacons,
trace-context propagation, done-retransmit), drives the sharded busd
pool + the centralized manager (+ solverd with ``--solver tpu``) at a
configurable load, and JUDGES the run against a declarative SLO spec
(obs/slo.py) evaluated from the fleet's own telemetry:

- fleet tasks/s + completion ratio: manager ``manager.tasks_dispatched``
  / ``manager.tasks_completed`` counter deltas over the measurement
  window (read from its ``mapd.metrics`` beacons — no harness-side
  instrumentation);
- phase-attributed latency: ``analysis/task_timeline.py`` percentiles
  over the run's lifecycle-event logs (JG_TRACE=1 is set by default),
  so a breached latency SLO names the phase that ate the budget;
- bus health: slow-consumer drops/evictions from the busd beacons, via
  the fleet aggregator rollup.

Modes:

- single run (default): one rung at ``--agents``/``--tick-ms``, verdict
  artifact written to ``--out`` (+ ``.md``), exit status = SLO gate
  (0 pass, 1 breach, 2 signal went dark) — the CI regression gate;
- ``--saturate N1,N2,...``: stepped-load search over agent counts (or
  ``--saturate-ticks T1,T2,...`` over tick periods at fixed agents):
  run rungs in order until an SLO breaches; the artifact records every
  rung's verdicts and the max sustainable tasks/s (the last passing
  rung), plus which SLO broke and the breaching phase.

Usage:
  python analysis/fleetsim.py --agents 1000 --shards 2 --out \\
      results/fleetsim_r09.json
  python analysis/fleetsim.py --agents 40 --side 24 --window 8 \\
      --settle 6 --spec ci_spec.json          # the scaled-down CI gate
  python analysis/fleetsim.py --saturate 250,500,1000,2000 --shards 3
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.obs import audit as _audit  # noqa: E402
from p2p_distributed_tswap_tpu.obs import capture as _capture  # noqa: E402
from p2p_distributed_tswap_tpu.obs import events as _events  # noqa: E402
from p2p_distributed_tswap_tpu.obs import flightrec as _flightrec  # noqa: E402
from p2p_distributed_tswap_tpu.obs import registry as _reg  # noqa: E402
from p2p_distributed_tswap_tpu.obs import trace as _trace  # noqa: E402
from p2p_distributed_tswap_tpu.obs import slo as _slo  # noqa: E402
from p2p_distributed_tswap_tpu.obs.beacon import METRICS_TOPIC  # noqa: E402
from p2p_distributed_tswap_tpu.obs.fleet_aggregator import (  # noqa: E402
    FleetAggregator, counter_total)
from p2p_distributed_tswap_tpu.obs.registry import hist_quantile  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import buspool  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import ha as _ha  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import region as regionlib  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built, wait_for_log)
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501


class _PeerWindow:
    __slots__ = ("proc", "first", "last", "first_t", "last_t")

    def __init__(self, proc: str, metrics: dict, t: float):
        self.proc = proc
        self.first = self.last = metrics
        self.first_t = self.last_t = t


class MetricsWindow:
    """Ingest ``mapd.metrics`` beacons: feed the fleet aggregator (the
    rollup the SLO engine reads) and keep per-PEER first/last snapshots
    (keyed by peer_id — a busd pool's shards share the ``busd`` proc
    name) so window-scoped counter deltas are exact, not beacon-cadence
    approximations.  Each snapshot records its arrival time: rates
    divide by the FIRST→LAST BEACON span, not the harness's window
    wall clock (beacons land up to an interval late on either edge)."""

    def __init__(self, port: int, audit: bool = False, ha: bool = False):
        self.bus = BusClient(port=port, peer_id="fleetsim-watch")
        self.bus.subscribe(METRICS_TOPIC)
        if audit and _audit.enabled():
            # replay mode joins the audit plane too: final-watermark
            # ledger/view digests are the determinism proof (ISSUE 11)
            self.bus.subscribe(_audit.AUDIT_TOPIC, raw=True)
        if ha:
            # HA replays (ISSUE 15) watch mapd.ha too: the aggregator
            # records ha_takeover announcements — the digest-equal
            # takeover proof the failover judges read
            self.bus.subscribe(_ha.HA_TOPIC, raw=True)
        self.agg = FleetAggregator()
        self._peers = {}  # peer_id -> _PeerWindow

    def pump(self, budget_s: float) -> None:
        end = time.monotonic() + budget_s
        while True:
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            f = self.bus.recv(timeout=min(0.2, remaining))
            if not f or f.get("op") != "msg":
                continue
            d = f.get("data") or {}
            if d.get("type") != "metrics_beacon":
                # audit beacons (and the replay driver's own beacons)
                # route into the aggregator but never into the per-peer
                # metrics windows — their payloads carry no counters
                self.agg.ingest(d)
                continue
            if not self.agg.ingest(d):
                continue
            proc = d.get("proc", "?")
            key = str(d.get("peer_id") or proc)
            m = d.get("metrics") or {}
            now = time.monotonic()
            st = self._peers.get(key)
            if st is None:
                self._peers[key] = _PeerWindow(proc, m, now)
            else:
                st.last = m
                st.last_t = now

    def reset_window(self) -> None:
        """Measurement window starts fresh (the aggregator keeps its
        history: its delta rates want consecutive beacons)."""
        self._peers.clear()

    def seen(self, proc: str) -> bool:
        return any(st.proc == proc for st in self._peers.values())

    def delta(self, proc: str, counter: str) -> float:
        """Window delta of a counter summed over every peer of ``proc``,
        clamped at zero per peer (a restart inside the window resets
        cumulative counters)."""
        total = 0.0
        for st in self._peers.values():
            if st.proc != proc or st.last is st.first:
                continue
            total += max(0.0, counter_total(st.last, counter)
                         - counter_total(st.first, counter))
        return total

    def span_s(self, proc: str) -> float:
        """Longest first→last beacon span among ``proc``'s peers — the
        honest denominator for the window delta rates."""
        return max((st.last_t - st.first_t for st in self._peers.values()
                    if st.proc == proc), default=0.0)

    def close(self) -> None:
        self.bus.close()


class WorldToggler:
    """Dynamic-obstacle injection (ISSUE 9): every ``every`` seconds,
    reopen the previous wall and ask the manager to close a fresh random
    vertical wall of ``cells`` cells (world_update_request on "mapd").
    The MANAGER validates and applies — cells under agents, goals or
    task endpoints come back rejected, which is the correct behavior,
    not a harness failure; the pool's world counters record what
    actually landed."""

    def __init__(self, sim: SimAgentPool, side: int, cells: int,
                 every: float, seed: int):
        import random

        self.sim = sim
        self.side = side
        self.cells = cells
        self.every = every
        self.rng = random.Random(seed)
        self.prev = []
        self.next_at = time.monotonic() + every
        self.sent = 0

    def maybe(self) -> None:
        if not self.cells or time.monotonic() < self.next_at:
            return
        self.next_at = time.monotonic() + self.every
        toggles = [[x, y, 0] for x, y in self.prev]  # reopen the old wall
        x0 = self.rng.randrange(2, max(3, self.side - 2))
        y0 = self.rng.randrange(0, max(1, self.side - self.cells))
        wall = [(x0, y0 + i) for i in range(self.cells)]
        toggles += [[x, y, 1] for x, y in wall]
        self.prev = wall
        self.sim.bus.publish("mapd", {"type": "world_update_request",
                                      "toggles": toggles})
        self.sent += 1

    def reopen_all(self) -> None:
        """End-of-run cleanup: reopen the last wall so the drain phase
        (in-flight tasks finishing) faces the static world again."""
        if self.prev:
            self.sim.bus.publish(
                "mapd", {"type": "world_update_request",
                         "toggles": [[x, y, 0] for x, y in self.prev]})
            self.prev = []


def _timeline_summary(trace_dir: Path) -> dict:
    from analysis import task_timeline

    summary = task_timeline.summarize(trace_dir)
    summary.pop("tasks", None)  # per-task records stay out of artifacts
    return summary


def _federation_counters(watch, mgr_proc: str) -> dict:
    """Window deltas of the handoff-protocol counters, summed across
    every region manager — the one evidence dict both the load rungs
    and the chaos judge read (keep them from diverging)."""
    return {
        "handoffs_sent": int(watch.delta(
            mgr_proc, "manager.handoffs_sent")),
        "handoffs_acked": int(watch.delta(
            mgr_proc, "manager.handoffs_acked")),
        "handoffs_received": int(watch.delta(
            mgr_proc, "manager.handoffs_received")),
        "handoffs_dup_dropped": int(watch.delta(
            mgr_proc, "manager.handoffs_dup_dropped")),
        "handoff_retransmits": int(watch.delta(
            mgr_proc, "manager.handoff_retransmits")),
        "handoff_outbox_overflow": int(watch.delta(
            mgr_proc, "manager.handoff_outbox_overflow")),
        "conflict_releases": int(watch.delta(
            mgr_proc, "manager.fed_conflict_releases")),
    }


def shape_rate(shape: str, t_s: float, base: float, peak: float,
               period_s: float) -> float:
    """Open-loop injection rate (tasks/s) at elapsed time ``t_s`` for a
    traffic shape (ISSUE 16) — the rehearsal generators healthd's
    forecast is validated against:

    - ``ramp``  — diurnal climb: linear base→peak across the period,
      held at peak after (the smooth monotone trend a slope forecaster
      must catch BEFORE the breach);
    - ``flash`` — flash crowd: base load with a peak burst through the
      last 20% of each period (a step, which must NOT forecast — it
      confirms the fast way, via the burn window);
    - ``storm`` — tenant arrival storm: a 4-step staircase base→peak
      per period (each arriving tenant adds a load quantum).

    ``none`` (or an unknown shape) is constant ``base`` — the legacy
    open-loop wire, byte-identical.
    """
    if shape in (None, "", "none") or period_s <= 0:
        return base
    if shape == "ramp":
        frac = min(1.0, max(0.0, t_s / period_s))
        return base + (peak - base) * frac
    phase = (t_s % period_s) / period_s
    if shape == "flash":
        return peak if phase >= 0.8 else base
    if shape == "storm":
        step = min(3, int(phase * 4))
        return base + (peak - base) * step / 3.0
    return base


def _fed_spec(args):
    """``(cols, rows, total)`` from the rung's --regions spec (None/1 =
    the single-pair fleet)."""
    cols, rows = regionlib.fed_parse_spec(getattr(args, "regions", None))
    return cols, rows, cols * rows


def run_rung(args, agents: int, tick_ms: int, spec) -> dict:
    """One measured load rung: fresh fleet, settle, window, verdicts.

    With ``--regions CxR`` (ISSUE 14) the fleet is FEDERATED: one
    (manager [, solverd]) pair per region on the shared bus pool, each
    manager owning its rectangle and sampling its own pickups, one
    world-spanning sim pool driven through all of them; the window
    signals sum across managers and the rung grows a ``federation``
    section (per-region tasks/s + handoff counters)."""
    import shutil

    ensure_built()
    fed_cols, fed_rows, fed_total = _fed_spec(args)
    home_port = buspool.free_port()
    log_dir = Path(args.log_dir) / (f"a{agents}_t{tick_ms}_s{args.shards}"
                                    + (f"_r{fed_cols}x{fed_rows}"
                                       if fed_total > 1 else ""))
    # a fresh rung directory every time: event logs append per-pid and
    # task_timeline merges every *.events.jsonl it finds, so a stale
    # previous run at the same config (the CI gate's fixed --log-dir)
    # would dilute — or fail — this run's phase signals
    if log_dir.exists():
        shutil.rmtree(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    trace_dir = log_dir / "trace"
    saved_env = dict(os.environ)
    procs, logs = [], []

    def spawn(name, cmd, stdin=None, env=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ, **(env or {})))
        procs.append(p)
        return p

    pool = watch = sim = None
    # fresh harness-process registry per rung: in a saturation ladder the
    # pool's claim-wire histogram must not carry the previous rung's
    # samples into this rung's p99
    _reg.get_registry().clear()
    try:
        pool = buspool.BusPool(
            BUILD_DIR / "mapd_bus", num_shards=args.shards,
            home_port=home_port, spawn=spawn)
        time.sleep(0.4)
        # the harness process hosts the sim pool: it needs the same
        # fleet environment the children get (shard map, trace sinks)
        os.environ.update(pool.env())
        if not args.no_trace:
            os.environ["JG_TRACE"] = "1"
            os.environ["JG_TRACE_DIR"] = str(trace_dir)
            os.environ.setdefault("JG_TRACE_SAMPLE", "1.0")
        os.environ.setdefault("JG_FLIGHT_DIR", str(log_dir))
        # re-arm the harness-process sinks under the rung environment:
        # the span tracer caches JG_TRACE at configure time, and the sim
        # pool's lifecycle events only reach disk with it armed
        _trace.configure(proc="simfleet")
        _events.configure("simfleet")
        if args.solver == "tpu":
            for rid in range(fed_total):
                tag = f"_r{rid}" if fed_total > 1 else ""
                sd_cmd = [sys.executable, "-m",
                          "p2p_distributed_tswap_tpu.runtime.solverd",
                          "--port", str(home_port), "--map",
                          args.map_file, "--warm", str(agents), "--cpu",
                          *regionlib.fed_cli_args(rid, fed_cols,
                                                  fed_rows, "solverd")]
                sd_proc = spawn(f"solverd{tag}", sd_cmd)
                if not wait_for_log(log_dir / f"solverd{tag}.log",
                                    "solverd up", 900, proc=sd_proc):
                    raise RuntimeError(f"solverd{tag} never became ready")
        mgrs = []
        for rid in range(fed_total):
            tag = f"_r{rid}" if fed_total > 1 else ""
            mgr_cmd = [
                str(BUILD_DIR / "mapd_manager_centralized"),
                "--port", str(home_port), "--map", args.map_file,
                "--solver", "cpu" if args.solver == "native" else "tpu",
                "--planning-interval-ms", str(tick_ms),
                "--max-tracked-agents", str(agents + 16),
                # seed audit (ISSUE 11): the manager's task sampling is
                # the last stochastic path fleetsim touches — thread the
                # one harness seed through it so a rung is re-runnable
                # (per-region offset keeps the samplers independent)
                "--seed", str(args.seed + rid),
                *regionlib.fed_cli_args(rid, fed_cols, fed_rows,
                                        "manager")]
            mgrs.append(spawn(f"manager{tag}", mgr_cmd,
                              stdin=subprocess.PIPE))
        mgr = mgrs[0]
        time.sleep(0.5)
        sim = SimAgentPool(agents, args.side, port=home_port,
                           seed=args.seed, heartbeat_s=args.heartbeat_s)
        recorder = None
        if getattr(args, "capture", None):
            # traffic capture (ISSUE 11): record every dispatched task
            # and accepted world update as replayable traffic, anchored
            # at pool creation so the ramp is part of the window
            recorder = _capture.CaptureRecorder({
                "agents": agents, "side": args.side, "seed": args.seed,
                "shards": args.shards, "solver": args.solver,
                "tick_ms": tick_ms, "heartbeat_s": args.heartbeat_s,
                "manager_seed": args.seed})
            sim.capture = recorder
            # harness-side config into the flight ring: the post-mortem
            # assembly path (blackbox --capture) merges it with the
            # pool's own capture.meta
            _events.emit("capture.meta", shards=args.shards,
                         solver=args.solver, tick_ms=tick_ms,
                         manager_seed=args.seed)
        watch = MetricsWindow(home_port)
        sim.heartbeat_all()
        sim.pump(1.5)

        def inject(k):
            # federated fleets split the injection across region
            # managers (each samples pickups in its own rectangle)
            share = -(-k // fed_total)
            left = k
            for m in mgrs:
                n = min(share, left)
                if n <= 0:
                    break
                m.stdin.write(f"tasks {n}\n".encode())
                m.stdin.flush()
                left -= n

        open_loop = args.mode == "open"
        inject_every = 1.0
        per_inject = max(1, int(round(args.rate * inject_every)))
        # traffic shapes (ISSUE 16): the open-loop rate becomes a
        # function of elapsed time; `none` keeps the legacy constant
        # wire exactly (per_inject path untouched)
        shape = getattr(args, "shape", "none") or "none"
        shape_peak = getattr(args, "shape_peak", None)
        if shape_peak is None:
            shape_peak = 4.0 * args.rate
        shape_period = getattr(args, "shape_period_s", None)
        if shape_period is None:
            shape_period = args.settle + args.window
        shape_t0 = time.monotonic()
        if not open_loop:
            # ramped closed-loop fill (manager refills on every done):
            # the fleet's standing load goes out in chunks, so
            # dispatch->claim measures the steady wire rather than one
            # thundering-herd burst the pool drains for seconds
            ramp_s = min(args.ramp_s, args.settle * 0.5)
            steps = max(1, int(ramp_s / 0.5))
            chunk = -(-agents // steps)
            sent = 0
            while sent < agents:
                inject(min(chunk, agents - sent))
                sent += chunk
                sim.pump(0.45)
                watch.pump(0.05)

        toggler = WorldToggler(sim, args.side, args.world_toggle_cells,
                               args.world_toggle_every, args.seed + 77)

        def drive(seconds: float):
            nonlocal next_inject
            end = time.monotonic() + seconds
            while time.monotonic() < end:
                if open_loop and time.monotonic() >= next_inject:
                    next_inject = time.monotonic() + inject_every
                    if shape != "none":
                        rate = shape_rate(shape,
                                          time.monotonic() - shape_t0,
                                          args.rate, shape_peak,
                                          shape_period)
                        k = int(round(rate * inject_every))
                        if k > 0:
                            inject(k)
                    else:
                        inject(per_inject)
                toggler.maybe()
                sim.pump(0.3)
                watch.pump(0.05)

        next_inject = shape_t0 = time.monotonic()
        drive(args.settle)
        # measurement window starts fresh: counters re-baseline, the sim
        # pool's own done count snapshots
        watch.reset_window()
        sim_done0 = sim.done_count
        t0 = time.monotonic()
        drive(args.window)
        wall = time.monotonic() - t0
        if toggler.sent:
            # reopen the final wall so the post-window drain (in-flight
            # tasks completing, done-acks landing) faces the static map
            toggler.reopen_all()
            sim.pump(1.0)
        watch.pump(2.5)  # one more beacon interval: final counters land

        rollup = watch.agg.rollup()
        signals = _slo.signals_from_rollup(rollup)
        # window-exact overrides: beacon-cadence delta rates are the
        # live view; the SLO verdict wants the measured window.  The
        # rate denominator is the manager's own first->last beacon span
        # (its counters move with its beacons, not with our wall clock).
        mgr_proc = "manager_centralized"
        d_disp = watch.delta(mgr_proc, "manager.tasks_dispatched")
        d_done = watch.delta(mgr_proc, "manager.tasks_completed")
        span = watch.span_s(mgr_proc)
        if watch.seen(mgr_proc) and span > 0:
            signals["fleet.tasks_per_s"] = round(d_done / span, 3)
            if d_disp > 0:
                signals["fleet.completion_ratio"] = round(
                    min(1.0, d_done / d_disp), 4)
            elif d_done > 0:
                # window with completions but no fresh dispatches (e.g.
                # drain phase): everything that could complete did
                signals["fleet.completion_ratio"] = 1.0
        else:
            # <2 manager beacons in the window = DARK telemetry: drop
            # the rollup-derived values too (they span the settle
            # phase) so the SLO reads unknown (exit 2) — never a stale
            # pre-window rate passing as measurement, never a
            # fabricated 0.0 breach
            signals.pop("fleet.tasks_per_s", None)
            signals.pop("fleet.completion_ratio", None)
        if watch.seen("busd") and watch.span_s("busd") > 0:
            # bus-shedding SLOs judge the MEASURED WINDOW ("zero
            # evictions at rated load"), not the warm-up thundering
            # herd the cumulative busd counters include.  With <2
            # beacons per shard the cumulative rollup value stands —
            # conservative (includes warm-up), never a fabricated 0.
            signals["bus.slow_consumer_evictions"] = int(
                watch.delta("busd", "bus.slow_consumer_evictions"))
            signals["bus.slow_consumer_drops"] = int(
                watch.delta("busd", "bus.slow_consumer_drops"))
        # always-on claim-wire percentile from the pool's own registry
        # (hop_latency_ms{edge="task.claim"}) — works without JG_TRACE
        snap = _reg.snapshot()
        claim = (snap.get("hists") or {}).get(
            'hop_latency_ms{edge="task.claim"}')
        if claim and claim.get("count"):
            signals["sim.claim_wire_p99_ms"] = round(
                hist_quantile(claim, 0.99), 3)
            signals["sim.claim_wire_p50_ms"] = round(
                hist_quantile(claim, 0.5), 3)
        if open_loop and shape != "none":
            # shape evidence rides the signals (ISSUE 16): the health
            # artifact (and item 1's rehearsals) record exactly which
            # traffic curve the verdict was judged under
            signals["shape.kind"] = shape
            signals["shape.base_rate"] = args.rate
            signals["shape.peak_rate"] = round(shape_peak, 3)
            signals["shape.period_s"] = round(shape_period, 1)
        if toggler.sent:
            # dynamic-world evidence rides the signals so a spec can
            # demand toggles actually landed (unknown = exit 2 otherwise)
            signals["world.requests"] = toggler.sent
            signals["world.updates_seen"] = sim.world_updates
            signals["world.toggles_accepted"] = sim.world_accepted
        federation = None
        if fed_total > 1:
            # federation evidence (ISSUE 14): window handoff counters
            # summed across region managers + the aggregator's
            # per-region view — the signals a spec can gate on
            federation = {
                "regions": f"{fed_cols}x{fed_rows}",
                "region_count": fed_total,
                **_federation_counters(watch, mgr_proc),
                "per_region": (rollup.get("federation") or {}).get(
                    "per_region"),
            }
            signals["fed.handoffs_sent"] = federation["handoffs_sent"]
            signals["fed.handoffs_acked"] = federation["handoffs_acked"]
            signals["fed.handoffs_dup_dropped"] = \
                federation["handoffs_dup_dropped"]
        timeline = None
        if not args.no_trace and trace_dir.exists():
            timeline = _timeline_summary(trace_dir)
            signals.update(_slo.signals_from_timeline(timeline))
        result = _slo.evaluate(spec, signals)
        rung = {
            "agents": agents,
            "tick_ms": tick_ms,
            "shards": args.shards,
            "regions": f"{fed_cols}x{fed_rows}" if fed_total > 1 else None,
            "mode": args.mode,
            "solver": args.solver,
            "map": f"{args.side}x{args.side} empty",
            "window_s": round(wall, 1),
            "settle_s": args.settle,
            "seed": args.seed,
            "window_tasks_dispatched": int(d_disp),
            "window_tasks_completed": int(d_done),
            "sim": {**sim.stats(),
                    "done_in_window": sim.done_count - sim_done0},
            "signals": signals,
            "slo": result,
        }
        if open_loop and shape != "none":
            rung["shape"] = {"kind": shape, "base_rate": args.rate,
                             "peak_rate": round(shape_peak, 3),
                             "period_s": round(shape_period, 1)}
        if federation is not None:
            rung["federation"] = federation
        if toggler.sent:
            rung["world"] = {
                "toggle_cells": args.world_toggle_cells,
                "toggle_every_s": args.world_toggle_every,
                "requests": toggler.sent,
                "updates_seen": sim.world_updates,
                "toggles_accepted": sim.world_accepted,
                "toggles_rejected": sim.world_rejected,
            }
        if timeline is not None:
            rung["timeline"] = timeline
        if recorder is not None:
            phase_p95 = {
                ph: pcts.get("p95")
                for ph, pcts in ((timeline or {}).get("fleet_phases_ms")
                                 or {}).items()
                if isinstance(pcts, dict)
                and pcts.get("p95") is not None}
            baseline = {
                "window_s": round(wall, 1),
                "tasks_per_s": signals.get("fleet.tasks_per_s"),
                "completion_ratio": signals.get("fleet.completion_ratio"),
                "claim_wire_p99_ms": signals.get("sim.claim_wire_p99_ms"),
                "phase_p95_ms": phase_p95 or None,
            }
            try:
                path = _capture.save(args.capture,
                                     recorder.finalize(baseline=baseline))
            except _capture.CaptureError as e:
                # an unreplayable window (e.g. no task ever dispatched)
                # must not discard the rung's own verdicts/signals — the
                # window is still diagnosable, just not re-drivable
                rung["capture_error"] = str(e)
                print(f"fleetsim: capture SKIPPED: {e}", flush=True)
            else:
                rung["capture"] = str(path)
                print(f"fleetsim: capture1 written to {path} "
                      f"({len(recorder.tasks)} task(s), "
                      f"{len(recorder.world)} world event(s))",
                      flush=True)
        return rung
    finally:
        for obj in (sim, watch):
            if obj is not None:
                obj.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        # the harness's own flight ring holds the capture evidence
        # (capture.meta / task.spec / world.update, ISSUE 11) — dump it
        # into the run's log dir NOW, while we still know which run this
        # was: the atexit dump fires after the env restore below and
        # would land in the default dir, stranding the post-mortem
        # `blackbox.py --capture` path for in-process windows
        _rec = _flightrec.get_recorder()
        _flightrec.dump(str(log_dir / f"{_rec.proc}-{_rec.pid}"
                                      ".flight.jsonl"),
                        reason="rung_teardown")
        os.environ.clear()
        os.environ.update(saved_env)
        # re-bind the sinks to the restored environment
        _trace.configure(proc="simfleet")
        _events.configure("simfleet")


class ReplayCtx:
    """The live handles a chaos fault script pokes at (ISSUE 11,
    scripts/chaos_gate.py): the busd pool (kill_shard / SIGSTOP a
    member), the manager and solverd processes (signals), the sim pool,
    and a solverd respawner for kill-and-recover faults.  ``notes``
    accumulates a human-readable fault log that rides the replay
    artifact."""

    def __init__(self, pool, mgr, sim, solverd, start_solverd,
                 managers=None, solverds=None, standbys=None):
        self.pool = pool
        self.manager = mgr
        # warm standbys (ISSUE 15), index = region id; empty without HA
        self.standbys = list(standbys) if standbys else []
        # federated replays (ISSUE 14): every region manager/solverd,
        # index = region id — the handoff-kill fault targets
        # managers[1]; a fault combining regions with a solverd
        # kill/restart must target (and respawn) the RIGHT region's
        # daemon or one plan wire goes dark while another doubles up
        self.managers = list(managers) if managers else [mgr]
        self.solverds = (list(solverds) if solverds
                         else ([solverd] if solverd is not None else []))
        self.sim = sim
        self.solverd = solverd
        self._start_solverd = start_solverd
        self._solverd_generation = 0
        self.notes: list = []

    def note(self, text: str) -> None:
        self.notes.append(text)
        print(f"chaos: {text}", flush=True)

    def restart_solverd(self, wait: bool = False, rid: int = 0):
        """Respawn region ``rid``'s solverd on ITS plan-wire topic
        (default non-blocking: a chaos recovery must not stall the
        replay loop for the whole JAX warmup — the fleet's own resync
        machinery picks the daemon up when it's ready)."""
        self._solverd_generation += 1
        tag = ((f"_r{rid}" if len(self.solverds) > 1 else "")
               + f"_g{self._solverd_generation}")
        p = self._start_solverd(tag, wait=wait, rid=rid)
        while len(self.solverds) <= rid:
            self.solverds.append(None)
        self.solverds[rid] = p
        if rid == 0:
            self.solverd = p
        return p


def _final_digests(joiner) -> dict:
    """The determinism proof's raw material: each role's NEWEST audit
    digests at the drained watermark — the manager's ledger / in-flight
    view / lane shadow, the agent pool's held view, solverd's mirror.
    Ledger and view must be equal across two replays of one capture
    (both sides fully drained); lane digests (positions) are recorded
    for diagnosis only — assignment interleaving is the live planner's."""
    out = {}
    for name, st in joiner._peers.items():
        sections = []
        if st.proc.startswith("manager"):
            sections = [(_audit.SEC_LEDGER, "ledger"),
                        (_audit.SEC_VIEW, "view"),
                        (_audit.SEC_SHADOW, "lanes")]
        elif st.proc.startswith("solverd"):
            sections = [(_audit.SEC_MIRROR, "mirror")]
        elif st.proc == "simagent_pool":
            sections = [(_audit.SEC_VIEW, "view_agents")]
        for sec, key in sections:
            e = st.latest.get(sec)
            if e is not None:
                out[key] = {"peer": name,
                            "digest": _audit.digest_hex(e.digest),
                            "count": e.count, "seq": e.seq,
                            "epoch": e.epoch}
    return out


def run_replay(capture: dict, log_dir, solver=None, shards=None,
               no_trace: bool = False, chaos=None, drain_s=None,
               label: str = "replay", regions=None,
               ha: bool = False) -> dict:
    """Re-drive a captured window open-loop as a DETERMINISTIC load
    (ISSUE 11): a fresh fleet (seeded from the capture), the captured
    tasks injected via the manager's ``taskat`` command at their
    original arrival offsets with their original ids and endpoints, the
    captured world toggles re-requested at their offsets — then drain
    until every captured task completed (or timeout).  ``chaos``, when
    given, is polled with ``(ctx, t_rel_s)`` throughout and may kill /
    stop / restart fleet members (scripts/chaos_gate.py).

    ``regions`` (ISSUE 14): a "CxR" spec replays the capture through a
    FEDERATED fleet — per-region (manager [, solverd]) pairs, each
    captured task injected into the manager owning its pickup cell —
    so the chaos matrix can fault a region manager mid-handoff.

    Returns the replay record: outcome ledger (completed ids, missing,
    duplicates), final-watermark audit digests, the auditor's confirmed
    divergences, and fidelity drift vs the capture baseline."""
    import shutil

    capture = _capture.validate(capture)
    fleet = capture["fleet"]
    agents, side = fleet["agents"], fleet["side"]
    solver = solver or fleet.get("solver") or "native"
    shards = int(shards or fleet.get("shards") or 1)
    tick_ms = int(fleet.get("tick_ms") or 250)
    seed = int(fleet.get("seed") or 1)
    mseed = fleet.get("manager_seed")
    mseed = seed if mseed is None else int(mseed)
    heartbeat_s = float(fleet.get("heartbeat_s") or 2.0)
    fed_cols, fed_rows = regionlib.fed_parse_spec(regions)
    fed_total = fed_cols * fed_rows

    ensure_built()
    map_file = f"/tmp/fleetsim_replay_{side}.map.txt"
    Path(map_file).write_text("\n".join(["." * side] * side) + "\n")
    home_port = buspool.free_port()
    log_dir = Path(log_dir) / label
    if log_dir.exists():
        shutil.rmtree(log_dir)
    log_dir.mkdir(parents=True, exist_ok=True)
    trace_dir = log_dir / "trace"
    saved_env = dict(os.environ)
    procs, logs = [], []

    def spawn(name, cmd, stdin=None, env=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ, **(env or {})))
        procs.append(p)
        return p

    pool = watch = sim = None
    _reg.get_registry().clear()
    try:
        pool = buspool.BusPool(
            BUILD_DIR / "mapd_bus", num_shards=shards,
            home_port=home_port, spawn=spawn)
        time.sleep(0.4)
        os.environ.update(pool.env())
        if not no_trace:
            os.environ["JG_TRACE"] = "1"
            os.environ["JG_TRACE_DIR"] = str(trace_dir)
            os.environ.setdefault("JG_TRACE_SAMPLE", "1.0")
        os.environ.setdefault("JG_FLIGHT_DIR", str(log_dir))
        # fast audit cadence: the final-watermark digests are the
        # determinism proof, and the chaos judge needs silent-peer
        # detection well inside the drain budget
        os.environ.setdefault("JG_AUDIT_INTERVAL_MS", "400")
        os.environ.setdefault("JG_AUDIT_INTERVAL_S", "0.4")
        if capture.get("world"):
            # replayed toggles must reach solverd from tick one
            os.environ.setdefault("JG_DYNAMIC_WORLD", "1")
        if ha:
            # control-plane HA (ISSUE 15): every region manager ships
            # its ledger1 stream and gets a warm standby below
            os.environ["JG_HA"] = "1"
        if chaos is not None and getattr(chaos, "needs_shm", False):
            # zero-copy lanes (ISSUE 18): the lane faults replay with
            # the rings armed for every client spawned below (and the
            # in-process sim pool); ring files live with the run's logs
            os.environ["JG_BUS_SHM"] = "1"
            os.environ.setdefault("JG_BUS_SHM_DIR",
                                  str(log_dir / "shm_lanes"))
        _trace.configure(proc="simfleet")
        _events.configure("simfleet")

        def start_solverd(tag: str = "", wait: bool = True, rid: int = 0):
            name = f"solverd{tag}"
            cmd = [sys.executable, "-m",
                   "p2p_distributed_tswap_tpu.runtime.solverd",
                   "--port", str(home_port), "--map", map_file,
                   "--warm", str(agents), "--cpu",
                   *regionlib.fed_cli_args(rid, fed_cols, fed_rows,
                                           "solverd")]
            p = spawn(name, cmd)
            if wait and not wait_for_log(log_dir / f"{name}.log",
                                         "solverd up", 900, proc=p):
                raise RuntimeError(f"{name} never became ready")
            return p

        sds = []
        if solver == "tpu":
            for rid in range(fed_total):
                sds.append(start_solverd(
                    f"_r{rid}" if fed_total > 1 else "", rid=rid))
        sd = sds[0] if sds else None
        mgrs, stbys = [], []
        for rid in range(fed_total):
            tag = f"_r{rid}" if fed_total > 1 else ""
            cmd = [str(BUILD_DIR / "mapd_manager_centralized"),
                   "--port", str(home_port), "--map", map_file,
                   "--solver", "cpu" if solver == "native" else "tpu",
                   "--planning-interval-ms", str(tick_ms),
                   "--max-tracked-agents", str(agents + 16),
                   "--seed", str(mseed + rid),
                   # open-loop: completions must NOT mint fresh rng
                   # tasks — the load is exactly the captured taskat
                   # stream
                   "--open-loop",
                   *regionlib.fed_cli_args(rid, fed_cols, fed_rows,
                                           "manager")]
            if ha:
                cmd += ["--ha", "1"]
            mgrs.append(spawn(f"manager{tag}", cmd,
                              stdin=subprocess.PIPE))
            if ha:
                # the warm standby tails the active's ledger1 stream;
                # taskat lines sent to it while the active lives are
                # deferred and drained at promotion
                stbys.append(spawn(f"standby{tag}",
                                   cmd + ["--standby"],
                                   stdin=subprocess.PIPE))
        mgr = mgrs[0]
        time.sleep(0.5)
        sim = SimAgentPool(agents, side, port=home_port, seed=seed,
                           heartbeat_s=heartbeat_s)
        watch = MetricsWindow(home_port, audit=True, ha=ha)
        sim.heartbeat_all()
        sim.pump(1.5)
        watch.pump(0.5)

        ctx = ReplayCtx(pool, mgr, sim, sd, start_solverd, managers=mgrs,
                        solverds=sds, standbys=stbys)
        events = _capture.schedule(capture)
        expected = set(_capture.task_ids(capture))
        baseline = capture.get("baseline") or {}
        orig_tps = baseline.get("tasks_per_s")
        injected = world_injected = 0
        last_beacon = [0.0]
        last_eval = [0.0]
        t0 = time.monotonic()
        t0_wall_ms = time.time_ns() // 1_000_000

        def replay_beacon(final: bool = False, extra: dict = None):
            """Progress on the metrics plane: fleet_top's REPLAY line
            and the aggregator's replay section render this."""
            elapsed = max(time.monotonic() - t0, 1e-9)
            done = len(sim.done_ids & expected)
            payload = {"type": "replay_beacon",
                       "peer_id": "replay-driver",
                       "proc": "replay",
                       "capture_source": capture.get("source"),
                       "t_s": round(elapsed, 1),
                       "injected": injected,
                       "total": len(expected),
                       "world_injected": world_injected,
                       "done": done,
                       "done_dups": sim.done_dups,
                       "tasks_per_s": round(done / elapsed, 3),
                       "orig_tasks_per_s": orig_tps,
                       "final": final}
            payload.update(extra or {})
            sim.bus.publish(METRICS_TOPIC, payload)
            return payload

        def tick(slice_s: float):
            now = time.monotonic()
            if chaos is not None:
                chaos.poll(ctx, now - t0)
            sim.pump(slice_s)
            watch.pump(0.02)
            if now - last_eval[0] >= 0.5:
                last_eval[0] = now
                watch.agg.audit.evaluate()
            if now - last_beacon[0] >= 2.0:
                last_beacon[0] = now
                replay_beacon()

        for t_ms, kind, payload in events:
            target = t0 + t_ms / 1000.0
            while True:
                remaining = target - time.monotonic()
                if remaining <= 0:
                    break
                tick(min(0.1, remaining))
            if kind == "task":
                px, py = payload["pickup"]
                dx, dy = payload["delivery"]
                # federated replays route each task to the manager that
                # OWNS its pickup cell (the ownership canon); a manager
                # a chaos fault already killed loses its stream UNLESS
                # an HA standby stands in — the standby defers taskat
                # lines and drains them at promotion (ISSUE 15), so a
                # failover replay loses nothing
                rid0 = 0
                if fed_total > 1:
                    rid0 = regionlib.fed_region_of(
                        int(px), int(py), fed_cols, fed_rows, side, side)
                tgt = mgrs[rid0]
                if tgt.poll() is not None and rid0 < len(stbys) \
                        and stbys[rid0].poll() is None:
                    tgt = stbys[rid0]
                try:
                    tgt.stdin.write(
                        f"taskat {px} {py} {dx} {dy} "
                        f"{payload['id']}\n".encode())
                    tgt.stdin.flush()
                    injected += 1
                except (BrokenPipeError, OSError):
                    ctx.note(f"task {payload['id']} lost: its region "
                             "manager is down")
            else:
                sim.bus.publish("mapd", {"type": "world_update_request",
                                         "toggles": payload["toggles"]})
                world_injected += 1
        inject_wall_s = time.monotonic() - t0
        dur_s = capture["duration_ms"] / 1000.0
        budget = (drain_s if drain_s is not None else max(30.0, dur_s))
        budget += getattr(chaos, "extra_drain_s", 0.0) or 0.0
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline \
                and not expected <= sim.done_ids:
            tick(0.25)
        drained = expected <= sim.done_ids
        # the final watermark: stop injecting, let every role beacon its
        # drained digests (>= 3 audit intervals), judge one last time
        end_pump = time.monotonic() + 2.5
        while time.monotonic() < end_pump:
            tick(0.2)
        watch.pump(1.0)
        watch.agg.audit.evaluate()

        completed = sorted(sim.done_ids & expected)
        missing = sorted(expected - sim.done_ids)
        extra_done = sorted(sim.done_ids - expected)
        # ledger-level completion count: the manager's dedup-guarded
        # counter — each id counts at most once, so > expected means the
        # system of record double-counted (a real duplication), while
        # pool-side done_dups also catches the benign positional-done /
        # goal-exchange race the reference architecture carries
        mgr_proc = "manager_centralized"
        mgr_completed = int(watch.delta(mgr_proc,
                                        "manager.tasks_completed"))
        mgr_dispatched = int(watch.delta(mgr_proc,
                                         "manager.tasks_dispatched"))
        federation = None
        if fed_total > 1:
            # the chaos judge's handoff evidence: summed across every
            # region manager's beacons over the whole replay window
            federation = {
                "regions": f"{fed_cols}x{fed_rows}",
                **_federation_counters(watch, mgr_proc),
            }
        ha_section = None
        if ha:
            # the failover judge's evidence (ISSUE 15): every observed
            # takeover announcement with its digest-equality verdict
            # and its latency relative to the replay clock (the fault
            # records when it killed the active on the same clock)
            takeovers = []
            for rec in watch.agg.ha_takeovers:
                # the ONE digest-equality rule (runtime/ha.py): None =
                # cold start (nothing shipped to compare) — the chaos
                # judges treat that as failing the proof, correctly
                eq = _ha.takeover_digests_equal(rec)
                takeovers.append({
                    "peer": rec.get("peer_id"),
                    "ns": rec.get("ns"),
                    "why": rec.get("why"),
                    "repl_seq": rec.get("repl_seq"),
                    "pending": rec.get("pending"),
                    "inflight": rec.get("inflight"),
                    "ledger_digest": rec.get("ledger_digest"),
                    "active_ledger_digest":
                        rec.get("active_ledger_digest"),
                    "view_digest": rec.get("view_digest"),
                    "active_view_digest": rec.get("active_view_digest"),
                    "digests_equal": eq,
                    "t_rel_s": round(
                        (rec["seen_ms"] - t0_wall_ms) / 1000.0, 2),
                })
            ha_section = {"enabled": True, "takeovers": takeovers}
        wall = time.monotonic() - t0
        window_done = len(completed)
        tps_wall = round(window_done / max(wall, 1e-9), 3)
        # fidelity vs baseline: completions over the capture's own
        # duration is the comparable rate (the drain tail would bias
        # the wall-clock rate low vs a steady-state window)
        tps_window = round(window_done / max(dur_s, 1e-9), 3)
        drift = None
        if orig_tps:
            drift = round(100.0 * (tps_window - orig_tps) / orig_tps, 1)

        timeline = None
        phase_drift = None
        if not no_trace and trace_dir.exists():
            try:
                timeline = _timeline_summary(trace_dir)
            except Exception as e:  # timeline is fidelity evidence,
                timeline = {"error": str(e)}  # never a replay failure
            base_p95 = baseline.get("phase_p95_ms") or {}
            got = (timeline or {}).get("fleet_phases_ms") or {}
            if base_p95 and got:
                phase_drift = {
                    ph: round(got[ph]["p95"] - v, 1)
                    for ph, v in base_p95.items()
                    if isinstance(got.get(ph), dict)
                    and got[ph].get("p95") is not None}

        joiner = watch.agg.audit
        audit_status = joiner.status()
        confirmed = [{k: d.get(k) for k in
                      ("class", "ns", "peer_a", "peer_b", "detail")}
                     for d in joiner.divergences]
        result = {
            "label": label,
            "capture_source": capture.get("source"),
            "fleet": dict(fleet),
            "solver": solver,
            "shards": shards,
            "federation": federation,
            "ha": ha_section,
            "injected": injected,
            "world_injected": world_injected,
            "expected": len(expected),
            "completed": len(completed),
            "completed_ids": completed,
            "missing": missing,
            "extra_done": extra_done,
            "done_dups": sim.done_dups,
            "mgr_completed": mgr_completed,
            "mgr_dispatched": mgr_dispatched,
            "completion_ratio": round(
                len(completed) / max(1, len(expected)), 4),
            "drained": drained,
            "wall_s": round(wall, 1),
            "inject_wall_s": round(inject_wall_s, 1),
            "tasks_per_s": tps_wall,
            "window_tasks_per_s": tps_window,
            "baseline": baseline or None,
            "drift": {"tasks_per_s_pct": drift,
                      "phase_p95_ms": phase_drift},
            "digests": _final_digests(joiner),
            "audit": {"verdict": audit_status["verdict"],
                      "joins": audit_status["joins"],
                      "beacons": audit_status["beacons"],
                      "active": audit_status["active"],
                      "confirmed": confirmed,
                      # peer -> {proc, ns, epoch}: the chaos judge maps
                      # a divergence record's peer id to its role
                      "epochs": audit_status["epochs"]},
            "sim": sim.stats(),
            "world": {"updates_seen": sim.world_updates,
                      "toggles_accepted": sim.world_accepted,
                      "toggles_rejected": sim.world_rejected},
            "chaos": (chaos.summary() if chaos is not None else None),
            "chaos_notes": list(ctx.notes),
            # the outcome contract: every captured task completed (none
            # lost), no id the capture never issued completed, and the
            # system of record never double-counted.  Pool-side
            # done_dups stays EVIDENCE, not a failure: the positional-
            # done/goal-exchange race double-delivers occasionally by
            # reference design, and the manager's ledger dedups it.
            "ok": (not missing and not extra_done
                   and mgr_completed <= len(expected)),
        }
        replay_beacon(final=True, extra={
            "drift_pct": drift,
            "phase_p95_delta_ms": phase_drift})
        if timeline is not None:
            result["timeline"] = timeline
        return result
    finally:
        for obj in (sim, watch):
            if obj is not None:
                obj.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        # dump the in-process ring into the replay's log dir (same
        # rationale as run_rung: the atexit dump fires after the env
        # restore and would strand the evidence elsewhere)
        _rec = _flightrec.get_recorder()
        _flightrec.dump(str(log_dir / f"{_rec.proc}-{_rec.pid}"
                                      ".flight.jsonl"),
                        reason="replay_teardown")
        os.environ.clear()
        os.environ.update(saved_env)
        _trace.configure(proc="simfleet")
        _events.configure("simfleet")


def run_tenant_smoke(args) -> int:
    """ISSUE 9 satellite (ROADMAP item 2 remaining headroom): admit N
    tenants DYNAMICALLY through the live ``solver.admit`` tenant_hello
    flow — orchestrator-style, the way a real control plane would — and
    prove each namespaced fleet completes tasks on the one solverd.
    tenant_scaling.py pre-registers via ``--tenants``; this path runs
    solverd with ``--multi-tenant`` ONLY, so admission happens on the
    wire."""
    ensure_built()
    tenants = [f"ft{i}" for i in range(args.tenants)]
    port = buspool.free_port()
    log_dir = Path(args.log_dir) / f"tenant_smoke_{args.tenants}"
    log_dir.mkdir(parents=True, exist_ok=True)
    procs, logs = [], []

    def spawn(name, cmd, stdin=None, env=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ, **(env or {})))
        procs.append(p)
        return p

    pool = orch = None
    pools = {}
    try:
        pool = buspool.BusPool(BUILD_DIR / "mapd_bus", num_shards=1,
                               home_port=port, spawn=spawn)
        os.environ.update(pool.env())
        time.sleep(0.3)
        sd = spawn("solverd",
                   [sys.executable, "-m",
                    "p2p_distributed_tswap_tpu.runtime.solverd",
                    "--port", str(port), "--map", args.map_file, "--cpu",
                    "--multi-tenant",
                    "--max-tenants", str(args.tenants)])
        if not wait_for_log(log_dir / "solverd.log", "solverd up", 600,
                            proc=sd):
            raise RuntimeError("solverd never became ready")
        # the orchestrator announces each tenant and waits for its
        # welcome BEFORE spawning the fleet — plan_requests published
        # into an unsubscribed topic would be lost
        orch = BusClient(port=port, peer_id="fleetsim-orch")
        orch.subscribe("solver.admit")
        welcomed = set()
        for ns in tenants:
            orch.publish("solver.admit",
                         {"type": "tenant_hello", "ns": ns})
        deadline = time.monotonic() + 30.0
        while len(welcomed) < len(tenants) \
                and time.monotonic() < deadline:
            f = orch.recv(timeout=0.5)
            if f and f.get("op") == "msg":
                d = f.get("data") or {}
                if d.get("type") == "tenant_welcome":
                    welcomed.add(d.get("ns"))
        if welcomed != set(tenants):
            print(f"tenant smoke FAILED: welcomes {sorted(welcomed)} != "
                  f"{tenants}", flush=True)
            return 1
        print(f"tenant smoke: {len(welcomed)} tenants admitted via "
              "solver.admit", flush=True)
        mgrs = {}
        for ns in tenants:
            mgrs[ns] = spawn(
                f"manager_{ns}",
                [str(BUILD_DIR / "mapd_manager_centralized"),
                 "--port", str(port), "--map", args.map_file,
                 "--solver", "tpu",
                 "--max-tracked-agents", str(args.agents + 8),
                 "--seed", str(args.seed)],
                stdin=subprocess.PIPE, env={"JG_BUS_NS": ns})
        time.sleep(0.5)
        for i, ns in enumerate(tenants):
            pools[ns] = SimAgentPool(args.agents, args.side, port=port,
                                     seed=i + 1, peer_id=f"sim-{ns}",
                                     namespace=ns)
        for p in pools.values():
            p.heartbeat_all()

        def pump_all(budget_s: float) -> None:
            end = time.monotonic() + budget_s
            while time.monotonic() < end:
                for p in pools.values():
                    p.pump(0.05)

        pump_all(2.0)
        for m in mgrs.values():
            m.stdin.write(f"tasks {args.agents}\n".encode())
            m.stdin.flush()
        pump_all(args.settle + args.window)
        done = {ns: p.done_count for ns, p in pools.items()}
        ok = all(v >= 1 for v in done.values())
        print(f"tenant smoke {'OK' if ok else 'FAILED'}: dynamic "
              f"admission + per-tenant dones {done}", flush=True)
        return 0 if ok else 1
    finally:
        for p in pools.values():
            p.close()
        if orch is not None:
            orch.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        os.environ.pop(buspool.SHARD_PORTS_ENV, None)


def write_replay_artifact(out: Path, res: dict, capture_path) -> None:
    """One replay's record (json + md): outcome ledger, final digests,
    fidelity drift, audit verdict."""
    out.parent.mkdir(parents=True, exist_ok=True)
    doc = {"experiment": "fleetsim replay: captured window re-driven "
                         "open-loop as a deterministic load",
           "capture": str(capture_path),
           "replay": res}
    out.write_text(json.dumps(doc, indent=2) + "\n")
    dg = res["digests"]
    drift = res.get("drift") or {}
    md = [f"# fleetsim replay — {capture_path}", "",
          f"- outcome: **{res['completed']}/{res['expected']} tasks "
          f"completed**, {len(res['missing'])} missing, "
          f"{res['done_dups']} duplicated "
          f"({'OK' if res['ok'] else 'FAILED'})",
          f"- fidelity: {res['window_tasks_per_s']} tasks/s vs original "
          f"{(res.get('baseline') or {}).get('tasks_per_s')} "
          f"(drift {drift.get('tasks_per_s_pct')}%)",
          f"- audit: {res['audit']['verdict']} "
          f"({len(res['audit']['confirmed'])} confirmed divergence(s))",
          "", "| digest | value | count | seq | epoch |", "|---|---|---|---|---|"]
    for k, v in dg.items():
        md.append(f"| {k} | `{v['digest']}` | {v['count']} | {v['seq']} "
                  f"| {v['epoch']} |")
    out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")


def write_artifact(out: Path, doc: dict) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=2) + "\n")
    md = [f"# fleetsim — {doc['experiment']}", ""]
    fl = doc.get("federation_ladder")
    if fl:
        md += ["## federation scaling ladder", "",
               f"aggregate tasks/s monotone with region count: "
               f"**{fl['monotone_tasks_per_s']}**", "",
               "| regions | pairs | tasks/s | completion | handoffs "
               "sent/acked | dup dropped |", "|---|---|---|---|---|---|"]
        for r in fl["rungs"]:
            md.append(
                f"| {r['regions']} | {r['region_count']} "
                f"| {r['tasks_per_s']} | {r['completion_ratio']} "
                f"| {r.get('handoffs_sent', '-')}"
                f"/{r.get('handoffs_acked', '-')} "
                f"| {r.get('handoffs_dup_dropped', '-')} |")
        md.append("")
    for rung in doc["rungs"]:
        md.append(f"### rung: {rung['agents']} agents @ "
                  f"{rung['tick_ms']} ms tick, {rung['shards']} bus "
                  f"shard(s) ({rung['mode']} loop, {rung['solver']}"
                  + (f", regions {rung['regions']}"
                     if rung.get("regions") else "") + ")")
        md.append("")
        md.append(f"- window: {rung['window_s']} s — "
                  f"{rung['window_tasks_completed']} completed / "
                  f"{rung['window_tasks_dispatched']} dispatched "
                  f"(fleet tasks/s "
                  f"{rung['signals'].get('fleet.tasks_per_s', '-')})")
        md.append("")
        md.append(_slo.render_md(rung["slo"]))
    if doc.get("saturation") is not None:
        s = doc["saturation"]
        md.append("## saturation search")
        md.append("")
        md.append(f"- max sustainable: **{s['max_sustainable_tasks_per_s']}"
                  f" tasks/s** at {s['max_sustainable_agents']} agents"
                  f" @ {s['max_sustainable_tick_ms']} ms tick"
                  if s.get("max_sustainable_tasks_per_s") is not None
                  else "- no rung passed the spec")
        if s.get("breached_at") is not None:
            md.append(f"- first breach: {s['breached_at']} — "
                      f"SLO(s) {', '.join(s['breached_slos'])}"
                      + (f", breaching phase {s['breaching_phase']}"
                         if s.get("breaching_phase") else ""))
        md.append("")
    out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--agents", type=int, default=200)
    ap.add_argument("--side", type=int, default=96,
                    help="empty square map side (96 puts 1000 agents at "
                         "~11%% density)")
    ap.add_argument("--shards", type=int,
                    default=int(os.environ.get("JG_BUS_SHARDS", "1") or 1),
                    help="busd pool shards (the federated plane)")
    ap.add_argument("--tick-ms", type=int, default=250,
                    help="manager planning interval")
    ap.add_argument("--regions", default=None,
                    help="federated world regions (ISSUE 14): a CxR "
                         "spec (e.g. 2x1) brings up one (manager"
                         "[, solverd]) pair per region on the shared "
                         "bus pool; unset/1 = single-pair fleet")
    ap.add_argument("--region-ladder", default=None,
                    help="comma list of region specs (e.g. 1,2x1,2x2): "
                         "run the SAME workload through each federation "
                         "size and record aggregate tasks/s per rung — "
                         "the scaling artifact mode")
    ap.add_argument("--mode", choices=["closed", "open"], default="closed",
                    help="closed: one task per agent, manager refills on "
                         "done (peak sustainable); open: inject --rate "
                         "tasks/s regardless of completion")
    ap.add_argument("--rate", type=float, default=10.0,
                    help="open-loop injection rate (tasks/s)")
    ap.add_argument("--shape", default="none",
                    choices=["none", "ramp", "flash", "storm"],
                    help="open-loop traffic shape (ISSUE 16): diurnal "
                         "ramp / flash crowd / tenant arrival storm; "
                         "'none' = constant --rate (legacy wire)")
    ap.add_argument("--shape-peak", type=float, default=None,
                    help="shape peak rate tasks/s (default 4x --rate)")
    ap.add_argument("--shape-period-s", type=float, default=None,
                    help="shape period seconds (default settle+window)")
    ap.add_argument("--window", type=float, default=30.0)
    ap.add_argument("--settle", type=float, default=45.0,
                    help="warmup before the window (first completions "
                         "need ~one task duration)")
    ap.add_argument("--ramp-s", type=float, default=20.0,
                    help="closed-loop fill ramp (chunked task injection; "
                         "clamped to settle/2)")
    ap.add_argument("--heartbeat-s", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--solver", choices=["native", "tpu"], default="native")
    ap.add_argument("--spec", default=None,
                    help="SLO spec JSON file (default: obs/slo.py "
                         "rated-load spec)")
    ap.add_argument("--saturate", default=None,
                    help="comma list of agent counts: stepped-load "
                         "search, stop at first SLO breach")
    ap.add_argument("--saturate-ticks", default=None,
                    help="comma list of tick periods (ms) at fixed "
                         "--agents: rate-laddered search")
    ap.add_argument("--keep-going", action="store_true",
                    help="saturation search: run EVERY ladder rung even "
                         "past the first breach (the committed-artifact "
                         "mode: breached rungs stay in the record with "
                         "their verdicts and breaching phases)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--log-dir", default="/tmp/fleetsim_logs")
    ap.add_argument("--capture", default=None, metavar="FILE",
                    help="record this run's traffic as a versioned "
                         "capture1 artifact (ISSUE 11): task ids + "
                         "arrival offsets + endpoints, accepted world "
                         "toggles, fleet config, baseline signals — "
                         "replayable via --replay")
    ap.add_argument("--replay", default=None, metavar="FILE",
                    help="re-drive a capture1 file open-loop as a "
                         "deterministic load (same task ids, arrival "
                         "offsets, world toggles) and judge the "
                         "outcome: exit 0 iff every captured task "
                         "completed, nothing uncaptured completed, and "
                         "the manager ledger never double-counted")
    ap.add_argument("--replay-solver", choices=["native", "tpu"],
                    default=None,
                    help="override the capture's solver for --replay")
    ap.add_argument("--replay-shards", type=int, default=None,
                    help="override the capture's bus shard count for "
                         "--replay")
    ap.add_argument("--replay-drain-s", type=float, default=None,
                    help="post-injection completion budget (default: "
                         "max(30, capture duration))")
    ap.add_argument("--replay-regions", default=None,
                    help="replay the capture through a federated CxR "
                         "fleet (tasks routed to their pickup region's "
                         "manager)")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip JG_TRACE (phase-attribution SLOs read "
                         "unknown)")
    ap.add_argument("--world-toggle-cells", type=int, default=0,
                    help="dynamic worlds (ISSUE 9): close a random "
                         "N-cell wall every --world-toggle-every "
                         "seconds (reopening the previous one); 0 = "
                         "static world")
    ap.add_argument("--world-toggle-every", type=float, default=6.0)
    ap.add_argument("--tenants", type=int, default=0,
                    help="dynamic-admission smoke (ISSUE 9 satellite): "
                         "N namespaced fleets admitted LIVE through the "
                         "solver.admit tenant_hello flow against one "
                         "--multi-tenant solverd (no pre-registration); "
                         "exit 0 iff every tenant gets a welcome and "
                         "completes >= 1 task")
    args = ap.parse_args(argv)

    if args.replay:
        try:
            capture = _capture.load(args.replay)
        except _capture.CaptureError as e:
            print(f"fleetsim: cannot replay {args.replay}: {e}",
                  file=sys.stderr)
            return 2
        res = run_replay(capture, args.log_dir,
                         solver=args.replay_solver,
                         shards=args.replay_shards,
                         no_trace=args.no_trace,
                         drain_s=args.replay_drain_s,
                         regions=args.replay_regions)
        print(json.dumps({k: res[k] for k in
                          ("expected", "completed", "missing",
                           "extra_done", "done_dups", "mgr_completed",
                           "window_tasks_per_s", "drift", "ok")}),
              flush=True)
        dg = res["digests"]
        print("replay digests: " + ", ".join(
            f"{k}={v['digest']}/{v['count']}" for k, v in dg.items()),
            flush=True)
        if args.out:
            write_replay_artifact(Path(args.out), res, args.replay)
        return 0 if res["ok"] else 1

    if args.tenants >= 1:
        args.map_file = f"/tmp/fleetsim_{args.side}.map.txt"
        Path(args.map_file).write_text(
            "\n".join(["." * args.side] * args.side) + "\n")
        return run_tenant_smoke(args)

    args.map_file = f"/tmp/fleetsim_{args.side}.map.txt"
    Path(args.map_file).write_text(
        "\n".join(["." * args.side] * args.side) + "\n")
    spec = _slo.load_spec(args.spec)

    if args.region_ladder:
        # federation scaling ladder (ISSUE 14): the SAME workload driven
        # through 1, 2, ... region pairs — the artifact behind
        # results/federation_r15.json (aggregate tasks/s must rise
        # monotonically on a workload that saturates one manager)
        rungs = []
        ladder = []
        for rspec in [r.strip() for r in args.region_ladder.split(",")
                      if r.strip()]:
            args.regions = None if rspec in ("", "1", "1x1") else rspec
            cols, rows, total = _fed_spec(args)
            print(f"fleetsim: federation rung {cols}x{rows} "
                  f"({total} region pair(s))", flush=True)
            rung = run_rung(args, args.agents, args.tick_ms, spec)
            rungs.append(rung)
            fed = rung.get("federation") or {}
            ladder.append({
                "regions": f"{cols}x{rows}",
                "region_count": total,
                "tasks_per_s": rung["signals"].get("fleet.tasks_per_s"),
                "completion_ratio": rung["signals"].get(
                    "fleet.completion_ratio"),
                "handoffs_sent": fed.get("handoffs_sent"),
                "handoffs_acked": fed.get("handoffs_acked"),
                "handoffs_dup_dropped": fed.get("handoffs_dup_dropped"),
            })
            print(json.dumps(ladder[-1]), flush=True)
        tps = [r["tasks_per_s"] for r in ladder]
        monotone = (all(v is not None for v in tps)
                    and all(b >= a for a, b in zip(tps, tps[1:])))
        doc = {
            "experiment": "federated world regions: aggregate tasks/s "
                          "vs region count on one saturating workload",
            "spec": spec,
            "rungs": rungs,
            "saturation": None,
            "federation_ladder": {"rungs": ladder,
                                  "monotone_tasks_per_s": monotone},
        }
        print(json.dumps({"ladder": ladder, "monotone": monotone}),
              flush=True)
        if args.out:
            write_artifact(Path(args.out), doc)
        return 0 if monotone else 1

    rungs = []
    saturation = None
    if args.saturate or args.saturate_ticks:
        if args.saturate:
            ladder = [(int(n), args.tick_ms)
                      for n in args.saturate.split(",")]
        else:
            ladder = [(args.agents, int(t))
                      for t in args.saturate_ticks.split(",")]
        last_pass = None
        breach = None
        for agents, tick_ms in ladder:
            print(f"fleetsim: rung {agents} agents @ {tick_ms} ms tick",
                  flush=True)
            rung = run_rung(args, agents, tick_ms, spec)
            rungs.append(rung)
            print(json.dumps({k: rung[k] for k in
                              ("agents", "tick_ms", "signals")}),
                  flush=True)
            print(_slo.render_line(rung["slo"]), flush=True)
            if rung["slo"]["ok"]:
                last_pass = rung
            elif breach is None:
                breach = rung
                if not args.keep_going:
                    break  # stepped-load search stops at the first breach
        breaching_phase = None
        if breach is not None:
            for v in breach["slo"]["verdicts"]:
                if v["status"] == "fail" and v.get("breaching_phase"):
                    breaching_phase = v["breaching_phase"]
                    break
        saturation = {
            "ladder": [{"agents": a, "tick_ms": t} for a, t in ladder],
            "max_sustainable_tasks_per_s":
                last_pass["signals"].get("fleet.tasks_per_s")
                if last_pass else None,
            "max_sustainable_agents":
                last_pass["agents"] if last_pass else None,
            "max_sustainable_tick_ms":
                last_pass["tick_ms"] if last_pass else None,
            "breached_at": (f"{breach['agents']} agents @ "
                            f"{breach['tick_ms']} ms"
                            if breach else None),
            "breached_slos": (breach["slo"]["failed"]
                              + breach["slo"]["unknown"])
            if breach else [],
            "breaching_phase": breaching_phase,
        }
    else:
        rung = run_rung(args, args.agents, args.tick_ms, spec)
        rungs.append(rung)
        print(_slo.render_line(rung["slo"]), flush=True)

    doc = {
        "experiment": "fleetsim load rehearsal: simulated wire-faithful "
                      "agent pool vs sharded bus + centralized manager",
        "spec": spec,
        "rungs": rungs,
        "saturation": saturation,
    }
    print(json.dumps({"rungs": len(rungs),
                      "ok": all(r["slo"]["ok"] for r in rungs),
                      "saturation": saturation}), flush=True)
    if args.out:
        write_artifact(Path(args.out), doc)
    if saturation is not None:
        return 0 if saturation["max_sustainable_agents"] is not None else 1
    return _slo.exit_code(rungs[0]["slo"])


if __name__ == "__main__":
    sys.exit(main())
