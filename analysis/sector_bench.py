#!/usr/bin/env python
"""Sector-planner bench (ISSUE 19): fresh-goal latency of the full
field pipeline vs the hierarchical sector planner, on the flagship-style
grid.

Four measured sections feed ``results/sector_r20.json``:

1. ``fresh_goal`` — ms per FRESH goal: the full jitted pipeline
   (fixpoint sweep -> direction extraction -> nibble pack, exactly what
   solverd's chunk-of-1 pays) against ``SectorPlanner.plan_goal`` for
   S in {32, 64, 128}, p50/p95 over seeded random goal/start draws;
2. ``epsilon`` — corridor suboptimality distribution: corridor distance
   at each start vs the true shortest path (scipy BFS reference), the
   committed bound is eps <= 0.05;
3. ``resident_bytes`` — per-goal host bytes: the corridor packed row
   (HW/2) vs the full repair mirror (5 bytes/cell: int32 distances +
   byte dirs), plus the corridor cell fraction actually computed;
4. ``fleet`` — a live-churn fleetsim rung (walls toggling mid-run via
   world_update_request) served with JG_SECTOR=1; completion ratio must
   hold 1.0.

Usage:
  python analysis/sector_bench.py --out results/sector_r20.json
  python analysis/sector_bench.py --quick     # 512^2 axis for bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.ops import sector  # noqa: E402
from p2p_distributed_tswap_tpu.ops.distance import (  # noqa: E402
    direction_fields,
    pack_directions,
)

FULL_SWEEP_BASELINE_MS = 3644.0  # results/field_engine_r11.json, 1024^2


def _pct(xs, p):
    return round(float(np.percentile(np.asarray(xs, np.float64), p)), 3)


def _ref_dist(free: np.ndarray, goal: int) -> np.ndarray:
    """True shortest-path distances from ``goal`` (scipy C BFS over the
    full-grid 4-adjacency CSR — independent of the planner's tables)."""
    from scipy.sparse.csgraph import dijkstra

    g = sector._grid_graph(free)
    d = dijkstra(g, directed=False, indices=goal, unweighted=True)
    d[~np.isfinite(d)] = float(sector.INF)
    return d


def bench_fresh_goal(free: np.ndarray, sizes, goals: int, full_goals: int,
                     starts_per_goal: int, seed: int) -> dict:
    h, w = free.shape
    rng = np.random.default_rng(seed)
    cells = np.flatnonzero(free.reshape(-1))

    # full pipeline: one cached field end to end, jitted like _fields
    fj = jnp.asarray(free)
    full = jax.jit(lambda fr, gl: pack_directions(
        direction_fields(fr, gl).reshape(1, -1)))
    full(fj, jnp.asarray([int(cells[0])], jnp.int32)).block_until_ready()
    full_ms = []
    for _ in range(full_goals):
        gl = jnp.asarray([int(rng.choice(cells))], jnp.int32)
        t0 = time.perf_counter()
        full(fj, gl).block_until_ready()
        full_ms.append(1000.0 * (time.perf_counter() - t0))

    out = {
        "grid": f"{h}x{w}",
        "full_goals": full_goals,
        "full_ms_p50": _pct(full_ms, 50),
        "full_ms_p95": _pct(full_ms, 95),
        "full_sweep_baseline_1024_ms": FULL_SWEEP_BASELINE_MS,
        "sector": [],
    }
    for s in sizes:
        t0 = time.perf_counter()
        pl = sector.SectorPlanner(free, s=s)
        build_ms = 1000.0 * (time.perf_counter() - t0)
        plan_ms, corridor_cells, plan_bytes = [], [], []
        for _ in range(goals):
            gl = int(rng.choice(cells))
            sts = [int(c)
                   for c in rng.choice(cells, starts_per_goal,
                                       replace=False) if int(c) != gl]
            t0 = time.perf_counter()
            plan = pl.plan_goal(gl, sts)
            plan_ms.append(1000.0 * (time.perf_counter() - t0))
            corridor_cells.append(plan.cells)
            plan_bytes.append(int(plan.packed.nbytes))
            pl.forget(gl)  # every draw pays the FRESH-goal cost
        out["sector"].append({
            "s": s,
            "build_ms": round(build_ms, 1),
            "goals": goals,
            "starts_per_goal": starts_per_goal,
            "plan_ms_p50": _pct(plan_ms, 50),
            "plan_ms_p95": _pct(plan_ms, 95),
            "speedup_p95_vs_full": round(
                _pct(full_ms, 95) / max(1e-9, _pct(plan_ms, 95)), 1),
            "corridor_cells_mean": int(np.mean(corridor_cells)),
            "corridor_fraction": round(
                float(np.mean(corridor_cells)) / (h * w), 4),
            "packed_row_bytes": int(np.mean(plan_bytes)),
        })
    return out


def bench_epsilon(free: np.ndarray, s: int, goals: int,
                  starts_per_goal: int, seed: int) -> dict:
    """Corridor distance vs true shortest path on seeded draws."""
    rng = np.random.default_rng(seed + 1)
    cells = np.flatnonzero(free.reshape(-1))
    pl = sector.SectorPlanner(free, s=s)
    eps, checked = [], 0
    for _ in range(goals):
        gl = int(rng.choice(cells))
        sts = [int(c) for c in rng.choice(cells, starts_per_goal,
                                          replace=False) if int(c) != gl]
        plan = pl.plan_goal(gl, sts, keep_dist=True)
        fd = _ref_dist(free, gl)
        cdist = plan.dist.reshape(-1)
        for st in sts:
            if fd[st] >= float(sector.INF):
                continue
            cd, truth = int(cdist[st]), int(fd[st])
            assert cd >= truth, (gl, st)
            eps.append((cd - truth) / max(1, truth))
            checked += 1
        pl.forget(gl)
    return {
        "s": s,
        "pairs": checked,
        "eps_mean": round(float(np.mean(eps)), 5) if eps else None,
        "eps_p95": _pct(eps, 95) if eps else None,
        "eps_max": round(float(np.max(eps)), 5) if eps else None,
        "bound": 0.05,
        "within_bound": bool(eps and float(np.max(eps)) <= 0.05),
    }


def resident_bytes(fresh: dict, free: np.ndarray) -> dict:
    """Per-goal host-resident bytes: corridor vs full repair mirror.
    The device row is HW/2 packed either way — the saving is the host
    mirror solverd keeps per cached goal (5 bytes/cell: int32 distance
    + byte dirs, runtime/solverd.py MIRROR_BYTES sizing)."""
    hw = int(np.prod(free.shape))
    full_mirror = 5 * hw
    rows = []
    for r in fresh["sector"]:
        rows.append({
            "s": r["s"],
            "corridor_packed_bytes": r["packed_row_bytes"],
            "corridor_fraction_computed": r["corridor_fraction"],
            "full_mirror_bytes": full_mirror,
            "ratio_vs_full_mirror": round(
                full_mirror / max(1, r["packed_row_bytes"]), 1),
        })
    return {"grid_cells": hw, "per_goal": rows}


def bench_fleet(args) -> dict:
    """Live-churn fleetsim rung served with JG_SECTOR=1: walls toggle
    mid-run, the sector corridors re-plan through the repair queue, and
    completion ratio must hold 1.0."""
    root = Path(__file__).resolve().parents[1]
    from p2p_distributed_tswap_tpu.runtime.fleet import BUILD_DIR
    import shutil

    if not (BUILD_DIR / "mapd_bus").exists() \
            and (shutil.which("cmake") is None
                 or shutil.which("ninja") is None):
        return {"skipped": "C++ runtime unavailable"}
    out = Path("/tmp/jg_sector_bench_fleet.json")
    out.unlink(missing_ok=True)
    cmd = [sys.executable, str(root / "analysis" / "fleetsim.py"),
           "--agents", str(args.fleet_agents),
           "--side", str(args.fleet_side),
           "--tick-ms", "250", "--settle", "16",
           "--window", str(args.fleet_window), "--seed", "1",
           "--solver", "tpu", "--world-toggle-cells", "5",
           "--world-toggle-every", "5", "--no-trace",
           "--log-dir", "/tmp/jg_sector_bench_fleet_logs",
           "--out", str(out)]
    env = dict(os.environ, JAX_PLATFORMS="cpu", JG_SECTOR="1",
               JG_SECTOR_CELLS=str(args.fleet_sector_cells))
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=900, env=env, cwd=root)
    except subprocess.TimeoutExpired:
        return {"error": "fleetsim timeout"}
    if not out.exists():
        return {"error": (proc.stderr or proc.stdout or "no output")[-300:]}
    rung = json.loads(out.read_text())["rungs"][0]
    sig = rung.get("signals") or {}
    return {
        "agents": rung.get("agents"),
        "side": args.fleet_side,
        "sector_cells": args.fleet_sector_cells,
        "world": rung.get("world"),
        "tasks_per_s": sig.get("fleet.tasks_per_s"),
        "completion_ratio": sig.get("fleet.completion_ratio"),
        "completion_ratio_is_1": sig.get("fleet.completion_ratio") == 1.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--side", type=int, default=1024)
    ap.add_argument("--sizes", default="32,64,128",
                    help="comma list of sector sizes S")
    ap.add_argument("--goals", type=int, default=20,
                    help="fresh-goal draws per sector size")
    ap.add_argument("--full-goals", type=int, default=5,
                    help="full-pipeline draws (each costs a full sweep)")
    ap.add_argument("--starts", type=int, default=2,
                    help="starts folded per fresh goal (serving hands "
                         "plan_goal the requesting lane positions — "
                         "one or two on a fresh goal; more starts "
                         "union more route corridors)")
    ap.add_argument("--eps-goals", type=int, default=8,
                    help="goals sampled for the suboptimality section")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--quick", action="store_true",
                    help="bench.py axis scale: 512^2, S=64 only, "
                         "no fleet rung")
    ap.add_argument("--no-fleet", action="store_true")
    ap.add_argument("--fleet-agents", type=int, default=12)
    ap.add_argument("--fleet-side", type=int, default=48)
    ap.add_argument("--fleet-sector-cells", type=int, default=16)
    ap.add_argument("--fleet-window", type=float, default=30.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.quick:
        args.side, args.sizes = 512, "64"
        args.goals, args.full_goals, args.eps_goals = 8, 3, 4
        args.no_fleet = True
    sizes = [int(s) for s in args.sizes.split(",") if s.strip()]

    grid = Grid.random_obstacles(args.side, args.side, 0.15, args.seed)
    free = np.asarray(grid.free).copy()
    doc = {
        "experiment": "hierarchical sector-graph planner: fresh-goal "
                      "latency vs full field pipeline (ISSUE 19)",
        "backend": jax.default_backend(),
        "host_note": "CPU-container numbers; the full-vs-sector RATIO "
                     "is the portable claim — the sector path is host "
                     "scipy BFS over corridor windows, the full path "
                     "is the jitted whole-grid fixpoint.",
    }
    print(f"sector_bench: fresh goal @ {args.side}^2, S={sizes}",
          flush=True)
    doc["fresh_goal"] = bench_fresh_goal(free, sizes, args.goals,
                                         args.full_goals, args.starts,
                                         args.seed)
    print(json.dumps(doc["fresh_goal"]), flush=True)
    print("sector_bench: suboptimality", flush=True)
    eps_s = 64 if 64 in sizes else sizes[0]
    doc["epsilon"] = bench_epsilon(free, eps_s, args.eps_goals,
                                   args.starts, args.seed)
    print(json.dumps(doc["epsilon"]), flush=True)
    doc["resident_bytes"] = resident_bytes(doc["fresh_goal"], free)
    if not args.no_fleet:
        print("sector_bench: live-churn fleet rung", flush=True)
        doc["fleet"] = bench_fleet(args)
        print(json.dumps(doc["fleet"]), flush=True)

    default_row = next((r for r in doc["fresh_goal"]["sector"]
                        if r["s"] == 64), doc["fresh_goal"]["sector"][0])
    doc["acceptance"] = {
        "fresh_goal_p95_speedup_at_default_s":
            default_row["speedup_p95_vs_full"],
        "speedup_ge_20x": default_row["speedup_p95_vs_full"] >= 20.0,
        "p95_vs_3644ms_baseline": round(
            FULL_SWEEP_BASELINE_MS / max(1e-9, default_row["plan_ms_p95"]),
            1) if args.side == 1024 else None,
        "eps_within_bound": doc["epsilon"]["within_bound"],
        "fleet_completion_1": (doc.get("fleet") or {}).get(
            "completion_ratio_is_1"),
    }
    ok = bool(doc["acceptance"]["speedup_ge_20x"]
              and doc["acceptance"]["eps_within_bound"])
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2) + "\n")
        fg, ep = doc["fresh_goal"], doc["epsilon"]
        md = [
            "# sector — hierarchical sector-graph planner (ISSUE 19)",
            "",
            f"- grid: {fg['grid']} (15% obstacles), backend "
            f"{doc['backend']}",
            f"- full pipeline fresh goal: p50 {fg['full_ms_p50']} ms / "
            f"p95 {fg['full_ms_p95']} ms "
            f"(1024^2 full-sweep baseline {FULL_SWEEP_BASELINE_MS} ms)",
        ]
        for r in fg["sector"]:
            md.append(
                f"- S={r['s']}: plan p50 {r['plan_ms_p50']} ms / p95 "
                f"{r['plan_ms_p95']} ms (**{r['speedup_p95_vs_full']}x** "
                f"vs full p95), corridor "
                f"{100 * r['corridor_fraction']:.1f}% of cells, build "
                f"{r['build_ms']} ms")
        md.append(
            f"- suboptimality (S={ep['s']}, {ep['pairs']} pairs): mean "
            f"{ep['eps_mean']}, p95 {ep['eps_p95']}, max {ep['eps_max']} "
            f"(bound {ep['bound']}; within: {ep['within_bound']})")
        rb = doc["resident_bytes"]["per_goal"][0]
        md.append(
            f"- per-goal host bytes: corridor packed row "
            f"{rb['corridor_packed_bytes']} vs full repair mirror "
            f"{rb['full_mirror_bytes']} "
            f"(**{rb['ratio_vs_full_mirror']}x** smaller)")
        if doc.get("fleet") and not doc["fleet"].get("skipped"):
            f = doc["fleet"]
            md.append(
                f"- live-churn fleet rung (JG_SECTOR=1, "
                f"{f['side']}^2, S={f['sector_cells']}): "
                f"{(f.get('world') or {}).get('requests')} wall "
                f"event(s), completion ratio {f['completion_ratio']} "
                f"(1.0: {f['completion_ratio_is_1']})")
        out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")
    print(json.dumps({"acceptance": doc["acceptance"]}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
