#!/usr/bin/env python
"""Mesh-solverd measurement harness (ISSUE 13): the first rungs of the
sharded serving-plane perf trajectory, on the virtual CPU mesh.

For each mesh rung (flat, 2-way, 8-way agent-axis by default) a FRESH
subprocess — the virtual device count must be forced before jax creates
its CPU client — drives a synthetic packed-wire fleet through a real
``TickRunner`` and reports:

- ``tick_ms`` p50/p95 of the full decode->plan->encode tick;
- ``sweep_ms``: one cold 8-goal direction-field sweep chunk;
- per-shard resident bytes of the planning state (dirs cache + lanes)
  — THE LEVER: peak per-device HBM shrinks ~mesh-size;
- a determinism fingerprint: FNV-1a over every packed response byte,
  plus the final mirror/device/fields audit digests.

The driver compares fingerprints across rungs (``bit_identical`` must
be true — the mesh is a residency/throughput lever, never a semantics
one), optionally replays the committed CI capture through a 2-way mesh
solverd (scripts/chaos_gate.py --determinism --solver tpu with
JG_SOLVER_MESH=2) for the live determinism proof, and writes the
``results/mesh_solverd_r14.json(.md)`` artifact.

Wall-clock note: on this 2-core container the virtual mesh TIME-SLICES
one CPU, so mesh rungs are expected slower end-to-end — the committed
verdict is exactness + residency; step-time speedups await real
multi-chip ICI (SCALING.md "Sharded step overhead" measured the mesh
collective pattern at 0.75x total work at 2x4).

Usage:
  python analysis/mesh_bench.py [--meshes 1,2,8] [--agents 16]
      [--side 32] [--ticks 12] [--no-replay] [--out results/...json]
  python analysis/mesh_bench.py --rung --mesh 2 ...   # one subprocess
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))
DEFAULT_CAPTURE = ROOT / "results" / "captures" / "ci_small.capture.json"


def run_rung(args) -> dict:
    """One mesh rung in THIS process (spawned with the right XLA_FLAGS
    by the driver)."""
    from p2p_distributed_tswap_tpu.parallel.virtual_mesh import (
        pin_cpu_backend)

    spec = args.mesh
    from p2p_distributed_tswap_tpu.parallel import solver_mesh

    shape = solver_mesh.mesh_spec_from_env(spec)
    n_dev = shape[0] * shape[1] if shape else 1
    pin_cpu_backend(max(n_dev, 1))

    import numpy as np

    from p2p_distributed_tswap_tpu.core.grid import Grid
    from p2p_distributed_tswap_tpu.obs import audit as au
    from p2p_distributed_tswap_tpu.runtime import plan_codec as pc
    from p2p_distributed_tswap_tpu.runtime.solverd import (PlanService,
                                                           TickRunner)

    grid = Grid.from_ascii("\n".join(["." * args.side] * args.side) + "\n")
    mesh = solver_mesh.SolverMesh(*shape) if shape else None
    svc = PlanService(grid, capacity_min=16, mesh=mesh)
    svc.defer_fields = False
    runner = TickRunner(svc, grid)
    enc = pc.PackedFleetEncoder(snapshot_every=64)

    rng = np.random.default_rng(11)
    free = np.flatnonzero(np.asarray(grid.free).reshape(-1)).astype(int)
    n = args.agents
    cells = rng.choice(free, size=2 * n, replace=False)
    fleet = {f"p{k}": [int(cells[k]), int(cells[n + k])]
             for k in range(n)}

    def items():
        return [(nm, p, g) for nm, (p, g) in sorted(fleet.items())]

    # cold sweep chunk: 8 fresh goals through the (possibly sharded)
    # field program — compile excluded via one warm call on 8 other goals
    warm_goals = [int(c) for c in rng.choice(free, size=8, replace=False)]
    svc._ensure_fields(warm_goals)
    cold_goals = [int(c) for c in rng.choice(
        np.setdiff1d(free, warm_goals), size=8, replace=False)]
    t0 = time.perf_counter()
    svc._ensure_fields(cold_goals)
    sweep_ms = 1000.0 * (time.perf_counter() - t0)

    fp = au.FNV64_OFFSET
    tick_ms = []
    for seq in range(1, args.ticks + 1):
        t0 = time.perf_counter()
        resp = runner.handle({"type": "plan_request", "seq": seq,
                              "codec": pc.CODEC_NAME,
                              "caps": [pc.CODEC_NAME],
                              "data": pc.encode_b64(
                                  enc.encode_tick(seq, items()))})
        tick_ms.append(1000.0 * (time.perf_counter() - t0))
        fp = au.fnv1a64(resp["data"].encode(), fp)
        rp = pc.decode_b64(resp["data"])
        for lane, c, g in zip(rp.idx, rp.pos, rp.goal):
            fleet[runner.packed.name_of(int(lane))] = [int(c), int(g)]
        k = f"p{int(rng.integers(n))}"
        fleet[k][1] = int(rng.choice(free))  # task churn

    tick_ms.sort()
    m, _ = au.lane_digest(*svc.audit_views("mirror"))
    d, _ = au.lane_digest(*svc.audit_views("device"))
    fresh = [g for g in svc.goal_rows if g != -1 and not svc._is_stale(g)]
    fd, _ = au.cells_digest(fresh)
    per = svc.resident_shard_bytes()
    total = (sum(per.values()) if per else
             sum(int(np.prod(a.shape)) * a.dtype.itemsize
                 for a in (svc.dirs, svc.d_pos, svc.d_goal, svc.d_slot,
                           svc.d_active) if a is not None))
    return {
        "mesh": spec or "1",
        "devices": n_dev,
        "agents": n,
        "side": args.side,
        "ticks": args.ticks,
        "tick_ms_p50": round(tick_ms[len(tick_ms) // 2], 2),
        # nearest-rank p95 (ceil, not trunc — trunc under-reports by a
        # whole rank at these small tick counts)
        "tick_ms_p95": round(
            tick_ms[max(0, -(-len(tick_ms) * 19 // 20) - 1)], 2),
        "sweep_chunk8_ms": round(sweep_ms, 2),
        "resident_bytes_total": int(total),
        "resident_bytes_per_shard": {str(k): int(v)
                                     for k, v in sorted(per.items())},
        "resident_bytes_peak_shard": int(max(per.values())) if per
        else int(total),
        "fingerprint": {
            "responses": au.digest_hex(fp),
            "mirror": au.digest_hex(m),
            "device": au.digest_hex(d),
            "fields": au.digest_hex(fd),
        },
    }


def run_replay_proof(log_dir: str, capture: Path) -> dict:
    """The live proof: the committed CI capture re-driven through a
    2-way-mesh solverd, twice — scripts/chaos_gate.py's determinism
    pair must come back green (identical completed sets + equal audit
    digests at the drained watermark)."""
    import shutil
    import tempfile

    if not capture.exists():
        return {"skipped": f"no capture at {capture}"}
    # same availability rule as every other gate: prebuilt binaries OR
    # the cmake+ninja toolchain ensure_built() actually uses
    if not (ROOT / "cpp" / "build" / "mapd_bus").exists() \
            and (shutil.which("cmake") is None
                 or shutil.which("ninja") is None):
        return {"skipped": "C++ runtime unavailable"}
    out = Path(tempfile.mkdtemp(prefix="jg-mesh-replay-")) / "chaos.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", JG_SOLVER_MESH="2")
    cmd = [sys.executable, str(ROOT / "scripts" / "chaos_gate.py"),
           "--capture", str(capture), "--determinism", "--solver", "tpu",
           "--out", str(out), "--log-dir", log_dir]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=900, env=env, cwd=str(ROOT))
    except subprocess.TimeoutExpired:
        return {"error": "replay timeout"}
    if not out.exists():
        return {"error": (proc.stderr or proc.stdout or "no output")[-400:]}
    doc = json.loads(out.read_text())
    det = doc.get("determinism") or {}
    return {
        "capture": str(capture.relative_to(ROOT)),
        "solver_mesh": "2",
        "determinism_ok": det.get("ok"),
        "completed_equal": det.get("completed_equal"),
        "digests": {k: v.get("equal")
                    for k, v in (det.get("digests") or {}).items()},
        "verdicts": {v["fault"]: v["verdict"]
                     for v in doc.get("matrix") or []},
    }


def render_md(doc: dict) -> str:
    md = ["# Mesh-sharded solverd — exactness + residency rungs "
          "(ISSUE 13)", ""]
    md.append(f"- bit-identical across rungs: **{doc['bit_identical']}** "
              "(packed responses, mirror/device/fields digests)")
    rungs = doc["rungs"]
    md.append("")
    md.append("| mesh | devices | tick p50 ms | tick p95 ms | "
              "sweep(8) ms | peak shard MB | total MB |")
    md.append("|---|---|---|---|---|---|---|")
    for r in rungs:
        md.append(
            f"| {r['mesh']} | {r['devices']} | {r['tick_ms_p50']} "
            f"| {r['tick_ms_p95']} | {r['sweep_chunk8_ms']} "
            f"| {r['resident_bytes_peak_shard'] / 2**20:.2f} "
            f"| {r['resident_bytes_total'] / 2**20:.2f} |")
    md.append("")
    rp = doc.get("replay") or {}
    if rp.get("determinism_ok") is not None:
        md.append(f"Replay through a 2-way mesh solverd "
                  f"(`{rp.get('capture')}`): determinism proof "
                  f"**{'PASS' if rp['determinism_ok'] else 'FAIL'}** "
                  f"(completed sets equal={rp.get('completed_equal')}, "
                  f"digests {rp.get('digests')}).")
    elif rp:
        md.append(f"Replay proof: {rp.get('skipped') or rp.get('error')}")
    md.append("")
    md.append(doc["note"])
    return "\n".join(md) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", action="store_true",
                    help="internal: run ONE mesh rung in this process")
    ap.add_argument("--mesh", default=None,
                    help="mesh spec for --rung (None/1 = flat)")
    ap.add_argument("--meshes", default="1,2,8",
                    help="comma list of rung specs for the driver")
    ap.add_argument("--agents", type=int, default=16)
    ap.add_argument("--side", type=int, default=32)
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--no-replay", action="store_true")
    ap.add_argument("--capture", default=str(DEFAULT_CAPTURE))
    ap.add_argument("--out", default=None)
    ap.add_argument("--log-dir", default="/tmp/jg_mesh_bench_logs")
    args = ap.parse_args(argv)

    if args.rung:
        print(json.dumps(run_rung(args)), flush=True)
        return 0

    # one shared grammar + validation (jax stays un-imported in the
    # rung subprocesses' parents until here; importing the parser is
    # harmless — no device client is created)
    from p2p_distributed_tswap_tpu.parallel.solver_mesh import (
        parse_mesh_spec)

    rungs = []
    for spec in [s.strip() for s in args.meshes.split(",") if s.strip()]:
        a, t = parse_mesh_spec(spec)
        n_dev = a * t
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # the rung process re-pins anyway (pin_cpu_backend), but the
        # flag must be in the env before ITS jax import
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count="
                              f"{max(n_dev, 1)}").strip()
        cmd = [sys.executable, str(Path(__file__).resolve()), "--rung",
               "--mesh", spec, "--agents", str(args.agents),
               "--side", str(args.side), "--ticks", str(args.ticks)]
        print(f"mesh_bench: rung mesh={spec} ({n_dev} devices)...",
              flush=True)
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1200, env=env, cwd=str(ROOT))
        if proc.returncode != 0:
            print(proc.stdout, proc.stderr, file=sys.stderr)
            return 1
        rung = json.loads(proc.stdout.strip().splitlines()[-1])
        print(f"mesh_bench:   tick p50 {rung['tick_ms_p50']} ms, "
              f"peak shard {rung['resident_bytes_peak_shard'] / 2**20:.2f}"
              f" MB, responses {rung['fingerprint']['responses']}",
              flush=True)
        rungs.append(rung)

    fps = {json.dumps(r["fingerprint"], sort_keys=True) for r in rungs}
    bit_identical = len(fps) == 1
    doc = {
        "experiment": "mesh-sharded solverd rungs (virtual CPU mesh)",
        "bit_identical": bit_identical,
        "rungs": rungs,
        "replay": None,
        "note": ("Virtual-mesh rungs on a shared-CPU host: the committed "
                 "verdict is EXACTNESS (bit-identical responses + audit "
                 "digests) and the per-shard residency lever; wall-clock "
                 "speedups await real multi-chip ICI (SCALING.md)."),
    }
    if not args.no_replay:
        doc["replay"] = run_replay_proof(args.log_dir, Path(args.capture))
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2) + "\n")
        Path(str(out) + ".md").write_text(render_md(doc))
        print(f"mesh_bench: wrote {out} (+.md)", flush=True)
    print(json.dumps({"bit_identical": bit_identical,
                      "replay_ok": (doc["replay"] or {}).get(
                          "determinism_ok"),
                      "peak_shard_mb": [
                          round(r["resident_bytes_peak_shard"] / 2**20, 2)
                          for r in rungs]}), flush=True)
    return 0 if bit_identical else 1


if __name__ == "__main__":
    sys.exit(main())
