"""Merged host+solver trace report (obs/ tentpole).

Consumes Chrome trace-event JSONL written by the Python tracer
(p2p_distributed_tswap_tpu/obs/trace.py) and the C++ tracer
(cpp/common/trace.hpp) — any mix of files, typically one per process of a
fleet — and prints:

1. per-span latency table: count, p50/p95/p99/max milliseconds, total;
2. the tick-budget breakdown: mean per-phase cost inside each tick span
   (``solverd.tick``, ``manager.plan_tick``) against the 500 ms planning
   tick, including the untraced remainder, plus over-budget tick counts;
3. final counter values per process (Chrome "C" events);
4. optionally (--perfetto OUT.json) one merged ``{"traceEvents": [...]}``
   file that https://ui.perfetto.dev opens directly — the per-process
   wall-clock anchors make the timelines interleave at ~ms alignment.

Usage:
    python analysis/trace_report.py [FILE_OR_DIR ...]
        [--budget-ms 500] [--perfetto merged.json]

With no paths, reads every *.trace.jsonl under $JG_TRACE_DIR
(default results/trace).  Heartbeat sidecars (*.heartbeat.jsonl) found
next to trace files contribute the over-budget tick summary.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# the centralized manager's planning tick (ref manager.rs:567)
DEFAULT_BUDGET_MS = 500.0
# top-level per-tick spans whose children form the budget breakdown
TICK_SPANS = ("solverd.tick", "manager.plan_tick")


def _discover(paths: List[str]) -> Tuple[List[str], List[str]]:
    """Expand args (files or dirs) into (trace_files, heartbeat_files)."""
    if not paths:
        paths = [os.environ.get("JG_TRACE_DIR", "results/trace")]
    traces, beats = [], []
    for p in paths:
        if os.path.isdir(p):
            traces += sorted(glob.glob(os.path.join(p, "*.trace.jsonl")))
            beats += sorted(glob.glob(os.path.join(p, "*.heartbeat.jsonl")))
        elif p.endswith(".heartbeat.jsonl"):
            beats.append(p)
        else:
            traces.append(p)
    return traces, beats


def load_events(trace_files: List[str]) -> List[dict]:
    """All parseable event objects from the given JSONL files (bad lines —
    e.g. a truncated final line from a killed process — are skipped)."""
    events = []
    for path in trace_files:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(ev, dict) and "ph" in ev:
                        events.append(ev)
        except OSError as e:
            print(f"⚠️ cannot read {path}: {e}", file=sys.stderr)
    return events


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[k]


def build_report(events: List[dict],
                 budget_ms: float = DEFAULT_BUDGET_MS) -> dict:
    """Fold events into the report structure (testable, print-free)."""
    proc_names: Dict[int, str] = {}
    spans: Dict[str, List[float]] = defaultdict(list)  # name -> durs (ms)
    counters: Dict[Tuple[str, str], int] = {}
    ticks: Dict[str, List[dict]] = defaultdict(list)
    children: Dict[str, Dict[str, float]] = defaultdict(
        lambda: defaultdict(float))  # tick span -> child name -> total ms

    for ev in events:
        ph = ev.get("ph")
        if ph == "M" and ev.get("name") == "process_name":
            proc_names[ev.get("pid", 0)] = ev.get("args", {}).get("name", "?")
        elif ph == "X":
            name = ev.get("name", "?")
            dur_ms = ev.get("dur", 0) / 1000.0
            spans[name].append(dur_ms)
            parent = ev.get("args", {}).get("parent")
            if name in TICK_SPANS:
                ticks[name].append(ev)
            elif parent in TICK_SPANS:
                children[parent][name] += dur_ms
        elif ph == "C":
            proc = proc_names.get(ev.get("pid", 0), str(ev.get("pid", "?")))
            # last value wins: flushes append cumulative snapshots
            counters[(proc, ev.get("name", "?"))] = \
                ev.get("args", {}).get("value", 0)

    span_stats = {}
    for name, durs in spans.items():
        s = sorted(durs)
        span_stats[name] = {
            "count": len(s), "p50_ms": round(_pct(s, 0.50), 3),
            "p95_ms": round(_pct(s, 0.95), 3),
            "p99_ms": round(_pct(s, 0.99), 3),
            "max_ms": round(s[-1], 3), "total_ms": round(sum(s), 3),
        }

    budget = {}
    for tick_name, tick_evs in ticks.items():
        durs = sorted(ev.get("dur", 0) / 1000.0 for ev in tick_evs)
        n = len(durs)
        phases = {}
        for child, total in sorted(children[tick_name].items(),
                                   key=lambda kv: -kv[1]):
            mean = total / n if n else 0.0
            phases[child] = {"mean_ms": round(mean, 3),
                             "pct_of_budget": round(100 * mean / budget_ms, 1)}
        mean_tick = sum(durs) / n if n else 0.0
        traced = sum(v["mean_ms"] for v in phases.values())
        budget[tick_name] = {
            "ticks": n,
            "mean_ms": round(mean_tick, 3),
            "p50_ms": round(_pct(durs, 0.50), 3),
            "p95_ms": round(_pct(durs, 0.95), 3),
            "p99_ms": round(_pct(durs, 0.99), 3),
            "budget_ms": budget_ms,
            "over_budget_ticks": sum(1 for d in durs if d > budget_ms),
            "phases": phases,
            "untraced_ms": round(max(0.0, mean_tick - traced), 3),
        }

    by_proc: Dict[str, Dict[str, int]] = defaultdict(dict)
    for (proc, name), v in sorted(counters.items()):
        by_proc[proc][name] = v
    return {"processes": sorted(proc_names.values()),
            "spans": span_stats, "budget": budget,
            "counters": dict(by_proc)}


def load_heartbeats(beat_files: List[str]) -> Optional[dict]:
    total = over = 0
    worst = 0.0
    for path in beat_files:
        try:
            with open(path) as f:
                for line in f:
                    try:
                        hb = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    total += 1
                    if hb.get("over_budget"):
                        over += 1
                    worst = max(worst, hb.get("ms", {}).get("total", 0.0))
        except OSError:
            continue
    if not total:
        return None
    return {"ticks": total, "over_budget": over, "worst_ms": round(worst, 3)}


def print_report(report: dict, heartbeats: Optional[dict] = None) -> None:
    if report["processes"]:
        print(f"processes: {', '.join(report['processes'])}")
    print()
    print("| span | count | p50 ms | p95 ms | p99 ms | max ms | total ms |")
    print("|---|---|---|---|---|---|---|")
    for name, s in sorted(report["spans"].items(),
                          key=lambda kv: -kv[1]["total_ms"]):
        print(f"| {name} | {s['count']} | {s['p50_ms']} | {s['p95_ms']} "
              f"| {s['p99_ms']} | {s['max_ms']} | {s['total_ms']} |")

    for tick_name, b in report["budget"].items():
        print()
        print(f"## tick budget — {tick_name} "
              f"({b['ticks']} ticks vs {b['budget_ms']:.0f} ms budget)")
        print(f"mean {b['mean_ms']} ms, p50 {b['p50_ms']} / "
              f"p95 {b['p95_ms']} / p99 {b['p99_ms']} ms; "
              f"{b['over_budget_ticks']} tick(s) over budget")
        print()
        print("| phase | mean ms/tick | % of budget |")
        print("|---|---|---|")
        for child, v in b["phases"].items():
            print(f"| {child} | {v['mean_ms']} | {v['pct_of_budget']}% |")
        print(f"| (untraced remainder) | {b['untraced_ms']} | "
              f"{round(100 * b['untraced_ms'] / b['budget_ms'], 1)}% |")

    if heartbeats:
        print()
        print(f"heartbeats: {heartbeats['ticks']} ticks, "
              f"{heartbeats['over_budget']} over budget, "
              f"worst {heartbeats['worst_ms']} ms")

    if report["counters"]:
        print()
        print("## counters")
        for proc, cs in report["counters"].items():
            for name, v in cs.items():
                print(f"{proc}: {name} = {v}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="*",
                    help="trace JSONL files or directories "
                         "(default: $JG_TRACE_DIR or results/trace)")
    ap.add_argument("--budget-ms", type=float, default=DEFAULT_BUDGET_MS)
    ap.add_argument("--perfetto", default=None, metavar="OUT.json",
                    help="also write one merged traceEvents JSON for "
                         "ui.perfetto.dev")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON object instead of "
                         "markdown tables")
    args = ap.parse_args(argv)

    traces, beats = _discover(args.paths)
    if not traces:
        print("no *.trace.jsonl found (is JG_TRACE=1 set on the fleet?)",
              file=sys.stderr)
        return 1
    events = load_events(traces)
    if not events:
        print("trace files contained no events", file=sys.stderr)
        return 1
    report = build_report(events, budget_ms=args.budget_ms)
    if args.perfetto:
        Path(args.perfetto).write_text(json.dumps({"traceEvents": events}))
        print(f"merged perfetto trace: {args.perfetto} "
              f"({len(events)} events from {len(traces)} file(s))",
              file=sys.stderr)
    if args.json:
        print(json.dumps(report))
    else:
        print_report(report, load_heartbeats(beats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
