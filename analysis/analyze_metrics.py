#!/usr/bin/env python3
"""Offline task-metrics analysis (capability of the reference's
analyze_metrics.py: success rate, total/processing/startup-latency
distributions, per-agent fairness table, latency histogram, percentiles, and
an executive summary with coefficient-of-variation interpretation, with
--save report export).

Usage: python analysis/analyze_metrics.py task_metrics.csv [--all] [--save R]
"""

from __future__ import annotations

import argparse
import sys

import pandas as pd


def banner(title: str) -> str:
    return f"\n{'=' * 64}\n{title}\n{'=' * 64}"


def basic_stats(df: pd.DataFrame) -> str:
    out = [banner("TASK COMPLETION")]
    total = len(df)
    completed = int((df["status"] == "completed").sum())
    failed = int((df["status"] == "failed").sum())
    rate = 100.0 * completed / total if total else 0.0
    out.append(f"tasks: {total}  completed: {completed}  failed: {failed}")
    out.append(f"success rate: {rate:.1f}%")
    for col, label in [("total_time_ms", "total latency"),
                       ("processing_time_ms", "processing time"),
                       ("startup_latency_ms", "startup latency")]:
        if col not in df.columns:
            continue
        v = df[df[col] > 0][col]
        if v.empty:
            continue
        out.append(f"{label}: mean {v.mean():.1f} ms  median {v.median():.1f}"
                   f" ms  std {v.std():.1f} ms  min {v.min():.0f}"
                   f" ms  max {v.max():.0f} ms")
    return "\n".join(out)


def per_agent(df: pd.DataFrame) -> str:
    if "peer_id" not in df.columns:
        return ""
    out = [banner("PER-AGENT BREAKDOWN")]
    done = df[df["status"] == "completed"]
    if done.empty:
        return "\n".join(out + ["no completed tasks"])
    g = done.groupby("peer_id")["total_time_ms"].agg(
        ["count", "mean", "min", "max", "std"])
    out.append(f"{'agent':<16}{'tasks':>6}{'avg':>10}{'min':>10}"
               f"{'max':>10}{'std':>10}")
    for peer, row in g.iterrows():
        out.append(f"{str(peer)[:14]:<16}{int(row['count']):>6}"
                   f"{row['mean']:>10.1f}{row['min']:>10.1f}"
                   f"{row['max']:>10.1f}{row['std'] if row['std'] == row['std'] else 0:>10.1f}")
    return "\n".join(out)


def histogram(df: pd.DataFrame) -> str:
    if "total_time_ms" not in df.columns:
        return ""
    v = df[df["total_time_ms"] > 0]["total_time_ms"] / 1000.0
    if v.empty:
        return ""
    out = [banner("LATENCY HISTOGRAM (s)")]
    bins = [0, 1, 5, 10, 30, 60, float("inf")]
    labels = ["<1s", "1-5s", "5-10s", "10-30s", "30-60s", ">60s"]
    counts = pd.cut(v, bins=bins, labels=labels, right=False).value_counts()
    for label in labels:
        c = int(counts.get(label, 0))
        bar = "#" * int(40 * c / max(1, counts.max()))
        out.append(f"{label:>7} | {c:>5} {bar}")
    return "\n".join(out)


def percentiles(df: pd.DataFrame) -> str:
    if "total_time_ms" not in df.columns:
        return ""
    v = df[df["total_time_ms"] > 0]["total_time_ms"]
    if v.empty:
        return ""
    out = [banner("PERCENTILES (total latency, ms)")]
    for p in (10, 25, 50, 75, 90, 95, 99):
        out.append(f"P{p:<3} {v.quantile(p / 100):>12.1f}")
    return "\n".join(out)


def executive_summary(df: pd.DataFrame) -> str:
    out = [banner("EXECUTIVE SUMMARY")]
    total = len(df)
    completed = int((df["status"] == "completed").sum())
    rate = 100.0 * completed / total if total else 0.0
    verdict = ("healthy" if rate >= 90 else
               "degraded" if rate >= 50 else "unhealthy")
    out.append(f"system completed {completed}/{total} tasks "
               f"({rate:.1f}%) -> {verdict}")
    if "total_time_ms" in df.columns:
        v = df[df["total_time_ms"] > 0]["total_time_ms"]
        if not v.empty and v.mean() > 0:
            cv = v.std() / v.mean()
            interp = ("consistent" if cv < 0.5 else
                      "moderately variable" if cv < 1.0 else "highly variable")
            out.append(f"latency avg {v.mean() / 1000:.1f}s, "
                       f"CV {cv:.2f} -> {interp} performance")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("csv")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--agents", action="store_true")
    ap.add_argument("--histogram", action="store_true")
    ap.add_argument("--percentiles", action="store_true")
    ap.add_argument("--save", default=None)
    args = ap.parse_args(argv)

    try:
        df = pd.read_csv(args.csv)
    except Exception as e:
        print(f"cannot read {args.csv}: {e}", file=sys.stderr)
        return 1

    sections = [basic_stats(df)]
    if args.all or args.agents:
        sections.append(per_agent(df))
    if args.all or args.histogram:
        sections.append(histogram(df))
    if args.all or args.percentiles:
        sections.append(percentiles(df))
    sections.append(executive_summary(df))
    report = "\n".join(s for s in sections if s)
    print(report)
    if args.save:
        with open(args.save, "w") as f:
            f.write(report + "\n")
        print(f"\nreport saved to {args.save}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
