"""Virtual-mesh step-time comparison: single device vs 8-way agent-sharded
vs 2x4 (agents x tiles) — VERDICT r2 item 4.

Real multi-chip hardware does not exist in this environment (one chip via
the axon tunnel), so the sharded step's OVERHEAD — collective next-hop
psum, sharded replan bookkeeping, halo-exchanged sweeps — is measured on
the same 8-device virtual CPU mesh the correctness tests use.  The box
has ONE physical core, so the 8 "devices" serialize: the ratio
sharded/single measures TOTAL WORK added by sharding (collectives +
bookkeeping), not parallel wall-clock — on real chips the sharded per-step
time would be roughly (single-device work / n_devices) + the overhead this
table isolates.  The config is sized for a 1-core box.

Usage: python analysis/sharded_steptime.py [--steps K]
Prints one aligned table; paste into SCALING.md.
"""

from __future__ import annotations

import argparse
import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.parallel.virtual_mesh import pin_cpu_backend  # noqa: E402

DEVICES = pin_cpu_backend(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from p2p_distributed_tswap_tpu.core.config import SolverConfig  # noqa: E402
from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.core.sampling import (  # noqa: E402
    start_positions_array)
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator  # noqa: E402
from p2p_distributed_tswap_tpu.parallel import (  # noqa: E402
    sharded, sharded2d)
from p2p_distributed_tswap_tpu.parallel.mesh import (  # noqa: E402
    TILES_AXIS, agent_mesh, agent_tile_mesh, shard_map)
from p2p_distributed_tswap_tpu.solver import mapd  # noqa: E402

WARMUP = 8


def _measure(step, s, tasks, free, steps):
    for _ in range(WARMUP):
        s = step(s, tasks, free)
    jax.block_until_ready(s)
    t0 = time.perf_counter()
    for _ in range(steps):
        s = step(s, tasks, free)
    jax.block_until_ready(s)
    return 1000.0 * (time.perf_counter() - t0) / steps, s


def build_problem():
    grid = Grid.random_obstacles(128, 128, 0.1, seed=0)
    n = 128
    cfg = SolverConfig(height=128, width=128, num_agents=n, replan_chunk=32)
    starts = start_positions_array(grid, n, seed=0)
    tasks = TaskGenerator(grid, seed=1).generate_task_arrays(n)
    return (grid, cfg, jnp.asarray(starts, jnp.int32),
            jnp.asarray(tasks, jnp.int32), jnp.asarray(grid.free))


def bench_single(cfg, starts, tasks, free, steps):
    step = jax.jit(functools.partial(mapd.mapd_step, cfg))
    s, tasks = jax.jit(functools.partial(mapd.prepare_state, cfg))(
        starts, tasks, free)
    return _measure(step, s, tasks, free, steps)


def _prep_replicated(cfg, starts, tasks):
    s, _ = mapd.prepare_state_unprimed(cfg, starts, tasks)
    return s


def bench_sharded(cfg, starts, tasks, free, steps):
    mesh = agent_mesh(devices=DEVICES)
    specs = sharded.agent_state_specs()
    sm = functools.partial(shard_map, mesh=mesh, check_vma=False)
    step = jax.jit(sm(functools.partial(sharded.sharded_mapd_step, cfg),
                      in_specs=(specs, P(), P()), out_specs=specs))
    prime = jax.jit(sm(functools.partial(sharded._sharded_prime, cfg),
                       in_specs=(specs, P()), out_specs=specs))
    s = _prep_replicated(cfg, starts, tasks)
    s = jax.device_put(s, jax.tree.map(
        lambda sp: jax.sharding.NamedSharding(mesh, sp), specs))
    s = prime(s, free)
    return _measure(step, s, tasks, free, steps)


def bench_sharded2d(cfg, starts, tasks, free, steps):
    mesh = agent_tile_mesh(2, 4, devices=DEVICES)
    specs = sharded2d.state_specs_2d()
    sm = functools.partial(shard_map, mesh=mesh, check_vma=False)
    step = jax.jit(sm(functools.partial(sharded2d.sharded2d_mapd_step, cfg),
                      in_specs=(specs, P(), P(TILES_AXIS, None)),
                      out_specs=specs))
    prime = jax.jit(sm(functools.partial(sharded2d._prime_2d, cfg),
                       in_specs=(specs, P(TILES_AXIS, None)),
                       out_specs=specs))
    s = _prep_replicated(cfg, starts, tasks)
    s = jax.device_put(s, jax.tree.map(
        lambda sp: jax.sharding.NamedSharding(mesh, sp), specs))
    s = prime(s, free)
    return _measure(step, s, tasks, free, steps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=25)
    args = ap.parse_args()
    grid, cfg, starts, tasks, free = build_problem()
    print(f"# config: {cfg.num_agents} agents, {grid.height}x{grid.width} "
          f"random-obstacle grid, {int(tasks.shape[0])} tasks, "
          f"replan_chunk_small={cfg.replan_chunk_small}, "
          f"{args.steps} measured steps on the 8-device virtual CPU mesh")
    rows = []
    ms1, s1 = bench_single(cfg, starts, tasks, free, args.steps)
    rows.append(("single-device", ms1, 1.0))
    ms8, s8 = bench_sharded(cfg, starts, tasks, free, args.steps)
    rows.append(("sharded 8 (agents)", ms8, ms8 / ms1))
    ms2d, s2d = bench_sharded2d(cfg, starts, tasks, free, args.steps)
    rows.append(("sharded 2x4 (agents x tiles)", ms2d, ms2d / ms1))
    # same trajectory on every variant (bit-identity spot check)
    import numpy as np
    assert np.array_equal(np.asarray(s1.pos), np.asarray(s8.pos)), \
        "sharded-8 diverged from single-device"
    assert np.array_equal(np.asarray(s1.pos), np.asarray(s2d.pos)), \
        "sharded-2x4 diverged from single-device"
    print(f"{'variant':<30} {'ms/step':>9} {'vs single':>10}")
    for name, ms, ratio in rows:
        print(f"{name:<30} {ms:>9.2f} {ratio:>9.2f}x")
    print("# positions bit-identical across all variants after "
          f"{WARMUP + args.steps} steps")


if __name__ == "__main__":
    main()
