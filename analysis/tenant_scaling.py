#!/usr/bin/env python
"""Tenants x agents throughput of ONE multi-tenant solverd (ISSUE 8).

``analysis/solver_crossover.py`` measures one fleet against one solverd;
this harness measures MANY fleets against one: N tenants — each a whole
namespaced fleet (C++ centralized manager with ``JG_BUS_NS=t<i>``
``--solver tpu`` on the packed wire + a wire-faithful SimAgentPool in
its namespace) — share one busd pool and ONE solverd whose
device-resident state batches every tenant's lanes into a single
[T, L] super-batch (runtime/solverd.py TenantSlab).

Per variant the harness reports, from the fleets' own ``mapd.metrics``
beacons (window-exact counter deltas, no harness instrumentation):

- per-tenant tasks/s + completion ratio
  (``manager.tasks_dispatched`` / ``manager.tasks_completed``);
- aggregate tasks/s across tenants — the "N fleets per chip" headline;
- solverd ms/tick-per-superbatch (its ``tick_ms`` histogram: one tick =
  one vmapped step answering every tenant that asked that burst) and
  ``solverd.superbatch_lanes``/``solverd.tenants``.

The committed artifact (``results/tenant_scaling_r10.json``) runs the
single-tenant BASELINE first, then the multi-tenant rung, and embeds
the acceptance checks: aggregate tasks/s >= 4x the single tenant's and
min per-tenant completion ratio >= the baseline's.

Usage:
  python analysis/tenant_scaling.py --tenants 8 --agents 6 \\
      --out results/tenant_scaling_r10.json
  python analysis/tenant_scaling.py --smoke      # the CI gate (fast)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.obs.registry import hist_quantile  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import busns  # noqa: E402
from p2p_distributed_tswap_tpu.runtime import buspool  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402,E501
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built, wait_for_log)
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501


def _counter(m, name):
    total = 0.0
    for key, v in (m.get("counters") or {}).items():
        if key == name or key.startswith(name + "{"):
            total += v
    return total


def _hist_delta(first, last, name):
    h0 = (first.get("hists") or {}).get(name)
    h1 = (last.get("hists") or {}).get(name)
    if h1 is None:
        return None
    if h0 is None:
        h0 = {"buckets": h1["buckets"], "counts": [0] * len(h1["counts"]),
              "sum": 0.0, "count": 0}
    counts = [b - a for a, b in zip(h0["counts"], h1["counts"])]
    return {"buckets": h1["buckets"], "counts": counts,
            "sum": h1["sum"] - h0["sum"], "count": h1["count"] - h0["count"]}


class TenantWatch:
    """Beacon windows per (tenant ns, proc) over one un-namespaced
    client: tenant managers beacon on ``<ns>:mapd.metrics`` (their
    namespaced wire), solverd on the raw ``mapd.metrics``."""

    def __init__(self, port: int, tenants):
        self.bus = BusClient(port=port, peer_id="tenantwatch")
        self.bus.subscribe("mapd.metrics")
        for ns in tenants:
            self.bus.subscribe(busns.wire_topic(ns, "mapd.metrics"),
                               raw=True)
        self.samples = {}  # (ns, proc) -> [(mono_t, metrics)]

    def pump(self, budget_s: float) -> None:
        end = time.monotonic() + budget_s
        while True:
            now = time.monotonic()
            if now >= end:
                return
            f = self.bus.recv(timeout=min(0.2, end - now))
            if not f or f.get("op") != "msg":
                continue
            d = f.get("data") or {}
            if d.get("type") != "metrics_beacon":
                continue
            ns, _ = busns.split_ns(f.get("topic") or "")
            self.samples.setdefault((ns, d.get("proc")), []).append(
                (time.monotonic(), d.get("metrics") or {}))

    def reset(self) -> None:
        self.samples.clear()

    def window(self, ns: str, proc: str):
        s = self.samples.get((ns, proc)) or []
        if len(s) < 2:
            return None
        return s[0], s[-1]

    def close(self) -> None:
        self.bus.close()


def run_variant(args, n_tenants: int) -> dict:
    """One measured rung: ``n_tenants`` namespaced fleets on one busd
    pool + ONE multi-tenant solverd."""
    tenants = [f"t{i}" for i in range(n_tenants)]
    port = buspool.free_port()
    procs, logs = [], []
    log_dir = Path(args.log_dir) / f"tenants{n_tenants}"
    log_dir.mkdir(parents=True, exist_ok=True)

    def spawn(name, cmd, stdin=None, env=None):
        log = open(log_dir / f"{name}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ, **(env or {})))
        procs.append(p)
        return p

    pool = watch = None
    pools = {}
    try:
        pool = buspool.BusPool(BUILD_DIR / "mapd_bus",
                               num_shards=args.shards, home_port=port,
                               spawn=spawn)
        os.environ.update(pool.env())
        time.sleep(0.3)
        sd = spawn("solverd",
                   [sys.executable, "-m",
                    "p2p_distributed_tswap_tpu.runtime.solverd",
                    "--port", str(port), "--map", args.map_file, "--cpu",
                    "--tenants", ",".join(tenants),
                    "--max-tenants", str(max(n_tenants, 1))])
        if not wait_for_log(log_dir / "solverd.log", "solverd up", 600,
                            proc=sd):
            raise RuntimeError("solverd never became ready")
        mgrs = {}
        for ns in tenants:
            mgrs[ns] = spawn(
                f"manager_{ns}",
                [str(BUILD_DIR / "mapd_manager_centralized"),
                 "--port", str(port), "--map", args.map_file,
                 "--solver", "tpu",
                 "--max-tracked-agents", str(args.agents + 8)],
                stdin=subprocess.PIPE, env={"JG_BUS_NS": ns})
        time.sleep(0.5)
        for i, ns in enumerate(tenants):
            pools[ns] = SimAgentPool(args.agents, args.side, port=port,
                                     seed=i + 1, peer_id=f"sim-{ns}",
                                     namespace=ns)
        watch = TenantWatch(port, tenants)

        def pump_all(budget_s: float) -> None:
            end = time.monotonic() + budget_s
            while time.monotonic() < end:
                for p in pools.values():
                    p.pump(0.05)
                watch.pump(0.02)

        for p in pools.values():
            p.heartbeat_all()
        pump_all(2.0)
        for m in mgrs.values():
            m.stdin.write(f"tasks {args.agents}\n".encode())
            m.stdin.flush()
        pump_all(args.settle)
        watch.reset()
        done0 = {ns: p.done_count for ns, p in pools.items()}
        t0 = time.monotonic()
        pump_all(args.window)
        wall = time.monotonic() - t0
        pump_all(2.5)  # one more beacon interval: final counters land

        per_tenant = {}
        for ns in tenants:
            win = watch.window(ns, "manager_centralized")
            row = {"sim_done_in_window": pools[ns].done_count - done0[ns],
                   "sim": pools[ns].stats()}
            if win is not None:
                (ft, first), (lt, last) = win
                span = max(lt - ft, 1e-9)
                disp = _counter(last, "manager.tasks_dispatched") \
                    - _counter(first, "manager.tasks_dispatched")
                done = _counter(last, "manager.tasks_completed") \
                    - _counter(first, "manager.tasks_completed")
                row.update({
                    "tasks_dispatched": int(disp),
                    "tasks_completed": int(done),
                    "tasks_per_s": round(done / span, 3),
                    "completion_ratio": round(min(1.0, done / disp), 4)
                    if disp > 0 else (1.0 if done > 0 else None),
                    "beacon_span_s": round(span, 1),
                })
            per_tenant[ns] = row
        rates = [r["tasks_per_s"] for r in per_tenant.values()
                 if r.get("tasks_per_s") is not None]
        ratios = [r["completion_ratio"] for r in per_tenant.values()
                  if r.get("completion_ratio") is not None]
        variant = {
            "tenants": n_tenants,
            "agents_per_tenant": args.agents,
            "total_agents": n_tenants * args.agents,
            "window_s": round(wall, 1),
            "per_tenant": per_tenant,
            "aggregate_tasks_per_s": round(sum(rates), 3) if rates else None,
            "min_tenant_tasks_per_s": round(min(rates), 3)
            if rates else None,
            "min_completion_ratio": round(min(ratios), 4)
            if ratios else None,
        }
        sd_win = watch.window("", "solverd")
        if sd_win is not None:
            (ft, first), (lt, last) = sd_win
            tick = _hist_delta(first, last, "tick_ms")
            sd = {"superbatch_ticks": int(tick["count"]) if tick else 0}
            if tick and tick["count"] > 0:
                sd["ms_per_superbatch_p50"] = round(
                    hist_quantile(tick, 0.5), 2)
                sd["ms_per_superbatch_p95"] = round(
                    hist_quantile(tick, 0.95), 2)
            g = (last.get("gauges") or {})
            for k in ("solverd.tenants", "solverd.superbatch_lanes",
                      "solverd.superbatch_tenants", "solverd.slab_lanes"):
                if k in g:
                    sd[k.split(".", 1)[1]] = g[k]
            for k in ("solverd.tenant_admissions",
                      "solverd.tenant_evictions",
                      "solverd.tenant_resyncs", "solverd.seq_gaps"):
                v = _counter(last, k)
                if v:
                    sd[k.split(".", 1)[1]] = int(v)
            variant["solverd"] = sd
        return variant
    finally:
        for p in pools.values():
            p.close()
        if watch is not None:
            watch.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        if pool is not None:
            pool.close()
        for log in logs:
            log.close()
        os.environ.pop(buspool.SHARD_PORTS_ENV, None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--agents", type=int, default=6,
                    help="agents per tenant (the 'most scenarios are "
                         "small' regime)")
    ap.add_argument("--side", type=int, default=32)
    ap.add_argument("--shards", type=int,
                    default=int(os.environ.get("JG_BUS_SHARDS", "1") or 1))
    ap.add_argument("--window", type=float, default=30.0)
    ap.add_argument("--settle", type=float, default=20.0)
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the single-tenant baseline variant")
    ap.add_argument("--out", default=None)
    ap.add_argument("--log-dir", default="/tmp/tenant_scaling_logs")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: 2 tenants, short windows; asserts "
                         "both tenants complete tasks on one solverd "
                         "with zero cross-tenant resyncs/evictions")
    args = ap.parse_args(argv)
    if args.smoke:
        args.tenants, args.agents, args.side = 2, 4, 24
        args.window, args.settle = 10.0, 8.0
        args.no_baseline = True
    ensure_built()
    args.map_file = f"/tmp/tenant_scaling_{args.side}.map.txt"
    Path(args.map_file).write_text(
        "\n".join(["." * args.side] * args.side) + "\n")

    variants = []
    if not args.no_baseline:
        print("tenant_scaling: single-tenant baseline", flush=True)
        variants.append(run_variant(args, 1))
        print(json.dumps(variants[-1]), flush=True)
    print(f"tenant_scaling: {args.tenants} tenants", flush=True)
    variants.append(run_variant(args, args.tenants))
    print(json.dumps(variants[-1]), flush=True)

    multi = variants[-1]
    base = variants[0] if len(variants) > 1 else None
    accept = {}
    if base is not None:
        base_rate = base.get("aggregate_tasks_per_s") or 0.0
        base_ratio = base.get("min_completion_ratio")
        agg = multi.get("aggregate_tasks_per_s") or 0.0
        accept = {
            "single_tenant_tasks_per_s": base_rate,
            "aggregate_tasks_per_s": agg,
            "speedup_vs_single": round(agg / base_rate, 2)
            if base_rate else None,
            "aggregate_ge_4x_single": bool(base_rate
                                           and agg >= 4.0 * base_rate),
            "single_tenant_completion_ratio": base_ratio,
            "min_tenant_completion_ratio": multi.get(
                "min_completion_ratio"),
            "per_tenant_completion_ge_baseline": bool(
                base_ratio is not None
                and multi.get("min_completion_ratio") is not None
                and multi["min_completion_ratio"] >= base_ratio),
        }
    doc = {
        "experiment": "tenants x agents throughput of one multi-tenant "
                      "solverd (namespaced fleets, shared device "
                      "super-batch)",
        "map": f"{args.side}x{args.side} empty",
        "solverd_backend": "cpu",
        "note": "each tenant = C++ centralized manager (JG_BUS_NS, "
                "--solver tpu, packed wire) + wire-faithful sim pool in "
                "its namespace; ONE solverd plans every tenant per tick "
                "via a [T,L] vmapped super-batch with a shared "
                "direction-field cache.",
        "variants": variants,
        "acceptance": accept,
    }
    print(json.dumps({"acceptance": accept}), flush=True)
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2) + "\n")
        md = ["# tenant_scaling — N fleets per chip", "",
              "| variant | tenants | agents | aggregate tasks/s | "
              "min tenant tasks/s | min completion | solverd ms/superbatch "
              "p50 |", "|---|---|---|---|---|---|---|"]
        for v in variants:
            sd = v.get("solverd") or {}
            md.append(
                f"| {'baseline' if v['tenants'] == 1 else 'multi'} "
                f"| {v['tenants']} | {v['total_agents']} "
                f"| {v.get('aggregate_tasks_per_s')} "
                f"| {v.get('min_tenant_tasks_per_s')} "
                f"| {v.get('min_completion_ratio')} "
                f"| {sd.get('ms_per_superbatch_p50')} |")
        if accept:
            md += ["",
                   f"- aggregate vs single-tenant: "
                   f"**{accept.get('speedup_vs_single')}x** "
                   f"(>=4x: {accept.get('aggregate_ge_4x_single')})",
                   f"- min per-tenant completion ratio "
                   f"{accept.get('min_tenant_completion_ratio')} vs "
                   f"baseline "
                   f"{accept.get('single_tenant_completion_ratio')} "
                   f"(>=: "
                   f"{accept.get('per_tenant_completion_ge_baseline')})"]
        out.with_name(out.name + ".md").write_text("\n".join(md) + "\n")
    if args.smoke:
        sd = multi.get("solverd") or {}
        ok = all((r.get("tasks_completed") or 0) >= 1
                 for r in multi["per_tenant"].values()) \
            and sd.get("tenants") == 2 \
            and not sd.get("tenant_evictions") \
            and not sd.get("seq_gaps")
        print(f"tenant smoke {'OK' if ok else 'FAILED'}: "
              f"{json.dumps(multi['per_tenant'])}", flush=True)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
