"""Native-vs-solverd planning-time crossover sweep (VERDICT r4 item 1.ii).

The reference's centralized manager plans in ~180 ms at 50 agents and is
pinned to a 500 ms tick by it (manager.rs:564-567).  Our native C++
``tswap_step`` demolishes that wall at small N (0.04 ms at the fleet
envelope) — but its occupant scan is O(N^2) (cpp/common/tswap.hpp:33-38),
so it must blow past the tick at fleet sizes the TPU path shrugs at.  This
sweep measures both sides at N ∈ {50, 500, 2000, 5000} on a 256² map:

- native: ``mapd_tswap_bench`` (steady state, fields pre-warmed and never
  trimmed — strictly flattering to the native path; the real manager trims
  its cache at 512 fields and would also pay BFS recomputes);
- solverd: a synthetic plan_request driver over the real bus against the
  real daemon (``--warm N --capacity-min N``, accelerator backend),
  measuring the manager-visible request->response round-trip.

Output: one JSON with both curves and the crossover agent count, plus a
markdown table for the README.

Usage:
  python analysis/crossover_sweep.py --out results/crossover_r05.json
  python analysis/crossover_sweep.py --counts 50,500 --cpu   # smoke test
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built)

SIDE = 256
TICK_MS = 500.0  # the reference's planning tick


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def native_ms(n: int, iters: int) -> dict:
    out = subprocess.run(
        [str(BUILD_DIR / "mapd_tswap_bench"), "--agents", str(n),
         "--side", str(SIDE), "--iters", str(iters)],
        capture_output=True, text=True, timeout=3600, check=True)
    return json.loads(out.stdout.strip())


def solverd_ms(n: int, rounds: int, warm_rounds: int, map_file: str,
               cpu: bool) -> dict:
    """Round-trip plan latency as the manager sees it: publish
    plan_request, wait for the matching plan_response."""
    import numpy as np

    port = _free_port()
    bus_p = subprocess.Popen([str(BUILD_DIR / "mapd_bus"), str(port)],
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    sd = None
    try:
        time.sleep(0.3)
        sd_cmd = [sys.executable, "-m",
                  "p2p_distributed_tswap_tpu.runtime.solverd",
                  "--port", str(port), "--map", map_file,
                  "--warm", str(n), "--capacity-min", str(n)]
        if cpu:
            sd_cmd.append("--cpu")
        sd = subprocess.Popen(sd_cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        lines = []
        import threading
        threading.Thread(target=lambda: [lines.append(l) for l in sd.stdout],
                         daemon=True).start()
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if any("solverd up" in l for l in lines):
                break
            if sd.poll() is not None:
                raise RuntimeError("solverd died:\n" + "".join(lines[-20:]))
            time.sleep(0.5)
        else:
            raise RuntimeError("solverd never became ready")
        warm_s = next((l for l in lines if "pre-warmed" in l), "").strip()
        warm_cut = len(lines)  # recompiles BEFORE this are the warm itself

        rng = np.random.default_rng(1)
        cells = rng.choice(SIDE * SIDE, size=2 * n, replace=False)
        agents = [{"peer_id": f"a{k}",
                   "pos": [int(cells[k]) % SIDE, int(cells[k]) // SIDE],
                   "goal": [int(cells[n + k]) % SIDE,
                            int(cells[n + k]) // SIDE]}
                  for k in range(n)]
        cli = BusClient(port=port, peer_id="sweepmgr")
        cli.subscribe("solver")
        time.sleep(0.3)

        def round_trip(seq: int):
            t0 = time.perf_counter()
            cli.publish("solver", {"type": "plan_request", "seq": seq,
                                   "agents": agents})
            end = time.monotonic() + 120
            while time.monotonic() < end:
                f = cli.recv(timeout=2.0)
                if (f and f.get("op") == "msg"
                        and (f.get("data") or {}).get("type")
                        == "plan_response"
                        and f["data"]["seq"] == seq):
                    return (1000.0 * (time.perf_counter() - t0),
                            f["data"].get("duration_micros", 0) / 1000.0)
            raise RuntimeError(f"no plan_response for seq {seq}")

        for k in range(warm_rounds):
            round_trip(k + 1)
        pairs = [round_trip(warm_rounds + k + 1) for k in range(rounds)]
        rtt = [p[0] for p in pairs]
        plan = [p[1] for p in pairs]  # daemon-side: parse + device step
        return {"agents": n,
                "ms_round_trip_avg": round(sum(rtt) / len(rtt), 3),
                "ms_round_trip_max": round(max(rtt), 3),
                "ms_daemon_plan_avg": round(sum(plan) / len(plan), 3),
                "warm_line": warm_s,
                "recompile_stalls_after_warm": sum(
                    1 for l in lines[warm_cut:] if "recompiled" in l)}
    finally:
        if sd is not None:
            sd.terminate()
        bus_p.terminate()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--counts", default="50,500,2000,5000,10000")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--rounds", type=int, default=15)
    ap.add_argument("--warm-rounds", type=int, default=3)
    ap.add_argument("--out", default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="solverd on CPU (smoke test; the artifact run "
                         "uses the accelerator)")
    args = ap.parse_args()
    ensure_built()
    subprocess.run(["ninja", "-C", str(BUILD_DIR), "mapd_tswap_bench"],
                   check=True, capture_output=True)

    map_file = str(Path("/tmp") / f"sweep_{SIDE}.map.txt")
    Path(map_file).write_text("\n".join(["." * SIDE] * SIDE) + "\n")

    counts = [int(c) for c in args.counts.split(",")]
    rows = []
    for n in counts:
        nat = native_ms(n, args.iters)
        sol = solverd_ms(n, args.rounds, args.warm_rounds, map_file,
                         args.cpu)
        row = {
            "agents": n,
            "native_ms_avg": nat["ms_per_step_avg"],
            "native_ms_max": nat["ms_per_step_max"],
            "native_over_tick": nat["ms_per_step_avg"] > TICK_MS,
            "solverd_ms_avg": sol["ms_round_trip_avg"],
            "solverd_ms_max": sol["ms_round_trip_max"],
            "solverd_daemon_plan_ms": sol["ms_daemon_plan_avg"],
            "solverd_over_tick": sol["ms_round_trip_avg"] > TICK_MS,
            "recompile_stalls_after_warm":
                sol["recompile_stalls_after_warm"],
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    crossover = next((r["agents"] for r in rows
                      if r["solverd_ms_avg"] < r["native_ms_avg"]), None)
    native_wall = next((r["agents"] for r in rows if r["native_over_tick"]),
                       None)
    # quadratic fit of the native curve (the occupant scan is O(N^2)):
    # projected N where native alone eats the whole 500 ms tick
    big = [r for r in rows if r["agents"] >= 1000]
    native_wall_projected = None
    if len(big) >= 2 and native_wall is None:
        import math
        c = (sum(r["native_ms_avg"] / r["agents"] ** 2 for r in big)
             / len(big))
        native_wall_projected = int(math.sqrt(TICK_MS / c))
    result = {
        "experiment": "native tswap_step vs solverd plan round-trip",
        "map": f"{SIDE}x{SIDE} empty",
        "tick_ms": TICK_MS,
        "backend": "cpu" if args.cpu else "accelerator",
        "note": ("solverd round-trips ride the axon tunnel in this "
                 "environment (~100-130 ms per synchronous dispatch+fetch "
                 "each way); a host-attached TPU pays ~1-2 ms. "
                 "solverd_daemon_plan_ms is the daemon-side figure "
                 "(request parse + one batched device step)."),
        "rows": rows,
        "crossover_agents": crossover,
        "native_blows_tick_at": native_wall,
        "native_blows_tick_at_projected": native_wall_projected,
    }
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(result, indent=2))
        md = ["| agents | native ms/plan | solverd ms/plan | within 500 ms tick |",
              "|---|---|---|---|"]
        for r in rows:
            who = ("both" if not r["native_over_tick"]
                   and not r["solverd_over_tick"] else
                   "solverd only" if r["native_over_tick"]
                   and not r["solverd_over_tick"] else
                   "native only" if not r["native_over_tick"] else "neither")
            md.append(f"| {r['agents']} | {r['native_ms_avg']:.2f} "
                      f"| {r['solverd_ms_avg']:.1f} | {who} |")
        Path(str(args.out) + ".md").write_text("\n".join(md) + "\n")


if __name__ == "__main__":
    main()
