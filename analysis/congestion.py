"""Centralized-vs-decentralized divergence experiment (VERDICT r3 item 2).

The reference's central experiment compares the two control modes and shows
decentralization CHANGING the outcome — per-step time AND solution paths
(/root/reference/compare_path_metrics.py:33-106, DECENTRALIZED_ISSUES.md:
27-49).  Round 3's bench rungs never reproduced that at TPU scale: at bench
densities the radius mask never fired and every ``-decent`` makespan equaled
its centralized twin.  This experiment runs the CONGESTED config (3k agents
on a 256^2 warehouse, ~6% density — dense enough that local visibility and
staleness bite) over >= 5 seeds in three modes:

- centralized            (global view, atomic)
- decentralized-r15      (fresh radius mask — round-3 semantics)
- decentralized-r15-stale (views refreshed every 2 steps, TTL 20,
                           one-step non-atomic swap commits — the
                           reference's actual decentralized reality)

and emits ms/step AND makespan per (mode, seed) plus per-seed makespan
ratios, as a markdown table (stdout) and a JSON artifact
(results/congestion_rNN.json) for SCALING.md / README.

Usage:  python analysis/congestion.py [--seeds 5] [--out results/...]
(~minutes on the real chip; per-mode compile is reused across seeds.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(scn, seed):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_distributed_tswap_tpu.solver import mapd

    grid, starts, tasks, cfg = scn.build(seed=seed)
    args = (cfg, jnp.asarray(starts, jnp.int32),
            jnp.asarray(tasks, jnp.int32), jnp.asarray(grid.free))
    final = mapd._run_mapd_jit(*args)   # compile (first seed) + warm
    jax.block_until_ready(final)
    t0 = time.perf_counter()
    final = mapd._run_mapd_jit(*args)
    jax.block_until_ready(final)
    elapsed = time.perf_counter() - t0
    steps = int(final.t)
    completed = bool(np.asarray(final.task_used).all()) \
        and steps <= cfg.max_timesteps
    return {"seed": seed, "ms_per_step": round(1000.0 * elapsed / steps, 4),
            "makespan": steps if completed else None,
            "completed": completed}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--out", default="results/congestion_r04.json")
    args = ap.parse_args()

    from p2p_distributed_tswap_tpu.models import scenarios

    modes = {
        "centralized": scenarios.CONGESTED,
        "decent-fresh": scenarios.CONGESTED_DECENT,
        "decent-stale": scenarios.CONGESTED_DECENT_STALE,
    }
    results = {name: [] for name in modes}
    for name, scn in modes.items():
        for seed in range(args.seeds):
            r = run_one(scn, seed)
            r["mode"] = scn.mode
            results[name].append(r)
            print(json.dumps({"rung": scn.name, **r}), flush=True)

    # per-seed ratios vs centralized
    rows = []
    for seed in range(args.seeds):
        c = results["centralized"][seed]
        f = results["decent-fresh"][seed]
        s = results["decent-stale"][seed]

        def ratio(x):
            if c["makespan"] and x["makespan"]:
                return round(x["makespan"] / c["makespan"], 3)
            return None

        rows.append({
            "seed": seed,
            "cent_ms": c["ms_per_step"], "cent_makespan": c["makespan"],
            "fresh_ms": f["ms_per_step"], "fresh_makespan": f["makespan"],
            "fresh_ratio": ratio(f),
            "stale_ms": s["ms_per_step"], "stale_makespan": s["makespan"],
            "stale_ratio": ratio(s),
        })

    artifact = {
        "experiment": "congested cent-vs-decent divergence",
        "config": {"agents": scenarios.CONGESTED.num_agents,
                   "grid": "256x256 warehouse",
                   "seeds": args.seeds,
                   "stale_mode": scenarios.CONGESTED_DECENT_STALE.mode},
        "rows": rows,
        "raw": results,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=2)

    print("\n| seed | cent ms/step | cent makespan | fresh ms/step | "
          "fresh makespan (ratio) | stale ms/step | stale makespan (ratio) |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['seed']} | {r['cent_ms']} | {r['cent_makespan']} "
              f"| {r['fresh_ms']} | {r['fresh_makespan']} "
              f"({r['fresh_ratio']}) | {r['stale_ms']} "
              f"| {r['stale_makespan']} ({r['stale_ratio']}) |")
    print(f"\nartifact: {args.out}")


if __name__ == "__main__":
    main()
