"""EXTREME-shaped rehearsal on the virtual 8-device mesh (VERDICT r3 weak
#3: the 2-D sharded solver had only ever solved toy configs, so the EXTREME
memory plan rested on extrapolation).

Runs the 2-D (agents x tiles) sharded solver at EXTREME's *shape* scaled by
memory, not structure — thousands of agents, warehouse bands, EXTREME's
per-device replan chunk — TO COMPLETION with a device-side invariant fold
riding every step, and records per-device field residency (the arithmetic
the 840 GB EXTREME plan rests on) next to the measured run.

The host has ONE physical core, so the 8 virtual devices serialize:
ms/step here measures TOTAL WORK, not parallel wall-clock (same caveat as
analysis/sharded_steptime.py).  The point is capability + residency, not
speed.

Checkpoint/resume (VERDICT r4 item 5: the round-4 1024²-band attempt was
abandoned after 85 min with the rescue tool sitting unused in the repo):
``--checkpoint PATH`` saves the full sharded MapdState (solver/
checkpoint.py) plus a sidecar of loop latches every ``--checkpoint-every``
steps and at ``--max-seconds`` session end; ``--resume`` restores it —
skipping the multi-thousand-second prime burst, because the direction
fields ride the checkpoint — and continues bit-identically (the solver is
deterministic; tests/test_checkpoint.py).  A multi-hour band solve thus
runs as bounded sessions that survive kills, with wall-clock accumulated
across sessions in the sidecar.

Usage:
  python analysis/extreme_rehearsal.py --probe 8        # feasibility: time 8 steps
  python analysis/extreme_rehearsal.py                  # full certified run
  python analysis/extreme_rehearsal.py --out MULTICHIP_REHEARSAL_r04.json
  python analysis/extreme_rehearsal.py --checkpoint ck.npz --max-seconds 3600
  python analysis/extreme_rehearsal.py --checkpoint ck.npz --resume  # next session
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import os  # noqa: E402,F811

# The 8 virtual devices serialize on this host's ONE core, so a device
# thread can reach a collective minutes after its peers.  XLA CPU's
# rendezvous aborts the process after 40 s by default (rendezvous.cc
# "Termination timeout ... Exiting to ensure a consistent program
# state" — crashed the first full run); raise the limits far above the
# serialized skew.  Must be set before the CPU client exists.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_cpu_collective_timeout_seconds=7200"
    + " --xla_cpu_collective_call_terminate_timeout_seconds=7200").strip()

from p2p_distributed_tswap_tpu.parallel.virtual_mesh import pin_cpu_backend  # noqa: E402

pin_cpu_backend(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from p2p_distributed_tswap_tpu.core.config import SolverConfig  # noqa: E402
from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.core.sampling import start_positions_array  # noqa: E402
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator  # noqa: E402
from p2p_distributed_tswap_tpu.ops.distance import packed_cells  # noqa: E402
from p2p_distributed_tswap_tpu.parallel import sharded2d  # noqa: E402
from p2p_distributed_tswap_tpu.parallel.mesh import (  # noqa: E402
    AGENTS_AXIS,
    TILES_AXIS,
    agent_tile_mesh,
    shard_map,
)
from p2p_distributed_tswap_tpu.solver import invariants, mapd  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=2048)
    ap.add_argument("--tasks", type=int, default=2048)
    ap.add_argument("--side", type=int, default=1024,
                    help="warehouse side (EXTREME is 4096)")
    ap.add_argument("--a-shards", type=int, default=2)
    ap.add_argument("--tiles", type=int, default=4)
    ap.add_argument("--replan-chunk", type=int, default=64,
                    help="EXTREME's 512 / 8 devices")
    ap.add_argument("--horizon", type=int, default=6000)
    ap.add_argument("--probe", type=int, default=0,
                    help="time N steps and exit (feasibility probe)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--checkpoint", default=None,
                    help="save resumable state here periodically")
    ap.add_argument("--checkpoint-every", type=int, default=256,
                    help="steps between checkpoint saves")
    ap.add_argument("--resume", action="store_true",
                    help="resume from --checkpoint if it exists")
    ap.add_argument("--max-seconds", type=float, default=0,
                    help="end this session (with a checkpoint) after N s")
    args = ap.parse_args()

    grid = Grid.warehouse(args.side, args.side)
    n = args.agents
    cfg = SolverConfig(height=args.side, width=args.side, num_agents=n,
                       max_timesteps=args.horizon, record_paths=False,
                       replan_chunk=args.replan_chunk)
    starts = start_positions_array(grid, n, seed=0)
    tasks = TaskGenerator(grid, seed=1).generate_task_arrays(args.tasks)
    mesh = agent_tile_mesh(args.a_shards, args.tiles)
    specs = sharded2d.state_specs_2d()

    # per-device residency arithmetic (what EXTREME's 840 GB plan scales up)
    rows_dev = n // args.a_shards
    band_words = packed_cells(cfg.num_cells) // args.tiles
    dirs_dev_mb = rows_dev * band_words * 4 / 2**20
    sweep_dev_mb = (args.replan_chunk * (args.side // args.tiles)
                    * args.side * 4) / 2**20

    step_shard = shard_map(
        functools.partial(sharded2d.sharded2d_mapd_step, cfg),
        mesh=mesh, in_specs=(specs, P(), P(TILES_AXIS, None)),
        out_specs=specs, check_vma=False)
    prime = jax.jit(shard_map(
        functools.partial(sharded2d._prime_2d, cfg),
        mesh=mesh, in_specs=(specs, P(TILES_AXIS, None)), out_specs=specs,
        check_vma=False))

    # ONE program per loop iteration: step + invariant fold + makespan
    # latch + finished flag fused into a single jitted dispatch.  Separate
    # jitted programs over sharded operands interleave their collectives
    # across the serialized device threads in inconsistent order and
    # DEADLOCK the CPU rendezvous (observed live: worker CPU time frozen
    # mid-run); inside one program XLA orders every collective.
    @jax.jit
    def fused_iter(s, tasks, free, ok, done_t):
        prev = s.pos
        s = step_shard(s, tasks, free)
        ok = ok & invariants.step_invariants(cfg, prev, s.pos, free)
        done_t = jnp.where((done_t < 0) & mapd._finished(cfg, s),
                           s.t, done_t)
        return s, ok, done_t, mapd._finished(cfg, s)

    from p2p_distributed_tswap_tpu.solver.checkpoint import (
        load_extra, load_state, save_state)

    tasks_j = jnp.asarray(tasks, jnp.int32)
    to_mesh = functools.partial(
        jax.device_put,
        device=jax.tree.map(lambda sp: NamedSharding(mesh, sp), specs))
    free_j = jax.device_put(jnp.asarray(grid.free),
                            NamedSharding(mesh, P(TILES_AXIS, None)))

    print(f"# config: {n} agents, {args.side}^2 warehouse, mesh "
          f"{args.a_shards}x{args.tiles}, replan_chunk {args.replan_chunk}",
          flush=True)
    print(f"# per-device: {rows_dev} field rows x {args.side//args.tiles}-row "
          f"band = {dirs_dev_mb:.0f} MB packed dirs, "
          f"{sweep_dev_mb:.0f} MB sweep transient", flush=True)

    steps = 0
    prime_s = 0.0     # one-time field prime (paid once, rides checkpoints)
    prior_s = 0.0     # loop wall-clock banked by previous sessions
    sessions = 1
    ok = jnp.bool_(True)
    done_t = jnp.int32(-1)
    if args.resume and args.checkpoint and os.path.exists(args.checkpoint):
        # state AND loop latches live in the same npz (save_state extra=),
        # atomically replaced as one file — a sidecar could tear from the
        # state on a mid-save kill
        s = to_mesh(load_state(args.checkpoint, cfg,
                               expected_num_tasks=len(tasks)))
        meta = load_extra(args.checkpoint)
        steps = int(meta["steps"])
        ok = jnp.bool_(bool(meta["invariants_ok"]))
        done_t = jnp.int32(int(meta["done_t"]))
        prime_s = float(meta["prime_s"])
        prior_s = float(meta["loop_s"])
        sessions = int(meta["sessions"]) + 1
        print(f"# resumed session {sessions} at t={steps} "
              f"({prior_s:.0f}s loop banked; prime burst skipped — the "
              f"fields ride the checkpoint)", flush=True)
    else:
        s = mapd.init_state(cfg, jnp.asarray(starts, jnp.int32), len(tasks))
        s = mapd._transitions(cfg, s, tasks_j)
        s = mapd._assign(cfg, s, tasks_j)
        s = to_mesh(s)
        t0 = time.perf_counter()
        s = prime(s, free_j)
        int(s.t)
        prime_s = time.perf_counter() - t0
        print(f"# prime burst: {prime_s:.1f}s", flush=True)

    def save_ckpt(elapsed_now):
        # .npz suffix so np.savez doesn't append one behind our back
        tmp = args.checkpoint + ".tmp.npz"
        save_state(tmp, s, extra={
            "steps": steps, "done_t": int(done_t),
            "invariants_ok": bool(ok), "sessions": sessions,
            "prime_s": prime_s, "loop_s": prior_s + elapsed_now})
        os.replace(tmp, args.checkpoint)

    t0 = time.perf_counter()
    if args.probe:
        for _ in range(args.probe):
            s, ok, done_t, _ = fused_iter(s, tasks_j, free_j, ok, done_t)
            steps += 1
        int(s.t)
        ms = 1000.0 * (time.perf_counter() - t0) / steps
        print(f"# probe: {ms:.0f} ms/step (1-core serialized), "
              f"invariants_ok={bool(ok)}")
        return

    FETCH_EVERY = 32
    session_steps = 0
    last_saved = steps
    finished = False
    while not finished and steps < cfg.max_timesteps + FETCH_EVERY:
        for _ in range(FETCH_EVERY):
            s, ok, done_t, fin = fused_iter(s, tasks_j, free_j, ok, done_t)
            steps += 1
            session_steps += 1
        finished = bool(fin)
        elapsed = time.perf_counter() - t0
        if steps % 512 == 0:
            print(f"# t={steps} elapsed={elapsed:.0f}s (session "
                  f"{sessions})", flush=True)
        # steps only lands on multiples of FETCH_EVERY, so compare against
        # the last save instead of a modulo that could never fire
        if args.checkpoint and steps - last_saved >= args.checkpoint_every:
            save_ckpt(elapsed)
            last_saved = steps
        if args.max_seconds and elapsed > args.max_seconds and not finished:
            save_ckpt(elapsed)
            print(json.dumps({
                "session": sessions, "paused_at_step": steps,
                "session_steps": session_steps,
                "session_s": round(elapsed, 1),
                "total_s": round(prime_s + prior_s + elapsed, 1),
                "resume": f"--checkpoint {args.checkpoint} --resume",
            }), flush=True)
            return
    elapsed = time.perf_counter() - t0
    if args.checkpoint:
        save_ckpt(elapsed)
    makespan = int(done_t)
    completed = bool(np.asarray(s.task_used).all()) and 0 < makespan
    result = {
        "experiment": "EXTREME-shaped 2-D mesh rehearsal (virtual 8-dev CPU)",
        "agents": n, "grid": f"{args.side}x{args.side} warehouse",
        "tasks": args.tasks,
        "mesh": f"{args.a_shards}x{args.tiles}",
        "replan_chunk": args.replan_chunk,
        "per_device_dirs_mb": round(dirs_dev_mb, 1),
        "per_device_sweep_mb": round(sweep_dev_mb, 1),
        "ms_per_step_serialized": round(
            1000.0 * (prior_s + elapsed) / max(steps, 1), 1),
        "makespan": makespan if completed else None,
        "completed": completed,
        "invariants_ok": bool(ok),
        "steps_run": steps,
        "prime_s": round(prime_s, 1),
        "wallclock_s": round(prime_s + prior_s + elapsed, 1),
        "sessions": sessions,
    }
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=2)


if __name__ == "__main__":
    main()
