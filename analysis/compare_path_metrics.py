#!/usr/bin/env python3
"""Compare centralized vs decentralized planning cost per step (capability of
the reference's compare_path_metrics.py).

Centralized: one sample = one whole planning call for all agents, so the
per-step cost is the sample mean.  Decentralized: each agent reports its own
decision time, so samples are grouped into 100 ms wall-clock buckets
(timestamp_ms column) and one logical step costs the *max* over the parallel
agents in the bucket.

Usage: python analysis/compare_path_metrics.py centralized.csv decentralized.csv
"""

from __future__ import annotations

import argparse
import sys

import pandas as pd


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("centralized_csv")
    ap.add_argument("decentralized_csv")
    args = ap.parse_args(argv)

    try:
        cent = pd.read_csv(args.centralized_csv)
        dec = pd.read_csv(args.decentralized_csv)
    except Exception as e:
        print(f"cannot read inputs: {e}", file=sys.stderr)
        return 1

    print("=" * 64)
    print("PATH COMPUTATION: centralized vs decentralized")
    print("=" * 64)

    c = cent["duration_micros"]
    print(f"\nCentralized ({len(c)} planning calls):")
    print(f"  mean {c.mean() / 1000:.3f} ms   median {c.median() / 1000:.3f} ms"
          f"   max {c.max() / 1000:.3f} ms")
    cent_step = c.mean()

    d = dec["duration_micros"]
    print(f"\nDecentralized ({len(d)} per-agent decisions):")
    print(f"  mean {d.mean() / 1000:.3f} ms   median {d.median() / 1000:.3f} ms"
          f"   max {d.max() / 1000:.3f} ms")
    if "timestamp_ms" in dec.columns and dec["timestamp_ms"].notna().any():
        grouped = dec.dropna(subset=["timestamp_ms"]).copy()
        grouped["bucket"] = (grouped["timestamp_ms"] // 100) * 100
        per_step_max = grouped.groupby("bucket")["duration_micros"].max()
        per_step_mean = grouped.groupby("bucket")["duration_micros"].mean()
        print(f"  per-step (100 ms buckets, {len(per_step_max)} steps): "
              f"max-mean {per_step_max.mean() / 1000:.3f} ms, "
              f"mean-mean {per_step_mean.mean() / 1000:.3f} ms")
        dec_step = per_step_max.mean()
    else:
        dec_step = d.mean()

    print("\n" + "-" * 64)
    if cent_step > 0:
        ratio = dec_step / cent_step
        print(f"one decentralized step costs {ratio:.4f}x "
              f"one centralized step")
        if ratio < 1:
            print(f"-> decentralized per-step compute is "
                  f"{1 / ratio:.1f}x cheaper (it parallelizes across agents)")
        else:
            print("-> centralized per-step compute is cheaper at this scale")
    return 0


if __name__ == "__main__":
    sys.exit(main())
