"""HBM roofline for the flagship fused step (VERDICT r3 weak #4).

The round-3 flagship number (21.9 ms/step, BENCH_r03) was fast but
unanchored: nothing said how far from the hardware bound it sits.  The
post-Pallas step is replan-dominated and the Pallas sweep is ~1 memory pass
per directional sweep, so a bytes-touched-per-step / HBM-bandwidth roofline
is computable from first principles plus two measured quantities:

1. **Fixpoint rounds per replanned field** — measured here by running the
   sweep round host-side to convergence on real flagship task fields (the
   warehouse shelf maze sets the count; an empty grid would converge in 1).
2. **Dirty rows per step** — steady-state replan traffic.  Goal SWAPS are
   slot permutations and dirty nothing; only task-lifecycle goal changes
   (assignment, pickup->delivery flip) recompute fields, so total in-loop
   dirties across a solve are 2T - N (T tasks assigned + T flips, minus the
   N first assignments folded into the t=0 prime), spread over the
   makespan.  Cross-checked against the same arithmetic at the medium rung.

Byte model per fixpoint round over a (R, H, W) int32 batch (R =
replan_chunk_small): 4 directional sweeps, each reading and writing the
batch once (the Pallas kernel's whole point) plus the shared (H, W) mask;
one convergence check reading old+new.  Extraction adds ~3 passes
(direction compare + nibble pack) and the dirs scatter writes R packed
rows.  v5e HBM bandwidth: 819 GB/s (public v5e spec).

Usage: python analysis/roofline.py [--chunks 8]
Prints the roofline table for SCALING.md and a go/no-go on the
multi-field-per-program Pallas variant (ops/field_fused.py's named next
lever).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.models import scenarios
from p2p_distributed_tswap_tpu.ops import distance

V5E_HBM_GBPS = 819.0  # public TPU v5e spec
FLAGSHIP_MS = 21.87   # BENCH_r03 shipped fused-solve number


def measure_fixpoint_rounds(grid, goals, max_rounds=256):
    """Host-driven replica of distance_fields' while_loop, counting rounds
    to convergence for one (R, H, W) seed batch."""
    h, w = grid.height, grid.width
    free = jnp.asarray(grid.free)
    g = goals.shape[0]
    cell = jnp.arange(h * w, dtype=jnp.int32).reshape(1, h, w)
    d = jnp.where(cell == goals.reshape(g, 1, 1), jnp.int32(0), distance.INF)
    d = jnp.where(free[None], d, distance.INF)
    xcoord = jnp.arange(w, dtype=jnp.int32).reshape(1, 1, w)
    ycoord = jnp.arange(h, dtype=jnp.int32).reshape(1, h, 1)

    @jax.jit
    def one_round(d):
        d = distance._sweep(d, free, axis=2, reverse=False, coord=xcoord)
        d = distance._sweep(d, free, axis=2, reverse=True, coord=-xcoord)
        d = distance._sweep(d, free, axis=1, reverse=False, coord=ycoord)
        d = distance._sweep(d, free, axis=1, reverse=True, coord=-ycoord)
        return d

    rounds = 0
    t0 = time.perf_counter()
    while rounds < max_rounds:
        nd = one_round(d)
        rounds += 1
        if not bool(jnp.any(nd != d)):
            break
        d = nd
    elapsed = time.perf_counter() - t0
    return rounds, elapsed


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunks", type=int, default=8,
                    help="how many 4-goal chunks to sample for round counts")
    args = ap.parse_args()

    scn = scenarios.FLAGSHIP
    grid, starts, tasks, cfg = scn.build(seed=0)
    rng = np.random.default_rng(0)
    r = cfg.replan_chunk_small
    counts = []
    for i in range(args.chunks):
        sel = rng.choice(len(tasks), size=r, replace=False)
        goals = jnp.asarray(tasks[sel, 1], jnp.int32)
        rounds, secs = measure_fixpoint_rounds(grid, goals)
        counts.append(rounds)
        print(f"# chunk {i}: {rounds} fixpoint rounds ({secs:.2f}s incl. "
              "host loop)", flush=True)
    rounds_mean = float(np.mean(counts))

    # steady-state dirty rows per step: 2T - N task-lifecycle goal changes
    # over the certified makespan (BENCH_r03: 1388)
    T, N = len(tasks), cfg.num_agents
    makespan = 1388
    dirty_per_step = (2 * T - N) / makespan
    loops_per_step = dirty_per_step / r  # while_loop iterations (chunk = r)

    hw_bytes = cfg.num_cells * 4
    batch = r * hw_bytes                        # (R, H, W) int32
    per_round = 4 * (2 * batch + hw_bytes) + 2 * batch   # sweeps + converge
    extract = 3 * batch + r * cfg.num_cells // 2         # dirs + pack
    per_loop = rounds_mean * per_round + extract
    replan_bytes = loops_per_step * per_loop
    # TSWAP kernel traffic: occupancy scatter/gathers, a few (HW,) passes
    kernel_bytes = 6 * hw_bytes
    total = replan_bytes + kernel_bytes
    ideal_ms = total / (V5E_HBM_GBPS * 1e9) * 1000.0
    pct = 100.0 * ideal_ms / FLAGSHIP_MS

    print()
    print("| quantity | value |")
    print("|---|---|")
    print(f"| fixpoint rounds per flagship field (measured, {args.chunks} "
          f"chunks) | {rounds_mean:.1f} |")
    print(f"| dirty field rows per step ((2T-N)/makespan) | "
          f"{dirty_per_step:.1f} |")
    print(f"| replan while_loop iterations per step | {loops_per_step:.2f} |")
    print(f"| bytes touched per step (replan {replan_bytes/1e9:.2f} GB + "
          f"kernel {kernel_bytes/1e9:.2f} GB) | {total/1e9:.2f} GB |")
    print(f"| ideal ms/step at {V5E_HBM_GBPS:.0f} GB/s | {ideal_ms:.1f} |")
    print(f"| shipped ms/step (BENCH_r03) | {FLAGSHIP_MS} |")
    print(f"| bandwidth-bound fraction | {pct:.0f}% |")


if __name__ == "__main__":
    main()
