#!/usr/bin/env python
"""Print the fleet's interleaved last seconds from flight-recorder dumps.

Every process keeps an always-on ring of its newest structured events
(obs/flightrec.py ≡ cpp/common/flightrec.hpp) and dumps it to
``<proc>-<pid>.flight.jsonl`` on crash, exit, SIGUSR2, or a bus
``flight_dump`` request.  This tool merges every dump in a directory into
one wall-clock-ordered view of the moments before an incident — the
aviation black-box readout for a fleet outage (ISSUE 5).

``--audit`` (ISSUE 10) additionally merges the auditor's confirmed
divergence records (``*.audit.jsonl``, written by the standalone auditor
/ scripts/audit_smoke.py ``--record``) into the same timeline as
``audit.divergence`` events — a post-mortem then shows *when* the
distributed state forked relative to the last seconds of lifecycle
events, not just that it did.

``--alerts`` (ISSUE 16) merges healthd's alert records
(``healthd.alerts.jsonl``, written by ``obs/health.py --record``) into
the same timeline as 🔴 ``health.alert`` events — burning SLO,
severity, forecast lead, attribution, and the auto-captured replay
artifact, in wall-clock order against the fleet's last seconds.

``--capture OUT`` (ISSUE 11) rebuilds a replayable ``capture1``
artifact from the same flight rings: the sim pool's ``capture.meta`` /
``task.spec`` / ``world.update`` evidence events become the fleet
config, the task list with arrival offsets, and the world-toggle
timeline — so a crash's last window re-drives on demand via
``analysis/fleetsim.py --replay OUT``.

Usage:
  python analysis/blackbox.py --dir <fleet log dir> [--last 30] [--json]
  python analysis/blackbox.py --dir results/trace --grep task.dispatch
  python analysis/blackbox.py --dir <fleet log dir> --audit
  python analysis/blackbox.py --dir <fleet log dir> --alerts
  python analysis/blackbox.py --dir <fleet log dir> --capture out.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def load_dumps(directory: Path) -> tuple:
    """(meta-records, merged time-ordered events)."""
    metas, events = [], []
    for path in sorted(directory.glob("*.flight.jsonl")):
        for line in path.read_text(errors="ignore").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("meta") == "flight":
                rec["file"] = path.name
                metas.append(rec)
            elif "ts_ms" in rec:
                events.append(rec)
    events.sort(key=lambda e: e.get("ts_ms", 0))
    return metas, events


def load_audit(directory: Path) -> list:
    """Auditor divergence records (``*.audit.jsonl``) as flight-style
    events: ``audit.divergence`` with the class/peers/watermarks in the
    detail fields, time-ordered."""
    out = []
    for path in sorted(directory.glob("*.audit.jsonl")):
        for line in path.read_text(errors="ignore").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "ts_ms" not in rec:
                continue
            ev = {
                "ts_ms": rec["ts_ms"],
                "proc": "auditor",
                "pid": path.stem.split(".")[0],
                "event": "audit.divergence",
                "class": rec.get("class"),
                "peer": (f"{rec.get('peer_a')}"
                         + (f"~{rec.get('peer_b')}" if rec.get("peer_b")
                            else "")),
                "seq": rec.get("seq"),
                "epoch": rec.get("epoch"),
                "error": rec.get("detail"),
            }
            if rec.get("capture"):
                # the auto-dumped replayable capture (ISSUE 11): the
                # post-mortem names the file that reproduces the window
                ev["capture"] = rec["capture"]
            out.append(ev)
    return out


def load_alerts(directory: Path) -> list:
    """healthd alert records (``*.alerts.jsonl``, ISSUE 16) as
    flight-style events: ``health.alert`` carrying the burning SLO,
    severity, forecast lead, attribution, and — for page-severity
    breaches — the auto-captured replay artifact, time-ordered."""
    out = []
    for path in sorted(directory.glob("*.alerts.jsonl")):
        for line in path.read_text(errors="ignore").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "ts_ms" not in rec:
                continue
            att = rec.get("attribution") or {}
            reco = rec.get("recommendation") or {}
            fc = rec.get("forecast") or {}
            ev = {
                "ts_ms": rec["ts_ms"],
                "proc": "healthd",
                "pid": path.stem.split(".")[0],
                "event": "health.alert",
                "class": (f"{rec.get('severity')}."
                          f"{rec.get('kind')}.{rec.get('state')}"),
                "seq": rec.get("seq"),
                "error": (f"[{rec.get('name')}] {rec.get('signal')}"
                          f"={rec.get('observed')}"),
            }
            if fc.get("eta_s") is not None:
                ev["error"] += f" eta={fc['eta_s']}s"
            if att:
                ev["peer"] = f"{att.get('kind')}:{att.get('id')}"
            if reco:
                ev["error"] += (f" -> {reco.get('actuator')}"
                                f"({reco.get('target')})")
            if rec.get("capture"):
                ev["capture"] = rec["capture"]
            out.append(ev)
    return out


def render_event(ev: dict, t_end_ms: int) -> str:
    rel = (ev.get("ts_ms", 0) - t_end_ms) / 1000.0
    who = f"{ev.get('proc', '?')}/{ev.get('pid', '?')}"
    detail = " ".join(
        f"{k}={ev[k]}" for k in ("task_id", "trace_id", "hop", "peer",
                                 "wire_ms", "seq", "epoch", "class",
                                 "error", "capture")
        if k in ev)
    mark = ("🔴 " if ev.get("event") in ("audit.divergence",
                                         "health.alert") else "  ")
    return (f"{mark}{rel:+9.3f}s  {who:<28} "
            f"{ev.get('event', '?'):<22} {detail}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="results/trace",
                    help="directory holding *.flight.jsonl dumps "
                         "(JG_FLIGHT_DIR / a fleet log dir)")
    ap.add_argument("--last", type=float, default=30.0,
                    help="window before the newest event, seconds")
    ap.add_argument("--grep", default="",
                    help="substring filter on the event name")
    ap.add_argument("--audit", action="store_true",
                    help="merge auditor divergence records "
                         "(*.audit.jsonl) into the timeline (ISSUE 10)")
    ap.add_argument("--alerts", action="store_true",
                    help="merge healthd alert records (*.alerts.jsonl) "
                         "into the timeline (ISSUE 16)")
    ap.add_argument("--capture", default=None, metavar="OUT",
                    help="rebuild a replayable capture1 artifact from "
                         "the flight rings' evidence events (ISSUE 11) "
                         "and write it to OUT")
    ap.add_argument("--capture-agents", type=int, default=None,
                    help="fleet-config override when the rings' "
                         "capture.meta rotated out")
    ap.add_argument("--capture-side", type=int, default=None)
    ap.add_argument("--capture-seed", type=int, default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    directory = Path(args.dir)
    if args.capture:
        from p2p_distributed_tswap_tpu.obs import capture as _capture

        overrides = {k: v for k, v in
                     (("agents", args.capture_agents),
                      ("side", args.capture_side),
                      ("seed", args.capture_seed)) if v is not None}
        try:
            doc = _capture.from_flight_dir(directory,
                                           fleet_overrides=overrides)
        except _capture.CaptureError as e:
            print(f"blackbox: cannot assemble a capture from "
                  f"{directory}: {e}", file=sys.stderr)
            return 1
        path = _capture.save(args.capture, doc)
        print(f"capture1 written to {path}: {len(doc['tasks'])} task(s), "
              f"{len(doc['world'])} world event(s), fleet "
              f"{doc['fleet']['agents']} agents on "
              f"{doc['fleet']['side']}x{doc['fleet']['side']} — replay "
              f"with: python analysis/fleetsim.py --replay {path}")
        return 0
    metas, events = load_dumps(directory)
    audit_events = load_audit(directory) if args.audit else []
    alert_events = load_alerts(directory) if args.alerts else []
    if audit_events or alert_events:
        events = sorted(events + audit_events + alert_events,
                        key=lambda e: e.get("ts_ms", 0))
    if args.grep:
        events = [e for e in events if args.grep in str(e.get("event", ""))]
    t_end = max((e.get("ts_ms", 0) for e in events), default=0)
    window = [e for e in events
              if e.get("ts_ms", 0) >= t_end - args.last * 1000.0]
    if args.as_json:
        print(json.dumps({"dir": str(directory), "dumps": metas,
                          "t_end_ms": t_end, "window_s": args.last,
                          "audit_divergences": len(audit_events),
                          "health_alerts": len(alert_events),
                          "events": window}))
        return 0 if metas or audit_events or alert_events else 1
    if not metas and not audit_events and not alert_events:
        print(f"no *.flight.jsonl dumps in {directory} — trigger one with "
              f"SIGUSR2, a bus flight_dump message, or a process exit")
        return 1
    print(f"black box: {len(metas)} ring dump(s) in {directory}"
          + (f", {len(audit_events)} audit divergence(s)"
             if args.audit else "")
          + (f", {len(alert_events)} health alert(s)"
             if args.alerts else ""))
    for m in metas:
        print(f"  {m['file']}: {m.get('proc')}/{m.get('pid')} "
              f"reason={m.get('reason')} events={m.get('events')}")
    print(f"last {args.last:g}s before t_end "
          f"({len(window)}/{len(events)} events):")
    for ev in window:
        print(render_event(ev, t_end))
    return 0


if __name__ == "__main__":
    sys.exit(main())
