"""Live-fleet native-vs-tpu planning crossover (ISSUE 3 acceptance).

analysis/crossover_sweep.py measured the two planners in ISOLATION
(tswap_bench vs a synthetic request driver); this harness measures them in
a LIVE fleet: busd + the real centralized manager + (for tpu) the real
solverd, with N simulated agents closing the control loop over the bus —
they adopt tasks, follow move_instructions, publish position updates and
dones, so the manager plans a genuinely churning fleet every 500 ms tick.

Per agent-count rung the harness runs up to three variants:

- ``native``  --solver=cpu: the manager's sequential TSWAP + BFS cache.
  End-to-end ms/tick = the manager's own ``tick_ms`` histogram (plan +
  emit + adopt, from its live-metrics beacon).
- ``packed``  --solver=tpu on the packed delta wire (the fast path).
  End-to-end ms/tick = ``manager.plan_rtt_ms`` (request publish -> fresh
  response applied) — everything the fleet pays beyond the native path.
- ``json``    --solver=tpu on the legacy JSON wire, for the wire-bytes
  comparison (``bus.bytes_*{topic="solver"}`` registry counters).

All numbers come from the processes' own ``mapd.metrics`` beacons
(registry snapshots), diffed across the measurement window — no
instrumentation is added for the benchmark.  Evidence for the fast-path
mechanics rides along: ``solverd.delta_agents`` per tick (O(churn)
upload), ``solverd.decode_bytes``, ``solverd.pipeline_overlap_ms``.

Usage:
  python analysis/solver_crossover.py --out results/solver_crossover.json
  python analysis/solver_crossover.py --counts 50,300 --window 10  # smoke

The committed artifact runs solverd with --cpu (JAX CPU backend): the axon
tunnel in this environment adds a ~100-130 ms dispatch+fetch floor per
round-trip that a host-attached TPU does not pay, so CPU-backend numbers
are the honest conservative floor for the daemon side.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from p2p_distributed_tswap_tpu.obs.registry import hist_quantile  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient  # noqa: E402
from p2p_distributed_tswap_tpu.runtime.fleet import (  # noqa: E402
    BUILD_DIR, ensure_built, wait_for_log)
# The sim-agent loop that used to live here (SimFleet) was generalized
# into the reusable, shard-aware, pos1-speaking pool behind the fleetsim
# load harness (ISSUE 7); this harness now drives the same pool.
from p2p_distributed_tswap_tpu.runtime.simagent import SimAgentPool  # noqa: E402,E501

TICK_MS = 500.0


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BeaconWatch:
    """Collect mapd.metrics beacons per process name."""

    def __init__(self, port: int):
        self.bus = BusClient(port=port, peer_id="beaconwatch")
        self.bus.subscribe("mapd.metrics")
        self.samples = {}  # proc -> list of (mono_t, metrics)

    def pump(self, budget_s: float):
        end = time.monotonic() + budget_s
        while True:
            now = time.monotonic()
            if now >= end:
                return
            f = self.bus.recv(timeout=min(0.2, end - now))
            if not f or f.get("op") != "msg":
                continue
            d = f.get("data") or {}
            if d.get("type") == "metrics_beacon":
                self.samples.setdefault(d.get("proc"), []).append(
                    (time.monotonic(), d.get("metrics") or {}))

    def window(self, proc: str):
        """(first, last) snapshots of a proc, or None."""
        s = self.samples.get(proc) or []
        if len(s) < 2:
            return None
        return s[0][1], s[-1][1]

    def close(self):
        self.bus.close()


def _counter(m, name, topic=None):
    total = 0.0
    for key, v in (m.get("counters") or {}).items():
        if key == name or (key.startswith(name + "{")
                           and (topic is None or f'topic="{topic}"' in key)):
            if topic is None or "topic=" not in key \
                    or f'topic="{topic}"' in key:
                total += v
    return total


def _hist_delta(first, last, name):
    h0 = (first.get("hists") or {}).get(name)
    h1 = (last.get("hists") or {}).get(name)
    if h1 is None:
        return None
    if h0 is None:
        h0 = {"buckets": h1["buckets"], "counts": [0] * len(h1["counts"]),
              "sum": 0.0, "count": 0}
    counts = [b - a for a, b in zip(h0["counts"], h1["counts"])]
    return {"buckets": h1["buckets"], "counts": counts,
            "sum": h1["sum"] - h0["sum"], "count": h1["count"] - h0["count"]}


def run_variant(variant: str, n: int, side: int, map_file: str,
                window_s: float, settle_s: float, cpu: bool) -> dict:
    port = _free_port()
    procs = []
    logs = []

    def spawn(name, cmd, stdin=None, env=None):
        import os

        log = open(f"/tmp/crossover_{name}_{variant}_{n}.log", "w")
        logs.append(log)
        p = subprocess.Popen(cmd, stdin=stdin, stdout=log,
                             stderr=subprocess.STDOUT,
                             env=dict(os.environ, **(env or {})))
        procs.append(p)
        return p

    sim = watch = None
    try:
        spawn("bus", [str(BUILD_DIR / "mapd_bus"), str(port)])
        time.sleep(0.3)
        if variant != "native":
            sd_cmd = [sys.executable, "-m",
                      "p2p_distributed_tswap_tpu.runtime.solverd",
                      "--port", str(port), "--map", map_file,
                      "--warm", str(n)]
            if cpu:
                sd_cmd.append("--cpu")
            sd_proc = spawn("solverd", sd_cmd)
            if not wait_for_log(
                    f"/tmp/crossover_solverd_{variant}_{n}.log",
                    "solverd up", 900, proc=sd_proc):
                raise RuntimeError("solverd never became ready")
        mgr_env = {"JG_PLAN_CODEC": "packed" if variant == "packed"
                   else "json"}
        mgr = spawn("manager",
                    [str(BUILD_DIR / "mapd_manager_centralized"),
                     "--port", str(port), "--map", map_file,
                     "--solver", "cpu" if variant == "native" else "tpu",
                     "--max-tracked-agents", str(n + 16)],
                    stdin=subprocess.PIPE, env=mgr_env)
        time.sleep(0.5)
        sim = SimAgentPool(n, side, port=port)
        watch = BeaconWatch(port)
        sim.heartbeat_all()
        sim.pump(2.0)
        mgr.stdin.write(f"tasks {n}\n".encode())
        mgr.stdin.flush()
        # settle: tasks dispatch, caches warm, failover window closes
        t_end = time.monotonic() + settle_s
        while time.monotonic() < t_end:
            sim.pump(0.5)
            watch.pump(0.05)
        if variant == "packed":
            # deferred-field drain: the initial task burst queues N fresh
            # goal sweeps that run in solverd's idle windows — the
            # steady-state measurement starts once the queue is empty
            # (solverd.field_queue gauge rides its beacon)
            drain_end = time.monotonic() + 600
            while time.monotonic() < drain_end:
                sim.pump(0.5)
                watch.pump(0.1)
                s = watch.samples.get("solverd") or []
                if s:
                    q = (s[-1][1].get("gauges") or {}).get(
                        "solverd.field_queue")
                    if q is not None and q <= 0:
                        break
        watch.samples.clear()  # measurement window starts fresh
        t_end = time.monotonic() + window_s
        while time.monotonic() < t_end:
            sim.pump(0.4)
            watch.pump(0.1)
        win = watch.window("manager_centralized")
        if win is None:
            raise RuntimeError(
                f"no manager beacons in the window ({variant}, n={n})")
        first, last = win
        # tick count from the always-on tick_ms histogram (manager.
        # plan_ticks is a trace counter, gated behind JG_TRACE)
        tick_hist = _hist_delta(first, last, "tick_ms")
        rtt_hist = _hist_delta(first, last, "manager.plan_rtt_ms")
        ticks = max(tick_hist["count"] if tick_hist else 0, 1)
        row = {"variant": variant, "agents": n, "ticks": int(ticks),
               "sim_done_tasks": sim.done_count}
        if variant == "native":
            src = tick_hist
        else:
            src = rtt_hist
            row["responses_applied"] = 0 if rtt_hist is None \
                else rtt_hist["count"]
        if src is not None and src["count"] > 0:
            row["ms_per_tick_p50"] = round(hist_quantile(src, 0.5), 2)
            row["ms_per_tick_p95"] = round(hist_quantile(src, 0.95), 2)
            row["ms_per_tick_mean"] = round(src["sum"] / src["count"], 2)
            row["over_tick_budget"] = bool(
                (src["sum"] / src["count"]) > TICK_MS)
        wire = 0.0
        for name in ("bus.bytes_sent", "bus.bytes_received"):
            wire += _counter(last, name, topic="solver") \
                - _counter(first, name, topic="solver")
        row["solver_wire_bytes_per_tick"] = round(wire / ticks, 1)
        sd_win = watch.window("solverd")
        if sd_win is not None:
            f2, l2 = sd_win
            sd_ticks = max((l2.get("hists", {}).get("tick_ms", {})
                            .get("count", 0))
                           - (f2.get("hists", {}).get("tick_ms", {})
                              .get("count", 0)), 1)
            row["solverd"] = {
                "delta_agents_per_tick": round(
                    (_counter(l2, "solverd.delta_agents")
                     - _counter(f2, "solverd.delta_agents")) / sd_ticks, 1),
                "decode_bytes_per_tick": round(
                    (_counter(l2, "solverd.decode_bytes")
                     - _counter(f2, "solverd.decode_bytes")) / sd_ticks, 1),
                "scatter_lanes_per_tick": round(
                    (_counter(l2, "solverd.resident_scatter_lanes")
                     - _counter(f2, "solverd.resident_scatter_lanes"))
                    / sd_ticks, 1),
                "snapshots": int(
                    _counter(l2, "solverd.snapshots_applied")
                    - _counter(f2, "solverd.snapshots_applied")),
                "seq_gaps": int(_counter(l2, "solverd.seq_gaps")
                                - _counter(f2, "solverd.seq_gaps")),
            }
            ov = _hist_delta(f2, l2, "solverd.pipeline_overlap_ms")
            if ov is not None and ov["count"] > 0:
                row["solverd"]["pipeline_overlap_ms_mean"] = round(
                    ov["sum"] / ov["count"], 3)
        fo = _counter(last, "manager.solver_failovers") \
            - _counter(first, "manager.solver_failovers")
        if fo:
            row["solver_failovers_in_window"] = int(fo)
        return row
    finally:
        if sim is not None:
            sim.close()
        if watch is not None:
            watch.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=3)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--counts", default="50,300,1000,3000")
    ap.add_argument("--variants", default="native,packed,json")
    ap.add_argument("--side", type=int, default=128,
                    help="map side; 128 puts the 3000-agent rung at ~18%% "
                         "density, the dense-warehouse regime TSWAP "
                         "targets")
    ap.add_argument("--window", type=float, default=20.0,
                    help="measurement window seconds per run")
    ap.add_argument("--settle", type=float, default=10.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--tpu", action="store_true",
                    help="run solverd on the accelerator backend "
                         "(default: --cpu, the honest CI floor)")
    args = ap.parse_args()
    ensure_built()

    map_file = f"/tmp/crossover_{args.side}.map.txt"
    Path(map_file).write_text(
        "\n".join(["." * args.side] * args.side) + "\n")

    counts = [int(c) for c in args.counts.split(",")]
    variants = args.variants.split(",")
    rows = []
    for n in counts:
        for variant in variants:
            row = run_variant(variant, n, args.side, map_file,
                              args.window, args.settle, cpu=not args.tpu)
            rows.append(row)
            print(json.dumps(row), flush=True)

    by_n = {}
    for r in rows:
        by_n.setdefault(r["agents"], {})[r["variant"]] = r
    crossover = None
    wire_ratios = {}
    for n in sorted(by_n):
        v = by_n[n]
        if ("native" in v and "packed" in v
                and "ms_per_tick_p50" in v["native"]
                and "ms_per_tick_p50" in v["packed"]):
            if crossover is None and (v["packed"]["ms_per_tick_p50"]
                                      < v["native"]["ms_per_tick_p50"]):
                crossover = n
        if "packed" in v and "json" in v:
            jb = v["json"]["solver_wire_bytes_per_tick"]
            pb = v["packed"]["solver_wire_bytes_per_tick"]
            if pb > 0:
                wire_ratios[n] = round(jb / pb, 1)
    result = {
        "experiment": "live-fleet native vs solverd end-to-end ms/tick",
        "map": f"{args.side}x{args.side} empty",
        "tick_ms": TICK_MS,
        "solverd_backend": "accelerator" if args.tpu else "cpu",
        "note": ("native = manager tick_ms (plan+emit+adopt); "
                 "tpu = manager.plan_rtt_ms (request publish -> fresh "
                 "response applied).  Fleet is live: sim agents adopt "
                 "tasks, follow move_instructions, publish positions and "
                 "dones over busd."),
        "rows": rows,
        "crossover_agents": crossover,
        "json_over_packed_wire_ratio": wire_ratios,
    }
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(result, indent=2))
        md = ["| agents | native ms/tick p50 | tpu packed ms/tick p50 "
              "| winner | solver wire B/tick json | packed | ratio |",
              "|---|---|---|---|---|---|---|"]
        for n in sorted(by_n):
            v = by_n[n]
            nat = v.get("native", {}).get("ms_per_tick_p50")
            pk = v.get("packed", {}).get("ms_per_tick_p50")
            jw = v.get("json", {}).get("solver_wire_bytes_per_tick")
            pw = v.get("packed", {}).get("solver_wire_bytes_per_tick")
            win = "-" if nat is None or pk is None else (
                "tpu" if pk < nat else "native")
            md.append(f"| {n} | {nat} | {pk} | {win} | {jw} | {pw} | "
                      f"{wire_ratios.get(n, '-')} |")
        Path(str(args.out) + ".md").write_text("\n".join(md) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
