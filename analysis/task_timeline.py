#!/usr/bin/env python
"""Reconstruct per-task causal timelines from merged lifecycle-event logs.

The fleet's processes emit structured lifecycle events (obs/events.py,
cpp/common/events.hpp) into ``<proc>-<pid>.events.jsonl`` files, each event
stamped with the task's trace context (trace_id / hop / sender wall clock).
This tool merges every process's log, groups events by trace_id, orders each
task's hops, and attributes its end-to-end latency to phases:

  queueing   task.queue    -> task.dispatch   (manager-side wait)
  wire       task.dispatch -> task.claim      (dispatch one-way)
  planning   task.claim    -> task.exec       (first obeyed instruction —
                                               centralized only; 0 when the
                                               agent plans locally)
  to_pickup  claim/exec    -> task.pickup
  to_deliver task.pickup   -> task.delivery
  done_wire  task.delivery -> task.done       (done one-way)
  ack        task.done     -> task.done_ack   (ack round trip)

Phases are CONSECUTIVE segment diffs, so they telescope: their sum equals
end-to-end (done_ack - dispatch) exactly, modulo clock-skew clamps (negative
segments clamp to 0 and are reported as ``skew_ms`` — the same discipline as
the PR-1 task-metric clamps).  Swap negotiation (decentralized task
exchanges) overlaps the travel legs, so it is reported as an overlay
(``swap_ms``: sum of swap_req -> swap_resp/adopt intervals), not a summand.

A timeline is COMPLETE (gap-free) when every required hop is present:
dispatch, claim, pickup, delivery, done, done_ack.  Coverage = complete /
done-ACKED traces (a task finishing right at fleet shutdown can have its
ack truncated — a run boundary, not a propagation gap); the e2e gate
asserts >= 0.95.  Orphan events —
a trace with POST-DISPATCH lifecycle events but no dispatch root —
indicate a broken propagation path and are listed.  Queued-but-not-yet-
dispatched tasks (manager-side events only) are a healthy backlog and are
counted separately as ``pending``.

Usage:
  python analysis/task_timeline.py --dir results/trace --once --json
  python analysis/task_timeline.py --dir <fleet log dir>    # live watch
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

REQUIRED = ("task.dispatch", "task.claim", "task.pickup", "task.delivery",
            "task.done", "task.done_ack")
# The reference's done detection is purely positional (pos == delivery),
# so a task whose delivery cell is crossed BEFORE its pickup completes
# without a pickup phase ever happening — a missing task.pickup alongside
# a full delivery->done->ack tail is that legitimate early-done shape,
# not a propagation gap.

# phase boundaries: consecutive anchors; a missing optional anchor folds
# its segment into the next one
ANCHORS = ("task.queue", "task.dispatch", "task.claim", "task.exec",
           "task.pickup", "task.delivery", "task.done", "task.done_ack")
PHASE_OF_SEGMENT = {
    ("task.queue", "task.dispatch"): "queueing",
    ("task.dispatch", "task.claim"): "wire",
    ("task.claim", "task.exec"): "planning",
    ("task.exec", "task.pickup"): "to_pickup",
    ("task.claim", "task.pickup"): "to_pickup",  # no exec: local planner
    ("task.pickup", "task.delivery"): "to_delivery",
    ("task.exec", "task.delivery"): "to_delivery",   # early-done shapes
    ("task.claim", "task.delivery"): "to_delivery",
    ("task.delivery", "task.done"): "done_wire",
    ("task.done", "task.done_ack"): "ack",
}

PHASES = ("queueing", "wire", "planning", "to_pickup", "to_delivery",
          "done_wire", "ack")

# Hop "violations" (a later event carrying a SMALLER hop) are usually not
# propagation bugs: when the receiver's inbound queue backs up, an event
# stamped early drains late and lands behind a fresher, higher-hop one —
# SCALING.md finding 2 (hop inversions as receiver lag).  The tell is
# co-occurrence with a dispatch->claim wire tail breach: both are the same
# backlog.  Above this claim-wire p99 the summary labels them
# receiver_backlog so SLO artifacts stop reading them as protocol faults.
WIRE_TAIL_BREACH_MS = 1000.0


def load_events(directory: Path) -> list:
    events = []
    for path in sorted(directory.glob("*.events.jsonl")):
        for line in path.read_text(errors="ignore").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn line from a live writer
            if isinstance(ev, dict) and "event" in ev:
                events.append(ev)
    return events


def group_tasks(events: list) -> dict:
    """trace_id -> time-ordered task lifecycle events (plan.* and
    bus.* events are a different subsystem's traffic)."""
    by_trace: dict = {}
    for ev in events:
        tid = ev.get("trace_id")
        if tid is None or not str(ev.get("event", "")).startswith("task."):
            continue
        by_trace.setdefault(int(tid), []).append(ev)
    for evs in by_trace.values():
        evs.sort(key=lambda e: (e.get("ts_ms", 0), e.get("hop", 0)))
    return by_trace


def reconstruct(evs: list) -> dict:
    """One trace's timeline record (see module docstring for semantics)."""
    present = {}
    for ev in evs:
        name = ev["event"]
        if name not in present:  # first occurrence anchors the phase
            present[name] = ev
    missing = [r for r in REQUIRED if r not in present]
    early_done = missing == ["task.pickup"]  # see REQUIRED comment
    if early_done:
        missing = []
    # hop monotonicity along the time-ordered chain (max-merge semantics:
    # equal hops repeat on heartbeats/duplicates, decreases are violations)
    hop_violations = 0
    last_hop = -1
    for ev in evs:
        h = ev.get("hop")
        if h is None:
            continue
        if h < last_hop:
            hop_violations += 1
        last_hop = max(last_hop, h)
    rec = {
        "trace_id": evs[0].get("trace_id"),
        "task_id": next((e.get("task_id") for e in evs
                         if e.get("task_id") is not None), None),
        "events": len(evs),
        "events_seen": sorted({e["event"] for e in evs}),
        "first_ts_ms": evs[0].get("ts_ms"),
        "missing": missing,
        "complete": not missing,
        "early_done": early_done,
        "hop_violations": hop_violations,
        "procs": sorted({e.get("proc", "?") for e in evs}),
    }
    if missing:
        return rec
    # consecutive anchor segments -> phases (telescoping sum)
    anchors = [(a, present[a]["ts_ms"]) for a in ANCHORS if a in present]
    phases = {p: 0.0 for p in PHASES}
    skew_ms = 0.0
    for (a_name, a_ts), (b_name, b_ts) in zip(anchors, anchors[1:]):
        seg = b_ts - a_ts
        if seg < 0:
            skew_ms += -seg
            seg = 0
        # only queue/exec are optional, so every consecutive anchor pair
        # is enumerated in the map; "to_pickup" is an unreachable default
        phase = PHASE_OF_SEGMENT.get((a_name, b_name), "to_pickup")
        phases[phase] += seg
    end_to_end = present["task.done_ack"]["ts_ms"] \
        - present["task.dispatch"]["ts_ms"]
    # swap overlay: each swap_req pairs with the next swap_resp/adopt
    swap_ms = 0.0
    swaps = 0
    open_req = None
    for ev in evs:
        if ev["event"] == "task.swap_req":
            open_req = ev["ts_ms"]
        elif ev["event"] in ("task.swap_resp", "task.adopt") \
                and open_req is not None:
            swap_ms += max(0, ev["ts_ms"] - open_req)
            swaps += 1
            open_req = None
    rec.update({
        "phases_ms": {k: round(v, 3) for k, v in phases.items()},
        "end_to_end_ms": round(float(max(0, end_to_end)), 3),
        "queue_to_ack_ms": round(float(
            present["task.done_ack"]["ts_ms"]
            - present.get("task.queue", present["task.dispatch"])["ts_ms"]),
            3),
        "skew_ms": round(skew_ms, 3),
        "swap_ms": round(swap_ms, 3),
        "swaps": swaps,
        "wire_oneway_ms": {
            name.split(".", 1)[1]: present[name]["wire_ms"]
            for name in ("task.claim", "task.done", "task.done_ack")
            if "wire_ms" in present[name]},
    })
    return rec


def percentile(values: list, q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return float(vs[idx])


def summarize(directory: Path,
              wire_tail_ms: float = WIRE_TAIL_BREACH_MS) -> dict:
    events = load_events(directory)
    tasks = group_tasks(events)
    records = [reconstruct(evs) for evs in tasks.values()]
    records.sort(key=lambda r: r.get("first_ts_ms") or 0)
    done_traces = [r for r in records
                   if r["complete"] or "task.done" not in r["missing"]]
    # coverage denominator: tasks whose lifecycle FINISHED (done-acked).
    # A task completing right at fleet shutdown can have its ack (and the
    # ack's event) truncated — that is a run boundary, not a propagation
    # gap, and must not dilute the coverage gate.
    acked = [r for r in records
             if r["complete"] or "task.done_ack" not in r["missing"]]
    complete = [r for r in records if r["complete"]]
    # pending: manager-side-only traces (queued/requeued, never dispatched
    # yet) — a healthy backlog, NOT a propagation failure.  An orphan has
    # post-dispatch lifecycle events but no dispatch root.
    manager_only = {"task.queue", "task.requeue"}
    pending = [r for r in records
               if "task.dispatch" in r["missing"]
               and not (set(r["events_seen"]) - manager_only)]
    pending_ids = {id(r) for r in pending}
    orphans = [r for r in records
               if "task.dispatch" in r["missing"] and r["events"] > 0
               and id(r) not in pending_ids]
    summary: dict = {
        "dir": str(directory),
        "event_files": len(list(directory.glob("*.events.jsonl"))),
        "events": len(events),
        "traces": len(records),
        "tasks_done": len(done_traces),
        "tasks_acked": len(acked),
        "tasks_complete": len(complete),
        "coverage": round(len(complete) / len(acked), 4)
        if acked else None,
        "pending": len(pending),
        "orphans": len(orphans),
        "orphan_trace_ids": [r["trace_id"] for r in orphans][:20],
        "hop_violations": sum(r["hop_violations"] for r in records),
    }
    if complete:
        summary["fleet_phases_ms"] = {
            p: {"p50": round(percentile(
                    [r["phases_ms"][p] for r in complete], 0.50), 1),
                "p95": round(percentile(
                    [r["phases_ms"][p] for r in complete], 0.95), 1),
                "p99": round(percentile(
                    [r["phases_ms"][p] for r in complete], 0.99), 1)}
            for p in PHASES}
        e2e = [r["end_to_end_ms"] for r in complete]
        summary["end_to_end_ms"] = {
            "p50": round(percentile(e2e, 0.50), 1),
            "p95": round(percentile(e2e, 0.95), 1),
            "p99": round(percentile(e2e, 0.99), 1)}
        summary["swap_ms_total"] = round(
            sum(r["swap_ms"] for r in complete), 1)
    # receiver-backlog attribution (ISSUE 8 satellite; SCALING finding 2):
    # hop inversions co-occurring with a claim-wire tail breach are the
    # receive queue draining late, not a propagation bug — label them so
    # downstream SLO artifacts read the signal correctly
    if summary["hop_violations"]:
        wire_p99 = (summary.get("fleet_phases_ms", {})
                    .get("wire", {}).get("p99"))
        backlog = wire_p99 is not None and wire_p99 >= wire_tail_ms
        summary["hop_violations_indicator"] = (
            "receiver_backlog" if backlog else "unexplained")
        summary["hop_violations_note"] = (
            f"co-occurs with dispatch->claim wire p99 {wire_p99} ms >= "
            f"{wire_tail_ms} ms: inversions are the receiver's inbound "
            "queue draining late (SCALING.md finding 2), not a "
            "propagation bug" if backlog else
            "no claim-wire tail breach in this window: inversions are "
            "NOT explained by receiver backlog — investigate propagation")
    summary["tasks"] = records
    return summary


def render(summary: dict) -> str:
    out = []
    cov = summary["coverage"]
    out.append(f"task timelines from {summary['dir']} "
               f"({summary['event_files']} event files, "
               f"{summary['events']} events)")
    out.append(f"  traces {summary['traces']}  done {summary['tasks_done']}"
               f"  acked {summary['tasks_acked']}"
               f"  complete {summary['tasks_complete']}"
               f"  coverage {'-' if cov is None else f'{cov:.1%}'}"
               f"  pending {summary['pending']}"
               f"  orphans {summary['orphans']}"
               f"  hop-violations {summary['hop_violations']}"
               + (f" ({summary['hop_violations_indicator']})"
                  if "hop_violations_indicator" in summary else ""))
    if "fleet_phases_ms" in summary:
        out.append(f"  end-to-end ms  p50 {summary['end_to_end_ms']['p50']}"
                   f"  p95 {summary['end_to_end_ms']['p95']}"
                   f"  p99 {summary['end_to_end_ms']['p99']}")
        out.append("  phase          p50        p95        p99  (ms)")
        for p in PHASES:
            s = summary["fleet_phases_ms"][p]
            out.append(f"  {p:<12} {s['p50']:>8} {s['p95']:>10}"
                       f" {s['p99']:>10}")
    for r in summary["tasks"][:40]:
        if r["complete"]:
            ph = " ".join(f"{k}={v:.0f}" for k, v in r["phases_ms"].items()
                          if v)
            out.append(f"  task {r['task_id']}: {r['end_to_end_ms']:.0f} ms"
                       f"  [{ph}]"
                       + (f"  swap={r['swap_ms']:.0f}x{r['swaps']}"
                          if r["swaps"] else "")
                       + (f"  skew={r['skew_ms']:.0f}"
                          if r["skew_ms"] else ""))
        else:
            out.append(f"  task {r['task_id']} trace {r['trace_id']}: "
                       f"INCOMPLETE missing={','.join(r['missing'])} "
                       f"({r['events']} events from "
                       f"{'/'.join(r['procs'])})")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="results/trace",
                    help="directory holding *.events.jsonl (JG_TRACE_DIR "
                         "or a fleet log dir)")
    ap.add_argument("--once", action="store_true",
                    help="one shot (default: refresh every --interval)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--wire-tail-ms", type=float,
                    default=WIRE_TAIL_BREACH_MS,
                    help="claim-wire p99 above which hop inversions are "
                         "labeled receiver_backlog (SCALING finding 2)")
    args = ap.parse_args(argv)

    directory = Path(args.dir)
    while True:
        summary = summarize(directory, wire_tail_ms=args.wire_tail_ms)
        if args.as_json:
            print(json.dumps(summary))
        else:
            print(render(summary), flush=True)
        if args.once:
            # exit status doubles as the CI smoke gate: 0 iff at least
            # one fully-attributed task reconstructed
            return 0 if summary["tasks_complete"] >= 1 else 1
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
