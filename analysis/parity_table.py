#!/usr/bin/env python3
"""Empirical makespan-parity table: parallel TSWAP vs the sequential oracle.

SURVEY §7 hard part 1: the batched solver replaces the reference's sequential
agent-id-order TSWAP (src/algorithm/tswap.rs:174-286) with a
parallel-consistent formulation, so makespan parity must be validated
*empirically per config*.  This script runs the sequential oracle
(solver/oracle.py — the transcribed reference semantics) and the parallel
solver (solver/mapd.py) on the same instances across seeds and writes
PARITY.md: one row per config with mean/max makespan ratio
(parallel / oracle), plus a sweep over the two parallel-only knobs
``swap_rounds`` and ``cycle_cap`` justifying their defaults.

The configs cover the reference's own comfortable envelope (50 agents on the
built-in 100x100 empty grid — the scale behind its ~180 ms/step baseline,
src/bin/centralized/manager.rs:564-567), a congested warehouse map, dense
random obstacles, and the small empty grid the unit tests use.

Usage:
    python analysis/parity_table.py [--quick] [--out PARITY.md]

Runs on the virtual CPU backend for reproducibility (results are integer
arithmetic and backend-independent; tests/test_sharded.py checks
bit-identical sharded runs).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from p2p_distributed_tswap_tpu.parallel.virtual_mesh import pin_cpu_backend  # noqa: E402

pin_cpu_backend(1)

import numpy as np  # noqa: E402

from p2p_distributed_tswap_tpu.core.config import SolverConfig  # noqa: E402
from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: E402
from p2p_distributed_tswap_tpu.core.sampling import start_positions_array  # noqa: E402
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator  # noqa: E402
from p2p_distributed_tswap_tpu.solver.mapd import solve_offline  # noqa: E402
from p2p_distributed_tswap_tpu.solver.oracle import OracleSim  # noqa: E402


def configs(quick: bool):
    n_seeds = 3 if quick else 10
    # (name, grid factory, agents, tasks, seeds, distinct_endpoints)
    #
    # distinct_endpoints=True for the >=200-agent rows: with random
    # endpoints the birthday bound makes a shared delivery cell — the
    # reference's documented deadlock (tswap.rs:197-202) — near-certain at
    # hundreds of tasks, which would leave the oracle zero completing
    # seeds.  Distinct endpoints keep the sequential semantics comparable
    # at scale (and model warehouse stations).  The warehouse 64x64 row
    # keeps random endpoints but runs 25 seeds so enough survive
    # (VERDICT r2 item 7).
    return [
        ("ref-envelope 50a 100x100 empty", Grid.default, 50, 50, n_seeds,
         False),
        # double the reference's fleet on its own grid
        ("dense 100a 100x100 empty", Grid.default, 100, 100, n_seeds, False),
        ("warehouse 64x64 40a (congested)",
         lambda: Grid.warehouse(64, 64), 40, 40,
         6 if quick else 25, False),
        ("random-obstacles 32x32 p=0.2 16a",
         lambda: Grid.random_obstacles(32, 32, 0.2, seed=0), 16, 16, n_seeds,
         False),
        ("empty 14x14 6a", lambda: Grid.from_ascii("\n".join(["." * 14] * 14)),
         6, 6, n_seeds, False),
        ("warehouse 128x128 200a (distinct endpoints)",
         lambda: Grid.warehouse(128, 128), 200, 200,
         2 if quick else 5, True),
        ("random-obstacles 128x128 p=0.1 300a (distinct endpoints)",
         lambda: Grid.random_obstacles(128, 128, 0.1, seed=0), 300, 300,
         2 if quick else 5, True),
        ("warehouse 192x192 500a (distinct endpoints)",
         lambda: Grid.warehouse(192, 192), 500, 500,
         1 if quick else 3, True),
    ]


def run_pair(grid: Grid, na: int, nt: int, seed: int,
             cfg: SolverConfig | None = None, distinct: bool = False):
    """Returns (oracle makespan, parallel makespan, oracle_completed).

    The parallel solver must ALWAYS complete.  The oracle may not: the
    reference deadlocks when two tasks share a delivery cell and the first
    deliverer parks on it (Rule-3 swap of identical goals is a no-op,
    tswap.rs:197-202).  The parallel solver carries a documented push
    extension for exactly this (solver/step.py); such seeds are reported
    separately instead of entering the ratio."""
    starts = start_positions_array(grid, na, seed=seed)
    gen = TaskGenerator(grid, seed=seed + 1)
    tasks = (gen.generate_distinct_task_arrays(nt, exclude=starts)
             if distinct else gen.generate_task_arrays(nt))
    oracle = OracleSim(grid, starts, tasks)
    mk_o = oracle.run()
    oracle.assert_no_collisions()
    oracle_done = bool(oracle.task_used.all()) and mk_o <= 2000
    _, _, mk_p = solve_offline(grid, starts, tasks, cfg)
    assert 0 < mk_p <= 2000, "parallel solver hit the horizon"
    return mk_o, mk_p, oracle_done


def sweep_knobs(quick: bool):
    """swap_rounds / cycle_cap sweep on the congested warehouse config."""
    grid = Grid.warehouse(64, 64)
    na = nt = 40
    seeds = range(3 if quick else 6)
    rows = []
    for swap_rounds, cycle_cap in [(1, 32), (2, 32), (4, 32),
                                   (2, 8), (2, 64)]:
        ratios = []
        for seed in seeds:
            cfg = SolverConfig(height=grid.height, width=grid.width,
                               num_agents=na, swap_rounds=swap_rounds,
                               cycle_cap=cycle_cap)
            mk_o, mk_p, ok = run_pair(grid, na, nt, seed, cfg)
            if ok:
                ratios.append(mk_p / mk_o)
        mean_r = float(np.mean(ratios)) if ratios else float("nan")
        max_r = float(np.max(ratios)) if ratios else float("nan")
        rows.append((swap_rounds, cycle_cap, mean_r, max_r))
        print(f"  knobs swap_rounds={swap_rounds} cycle_cap={cycle_cap}: "
              f"mean {mean_r:.3f} max {max_r:.3f}", flush=True)
    return rows


def worst_case_distribution(quick: bool):
    """Ratio distribution on the worst-case config (random-obstacles 32x32
    p=0.2, 16 agents — the 1.44 max in round 2) over many seeds, plus a
    swap_rounds sensitivity check on the worst observed seed (VERDICT r2
    item 7: root-cause or bound the 1.44)."""
    grid = Grid.random_obstacles(32, 32, 0.2, seed=0)
    na = nt = 16
    n = 20 if quick else 100
    ratios, worst = [], (0.0, -1)
    for seed in range(n):
        mk_o, mk_p, ok = run_pair(grid, na, nt, seed)
        if not ok:
            continue
        r = mk_p / mk_o
        ratios.append(r)
        if r > worst[0]:
            worst = (r, seed)
    arr = np.sort(np.array(ratios))
    if not len(arr):  # every sampled seed deadlocked the oracle
        print("worst-case distribution: no oracle-completing seeds",
              flush=True)
        return {"seeds": n, "completing": 0, "mean": float("nan"),
                "median": float("nan"), "p90": float("nan"),
                "max": float("nan"), "min": float("nan"),
                "frac_below_1": float("nan"), "worst_seed": None}, []
    stats = {
        "seeds": n, "completing": len(arr),
        "mean": float(arr.mean()), "median": float(np.median(arr)),
        "p90": float(arr[min(int(0.9 * len(arr)), len(arr) - 1)]),
        "max": float(arr.max()), "min": float(arr.min()),
        "frac_below_1": float((arr < 1.0).mean()),
        "worst_seed": worst[1],
    }
    print(f"worst-case distribution: {stats}", flush=True)
    # knob sensitivity on the worst seed: more swap rounds / larger cycle
    # cap change nothing — the gap is ordering luck, not a missing rule
    sens = []
    for sr in (2, 4, 8):
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=na, swap_rounds=sr)
        _, mk_p, _ = run_pair(grid, na, nt, worst[1], cfg)
        sens.append((sr, mk_p))
        print(f"  worst seed {worst[1]} swap_rounds={sr}: parallel mk={mk_p}",
              flush=True)
    return stats, sens


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=str(REPO / "PARITY.md"))
    args = ap.parse_args()

    lines = [
        "# Makespan parity: parallel TSWAP vs sequential oracle",
        "",
        "Generated by `python analysis/parity_table.py`"
        + (" --quick" if args.quick else "") + ".",
        "",
        "Ratio = parallel makespan / oracle makespan on identical instances",
        "(same grid, starts, tasks).  The oracle (solver/oracle.py) is the",
        "transcribed sequential semantics of the reference's `tswap_mapd`",
        "(src/algorithm/tswap.rs:39-172); the parallel solver resolves",
        "swaps/rotations/moves simultaneously with deterministic agent-id",
        "priority, so orderings — and therefore makespans — differ per",
        "instance in both directions.  Every run also passes the hard",
        "invariants (vertex-disjoint, legal moves, completion).",
        "",
        "A seed where the ORACLE deadlocks (the reference's shared-delivery",
        "flaw; the parallel solver's push extension completes it) is counted",
        "in the last column and excluded from the ratio.",
        "",
        "## Config table",
        "",
        "| config | seeds | mean ratio | max ratio | oracle mk (mean) "
        "| oracle deadlocks |",
        "|---|---|---|---|---|---|",
    ]
    for name, gf, na, nt, n_seeds, distinct in configs(args.quick):
        grid = gf()
        t0 = time.time()
        mks_o, ratios, deadlocks = [], [], 0
        for seed in range(n_seeds):
            mk_o, mk_p, ok = run_pair(grid, na, nt, seed, distinct=distinct)
            if ok:
                mks_o.append(mk_o)
                ratios.append(mk_p / mk_o)
            else:
                deadlocks += 1
        # a config can deadlock the oracle on every sampled seed (the
        # shared-delivery flaw is common on small congested maps)
        mean_r = f"{np.mean(ratios):.3f}" if ratios else "n/a"
        max_r = f"{np.max(ratios):.3f}" if ratios else "n/a"
        mean_mk = f"{np.mean(mks_o):.0f}" if mks_o else "n/a"
        print(f"{name}: mean {mean_r} max {max_r} "
              f"oracle-deadlocks {deadlocks} ({time.time()-t0:.0f}s)",
              flush=True)
        lines.append(f"| {name} | {n_seeds} | {mean_r} "
                     f"| {max_r} | {mean_mk} "
                     f"| {deadlocks} |")

    lines += [
        "",
        "## Knob sweep (warehouse 64x64, 40 agents)",
        "",
        "`swap_rounds` = parallel goal-swap/rotation rounds per step",
        "(approximates the reference's in-step swap cascades);",
        "`cycle_cap` = max deadlock-cycle length detected exactly.",
        "",
        "| swap_rounds | cycle_cap | mean ratio | max ratio |",
        "|---|---|---|---|",
    ]
    print("knob sweep:", flush=True)
    for sr, cc, mean_r, max_r in sweep_knobs(args.quick):
        lines.append(f"| {sr} | {cc} | {mean_r:.3f} | {max_r:.3f} |")
    lines += [
        "",
        "Both knobs are flat across the measured range on every config",
        "tried: extra swap rounds find no additional swaps to make, and",
        "deadlock cycles longer than 8 essentially never occur (a capped",
        "cycle would just wait and retry next step).  The defaults",
        "(`swap_rounds=2`, `cycle_cap=32`, core/config.py) are therefore",
        "safety margin, not tuning: they cost one extra cheap gather round",
        "and cover cycle lengths far beyond anything observed.",
        "",
    ]

    stats, sens = worst_case_distribution(args.quick)
    sens_str = ", ".join(f"swap_rounds={sr} -> mk {mk}" for sr, mk in sens)
    lines += [
        "## Worst-case analysis (random-obstacles 32x32 p=0.2, 16 agents)",
        "",
        f"Ratio distribution over {stats['seeds']} seeds"
        f" ({stats['completing']} oracle-completing):",
        "",
        "| mean | median | p90 | max | min | % of seeds parallel beats "
        "oracle |",
        "|---|---|---|---|---|---|",
        f"| {stats['mean']:.3f} | {stats['median']:.3f} "
        f"| {stats['p90']:.3f} | {stats['max']:.3f} | {stats['min']:.3f} "
        f"| {100 * stats['frac_below_1']:.0f}% |",
        "",
        "The round-2 outlier (1.44) is ORDERING VARIANCE on a tiny",
        "congested instance, not a missing rule: the spread is two-sided",
        "(the parallel solver *beats* the oracle on a substantial fraction",
        "of seeds), the distribution's bulk sits near 1.0, and on the worst",
        f"seed ({stats['worst_seed']}) raising the swap budget does not",
        f"move the makespan ({sens_str}) — there is no additional",
        "coordination the parallel rules are failing to perform.  Both",
        "solvers are greedy heuristics whose per-step tie-breaks simply",
        "diverge; makespans on instances this small (oracle ~50-90 steps)",
        "amplify a handful of unlucky steps into tens of percent.  The",
        ">=200-agent rows above show the divergence washing out at scale.",
        "",
    ]
    Path(args.out).write_text("\n".join(lines))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
