#!/usr/bin/env bash
# Launch N agents against an already-running bus + manager.  (The reference's
# README references a start_agents.sh that does not exist in its snapshot —
# SURVEY C15; this provides the documented capability.)
#
# Usage: ./start_agents.sh [N] [centralized|decentralized]
set -u

N=${1:-3}
MODE=${2:-decentralized}
PORT=${MAPD_BUS_PORT:-7400}
ROOT="$(cd "$(dirname "$0")" && pwd)"
BUILD="$ROOT/cpp/build"

ninja -C "$BUILD" >/dev/null 2>&1 || {
  cmake -S "$ROOT/cpp" -B "$BUILD" -G Ninja >/dev/null
  ninja -C "$BUILD" >/dev/null || { echo "build failed"; exit 1; }
}

for i in $(seq 1 "$N"); do
  "$BUILD/mapd_agent_$MODE" --port "$PORT" --seed "$i" &
  sleep 0.15
done
echo "🤖 started $N $MODE agents on bus port $PORT (PIDs: $(jobs -p | tr '\n' ' '))"
wait
