#!/usr/bin/env bash
# Ops helper (capability of the reference's clear_and_start_manager.sh,
# without its hardcoded developer path): kill any running fleet processes,
# verify they are gone, then start bus + a fresh manager with --clean.
#
# Usage: ./clear_and_start_manager.sh [centralized|decentralized]
set -u

MODE=${1:-decentralized}
PORT=${MAPD_BUS_PORT:-7400}
ROOT="$(cd "$(dirname "$0")" && pwd)"
BUILD="$ROOT/cpp/build"

echo "🧹 stopping existing mapd processes..."
pkill -f mapd_agent_ 2>/dev/null
pkill -f mapd_manager_ 2>/dev/null
pkill -f mapd_bus 2>/dev/null
pkill -f "p2p_distributed_tswap_tpu.runtime.solverd" 2>/dev/null
sleep 1

REMAINING=$(pgrep -fc "mapd_(bus|agent_|manager_)" 2>/dev/null || true)
if [ "${REMAINING:-0}" -gt 0 ] 2>/dev/null; then
  echo "⚠️  ${REMAINING} processes still running; sending SIGKILL"
  pkill -9 -f "mapd_(bus|agent_|manager_)" 2>/dev/null
  sleep 1
fi
echo "✅ clean"

cmake -S "$ROOT/cpp" -B "$BUILD" -G Ninja >/dev/null
ninja -C "$BUILD" >/dev/null || { echo "build failed"; exit 1; }

"$BUILD/mapd_bus" "$PORT" &
sleep 0.3
echo "🧠 starting $MODE manager (--clean) on bus port $PORT"
exec "$BUILD/mapd_manager_$MODE" --port "$PORT" --clean
