#!/usr/bin/env bash
# E2E test: decentralized fleet (capability of the reference's
# test_decentralized.sh — build, FIFO-driven manager, N agents with staggered
# startup, warmup, continuous task injection, CSV + summary harvest).
#
# Usage: ./test_decentralized.sh [NUM_AGENTS] [DURATION_SECS]
set -u

NUM_AGENTS=${1:-3}
DURATION=${2:-60}
PORT=${MAPD_BUS_PORT:-7421}
ROOT="$(cd "$(dirname "$0")" && pwd)"
BUILD="$ROOT/cpp/build"
OUT="$ROOT/results/decentralized_$(date +%Y%m%d_%H%M%S)"
mkdir -p "$OUT"

# -- build ---------------------------------------------------------------
cmake -S "$ROOT/cpp" -B "$BUILD" -G Ninja >/dev/null
ninja -C "$BUILD" >/dev/null || { echo "build failed"; exit 1; }

PIDS=()
cleanup() {
  for p in "${PIDS[@]:-}"; do kill "$p" 2>/dev/null; done
  wait 2>/dev/null
}
trap cleanup EXIT

# -- launch bus + manager (stdin held open through a FIFO) ---------------
"$BUILD/mapd_bus" "$PORT" >"$OUT/bus.log" 2>&1 &
PIDS+=($!)
sleep 0.3

FIFO="$OUT/mgr_in"
mkfifo "$FIFO"
TASK_CSV_PATH="$OUT/task_metrics.csv" PATH_CSV_PATH="$OUT/path_metrics.csv" \
  "$BUILD/mapd_manager_decentralized" --port "$PORT" \
  >"$OUT/manager.log" 2>&1 <"$FIFO" &
MGR_PID=$!
PIDS+=($MGR_PID)
exec 3>"$FIFO"   # hold the write end so manager stdin stays open
sleep 0.5

# -- launch agents with staggered spacing --------------------------------
for i in $(seq 1 "$NUM_AGENTS"); do
  "$BUILD/mapd_agent_decentralized" --port "$PORT" --seed "$i" \
    >"$OUT/agent_$i.log" 2>&1 &
  PIDS+=($!)
  sleep 0.2
done

WARMUP=$((5 + NUM_AGENTS / 5))
echo "⏳ warmup ${WARMUP}s (mesh formation + initial positions)..."
sleep "$WARMUP"

# -- continuous task injection every 3 s ---------------------------------
echo "🚀 injecting tasks for ${DURATION}s..."
END=$(($(date +%s) + DURATION))
while [ "$(date +%s)" -lt "$END" ]; do
  echo "tasks $NUM_AGENTS" >&3
  sleep 3
done

echo "metrics" >&3
sleep 1
echo "quit" >&3
exec 3>&-
for _ in $(seq 1 10); do kill -0 $MGR_PID 2>/dev/null || break; sleep 1; done

# -- summary -------------------------------------------------------------
SUMMARY="$OUT/test_summary.txt"
{
  echo "test: decentralized  agents=$NUM_AGENTS duration=${DURATION}s"
  if [ -f "$OUT/task_metrics.csv" ]; then
    COMPLETED=$(awk -F, 'NR>1 && $10=="completed"' "$OUT/task_metrics.csv" | wc -l)
    TOTAL=$(awk 'NR>1' "$OUT/task_metrics.csv" | wc -l)
    echo "tasks_completed: $COMPLETED / $TOTAL"
    echo "throughput_tasks_per_sec: $(awk -v c="$COMPLETED" -v d="$DURATION" 'BEGIN{printf "%.3f", c/d}')"
    awk -F, 'NR>1 && $7!="" {s+=$7; n++} END{if(n) printf "avg_task_latency_s: %.2f\n", s/n/1000}' "$OUT/task_metrics.csv"
  fi
  if [ -f "$OUT/path_metrics.csv" ]; then
    awk -F, 'NR>1 {s+=$2; n++} END{if(n) printf "avg_plan_time_ms: %.3f (n=%d)\n", s/n/1000, n}' "$OUT/path_metrics.csv"
  fi
} | tee "$SUMMARY"
echo "📁 results in $OUT"
