// mapd_manager_decentralized — task dispatcher + metrics sink (SURVEY C8).
//
// Native rebuild of src/bin/decentralized/manager.rs: no pathfinding — it
// round-robins generated tasks over non-busy subscribed peers, answers
// occupied_request with all known peer positions, ingests position updates,
// task metrics and path metrics, auto-refills a fresh task when a peer
// reports done, runs the operator CLI on stdin (task | tasks N | metrics |
// save F | save path F | reset | quit; anything else is broadcast raw), does
// periodic bounded-cache cleanup, and auto-saves CSVs on exit when
// TASK_CSV_PATH / PATH_CSV_PATH are set.
//
// Usage: mapd_manager_decentralized [--port P] [--map FILE] [--seed S]
//                                   [--clean]

#include <poll.h>
#include <signal.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <optional>
#include <random>
#include <set>
#include <sstream>
#include <string>
#include <type_traits>
#include <vector>

#include <algorithm>
#include <deque>
#include <tuple>

#include "../common/audit.hpp"
#include "../common/bus.hpp"
#include "../common/events.hpp"
#include "../common/grid.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/plan_codec.hpp"
#include "../common/region.hpp"

using namespace mapd;

namespace {

volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  const std::string bus_host = knobs.get_str("--host", "MAPD_BUS_HOST",
                                             "127.0.0.1");
  const uint16_t port = static_cast<uint16_t>(
      knobs.get_int("--port", "MAPD_BUS_PORT", 7400));
  const std::string map_file = knobs.get_str("--map", "MAPD_MAP", "");
  // ignore re-discovered peers (ref --clean)
  const bool clean = knobs.get_bool("--clean", "MAPD_CLEAN");
  const uint64_t seed = static_cast<uint64_t>(knobs.get_int(
      "--seed", "MAPD_SEED",
      static_cast<int64_t>(std::random_device{}())));
  // federation-lite (ISSUE 14): a decentralized manager in a federated
  // world samples its task PICKUPS inside its own region (deliveries
  // stay global), so several of them can co-serve one world without a
  // shared sampler.  The full handoff protocol lives on the centralized
  // serving path (manager_centralized) — decentralized agents carry
  // their own task state peer-to-peer and need no lane transfer.
  const std::string regions_spec =
      knobs.get_str("--regions", "JG_REGIONS", "1");
  const int region_id = static_cast<int>(
      knobs.get_int("--region-id", "JG_REGION_ID", 0));
  // audit-pairing namespace (ISSUE 14): per-region label without bus
  // namespacing; defaults to the tenant ns
  const char* dns_env = getenv("JG_BUS_NS");
  const std::string audit_ns = knobs.get_str(
      "--audit-ns", "JG_AUDIT_NS", (dns_env && *dns_env) ? dns_env : "");
  // RuntimeConfig knobs, reference-parity defaults (core/config.py).
  const int64_t cleanup_ms =
      knobs.get_int("--cleanup-interval-ms", "MAPD_CLEANUP_INTERVAL_MS",
                    30000);                                 // ref :158-194
  const size_t max_peers = static_cast<size_t>(
      knobs.get_int("--max-tracked-peers", "MAPD_MAX_TRACKED_PEERS",
                    200));                                  // ref :173
  const size_t max_positions = static_cast<size_t>(
      knobs.get_int("--max-cached-positions", "MAPD_MAX_CACHED_POSITIONS",
                    60));
  // busy peers silent this long are treated as dead: task re-queued, peer
  // dropped (a mute-but-connected peer never emits peer_left)
  const int64_t agent_stale_ms =
      knobs.get_int("--agent-stale-ms", "MAPD_AGENT_STALE_MS", 60000);
  // a peer that keeps reporting idle this long past dispatch never got its
  // task (delivery lost in a bus outage) — re-send the same task
  const int64_t task_resend_ms =
      knobs.get_int("--task-resend-ms", "MAPD_TASK_RESEND_MS", 5000);
  // region-sharded position gossip (ISSUE 4): agents beacon packed pos1
  // on mapd.pos.<rx>.<ry>; the manager needs the GLOBAL view, so it
  // subscribes the wildcard (busd prefix matching) instead of N² flat
  // heartbeats.  JG_REGION_GOSSIP=0 falls back to flat position_update.
  const bool region_gossip =
      knobs.get_int("--region-gossip", "JG_REGION_GOSSIP", 1) != 0;
  // audit plane (ISSUE 10): periodic task-ledger digest beacons on
  // mapd.audit.  The decentralized manager has no packed plan wire to
  // shadow, but its ledger (in-flight + orphan-requeue tasks) is the
  // system of record the auditor's view checks compare against.
  // JG_AUDIT=0 keeps the wire byte-identical.
  const bool audit_on = knobs.get_int("--audit", "JG_AUDIT", 1) != 0;
  const int64_t audit_interval_ms =
      knobs.get_int("--audit-interval-ms", "JG_AUDIT_INTERVAL_MS", 2000);
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);
  // lifecycle events + flight recorder (ISSUE 5); trace-context
  // propagation gated by JG_TRACE_CTX
  events_init("manager_decentralized");
  const bool tctx = trace_ctx_enabled();
  // trace_id = run-epoch | task_id (unique across manager restarts);
  // 20 epoch bits keep ids under 2^53 (the JSON wire rounds past that)
  const int64_t trace_epoch = (unix_ms() & 0xFFFFF) << 32;

  Grid grid = Grid::default_grid();
  if (!map_file.empty()) {
    auto g = Grid::from_file(map_file);
    if (!g) {
      fprintf(stderr, "cannot load map %s\n", map_file.c_str());
      return 1;
    }
    grid = *g;
  }
  std::mt19937_64 rng(seed);

  BusClient bus;
  std::string my_id = random_peer_id();
  if (!bus.connect(bus_host, port, my_id)) {
    fprintf(stderr, "cannot connect to bus on port %u\n", port);
    return 1;
  }
  bus.subscribe("mapd");
  if (region_gossip) {
    bus.subscribe(kPosTopicWildcard);
    bus.subscribe("mapd.path");  // interest-scoped path_metric stream
  }
  // drill answering needs the audit topic; beacons alone are publish-only
  if (audit_on) bus.subscribe(audit::kAuditTopic, /*raw=*/true);
  // survive a bus restart (reconnect + resubscribe inside BusClient);
  // agents re-announce position+goal on their own reconnect.  ADVICE r5:
  // no liveness evidence can arrive while the hub is down, so the stale
  // sweeps must not age anything out during an outage — and after the
  // reconnect they hold one claim-freshness window so the agents'
  // post-outage heartbeats land BEFORE the deliberate-duplicate
  // re-dispatch fires (sweep_hold_until, checked in the cleanup pass).
  int64_t sweep_hold_until = 0;
  const int64_t claim_fresh_ms = 2500;
  bus.set_reconnect([&sweep_hold_until, claim_fresh_ms]() {
    sweep_hold_until = mono_ms() + claim_fresh_ms;
  });
  bus.enable_metrics_beacon("manager_decentralized");
  // world-epoch tracking (ISSUE 10 satellite): always-present gauges so
  // the fleet_top WORLD line shows this manager's (static) world view
  metrics_gauge("manager.world_seq", 0.0);
  metrics_gauge("manager.dynamic_world", 0.0);
  log_info("🧠 decentralized manager %s up (grid %dx%d)\n", my_id.c_str(),
           grid.width, grid.height);
  log_info("Commands: task | tasks N | metrics | save <file> | "
           "save path <file> | reset | quit\n");

  std::set<std::string> subscribed_peers;
  std::set<std::string> known_left;  // --clean: never re-add these
  std::map<std::string, Cell> peer_positions;
  std::map<std::string, int64_t> peer_last_seen;  // position_update times
  std::map<std::string, Json> peer_busy;   // peer -> active task (full JSON)
  std::map<std::string, int64_t> busy_since;  // peer -> dispatch mono_ms
  std::deque<Json> requeue;                // tasks orphaned by dead peers
  // Done dedup (bounded): agents retransmit done until acked, and a task
  // re-queued from a presumed-dead peer can complete twice — only the
  // first done per task id may trigger the free-the-peer + refill path.
  std::set<long long> completed_ids;
  std::deque<long long> completed_order;
  // In-flight task ledger: agents exchange tasks PEER TO PEER (a TSWAP
  // goal exchange is a task re-assignment), so per-peer bookkeeping alone
  // cannot tell a healthy exchange from a stranded task.  Heartbeats
  // carry busy_task; the ledger records the last time ANY peer claimed
  // each dispatched task, and the cleanup sweep re-queues tasks no one
  // has claimed for agent_stale_ms (e.g. a swap_response lost in a bus
  // outage stranding the handed-over task).
  std::map<long long, Json> inflight;        // task_id -> bare Task JSON
  std::map<long long, int64_t> last_claimed; // task_id -> last claim mono_ms
  // Last peer whose heartbeat claimed each task, with its claim time:
  // duplicate copies after a severed exchange make TWO live peers claim
  // the same id, and believing every claim flips peer_busy between them
  // on alternating heartbeats (each flip frees the other peer, so the
  // done-refill can dispatch fresh work to a peer still holding its
  // duplicate — ADVICE r5 low).  A claim only moves the bookkeeping when
  // the recorded holder's own claim has gone stale (>= 2 heartbeat
  // periods): a genuinely exchanged-away holder stops claiming within one.
  std::map<long long, std::pair<std::string, int64_t>> holder_claim;
  TaskMetricsCollector task_metrics;
  PathComputationMetrics path_metrics;
  // federation-lite pickup sampling + region-strided ids (ISSUE 14):
  // co-serving managers must mint task ids from disjoint residue
  // classes — colliding ids poison every task-id-keyed dedup (see
  // manager_centralized)
  FedMap fed = FedMap::parse(regions_spec);
  if (!fed.valid()) {
    fprintf(stderr, "bad --regions spec %s (want N or CxR)\n",
            regions_spec.c_str());
    return 2;
  }
  if (fed.total() > 1 && (region_id < 0 || region_id >= fed.total())) {
    // an out-of-range id would silently collide task-id residue
    // classes with a real region's manager — fail at startup like the
    // centralized manager does
    fprintf(stderr, "--region-id %d out of range for %s\n", region_id,
            regions_spec.c_str());
    return 2;
  }
  const uint64_t task_id_stride = fed.total() > 1 ? fed.total() : 1;
  uint64_t next_task_id = fed.total() > 1 ? 1 + region_id : 1;
  // per-task wire-hop ledger (common/events.hpp: send advances, receive
  // max-merges, bounded by oldest-id eviction)
  TaskHopLedger hops(trace_epoch);

  auto free_cells = grid.free_cells();
  auto gen_point = [&]() { return free_cells[rng() % free_cells.size()]; };
  // federation-lite pickup sampling (see the --regions knob above)
  std::vector<Cell> rect_free;
  if (fed.total() > 1) {
    const FedRect r = fed.rect_of(grid.width, grid.height, region_id);
    for (Cell c : free_cells) {
      const int x = grid.x_of(c), y = grid.y_of(c);
      if (x >= r.x0 && x < r.x1 && y >= r.y0 && y < r.y1)
        rect_free.push_back(c);
    }
    metrics_gauge("manager.region", static_cast<double>(region_id));
    metrics_gauge("manager.regions", static_cast<double>(fed.total()));
  }
  auto gen_pickup = [&]() {
    return rect_free.empty() ? gen_point()
                             : rect_free[rng() % rect_free.size()];
  };

  auto dispatch_task = [&](const std::string& peer, Json t) {
    uint64_t id = static_cast<uint64_t>(t["task_id"].as_int());
    t.set("peer_id", peer);
    if (tctx) {
      auto tcx = hops.next(static_cast<long long>(id));
      t.set("tc", tc_json(tcx));  // stored copies carry it for re-sends
      event_emit("task.dispatch", &tcx, static_cast<long long>(id), peer);
    }
    TaskMetric m;
    m.task_id = id;
    m.peer_id = peer;
    m.sent_time = unix_ms();
    task_metrics.add_metric(m);
    peer_busy[peer] = t;
    busy_since[peer] = mono_ms();
    peer_last_seen.emplace(peer, mono_ms());  // monitor from dispatch
    inflight[static_cast<long long>(id)] = t;
    last_claimed[static_cast<long long>(id)] = mono_ms();
    bus.publish("mapd", t);
    // live dispatch counter: the fleet rollup derives tasks/s and the
    // completion ratio from the dispatched/completed counter pair
    metrics_count("manager.tasks_dispatched");
    log_info("📤 Task %llu -> %s\n", static_cast<unsigned long long>(id),
             peer.c_str());
  };

  auto send_task_to = [&](const std::string& peer) {
    Cell pickup = gen_pickup(), delivery = gen_point();
    while (delivery == pickup) delivery = gen_point();
    Json t;  // bare Task JSON, the one shared serde struct (ref C10)
    Json pk, dl;
    pk.push_back(Json(grid.x_of(pickup)));
    pk.push_back(Json(grid.y_of(pickup)));
    dl.push_back(Json(grid.x_of(delivery)));
    dl.push_back(Json(grid.y_of(delivery)));
    t.set("pickup", pk).set("delivery", dl).set("peer_id", peer)
        .set("task_id", static_cast<int64_t>(next_task_id));
    next_task_id += task_id_stride;
    if (tctx) {
      // hop 0 = creation: the trace root (dispatch is hop 1, a breath
      // later — decentralized tasks are born assigned)
      long long id = t["task_id"].as_int();
      codec::TraceCtx t0{trace_epoch | id, 0, unix_ms()};
      event_emit("task.queue", &t0, id, peer);
    }
    dispatch_task(peer, std::move(t));
  };

  // Orphaned tasks from dead peers go to the next free peer — the reference
  // loses them (src/bin/decentralized/manager.rs:185-189, documented flaw;
  // SURVEY §5 calls it out).  Drained on done / peer_joined / task commands.
  auto drain_requeue = [&]() {
    while (!requeue.empty()) {
      std::string free_peer;
      for (const auto& peer : subscribed_peers)
        if (!peer_busy.count(peer)) {
          free_peer = peer;
          break;
        }
      if (free_peer.empty()) return;
      Json t = requeue.front();
      requeue.pop_front();
      log_info("♻️  re-dispatching orphaned task %lld\n",
               static_cast<long long>(t["task_id"].as_int()));
      dispatch_task(free_peer, std::move(t));
    }
  };

  auto assign_round_robin = [&](size_t count) {
    // ref :256-329: rounds over non-busy subscribed peers until count sent
    if (subscribed_peers.empty()) {
      log_warn("⚠️  no subscribed peers\n");
      return;
    }
    size_t sent = 0;
    while (sent < count) {
      size_t sent_this_round = 0;
      for (const auto& peer : subscribed_peers) {
        if (sent >= count) break;
        if (peer_busy.count(peer)) continue;
        send_task_to(peer);
        ++sent;
        ++sent_this_round;
      }
      if (sent_this_round == 0) break;  // everyone busy
    }
    log_info("📦 dispatched %zu/%zu tasks\n", sent, count);
  };

  auto save_csv = [&](const std::string& path, const std::string& content) {
    std::ofstream out(path);
    if (!out) {
      log_warn("⚠️  cannot write %s\n", path.c_str());
      return;
    }
    out << content;
    log_info("💾 saved %s\n", path.c_str());
  };

  auto handle_command = [&](const std::string& line) -> bool {
    std::istringstream in(line);
    std::string cmd;
    in >> cmd;
    if (cmd == "quit" || cmd == "exit") return false;
    if (cmd == "task") {
      drain_requeue();
      for (const auto& peer : subscribed_peers)
        if (!peer_busy.count(peer)) {
          send_task_to(peer);
          return true;
        }
      log_warn("⚠️  all peers busy\n");
    } else if (cmd == "tasks") {
      size_t n = 0;
      in >> n;
      drain_requeue();
      assign_round_robin(n ? n : subscribed_peers.size());
    } else if (cmd == "metrics") {
      log_info("%s\n", task_metrics.statistics().to_string().c_str());
      if (auto ps = path_metrics.statistics())
        log_info("%s\n", ps->to_string().c_str());
      log_info("%s\n",
               MetricsRegistry::instance().network_summary_string().c_str());
      // live registry dump (Prometheus text): ticks, tasks, per-topic bytes
      log_info("%s", MetricsRegistry::instance().expose_text().c_str());
    } else if (cmd == "save") {
      std::string a, b;
      in >> a >> b;
      if (a == "path")
        save_csv(b.empty() ? "path_metrics.csv" : b,
                 path_metrics.to_csv_string());
      else
        save_csv(a.empty() ? "task_metrics.csv" : a,
                 task_metrics.to_csv_string());
    } else if (cmd == "reset") {
      task_metrics.clear();
      path_metrics.clear();
      peer_busy.clear();
      busy_since.clear();
      requeue.clear();
      completed_ids.clear();
      completed_order.clear();
      inflight.clear();
      last_claimed.clear();
      holder_claim.clear();
      log_info("🔄 state reset\n");
    } else if (!cmd.empty()) {
      Json raw;  // unknown lines broadcast raw (ref :389-395)
      raw.set("raw", line);
      bus.publish("mapd", raw);
    }
      return true;
  };

  // Heartbeat ingestion, shared by the JSON position_update wire and the
  // packed pos1 region beacon (peer identity rides the bus frame's own
  // `from` on the packed wire): tracking + the idle-but-marked-busy
  // reconciliation + the busy-claim ledger.
  auto handle_heartbeat = [&](const std::string& peer,
                              std::optional<Cell> cell, bool has_busy,
                              long long busy_tid,
                              const std::optional<codec::TraceCtx>& hb_tc
                              = std::nullopt) {
    // busy-claim heartbeats carry their task's trace context: per-hop
    // one-way latency (no event — beacon rate), hop max-merge
    if (tctx && hb_tc) {
      hop_latency_ms(hb_tc->send_ms, "task.claim_hb");
      hops.seen(busy_tid, *hb_tc);
    }
    if (cell) peer_positions[peer] = *cell;
    subscribed_peers.insert(peer);
    peer_last_seen[peer] = mono_ms();
    // idle-but-marked-busy reconciliation: the heartbeat carries a
    // busy_task field while the agent holds a task.  A peer still
    // reporting idle well past dispatch never received its Task
    // (publish into a bus outage is dropped) — re-send the SAME
    // task.  An agent whose done was lost instead is healed by its
    // own retransmit (and refuses this duplicate by task id).
    auto busy = peer_busy.find(peer);
    if (busy != peer_busy.end() && !has_busy) {
      const long long btid = busy->second["task_id"].as_int();
      if (completed_ids.count(btid)) {
        // someone ELSE completed this peer's task (peer-side
        // exchange): never re-send a finished task — free the
        // peer for fresh work instead
        peer_busy.erase(busy);
        busy_since.erase(peer);
        if (subscribed_peers.count(peer)) send_task_to(peer);
      } else {
        int64_t now = mono_ms();
        auto since = busy_since.find(peer);
        if (since != busy_since.end()
            && now - since->second > task_resend_ms) {
          log_info("↻ %s reports idle but task %lld is in flight; "
                   "re-sending\n", peer.c_str(), btid);
          if (tctx) {
            auto t = hops.next(btid);
            busy->second.set("tc", tc_json(t));
            event_emit("task.resend", &t, btid, peer);
          }
          bus.publish("mapd", busy->second);
          since->second = now;
        }
      }
    } else if (has_busy) {
      // the heartbeat claims a task: refresh the ledger, and on
      // an id MISMATCH believe the agent — tasks move between
      // peers in exchanges the manager never arbitrates
      const long long ctid = busy_tid;
      auto inf = inflight.find(ctid);
      if (inf != inflight.end()) {
        last_claimed[ctid] = mono_ms();
        // a queued requeue copy is now moot: its holder is alive
        // (same race the done handler cancels for completions)
        for (auto q = requeue.begin(); q != requeue.end(); ++q)
          if ((*q)["task_id"].as_int() == ctid) {
            log_info("♻️  task %lld re-claimed by %s; queued "
                     "duplicate cancelled\n", ctid, peer.c_str());
            requeue.erase(q);
            break;
          }
        if (busy == peer_busy.end()
            || busy->second["task_id"].as_int() != ctid) {
          // freshness guard (see holder_claim above): ignore a
          // claim that would evict a holder whose own claim is
          // fresher than the heartbeat cadence — ends the
          // peer_busy ping-pong between duplicate holders
          auto hc = holder_claim.find(ctid);
          if (hc != holder_claim.end() && hc->second.first != peer
              && mono_ms() - hc->second.second < claim_fresh_ms) {
            metrics_count("manager.duplicate_claims_ignored");
            log_debug("… ignoring %s's claim on task %lld (%s "
                      "claimed it %lld ms ago)\n", peer.c_str(),
                      ctid, hc->second.first.c_str(),
                      static_cast<long long>(
                          mono_ms() - hc->second.second));
            return;
          }
          log_info("🔁 %s now carries task %lld (peer-side "
                   "exchange); bookkeeping follows\n",
                   peer.c_str(), ctid);
          if (tctx) {
            codec::TraceCtx t0 = hb_tc ? *hb_tc : hops.current(ctid);
            event_emit("task.exchange", &t0, ctid, peer);
          }
          // the previous holder's entry is stale: drop it so the
          // idle-resend cannot hand the task back out twice
          for (auto b = peer_busy.begin(); b != peer_busy.end();)
            if (b->first != peer
                && b->second["task_id"].as_int() == ctid) {
              busy_since.erase(b->first);
              b = peer_busy.erase(b);
            } else {
              ++b;
            }
          peer_busy[peer] = inf->second;
          peer_busy[peer].set("peer_id", peer);
          busy_since[peer] = mono_ms();
        }
        holder_claim[ctid] = {peer, mono_ms()};
      }
    }
  };

  // ---- audit plane (ISSUE 10): ledger + in-flight view digests ----
  // canonical ledger tuples + sorted in-flight view, shared by the
  // beacon and the drill responder so both hash the same material
  auto ledger_tuples = [&]() {
    auto cell_of = [&](const Json& pt) -> int32_t {
      const auto& arr = pt.as_array();
      if (arr.size() != 2) return -1;
      int x = static_cast<int>(arr[0].as_int());
      int y = static_cast<int>(arr[1].as_int());
      if (!grid.in_bounds(x, y)) return -1;
      return static_cast<int32_t>(grid.cell(x, y));
    };
    // pending = the orphan requeue; in-flight tasks all carry the
    // generic in-flight state byte (agents own the pickup flip here —
    // this manager never learns the phase, and the digest canon must
    // only hash what the ledger actually knows)
    std::vector<std::tuple<int64_t, uint8_t, int32_t, int32_t>> tup;
    for (const auto& t : requeue)
      tup.emplace_back(t["task_id"].as_int(), audit::kTaskPending,
                       cell_of(t["pickup"]), cell_of(t["delivery"]));
    std::vector<int64_t> view;
    for (const auto& [id, t] : inflight) {
      tup.emplace_back(id, audit::kTaskToPickup, cell_of(t["pickup"]),
                       cell_of(t["delivery"]));
      view.push_back(id);
    }
    std::sort(tup.begin(), tup.end());
    std::sort(view.begin(), view.end());
    return std::make_pair(tup, view);
  };

  auto publish_audit_beacon = [&]() {
    auto [tup, view] = ledger_tuples();
    audit::LedgerDigest ld;
    for (const auto& [id, st, pk, dl] : tup) ld.add(id, st, pk, dl);
    std::vector<audit::Entry> entries;
    audit::Entry el;
    el.section = audit::kSecLedger;
    el.count = ld.count;
    el.seq = 0;
    el.epoch = 0;
    el.digest = ld.digest();
    entries.push_back(el);
    audit::Entry ev;
    ev.section = audit::kSecView;
    ev.count = static_cast<uint32_t>(view.size());
    ev.seq = 0;
    ev.epoch = 0;
    ev.digest = audit::view_digest(view);
    entries.push_back(ev);
    Json caps;
    caps.push_back(Json(std::string(audit::kAuditCap)));
    Json buckets;
    buckets.set("pending", static_cast<int64_t>(requeue.size()))
        .set("in_flight", static_cast<int64_t>(inflight.size()));
    Json b;
    b.set("type", "audit_beacon")
        .set("peer_id", my_id)
        .set("proc", "manager_decentralized")
        .set("ns", audit_ns)
        .set("ts_ms", unix_ms())
        .set("interval_s", audit_interval_ms / 1000.0)
        .set("caps", caps)
        .set("dynamic_world", false)
        .set("buckets", buckets)
        .set("data", codec::b64_encode(audit::encode_audit(entries)));
    bus.publish(audit::kAuditTopic, b, /*raw=*/true);
  };

  // Bisect drill responder over task-id halves: "ledger" hashes the
  // (id,state,pickup,delivery) tuples in [lo,hi), "view" the in-flight
  // ids — the auditor recurses to the first divergent id range, same
  // wire contract as the centralized manager's responder.
  auto handle_drill = [&](const Json& d) {
    if (!audit_on) return;
    const std::string target = d["target"].as_str();
    if (target != "manager_decentralized" && target != my_id) return;
    const std::string view = d["view"].as_str();
    const int64_t lo = d["lo"].as_int();
    const int64_t hi = d["hi"].as_int();
    Json resp;
    resp.set("type", "audit_drill_response")
        .set("req_id", d["req_id"])
        .set("peer_id", my_id)
        .set("target", target)
        .set("view", view)
        .set("lo", lo)
        .set("hi", hi);
    auto [tup, ids] = ledger_tuples();
    if (view == "view") {
      std::vector<int64_t> in;
      for (int64_t id : ids)
        if (id >= lo && id < hi) in.push_back(id);
      resp.set("digest", audit::digest_hex(audit::view_digest(in)))
          .set("count", static_cast<int64_t>(in.size()));
    } else {  // "ledger"
      audit::LedgerDigest ld;
      for (const auto& [id, st, pk, dl] : tup) {
        if (id < lo || id >= hi) continue;
        ld.add(id, st, pk, dl);
      }
      resp.set("digest", audit::digest_hex(ld.digest()))
          .set("count", static_cast<int64_t>(ld.count));
    }
    bus.publish(audit::kAuditTopic, resp, /*raw=*/true);
  };

  bus.query_peers("mapd");
  int64_t last_cleanup = mono_ms(), last_audit = 0;
  std::string stdin_buf;
  bool running = true;

  while (running && !g_stop && bus.connected()) {
    // poll every shard link plus stdin (stdin stays LAST in the vector)
    std::vector<pollfd> pfds;
    bus.append_pollfds(pfds);
    pfds.push_back({STDIN_FILENO, POLLIN, 0});
    poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 200);

    if (pfds.back().revents & POLLIN) {
      char buf[4096];
      ssize_t n = read(STDIN_FILENO, buf, sizeof(buf));
      if (n > 0) {
        stdin_buf.append(buf, static_cast<size_t>(n));
        size_t nl;
        while ((nl = stdin_buf.find('\n')) != std::string::npos) {
          std::string line = stdin_buf.substr(0, nl);
          stdin_buf.erase(0, nl + 1);
          if (!handle_command(line)) {
            running = false;
            break;
          }
        }
      } else if (n == 0) {
        running = false;  // stdin closed: graceful exit like `quit`
      }
    }

    bool alive = bus.pump(
        [&](const BusClient::Msg& m) {
          const Json& d = m.data;
          const std::string& type = d["type"].as_str();
          if (type == "position_update") {
            const std::string& peer = d["peer_id"].as_str();
            std::optional<Cell> cell;
            const auto& p = d["position"].as_array();
            if (p.size() == 2) {
              int x = static_cast<int>(p[0].as_int());
              int y = static_cast<int>(p[1].as_int());
              if (grid.in_bounds(x, y)) cell = grid.cell(x, y);
            }
            handle_heartbeat(peer, cell, d.has("busy_task"),
                             d["busy_task"].as_int(), tc_parse(d));
          } else if (type == "pos1") {
            // packed region beacon (wildcard subscription): the same
            // heartbeat, ~4x fewer wire bytes, addressed by bus `from`
            auto p1 = codec::decode_pos1_b64(d["data"].as_str());
            if (!p1) return;
            std::optional<Cell> cell;
            if (p1->pos >= 0 &&
                p1->pos < static_cast<Cell>(grid.free.size()))
              cell = p1->pos;
            handle_heartbeat(
                m.from, cell, p1->has_task, p1->task_id,
                p1->has_trace ? std::optional<codec::TraceCtx>(p1->trace)
                              : std::nullopt);
          } else if (type == "occupied_request") {
            // manager answers with ALL known positions (ref :441-468)
            Json occ;
            for (const auto& [peer, c] : peer_positions) {
              Json p;
              p.push_back(Json(grid.x_of(c)));
              p.push_back(Json(grid.y_of(c)));
              occ.push_back(p);
            }
            if (occ.is_null()) occ = Json(JsonArray{});
            Json resp;
            resp.set("type", "occupied_response")
                .set("occupied", occ)
                .set("timestamp", unix_ms())
                .set("from_peer", my_id);
            bus.publish("mapd", resp);
          } else if (type == "task_metric_received") {
            task_metrics.update_received(
                static_cast<uint64_t>(d["task_id"].as_int()),
                d["timestamp_ms"].as_int());
          } else if (type == "task_metric_started") {
            task_metrics.update_started(
                static_cast<uint64_t>(d["task_id"].as_int()),
                d["timestamp_ms"].as_int());
          } else if (type == "task_metric_completed") {
            const uint64_t tid = static_cast<uint64_t>(d["task_id"].as_int());
            task_metrics.update_completed(tid, d["timestamp_ms"].as_int());
            // live task-latency histogram for the fleet rollup (beacons)
            auto itm = task_metrics.metrics.find(tid);
            if (itm != task_metrics.metrics.end())
              if (auto t = itm->second.total_time())
                metrics_observe("task.total_time_ms",
                                static_cast<double>(*t));
          } else if (type == "path_metric") {
            path_metrics.record_micros(d["duration_micros"].as_int(),
                                       d["timestamp_ms"].as_int());
          } else if (type == "flight_dump") {
            // black-box query: dump the ring and answer with the path
            bus.publish(
                "mapd", flight_dump_answer("manager_decentralized", my_id));
          } else if (type == "audit_drill_request") {
            handle_drill(d);
          } else if (d["status"].as_str() == "done") {
            const std::string& peer = m.from;
            const long long tid = d["task_id"].as_int();
            auto done_tc = tc_parse(d);
            if (done_tc) {
              hops.seen(tid, *done_tc);
              event_emit("task.done", &*done_tc, tid, peer,
                         done_tc->send_ms);
            }
            // ack unconditionally: agents retransmit done until acked, and
            // a duplicate (its ack was lost) must still be acked
            Json ack;
            ack.set("type", "done_ack").set("peer_id", peer)
                .set("task_id", Json(static_cast<int64_t>(tid)));
            if (tctx && done_tc) {
              auto t = hops.next(tid);
              ack.set("tc", tc_json(t));
            }
            bus.publish("mapd", ack);
            if (completed_ids.count(tid)) {
              // retransmit of an already-processed done, or the second
              // completion of a re-queued task: counted once already.  If
              // the reporter's CURRENT assignment is this very task (it
              // completed the re-dispatched copy), free it and keep it in
              // the work loop — but never clobber a DIFFERENT in-flight
              // assignment (late retransmit after a fresh dispatch).
              log_warn("⚠️  duplicate done for task %lld (%s) ignored\n",
                       tid, peer.c_str());
              auto busy = peer_busy.find(peer);
              if (busy != peer_busy.end()
                  && busy->second["task_id"].as_int() == tid) {
                peer_busy.erase(busy);
                busy_since.erase(peer);
                if (!requeue.empty()) drain_requeue();
                if (!peer_busy.count(peer) && subscribed_peers.count(peer))
                  send_task_to(peer);
              }
              return;
            }
            completed_ids.insert(tid);
            // deduped path only: retransmits/double-dones never inflate
            // the fleet tasks/s the rollup derives from this counter
            metrics_count("manager.tasks_completed");
            completed_order.push_back(tid);
            inflight.erase(tid);
            last_claimed.erase(tid);
            holder_claim.erase(tid);
            if (completed_order.size() > 4096) {
              completed_ids.erase(completed_order.front());
              completed_order.pop_front();
            }
            // the presumed-dead original agent finished after all: cancel
            // the queued duplicate before drain_requeue re-dispatches a
            // task that is already complete (re-dispatch would also reset
            // its metric from Completed back to Sent)
            for (auto q = requeue.begin(); q != requeue.end(); ++q)
              if ((*q)["task_id"].as_int() == tid) {
                log_info("♻️  task %lld done by its original agent; queued "
                         "duplicate cancelled\n", tid);
                requeue.erase(q);
                break;
              }
            // closed loop: fresh task for that peer immediately (ref :527-560)
            peer_busy.erase(peer);
            busy_since.erase(peer);
            log_info("🎉 %s finished task %lld\n", peer.c_str(),
                     static_cast<long long>(tid));
            if (!requeue.empty())
              drain_requeue();  // orphans take priority over fresh tasks
            if (!peer_busy.count(peer) && subscribed_peers.count(peer))
              send_task_to(peer);
          }
        },
        [&](const Json& ev) {
          const std::string& op = ev["op"].as_str();
          if (op == "peer_joined") {
            const std::string& peer = ev["peer_id"].as_str();
            if (clean && known_left.count(peer)) return;
            subscribed_peers.insert(peer);
            log_info("🔍 peer joined: %s (%zu peers)\n", peer.c_str(),
                     subscribed_peers.size());
            drain_requeue();
          } else if (op == "peer_left") {
            const std::string& peer = ev["peer_id"].as_str();
            known_left.insert(peer);
            subscribed_peers.erase(peer);
            peer_positions.erase(peer);
            peer_last_seen.erase(peer);
            auto busy = peer_busy.find(peer);
            if (busy != peer_busy.end()) {
              // Re-queue the dead peer's in-flight task — the reference
              // only cleans the mapping and the task is lost
              // (src/bin/decentralized/manager.rs:185-189, documented
              // flaw; SURVEY §5).
              log_info("♻️  peer %s died with task %lld in flight, "
                       "re-queueing\n", peer.c_str(),
                       static_cast<long long>(
                       busy->second["task_id"].as_int()));
              if (tctx) {
                long long tid = busy->second["task_id"].as_int();
                codec::TraceCtx t0 = hops.current(tid);
                event_emit("task.requeue", &t0, tid, peer);
              }
              requeue.push_back(std::move(busy->second));
              peer_busy.erase(busy);
              busy_since.erase(peer);
              drain_requeue();
            }
            log_info("👋 peer left: %s\n", peer.c_str());
          } else if (op == "peers") {
            for (const auto& p : ev["peers"].as_array())
              subscribed_peers.insert(p.as_str());
          }
                });
    if (!alive) break;

    int64_t now = mono_ms();
    if (audit_on && now - last_audit >= audit_interval_ms) {
      last_audit = now;
      publish_audit_beacon();
    }
    if (now - last_cleanup > cleanup_ms) {
      last_cleanup = now;
      // ADVICE r5: both liveness sweeps below act on the ABSENCE of
      // heartbeats — evidence that cannot arrive while the bus is down.
      // Hold them during an outage (fd < 0) and for one claim-freshness
      // window after the reconnect, so post-outage heartbeats/claims
      // land before the silence/unclaimed re-queues fire duplicates.
      const bool sweeps_armed = bus.fd() >= 0 && now >= sweep_hold_until;
      if (!sweeps_armed)
        log_debug("🧹 liveness sweeps held (%s)\n",
                  bus.fd() < 0 ? "bus outage" : "post-reconnect drain");
      // Mute-but-connected peers (no peer_left ever fires): drop ALL
      // tracking — an idle frozen peer would otherwise haunt every
      // occupied_response with a phantom position — and re-queue the
      // tasks of busy ones, mirroring the centralized manager's stale
      // age-out (the reference loses the task in every such case).
      for (auto it = peer_last_seen.begin();
           sweeps_armed && it != peer_last_seen.end();) {
        if (now - it->second <= agent_stale_ms) {
          ++it;
          continue;
        }
        const std::string peer = it->first;
        auto busy = peer_busy.find(peer);
        if (busy != peer_busy.end()) {
          log_info("♻️  peer %s silent for %lld ms with task %lld in "
                   "flight, re-queueing\n", peer.c_str(),
                   static_cast<long long>(now - it->second),
                   static_cast<long long>(
                       busy->second["task_id"].as_int()));
          if (tctx) {
            long long tid = busy->second["task_id"].as_int();
            codec::TraceCtx t0 = hops.current(tid);
            event_emit("task.requeue", &t0, tid, peer);
          }
          requeue.push_back(std::move(busy->second));
          peer_busy.erase(busy);
          busy_since.erase(peer);
        } else {
          log_info("🧹 dropping silent peer %s (%lld ms)\n", peer.c_str(),
                   static_cast<long long>(now - it->second));
        }
        subscribed_peers.erase(peer);
        peer_positions.erase(peer);
        it = peer_last_seen.erase(it);
      }
      // Unclaimed-task sweep (runs AFTER the silence sweep so a mute
      // peer's task is re-queued through the silence path first): a
      // dispatched task that no heartbeat has claimed for agent_stale_ms
      // has no live holder — e.g. its holder handed it over in an
      // exchange whose swap_response died with the bus.  Re-queue it.
      // Held with the silence sweep while sweeps_armed is false (outage /
      // post-reconnect drain) — see above.
      for (auto inf = inflight.begin();
           sweeps_armed && inf != inflight.end();) {
        const long long tid = inf->first;
        if (completed_ids.count(tid)) {
          last_claimed.erase(tid);
          holder_claim.erase(tid);
          inf = inflight.erase(inf);
          continue;
        }
        auto lc = last_claimed.find(tid);
        const int64_t claimed_ms = lc == last_claimed.end() ? 0 : lc->second;
        bool queued = false;
        for (const auto& q : requeue)
          queued = queued || q["task_id"].as_int() == tid;
        if (!queued && now - claimed_ms > agent_stale_ms) {
          log_info("♻️  task %lld unclaimed by any peer for %lld ms, "
                   "re-queueing\n", tid,
                   static_cast<long long>(now - claimed_ms));
          if (tctx) {
            codec::TraceCtx t0 = hops.current(tid);
            event_emit("task.requeue", &t0, tid);
          }
          requeue.push_back(inf->second);
          for (auto b = peer_busy.begin(); b != peer_busy.end(); ++b)
            if (b->second["task_id"].as_int() == tid) {
              busy_since.erase(b->first);
              peer_busy.erase(b);
              break;
            }
          last_claimed[tid] = now;  // one shot per stale window
          holder_claim.erase(tid);  // the re-dispatch's holder claims fresh
        }
        ++inf;
      }
      drain_requeue();
      // Cap enforcement evicts the chosen peer from ALL tracking maps at
      // once so they never drift apart (a peer dropped from the liveness
      // clock but kept in peer_positions would haunt occupied_response
      // unmonitored).  Victim = oldest-seen non-busy peer (unknown
      // last-seen counts as oldest); busy peers stay monitored — their
      // tasks could be lost otherwise — making each cap soft when every
      // remaining peer is busy.
      auto evict_one_nonbusy = [&](auto& over_cap_map) -> bool {
        auto peer_of = [](const auto& entry) -> const std::string& {
          if constexpr (std::is_same_v<std::decay_t<decltype(entry)>,
                                       std::string>)
            return entry;  // std::set<std::string>
          else
            return entry.first;  // std::map<std::string, ...>
        };
        std::string victim;
        int64_t victim_seen = 0;
        for (const auto& entry : over_cap_map) {
          const std::string& peer = peer_of(entry);
          if (peer_busy.count(peer)) continue;
          auto it = peer_last_seen.find(peer);
          int64_t seen = it == peer_last_seen.end() ? 0 : it->second;
          if (victim.empty() || seen < victim_seen) {
            victim = peer;
            victim_seen = seen;
          }
        }
        if (victim.empty()) return false;  // all busy: soft cap
        subscribed_peers.erase(victim);
        peer_positions.erase(victim);
        peer_last_seen.erase(victim);
        return true;
      };
      while (subscribed_peers.size() > max_peers)
        if (!evict_one_nonbusy(subscribed_peers)) break;
      while (peer_positions.size() > max_positions)
        if (!evict_one_nonbusy(peer_positions)) break;
      while (peer_last_seen.size() > max_peers)
        if (!evict_one_nonbusy(peer_last_seen)) break;
      log_info("🧹 [CLEANUP] peers=%zu positions=%zu busy=%zu requeue=%zu\n",
               subscribed_peers.size(), peer_positions.size(),
               peer_busy.size(), requeue.size());
        }
  }

  // graceful exit: env-var CSV auto-save (ref :48-50, :570-584)
  if (const char* p = getenv("TASK_CSV_PATH"))
    save_csv(p, task_metrics.to_csv_string());
  if (const char* p = getenv("PATH_CSV_PATH"))
    save_csv(p, path_metrics.to_csv_string());
  log_info("%s\n", task_metrics.statistics().to_string().c_str());
  log_info("manager: bye\n");
  bus.close();
  return 0;
}
