// codec_golden — drive the native packed-plan codec from a script on
// stdin, so the Python side (tests/test_plan_codec.py, scripts/ci.sh fuzz
// gate) can assert BYTE-IDENTICAL output against its own encoder and
// round-trip decode equivalence.
//
//   --encode   stdin: one JSON per line
//                {"seq":N, "fleet":[["peer",pos,goal],...],
//                 "force_snapshot":bool?, "snapshot_every":int?}
//              stdout: one base64 packet per line (PackedFleetEncoder,
//              state carried across lines like a live manager tick stream)
//   --decode   stdin: one base64 packet per line
//              stdout: canonical JSON of the decoded packet per line
//              ("null" for undecodable input)
//   --pos1-encode  stdin: one JSON per line {"pos":P,"goal":G,"task":T?}
//                  stdout: one base64 pos1 beacon per line
//   --pos1-decode  stdin: one base64 pos1 beacon per line
//                  stdout: {"pos":P,"goal":G,"task":T|null} per line
//                  ("null" for undecodable input)
//   --shardmap     stdin: one JSON per line {"topic":s,"shards":n}
//                  stdout: {"shard":k,"subs":[k...]} per line — the
//                  topic→shard map (cpp/common/shardmap.hpp) the Python
//                  side asserts choice-identical (ISSUE 6)
//   --world-encode stdin: one JSON per line
//                  {"seq":N,"cells":[...],"blocked":[0|1,...],
//                   "trace":[id,hop,ms]?}
//                  stdout: one base64 world1 packet per line (ISSUE 9;
//                  --decode round-trips it like any packed1 kind)
//   --audit-digest stdin: one JSON per line (ISSUE 10)
//                  {"lanes":[[lane,pos,goal],...]} |
//                  {"ledger":[[id,state,pickup,delivery],...]} |
//                  {"view":[id,...]} | {"cells":[c,...]}
//                  stdout: {"digest":"<16-hex>","count":n} per line —
//                  the audit-plane digest canon the Python side asserts
//                  byte-identical (obs/audit.py)
//   --audit-encode stdin: one JSON per line
//                  {"entries":[[section,count,seq,epoch,"hex"],...]}
//                  stdout: one base64 audit1 blob per line
//   --audit-decode stdin: one base64 audit1 blob per line
//                  stdout: {"entries":[[...],...]} per line ("null"
//                  for undecodable input)
//   --fedmap       stdin: one JSON per line (ISSUE 14)
//                  {"spec":"CxR","w":W,"h":H,"x":x,"y":y,
//                   "margin":m,"border":b,"shards":n}
//                  stdout: {"region":k,"rect":[x0,y0,x1,y1],
//                           "escaped":bool,"border":bool,"shard":s,
//                           "topic":"mapd.fed.k","solver":"solver.rk"}
//                  — the federated region-ownership canon the Python
//                  side (runtime/region.py fed_*) asserts rule-identical
//   --handoff-encode stdin: one JSON per line (ISSUE 14)
//                  {"seq":N,"src":R,"peer":"id","pos":P,"goal":G,
//                   "phase":0|1|2,"task":T?,"pickup":PK?,"delivery":D?}
//                  stdout: one base64 handoff1 packet per line
//                  (--decode round-trips it like any packed1 kind)
//   --ledger-encode stdin: one JSON per line (ISSUE 15)
//                  {"plan":P,"world_seq":W,"next":N,
//                   "tasks":[[id,state,pickup,delivery,"peer"],...],
//                   "world":[[cell,blocked],...],
//                   "handoffs":[[dst,seq,epoch,"peer",pos,goal,phase,
//                                task|null,pickup,delivery],...]?,
//                   "inc":I?,"snapshot_every":k?,"force_snapshot":bool?}
//                  stdout: one base64 ledger1 record per line — state
//                  carried across lines like a live replication stream
//                  ("null" when nothing changed and no snapshot is due)
//   --ledger-decode stdin: one base64 ledger1 record per line
//                  stdout: canonical JSON of the decoded record per
//                  line ("null" for undecodable input)

#include <algorithm>
#include <cstdio>
#include <iostream>
#include <string>

#include "../common/audit.hpp"
#include "../common/grid.hpp"
#include "../common/ha.hpp"
#include "../common/json.hpp"
#include "../common/plan_codec.hpp"
#include "../common/region.hpp"
#include "../common/shardmap.hpp"

using namespace mapd;

static Json i32_array(const std::vector<int32_t>& v) {
  Json a;
  for (int32_t x : v) a.push_back(Json(static_cast<int64_t>(x)));
  if (a.is_null()) a = Json(JsonArray{});
  return a;
}

// trace1 context rides scripts/outputs as "trace":[id,hop,send_ms]
static bool parse_trace(const Json& j, codec::TraceCtx* out) {
  if (!j.has("trace")) return false;
  const auto& arr = j["trace"].as_array();
  if (arr.size() != 3) return false;
  out->trace_id = arr[0].as_int();
  out->hop = static_cast<uint32_t>(arr[1].as_int());
  out->send_ms = arr[2].as_int();
  return true;
}

static Json trace_json(bool has, const codec::TraceCtx& t) {
  if (!has) return Json();
  Json a;
  a.push_back(Json(t.trace_id));
  a.push_back(Json(static_cast<int64_t>(t.hop)));
  a.push_back(Json(t.send_ms));
  return a;
}

int main(int argc, char** argv) {
  const std::string mode = argc > 1 ? argv[1] : "";
  if (mode != "--encode" && mode != "--decode" && mode != "--pos1-encode" &&
      mode != "--pos1-decode" && mode != "--shardmap" &&
      mode != "--world-encode" && mode != "--audit-digest" &&
      mode != "--audit-encode" && mode != "--audit-decode" &&
      mode != "--fedmap" && mode != "--handoff-encode" &&
      mode != "--ledger-encode" && mode != "--ledger-decode" &&
      mode != "--agg1-encode" && mode != "--agg1-decode") {
    fprintf(stderr,
            "usage: codec_golden --encode|--decode|--pos1-encode|"
            "--pos1-decode|--shardmap|--world-encode|--audit-digest|"
            "--audit-encode|--audit-decode|--fedmap|--handoff-encode|"
            "--ledger-encode|--ledger-decode|--agg1-encode|--agg1-decode"
            " < lines\n");
    return 2;
  }
  codec::PackedFleetEncoder enc;
  bool enc_configured = false;
  ha::LedgerEncoder ledger_enc(0);
  bool ledger_configured = false;
  std::string line;
  while (std::getline(std::cin, line)) {
    if (line.empty()) continue;
    if (mode == "--pos1-encode") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad pos1 script line\n");
        return 1;
      }
      const Json& j = *parsed;
      codec::TraceCtx tc;
      const bool has_tc = parse_trace(j, &tc);
      printf("%s\n",
             codec::encode_pos1_b64(
                 static_cast<int32_t>(j["pos"].as_int()),
                 static_cast<int32_t>(j["goal"].as_int()), j.has("task"),
                 j["task"].as_int(), has_tc ? &tc : nullptr)
                 .c_str());
      continue;
    }
    if (mode == "--shardmap") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad shardmap script line\n");
        return 1;
      }
      const std::string topic = (*parsed)["topic"].as_str();
      const int n = static_cast<int>((*parsed)["shards"].as_int());
      Json subs;
      for (int s : shardmap::shards_for_subscription(topic, n))
        subs.push_back(Json(static_cast<int64_t>(s)));
      if (subs.is_null()) subs = Json(JsonArray{});
      Json out;
      out.set("shard",
              static_cast<int64_t>(shardmap::shard_of(topic, n)))
          .set("subs", subs);
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--pos1-decode") {
      auto p = codec::decode_pos1_b64(line);
      if (!p) {
        printf("null\n");
        continue;
      }
      Json out;
      out.set("pos", static_cast<int64_t>(p->pos))
          .set("goal", static_cast<int64_t>(p->goal))
          .set("task", p->has_task ? Json(p->task_id) : Json())
          .set("trace", trace_json(p->has_trace, p->trace));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--agg1-encode") {
      // {"entries": [["peer", "<b64 pos1 blob>"], ...],
      //  "trace": [tid, hop, send_ms]?}  ->  one base64 agg1 per line
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad agg1 script line\n");
        return 1;
      }
      const Json& j = *parsed;
      std::vector<codec::Agg1Entry> entries;
      for (const auto& e : j["entries"].as_array()) {
        const auto& pair = e.as_array();
        auto blob = codec::b64_decode(pair[1].as_str());
        if (pair.size() != 2 || !blob) {
          fprintf(stderr, "codec_golden: bad agg1 entry\n");
          return 1;
        }
        entries.push_back({pair[0].as_str(), *blob});
      }
      codec::TraceCtx tc;
      const bool has_tc = parse_trace(j, &tc);
      printf("%s\n",
             codec::encode_agg1_b64(entries, has_tc ? &tc : nullptr)
                 .c_str());
      continue;
    }
    if (mode == "--agg1-decode") {
      auto a = codec::decode_agg1_b64(line);
      if (!a) {
        printf("null\n");
        continue;
      }
      Json entries;
      for (const auto& e : a->entries) {
        Json pair;
        pair.push_back(Json(e.name));
        pair.push_back(Json(codec::b64_encode(e.blob)));
        entries.push_back(pair);
      }
      if (entries.is_null()) entries = Json(JsonArray{});
      Json out;
      out.set("entries", entries)
          .set("trace", trace_json(a->has_trace, a->trace));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--world-encode") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad world script line\n");
        return 1;
      }
      const Json& j = *parsed;
      std::vector<int32_t> cells, blocked;
      for (const auto& c : j["cells"].as_array())
        cells.push_back(static_cast<int32_t>(c.as_int()));
      for (const auto& b : j["blocked"].as_array())
        blocked.push_back(static_cast<int32_t>(b.as_int()));
      codec::Packet pkt = codec::encode_world(j["seq"].as_int(), cells,
                                              blocked);
      codec::TraceCtx tc;
      if (parse_trace(j, &tc)) {
        pkt.has_trace = true;
        pkt.trace = tc;
      }
      printf("%s\n", codec::encode_b64(pkt).c_str());
      continue;
    }
    if (mode == "--audit-digest") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad audit-digest script line\n");
        return 1;
      }
      const Json& j = *parsed;
      uint64_t digest = 0;
      uint32_t count = 0;
      if (j.has("lanes")) {
        // triples arrive in script order; the canon sorts by lane
        std::vector<std::tuple<int32_t, int32_t, int32_t>> tri;
        for (const auto& e : j["lanes"].as_array()) {
          const auto& t = e.as_array();
          tri.emplace_back(static_cast<int32_t>(t[0].as_int()),
                           static_cast<int32_t>(t[1].as_int()),
                           static_cast<int32_t>(t[2].as_int()));
        }
        std::stable_sort(tri.begin(), tri.end(),
                         [](const auto& a, const auto& b) {
                           return std::get<0>(a) < std::get<0>(b);
                         });
        audit::LaneDigest ld;
        for (const auto& [l, p, g] : tri) ld.add(l, p, g);
        digest = ld.digest();
        count = ld.count;
      } else if (j.has("ledger")) {
        std::vector<std::tuple<int64_t, uint8_t, int32_t, int32_t>> tup;
        for (const auto& e : j["ledger"].as_array()) {
          const auto& t = e.as_array();
          tup.emplace_back(t[0].as_int(),
                           static_cast<uint8_t>(t[1].as_int()),
                           static_cast<int32_t>(t[2].as_int()),
                           static_cast<int32_t>(t[3].as_int()));
        }
        std::sort(tup.begin(), tup.end());
        audit::LedgerDigest ld;
        for (const auto& [id, st, pk, dl] : tup) ld.add(id, st, pk, dl);
        digest = ld.digest();
        count = ld.count;
      } else if (j.has("view")) {
        std::vector<int64_t> ids;
        for (const auto& e : j["view"].as_array())
          ids.push_back(e.as_int());
        std::sort(ids.begin(), ids.end());
        digest = audit::view_digest(ids);
        count = static_cast<uint32_t>(ids.size());
      } else if (j.has("cells")) {
        std::vector<int32_t> cs;
        for (const auto& e : j["cells"].as_array())
          cs.push_back(static_cast<int32_t>(e.as_int()));
        std::sort(cs.begin(), cs.end());
        digest = audit::cells_digest(cs);
        count = static_cast<uint32_t>(cs.size());
      } else {
        fprintf(stderr, "codec_golden: unknown audit-digest kind\n");
        return 1;
      }
      Json out;
      out.set("digest", audit::digest_hex(digest))
          .set("count", static_cast<int64_t>(count));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--audit-encode") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad audit-encode script line\n");
        return 1;
      }
      std::vector<audit::Entry> entries;
      for (const auto& e : (*parsed)["entries"].as_array()) {
        const auto& t = e.as_array();
        audit::Entry en;
        en.section = static_cast<uint8_t>(t[0].as_int());
        en.count = static_cast<uint32_t>(t[1].as_int());
        en.seq = t[2].as_int();
        en.epoch = t[3].as_int();
        // digests ride scripts as hex (u64 would round through doubles)
        en.digest = strtoull(t[4].as_str().c_str(), nullptr, 16);
        entries.push_back(en);
      }
      printf("%s\n",
             codec::b64_encode(audit::encode_audit(entries)).c_str());
      continue;
    }
    if (mode == "--audit-decode") {
      auto raw = codec::b64_decode(line);
      std::vector<audit::Entry> entries;
      if (!raw || !audit::decode_audit(*raw, &entries)) {
        printf("null\n");
        continue;
      }
      Json arr;
      for (const auto& e : entries) {
        Json t;
        t.push_back(Json(static_cast<int64_t>(e.section)));
        t.push_back(Json(static_cast<int64_t>(e.count)));
        t.push_back(Json(e.seq));
        t.push_back(Json(e.epoch));
        t.push_back(Json(audit::digest_hex(e.digest)));
        arr.push_back(t);
      }
      if (arr.is_null()) arr = Json(JsonArray{});
      Json out;
      out.set("entries", arr);
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--fedmap") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad fedmap script line\n");
        return 1;
      }
      const Json& j = *parsed;
      FedMap fm = FedMap::parse(j["spec"].as_str());
      if (!fm.valid()) {
        printf("null\n");
        continue;
      }
      const int w = static_cast<int>(j["w"].as_int());
      const int h = static_cast<int>(j["h"].as_int());
      const int x = static_cast<int>(j["x"].as_int());
      const int y = static_cast<int>(j["y"].as_int());
      const int margin = j.has("margin")
                             ? static_cast<int>(j["margin"].as_int())
                             : kDefaultFedHysteresis;
      const int border = j.has("border")
                             ? static_cast<int>(j["border"].as_int())
                             : kDefaultFedBorder;
      const int shards = j.has("shards")
                             ? static_cast<int>(j["shards"].as_int())
                             : 1;
      const int rid = fm.region_of(w, h, x, y);
      // the escape/border tests are judged against region 0's rect so
      // the Python side can sweep cells over a FIXED rectangle
      FedRect r0 = fm.rect_of(w, h, 0);
      Json rect;
      FedRect rr = fm.rect_of(w, h, rid);
      rect.push_back(Json(static_cast<int64_t>(rr.x0)));
      rect.push_back(Json(static_cast<int64_t>(rr.y0)));
      rect.push_back(Json(static_cast<int64_t>(rr.x1)));
      rect.push_back(Json(static_cast<int64_t>(rr.y1)));
      Json out;
      out.set("region", static_cast<int64_t>(rid))
          .set("rect", rect)
          .set("escaped", FedMap::escaped(x, y, r0, margin))
          .set("border", FedMap::in_border(x, y, r0, border))
          .set("shard", static_cast<int64_t>(rid % std::max(1, shards)))
          .set("topic", FedMap::fed_topic(rid))
          .set("solver", fm.solver_topic(rid));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--handoff-encode") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad handoff script line\n");
        return 1;
      }
      const Json& j = *parsed;
      codec::HandoffRec r;
      r.seq = j["seq"].as_int();
      r.src_region = static_cast<int32_t>(j["src"].as_int());
      r.peer = j["peer"].as_str();
      r.pos = static_cast<int32_t>(j["pos"].as_int());
      r.goal = static_cast<int32_t>(j["goal"].as_int());
      r.phase = static_cast<int32_t>(j["phase"].as_int());
      if (j.has("task")) {
        r.has_task = true;
        r.task_id = j["task"].as_int();
        r.pickup = static_cast<int32_t>(j["pickup"].as_int());
        r.delivery = static_cast<int32_t>(j["delivery"].as_int());
      }
      codec::Packet pkt = codec::encode_handoff(r);
      codec::TraceCtx tc;
      if (parse_trace(j, &tc)) {
        pkt.has_trace = true;
        pkt.trace = tc;
      }
      printf("%s\n", codec::encode_b64(pkt).c_str());
      continue;
    }
    if (mode == "--ledger-encode") {
      auto parsed = Json::parse(line);
      if (!parsed || !parsed->is_object()) {
        fprintf(stderr, "codec_golden: bad ledger script line\n");
        return 1;
      }
      const Json& j = *parsed;
      if (!ledger_configured) {
        ledger_enc = ha::LedgerEncoder(
            j.has("inc") ? j["inc"].as_int() : 0,
            j.has("snapshot_every")
                ? static_cast<int>(j["snapshot_every"].as_int())
                : ha::kSnapshotEvery);
        ledger_configured = true;
      }
      if (j["force_snapshot"].as_bool()) ledger_enc.request_snapshot();
      std::vector<ha::LedgerTask> tasks;
      for (const auto& e : j["tasks"].as_array()) {
        const auto& t = e.as_array();
        if (t.size() != 5) {
          fprintf(stderr, "codec_golden: ledger task needs "
                          "[id,state,pickup,delivery,peer]\n");
          return 1;
        }
        ha::LedgerTask lt;
        lt.task_id = t[0].as_int();
        lt.state = static_cast<uint8_t>(t[1].as_int());
        lt.pickup = static_cast<int32_t>(t[2].as_int());
        lt.delivery = static_cast<int32_t>(t[3].as_int());
        lt.peer = t[4].as_str();
        tasks.push_back(std::move(lt));
      }
      std::map<int32_t, int> world;
      for (const auto& e : j["world"].as_array()) {
        const auto& t = e.as_array();
        world[static_cast<int32_t>(t[0].as_int())] =
            t[1].as_int() ? 1 : 0;
      }
      std::vector<ha::HandoffOut> handoffs;
      for (const auto& e : j["handoffs"].as_array()) {
        const auto& t = e.as_array();
        if (t.size() != 10) {
          fprintf(stderr, "codec_golden: ledger handoff needs "
                          "[dst,seq,epoch,peer,pos,goal,phase,task,"
                          "pickup,delivery]\n");
          return 1;
        }
        ha::HandoffOut h;
        h.dst = static_cast<int32_t>(t[0].as_int());
        h.seq = t[1].as_int();
        h.epoch = t[2].as_int();
        h.peer = t[3].as_str();
        h.pos = static_cast<int32_t>(t[4].as_int());
        h.goal = static_cast<int32_t>(t[5].as_int());
        h.phase = static_cast<uint8_t>(t[6].as_int());
        h.has_task = !t[7].is_null();
        h.task_id = h.has_task ? t[7].as_int() : 0;
        h.pickup = static_cast<int32_t>(t[8].as_int());
        h.delivery = static_cast<int32_t>(t[9].as_int());
        handoffs.push_back(std::move(h));
      }
      auto rec = ledger_enc.encode_tick(
          j["plan"].as_int(), j["world_seq"].as_int(), j["next"].as_int(),
          tasks, world, handoffs);
      if (!rec) {
        printf("null\n");
        continue;
      }
      printf("%s\n", codec::b64_encode(ha::encode_ledger(*rec)).c_str());
      continue;
    }
    if (mode == "--ledger-decode") {
      auto raw = codec::b64_decode(line);
      std::optional<ha::LedgerRec> rec;
      if (raw) rec = ha::decode_ledger(*raw);
      if (!rec) {
        printf("null\n");
        continue;
      }
      Json tasks;
      for (const auto& t : rec->tasks) {
        Json e;
        e.push_back(Json(t.task_id));
        e.push_back(Json(static_cast<int64_t>(t.state)));
        e.push_back(Json(static_cast<int64_t>(t.pickup)));
        e.push_back(Json(static_cast<int64_t>(t.delivery)));
        e.push_back(Json(t.peer));
        tasks.push_back(e);
      }
      if (tasks.is_null()) tasks = Json(JsonArray{});
      Json removed;
      for (int64_t tid : rec->removed) removed.push_back(Json(tid));
      if (removed.is_null()) removed = Json(JsonArray{});
      Json world;
      for (const auto& [c, bl] : rec->world) {
        Json e;
        e.push_back(Json(static_cast<int64_t>(c)));
        e.push_back(Json(static_cast<int64_t>(bl)));
        world.push_back(e);
      }
      if (world.is_null()) world = Json(JsonArray{});
      Json hoffs;
      for (const auto& h : rec->handoffs) {
        Json e;
        e.push_back(Json(static_cast<int64_t>(h.dst)));
        e.push_back(Json(h.seq));
        e.push_back(Json(h.epoch));
        e.push_back(Json(h.peer));
        e.push_back(Json(static_cast<int64_t>(h.pos)));
        e.push_back(Json(static_cast<int64_t>(h.goal)));
        e.push_back(Json(static_cast<int64_t>(h.phase)));
        e.push_back(h.has_task ? Json(h.task_id) : Json());
        e.push_back(Json(static_cast<int64_t>(h.pickup)));
        e.push_back(Json(static_cast<int64_t>(h.delivery)));
        hoffs.push_back(e);
      }
      if (hoffs.is_null()) hoffs = Json(JsonArray{});
      Json out;
      out.set("seq", rec->seq)
          .set("base_seq", rec->base_seq)
          .set("inc", rec->incarnation)
          .set("plan", rec->plan_seq)
          .set("world_seq", rec->world_seq)
          .set("next", rec->next_task_id)
          .set("snapshot", rec->snapshot)
          .set("tasks", tasks)
          .set("removed", removed)
          .set("world", world)
          .set("handoffs", hoffs)
          .set("ledger_digest", audit::digest_hex(rec->ledger_digest))
          .set("view_digest", audit::digest_hex(rec->view_digest));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    if (mode == "--decode") {
      auto pkt = codec::decode_b64(line);
      if (!pkt) {
        printf("null\n");
        continue;
      }
      Json names;
      for (const auto& n : pkt->names) names.push_back(Json(n));
      if (names.is_null()) names = Json(JsonArray{});
      Json out;
      out.set("kind", static_cast<int64_t>(pkt->kind))
          .set("seq", pkt->seq)
          .set("base_seq", pkt->base_seq)
          .set("idx", i32_array(pkt->idx))
          .set("pos", i32_array(pkt->pos))
          .set("goal", i32_array(pkt->goal))
          .set("removed", i32_array(pkt->removed))
          .set("named_idx", i32_array(pkt->named_idx))
          .set("names", names)
          .set("trace", trace_json(pkt->has_trace, pkt->trace));
      printf("%s\n", out.dump().c_str());
      continue;
    }
    auto parsed = Json::parse(line);
    if (!parsed || !parsed->is_object()) {
      fprintf(stderr, "codec_golden: bad script line\n");
      return 1;
    }
    const Json& j = *parsed;
    if (!enc_configured && j.has("snapshot_every")) {
      enc = codec::PackedFleetEncoder(
          static_cast<int>(j["snapshot_every"].as_int()));
    }
    enc_configured = true;
    if (j["force_snapshot"].as_bool()) enc.request_snapshot();
    std::vector<std::tuple<std::string, int32_t, int32_t>> fleet;
    for (const auto& e : j["fleet"].as_array()) {
      const auto& t = e.as_array();
      if (t.size() != 3) {
        fprintf(stderr, "codec_golden: fleet entry needs [peer,pos,goal]\n");
        return 1;
      }
      fleet.emplace_back(t[0].as_str(), static_cast<int32_t>(t[1].as_int()),
                         static_cast<int32_t>(t[2].as_int()));
    }
    codec::Packet pkt = enc.encode_tick(j["seq"].as_int(), fleet);
    codec::TraceCtx tc;
    if (parse_trace(j, &tc)) {
      pkt.has_trace = true;
      pkt.trace = tc;
    }
    printf("%s\n", codec::encode_b64(pkt).c_str());
  }
  fflush(stdout);
  return 0;
}
