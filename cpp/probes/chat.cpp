// mapd_chat — interactive bus probe (SURVEY C13).
//
// Capability equivalent of the reference's two broadcast demos: `chat`
// (gossipsub + mDNS stdin chat on topic "test-net",
// src/test/libp2p/chat.rs:24-116) and `sns` (serialized Post broadcast on
// topic "sns", src/test/libp2p/sns.rs:21-127).  Lines typed on stdin are
// broadcast to every peer on the topic; `/post <text>` sends an sns-style
// structured post {author, content, timestamp} instead of a plain line.
// Peer join/leave events print as they arrive — the manual integration
// probe for discovery + pub/sub fanout, exactly what the reference used its
// demos for.
//
// Usage: mapd_chat [--port P] [--topic test-net] [--name NAME]

#include <poll.h>
#include <signal.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>

#include "../common/bus.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"

using namespace mapd;

namespace {
volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  const std::string host = knobs.get_str("--host", "MAPD_BUS_HOST",
                                         "127.0.0.1");
  const uint16_t port = static_cast<uint16_t>(
      knobs.get_int("--port", "MAPD_BUS_PORT", 7400));
  // the reference's chat demo topic (chat.rs:58)
  const std::string topic = knobs.get_str("--topic", "", "test-net");
  std::string name = knobs.get_str("--name", "", "");

  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);

  BusClient bus;
  std::string my_id = random_peer_id();
  if (!name.empty()) my_id = name;
  if (!bus.connect(host, port, my_id)) {
    fprintf(stderr, "cannot connect to bus on port %u\n", port);
    return 1;
  }
  bus.subscribe(topic);
  log_info("💬 chat probe %s on topic \"%s\" — type to broadcast, "
           "/post <text> for an sns-style post, /quit to exit\n",
           my_id.c_str(), topic.c_str());

  std::string stdin_buf;
  bool running = true;
  while (running && !g_stop && bus.connected()) {
    pollfd pfds[2] = {
        {bus.fd(),
         static_cast<short>(POLLIN | (bus.wants_write() ? POLLOUT : 0)), 0},
        {STDIN_FILENO, POLLIN, 0}};
    poll(pfds, 2, 200);

    if (pfds[1].revents & POLLIN) {
      char buf[4096];
      ssize_t r = read(STDIN_FILENO, buf, sizeof(buf));
      if (r > 0) {
        stdin_buf.append(buf, static_cast<size_t>(r));
        size_t nl;
        while ((nl = stdin_buf.find('\n')) != std::string::npos) {
          std::string line = stdin_buf.substr(0, nl);
          stdin_buf.erase(0, nl + 1);
          if (line == "/quit" || line == "/exit") {
            running = false;
            break;
          }
          Json m;
          if (line.rfind("/post ", 0) == 0) {
            // sns Post shape (sns.rs Post {author, content, timestamp})
            m.set("type", "post")
                .set("author", my_id)
                .set("content", line.substr(6))
                .set("timestamp", unix_ms());
          } else if (!line.empty()) {
            m.set("type", "chat").set("from", my_id).set("text", line);
          } else {
            continue;
          }
          bus.publish(topic, m);
        }
      } else if (r == 0) {
        running = false;
      }
    }

    bool alive = bus.pump(
        [&](const BusClient::Msg& msg) {
          const Json& d = msg.data;
          // received messages are the probe's product output, not
          // diagnostics: always print, independent of --log-level
          if (d["type"].as_str() == "post")
            printf("📝 [%s] %s\n", d["author"].as_str().c_str(),
                   d["content"].as_str().c_str());
          else if (d["type"].as_str() == "chat")
            printf("💬 <%s> %s\n", d["from"].as_str().c_str(),
                   d["text"].as_str().c_str());
          else
            printf("📦 %s\n", d.dump().c_str());
          fflush(stdout);
                },
        [&](const Json& ev) {
          const std::string& op = ev["op"].as_str();
          if (op == "peer_joined")
            log_info("🔍 peer joined: %s\n", ev["peer_id"].as_str().c_str());
          else if (op == "peer_left")
            log_info("👋 peer left: %s\n", ev["peer_id"].as_str().c_str());
                });
    if (!alive) break;
  }
  // Drain buffered publishes before closing: a "/quit" arriving in the
  // same stdin burst as the lines before it would otherwise race the
  // nonblocking socket and drop those messages on the floor.
  int64_t drain_deadline = mono_ms() + 1000;
  while (bus.wants_write() && mono_ms() < drain_deadline) {
    pollfd p{bus.fd(), POLLOUT, 0};
    poll(&p, 1, 100);
    if (!bus.flush()) break;
  }
  log_info("chat: bye\n");
  bus.close();
  return 0;
}
