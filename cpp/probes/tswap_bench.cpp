// mapd_tswap_bench — native planning-time probe for the TPU crossover sweep.
//
// Times the centralized manager's native planning step (cpp/common/
// tswap.hpp tswap_step — the semantic transcription of the reference's
// tswap_step, src/algorithm/tswap.rs:174-286) at a given agent count in
// STEADY STATE: distance fields pre-warmed and never trimmed (the most
// flattering setup for the native path — the fleet's manager trims its
// cache at 512 fields and would also pay BFS recomputes), agents that
// arrive get a fresh goal from a bounded pool so the scan keeps running
// against live traffic.  The occupant scan makes the step O(N^2)
// (occupant_of is a linear scan per hop, tswap.hpp:33-38) — this probe
// measures where that crosses the 500 ms planning tick, the wall the
// reference hit at ~180 ms / 50 agents (manager.rs:564-567) and the
// regime the TPU solver daemon exists for (analysis/crossover_sweep.py
// pairs these numbers with solverd latencies).
//
// Usage: mapd_tswap_bench --agents N [--side S] [--iters K] [--seed X]
// Prints one JSON line.

#include <chrono>
#include <cstdio>
#include <random>
#include <set>
#include <string>
#include <vector>

#include "../common/grid.hpp"
#include "../common/knobs.hpp"
#include "../common/tswap.hpp"

using namespace mapd;

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  const int n = static_cast<int>(knobs.get_int("--agents", "MAPD_AGENTS", 50));
  const int side = static_cast<int>(knobs.get_int("--side", "MAPD_SIDE", 256));
  const int iters = static_cast<int>(knobs.get_int("--iters", "MAPD_ITERS", 20));
  const uint64_t seed = static_cast<uint64_t>(
      knobs.get_int("--seed", "MAPD_SEED", 0));

  Grid grid;
  grid.width = grid.height = side;
  grid.free.assign(static_cast<size_t>(side) * side, 1);
  DistanceCache dc(grid);
  std::mt19937_64 rng(seed);

  // Distinct random starts; goals from a bounded pool (2N cells) so the
  // field cache is finite and fully warm after the first pass.
  auto cells = grid.free_cells();
  for (size_t i = cells.size() - 1; i > 0; --i)
    std::swap(cells[i], cells[rng() % (i + 1)]);
  if (static_cast<size_t>(n) >= cells.size()) {
    fprintf(stderr, "need at least one non-start free cell for goals\n");
    return 1;
  }
  std::vector<Cell> goal_pool(cells.begin() + n,
                              cells.begin() + n + std::min<size_t>(
                                  2 * n, cells.size() - n));
  std::vector<TswapAgent> agents(n);
  for (int i = 0; i < n; ++i)
    agents[i] = TswapAgent{i, cells[i], goal_pool[rng() % goal_pool.size()]};

  // Warm every field the pool can produce (steady-state cache).
  for (Cell g : goal_pool) dc.next_hop(0, g);
  tswap_step(agents, dc);  // untimed warm step

  double total_ms = 0, max_ms = 0;
  for (int k = 0; k < iters; ++k) {
    auto t0 = std::chrono::steady_clock::now();
    tswap_step(agents, dc);
    double ms = std::chrono::duration_cast<std::chrono::microseconds>(
                    std::chrono::steady_clock::now() - t0).count() / 1000.0;
    total_ms += ms;
    max_ms = ms > max_ms ? ms : max_ms;
    for (auto& a : agents)  // arrivals pick new work (steady-state churn)
      if (a.v == a.g) a.g = goal_pool[rng() % goal_pool.size()];
  }
  printf("{\"agents\": %d, \"side\": %d, \"iters\": %d, "
         "\"ms_per_step_avg\": %.3f, \"ms_per_step_max\": %.3f}\n",
         n, side, iters, total_ms / iters, max_ms);
  return 0;
}
