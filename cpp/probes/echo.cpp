// mapd_echo — self-validating echo probe (SURVEY C13).
//
// Capability equivalent of the reference's `stream` demo
// (src/test/libp2p/stream.rs:11-157): a QUIC echo protocol where the client
// sends a random payload and byte-verifies the echo (stream.rs:139-156).
// Here the transport under test is the host bus: the server role echoes
// every payload back on the topic; the client role sends N random hex
// payloads, verifies each echo byte-for-byte, and exits 0 only if all
// round-trips validate — an automatable smoke test of bus connect /
// subscribe / fanout / framing.
//
// Usage: mapd_echo --server [--port P] [--topic echo]
//        mapd_echo --client [--port P] [--topic echo] [--count 5]
//                  [--bytes 64] [--seed S]

#include <poll.h>
#include <signal.h>

#include <cstdio>
#include <cstring>
#include <random>
#include <string>

#include "../common/bus.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"

using namespace mapd;

namespace {
volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

std::string random_hex(std::mt19937_64& rng, size_t nbytes) {
  static const char* hexd = "0123456789abcdef";
  std::string s;
  s.reserve(nbytes * 2);
  for (size_t i = 0; i < nbytes; ++i) {
    uint8_t b = static_cast<uint8_t>(rng());
    s += hexd[b >> 4];
    s += hexd[b & 0xF];
  }
  return s;
}
}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  const std::string host = knobs.get_str("--host", "MAPD_BUS_HOST",
                                         "127.0.0.1");
  const uint16_t port = static_cast<uint16_t>(
      knobs.get_int("--port", "MAPD_BUS_PORT", 7400));
  const std::string topic = knobs.get_str("--topic", "", "echo");
  const bool server = knobs.get_bool("--server", "");
  const bool client = knobs.get_bool("--client", "");
  const int count = static_cast<int>(knobs.get_int("--count", "", 5));
  const size_t nbytes = static_cast<size_t>(
      knobs.get_int("--bytes", "", 64));  // ref stream.rs: random payloads
  const uint64_t seed = static_cast<uint64_t>(knobs.get_int(
      "--seed", "", static_cast<int64_t>(std::random_device{}())));
  if (server == client) {
    fprintf(stderr, "usage: mapd_echo --server | --client [--count N]\n");
    return 2;
  }

  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);

  BusClient bus;
  std::string my_id = random_peer_id();
  if (!bus.connect(host, port, my_id)) {
    fprintf(stderr, "cannot connect to bus on port %u\n", port);
    return 1;
  }
  bus.subscribe(topic);

  if (server) {
    log_info("🔁 echo server %s on topic \"%s\"\n", my_id.c_str(),
             topic.c_str());
      while (!g_stop && bus.connected()) {
      pollfd pfd{bus.fd(),
                 static_cast<short>(POLLIN | (bus.wants_write() ? POLLOUT : 0)),
                 0};
      poll(&pfd, 1, 200);
      if (!bus.pump(
              [&](const BusClient::Msg& m) {
                if (m.data["type"].as_str() != "echo_request") return;
                Json r;
                r.set("type", "echo_response")
                    .set("to", m.data["from"])
                    .set("nonce", m.data["nonce"])
                    .set("payload", m.data["payload"]);
                bus.publish(topic, r);
              },
              [](const Json&) {}))
        break;
    }
    bus.close();
    return 0;
  }

  // client: send `count` random payloads, verify each echo byte-for-byte
  // (the reference's self-validation, stream.rs:139-156)
  std::mt19937_64 rng(seed);
  int ok = 0;
  for (int k = 0; k < count && !g_stop; ++k) {
    const std::string payload = random_hex(rng, nbytes);
    const int64_t nonce = k + 1;
    Json req;
    req.set("type", "echo_request")
        .set("from", my_id)
        .set("nonce", nonce)
        .set("payload", payload);
    bus.publish(topic, req);

    bool verified = false;
    int64_t deadline = mono_ms() + 5000;
    while (!verified && !g_stop && mono_ms() < deadline && bus.connected()) {
      pollfd pfd{bus.fd(),
                 static_cast<short>(POLLIN | (bus.wants_write() ? POLLOUT : 0)),
                 0};
      poll(&pfd, 1, 100);
      if (!bus.pump(
              [&](const BusClient::Msg& m) {
                if (m.data["type"].as_str() != "echo_response") return;
                if (m.data["to"].as_str() != my_id) return;
                if (m.data["nonce"].as_int() != nonce) return;
                if (m.data["payload"].as_str() == payload) {
                  verified = true;
                } else {
                  fprintf(stderr, "❌ payload mismatch on nonce %lld\n",
                          static_cast<long long>(nonce));
                }
              },
              [](const Json&) {}))
        break;
    }
    if (verified) {
      ++ok;
      printf("✅ echo %d/%d verified (%zu bytes)\n", k + 1, count,
             payload.size());
    } else {
      log_warn("❌ echo %d/%d FAILED (timeout or mismatch)\n", k + 1, count);
    }
    }
  bus.close();
  printf("echo client: %d/%d verified\n", ok, count);
  return ok == count ? 0 : 1;
}
