// mapd_tswap_trace — golden-trace harness for the native sequential TSWAP.
//
// Reads one JSON instance from stdin:
//   {"map": "....\n.@..\n....", "v": [c0, c1, ...], "g": [c0, c1, ...],
//    "steps": N}
// (v/g are flat cell indices), runs N sequential tswap_step calls
// (cpp/common/tswap.hpp — the solver behind the centralized manager's
// --solver=cpu mode), and prints one JSON line per step:
//   {"v": [...], "g": [...]}
//
// tests/test_tswap_trace.py feeds scripted instances (Rule-3 swaps, Rule-4
// cycles, the push extension) and asserts the traces are IDENTICAL to the
// Python oracle's tswap_step — the two independent transcriptions of the
// reference's sequential semantics must agree exactly.

#include <cstdio>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include "../common/grid.hpp"
#include "../common/json.hpp"
#include "../common/tswap.hpp"

using namespace mapd;

int main() {
  std::stringstream buf;
  buf << std::cin.rdbuf();
  auto parsed = Json::parse(buf.str());
  if (!parsed) {
    fprintf(stderr, "tswap_trace: cannot parse instance JSON\n");
    return 2;
  }
  const Json& in = *parsed;
  auto grid_opt = Grid::from_ascii(in["map"].as_str());
  if (!grid_opt) {
    fprintf(stderr, "tswap_trace: bad map\n");
    return 2;
  }
  Grid grid = *grid_opt;
  DistanceCache dc(grid);

  std::vector<TswapAgent> agents;
  const auto& vs = in["v"].as_array();
  const auto& gs = in["g"].as_array();
  if (vs.size() != gs.size() || vs.empty()) {
    fprintf(stderr, "tswap_trace: v/g size mismatch\n");
    return 2;
  }
  for (size_t i = 0; i < vs.size(); ++i)
    agents.push_back(TswapAgent{static_cast<int>(i),
                                static_cast<Cell>(vs[i].as_int()),
                                static_cast<Cell>(gs[i].as_int())});

  int64_t steps = in["steps"].as_int();
  for (int64_t t = 0; t < steps; ++t) {
    tswap_step(agents, dc);
    Json v, g;
    for (const auto& a : agents) {
      v.push_back(Json(static_cast<int64_t>(a.v)));
      g.push_back(Json(static_cast<int64_t>(a.g)));
    }
    Json line;
    line.set("v", v).set("g", g);
    printf("%s\n", line.dump().c_str());
  }
  return 0;
}
