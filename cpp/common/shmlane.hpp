// Zero-copy same-host bus lanes — C++ mirror of runtime/shmlane.py.
//
// Layout-identical to the python side (see shmlane.py's header comment for
// the byte map): one mapped file per (peer, busd-shard) pair holding a c2s
// and an s2c SPSC ring of fixed-size slots that carry the exact fast-path
// `P`/`M` relay lines (no trailing newline).  Cursors are 8-byte words
// accessed through std::atomic_ref-equivalent volatile+fence discipline
// (plain __atomic builtins on the mapped words — both targets are
// little-endian with 8-byte atomic loads/stores).  Doorbells are named
// FIFOs next to the lane file; the reader parks by setting its `parked`
// word, re-checking the ring, then blocking in poll(2) on the FIFO.
//
// Contract (ISSUE 18): push() never blocks — a full ring or oversized
// frame returns false and the caller sends that frame over TCP
// (bus.shm_fallbacks).  Only droppable-class topics ride the lane, so the
// rare TCP/ring interleave cannot reorder the control plane.

#pragma once

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <fcntl.h>
#include <signal.h>
#include <unistd.h>

namespace mapd {
namespace shm {

constexpr uint32_t kMagic = 0x314C4853;  // "SHL1"
constexpr uint16_t kVersion = 1;
constexpr size_t kHeaderBytes = 4096;

// header field offsets (byte-identical to shmlane.py)
constexpr size_t kOffMagic = 0;
constexpr size_t kOffVersion = 4;
constexpr size_t kOffSlotSize = 8;
constexpr size_t kOffNSlots = 12;
constexpr size_t kOffCreatorPid = 16;
constexpr size_t kOffAttachedPid = 20;
constexpr size_t kOffDetached = 24;
// per-ring control offsets: {head, tail, parked}
constexpr size_t kRingCtrl[2][3] = {{64, 128, 192}, {256, 320, 384}};

inline size_t round_up(size_t n, size_t align) {
  return (n + align - 1) / align * align;
}

inline bool pid_alive(uint32_t pid) {
  if (pid == 0) return false;
  if (::kill((pid_t)pid, 0) == 0) return true;
  return errno == EPERM;
}

// --- atomic accessors on mapped memory -----------------------------------
inline uint64_t load_u64(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint64_t*>(p),
                         __ATOMIC_ACQUIRE);
}
inline void store_u64(uint8_t* p, uint64_t v) {
  __atomic_store_n(reinterpret_cast<uint64_t*>(p), v, __ATOMIC_RELEASE);
}
inline uint32_t load_u32(const uint8_t* p) {
  return __atomic_load_n(reinterpret_cast<const uint32_t*>(p),
                         __ATOMIC_ACQUIRE);
}
inline void store_u32(uint8_t* p, uint32_t v) {
  __atomic_store_n(reinterpret_cast<uint32_t*>(p), v, __ATOMIC_RELEASE);
}
inline uint32_t read_u32_plain(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline void write_u32_plain(uint8_t* p, uint32_t v) {
  std::memcpy(p, &v, 4);
}

// --- one SPSC ring over the mapping --------------------------------------
struct Ring {
  uint8_t* base = nullptr;   // mapping base
  size_t head_off = 0, tail_off = 0, parked_off = 0;
  size_t data_off = 0;
  uint32_t nslots = 0, slot_size = 0;
  size_t stride = 0;

  void init(uint8_t* b, const size_t ctrl[3], size_t data, uint32_t n,
            uint32_t ssz) {
    base = b;
    head_off = ctrl[0]; tail_off = ctrl[1]; parked_off = ctrl[2];
    data_off = data; nslots = n; slot_size = ssz;
    stride = round_up(4 + (size_t)ssz, 64);
  }
  uint64_t head() const { return load_u64(base + head_off); }
  uint64_t tail() const { return load_u64(base + tail_off); }
  bool empty() const { return tail() >= head(); }

  // writer: false = full/oversized, caller falls back to TCP
  bool push(const char* payload, size_t len) {
    if (len > slot_size) return false;
    uint64_t h = head();
    if (h - tail() >= nslots) return false;
    uint8_t* slot = base + data_off + (size_t)(h % nslots) * stride;
    std::memcpy(slot + 4, payload, len);
    write_u32_plain(slot, (uint32_t)len);
    store_u64(base + head_off, h + 1);  // release: publishes the slot
    return true;
  }
  // reader: false = empty
  bool pop(std::string* out) {
    uint64_t t = tail();
    if (t >= head()) return false;
    const uint8_t* slot = base + data_off + (size_t)(t % nslots) * stride;
    uint32_t n = read_u32_plain(slot);
    if (n > slot_size) n = slot_size;  // never trust beyond geometry
    out->assign(reinterpret_cast<const char*>(slot + 4), n);
    store_u64(base + tail_off, t + 1);
    return true;
  }

  // spin-then-park doorbell protocol (see shmlane.py)
  bool reader_park() {
    store_u32(base + parked_off, 1);
    if (!empty()) {  // lost-wakeup guard
      store_u32(base + parked_off, 0);
      return false;
    }
    return true;
  }
  void reader_unpark() { store_u32(base + parked_off, 0); }
  bool writer_should_ring() {
    if (load_u32(base + parked_off)) {
      store_u32(base + parked_off, 0);
      return true;
    }
    return false;
  }
};

inline size_t map_bytes(uint32_t slot_size, uint32_t nslots) {
  size_t stride = round_up(4 + (size_t)slot_size, 64);
  return kHeaderBytes + 2 * (size_t)nslots * stride;
}

// --- a mapped lane: the hub attaches, a client creates -------------------
struct Lane {
  uint8_t* base = nullptr;
  size_t map_len = 0;
  uint32_t slot_size = 0, nslots = 0;
  bool is_client = false;  // client role: tx=c2s, rx=s2c (hub: reversed)
  Ring rx;
  Ring tx;
  int bell_rx_fd = -1;   // our bell — a parked read blocks on this
  int bell_tx_fd = -1;   // the peer's bell (lazy open on first ring)
  std::string path;

  bool valid() const { return base != nullptr; }

  uint32_t creator_pid() const { return load_u32(base + kOffCreatorPid); }
  uint32_t attached_pid() const { return load_u32(base + kOffAttachedPid); }
  bool is_detached() const { return load_u32(base + kOffDetached) != 0; }
  void mark_detached() { store_u32(base + kOffDetached, 1); }
  bool peer_alive() const {
    const uint32_t pid = is_client ? attached_pid() : creator_pid();
    return pid == 0 || pid_alive(pid);  // 0: negotiation still in flight
  }

  const char* bell_rx_suffix() const {
    return is_client ? ".s2c.bell" : ".c2s.bell";
  }
  const char* bell_tx_suffix() const {
    return is_client ? ".c2s.bell" : ".s2c.bell";
  }

  // Client side: build (or rebuild) the lane file + doorbell FIFOs.  A
  // leftover same-name file (stale after a SIGKILL, or a prior session
  // of this peer id) is unlinked and rebuilt so the hub always attaches
  // clean cursors.
  static Lane create(const std::string& p, uint32_t slot_size,
                     uint32_t nslots, std::string* err) {
    Lane lane;
    if (nslots == 0 || (nslots & (nslots - 1))) {
      *err = "nslots not a power of two";
      return lane;
    }
    ::unlink(p.c_str());
    ::unlink((p + ".c2s.bell").c_str());
    ::unlink((p + ".s2c.bell").c_str());
    if (::mkfifo((p + ".c2s.bell").c_str(), 0600) != 0 ||
        ::mkfifo((p + ".s2c.bell").c_str(), 0600) != 0) {
      *err = "mkfifo failed";
      return lane;
    }
    const size_t size = map_bytes(slot_size, nslots);
    int fd = ::open(p.c_str(), O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) { *err = "lane create failed"; return lane; }
    if (::ftruncate(fd, (off_t)size) != 0) {
      ::close(fd);
      *err = "lane ftruncate failed";
      return lane;
    }
    void* mp = ::mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED,
                      fd, 0);
    ::close(fd);
    if (mp == MAP_FAILED) { *err = "lane mmap failed"; return lane; }
    uint8_t* b = static_cast<uint8_t*>(mp);
    write_u32_plain(b + kOffMagic, kMagic);
    uint16_t ver = kVersion;
    std::memcpy(b + kOffVersion, &ver, 2);
    write_u32_plain(b + kOffSlotSize, slot_size);
    write_u32_plain(b + kOffNSlots, nslots);
    write_u32_plain(b + kOffCreatorPid, (uint32_t)::getpid());
    lane.base = b;
    lane.map_len = size;
    lane.slot_size = slot_size;
    lane.nslots = nslots;
    lane.is_client = true;
    lane.path = p;
    const size_t stride = round_up(4 + (size_t)slot_size, 64);
    lane.tx.init(b, kRingCtrl[0], kHeaderBytes, nslots, slot_size);
    lane.rx.init(b, kRingCtrl[1], kHeaderBytes + (size_t)nslots * stride,
                 nslots, slot_size);
    lane.bell_rx_fd = ::open((p + lane.bell_rx_suffix()).c_str(),
                             O_RDONLY | O_NONBLOCK);
    err->clear();
    return lane;
  }

  // hub attach: validate header, map, record our pid.  Empty-path errors
  // only — a malformed offer must never crash or half-attach busd.
  static Lane attach(const std::string& p, std::string* err) {
    Lane lane;
    struct stat st{};
    if (::stat(p.c_str(), &st) != 0 || !S_ISREG(st.st_mode)) {
      *err = "lane not a regular file: " + p;
      return lane;
    }
    if ((size_t)st.st_size < kHeaderBytes) {
      *err = "lane too short";
      return lane;
    }
    int fd = ::open(p.c_str(), O_RDWR);
    if (fd < 0) { *err = "lane open failed"; return lane; }
    void* mp = ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                      MAP_SHARED, fd, 0);
    ::close(fd);
    if (mp == MAP_FAILED) { *err = "lane mmap failed"; return lane; }
    uint8_t* b = static_cast<uint8_t*>(mp);
    uint32_t magic = read_u32_plain(b + kOffMagic);
    uint16_t version; std::memcpy(&version, b + kOffVersion, 2);
    uint32_t ssz = read_u32_plain(b + kOffSlotSize);
    uint32_t n = read_u32_plain(b + kOffNSlots);
    if (magic != kMagic || version != kVersion || ssz == 0 ||
        ssz > (1u << 20) || n == 0 || n > (1u << 16) || (n & (n - 1)) ||
        (size_t)st.st_size < map_bytes(ssz, n)) {
      ::munmap(mp, (size_t)st.st_size);
      *err = "bad lane header";
      return lane;
    }
    lane.base = b;
    lane.map_len = (size_t)st.st_size;
    lane.slot_size = ssz;
    lane.nslots = n;
    lane.path = p;
    size_t stride = round_up(4 + (size_t)ssz, 64);
    lane.rx.init(b, kRingCtrl[0], kHeaderBytes, n, ssz);
    lane.tx.init(b, kRingCtrl[1], kHeaderBytes + (size_t)n * stride, n, ssz);
    store_u32(b + kOffAttachedPid, (uint32_t)::getpid());
    // our bell (c2s): clients create both FIFOs before the hello
    lane.bell_rx_fd =
        ::open((p + ".c2s.bell").c_str(), O_RDONLY | O_NONBLOCK);
    err->clear();
    return lane;
  }

  // push one frame toward the client; rings its doorbell if parked.
  // false = caller must deliver over TCP.
  bool send(const char* payload, size_t len) {
    if (!valid() || is_detached()) return false;
    if (!tx.push(payload, len)) return false;
    if (tx.writer_should_ring()) ring_bell();
    return true;
  }
  bool recv(std::string* out) { return valid() && rx.pop(out); }
  bool rx_pending() const { return valid() && !rx.empty(); }

  void ring_bell() {
    if (bell_tx_fd < 0) {
      bell_tx_fd = ::open((path + bell_tx_suffix()).c_str(),
                          O_WRONLY | O_NONBLOCK);
      if (bell_tx_fd < 0) return;  // reader side not open: not parked
    }
    char b = 'x';
    if (::write(bell_tx_fd, &b, 1) < 0 &&
        (errno == EPIPE || errno == ENXIO)) {
      ::close(bell_tx_fd);
      bell_tx_fd = -1;
    }
  }

  void drain_bell() {
    if (bell_rx_fd < 0) return;
    char buf[256];
    while (::read(bell_rx_fd, buf, sizeof buf) > 0) {}
  }

  void close_lane(bool unlink_files) {
    if (bell_rx_fd >= 0) { ::close(bell_rx_fd); bell_rx_fd = -1; }
    if (bell_tx_fd >= 0) { ::close(bell_tx_fd); bell_tx_fd = -1; }
    if (base) {
      ::munmap(base, map_len);
      base = nullptr;
    }
    if (unlink_files && !path.empty()) {
      ::unlink(path.c_str());
      ::unlink((path + ".c2s.bell").c_str());
      ::unlink((path + ".s2c.bell").c_str());
    }
  }
};

// Lanes are OPT-IN: JG_BUS_SHM unset/0/false keeps the wire byte-identical.
inline bool shm_enabled_env() {
  const char* v = std::getenv("JG_BUS_SHM");
  if (!v) return false;
  std::string s(v);
  return !s.empty() && s != "0" && s != "false";
}

// Lane files live under JG_BUS_SHM_DIR (the fleet runner points it at the
// run dir) or a per-uid tmp subdir — byte-for-byte the python lane_dir().
inline std::string lane_dir() {
  const char* v = std::getenv("JG_BUS_SHM_DIR");
  std::string d = (v && *v) ? std::string(v)
                            : std::string("/tmp/jg_shm_") +
                                  std::to_string(::getuid());
  ::mkdir(d.c_str(), 0777);  // best-effort; create_lane errors if unusable
  return d;
}

// Canonical lane path for a (peer, busd-shard) pair (= py lane_path_for).
inline std::string lane_path_for(const std::string& peer_id, int shard,
                                 const std::string& dir) {
  std::string safe;
  for (char ch : peer_id) {
    if (safe.size() >= 80) break;
    const bool ok = (ch >= 'a' && ch <= 'z') || (ch >= 'A' && ch <= 'Z') ||
                    (ch >= '0' && ch <= '9') || ch == '-' || ch == '_' ||
                    ch == '.';
    safe += ok ? ch : '_';
  }
  return dir + "/" + safe + "-s" + std::to_string(shard) + ".shl";
}

}  // namespace shm
}  // namespace mapd
