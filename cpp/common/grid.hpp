// Grid world + BFS pathfinding for the host runtime (native twin of
// p2p_distributed_tswap_tpu/core/grid.py and ops/distance.py, providing the
// capability of the reference's src/map/map.rs + per-binary parse_map /
// graph building — collapsed into ONE implementation, fixing the
// duplication SURVEY flags).
#pragma once

#include <cstdint>
#include <deque>
#include <fstream>
#include <optional>
#include <random>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace mapd {

using Cell = int32_t;  // flat row-major index, y * width + x
constexpr int32_t kDistInf = 1 << 30;

// Neighbor order of the reference (src/algorithm/tswap.rs:62): (dx, dy).
constexpr int kDirDx[4] = {0, 1, 0, -1};
constexpr int kDirDy[4] = {1, 0, -1, 0};

class Grid {
 public:
  int width = 0, height = 0;
  std::vector<uint8_t> free;  // 1 = traversable

  static Grid default_grid() {  // reference 100x100 all-free map
    Grid g;
    g.width = g.height = 100;
    g.free.assign(static_cast<size_t>(g.width) * g.height, 1);
    return g;
  }

  // '.'/'@' rows; blank lines skipped (same convention as parse_map).
  static std::optional<Grid> from_ascii(const std::string& text) {
    Grid g;
    std::istringstream in(text);
    std::string line;
    while (std::getline(in, line)) {
      if (!line.empty() && line.back() == '\r') line.pop_back();
      if (line.empty()) continue;
      if (g.width == 0) g.width = static_cast<int>(line.size());
      if (static_cast<int>(line.size()) != g.width) return std::nullopt;
      for (char c : line) g.free.push_back(c == '@' ? 0 : 1);
      ++g.height;
    }
    if (g.width == 0) return std::nullopt;
    return g;
  }

  static std::optional<Grid> from_file(const std::string& path) {
    std::ifstream in(path);
    if (!in) return std::nullopt;
    std::stringstream ss;
    ss << in.rdbuf();
    std::string text = ss.str();
    if (text.rfind("type", 0) == 0) {  // movingai .map header
      std::istringstream hin(text);
      std::string l, tok;
      int h = 0, w = 0;
      std::getline(hin, l);  // type ...
      hin >> tok >> h >> tok >> w;
      std::getline(hin, l);  // rest of width line
      std::getline(hin, l);  // "map"
      Grid g;
      g.width = w;
      g.height = h;
      g.free.assign(static_cast<size_t>(w) * h, 0);
      for (int y = 0; y < h && std::getline(hin, l); ++y)
        for (int x = 0; x < w && x < static_cast<int>(l.size()); ++x)
          g.free[static_cast<size_t>(y) * w + x] =
              (l[x] == '.' || l[x] == 'G' || l[x] == 'S') ? 1 : 0;
      return g;
    }
    return from_ascii(text);
  }

  size_t num_cells() const { return free.size(); }
  bool is_free(Cell c) const {
    return c >= 0 && c < static_cast<Cell>(free.size()) && free[c];
  }
  int x_of(Cell c) const { return c % width; }
  int y_of(Cell c) const { return c / width; }
  Cell cell(int x, int y) const { return y * width + x; }
  bool in_bounds(int x, int y) const {
    return x >= 0 && x < width && y >= 0 && y < height;
  }

  std::vector<Cell> free_cells() const {
    std::vector<Cell> out;
    for (Cell c = 0; c < static_cast<Cell>(free.size()); ++c)
      if (free[c]) out.push_back(c);
    return out;
  }

  Cell random_free_cell(std::mt19937_64& rng) const {
    auto cells = free_cells();
    return cells[rng() % cells.size()];
  }

  int manhattan(Cell a, Cell b) const {
    return std::abs(x_of(a) - x_of(b)) + std::abs(y_of(a) - y_of(b));
  }
};

// BFS distance fields from goals, memoized per goal (the native analog of
// the TPU direction-field cache; goals persist across many steps).
class DistanceCache {
 public:
  explicit DistanceCache(const Grid& grid) : grid_(grid) {}

  const std::vector<int32_t>& field(Cell goal) {
    auto it = cache_.find(goal);
    if (it != cache_.end()) return it->second;
    std::vector<int32_t> dist(grid_.num_cells(), kDistInf);
    if (grid_.is_free(goal)) {
      dist[goal] = 0;
      std::deque<Cell> q{goal};
      while (!q.empty()) {
        Cell c = q.front();
        q.pop_front();
        int cx = grid_.x_of(c), cy = grid_.y_of(c);
        for (int d = 0; d < 4; ++d) {
          int nx = cx + kDirDx[d], ny = cy + kDirDy[d];
          if (!grid_.in_bounds(nx, ny)) continue;
          Cell nc = grid_.cell(nx, ny);
          if (grid_.free[nc] && dist[nc] > dist[c] + 1) {
            dist[nc] = dist[c] + 1;
            q.push_back(nc);
          }
        }
      }
    }
    auto [ins, _] = cache_.emplace(goal, std::move(dist));
    return ins->second;
  }

  // First cell after `from` on a shortest path to `goal`; nullopt when at
  // goal or unreachable.  Tie-break: first minimum in reference neighbor
  // order — matches the Python oracle and the TPU direction fields.
  std::optional<Cell> next_hop(Cell from, Cell goal) {
    if (from == goal) return std::nullopt;
    const auto& dist = field(goal);
    if (dist[from] >= kDistInf) return std::nullopt;
    int fx = grid_.x_of(from), fy = grid_.y_of(from);
    int32_t best = dist[from];
    std::optional<Cell> out;
    for (int d = 0; d < 4; ++d) {
      int nx = fx + kDirDx[d], ny = fy + kDirDy[d];
      if (!grid_.in_bounds(nx, ny)) continue;
      Cell nc = grid_.cell(nx, ny);
      if (dist[nc] < best) {
        best = dist[nc];
        out = nc;
      }
    }
    return out;
  }

  void clear() { cache_.clear(); }
  size_t size() const { return cache_.size(); }
  // Bound memory like the reference bounds its caches (SURVEY §5): drop all
  // when over budget (goals churn slowly; refill is cheap).
  void trim(size_t max_fields) {
    if (cache_.size() > max_fields) cache_.clear();
  }

 private:
  const Grid& grid_;
  std::unordered_map<Cell, std::vector<int32_t>> cache_;
};

}  // namespace mapd
