// Native metrics: task lifecycle, path computation, network counters —
// behavior and CSV-schema parity with the reference's src/map/task_metrics.rs
// (SURVEY C11) and with the Python twin
// (p2p_distributed_tswap_tpu/metrics/task_metrics.py); the pandas analysis
// layer consumes either side's CSVs unchanged.
//
// Also home of MetricsRegistry: the native mirror of the unified
// live-metrics registry (p2p_distributed_tswap_tpu/obs/registry.py) —
// counters / gauges / fixed-bucket histograms keyed by the same flat
// Prometheus-style strings, with the same snapshot JSON schema, so the
// metrics beacons this side publishes (cpp/common/bus.hpp
// enable_metrics_beacon) merge into one fleet rollup with the Python
// processes' (obs/fleet_aggregator.py, analysis/fleet_top.py).
#pragma once

#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cstdint>
#include <map>
#include <mutex>
#include <numeric>
#include <optional>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "json.hpp"

namespace mapd {

enum class TaskStatus { Pending, Sent, Received, Running, Completed, Failed };

inline const char* task_status_str(TaskStatus s) {
  switch (s) {
    case TaskStatus::Pending: return "pending";
    case TaskStatus::Sent: return "sent";
    case TaskStatus::Received: return "received";
    case TaskStatus::Running: return "running";
    case TaskStatus::Completed: return "completed";
    case TaskStatus::Failed: return "failed";
  }
  return "?";
}

struct TaskMetric {
  uint64_t task_id = 0;
  std::string peer_id;
  int64_t sent_time = 0;  // unix ms
  std::optional<int64_t> received_time;
  std::optional<int64_t> start_time;
  std::optional<int64_t> completion_time;
  TaskStatus status = TaskStatus::Sent;

  // Clamped to >= 0 like the Python twin: the subtracted stamps come from
  // DIFFERENT peers' wall clocks (manager sent vs agent started/completed),
  // and skew must not produce negative latencies.  The collector counts
  // occurrences (clock_skew_events).
  std::optional<int64_t> total_time() const {
    if (!completion_time) return std::nullopt;
    return std::max<int64_t>(0, *completion_time - sent_time);
  }
  std::optional<int64_t> processing_time() const {
    if (!start_time || !completion_time) return std::nullopt;
    return std::max<int64_t>(0, *completion_time - *start_time);
  }
  std::optional<int64_t> startup_latency() const {
    if (!start_time) return std::nullopt;
    return std::max<int64_t>(0, *start_time - sent_time);
  }
};

struct TaskStatistics {
  size_t total_tasks = 0, completed_tasks = 0, failed_tasks = 0;
  int64_t avg_total_time = 0, avg_processing_time = 0, avg_startup_latency = 0;
  int64_t min_total_time = 0, max_total_time = 0;
  int64_t min_processing_time = 0, max_processing_time = 0;

  std::string to_string() const {
    double rate = total_tasks
                      ? 100.0 * static_cast<double>(completed_tasks) /
                            static_cast<double>(total_tasks)
                      : 0.0;
    char buf[640];
    snprintf(buf, sizeof(buf),
             "\U0001F4CA Task Statistics:\n"
             "├─ Total Tasks: %zu\n"
             "├─ Completed: %zu (Success Rate: %.1f%%)\n"
             "├─ Failed: %zu\n"
             "├─ Avg Total Time: %lld ms\n"
             "├─ Avg Processing Time: %lld ms\n"
             "├─ Avg Startup Latency: %lld ms\n"
             "├─ Min/Max Total Time: %lld ms / %lld ms\n"
             "└─ Min/Max Processing Time: %lld ms / %lld ms",
             total_tasks, completed_tasks, rate, failed_tasks,
             static_cast<long long>(avg_total_time),
             static_cast<long long>(avg_processing_time),
             static_cast<long long>(avg_startup_latency),
             static_cast<long long>(min_total_time),
             static_cast<long long>(max_total_time),
             static_cast<long long>(min_processing_time),
             static_cast<long long>(max_processing_time));
    return buf;
  }
};

class TaskMetricsCollector {
 public:
  std::map<uint64_t, TaskMetric> metrics;
  // peer-clock-skew evidence (see TaskMetric derivation clamps)
  uint64_t clock_skew_events = 0;
  int64_t clock_skew_worst_ms = 0;

  void add_metric(TaskMetric m) { metrics[m.task_id] = std::move(m); }

  void update_received(uint64_t id, int64_t at_ms) {
    auto it = metrics.find(id);
    if (it != metrics.end()) {
      it->second.received_time = at_ms;
      note_skew(it->second.sent_time, at_ms);
      it->second.status = TaskStatus::Received;
    }
  }
  void update_started(uint64_t id, int64_t at_ms) {
    auto it = metrics.find(id);
    if (it != metrics.end()) {
      it->second.start_time = at_ms;
      note_skew(it->second.sent_time, at_ms);
      it->second.status = TaskStatus::Running;
    }
  }
  void update_completed(uint64_t id, int64_t at_ms) {
    auto it = metrics.find(id);
    if (it != metrics.end()) {
      it->second.completion_time = at_ms;
      note_skew(it->second.start_time ? *it->second.start_time
                                      : it->second.sent_time,
                at_ms);
      it->second.status = TaskStatus::Completed;
    }
  }
  void update_failed(uint64_t id) {
    auto it = metrics.find(id);
    if (it != metrics.end()) it->second.status = TaskStatus::Failed;
  }
  void clear() {
    metrics.clear();
    clock_skew_events = 0;
    clock_skew_worst_ms = 0;
  }

  TaskStatistics statistics() const {
    TaskStatistics s;
    s.total_tasks = metrics.size();
    std::vector<int64_t> totals, procs, starts;
    for (const auto& [id, m] : metrics) {
      if (m.status == TaskStatus::Failed) ++s.failed_tasks;
      if (m.status != TaskStatus::Completed) continue;
      ++s.completed_tasks;
      if (auto t = m.total_time()) totals.push_back(*t);
      if (auto t = m.processing_time()) procs.push_back(*t);
      if (auto t = m.startup_latency()) starts.push_back(*t);
    }
    auto avg = [](const std::vector<int64_t>& v) -> int64_t {
      if (v.empty()) return 0;
      return std::accumulate(v.begin(), v.end(), int64_t{0}) /
             static_cast<int64_t>(v.size());
    };
    auto minv = [](const std::vector<int64_t>& v) {
      return v.empty() ? int64_t{0} : *std::min_element(v.begin(), v.end());
    };
    auto maxv = [](const std::vector<int64_t>& v) {
      return v.empty() ? int64_t{0} : *std::max_element(v.begin(), v.end());
    };
    s.avg_total_time = avg(totals);
    s.avg_processing_time = avg(procs);
    s.avg_startup_latency = avg(starts);
    s.min_total_time = minv(totals);
    s.max_total_time = maxv(totals);
    s.min_processing_time = minv(procs);
    s.max_processing_time = maxv(procs);
    return s;
  }

  // Exact schema of task_metrics.rs:179-227: missing timestamps as 0,
  // missing derived times as empty strings.
  std::string to_csv_string() const {
    std::ostringstream out;
    out << "task_id,peer_id,sent_time_ms,received_time_ms,start_time_ms,"
           "completion_time_ms,total_time_ms,processing_time_ms,"
           "startup_latency_ms,status\n";
    for (const auto& [id, m] : metrics) {  // std::map iterates id-sorted
      auto opt = [](std::optional<int64_t> v) {
        return v ? std::to_string(*v) : std::string();
      };
      out << m.task_id << ',' << m.peer_id << ',' << m.sent_time << ','
          << m.received_time.value_or(0) << ',' << m.start_time.value_or(0)
          << ',' << m.completion_time.value_or(0) << ','
          << opt(m.total_time()) << ',' << opt(m.processing_time()) << ','
          << opt(m.startup_latency()) << ',' << task_status_str(m.status)
          << '\n';
    }
    return out.str();
  }

 private:
  void note_skew(int64_t earlier, int64_t later) {
    if (later < earlier) {
      ++clock_skew_events;
      clock_skew_worst_ms = std::max(clock_skew_worst_ms, earlier - later);
    }
  }
};

class PathComputationMetrics {
 public:
  struct Stats {
    size_t samples;
    double avg_micros;
    int64_t min_micros, max_micros;
    std::string to_string() const {
      char buf[256];
      snprintf(buf, sizeof(buf),
               "⏱️ Path Computation Stats:\n"
               "├─ Samples: %zu\n├─ Avg: %.3f ms\n├─ Min: %.3f ms\n"
               "└─ Max: %.3f ms",
               samples, avg_micros / 1000.0,
               static_cast<double>(min_micros) / 1000.0,
               static_cast<double>(max_micros) / 1000.0);
      return buf;
    }
  };

  void record_micros(int64_t us, std::optional<int64_t> ts_ms = std::nullopt) {
    samples_.push_back(us);
    timestamps_.push_back(ts_ms);
  }
  void clear() {
    samples_.clear();
    timestamps_.clear();
  }
  size_t size() const { return samples_.size(); }
  bool empty() const { return samples_.empty(); }

  std::optional<Stats> statistics() const {
    if (samples_.empty()) return std::nullopt;
    Stats s;
    s.samples = samples_.size();
    s.min_micros = *std::min_element(samples_.begin(), samples_.end());
    s.max_micros = *std::max_element(samples_.begin(), samples_.end());
    s.avg_micros =
        static_cast<double>(
            std::accumulate(samples_.begin(), samples_.end(), int64_t{0})) /
        static_cast<double>(samples_.size());
    return s;
  }

  // Schema of task_metrics.rs:332-339 (+ optional trailing timestamp_ms
  // column for compare_path_metrics.py's per-step bucketing).
  std::string to_csv_string() const {
    bool with_ts = false;
    for (const auto& t : timestamps_) with_ts = with_ts || t.has_value();
    std::ostringstream out;
    out << "sample_index,duration_micros,duration_millis";
    if (with_ts) out << ",timestamp_ms";
    out << '\n';
    for (size_t i = 0; i < samples_.size(); ++i) {
      char ms[32];
      snprintf(ms, sizeof(ms), "%.3f",
               static_cast<double>(samples_[i]) / 1000.0);
      out << i << ',' << samples_[i] << ',' << ms;
      if (with_ts) {
        out << ',';
        if (timestamps_[i]) out << *timestamps_[i];
      }
      out << '\n';
    }
    return out.str();
  }

 private:
  std::vector<int64_t> samples_;
  std::vector<std::optional<int64_t>> timestamps_;
};

// ---------------------------------------------------------------------------
// MetricsRegistry — native mirror of obs/registry.py (see header comment).
// Series keys: `name` or `name{k="v",...}`; labels arrive pre-formatted
// (`topic="solver"`) since C++ call sites know them statically.  Metric
// names may contain dots (tracer style); Prometheus exposition sanitizes.
// ---------------------------------------------------------------------------

// Bucket bounds (ms) shared with obs/registry.py DEFAULT_MS_BUCKETS: the
// 500 ms planning budget sits on a bucket edge.
inline const std::vector<double>& default_ms_buckets() {
  static const std::vector<double> b{1,   2,   5,    10,   20,   50,
                                     100, 200, 500, 1000, 2000, 5000};
  return b;
}

class MetricsRegistry {
 public:
  static MetricsRegistry& instance() {
    static MetricsRegistry r;
    return r;
  }

  static std::string key(const std::string& name,
                         const std::string& labels = "") {
    return labels.empty() ? name : name + "{" + labels + "}";
  }

  void count(const std::string& name, double n = 1,
             const std::string& labels = "") {
    std::lock_guard<std::mutex> lk(mu_);
    counters_[key(name, labels)] += n;
  }

  void gauge(const std::string& name, double v,
             const std::string& labels = "") {
    std::lock_guard<std::mutex> lk(mu_);
    gauges_[key(name, labels)] = v;
  }

  void observe(const std::string& name, double v,
               const std::string& labels = "") {
    std::lock_guard<std::mutex> lk(mu_);
    Hist& h = hists_[key(name, labels)];
    if (h.counts.empty()) h.counts.assign(default_ms_buckets().size() + 1, 0);
    size_t i = 0;
    while (i < default_ms_buckets().size() && v > default_ms_buckets()[i]) ++i;
    ++h.counts[i];
    h.sum += v;
    ++h.count;
  }

  double uptime_s() const {
    return std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                         start_)
        .count();
  }

  // Sum of every series of `name` across its labels (Python twin:
  // Registry.counter_value with no label filter).
  double counter_total(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    double total = 0;
    const std::string prefix = name + "{";
    for (const auto& [k, v] : counters_)
      if (k == name || k.compare(0, prefix.size(), prefix) == 0) total += v;
    return total;
  }

  // The operator-facing network rollup (managers' `metrics` command),
  // derived from the same bus.* counters the beacons publish — the CLI
  // print and fleet_top cannot disagree (Python twin:
  // Registry.network_summary).
  std::string network_summary_string() {
    double e = uptime_s();
    double ms = counter_total("bus.msgs_sent");
    double mr = counter_total("bus.msgs_received");
    double bs = counter_total("bus.bytes_sent");
    double br = counter_total("bus.bytes_received");
    char buf[512];
    snprintf(buf, sizeof(buf),
             "\U0001F4E1 Network Communication Stats:\n"
             "├─ Messages sent: %.0f (%.1f msg/s)\n"
             "├─ Messages received: %.0f (%.1f msg/s)\n"
             "├─ Bandwidth sent: %.2f KB (%.1f kbps)\n"
             "├─ Bandwidth received: %.2f KB (%.1f kbps)\n"
             "└─ Duration: %.1fs",
             ms, e > 0 ? ms / e : 0.0, mr, e > 0 ? mr / e : 0.0,
             bs / 1024.0, e > 0 ? bs * 8.0 / (e * 1000.0) : 0.0,
             br / 1024.0, e > 0 ? br * 8.0 / (e * 1000.0) : 0.0, e);
    return buf;
  }

  // Same schema as Registry.snapshot() on the Python side: the beacon body.
  Json snapshot_json() {
    std::lock_guard<std::mutex> lk(mu_);
    // force Object type: a default Json is Null, and an empty section must
    // serialize as {} (the Python aggregator's schema), not null
    Json counters{JsonObject{}}, gauges{JsonObject{}}, hists{JsonObject{}};
    for (const auto& [k, v] : counters_) counters.set(k, Json(v));
    for (const auto& [k, v] : gauges_) gauges.set(k, Json(v));
    for (const auto& [k, h] : hists_) {
      Json jh, bounds, counts;
      for (double b : default_ms_buckets()) bounds.push_back(Json(b));
      for (uint64_t c : h.counts)
        counts.push_back(Json(static_cast<int64_t>(c)));
      jh.set("buckets", bounds)
          .set("counts", counts)
          .set("sum", Json(h.sum))
          .set("count", Json(static_cast<int64_t>(h.count)));
      hists.set(k, jh);
    }
    Json out;
    out.set("uptime_s", Json(uptime_s()))
        .set("counters", counters)
        .set("gauges", gauges)
        .set("hists", hists);
    return out;
  }

  // Prometheus text exposition (parity with Registry.expose_text; dots in
  // names become underscores, labels pass through).
  std::string expose_text() {
    std::lock_guard<std::mutex> lk(mu_);
    std::ostringstream out;
    auto prom = [](const std::string& k) {
      std::string name = k, labels;
      size_t brace = k.find('{');
      if (brace != std::string::npos) {
        name = k.substr(0, brace);
        labels = k.substr(brace);
      }
      for (size_t i = 0; i < name.size(); ++i) {
        char& c = name[i];
        // digits only past position 0, matching registry.py _prom_name
        if (!(isalpha(static_cast<unsigned char>(c)) || c == '_' ||
              c == ':' ||
              (isdigit(static_cast<unsigned char>(c)) && i > 0)))
          c = '_';
      }
      return std::make_pair(name, labels);
    };
    // ONE "# TYPE" line per metric name (labeled series share it) — the
    // Prometheus text format rejects duplicates; mirrors registry.py's
    // `typed` set
    std::set<std::string> typed;
    auto type_line = [&](const std::string& n, const char* kind) {
      if (typed.insert(n).second) out << "# TYPE " << n << ' ' << kind << '\n';
    };
    for (const auto& [k, v] : counters_) {
      auto [n, l] = prom(k);
      type_line(n, "counter");
      out << n << l << ' ' << v << '\n';
    }
    for (const auto& [k, v] : gauges_) {
      auto [n, l] = prom(k);
      type_line(n, "gauge");
      out << n << l << ' ' << v << '\n';
    }
    for (const auto& [k, h] : hists_) {
      auto [n, l] = prom(k);
      type_line(n, "histogram");
      uint64_t cum = 0;
      std::string base = l.empty() ? "" : l.substr(1, l.size() - 2);
      for (size_t i = 0; i < default_ms_buckets().size(); ++i) {
        cum += h.counts[i];
        out << n << "_bucket{" << (base.empty() ? "" : base + ",")
            << "le=\"" << default_ms_buckets()[i] << "\"} " << cum << '\n';
      }
      out << n << "_bucket{" << (base.empty() ? "" : base + ",")
          << "le=\"+Inf\"} " << h.count << '\n';
      out << n << "_sum" << l << ' ' << h.sum << '\n';
      out << n << "_count" << l << ' ' << h.count << '\n';
    }
    return out.str();
  }

 private:
  struct Hist {
    std::vector<uint64_t> counts;
    double sum = 0;
    uint64_t count = 0;
  };
  MetricsRegistry() : start_(std::chrono::steady_clock::now()) {}
  std::map<std::string, double> counters_;
  std::map<std::string, double> gauges_;
  std::map<std::string, Hist> hists_;
  std::chrono::steady_clock::time_point start_;
  std::mutex mu_;
};

inline void metrics_count(const std::string& name, double n = 1,
                          const std::string& labels = "") {
  MetricsRegistry::instance().count(name, n, labels);
}

inline void metrics_gauge(const std::string& name, double v,
                          const std::string& labels = "") {
  MetricsRegistry::instance().gauge(name, v, labels);
}

inline void metrics_observe(const std::string& name, double v,
                            const std::string& labels = "") {
  MetricsRegistry::instance().observe(name, v, labels);
}

// The one beacon-payload constructor (schema: obs/beacon.py) — used by
// BusClient::maybe_publish_beacon AND busd's in-hub beacon, so the schema
// cannot diverge between the hub and its clients.
inline Json make_metrics_beacon(const std::string& peer_id,
                                const std::string& proc, double interval_s) {
  Json b;
  b.set("type", "metrics_beacon")
      .set("peer_id", peer_id)
      .set("proc", proc)
      .set("pid", static_cast<int64_t>(getpid()))
      .set("ts_ms",
           static_cast<int64_t>(
               std::chrono::duration_cast<std::chrono::milliseconds>(
                   std::chrono::system_clock::now().time_since_epoch())
                   .count()))
      .set("interval_s", interval_s)
      .set("metrics", MetricsRegistry::instance().snapshot_json());
  return b;
}

// (The old NetworkMetrics store lived here; bus accounting now has ONE
// store — MetricsRegistry — and the operator print is
// network_summary_string() above, exactly as the Python side's
// registry.network_summary() replaced task_metrics.NetworkMetrics.)

}  // namespace mapd
