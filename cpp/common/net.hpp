// Line-framed TCP helpers for the host runtime: nonblocking sockets with
// per-connection read/write buffering, driven by a poll() loop.  This is the
// transport under the pub/sub bus — the TPU-native stand-in for the
// reference's libp2p TCP + noise + yamux stack (SURVEY C9); framing is one
// JSON document per '\n'-terminated line.
#pragma once

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <deque>
#include <optional>
#include <string>

namespace mapd {

inline int set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// Listening socket on bind_addr:port (default loopback; pass "0.0.0.0"
// or an interface address for cross-host fleets); returns fd or -1.
inline int tcp_listen(uint16_t port,
                      const std::string& bind_addr = "127.0.0.1") {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  addr.sin_port = htons(port);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 128) < 0) {
    close(fd);
    return -1;
  }
  return fd;
}

// Blocking connect to host:port; returns fd or -1.
inline int tcp_connect(const std::string& host, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// tcp_connect with a bounded wait: non-blocking connect + poll.  For
// reconnect paths inside single-threaded role loops, where the kernel's
// default SYN retry timeout (~130 s against a silently-unreachable host)
// would freeze the event loop for the whole attempt.
inline int tcp_connect_timeout(const std::string& host, uint16_t port,
                               int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc < 0) {
    if (errno != EINPROGRESS) {
      close(fd);
      return -1;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, timeout_ms) <= 0) {
      close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) < 0 || err != 0) {
      close(fd);
      return -1;
    }
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;  // left non-blocking: every caller wants it that way
}

// Buffered line-framed connection over a nonblocking fd.
class LineConn {
 public:
  explicit LineConn(int fd = -1) : fd_(fd) {}

  int fd() const { return fd_; }
  bool valid() const { return fd_ >= 0; }
  bool wants_write() const { return !outbuf_.empty(); }

  // Append a frame to the write buffer (flushed by on_writable).
  void send_line(const std::string& line) {
    outbuf_ += line;
    outbuf_ += '\n';
  }

  // Pump readable data; returns false when the peer closed or errored.
  bool on_readable() {
    char buf[65536];
    while (true) {
      ssize_t n = ::read(fd_, buf, sizeof(buf));
      if (n > 0) {
        inbuf_.append(buf, static_cast<size_t>(n));
        if (inbuf_.size() > kMaxBuffer) return false;  // protocol abuse
      } else if (n == 0) {
        return false;
      } else {
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      }
    }
  }

  // Flush pending writes; returns false on error.
  bool on_writable() {
    while (!outbuf_.empty()) {
      ssize_t n = ::write(fd_, outbuf_.data(), outbuf_.size());
      if (n > 0) {
        outbuf_.erase(0, static_cast<size_t>(n));
      } else {
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      }
    }
    return true;
  }

  // Pop the next complete line, if any.
  std::optional<std::string> next_line() {
    auto nl = inbuf_.find('\n');
    if (nl == std::string::npos) return std::nullopt;
    std::string line = inbuf_.substr(0, nl);
    inbuf_.erase(0, nl + 1);
    if (!line.empty() && line.back() == '\r') line.pop_back();
    return line;
  }

  void close_fd() {
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
  }

 private:
  static constexpr size_t kMaxBuffer = 16 * 1024 * 1024;
  int fd_;
  std::string inbuf_;
  std::string outbuf_;
};

}  // namespace mapd
