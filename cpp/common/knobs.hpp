// Unified runtime-knob lookup (SURVEY §5 "real config system").
//
// The reference scatters its knobs across compile-time constants, one CLI
// flag and two env vars (SURVEY §5; e.g. planning interval 500 ms hardcoded
// at src/bin/centralized/manager.rs:567, TSWAP_RADIUS=15 duplicated at
// src/bin/decentralized/agent.rs:796,801).  Here every knob of the Python
// ``RuntimeConfig`` (p2p_distributed_tswap_tpu/core/config.py) is settable
// end-to-end on each binary, with one precedence rule:
//
//   CLI flag  (--planning-interval-ms 400  or  --planning-interval-ms=400)
//   beats env (MAPD_PLANNING_INTERVAL_MS=400)
//   beats the reference-parity default.
//
// ``runtime/fleet.py`` passes a RuntimeConfig through as env vars so one
// Python dataclass configures a whole fleet.

#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace mapd {

class Knobs {
 public:
  Knobs(int argc, char** argv) : argc_(argc), argv_(argv) {}

  // "--flag value" / "--flag=value", else $env, else def.
  // A value starting with "--" is rejected (so "--map --clean" fails loudly
  // instead of yielding map="--clean"), and a trailing value-less flag is an
  // error rather than a silent fall-through to env/default.
  std::string get_str(const char* flag, const char* env,
                      const std::string& def) const {
    size_t flen = strlen(flag);
    for (int i = 1; i < argc_; ++i) {
      if (!strcmp(argv_[i], flag)) {
        if (i + 1 >= argc_ || !strncmp(argv_[i + 1], "--", 2)) {
          fprintf(stderr, "knobs: flag %s requires a value\n", flag);
          exit(2);
        }
        return argv_[i + 1];
      }
      if (!strncmp(argv_[i], flag, flen) && argv_[i][flen] == '=')
        return argv_[i] + flen + 1;
    }
    if (env && *env)
      if (const char* v = getenv(env)) return v;
    return def;
  }

  int64_t get_int(const char* flag, const char* env, int64_t def) const {
    std::string s = get_str(flag, env, "");
    if (s.empty()) return def;
    char* end = nullptr;
    int64_t v = strtoll(s.c_str(), &end, 10);
    if (end == s.c_str() || *end != '\0') {
      // unparsable value: keep the documented default rather than a silent 0
      fprintf(stderr, "knobs: ignoring non-numeric value \"%s\" for %s\n",
              s.c_str(), flag);
      return def;
    }
    return v;
  }

  // Bare boolean flag (--clean); env counts as true unless "0"/"false"/"".
  bool get_bool(const char* flag, const char* env) const {
    for (int i = 1; i < argc_; ++i)
      if (!strcmp(argv_[i], flag)) return true;
    if (env && *env)
      if (const char* v = getenv(env))
        return *v && strcmp(v, "0") && strcmp(v, "false");
    return false;
  }

 private:
  int argc_;
  char** argv_;
};

}  // namespace mapd
