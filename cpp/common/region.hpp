// Region-sharded position-gossip topic math — native mirror of
// p2p_distributed_tswap_tpu/runtime/region.py (ISSUE 4 tentpole; the
// geographic topic partitioning the reference's scalability post-mortem
// proposed but never built, DECENTRALIZED_ISSUES.md:62-96).
//
// The grid is partitioned into square regions of `region_cells` per edge;
// agents publish position beacons on topic "mapd.pos.<rx>.<ry>" and
// subscribe the (2k+1)^2 region neighborhood with k = ceil(radius /
// region_cells), re-subscribing on border crossings.  Coverage guarantee
// (property-tested in tests/test_region_bus.py): any two cells within
// Manhattan `radius` of each other land in regions at most k apart per
// axis, so the publisher's topic is always inside the subscriber's set.
// Managers subscribe the wildcard "mapd.pos.*" (busd prefix match).
#pragma once

#include <algorithm>
#include <set>
#include <string>

#include "grid.hpp"

namespace mapd {

constexpr const char* kPosTopicPrefix = "mapd.pos.";
constexpr const char* kPosTopicWildcard = "mapd.pos.*";
constexpr int kDefaultRegionCells = 32;

class RegionMap {
 public:
  explicit RegionMap(int cells) : cells_(cells < 1 ? 1 : cells) {}

  int cells() const { return cells_; }

  std::string topic_for(const Grid& grid, Cell c) const {
    return std::string(kPosTopicPrefix) +
           std::to_string(grid.x_of(c) / cells_) + "." +
           std::to_string(grid.y_of(c) / cells_);
  }

  std::set<std::string> neighborhood(const Grid& grid, Cell c,
                                     int radius) const {
    const int k = radius <= cells_ ? 1 : (radius + cells_ - 1) / cells_;
    const int rx = grid.x_of(c) / cells_, ry = grid.y_of(c) / cells_;
    const int nrx = (grid.width + cells_ - 1) / cells_;
    const int nry = (grid.height + cells_ - 1) / cells_;
    std::set<std::string> out;
    for (int gy = std::max(0, ry - k); gy <= std::min(nry - 1, ry + k); ++gy)
      for (int gx = std::max(0, rx - k); gx <= std::min(nrx - 1, rx + k);
           ++gx)
        out.insert(std::string(kPosTopicPrefix) + std::to_string(gx) + "." +
                   std::to_string(gy));
    return out;
  }

 private:
  int cells_;
};

}  // namespace mapd
