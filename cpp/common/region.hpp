// Region-sharded position-gossip topic math — native mirror of
// p2p_distributed_tswap_tpu/runtime/region.py (ISSUE 4 tentpole; the
// geographic topic partitioning the reference's scalability post-mortem
// proposed but never built, DECENTRALIZED_ISSUES.md:62-96).
//
// The grid is partitioned into square regions of `region_cells` per edge;
// agents publish position beacons on topic "mapd.pos.<rx>.<ry>" and
// subscribe the (2k+1)^2 region neighborhood with k = ceil(radius /
// region_cells), re-subscribing on border crossings.  Coverage guarantee
// (property-tested in tests/test_region_bus.py): any two cells within
// Manhattan `radius` of each other land in regions at most k apart per
// axis, so the publisher's topic is always inside the subscriber's set.
// Managers subscribe the wildcard "mapd.pos.*" (busd prefix match).
#pragma once

#include <algorithm>
#include <cctype>
#include <set>
#include <string>

#include "grid.hpp"

namespace mapd {

constexpr const char* kPosTopicPrefix = "mapd.pos.";
constexpr const char* kPosTopicWildcard = "mapd.pos.*";
constexpr int kDefaultRegionCells = 32;

class RegionMap {
 public:
  explicit RegionMap(int cells) : cells_(cells < 1 ? 1 : cells) {}

  int cells() const { return cells_; }

  std::string topic_for(const Grid& grid, Cell c) const {
    return std::string(kPosTopicPrefix) +
           std::to_string(grid.x_of(c) / cells_) + "." +
           std::to_string(grid.y_of(c) / cells_);
  }

  std::set<std::string> neighborhood(const Grid& grid, Cell c,
                                     int radius) const {
    const int k = radius <= cells_ ? 1 : (radius + cells_ - 1) / cells_;
    const int rx = grid.x_of(c) / cells_, ry = grid.y_of(c) / cells_;
    const int nrx = (grid.width + cells_ - 1) / cells_;
    const int nry = (grid.height + cells_ - 1) / cells_;
    std::set<std::string> out;
    for (int gy = std::max(0, ry - k); gy <= std::min(nry - 1, ry + k); ++gy)
      for (int gx = std::max(0, rx - k); gx <= std::min(nrx - 1, rx + k);
           ++gx)
        out.insert(std::string(kPosTopicPrefix) + std::to_string(gx) + "." +
                   std::to_string(gy));
    return out;
  }

 private:
  int cells_;
};

// ---------------------------------------------------------------------------
// Federated world regions (ISSUE 14) — native mirror of the ownership canon
// in runtime/region.py (fed_* helpers), kept RULE-IDENTICAL and golden-
// tested via codec_golden --fedmap.  The grid splits into cols x rows
// ceil-width rectangular slabs, region id = ry * cols + rx; hysteresis and
// the border-mirror strip are margin tests against the owning rectangle.
// ---------------------------------------------------------------------------

constexpr const char* kFedTopicPrefix = "mapd.fed.";
constexpr int kDefaultFedHysteresis = 2;
constexpr int kDefaultFedBorder = 2;

struct FedRect {
  int x0 = 0, y0 = 0, x1 = 0, y1 = 0;  // half-open
};

class FedMap {
 public:
  // spec "CxR" or bare "N" (= Nx1); ""/"0"/"1"/"1x1" = 1x1 = off.
  // Malformed specs yield cols_ = 0 (caller must treat as fatal — a
  // half-parsed world partition must never route silently).
  static FedMap parse(const std::string& spec) {
    FedMap m;
    std::string s;
    for (char c : spec) s += static_cast<char>(::tolower(c));
    if (s.empty() || s == "0" || s == "1" || s == "1x1") {
      m.cols_ = m.rows_ = 1;
      return m;
    }
    int cols = 0, rows = 1;
    size_t x = s.find('x');
    try {
      size_t used = 0;
      if (x == std::string::npos) {
        cols = std::stoi(s, &used);
        if (used != s.size()) cols = 0;
      } else {
        cols = std::stoi(s.substr(0, x), &used);
        if (used != x) cols = 0;
        rows = std::stoi(s.substr(x + 1), &used);
        if (used != s.size() - x - 1) rows = 0;
      }
    } catch (...) {
      cols = 0;
    }
    if (cols < 1 || rows < 1) {
      m.cols_ = 0;  // invalid marker
      m.rows_ = 0;
      return m;
    }
    m.cols_ = cols;
    m.rows_ = rows;
    return m;
  }

  FedMap() = default;
  FedMap(int cols, int rows) : cols_(cols), rows_(rows) {}

  bool valid() const { return cols_ >= 1 && rows_ >= 1; }
  int cols() const { return cols_; }
  int rows() const { return rows_; }
  int total() const { return cols_ * rows_; }

  static int slab(int extent, int n) { return (extent + n - 1) / n; }

  int region_of(int width, int height, int x, int y) const {
    const int cw = slab(width, cols_), rh = slab(height, rows_);
    const int rx = std::min(x / cw, cols_ - 1);
    const int ry = std::min(y / rh, rows_ - 1);
    return ry * cols_ + rx;
  }

  FedRect rect_of(int width, int height, int rid) const {
    const int cw = slab(width, cols_), rh = slab(height, rows_);
    const int rx = rid % cols_, ry = rid / cols_;
    FedRect r;
    r.x0 = rx * cw;
    r.y0 = ry * rh;
    r.x1 = std::min((rx + 1) * cw, width);
    r.y1 = std::min((ry + 1) * rh, height);
    return r;
  }

  // handoff trigger: strictly more than `margin` cells outside the rect
  // on either axis (margin >= 1 = border-ping-pong hysteresis)
  static bool escaped(int x, int y, const FedRect& r, int margin) {
    return x < r.x0 - margin || x > r.x1 - 1 + margin ||
           y < r.y0 - margin || y > r.y1 - 1 + margin;
  }

  // the border-mirror strip: OUTSIDE the rect but within `border` cells
  // of it on both axes
  static bool in_border(int x, int y, const FedRect& r, int border) {
    if (x >= r.x0 && x < r.x1 && y >= r.y0 && y < r.y1) return false;
    return x >= r.x0 - border && x <= r.x1 - 1 + border &&
           y >= r.y0 - border && y <= r.y1 - 1 + border;
  }

  static std::string fed_topic(int rid) {
    return std::string(kFedTopicPrefix) + std::to_string(rid);
  }

  std::string solver_topic(int rid) const {
    return total() <= 1 ? std::string("solver")
                        : "solver.r" + std::to_string(rid);
  }

 private:
  int cols_ = 1, rows_ = 1;
};

}  // namespace mapd
