// Span tracer for the C++ host runtime — the native mirror of
// p2p_distributed_tswap_tpu/obs/trace.py (one schema, one report tool:
// analysis/trace_report.py merges both sides into one Perfetto timeline).
//
// Same contract as the Python side:
//   - gated by JG_TRACE=1 (or the binary's --trace flag via trace_init);
//     disabled, a Span is one bool check — no clock read, no lock;
//   - monotonic durations on a wall-clock anchor, so events from this
//     process interleave with solverd's at ~ms alignment;
//   - bounded ring buffer (newest TRACE_CAPACITY events kept);
//   - counters exported as Chrome "C" events on flush;
//   - flush appends Chrome trace-event JSONL to
//     $JG_TRACE_DIR/<proc>-<pid>.trace.jsonl (default results/trace/),
//     and runs automatically at process exit.
//
// Spans nest lexically (RAII); each event carries its parent span's name in
// args.parent via a thread-local stack, matching the Python tracer.

#pragma once

#include <sys/types.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace mapd {

constexpr size_t TRACE_CAPACITY = 65536;

struct TraceEvent {
  std::string name;
  char ph = 'X';       // 'X' span, 'i' instant, 'C' counter, 's'/'t'/'f' flow
  int64_t ts_us = 0;   // wall-anchored microseconds
  int64_t dur_us = 0;  // 'X' only
  int64_t flow_id = -1;  // 's'/'t'/'f' only: the Perfetto flow-link id
  std::string parent;  // enclosing span name, "" at top level
  std::string args_json;  // extra args as a JSON fragment ("\"k\":1"), or ""
};

class Tracer {
 public:
  static Tracer& instance() {
    static Tracer t;
    return t;
  }

  bool enabled() const { return enabled_; }

  void init(const char* proc, bool force_on = false) {
    proc_ = proc;
    if (force_on) enabled_ = true;
  }

  int64_t now_us() const {
    auto mono = std::chrono::steady_clock::now();
    return anchor_us_ + std::chrono::duration_cast<std::chrono::microseconds>(
                            mono - mono0_)
                            .count();
  }

  void emit(TraceEvent ev) {
    std::lock_guard<std::mutex> lk(mu_);
    if (events_.size() >= TRACE_CAPACITY) events_.pop_front();
    events_.push_back(std::move(ev));
  }

  void instant(const std::string& name, const std::string& args_json = "") {
    if (!enabled_) return;
    TraceEvent ev;
    ev.name = name;
    ev.ph = 'i';
    ev.ts_us = now_us();
    ev.args_json = args_json;
    emit(std::move(ev));
  }

  // Chrome flow event ('s' start / 't' step / 'f' end): events sharing
  // (cat, name, id) link into cross-process arrows on the merged
  // timeline — the native side of obs/trace.py Tracer.flow (ISSUE 5).
  void flow(const std::string& name, int64_t id, char phase,
            const std::string& args_json = "") {
    if (!enabled_) return;
    if (phase != 's' && phase != 't' && phase != 'f') return;
    TraceEvent ev;
    ev.name = name;
    ev.ph = phase;
    ev.ts_us = now_us();
    ev.flow_id = id & INT64_C(0x7FFFFFFFFFFFFFFF);  // Chrome ids: unsigned
    ev.args_json = args_json;
    emit(std::move(ev));
  }

  void count(const std::string& name, int64_t n = 1) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> lk(mu_);
    counters_[name] += n;
  }

  void gauge(const std::string& name, double v) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> lk(mu_);
    gauges_[name] = v;
  }

  // thread-local span-nesting stack (parent attribution, like obs/trace.py)
  static std::vector<std::string>& stack() {
    thread_local std::vector<std::string> s;
    return s;
  }

  std::string default_path() const {
    const char* dir = getenv("JG_TRACE_DIR");
    std::string d = dir && *dir ? dir : "results/trace";
    return d + "/" + proc_ + "-" + std::to_string(getpid()) + ".trace.jsonl";
  }

  // Append buffered events (+ metadata line on first flush) as JSONL.
  void flush() {
    if (!enabled_) return;
    std::deque<TraceEvent> evs;
    std::map<std::string, int64_t> counters;
    std::map<std::string, double> gauges;
    {
      std::lock_guard<std::mutex> lk(mu_);
      evs.swap(events_);
      counters = counters_;
      gauges = gauges_;
    }
    std::string path = default_path();
    size_t slash = path.rfind('/');
    if (slash != std::string::npos)
      mkdirs(path.substr(0, slash));
    FILE* f = fopen(path.c_str(), "a");
    if (!f) return;
    if (!wrote_meta_) {
      fprintf(f,
              "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
              "\"args\":{\"name\":\"%s\"}}\n",
              getpid(), proc_.c_str());
      wrote_meta_ = true;
    }
    for (const auto& ev : evs) {
      fprintf(f, "{\"name\":\"%s\",\"ph\":\"%c\",\"ts\":%lld,",
              json_escape(ev.name).c_str(), ev.ph,
              static_cast<long long>(ev.ts_us));
      if (ev.ph == 'X')
        fprintf(f, "\"dur\":%lld,", static_cast<long long>(ev.dur_us));
      if (ev.ph == 'i') fprintf(f, "\"s\":\"p\",");
      if (ev.ph == 's' || ev.ph == 't' || ev.ph == 'f') {
        fprintf(f, "\"cat\":\"task\",\"id\":%lld,",
                static_cast<long long>(ev.flow_id));
        if (ev.ph != 's') fprintf(f, "\"bp\":\"e\",");
      }
      fprintf(f, "\"pid\":%d,\"tid\":1,\"args\":{", getpid());
      bool first = true;
      if (!ev.parent.empty()) {
        fprintf(f, "\"parent\":\"%s\"", json_escape(ev.parent).c_str());
        first = false;
      }
      if (!ev.args_json.empty())
        fprintf(f, "%s%s", first ? "" : ",", ev.args_json.c_str());
      fprintf(f, "}}\n");
    }
    int64_t ts = now_us();
    for (const auto& [name, v] : counters)
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"C\",\"ts\":%lld,\"pid\":%d,"
              "\"args\":{\"value\":%lld}}\n",
              json_escape(name).c_str(), static_cast<long long>(ts), getpid(),
              static_cast<long long>(v));
    for (const auto& [name, v] : gauges)
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"C\",\"ts\":%lld,\"pid\":%d,"
              "\"args\":{\"value\":%g}}\n",
              json_escape(name).c_str(), static_cast<long long>(ts), getpid(),
              v);
    fclose(f);
  }

  ~Tracer() { flush(); }

 private:
  Tracer() {
    const char* v = getenv("JG_TRACE");
    enabled_ = v && *v && strcmp(v, "0") != 0;
    mono0_ = std::chrono::steady_clock::now();
    anchor_us_ = std::chrono::duration_cast<std::chrono::microseconds>(
                     std::chrono::system_clock::now().time_since_epoch())
                     .count();
  }

  static void mkdirs(const std::string& dir) {
    std::string cur;
    for (size_t i = 0; i < dir.size(); ++i) {
      cur += dir[i];
      if (dir[i] == '/' || i + 1 == dir.size())
        mkdir(cur.c_str(), 0755);  // EEXIST is fine
    }
  }

  static std::string json_escape(const std::string& s) {
    std::string out;
    for (char c : s) {
      if (c == '"' || c == '\\') out += '\\';
      if (static_cast<unsigned char>(c) < 0x20) continue;
      out += c;
    }
    return out;
  }

  bool enabled_ = false;
  bool wrote_meta_ = false;
  std::string proc_ = "cpp";
  std::deque<TraceEvent> events_;
  std::map<std::string, int64_t> counters_;
  std::map<std::string, double> gauges_;
  std::chrono::steady_clock::time_point mono0_;
  int64_t anchor_us_ = 0;
  std::mutex mu_;
};

// RAII span: construct to open, destruct to record.  Near-zero when off.
class Span {
 public:
  explicit Span(const char* name, std::string args_json = "")
      : live_(Tracer::instance().enabled()) {
    if (!live_) return;
    name_ = name;
    args_json_ = std::move(args_json);
    auto& st = Tracer::stack();
    parent_ = st.empty() ? "" : st.back();
    st.push_back(name_);
    t0_us_ = Tracer::instance().now_us();
  }

  Span(const Span&) = delete;
  Span& operator=(const Span&) = delete;

  ~Span() {
    if (!live_) return;
    auto& st = Tracer::stack();
    if (!st.empty() && st.back() == name_) st.pop_back();
    TraceEvent ev;
    ev.name = name_;
    ev.ph = 'X';
    ev.ts_us = t0_us_;
    int64_t dur = Tracer::instance().now_us() - t0_us_;
    ev.dur_us = dur < 0 ? 0 : dur;
    ev.parent = parent_;
    ev.args_json = std::move(args_json_);
    Tracer::instance().emit(std::move(ev));
  }

 private:
  bool live_;
  std::string name_, parent_, args_json_;
  int64_t t0_us_ = 0;
};

inline void trace_init(const char* proc, bool force_on = false) {
  Tracer::instance().init(proc, force_on);
}

inline void trace_count(const char* name, int64_t n = 1) {
  Tracer::instance().count(name, n);
}

inline void trace_instant(const char* name, const std::string& args = "") {
  Tracer::instance().instant(name, args);
}

inline void trace_flush() { Tracer::instance().flush(); }

inline bool trace_enabled() { return Tracer::instance().enabled(); }

}  // namespace mapd
