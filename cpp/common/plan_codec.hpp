// Packed binary plan codec ("packed1") — native mirror of
// p2p_distributed_tswap_tpu/runtime/plan_codec.py.  BYTE-IDENTICAL: the
// golden round-trip tests (tests/test_plan_codec.py, via
// probes/codec_golden.cpp) assert both encoders produce the same bytes for
// the same fleet sequence, so keep every rule in lockstep with the Python
// side (lane assignment, removal scan order, snapshot compaction).
//
// Packet layout (little-endian, 40-byte header):
//   u32 magic "JGP1"  u16 version=1  u8 kind(1 snap|2 delta|3 response)
//   u8 flags  i64 seq  i64 base_seq
//   u32 n_entries  u32 n_removed  u32 n_named  u32 names_len
//   i32 idx[]  i32 pos[]  i32 goal[]  i32 removed[]  i32 named_idx[]
//   u8 names[]  ('\n'-joined peer ids)
//
// Framing: base64 in the "data" field of the existing bus-line JSON; the
// "caps":["packed1"] field on requests is the negotiation — solverd
// answers packed iff it is present, so plain-JSON peers keep working.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <optional>
#include <queue>
#include <set>
#include <string>
#include <vector>

namespace mapd {
namespace codec {

constexpr uint32_t kMagic = 0x3150474A;  // b"JGP1"
constexpr uint16_t kVersion = 1;
constexpr uint8_t kSnapshot = 1;
constexpr uint8_t kDelta = 2;
constexpr uint8_t kResponse = 3;
// world1 (ISSUE 9): obstacle-toggle batch on the unchanged packed1
// framing — idx[] = flat cells, pos[] = blocked flag (0/1), goal[] =
// zero padding; seq = the manager's monotone world_seq.  Byte-identical
// mirror of plan_codec.py encode_world/decode_world.
constexpr uint8_t kWorld = 4;
// handoff1 (ISSUE 14): a cross-region agent-lane + task-ledger transfer
// on the unchanged packed1 framing — byte-identical mirror of
// plan_codec.py encode_handoff/decode_handoff (see its layout comment:
// idx=[pos,goal,phase], pos=[pickup,delivery,has_task],
// goal=[id_lo,id_hi,0] with id = hi * 32768 + lo, names=[peer];
// seq = per-(src,dst) handoff chain seq, base_seq = source region id).
constexpr uint8_t kHandoff = 5;
constexpr int64_t kHandoffIdBase = 32768;
constexpr const char* kCodecName = "packed1";
constexpr const char* kWorldCap = "world1";
constexpr int kSnapshotEvery = 64;

// Compact per-message causal context (ISSUE 5 "trace1"): trace_id is
// rooted where the traced object was created, hop counts wire crossings
// monotonically, send_ms is the sender's unix wall clock at publish time.
// Mirrors plan_codec.py TraceCtx; 20 bytes on the wire (i64, i64, u32).
struct TraceCtx {
  int64_t trace_id = 0;
  uint32_t hop = 0;
  int64_t send_ms = 0;
};

struct Packet {
  uint8_t kind = 0;
  int64_t seq = 0;
  int64_t base_seq = 0;
  std::vector<int32_t> idx, pos, goal, removed, named_idx;
  std::vector<std::string> names;
  bool has_trace = false;
  TraceCtx trace;
};

// ---------- base64 (standard alphabet, '=' padding) ----------

inline std::string b64_encode(const std::string& in) {
  static const char* tbl =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 3 <= in.size()) {
    uint32_t v = (static_cast<uint8_t>(in[i]) << 16) |
                 (static_cast<uint8_t>(in[i + 1]) << 8) |
                 static_cast<uint8_t>(in[i + 2]);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  size_t rem = in.size() - i;
  if (rem == 1) {
    uint32_t v = static_cast<uint8_t>(in[i]) << 16;
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += "==";
  } else if (rem == 2) {
    uint32_t v = (static_cast<uint8_t>(in[i]) << 16) |
                 (static_cast<uint8_t>(in[i + 1]) << 8);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

inline std::optional<std::string> b64_decode(const std::string& in) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '+') return 62;
    if (c == '/') return 63;
    return -1;
  };
  if (in.size() % 4 != 0) return std::nullopt;
  std::string out;
  out.reserve(in.size() / 4 * 3);
  for (size_t i = 0; i < in.size(); i += 4) {
    int pad = 0;
    uint32_t v = 0;
    for (int k = 0; k < 4; ++k) {
      char c = in[i + k];
      if (c == '=') {
        if (i + 4 != in.size() || k < 2) return std::nullopt;
        ++pad;
        v <<= 6;
        continue;
      }
      if (pad) return std::nullopt;  // '=' only at the very end
      int d = val(c);
      if (d < 0) return std::nullopt;
      v = (v << 6) | static_cast<uint32_t>(d);
    }
    out += static_cast<char>((v >> 16) & 0xFF);
    if (pad < 2) out += static_cast<char>((v >> 8) & 0xFF);
    if (pad < 1) out += static_cast<char>(v & 0xFF);
  }
  return out;
}

// ---------- binary encode / decode ----------

namespace detail {
inline void put_u16(std::string& b, uint16_t v) {
  b += static_cast<char>(v & 0xFF);
  b += static_cast<char>((v >> 8) & 0xFF);
}
inline void put_u32(std::string& b, uint32_t v) {
  for (int k = 0; k < 4; ++k) b += static_cast<char>((v >> (8 * k)) & 0xFF);
}
inline void put_i64(std::string& b, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  for (int k = 0; k < 8; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline void put_i32v(std::string& b, const std::vector<int32_t>& v) {
  for (int32_t x : v) put_u32(b, static_cast<uint32_t>(x));
}
inline uint16_t get_u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}
inline uint32_t get_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}
inline int64_t get_i64(const uint8_t* p) {
  uint64_t v = 0;
  for (int k = 7; k >= 0; --k) v = (v << 8) | p[k];
  return static_cast<int64_t>(v);
}
}  // namespace detail

// flags bit 0: narrow — arrays are u16, not i32 (auto-chosen when every
// value < 65536: any grid up to 256x256, fleets up to 64k lanes)
constexpr uint8_t kFlagNarrow = 1;
// flags bit 1: a 20-byte trace-context block follows the header (trace1)
constexpr uint8_t kFlagTrace = 2;
constexpr size_t kTraceExtLen = 20;  // i64 trace_id, i64 send_ms, u32 hop

namespace detail {
inline void put_trace(std::string& b, const TraceCtx& t) {
  put_i64(b, t.trace_id);
  put_i64(b, t.send_ms);
  put_u32(b, t.hop);
}
inline TraceCtx get_trace(const uint8_t* p) {
  TraceCtx t;
  t.trace_id = get_i64(p);
  t.send_ms = get_i64(p + 8);
  t.hop = get_u32(p + 16);
  return t;
}
}  // namespace detail

inline std::string encode(const Packet& p) {
  std::string blob;
  for (size_t k = 0; k < p.names.size(); ++k) {
    if (k) blob += '\n';
    blob += p.names[k];
  }
  bool narrow = true;
  for (const auto* arr : {&p.idx, &p.pos, &p.goal, &p.removed,
                          &p.named_idx})
    for (int32_t x : *arr)
      narrow = narrow && x >= 0 && x < 65536;
  const size_t width = narrow ? 2 : 4;
  std::string out;
  out.reserve(40 + width * (3 * p.idx.size() + p.removed.size() +
                            p.named_idx.size()) + blob.size());
  detail::put_u32(out, kMagic);
  detail::put_u16(out, kVersion);
  out += static_cast<char>(p.kind);
  out += static_cast<char>((narrow ? kFlagNarrow : 0) |
                           (p.has_trace ? kFlagTrace : 0));
  detail::put_i64(out, p.seq);
  detail::put_i64(out, p.base_seq);
  detail::put_u32(out, static_cast<uint32_t>(p.idx.size()));
  detail::put_u32(out, static_cast<uint32_t>(p.removed.size()));
  detail::put_u32(out, static_cast<uint32_t>(p.named_idx.size()));
  detail::put_u32(out, static_cast<uint32_t>(blob.size()));
  if (p.has_trace) detail::put_trace(out, p.trace);
  auto put = [&](const std::vector<int32_t>& v) {
    if (narrow)
      for (int32_t x : v) detail::put_u16(out, static_cast<uint16_t>(x));
    else
      detail::put_i32v(out, v);
  };
  put(p.idx);
  put(p.pos);
  put(p.goal);
  put(p.removed);
  put(p.named_idx);
  out += blob;
  return out;
}

inline std::optional<Packet> decode(const std::string& buf) {
  if (buf.size() < 40) return std::nullopt;
  const uint8_t* b = reinterpret_cast<const uint8_t*>(buf.data());
  if (detail::get_u32(b) != kMagic) return std::nullopt;
  uint16_t version = static_cast<uint16_t>(b[4] | (b[5] << 8));
  if (version != kVersion) return std::nullopt;
  Packet p;
  p.kind = b[6];
  const bool narrow = (b[7] & kFlagNarrow) != 0;
  p.has_trace = (b[7] & kFlagTrace) != 0;
  const size_t width = narrow ? 2 : 4;
  const size_t trace_len = p.has_trace ? kTraceExtLen : 0;
  p.seq = detail::get_i64(b + 8);
  p.base_seq = detail::get_i64(b + 16);
  uint32_t n_entries = detail::get_u32(b + 24);
  uint32_t n_removed = detail::get_u32(b + 28);
  uint32_t n_named = detail::get_u32(b + 32);
  uint32_t names_len = detail::get_u32(b + 36);
  uint64_t need = 40 + trace_len +
      width * (3ull * n_entries + n_removed + n_named) + names_len;
  if (buf.size() != need) return std::nullopt;
  if (p.has_trace) p.trace = detail::get_trace(b + 40);
  const uint8_t* q = b + 40 + trace_len;
  auto take = [&](std::vector<int32_t>& v, uint32_t n) {
    v.resize(n);
    for (uint32_t k = 0; k < n; ++k, q += width)
      v[k] = narrow ? static_cast<int32_t>(q[0] | (q[1] << 8))
                    : static_cast<int32_t>(detail::get_u32(q));
  };
  take(p.idx, n_entries);
  take(p.pos, n_entries);
  take(p.goal, n_entries);
  take(p.removed, n_removed);
  take(p.named_idx, n_named);
  if (names_len) {
    std::string blob(reinterpret_cast<const char*>(q), names_len);
    size_t start = 0;
    while (true) {
      size_t nl = blob.find('\n', start);
      if (nl == std::string::npos) {
        p.names.push_back(blob.substr(start));
        break;
      }
      p.names.push_back(blob.substr(start, nl - start));
      start = nl + 1;
    }
  }
  if (p.names.size() != n_named) return std::nullopt;
  return p;
}

// world1 toggle batch: cells[k] becomes an obstacle when blocked[k] != 0.
inline Packet encode_world(int64_t world_seq,
                           const std::vector<int32_t>& cells,
                           const std::vector<int32_t>& blocked) {
  Packet p;
  p.kind = kWorld;
  p.seq = world_seq;
  p.base_seq = 0;
  p.idx = cells;
  p.pos.reserve(blocked.size());
  for (int32_t b : blocked) p.pos.push_back(b ? 1 : 0);
  p.goal.assign(cells.size(), 0);
  return p;
}

// One cross-region agent transfer (ISSUE 14; runtime/region.py is the
// ownership canon deciding WHEN it fires).
struct HandoffRec {
  int64_t seq = 0;
  int32_t src_region = 0;
  std::string peer;
  int32_t pos = 0;
  int32_t goal = 0;
  int32_t phase = 0;  // 0 idle, 1 to-pickup, 2 to-delivery
  bool has_task = false;
  int64_t task_id = 0;
  int32_t pickup = 0;
  int32_t delivery = 0;
};

inline Packet encode_handoff(const HandoffRec& r) {
  Packet p;
  p.kind = kHandoff;
  p.seq = r.seq;
  p.base_seq = r.src_region;
  p.idx = {r.pos, r.goal, r.phase};
  p.pos = {r.has_task ? r.pickup : 0, r.has_task ? r.delivery : 0,
           r.has_task ? 1 : 0};
  p.goal = {static_cast<int32_t>(r.has_task ? r.task_id % kHandoffIdBase
                                            : 0),
            static_cast<int32_t>(r.has_task ? r.task_id / kHandoffIdBase
                                            : 0),
            0};
  p.named_idx = {0};
  p.names = {r.peer};
  return p;
}

inline std::optional<HandoffRec> decode_handoff(const Packet& p) {
  if (p.kind != kHandoff || p.idx.size() != 3 || p.pos.size() != 3 ||
      p.goal.size() != 3 || p.names.size() != 1)
    return std::nullopt;
  HandoffRec r;
  r.seq = p.seq;
  r.src_region = static_cast<int32_t>(p.base_seq);
  r.peer = p.names[0];
  r.pos = p.idx[0];
  r.goal = p.idx[1];
  r.phase = p.idx[2];
  r.has_task = p.pos[2] != 0;
  if (r.has_task) {
    r.task_id = static_cast<int64_t>(p.goal[1]) * kHandoffIdBase + p.goal[0];
    r.pickup = p.pos[0];
    r.delivery = p.pos[1];
  }
  return r;
}

inline std::string encode_b64(const Packet& p) { return b64_encode(encode(p)); }

inline std::optional<Packet> decode_b64(const std::string& data) {
  auto raw = b64_decode(data);
  if (!raw) return std::nullopt;
  return decode(*raw);
}

// ---------- manager-side delta tracking ----------

// Mirrors plan_codec.py PackedFleetEncoder exactly (see its docstring for
// the determinism contract: ascending removal scan, lowest-free-lane
// assignment, caller's fleet order, snapshot compaction).
class PackedFleetEncoder {
 public:
  explicit PackedFleetEncoder(int snapshot_every = kSnapshotEvery)
      : snapshot_every_(snapshot_every) {}

  void request_snapshot() { force_snapshot_ = true; }
  int64_t last_seq() const { return last_seq_; }

  // fleet: ordered (peer_id, pos_cell, goal_cell) triplets.
  Packet encode_tick(
      int64_t seq,
      const std::vector<std::tuple<std::string, int32_t, int32_t>>& fleet) {
    Packet pkt;
    pkt.seq = seq;
    bool snapshot =
        force_snapshot_ || since_snapshot_ + 1 >= snapshot_every_;
    if (snapshot) {
      roster_.clear();
      roster_idx_.clear();
      free_ = {};
      shadow_.clear();
      pkt.kind = kSnapshot;
      pkt.base_seq = 0;
      for (const auto& [name, p, g] : fleet) {
        int32_t lane = static_cast<int32_t>(roster_.size());
        roster_.push_back(name);
        roster_idx_[name] = lane;
        shadow_[lane] = {p, g};
        pkt.idx.push_back(lane);
        pkt.pos.push_back(p);
        pkt.goal.push_back(g);
        pkt.named_idx.push_back(lane);
        pkt.names.push_back(name);
      }
      force_snapshot_ = false;
      since_snapshot_ = 0;
      last_seq_ = seq;
      return pkt;
    }
    pkt.kind = kDelta;
    pkt.base_seq = last_seq_;
    std::set<std::string> current;
    for (const auto& [name, p, g] : fleet) {
      (void)p;
      (void)g;
      current.insert(name);
    }
    for (size_t lane = 0; lane < roster_.size(); ++lane) {
      if (!roster_[lane].empty() && !current.count(roster_[lane])) {
        pkt.removed.push_back(static_cast<int32_t>(lane));
        roster_idx_.erase(roster_[lane]);
        roster_[lane].clear();
        shadow_.erase(static_cast<int32_t>(lane));
        free_.push(static_cast<int32_t>(lane));
      }
    }
    for (const auto& [name, p, g] : fleet) {
      int32_t lane;
      auto it = roster_idx_.find(name);
      if (it == roster_idx_.end()) {
        if (!free_.empty()) {
          lane = free_.top();
          free_.pop();
          roster_[lane] = name;
        } else {
          lane = static_cast<int32_t>(roster_.size());
          roster_.push_back(name);
        }
        roster_idx_[name] = lane;
        pkt.named_idx.push_back(lane);
        pkt.names.push_back(name);
      } else {
        lane = it->second;
        auto sh = shadow_.find(lane);
        if (sh != shadow_.end() && sh->second.first == p &&
            sh->second.second == g)
          continue;  // unchanged since the last packet
      }
      pkt.idx.push_back(lane);
      pkt.pos.push_back(p);
      pkt.goal.push_back(g);
      shadow_[lane] = {p, g};
    }
    last_seq_ = seq;
    ++since_snapshot_;
    return pkt;
  }

  // lane -> peer id ("" for vacated lanes / out of range)
  const std::string& peer_of(int32_t lane) const {
    static const std::string empty;
    if (lane < 0 || static_cast<size_t>(lane) >= roster_.size()) return empty;
    return roster_[lane];
  }

  // (pos, goal) as last SENT for a lane — the packed analog of the JSON
  // path's sent_goals map (phantom-exchange guard in the manager).
  std::optional<std::pair<int32_t, int32_t>> shadow_of(int32_t lane) const {
    auto it = shadow_.find(lane);
    if (it == shadow_.end()) return std::nullopt;
    return it->second;
  }

  // The whole lane -> (pos, goal) state as last sent, sorted by lane
  // (std::map) — the audit plane (ISSUE 10) digests this after every
  // tick and the drill responder range-hashes it.
  const std::map<int32_t, std::pair<int32_t, int32_t>>& shadow_map() const {
    return shadow_;
  }

 private:
  int snapshot_every_;
  std::vector<std::string> roster_;  // lane -> peer id ("" = free)
  std::map<std::string, int32_t> roster_idx_;
  std::priority_queue<int32_t, std::vector<int32_t>, std::greater<int32_t>>
      free_;
  std::map<int32_t, std::pair<int32_t, int32_t>> shadow_;
  int64_t last_seq_ = 0;
  int since_snapshot_ = 0;
  bool force_snapshot_ = true;
};

// ---------- pos1 — packed position/heartbeat beacon (ISSUE 4) ----------
//
// Byte-identical mirror of plan_codec.py encode_pos1/decode_pos1 (see its
// docstring for the layout).  One beacon replaces the per-tick JSON
// position + position_update pair; peer identity rides the bus frame's
// `from` field.  Wire shape: {"type":"pos1","data":"<base64>"} on a
// region topic (common/region.hpp) or the flat legacy topic.

constexpr uint32_t kPos1Magic = 0x31534F50;  // b"POS1"
constexpr uint8_t kPos1Version = 1;
constexpr uint8_t kPos1FlagNarrow = 1;
constexpr uint8_t kPos1FlagTask = 2;
constexpr uint8_t kPos1FlagTrace = 4;  // trailing 20-byte trace1 block

struct Pos1 {
  int32_t pos = 0;
  int32_t goal = 0;
  bool has_task = false;
  int64_t task_id = 0;
  bool has_trace = false;
  TraceCtx trace;
};

inline std::string encode_pos1(int32_t pos, int32_t goal,
                               bool has_task = false, int64_t task_id = 0,
                               const TraceCtx* trace = nullptr) {
  const bool narrow = pos >= 0 && pos < 65536 && goal >= 0 && goal < 65536;
  std::string out;
  out.reserve(44);
  detail::put_u32(out, kPos1Magic);
  out += static_cast<char>(kPos1Version);
  out += static_cast<char>((narrow ? kPos1FlagNarrow : 0) |
                           (has_task ? kPos1FlagTask : 0) |
                           (trace ? kPos1FlagTrace : 0));
  detail::put_u16(out, 0);  // reserved
  if (narrow) {
    detail::put_u16(out, static_cast<uint16_t>(pos));
    detail::put_u16(out, static_cast<uint16_t>(goal));
  } else {
    detail::put_u32(out, static_cast<uint32_t>(pos));
    detail::put_u32(out, static_cast<uint32_t>(goal));
  }
  if (has_task) detail::put_i64(out, task_id);
  if (trace) detail::put_trace(out, *trace);
  return out;
}

inline std::optional<Pos1> decode_pos1(const std::string& buf) {
  if (buf.size() < 8) return std::nullopt;
  const uint8_t* b = reinterpret_cast<const uint8_t*>(buf.data());
  if (detail::get_u32(b) != kPos1Magic) return std::nullopt;
  if (b[4] != kPos1Version) return std::nullopt;
  const uint8_t flags = b[5];
  const bool narrow = (flags & kPos1FlagNarrow) != 0;
  Pos1 p;
  p.has_task = (flags & kPos1FlagTask) != 0;
  p.has_trace = (flags & kPos1FlagTrace) != 0;
  const size_t need = 8 + (narrow ? 4 : 8) + (p.has_task ? 8 : 0) +
                      (p.has_trace ? kTraceExtLen : 0);
  if (buf.size() != need) return std::nullopt;
  if (narrow) {
    p.pos = static_cast<int32_t>(b[8] | (b[9] << 8));
    p.goal = static_cast<int32_t>(b[10] | (b[11] << 8));
  } else {
    p.pos = static_cast<int32_t>(detail::get_u32(b + 8));
    p.goal = static_cast<int32_t>(detail::get_u32(b + 12));
  }
  size_t off = 8 + (narrow ? 4u : 8u);
  if (p.has_task) {
    p.task_id = detail::get_i64(b + off);
    off += 8;
  }
  if (p.has_trace) p.trace = detail::get_trace(b + off);
  return p;
}

inline std::string encode_pos1_b64(int32_t pos, int32_t goal,
                                   bool has_task = false,
                                   int64_t task_id = 0,
                                   const TraceCtx* trace = nullptr) {
  return b64_encode(encode_pos1(pos, goal, has_task, task_id, trace));
}

inline std::optional<Pos1> decode_pos1_b64(const std::string& data) {
  auto raw = b64_decode(data);
  if (!raw) return std::nullopt;
  return decode_pos1(*raw);
}

// --- agg1: per-region beacon aggregate (ISSUE 18) -------------------------
// Byte-identical mirror of plan_codec.py encode_agg1/decode_agg1 (see its
// docstring for the layout).  busd coalesces one region topic's pos1
// beacons within a tick window into one frame:
//   u32 "AGG1", u8 version, u8 flags (bit0: 20-byte trace1 block follows),
//   u16 n_entries, [trace], then per entry u16 name_len + u16 blob_len +
//   sender peer id + the pos1 blob VERBATIM.
// Wire shape: {"type":"agg1","data":"<base64>"} on the original region
// topic.  Decode rejects (nullopt) any malformation: short buffer, bad
// magic/version, truncated entry, trailing bytes.

constexpr uint32_t kAgg1Magic = 0x31474741;  // b"AGG1"
constexpr uint8_t kAgg1Version = 1;
constexpr uint8_t kAgg1FlagTrace = 1;

struct Agg1Entry {
  std::string name;  // sender peer id
  std::string blob;  // verbatim pos1 packet
};

struct Agg1 {
  std::vector<Agg1Entry> entries;
  bool has_trace = false;
  TraceCtx trace;
};

inline std::string encode_agg1(const std::vector<Agg1Entry>& entries,
                               const TraceCtx* trace = nullptr) {
  std::string out;
  size_t body = 0;
  for (const auto& e : entries) body += 4 + e.name.size() + e.blob.size();
  out.reserve(8 + (trace ? kTraceExtLen : 0) + body);
  detail::put_u32(out, kAgg1Magic);
  out += static_cast<char>(kAgg1Version);
  out += static_cast<char>(trace ? kAgg1FlagTrace : 0);
  detail::put_u16(out, static_cast<uint16_t>(entries.size()));
  if (trace) detail::put_trace(out, *trace);
  for (const auto& e : entries) {
    detail::put_u16(out, static_cast<uint16_t>(e.name.size()));
    detail::put_u16(out, static_cast<uint16_t>(e.blob.size()));
    out += e.name;
    out += e.blob;
  }
  return out;
}

inline std::optional<Agg1> decode_agg1(const std::string& buf) {
  if (buf.size() < 8) return std::nullopt;
  const uint8_t* b = reinterpret_cast<const uint8_t*>(buf.data());
  if (detail::get_u32(b) != kAgg1Magic) return std::nullopt;
  if (b[4] != kAgg1Version) return std::nullopt;
  const uint8_t flags = b[5];
  const uint16_t n = detail::get_u16(b + 6);
  Agg1 a;
  size_t off = 8;
  if (flags & kAgg1FlagTrace) {
    if (buf.size() < off + kTraceExtLen) return std::nullopt;
    a.has_trace = true;
    a.trace = detail::get_trace(b + off);
    off += kTraceExtLen;
  }
  a.entries.reserve(n);
  for (uint16_t i = 0; i < n; ++i) {
    if (buf.size() < off + 4) return std::nullopt;
    const uint16_t name_len = detail::get_u16(b + off);
    const uint16_t blob_len = detail::get_u16(b + off + 2);
    off += 4;
    if (buf.size() < off + name_len + blob_len) return std::nullopt;
    Agg1Entry e;
    e.name.assign(buf, off, name_len);
    off += name_len;
    e.blob.assign(buf, off, blob_len);
    off += blob_len;
    a.entries.push_back(std::move(e));
  }
  if (off != buf.size()) return std::nullopt;
  return a;
}

inline std::string encode_agg1_b64(const std::vector<Agg1Entry>& entries,
                                   const TraceCtx* trace = nullptr) {
  return b64_encode(encode_agg1(entries, trace));
}

inline std::optional<Agg1> decode_agg1_b64(const std::string& data) {
  auto raw = b64_decode(data);
  if (!raw) return std::nullopt;
  return decode_agg1(*raw);
}

}  // namespace codec
}  // namespace mapd
