// Control-plane HA (ISSUE 15) — native mirror of
// p2p_distributed_tswap_tpu/runtime/ha.py: the ledger1 replication
// record, active-side delta encoder, standby-side replica, and the
// lease/election rules.  BYTE-IDENTICAL to the Python side (golden-
// tested via codec_golden --ledger-encode/--ledger-decode, fuzzed by
// scripts/codec_fuzz.py) — keep every packing and diff rule in
// lockstep.
//
// ledger1 record (little-endian):
//   u32 magic "LDG1"  u8 version=1  u8 flags(bit0 snapshot)
//   u16 reserved=0
//   u32 n_tasks  u32 n_removed  u32 n_world  u32 n_handoffs
//   i64 seq  i64 base_seq  i64 incarnation  i64 plan_seq
//   i64 world_seq  i64 next_task_id
//   u64 ledger_digest  u64 view_digest     (audit canon, FULL ledger)
//   per task:    i64 id  u8 state  i32 pickup  i32 delivery
//                u16 peer_len  u8 peer[]
//   per removed: i64 id
//   per world:   i32 cell  u8 blocked
//   per handoff: i32 dst  i64 seq  i64 epoch  i32 pos  i32 goal
//                u8 phase  u8 has_task  i64 task_id  i32 pickup
//                i32 delivery  u16 peer_len  u8 peer[]
//                (the sender's FULL unacked cross-region handoff
//                outbox, shipped wholesale — a promoted standby
//                RESUMES the retransmit-until-ack loop instead of
//                losing a mid-transfer task)
//
// Framing: base64 in the "data" field of a {"type":"ledger1"} frame on
// raw bus topic "mapd.ha"; liveness rides a separate tiny "ha_lease"
// frame.  JG_HA unset/0 = nothing published or subscribed (the
// single-manager wire stays byte-identical).
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <tuple>
#include <vector>

#include "audit.hpp"

namespace mapd {
namespace ha {

constexpr const char* kHaTopic = "mapd.ha";
constexpr uint32_t kLedgerMagic = 0x3147444C;  // b"LDG1"
constexpr uint8_t kLedgerVersion = 1;
constexpr uint8_t kFlagSnapshot = 1;
constexpr int kSnapshotEvery = 64;
constexpr int64_t kDefaultLeaseMs = 500;

struct LedgerTask {
  int64_t task_id = 0;
  uint8_t state = 0;  // 0 pending, 1 to-pickup, 2 to-delivery
  int32_t pickup = 0;
  int32_t delivery = 0;
  std::string peer;  // assigned agent for in-flight entries, else ""

  bool operator==(const LedgerTask& o) const {
    return task_id == o.task_id && state == o.state &&
           pickup == o.pickup && delivery == o.delivery && peer == o.peer;
  }
  bool operator!=(const LedgerTask& o) const { return !(*this == o); }
};

// One UNACKED outbound cross-region handoff (the sender's outbox
// entry): everything needed to rebuild the exact original handoff1
// frame — same seq + SENDER epoch, so the receiver's dedup guard
// keeps working across the takeover.
struct HandoffOut {
  int32_t dst = 0;
  int64_t seq = 0;
  int64_t epoch = 0;
  std::string peer;
  int32_t pos = 0;
  int32_t goal = 0;
  uint8_t phase = 0;
  bool has_task = false;
  int64_t task_id = 0;
  int32_t pickup = 0;
  int32_t delivery = 0;

  bool operator==(const HandoffOut& o) const {
    return dst == o.dst && seq == o.seq && epoch == o.epoch &&
           peer == o.peer && pos == o.pos && goal == o.goal &&
           phase == o.phase && has_task == o.has_task &&
           task_id == o.task_id && pickup == o.pickup &&
           delivery == o.delivery;
  }
};

struct LedgerRec {
  int64_t seq = 0;
  int64_t base_seq = 0;
  int64_t incarnation = 0;
  int64_t plan_seq = 0;
  int64_t world_seq = 0;
  int64_t next_task_id = 0;
  bool snapshot = false;
  std::vector<LedgerTask> tasks;
  std::vector<int64_t> removed;
  std::vector<std::pair<int32_t, int>> world;  // (cell, blocked)
  std::vector<HandoffOut> handoffs;  // full outbox, every record
  uint64_t ledger_digest = 0;
  uint64_t view_digest = 0;
};

// (ledger_digest, view_digest) over a FULL ledger, audit canon
// (audit.hpp): ledger tuples sorted by (id, state), view = sorted
// in-flight ids.
inline std::pair<uint64_t, uint64_t> ledger_view_digests(
    const std::vector<LedgerTask>& tasks) {
  std::vector<std::tuple<int64_t, uint8_t, int32_t, int32_t>> tup;
  std::vector<int64_t> inflight;
  tup.reserve(tasks.size());
  for (const auto& t : tasks) {
    tup.emplace_back(t.task_id, t.state, t.pickup, t.delivery);
    if (t.state != audit::kTaskPending) inflight.push_back(t.task_id);
  }
  std::sort(tup.begin(), tup.end());
  std::sort(inflight.begin(), inflight.end());
  audit::LedgerDigest ld;
  for (const auto& [id, st, pk, dl] : tup) ld.add(id, st, pk, dl);
  return {ld.digest(), audit::view_digest(inflight)};
}

namespace detail {
inline void put_u16(std::string& b, uint16_t v) {
  b += static_cast<char>(v & 0xFF);
  b += static_cast<char>((v >> 8) & 0xFF);
}
inline void put_i32(std::string& b, int32_t v) {
  uint32_t u = static_cast<uint32_t>(v);
  for (int k = 0; k < 4; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline void put_i64(std::string& b, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  for (int k = 0; k < 8; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline uint16_t get_u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}
inline uint32_t get_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}
inline uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  for (int k = 7; k >= 0; --k) v = (v << 8) | p[k];
  return v;
}
}  // namespace detail

inline std::string encode_ledger(const LedgerRec& r) {
  std::string out;
  out.reserve(84 + r.tasks.size() * 32 + r.removed.size() * 8 +
              r.world.size() * 5 + r.handoffs.size() * 96);
  detail::put_i32(out, static_cast<int32_t>(kLedgerMagic));
  out += static_cast<char>(kLedgerVersion);
  out += static_cast<char>(r.snapshot ? kFlagSnapshot : 0);
  detail::put_u16(out, 0);  // reserved
  detail::put_i32(out, static_cast<int32_t>(r.tasks.size()));
  detail::put_i32(out, static_cast<int32_t>(r.removed.size()));
  detail::put_i32(out, static_cast<int32_t>(r.world.size()));
  detail::put_i32(out, static_cast<int32_t>(r.handoffs.size()));
  detail::put_i64(out, r.seq);
  detail::put_i64(out, r.base_seq);
  detail::put_i64(out, r.incarnation);
  detail::put_i64(out, r.plan_seq);
  detail::put_i64(out, r.world_seq);
  detail::put_i64(out, r.next_task_id);
  detail::put_i64(out, static_cast<int64_t>(r.ledger_digest));
  detail::put_i64(out, static_cast<int64_t>(r.view_digest));
  for (const auto& t : r.tasks) {
    detail::put_i64(out, t.task_id);
    out += static_cast<char>(t.state);
    detail::put_i32(out, t.pickup);
    detail::put_i32(out, t.delivery);
    detail::put_u16(out, static_cast<uint16_t>(t.peer.size()));
    out += t.peer;
  }
  for (int64_t tid : r.removed) detail::put_i64(out, tid);
  for (const auto& [cell, blocked] : r.world) {
    detail::put_i32(out, cell);
    out += static_cast<char>(blocked ? 1 : 0);
  }
  for (const auto& h : r.handoffs) {
    detail::put_i32(out, h.dst);
    detail::put_i64(out, h.seq);
    detail::put_i64(out, h.epoch);
    detail::put_i32(out, h.pos);
    detail::put_i32(out, h.goal);
    out += static_cast<char>(h.phase);
    out += static_cast<char>(h.has_task ? 1 : 0);
    detail::put_i64(out, h.has_task ? h.task_id : 0);
    detail::put_i32(out, h.pickup);
    detail::put_i32(out, h.delivery);
    detail::put_u16(out, static_cast<uint16_t>(h.peer.size()));
    out += h.peer;
  }
  return out;
}

inline std::optional<LedgerRec> decode_ledger(const std::string& buf) {
  constexpr size_t kFixed = 24 + 64;  // head + watermarks
  if (buf.size() < kFixed) return std::nullopt;
  const uint8_t* b = reinterpret_cast<const uint8_t*>(buf.data());
  if (detail::get_u32(b) != kLedgerMagic) return std::nullopt;
  if (b[4] != kLedgerVersion) return std::nullopt;
  LedgerRec r;
  r.snapshot = (b[5] & kFlagSnapshot) != 0;
  const uint32_t n_tasks = detail::get_u32(b + 8);
  const uint32_t n_removed = detail::get_u32(b + 12);
  const uint32_t n_world = detail::get_u32(b + 16);
  const uint32_t n_handoffs = detail::get_u32(b + 20);
  r.seq = static_cast<int64_t>(detail::get_u64(b + 24));
  r.base_seq = static_cast<int64_t>(detail::get_u64(b + 32));
  r.incarnation = static_cast<int64_t>(detail::get_u64(b + 40));
  r.plan_seq = static_cast<int64_t>(detail::get_u64(b + 48));
  r.world_seq = static_cast<int64_t>(detail::get_u64(b + 56));
  r.next_task_id = static_cast<int64_t>(detail::get_u64(b + 64));
  r.ledger_digest = detail::get_u64(b + 72);
  r.view_digest = detail::get_u64(b + 80);
  size_t off = kFixed;
  for (uint32_t k = 0; k < n_tasks; ++k) {
    if (off + 19 > buf.size()) return std::nullopt;
    LedgerTask t;
    t.task_id = static_cast<int64_t>(detail::get_u64(b + off));
    t.state = b[off + 8];
    if (t.state > audit::kTaskToDelivery) return std::nullopt;
    t.pickup = static_cast<int32_t>(detail::get_u32(b + off + 9));
    t.delivery = static_cast<int32_t>(detail::get_u32(b + off + 13));
    const uint16_t peer_len = detail::get_u16(b + off + 17);
    off += 19;
    if (off + peer_len > buf.size()) return std::nullopt;
    t.peer.assign(buf, off, peer_len);
    off += peer_len;
    r.tasks.push_back(std::move(t));
  }
  if (off + static_cast<size_t>(n_removed) * 8 +
          static_cast<size_t>(n_world) * 5 > buf.size())
    return std::nullopt;
  for (uint32_t k = 0; k < n_removed; ++k, off += 8)
    r.removed.push_back(static_cast<int64_t>(detail::get_u64(b + off)));
  for (uint32_t k = 0; k < n_world; ++k, off += 5)
    r.world.emplace_back(static_cast<int32_t>(detail::get_u32(b + off)),
                         b[off + 4] ? 1 : 0);
  for (uint32_t k = 0; k < n_handoffs; ++k) {
    if (off + 48 > buf.size()) return std::nullopt;
    HandoffOut h;
    h.dst = static_cast<int32_t>(detail::get_u32(b + off));
    h.seq = static_cast<int64_t>(detail::get_u64(b + off + 4));
    h.epoch = static_cast<int64_t>(detail::get_u64(b + off + 12));
    h.pos = static_cast<int32_t>(detail::get_u32(b + off + 20));
    h.goal = static_cast<int32_t>(detail::get_u32(b + off + 24));
    h.phase = b[off + 28];
    h.has_task = b[off + 29] != 0;
    h.task_id = static_cast<int64_t>(detail::get_u64(b + off + 30));
    h.pickup = static_cast<int32_t>(detail::get_u32(b + off + 38));
    h.delivery = static_cast<int32_t>(detail::get_u32(b + off + 42));
    const uint16_t peer_len = detail::get_u16(b + off + 46);
    off += 48;
    if (off + peer_len > buf.size()) return std::nullopt;
    h.peer.assign(buf, off, peer_len);
    off += peer_len;
    r.handoffs.push_back(std::move(h));
  }
  if (buf.size() != off) return std::nullopt;
  return r;
}

// ---------- active-side delta tracking ----------
// Mirrors ha.py LedgerEncoder exactly: removed ids ascend, changed
// tasks keep caller order, world diffs sorted by cell, snapshot resets
// the chain and ships the full world sorted by cell.
class LedgerEncoder {
 public:
  explicit LedgerEncoder(int64_t incarnation,
                         int snapshot_every = kSnapshotEvery)
      : incarnation_(incarnation), snapshot_every_(snapshot_every) {}

  void request_snapshot() { force_snapshot_ = true; }
  int64_t last_seq() const { return last_seq_; }
  void set_incarnation(int64_t inc) { incarnation_ = inc; }

  std::optional<LedgerRec> encode_tick(
      int64_t plan_seq, int64_t world_seq, int64_t next_task_id,
      const std::vector<LedgerTask>& tasks,
      const std::map<int32_t, int>& world,
      const std::vector<HandoffOut>& handoffs_in = {}) {
    auto [ld, vd] = ledger_view_digests(tasks);
    // the outbox ships wholesale, sorted by (dst, seq) like ha.py
    std::vector<HandoffOut> handoffs = handoffs_in;
    std::sort(handoffs.begin(), handoffs.end(),
              [](const HandoffOut& a, const HandoffOut& b) {
                return a.dst != b.dst ? a.dst < b.dst : a.seq < b.seq;
              });
    const bool snapshot =
        force_snapshot_ || since_snapshot_ + 1 >= snapshot_every_;
    if (snapshot) {
      LedgerRec rec;
      rec.seq = last_seq_ + 1;
      rec.base_seq = 0;
      rec.incarnation = incarnation_;
      rec.plan_seq = plan_seq;
      rec.world_seq = world_seq;
      rec.next_task_id = next_task_id;
      rec.snapshot = true;
      rec.tasks = tasks;
      for (const auto& [c, bl] : world) rec.world.emplace_back(c, bl);
      rec.handoffs = handoffs;
      rec.ledger_digest = ld;
      rec.view_digest = vd;
      shadow_.clear();
      for (const auto& t : tasks) shadow_[t.task_id] = t;
      world_shadow_ = world;
      handoff_shadow_ = handoffs;
      last_seq_ = rec.seq;
      since_snapshot_ = 0;
      force_snapshot_ = false;
      return rec;
    }
    LedgerRec rec;
    rec.snapshot = false;
    std::set<int64_t> current;
    for (const auto& t : tasks) current.insert(t.task_id);
    for (const auto& [tid, t] : shadow_) {
      (void)t;
      if (!current.count(tid))
        rec.removed.push_back(tid);  // std::map: ascending
    }
    for (const auto& t : tasks) {
      auto it = shadow_.find(t.task_id);
      if (it == shadow_.end() || it->second != t) rec.tasks.push_back(t);
    }
    for (const auto& [c, bl] : world) {
      auto it = world_shadow_.find(c);
      if (it == world_shadow_.end() || it->second != bl)
        rec.world.emplace_back(c, bl);  // std::map: ascending by cell
    }
    if (rec.removed.empty() && rec.tasks.empty() && rec.world.empty() &&
        handoffs == handoff_shadow_)
      return std::nullopt;
    rec.seq = last_seq_ + 1;
    rec.base_seq = last_seq_;
    rec.incarnation = incarnation_;
    rec.plan_seq = plan_seq;
    rec.world_seq = world_seq;
    rec.next_task_id = next_task_id;
    rec.handoffs = handoffs;
    rec.ledger_digest = ld;
    rec.view_digest = vd;
    for (int64_t tid : rec.removed) shadow_.erase(tid);
    for (const auto& t : rec.tasks) shadow_[t.task_id] = t;
    for (const auto& [c, bl] : rec.world) world_shadow_[c] = bl;
    handoff_shadow_ = handoffs;
    last_seq_ = rec.seq;
    ++since_snapshot_;
    return rec;
  }

 private:
  int64_t incarnation_;
  int snapshot_every_;
  std::map<int64_t, LedgerTask> shadow_;
  std::map<int32_t, int> world_shadow_;
  std::vector<HandoffOut> handoff_shadow_;
  int64_t last_seq_ = 0;
  int since_snapshot_ = 0;
  bool force_snapshot_ = true;
};

// ---------- standby-side replica ----------
// Mirrors ha.py LedgerReplica.  apply() outcomes:
//   kApplied       applied, digests verified
//   kDivergent     applied but the recomputed full-ledger digests
//                  disagree with the record's — resync, never promote
//   kGap           chain break (incl. a new incarnation opening with a
//                  delta) — request a snapshot
//   kStale         dead-incarnation frame, dropped
enum class ApplyResult { kApplied, kDivergent, kGap, kStale };

class LedgerReplica {
 public:
  std::map<int64_t, LedgerTask> tasks;
  std::map<int32_t, int> world;
  // the active's unacked handoff outbox as last shipped — a promoted
  // standby resumes retransmitting exactly these
  std::vector<HandoffOut> handoffs;
  int64_t seq = 0;
  int64_t incarnation = 0;
  int64_t plan_seq = 0;
  int64_t world_seq = 0;
  int64_t next_task_id = 0;
  int64_t applied = 0;
  int64_t divergences = 0;

  ApplyResult apply(const LedgerRec& rec) {
    if (incarnation && rec.incarnation < incarnation)
      return ApplyResult::kStale;
    if (rec.incarnation > incarnation) {
      tasks.clear();
      world.clear();
      handoffs.clear();
      seq = 0;
      incarnation = rec.incarnation;
      if (!rec.snapshot) return ApplyResult::kGap;
    }
    if (rec.snapshot) {
      tasks.clear();
      for (const auto& t : rec.tasks) tasks[t.task_id] = t;
      world.clear();
      for (const auto& [c, bl] : rec.world) world[c] = bl;
    } else {
      if (rec.base_seq != seq) return ApplyResult::kGap;
      for (int64_t tid : rec.removed) tasks.erase(tid);
      for (const auto& t : rec.tasks) tasks[t.task_id] = t;
      for (const auto& [c, bl] : rec.world) world[c] = bl;
    }
    handoffs = rec.handoffs;  // wholesale, every record
    seq = rec.seq;
    plan_seq = rec.plan_seq;
    world_seq = rec.world_seq;
    next_task_id = rec.next_task_id;
    ++applied;
    std::vector<LedgerTask> all;
    all.reserve(tasks.size());
    for (const auto& [tid, t] : tasks) {
      (void)tid;
      all.push_back(t);
    }
    auto [ld, vd] = ledger_view_digests(all);
    if (ld != rec.ledger_digest || vd != rec.view_digest) {
      ++divergences;
      return ApplyResult::kDivergent;
    }
    return ApplyResult::kApplied;
  }
};

// The standby's lease rule — the auditor's silent-peer threshold:
// quiet past 3 of the active's own advertised intervals + 1 s grace.
inline bool lease_expired(int64_t now_ms, int64_t last_ms,
                          int64_t interval_ms) {
  if (!last_ms) return false;
  return now_ms - last_ms > 3 * interval_ms + 1000;
}

// Split-brain guard: between two claimants of one active role, the
// LOWER (incarnation, peer_id) demotes — both sides apply the same
// rule, so exactly one yields.  Mirrors ha.py should_demote.
inline bool should_demote(int64_t my_inc, const std::string& my_peer,
                          int64_t other_inc,
                          const std::string& other_peer) {
  if (other_inc != my_inc) return other_inc > my_inc;
  return other_peer > my_peer;
}

// HA is OFF unless JG_HA is set truthy (the kill switch that keeps the
// single-manager wire byte-identical: no mapd.ha frames at all).
inline bool ha_enabled() {
  const char* v = getenv("JG_HA");
  return v && v[0] && !(v[0] == '0' && v[1] == '\0');
}

}  // namespace ha
}  // namespace mapd
