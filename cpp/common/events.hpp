// Structured task-lifecycle events with cross-process causal context —
// native mirror of p2p_distributed_tswap_tpu/obs/events.py (one schema,
// one timeline tool: analysis/task_timeline.py merges every process's
// .events.jsonl into per-task causal timelines).
//
// Each emitted event carries the trace context that rode the triggering
// message (trace_id rooted at task creation, monotone hop counter, the
// sender's wall-clock send_ms) and fans out to:
//   1. the flight-recorder ring (common/flightrec.hpp) — ALWAYS on;
//   2. hop_latency_ms{edge=...} registry histograms (clock-skew clamped,
//      raw negatives counted as hop.clock_skew_events) whenever a
//      send_ms rode in;
//   3. with JG_TRACE=1 and the trace_id sampled in (JG_TRACE_SAMPLE,
//      deterministic mod-997 residue — identical to the Python side so a
//      task's whole multi-process timeline samples atomically): a
//      write-through line in $JG_TRACE_DIR/<proc>-<pid>.events.jsonl and
//      a Perfetto flow event in the span tracer.
//
// Wire helpers: tc_json / tc_parse move the JSON "tc":[id,hop,send_ms]
// field; the packed codecs carry codec::TraceCtx natively (trace1).
// JG_TRACE_CTX=0 is the kill switch: no context on the wire, no events.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <optional>
#include <string>

#include "flightrec.hpp"
#include "json.hpp"
#include "metrics.hpp"
#include "plan_codec.hpp"
#include "trace.hpp"

namespace mapd {

constexpr int kSampleMod = 997;  // prime, mirrored by obs/events.py
constexpr double kHopClampMaxMs = 60000.0;

inline bool trace_ctx_enabled() {
  const char* v = getenv("JG_TRACE_CTX");
  return !v || (*v && strcmp(v, "0") && strcmp(v, "false"));
}

inline double trace_sample_rate() {
  const char* v = getenv("JG_TRACE_SAMPLE");
  if (!v || !*v) return 1.0;
  char* end = nullptr;
  double r = strtod(v, &end);
  return end == v ? 1.0 : r;
}

inline bool trace_sampled(int64_t trace_id) {
  double rate = trace_sample_rate();
  if (rate >= 1.0) return true;
  if (rate <= 0.0) return false;
  int64_t res = trace_id % kSampleMod;
  if (res < 0) res += kSampleMod;
  return res < static_cast<int64_t>(rate * kSampleMod);
}

inline int64_t events_now_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(system_clock::now().time_since_epoch())
      .count();
}

// "tc":[trace_id, hop, send_ms] — stamped at build time (send side)
inline Json tc_json(int64_t trace_id, uint32_t hop) {
  Json a;
  a.push_back(Json(trace_id));
  a.push_back(Json(static_cast<int64_t>(hop)));
  a.push_back(Json(events_now_ms()));
  return a;
}

inline Json tc_json(const codec::TraceCtx& t) {
  Json a;
  a.push_back(Json(t.trace_id));
  a.push_back(Json(static_cast<int64_t>(t.hop)));
  a.push_back(Json(t.send_ms));
  return a;
}

inline std::optional<codec::TraceCtx> tc_parse(const Json& d) {
  if (!d.has("tc")) return std::nullopt;
  const auto& arr = d["tc"].as_array();
  if (arr.size() != 3) return std::nullopt;
  codec::TraceCtx t;
  t.trace_id = arr[0].as_int();
  t.hop = static_cast<uint32_t>(arr[1].as_int());
  t.send_ms = arr[2].as_int();
  return t;
}

// Clock-skew-clamped one-way latency, recorded per edge (same clamp
// discipline as the PR-1 task-metric derivations).
inline double hop_latency_ms(int64_t send_ms, const std::string& edge) {
  double raw = static_cast<double>(events_now_ms() - send_ms);
  if (raw < 0) metrics_count("hop.clock_skew_events");
  double lat = raw < 0 ? 0.0 : (raw > kHopClampMaxMs ? kHopClampMaxMs : raw);
  if (!edge.empty())
    metrics_observe("hop_latency_ms", lat, "edge=\"" + edge + "\"");
  return lat;
}

class EventLog {
 public:
  static EventLog& instance() {
    static EventLog e;
    return e;
  }

  void init(const char* proc) { proc_ = proc; }

  // One lifecycle event.  tc: the context that rode (or will ride) the
  // wire, nullptr when none.  task_id < 0 / empty peer / send_ms < 0 are
  // "absent".  send_ms is the TRIGGERING message's sender stamp —
  // present exactly when this event is the receive side of a wire hop.
  // JG_TRACE_CTX=0 kills the whole context subsystem: trace-correlated
  // events (tc != nullptr) are suppressed on BOTH send and receive sides;
  // context-free events (bus membership, crashes) still hit the ring.
  void emit(const char* event, const codec::TraceCtx* tc,
            long long task_id = -1, const std::string& peer = "",
            int64_t send_ms = -1) {
    if (tc && !trace_ctx_enabled()) return;
    const int64_t ts = events_now_ms();
    std::string line;
    line.reserve(192);
    line += "{\"ts_ms\":" + std::to_string(ts);
    line += ",\"proc\":\"" + proc_ + "\"";
    line += ",\"pid\":" + std::to_string(getpid());
    line += ",\"event\":\"";
    line += event;
    line += "\"";
    if (tc) {
      line += ",\"trace_id\":" + std::to_string(tc->trace_id);
      line += ",\"hop\":" + std::to_string(tc->hop);
    }
    if (task_id >= 0) line += ",\"task_id\":" + std::to_string(task_id);
    if (!peer.empty()) {
      line += ",\"peer\":\"";
      for (char c : peer)
        if (c != '"' && c != '\\' && static_cast<unsigned char>(c) >= 0x20)
          line += c;
      line += "\"";
    }
    double wire = -1.0;
    if (send_ms >= 0) {
      wire = hop_latency_ms(send_ms, event);
      line += ",\"send_ms\":" + std::to_string(send_ms);
      char buf[32];
      snprintf(buf, sizeof(buf), ",\"wire_ms\":%.3f", wire);
      line += buf;
    }
    line += "}";
    flight_record(line);
    metrics_count("events.emitted", 1,
                  "event=\"" + std::string(event) + "\"");
    if (!tc || !trace_enabled() || !trace_sampled(tc->trace_id)) return;
    write_line(line);
    // Perfetto flow: constant name/cat, id = trace_id (see obs/events.py)
    char phase = 't';
    const size_t n = strlen(event);
    if (!strcmp(event, "task.dispatch") && tc->hop <= 1)
      phase = 's';
    else if (n >= 8 && !strcmp(event + n - 8, "done_ack"))
      phase = 'f';
    Tracer::instance().flow("task", tc->trace_id, phase,
                            "\"step\":\"" + std::string(event) + "\"");
  }

  std::string events_path() const {
    const char* dir = getenv("JG_TRACE_DIR");
    std::string d = dir && *dir ? dir : "results/trace";
    return d + "/" + proc_ + "-" + std::to_string(getpid()) +
           ".events.jsonl";
  }

  ~EventLog() {
    if (f_) fclose(f_);
  }

 private:
  EventLog() = default;

  void write_line(const std::string& line) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!f_) {
      std::string path = events_path();
      size_t slash = path.rfind('/');
      if (slash != std::string::npos) {
        std::string dir = path.substr(0, slash);
        std::string cur;
        for (size_t i = 0; i < dir.size(); ++i) {
          cur += dir[i];
          if (dir[i] == '/' || i + 1 == dir.size())
            mkdir(cur.c_str(), 0755);  // EEXIST is fine
        }
      }
      f_ = fopen(path.c_str(), "a");
      if (!f_) return;
    }
    fprintf(f_, "%s\n", line.c_str());
    fflush(f_);  // write-through: timelines must be live, rates are tiny
  }

  std::string proc_ = "cpp";
  FILE* f_ = nullptr;
  std::mutex mu_;
};

// Call once at process entry: names the event log AND installs the
// flight-recorder dump triggers (they always travel together).
inline void events_init(const char* proc) {
  EventLog::instance().init(proc);
  flightrec_install(proc);
}

// Per-task wire-hop ledger (one per manager): every SEND of a
// task-lifecycle message advances the task's hop, incoming contexts
// fast-forward it (max-merge), so hops stay monotone along the causal
// chain even when the agent advanced it.  The map is BOUNDED by evicting
// the oldest ids (they ascend, so begin() is the oldest, long-done task)
// — entries are NOT erased at completion, because late duplicate dones
// must keep advancing the same counter.
class TaskHopLedger {
 public:
  explicit TaskHopLedger(int64_t epoch) : epoch_(epoch) {}

  // context for the NEXT send referencing this task (hop advances)
  codec::TraceCtx next(long long tid) {
    while (hops_.size() > 8192 && hops_.begin()->first != tid)
      hops_.erase(hops_.begin());
    uint32_t& h = hops_[tid];
    return codec::TraceCtx{epoch_ | tid, ++h, events_now_ms()};
  }

  // context at the CURRENT hop (local milestone events, not sends)
  codec::TraceCtx current(long long tid) {
    return codec::TraceCtx{epoch_ | tid, hops_[tid], events_now_ms()};
  }

  void seen(long long tid, const codec::TraceCtx& t) {
    uint32_t& h = hops_[tid];
    if (t.hop > h) h = t.hop;
  }

 private:
  int64_t epoch_;
  std::map<long long, uint32_t> hops_;
};

// The bus "flight_dump" answer every process publishes (ISSUE 5): dump
// the ring, report the path — one schema, built in one place.
inline Json flight_dump_answer(const char* proc,
                               const std::string& peer_id) {
  std::string path = FlightRec::instance().default_path();
  bool ok = FlightRec::instance().dump("bus_request", path);
  Json resp;
  resp.set("type", "flight_dump_response")
      .set("proc", proc)
      .set("peer_id", peer_id)
      .set("path", ok ? Json(path) : Json())
      .set("events", static_cast<int64_t>(FlightRec::instance().size()));
  return resp;
}

inline void event_emit(const char* event, const codec::TraceCtx* tc,
                       long long task_id = -1, const std::string& peer = "",
                       int64_t send_ms = -1) {
  EventLog::instance().emit(event, tc, task_id, peer, send_ms);
}

}  // namespace mapd
