// Pub/sub bus client.
//
// The reference's communication backend is a libp2p gossipsub mesh with mDNS
// LAN discovery (SURVEY C9); every runtime message is a broadcast on the
// single topic "mapd" (C10).  The host-runtime equivalent is a lightweight
// hub: roles connect to `busd` over loopback TCP, subscribe to topics, and
// publish JSON payloads that fan out to all other subscribers.  Discovery
// parity: the bus emits peer_joined / peer_left events (the capability of
// mDNS discovered/expired), and answers peers queries (the capability of
// gossipsub::all_peers the managers use for round-robin dispatch).
//
// Frame protocol (one JSON per line):
//   client->bus: {"op":"hello","peer_id":s} | {"op":"sub","topic":s}
//                | {"op":"unsub","topic":s} | {"op":"pub","topic":s,"data":v}
//                | {"op":"peers","topic":s}
//   bus->client: {"op":"msg","topic":s,"from":s,"data":v}
//                | {"op":"peer_joined","peer_id":s,"topic":s}
//                | {"op":"peer_left","peer_id":s}
//                | {"op":"peers","topic":s,"peers":[s...]}
#pragma once

#include <poll.h>

#include <chrono>
#include <cstdio>
#include <deque>
#include <functional>
#include <random>
#include <string>

#include "json.hpp"
#include "metrics.hpp"
#include "net.hpp"

namespace mapd {

inline int64_t unix_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(system_clock::now().time_since_epoch())
      .count();
}

inline int64_t mono_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(steady_clock::now().time_since_epoch())
      .count();
}

// Random peer id, shaped like a libp2p PeerId for log familiarity.
inline std::string random_peer_id() {
  static const char* alphabet =
      "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz";
  std::mt19937_64 rng(std::random_device{}());
  std::string id = "12D3KooW";
  for (int i = 0; i < 36; ++i) id += alphabet[rng() % 58];
  return id;
}

class BusClient {
 public:
  // Received application message.
  struct Msg {
    std::string topic;
    std::string from;
    Json data;
  };

  BusClient() = default;

  bool connect(const std::string& host, uint16_t port,
               const std::string& peer_id) {
    int fd = tcp_connect(host, port);
    if (fd < 0) return false;
    set_nonblocking(fd);
    conn_ = LineConn(fd);
    peer_id_ = peer_id;
    Json hello;
    hello.set("op", "hello").set("peer_id", peer_id);
    conn_.send_line(hello.dump());
    return true;
  }

  const std::string& peer_id() const { return peer_id_; }
  int fd() const { return conn_.fd(); }
  bool connected() const { return conn_.valid(); }
  bool wants_write() const { return conn_.wants_write(); }
  NetworkMetrics& net_metrics() { return net_; }

  void subscribe(const std::string& topic) {
    Json j;
    j.set("op", "sub").set("topic", topic);
    send_control(j);
  }

  void publish(const std::string& topic, const Json& data) {
    Json j;
    j.set("op", "pub").set("topic", topic).set("data", data);
    std::string line = j.dump();
    net_.record_sent(line.size());
    conn_.send_line(line);
  }

  void query_peers(const std::string& topic) {
    Json j;
    j.set("op", "peers").set("topic", topic);
    send_control(j);
  }

  // Pump socket events.  Returns false if the bus connection died.
  // on_msg: application messages; on_event: peer_joined/peer_left/peers.
  bool pump(const std::function<void(const Msg&)>& on_msg,
            const std::function<void(const Json&)>& on_event = nullptr) {
    if (!conn_.valid()) return false;
    if (!conn_.on_readable()) return false;
    while (auto line = conn_.next_line()) {
      auto parsed = Json::parse(*line);
      if (!parsed || !parsed->is_object()) continue;  // ignore garbage frames
      const Json& j = *parsed;
      const std::string& op = j["op"].as_str();
      if (op == "msg") {
        net_.record_received(line->size());
        if (on_msg) on_msg(Msg{j["topic"].as_str(), j["from"].as_str(),
                               j["data"]});
      } else if (on_event) {
        on_event(j);
      }
    }
    return conn_.on_writable();
  }

  bool flush() { return conn_.on_writable(); }
  void close() { conn_.close_fd(); }

 private:
  void send_control(const Json& j) { conn_.send_line(j.dump()); }

  LineConn conn_;
  std::string peer_id_;
  NetworkMetrics net_;
};

}  // namespace mapd
