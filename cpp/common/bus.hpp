// Pub/sub bus client.
//
// The reference's communication backend is a libp2p gossipsub mesh with mDNS
// LAN discovery (SURVEY C9); every runtime message is a broadcast on the
// single topic "mapd" (C10).  The host-runtime equivalent is a lightweight
// hub: roles connect to `busd` over loopback TCP, subscribe to topics, and
// publish JSON payloads that fan out to all other subscribers.  Discovery
// parity: the bus emits peer_joined / peer_left events (the capability of
// mDNS discovered/expired), and answers peers queries (the capability of
// gossipsub::all_peers the managers use for round-robin dispatch).
//
// Frame protocol (one JSON per line):
//   client->bus: {"op":"hello","peer_id":s,"caps":[s...]}
//                | {"op":"sub","topic":s}
//                | {"op":"unsub","topic":s} | {"op":"pub","topic":s,"data":v}
//                | {"op":"peers","topic":s}
//   bus->client: {"op":"msg","topic":s,"from":s,"data":v}
//                | {"op":"welcome","peer_id":s,"caps":[s...]}
//                | {"op":"peer_joined","peer_id":s,"topic":s}
//                | {"op":"peer_left","peer_id":s}
//                | {"op":"peers","topic":s,"peers":[s...]}
//
// Relay fast framing (ISSUE 4, caps-negotiated): a client advertises
// `caps:["relay1"]` in hello; when the hub's welcome echoes the cap, the
// hot path switches to topic-prefix lines the hub relays without JSON
// parsing (topics must not contain spaces):
//   client->bus publish: `P<topic> <payload-json>`
//   bus->client deliver: `M<topic> <from> <payload-json>`
// Everything else (hello/sub/welcome/peers/discovery events) stays JSON.
// Kill switch: JG_BUS_FASTFRAME=0 keeps this client on the legacy JSON
// wire end to end; an old hub (welcome without caps) does the same.
// A topic ending in ".*" subscribes by prefix (busd wildcard matching).
//
// Sharded bus pool (ISSUE 6): when JG_BUS_SHARD_PORTS advertises a pool,
// the client becomes SHARD-AWARE — one connection per shard it needs,
// each subscription/publish routed to the owning shard by the
// deterministic shardmap (cpp/common/shardmap.hpp ≡ runtime/shardmap.py:
// region position topics spread across the pool, the control plane on the
// home shard), the `shard1` cap advertised so busd suppresses duplicate
// peer-forwarded deliveries, and reconnect/backoff handled PER SHARD: a
// dead shard degrades its regions while the rest of the pool flows
// (non-home shards always self-heal, independent of set_reconnect).
// Every publish dropped while the owning shard is down is counted
// (`bus.pub_dropped_disconnected`) and — for control-plane topics — held
// in a small bounded outbox replayed when that shard returns.
// With a single port (JG_BUS_SHARDS=1 kill switch) the wire is
// byte-identical to the single-hub client.
//
// Zero-copy same-host lanes (ISSUE 18, caps `shm1`): with JG_BUS_SHM set
// truthy the client creates one shared-memory ring pair per shard link
// (common/shmlane.hpp ≡ runtime/shmlane.py) and offers it in hello
// (`"shm":{"path":...,"v":1}`); when the hub's welcome echoes `shm1`,
// droppable-class frames (beacons/metrics/path) move through the rings as
// the exact relay lines — publishes via the c2s ring, deliveries via s2c —
// while TCP keeps the control plane, oversized frames, and cross-host
// links.  Ring overflow falls back to TCP per frame
// (`bus.shm_fallbacks`), a dead hub tears the lane down with the TCP
// session.  JG_BUS_SHM unset keeps the wire byte-identical.
// Beacon aggregation (caps `agg1`, JG_BUS_AGG_MS>0): the hub delivers
// coalesced agg1 frames per region topic per window (chunked to fit
// lane slots, so aggregates ride the rings); this client transparently
// explodes them back into per-peer pos1 messages, so role code never
// sees the aggregate.
#pragma once

#include <poll.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <random>
#include <set>
#include <string>
#include <vector>

#include "json.hpp"
#include "metrics.hpp"
#include "net.hpp"
#include "plan_codec.hpp"  // agg1 explode (ISSUE 18)
#include "shardmap.hpp"
#include "shmlane.hpp"

namespace mapd {

inline int64_t unix_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(system_clock::now().time_since_epoch())
      .count();
}

inline int64_t mono_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(steady_clock::now().time_since_epoch())
      .count();
}

// JG_BUS_FASTFRAME=0 pins this client to the legacy JSON wire (default on).
inline bool fastframe_enabled() {
  const char* v = getenv("JG_BUS_FASTFRAME");
  return !v || (*v && strcmp(v, "0") && strcmp(v, "false"));
}

// Beacon-aggregation window (ms).  >0 makes this client advertise the
// `agg1` cap (it can decode coalesced region beacons); 0/unset keeps the
// hello — and therefore the whole wire — byte-identical.
inline int64_t agg_window_env() {
  const char* v = getenv("JG_BUS_AGG_MS");
  return v && *v ? atol(v) : 0;
}

// Control-plane topics are everything busd itself refuses to shed under
// backpressure: not position beacons, not metrics, not path samples.
// These are the frames the replay outbox preserves across an outage.
// Judged on the LOGICAL topic — a tenant's beacons shed like anyone's.
inline bool bus_control_topic(const std::string& topic) {
  const std::string logical = shardmap::strip_ns(topic);
  return logical.compare(0, 9, "mapd.pos.") != 0 &&
         logical != "mapd.metrics" && logical != "mapd.path";
}

// Tenant namespace (ISSUE 8, runtime/busns.py mirror): JG_BUS_NS
// prefixes every logical topic "<ns>:" on the wire; empty = the
// byte-identical legacy wire.  Separators that would corrupt framing
// are fatal — a half-applied namespace must never leak cross-tenant.
inline std::string bus_namespace_from_env() {
  const char* v = getenv("JG_BUS_NS");
  std::string ns = v ? v : "";
  if (ns.find(':') != std::string::npos ||
      ns.find(' ') != std::string::npos ||
      ns.find('\n') != std::string::npos) {
    fprintf(stderr, "bus: invalid JG_BUS_NS \"%s\"\n", ns.c_str());
    exit(2);
  }
  return ns;
}

// Random peer id, shaped like a libp2p PeerId for log familiarity.
inline std::string random_peer_id() {
  static const char* alphabet =
      "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz";
  std::mt19937_64 rng(std::random_device{}());
  std::string id = "12D3KooW";
  for (int i = 0; i < 36; ++i) id += alphabet[rng() % 58];
  return id;
}

class BusClient {
 public:
  // Received application message.
  struct Msg {
    std::string topic;
    std::string from;
    Json data;
  };

  BusClient() = default;

  // Connect to the bus.  `port` is the home shard; when
  // JG_BUS_SHARD_PORTS advertises a pool the other shards are dialed
  // lazily, on the first subscription or publish that routes to them.
  bool connect(const std::string& host, uint16_t port,
               const std::string& peer_id) {
    host_ = host;
    peer_id_ = peer_id;
    ns_ = bus_namespace_from_env();
    ns_prefix_ = ns_.empty() ? "" : ns_ + ":";
    auto ports = shardmap::shard_ports_from_env(port);
    links_.clear();
    links_.resize(ports.size());
    for (size_t i = 0; i < ports.size(); ++i) links_[i].port = ports[i];
    n_ = static_cast<int>(links_.size());
    // the HOME shard keeps the startup contract: fail loudly
    Link& home = links_[shardmap::kHomeShard];
    int fd = tcp_connect(host, home.port);
    if (fd < 0) return false;
    set_nonblocking(fd);
    home.conn = LineConn(fd);
    home.ever_attempted = true;
    home.fast_hub = false;  // until the hub's welcome advertises relay1
    send_hello(home);
    return true;
  }

  // Survive a bus restart: when the connection dies, pump() keeps returning
  // true and retries the connect with exponential backoff (250 ms .. 4 s);
  // on success the client re-sends hello, re-subscribes every topic, and
  // invokes `on_reconnect` so the role can re-announce itself (e.g. agents
  // re-broadcast their position).  The reference's brokerless gossipsub
  // mesh has no hub to lose (manager.rs:94-98) — with this, losing busd
  // degrades the fleet instead of destroying it (VERDICT r2 item 5).
  // Messages published while disconnected are counted
  // (bus.pub_dropped_disconnected); control-plane frames additionally ride
  // the bounded replay outbox, flushed when the owning shard reconnects.
  // NON-home shards of a pool always self-heal, reconnect mode or not.
  void set_reconnect(const std::function<void()>& on_reconnect) {
    reconnect_ = true;
    on_reconnect_ = on_reconnect;
  }

  const std::string& peer_id() const { return peer_id_; }
  int fd() const { return home().conn.fd(); }
  int num_shards() const { return n_; }
  // "Logically alive": role main-loops poll this; a client in reconnect
  // mode stays alive across bus outages.  Pool semantics: alive while the
  // HOME shard link lives (a dead region shard only degrades coverage).
  bool connected() const { return home().conn.valid() || reconnect_; }
  bool wants_write() const {
    for (const auto& l : links_)
      if (l.conn.valid() && l.conn.wants_write()) return true;
    return false;
  }

  // Append one pollfd per live shard link (role main-loops poll every
  // shard, not just home, so a region beacon on another shard wakes the
  // loop immediately instead of on the next timeout).
  void append_pollfds(std::vector<pollfd>& out) const {
    for (const auto& l : links_) {
      if (l.conn.valid())
        out.push_back({l.conn.fd(),
                       static_cast<short>(
                           POLLIN | (l.conn.wants_write() ? POLLOUT : 0)),
                       0});
      // the lane doorbell: the hub rings it when it pushes into the s2c
      // ring while this client is parked (pump() parks before returning)
      if (l.shm_live && l.lane.valid() && l.lane.bell_rx_fd >= 0)
        out.push_back({l.lane.bell_rx_fd, POLLIN, 0});
    }
  }

  // Fleet-wide live metrics: publish this process's MetricsRegistry
  // snapshot on topic "mapd.metrics" every `interval_ms` (same beacon
  // schema as obs/beacon.py — obs/fleet_aggregator.py and fleet_top merge
  // both sides).  The check rides every pump() call.
  void enable_metrics_beacon(const std::string& proc,
                             int64_t interval_ms = 2000) {
    beacon_proc_ = proc;
    beacon_interval_ms_ = interval_ms;
    next_beacon_ms_ = 0;  // first pump publishes immediately
  }

  // The on-the-wire topic: namespaced unless `raw` (cross-tenant
  // infrastructure addressing wire topics directly).
  std::string wire_topic(const std::string& topic, bool raw = false) const {
    return (raw || ns_prefix_.empty()) ? topic : ns_prefix_ + topic;
  }

  void subscribe(const std::string& topic, bool raw = false) {
    const std::string wt = wire_topic(topic, raw);
    for (int s : shardmap::shards_for_subscription(wt, n_)) {
      Link& l = ensure_link(s);
      l.topics.insert(wt);
      if (l.conn.valid()) {
        Json j;
        j.set("op", "sub").set("topic", wt);
        l.conn.send_line(j.dump());
      }
    }
  }

  void unsubscribe(const std::string& topic, bool raw = false) {
    const std::string wt = wire_topic(topic, raw);
    for (int s : shardmap::shards_for_subscription(wt, n_)) {
      Link& l = links_[static_cast<size_t>(s)];
      l.topics.erase(wt);
      if (l.conn.valid()) {
        Json j;
        j.set("op", "unsub").set("topic", wt);
        l.conn.send_line(j.dump());
      }
    }
  }

  // True once the hub's welcome advertised the relay1 fast framing (and
  // JG_BUS_FASTFRAME didn't veto it): publishes go out as P-frames.
  // (Per-link state in a pool; this reports the home shard.)
  bool fast_hub() const { return home().fast_hub; }

  void publish(const std::string& topic, const Json& data,
               bool raw = false) {
    const std::string wt = wire_topic(topic, raw);
    Link& l = ensure_link(shardmap::shard_of(wt, n_));
    if (!l.conn.valid()) {
      // disconnected: the drop is COUNTED, and control-plane frames ride
      // the bounded replay outbox for the shard's return
      metrics_count("bus.pub_dropped_disconnected", 1,
                    "topic=\"" + wt + "\"");
      outbox_maybe(wt, data.dump());
      return;
    }
    publish_on(l, wt, data.dump());
  }

  void query_peers(const std::string& topic, bool raw = false) {
    Json j;
    j.set("op", "peers").set("topic", wire_topic(topic, raw));
    send_control(j);
  }

  // Pump socket events on every shard link.  Returns false if the HOME
  // bus connection died and reconnect mode is off; with set_reconnect,
  // outages are absorbed (a backoff-paced reconnect attempt rides each
  // pump call) and pump keeps returning true.  Non-home shard outages
  // never end the loop — they self-heal with the same backoff.
  // on_msg: application messages; on_event: peer_joined/peer_left/peers.
  bool pump(const std::function<void(const Msg&)>& on_msg,
            const std::function<void(const Json&)>& on_event = nullptr) {
    maybe_publish_beacon();
    bool alive = true;
    for (auto& l : links_) {
      // drain the hub->client ring first (deliveries racing the TCP
      // control frames is fine: lanes carry only the droppable class)
      if (l.shm_live && l.lane.valid()) {
        l.lane.rx.reader_unpark();
        l.lane.drain_bell();
        std::string frame;
        while (l.lane.recv(&frame))
          if (!frame.empty() && frame[0] == 'M')
            handle_line(l, frame, on_msg, on_event);
      }
      if (!l.conn.valid()) {
        if (!try_reconnect(l)) alive = false;
        continue;
      }
      if (!l.conn.on_readable()) {
        if (!drop_or_retry(l)) alive = false;
        continue;
      }
      while (auto line = l.conn.next_line()) handle_line(l, *line, on_msg,
                                                         on_event);
      if (l.conn.valid() && !l.conn.on_writable())
        if (!drop_or_retry(l)) alive = false;
    }
    // spin-then-park: arm each drained lane's parked flag so the hub
    // rings the doorbell (in append_pollfds' poll set) on the next
    // frame; a frame that raced the flag is drained before we sleep.
    for (auto& l : links_) {
      if (!l.shm_live || !l.lane.valid()) continue;
      while (!l.lane.rx.reader_park()) {
        std::string frame;
        while (l.lane.recv(&frame))
          if (!frame.empty() && frame[0] == 'M')
            handle_line(l, frame, on_msg, on_event);
      }
    }
    return alive;
  }

  bool flush() {
    bool ok = true;
    for (auto& l : links_)
      if (l.conn.valid() && !l.conn.on_writable()) ok = false;
    return ok;
  }

  void close() {
    reconnect_ = false;
    for (auto& l : links_) {
      teardown_lane(l);
      l.conn.close_fd();
    }
  }

 private:
  struct Link {
    LineConn conn;
    uint16_t port = 0;
    bool fast_hub = false;
    bool ever_attempted = false;
    int64_t backoff_ms = 0;
    int64_t next_attempt_ms = 0;
    std::set<std::string> topics;  // subscriptions owned by this shard
    shm::Lane lane;         // offered ring pair (valid() once created)
    bool shm_live = false;  // hub's welcome echoed shm1: lane is on
  };

  void teardown_lane(Link& l) {
    if (!l.lane.valid()) return;
    l.lane.mark_detached();
    l.lane.close_lane(true);
    l.lane = shm::Lane();
    l.shm_live = false;
  }

  Link& home() { return links_[shardmap::kHomeShard]; }
  const Link& home() const { return links_[shardmap::kHomeShard]; }
  bool is_home(const Link& l) const { return &l == &links_[0]; }

  void send_control(const Json& j) {
    Link& h = home();
    if (h.conn.valid()) h.conn.send_line(j.dump());
  }

  void send_hello(Link& l) {
    Json hello;
    hello.set("op", "hello").set("peer_id", peer_id_);
    Json caps;
    if (fastframe_enabled()) caps.push_back(Json("relay1"));
    // shard1 is orthogonal to the relay framing: a pool client must
    // advertise it even with JG_BUS_FASTFRAME=0, or busd would count
    // its span wildcards as peering interest and double-deliver.  It
    // rides only on a real pool — the single-hub hello (and the
    // JG_BUS_SHARDS=1 kill switch) stays byte-identical.
    if (n_ > 1) caps.push_back(Json("shard1"));
    // namespaced tenant client (ISSUE 8); absent = legacy wire
    if (!ns_.empty()) caps.push_back(Json("ns1"));
    // shm lane offer (ISSUE 18): create the ring pair BEFORE the hello
    // so the hub can attach on receipt; live only after welcome echoes.
    teardown_lane(l);
    if (shm::shm_enabled_env() && fastframe_enabled()) {
      const int shard = static_cast<int>(&l - links_.data());
      std::string err;
      l.lane = shm::Lane::create(
          shm::lane_path_for(peer_id_, shard, shm::lane_dir()),
          768, 256, &err);
      if (l.lane.valid()) {
        caps.push_back(Json("shm1"));
        Json offer;
        offer.set("path", l.lane.path).set("v", 1);
        hello.set("shm", offer);
      } else {
        fprintf(stderr, "bus: shm lane create failed (%s); staying on "
                "TCP\n", err.c_str());
      }
    }
    if (agg_window_env() > 0) caps.push_back(Json("agg1"));
    if (!caps.is_null()) hello.set("caps", caps);
    l.conn.send_line(hello.dump());
  }

  // The link for `shard`, dialed lazily on first use.
  Link& ensure_link(int shard) {
    Link& l = links_[static_cast<size_t>(shard)];
    if (!l.conn.valid() && !l.ever_attempted) {
      l.ever_attempted = true;
      int fd = tcp_connect_timeout(host_, l.port, 250);
      if (fd < 0) {
        l.backoff_ms = 250;
        l.next_attempt_ms = mono_ms() + l.backoff_ms;
        return l;
      }
      l.conn = LineConn(fd);
      l.fast_hub = false;
      send_hello(l);
      for (const auto& t : l.topics) {
        Json j;
        j.set("op", "sub").set("topic", t);
        l.conn.send_line(j.dump());
      }
    }
    return l;
  }

  void publish_on(Link& l, const std::string& topic,
                  const std::string& payload) {
    std::string line;
    if (l.fast_hub && topic.find(' ') == std::string::npos) {
      // fast framing: the hub relays on a topic peek, no JSON parse
      line = "P" + topic + " " + payload;
      // shm lane fast path: droppable frames ride the c2s ring (exact
      // P-line, no newline); full/torn ring falls back to TCP per frame
      if (l.shm_live && l.lane.valid() && !bus_control_topic(topic)) {
        if (l.lane.send(line.data(), line.size())) {
          metrics_count("bus.shm_tx_frames");
          metrics_count("bus.msgs_sent", 1, "topic=\"" + topic + "\"");
          metrics_count("bus.bytes_sent",
                        static_cast<double>(line.size() + 1),
                        "topic=\"" + topic + "\"");
          return;
        }
        metrics_count("bus.shm_fallbacks");
      }
    } else {
      Json j;
      j.set("op", "pub").set("topic", topic);
      line = j.dump();
      // splice the pre-rendered payload in as the "data" member (the
      // outbox stores payload text, not Json values)
      line.insert(line.size() - 1, ",\"data\":" + payload);
    }
    // wire bytes: the framed line PLUS its newline (send_line appends it) —
    // keeps py/cpp bandwidth numbers byte-identical (bus_client.py publish)
    metrics_count("bus.msgs_sent", 1, "topic=\"" + topic + "\"");
    metrics_count("bus.bytes_sent", static_cast<double>(line.size() + 1),
                  "topic=\"" + topic + "\"");
    l.conn.send_line(line);
  }

  // Queue a dropped frame for replay-on-reconnect — control-plane topics
  // only (droppable beacon streams are superseded by the next beat).
  void outbox_maybe(const std::string& topic, const std::string& payload) {
    if (!bus_control_topic(topic)) return;
    if (outbox_max_ == 0) return;
    if (outbox_.size() >= outbox_max_) {
      metrics_count("bus.outbox_overflow");
      outbox_.pop_front();
    }
    outbox_.emplace_back(topic, payload);
  }

  void flush_outbox(Link& l) {
    if (outbox_.empty()) return;
    const int shard = static_cast<int>(&l - links_.data());
    std::deque<std::pair<std::string, std::string>> keep;
    for (auto& [topic, payload] : outbox_) {
      if (shardmap::shard_of(topic, n_) == shard) {
        publish_on(l, topic, payload);
        metrics_count("bus.pub_replayed", 1, "topic=\"" + topic + "\"");
      } else {
        keep.emplace_back(std::move(topic), std::move(payload));
      }
    }
    outbox_ = std::move(keep);
  }

  // Strip THIS client's namespace off a delivered wire topic, so role
  // code sees the logical topic it subscribed (un-namespaced clients —
  // e.g. cross-tenant infrastructure — see wire topics verbatim).
  std::string deliver_topic(const std::string& topic) const {
    if (!ns_prefix_.empty() &&
        topic.compare(0, ns_prefix_.size(), ns_prefix_) == 0)
      return topic.substr(ns_prefix_.size());
    return topic;
  }

  // Deliver an agg1 aggregate as its constituent pos1 messages (one per
  // coalesced sender) — role code never sees the aggregate frame.
  // Returns false when `data` isn't an agg1 frame.
  bool deliver_agg1(const std::string& topic, const Json& data,
                    const std::function<void(const Msg&)>& on_msg) {
    if (data["type"].as_str() != "agg1") return false;
    auto a = codec::decode_agg1_b64(data["data"].as_str());
    if (!a) {
      metrics_count("bus.agg_rx_malformed");
      return true;  // malformed aggregate: dropped, counted
    }
    metrics_count("bus.agg_rx_frames");
    metrics_count("bus.agg_rx_entries",
                  static_cast<double>(a->entries.size()));
    for (const auto& e : a->entries) {
      Json d;
      d.set("type", "pos1").set("data", codec::b64_encode(e.blob));
      if (on_msg) on_msg(Msg{topic, e.name, d});
    }
    return true;
  }

  void handle_line(Link& l, const std::string& line,
                   const std::function<void(const Msg&)>& on_msg,
                   const std::function<void(const Json&)>& on_event) {
    if (!line.empty() && line[0] == 'M') {
      // fast relay frame: `M<topic> <from> <payload-json>`
      size_t s1 = line.find(' ');
      size_t s2 = s1 == std::string::npos ? std::string::npos
                                          : line.find(' ', s1 + 1);
      if (s2 == std::string::npos) return;
      auto data = Json::parse(line.substr(s2 + 1));
      if (!data) return;  // garbage payload: ignore like any bad frame
      const std::string topic = line.substr(1, s1 - 1);
      metrics_count("bus.msgs_received", 1, "topic=\"" + topic + "\"");
      metrics_count("bus.bytes_received",
                    static_cast<double>(line.size() + 1),
                    "topic=\"" + topic + "\"");
      if (deliver_agg1(deliver_topic(topic), *data, on_msg)) return;
      if (on_msg)
        on_msg(Msg{deliver_topic(topic), line.substr(s1 + 1, s2 - s1 - 1),
                   *data});
      return;
    }
    auto parsed = Json::parse(line);
    if (!parsed || !parsed->is_object()) return;  // ignore garbage frames
    const Json& j = *parsed;
    const std::string& op = j["op"].as_str();
    if (op == "msg") {
      // wire bytes: framed line + its newline (stripped by next_line)
      const std::string& topic = j["topic"].as_str();
      metrics_count("bus.msgs_received", 1, "topic=\"" + topic + "\"");
      metrics_count("bus.bytes_received",
                    static_cast<double>(line.size() + 1),
                    "topic=\"" + topic + "\"");
      if (deliver_agg1(deliver_topic(topic), j["data"], on_msg)) return;
      if (on_msg)
        on_msg(Msg{deliver_topic(topic), j["from"].as_str(), j["data"]});
    } else {
      if (op == "welcome") {
        // caps negotiation: switch publishes to the fast framing only
        // when the hub advertises it (an old hub stays legacy), per link
        bool hub_shm = false;
        if (fastframe_enabled())
          for (const auto& cap : j["caps"].as_array()) {
            if (cap.as_str() == "relay1") l.fast_hub = true;
            if (cap.as_str() == "shm1") hub_shm = true;
          }
        if (l.lane.valid() && !(l.shm_live = hub_shm))
          teardown_lane(l);  // hub refused (or legacy): lane off, TCP on
      }
      if (on_event) on_event(j);
    }
  }

  // Connection died mid-pump: without reconnect mode propagate the death
  // (HOME shard only); otherwise drop the socket and arm the backoff.
  bool drop_or_retry(Link& l) {
    const bool fatal = is_home(l) && !reconnect_;
    const int err = errno;  // capture BEFORE close() can overwrite it
    l.conn.close_fd();
    l.fast_hub = false;  // renegotiate with whatever hub comes back
    teardown_lane(l);    // lane lifetime == TCP session; rebuilt on hello
    if (fatal) return false;
    l.backoff_ms = 250;
    l.next_attempt_ms = mono_ms() + l.backoff_ms;
    fprintf(stderr,
            "bus: shard %d connection lost (errno=%d), reconnecting "
            "(backoff %lld ms)\n",
            static_cast<int>(&l - links_.data()), err,
            static_cast<long long>(l.backoff_ms));
    return true;
  }

  bool try_reconnect(Link& l) {
    if (is_home(l) && !reconnect_) return false;
    if (!l.ever_attempted) return true;  // lazily dialed on first use
    int64_t now = mono_ms();
    if (now < l.next_attempt_ms) return true;  // not due yet
    // bounded connect: a silently-unreachable bus host must not freeze
    // the single-threaded role loop for the kernel SYN timeout.  The
    // timeout scales with the backoff (250 ms first try, up to 1 s) so a
    // reachable-but-slow link (SYN+accept > 250 ms) converges instead of
    // aborting every attempt forever.
    int fd = tcp_connect_timeout(
        host_, l.port,
        static_cast<int>(std::min<int64_t>(
            std::max<int64_t>(l.backoff_ms, 250), 1000)));
    if (fd < 0) {
      l.backoff_ms = l.backoff_ms
                         ? std::min<int64_t>(l.backoff_ms * 2, 4000)
                         : 250;
      l.next_attempt_ms = now + l.backoff_ms;
      fprintf(stderr, "bus: shard %d reconnect attempt failed (errno=%d), "
              "next in %lld ms\n",
              static_cast<int>(&l - links_.data()), errno,
              static_cast<long long>(l.backoff_ms));
      return true;
    }
    set_nonblocking(fd);
    l.conn = LineConn(fd);
    l.backoff_ms = 0;
    l.fast_hub = false;
    send_hello(l);
    for (const auto& t : l.topics) {
      Json j;
      j.set("op", "sub").set("topic", t);
      l.conn.send_line(j.dump());
    }
    fprintf(stderr, "bus: reconnected as %s (shard %d, %zu topics "
            "resubscribed)\n", peer_id_.c_str(),
            static_cast<int>(&l - links_.data()), l.topics.size());
    flush_outbox(l);
    if (is_home(l) && on_reconnect_) on_reconnect_();
    return true;
  }

  void maybe_publish_beacon() {
    if (beacon_proc_.empty() || !home().conn.valid()) return;
    int64_t now = mono_ms();
    if (now < next_beacon_ms_) return;
    next_beacon_ms_ = now + beacon_interval_ms_;
    publish("mapd.metrics",
            make_metrics_beacon(peer_id_, beacon_proc_,
                                beacon_interval_ms_ / 1000.0));
  }

  std::vector<Link> links_ = std::vector<Link>(1);
  int n_ = 1;
  std::string peer_id_;
  std::string host_;
  std::string ns_;         // tenant namespace (JG_BUS_NS; empty = legacy)
  std::string ns_prefix_;  // "<ns>:" or ""
  bool reconnect_ = false;
  std::function<void()> on_reconnect_;
  std::deque<std::pair<std::string, std::string>> outbox_;
  size_t outbox_max_ = []() -> size_t {
    const char* v = getenv("JG_BUS_OUTBOX");
    if (!v || !*v) return 128;
    long n = atol(v);
    return n > 0 ? static_cast<size_t>(n) : 0;  // <=0 disables replay
  }();
  std::string beacon_proc_;  // empty = beacons off
  int64_t beacon_interval_ms_ = 2000;
  int64_t next_beacon_ms_ = 0;
};

}  // namespace mapd
