// Pub/sub bus client.
//
// The reference's communication backend is a libp2p gossipsub mesh with mDNS
// LAN discovery (SURVEY C9); every runtime message is a broadcast on the
// single topic "mapd" (C10).  The host-runtime equivalent is a lightweight
// hub: roles connect to `busd` over loopback TCP, subscribe to topics, and
// publish JSON payloads that fan out to all other subscribers.  Discovery
// parity: the bus emits peer_joined / peer_left events (the capability of
// mDNS discovered/expired), and answers peers queries (the capability of
// gossipsub::all_peers the managers use for round-robin dispatch).
//
// Frame protocol (one JSON per line):
//   client->bus: {"op":"hello","peer_id":s,"caps":[s...]}
//                | {"op":"sub","topic":s}
//                | {"op":"unsub","topic":s} | {"op":"pub","topic":s,"data":v}
//                | {"op":"peers","topic":s}
//   bus->client: {"op":"msg","topic":s,"from":s,"data":v}
//                | {"op":"welcome","peer_id":s,"caps":[s...]}
//                | {"op":"peer_joined","peer_id":s,"topic":s}
//                | {"op":"peer_left","peer_id":s}
//                | {"op":"peers","topic":s,"peers":[s...]}
//
// Relay fast framing (ISSUE 4, caps-negotiated): a client advertises
// `caps:["relay1"]` in hello; when the hub's welcome echoes the cap, the
// hot path switches to topic-prefix lines the hub relays without JSON
// parsing (topics must not contain spaces):
//   client->bus publish: `P<topic> <payload-json>`
//   bus->client deliver: `M<topic> <from> <payload-json>`
// Everything else (hello/sub/welcome/peers/discovery events) stays JSON.
// Kill switch: JG_BUS_FASTFRAME=0 keeps this client on the legacy JSON
// wire end to end; an old hub (welcome without caps) does the same.
// A topic ending in ".*" subscribes by prefix (busd wildcard matching).
#pragma once

#include <poll.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <random>
#include <set>
#include <string>

#include "json.hpp"
#include "metrics.hpp"
#include "net.hpp"

namespace mapd {

inline int64_t unix_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(system_clock::now().time_since_epoch())
      .count();
}

inline int64_t mono_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(steady_clock::now().time_since_epoch())
      .count();
}

// JG_BUS_FASTFRAME=0 pins this client to the legacy JSON wire (default on).
inline bool fastframe_enabled() {
  const char* v = getenv("JG_BUS_FASTFRAME");
  return !v || (*v && strcmp(v, "0") && strcmp(v, "false"));
}

// Random peer id, shaped like a libp2p PeerId for log familiarity.
inline std::string random_peer_id() {
  static const char* alphabet =
      "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz";
  std::mt19937_64 rng(std::random_device{}());
  std::string id = "12D3KooW";
  for (int i = 0; i < 36; ++i) id += alphabet[rng() % 58];
  return id;
}

class BusClient {
 public:
  // Received application message.
  struct Msg {
    std::string topic;
    std::string from;
    Json data;
  };

  BusClient() = default;

  bool connect(const std::string& host, uint16_t port,
               const std::string& peer_id) {
    host_ = host;
    port_ = port;
    peer_id_ = peer_id;
    int fd = tcp_connect(host, port);
    if (fd < 0) return false;
    set_nonblocking(fd);
    conn_ = LineConn(fd);
    fast_hub_ = false;  // until the hub's welcome advertises relay1
    send_hello();
    return true;
  }

  // Survive a bus restart: when the connection dies, pump() keeps returning
  // true and retries the connect with exponential backoff (250 ms .. 4 s);
  // on success the client re-sends hello, re-subscribes every topic, and
  // invokes `on_reconnect` so the role can re-announce itself (e.g. agents
  // re-broadcast their position).  The reference's brokerless gossipsub
  // mesh has no hub to lose (manager.rs:94-98) — with this, losing busd
  // degrades the fleet instead of destroying it (VERDICT r2 item 5).
  // Messages published while disconnected are dropped (the bus is a lossy
  // broadcast medium; periodic heartbeats re-establish state).
  void set_reconnect(const std::function<void()>& on_reconnect) {
    reconnect_ = true;
    on_reconnect_ = on_reconnect;
  }

  const std::string& peer_id() const { return peer_id_; }
  int fd() const { return conn_.fd(); }
  // "Logically alive": role main-loops poll this; a client in reconnect
  // mode stays alive across bus outages.
  bool connected() const { return conn_.valid() || reconnect_; }
  bool wants_write() const { return conn_.wants_write(); }

  // Fleet-wide live metrics: publish this process's MetricsRegistry
  // snapshot on topic "mapd.metrics" every `interval_ms` (same beacon
  // schema as obs/beacon.py — obs/fleet_aggregator.py and fleet_top merge
  // both sides).  The check rides every pump() call.
  void enable_metrics_beacon(const std::string& proc,
                             int64_t interval_ms = 2000) {
    beacon_proc_ = proc;
    beacon_interval_ms_ = interval_ms;
    next_beacon_ms_ = 0;  // first pump publishes immediately
  }

  void subscribe(const std::string& topic) {
    topics_.insert(topic);
    Json j;
    j.set("op", "sub").set("topic", topic);
    send_control(j);
  }

  void unsubscribe(const std::string& topic) {
    topics_.erase(topic);
    Json j;
    j.set("op", "unsub").set("topic", topic);
    send_control(j);
  }

  // True once the hub's welcome advertised the relay1 fast framing (and
  // JG_BUS_FASTFRAME didn't veto it): publishes go out as P-frames.
  bool fast_hub() const { return fast_hub_; }

  void publish(const std::string& topic, const Json& data) {
    if (!conn_.valid()) return;  // disconnected: lossy medium, drop
    std::string line;
    if (fast_hub_ && topic.find(' ') == std::string::npos) {
      // fast framing: the hub relays on a topic peek, no JSON parse
      line = "P" + topic + " " + data.dump();
    } else {
      Json j;
      j.set("op", "pub").set("topic", topic).set("data", data);
      line = j.dump();
    }
    // wire bytes: the framed line PLUS its newline (send_line appends it) —
    // keeps py/cpp bandwidth numbers byte-identical (bus_client.py publish)
    metrics_count("bus.msgs_sent", 1, "topic=\"" + topic + "\"");
    metrics_count("bus.bytes_sent", static_cast<double>(line.size() + 1),
                  "topic=\"" + topic + "\"");
    conn_.send_line(line);
  }

  void query_peers(const std::string& topic) {
    Json j;
    j.set("op", "peers").set("topic", topic);
    send_control(j);
  }

  // Pump socket events.  Returns false if the bus connection died and
  // reconnect mode is off; with set_reconnect, outages are absorbed (a
  // backoff-paced reconnect attempt rides each pump call) and pump keeps
  // returning true.
  // on_msg: application messages; on_event: peer_joined/peer_left/peers.
  bool pump(const std::function<void(const Msg&)>& on_msg,
            const std::function<void(const Json&)>& on_event = nullptr) {
    maybe_publish_beacon();
    if (!conn_.valid()) return try_reconnect();
    if (!conn_.on_readable()) return drop_or_retry();
    while (auto line = conn_.next_line()) {
      if (!line->empty() && (*line)[0] == 'M') {
        // fast relay frame: `M<topic> <from> <payload-json>`
        size_t s1 = line->find(' ');
        size_t s2 = s1 == std::string::npos ? std::string::npos
                                            : line->find(' ', s1 + 1);
        if (s2 == std::string::npos) continue;
        auto data = Json::parse(line->substr(s2 + 1));
        if (!data) continue;  // garbage payload: ignore like any bad frame
        const std::string topic = line->substr(1, s1 - 1);
        metrics_count("bus.msgs_received", 1, "topic=\"" + topic + "\"");
        metrics_count("bus.bytes_received",
                      static_cast<double>(line->size() + 1),
                      "topic=\"" + topic + "\"");
        if (on_msg)
          on_msg(Msg{topic, line->substr(s1 + 1, s2 - s1 - 1), *data});
        continue;
      }
      auto parsed = Json::parse(*line);
      if (!parsed || !parsed->is_object()) continue;  // ignore garbage frames
      const Json& j = *parsed;
      const std::string& op = j["op"].as_str();
      if (op == "msg") {
        // wire bytes: framed line + its newline (stripped by next_line)
        const std::string& topic = j["topic"].as_str();
        metrics_count("bus.msgs_received", 1, "topic=\"" + topic + "\"");
        metrics_count("bus.bytes_received",
                      static_cast<double>(line->size() + 1),
                      "topic=\"" + topic + "\"");
        if (on_msg) on_msg(Msg{topic, j["from"].as_str(), j["data"]});
      } else {
        if (op == "welcome") {
          // caps negotiation: switch publishes to the fast framing only
          // when the hub advertises it (an old hub stays legacy)
          if (fastframe_enabled())
            for (const auto& cap : j["caps"].as_array())
              if (cap.as_str() == "relay1") fast_hub_ = true;
        }
        if (on_event) on_event(j);
      }
    }
    if (!conn_.on_writable()) return drop_or_retry();
    return true;
  }

  bool flush() { return conn_.on_writable(); }
  void close() {
    reconnect_ = false;
    conn_.close_fd();
  }

 private:
  void send_control(const Json& j) {
    if (conn_.valid()) conn_.send_line(j.dump());
  }

  void send_hello() {
    Json hello;
    hello.set("op", "hello").set("peer_id", peer_id_);
    if (fastframe_enabled()) {
      Json caps;
      caps.push_back(Json("relay1"));
      hello.set("caps", caps);
    }
    conn_.send_line(hello.dump());
  }

  void maybe_publish_beacon() {
    if (beacon_proc_.empty() || !conn_.valid()) return;
    int64_t now = mono_ms();
    if (now < next_beacon_ms_) return;
    next_beacon_ms_ = now + beacon_interval_ms_;
    publish("mapd.metrics",
            make_metrics_beacon(peer_id_, beacon_proc_,
                                beacon_interval_ms_ / 1000.0));
  }

  // Connection died mid-pump: without reconnect mode propagate the death;
  // with it, drop the socket and arm the backoff timer.
  bool drop_or_retry() {
    if (!reconnect_) return false;
    const int err = errno;  // capture BEFORE close() can overwrite it
    conn_.close_fd();
    fast_hub_ = false;  // renegotiate with whatever hub comes back
    backoff_ms_ = 250;
    next_attempt_ms_ = mono_ms() + backoff_ms_;
    fprintf(stderr,
            "bus: connection lost (errno=%d), reconnecting (backoff "
            "%lld ms)\n", err, static_cast<long long>(backoff_ms_));
    return true;
  }

  bool try_reconnect() {
    if (!reconnect_) return false;
    int64_t now = mono_ms();
    if (now < next_attempt_ms_) return true;  // not due yet
    // bounded connect: a silently-unreachable bus host must not freeze
    // the single-threaded role loop for the kernel SYN timeout.  The
    // timeout scales with the backoff (250 ms first try, up to 1 s) so a
    // reachable-but-slow link (SYN+accept > 250 ms) converges instead of
    // aborting every attempt forever.
    int fd = tcp_connect_timeout(
        host_, port_,
        static_cast<int>(std::min<int64_t>(std::max<int64_t>(backoff_ms_, 250),
                                           1000)));
    if (fd < 0) {
      backoff_ms_ = backoff_ms_ ? std::min<int64_t>(backoff_ms_ * 2, 4000)
                                : 250;
      next_attempt_ms_ = now + backoff_ms_;
      fprintf(stderr, "bus: reconnect attempt failed (errno=%d), next in "
              "%lld ms\n", errno, static_cast<long long>(backoff_ms_));
      return true;
    }
    set_nonblocking(fd);
    conn_ = LineConn(fd);
    backoff_ms_ = 0;
    fast_hub_ = false;
    send_hello();
    for (const auto& t : topics_) {
      Json j;
      j.set("op", "sub").set("topic", t);
      conn_.send_line(j.dump());
    }
    fprintf(stderr, "bus: reconnected as %s (%zu topics resubscribed)\n",
            peer_id_.c_str(), topics_.size());
    if (on_reconnect_) on_reconnect_();
    return true;
  }

  LineConn conn_;
  std::string peer_id_;
  std::string host_;
  uint16_t port_ = 0;
  bool fast_hub_ = false;
  bool reconnect_ = false;
  std::function<void()> on_reconnect_;
  std::set<std::string> topics_;
  int64_t backoff_ms_ = 0;
  int64_t next_attempt_ms_ = 0;
  std::string beacon_proc_;  // empty = beacons off
  int64_t beacon_interval_ms_ = 2000;
  int64_t next_beacon_ms_ = 0;
};

}  // namespace mapd
