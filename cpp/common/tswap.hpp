// The ONE native sequential TSWAP implementation (the reference keeps three
// near-identical copies — src/algorithm/tswap.rs:174-390 and verbatim clones
// in both binaries; SURVEY explicitly asks for exactly one).
//
// Semantics transcribed from tswap_step (src/algorithm/tswap.rs:174-286):
// Rule 1 stay at goal; Rule 3 swap goals with a blocker parked on its goal;
// Rule 4 deadlock-chain walk with abort-on-revisit and backward goal
// rotation; movement pass with mutual position swaps.  Next hops descend BFS
// distance fields (DistanceCache) instead of per-call A* — same shortest
// paths, deterministic tie-break, shared with the Python oracle and the TPU
// kernels.
//
// Used by: the centralized manager's native planning tick (its --solver=cpu
// mode) and, through decide_local below, the decentralized agent's local
// radius-limited decision.
#pragma once

#include <algorithm>
#include <optional>
#include <string>
#include <vector>

#include "grid.hpp"

namespace mapd {

struct TswapAgent {
  int id = 0;
  Cell v = 0;  // current cell
  Cell g = 0;  // goal cell
};

inline std::optional<size_t> occupant_of(const std::vector<TswapAgent>& agents,
                                         Cell cell) {
  for (size_t k = 0; k < agents.size(); ++k)
    if (agents[k].v == cell) return k;  // first match, like iter().position
  return std::nullopt;
}

// One sequential TSWAP step over all agents, in index order.
inline void tswap_step(std::vector<TswapAgent>& agents, DistanceCache& dc) {
  const size_t n = agents.size();
  // agents whose goal was retargeted by the push extension this step; they
  // skip the goal phase and absorb chain walks so Rule 4 cannot rotate the
  // push away before the movement pass's mutual swap lands it
  std::vector<char> pushed(n, 0);

  // --- goal-swapping phase (Rules 1, 3, 4) ---
  for (size_t i = 0; i < n; ++i) {
    if (pushed[i]) continue;
    if (agents[i].v == agents[i].g) continue;  // Rule 1
    auto u = dc.next_hop(agents[i].v, agents[i].g);
    if (!u) continue;
    auto j = occupant_of(agents, *u);
    if (!j || *j == i) continue;
    if (agents[*j].v == agents[*j].g) {
      if (agents[*j].g == agents[i].g) {
        // Push extension (deliberate fix of a reference deadlock): two
        // tasks sharing a delivery cell make the Rule-3 swap exchange
        // identical goals and no-op forever (tswap.rs:197-202).  Retarget
        // the parked blocker toward the mover's cell; the movement pass
        // resolves the pair as a mutual swap.  Mirrors solver/step.py.
        agents[*j].g = agents[i].v;
        pushed[*j] = 1;
      } else {
        std::swap(agents[i].g, agents[*j].g);  // Rule 3
      }
    } else {
      // Rule 4: walk the blocking chain
      std::vector<size_t> a_p{i};
      size_t cur = *j;
      bool deadlock = false;
      while (true) {
        if (pushed[cur]) break;  // pushed agents absorb (see push above)
        if (agents[cur].v == agents[cur].g) break;
        auto w = dc.next_hop(agents[cur].v, agents[cur].g);
        if (!w) break;
        auto c = occupant_of(agents, *w);
        if (!c) break;
        if (std::find(a_p.begin(), a_p.end(), cur) != a_p.end()) {
          a_p.clear();
          break;  // rho-shaped revisit not through i: abort
        }
        a_p.push_back(cur);
        cur = *c;
        if (cur == i) {
          deadlock = true;
          break;
        }
      }
      if (deadlock && a_p.size() > 1) {
        Cell last_goal = agents[a_p.back()].g;
        for (size_t k = a_p.size() - 1; k >= 1; --k)
          agents[a_p[k]].g = agents[a_p[k - 1]].g;
        agents[a_p[0]].g = last_goal;
      }
    }
  }

  // --- movement phase (Rules 2, 5, mutual swap) ---
  for (size_t i = 0; i < n; ++i) {
    if (agents[i].v == agents[i].g) continue;
    auto u = dc.next_hop(agents[i].v, agents[i].g);
    if (!u) continue;
    auto j = occupant_of(agents, *u);
    if (j) {
      if (*j != i) {
        auto uj = dc.next_hop(agents[*j].v, agents[*j].g);
        if (uj && *uj == agents[i].v)
          std::swap(agents[i].v, agents[*j].v);  // mutual swap
        // else Rule 5: stay
      }
    } else {
      agents[i].v = *u;  // Rule 2
    }
  }
}

// ---------- decentralized local decision (SURVEY C7) ----------
//
// Transcribed semantics of compute_next_move_with_tswap
// (src/bin/decentralized/agent.rs:329-462): one agent decides from its own
// (pos, goal) and the cached positions/goals of neighbors within the
// visibility radius; coordination (goal swap / rotation) happens over the
// wire instead of by direct mutation.

struct Neighbor {
  std::string peer_id;
  Cell pos = 0;
  Cell goal = 0;
};

struct LocalDecision {
  enum class Kind { Move, Wait, WaitForGoalSwap, WaitForRotation };
  Kind kind = Kind::Wait;
  Cell next = 0;                         // Move
  std::string swap_peer;                 // WaitForGoalSwap
  std::vector<std::string> participants; // WaitForRotation (peer ids, ring order)
  std::vector<Cell> goals;               // WaitForRotation goals, same order
};

inline LocalDecision decide_local(Cell my_pos, Cell my_goal,
                                  const std::string& my_id,
                                  const std::vector<Neighbor>& nearby,
                                  DistanceCache& dc) {
  LocalDecision wait;
  wait.kind = LocalDecision::Kind::Wait;
  if (my_pos == my_goal) return wait;  // Rule 1
  auto u = dc.next_hop(my_pos, my_goal);
  if (!u) return wait;

  auto occupant = [&](Cell c) -> const Neighbor* {
    for (const auto& nb : nearby)
      if (nb.pos == c) return &nb;
    return nullptr;
  };

  const Neighbor* blocker = occupant(*u);
  if (!blocker) {
    LocalDecision d;
    d.kind = LocalDecision::Kind::Move;  // Rule 2
    d.next = *u;
    return d;
  }
  if (blocker->pos == blocker->goal) {
    LocalDecision d;
    d.kind = LocalDecision::Kind::WaitForGoalSwap;
    d.swap_peer = blocker->peer_id;  // Rule 3 via request/response
    return d;
  }
  // Rule 4: chain walk over the local neighbor view
  std::vector<const Neighbor*> chain;
  const Neighbor* cur = blocker;
  bool deadlock = false;
  while (true) {
    if (cur->pos == cur->goal) break;
    auto w = dc.next_hop(cur->pos, cur->goal);
    if (!w) break;
    if (*w == my_pos) {
      deadlock = true;  // chain closes back on us
      break;
    }
    const Neighbor* nxt = occupant(*w);
    if (!nxt) break;
    bool seen = false;
    for (auto* p : chain) seen = seen || p == cur;
    if (seen) break;
    chain.push_back(cur);
    cur = nxt;
  }
  if (deadlock) {
    if (std::find(chain.begin(), chain.end(), cur) == chain.end())
      chain.push_back(cur);
    LocalDecision d;
    d.kind = LocalDecision::Kind::WaitForRotation;
    d.participants.push_back(my_id);
    d.goals.push_back(my_goal);
    for (auto* p : chain) {
      d.participants.push_back(p->peer_id);
      d.goals.push_back(p->goal);
    }
    return d;
  }
  return wait;  // Rule 5
}

}  // namespace mapd
