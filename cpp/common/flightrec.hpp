// Flight recorder — native mirror of p2p_distributed_tswap_tpu/obs/
// flightrec.py: an ALWAYS-ON bounded ring of the newest structured
// lifecycle events (pre-rendered JSON lines, so a dump is pure write()),
// the fleet's black box for crashes/wedges/e2e failures.
//
// Dump triggers, same contract as the Python side:
//   - SIGUSR2 (flightrec_install; SIGUSR1 stays the stats dump);
//   - fatal signals (SIGSEGV/SIGABRT/SIGBUS/SIGFPE): best-effort dump,
//     then the default action re-raised so the exit status stays honest;
//   - process exit (static destructor, like the tracer's flush);
//   - a bus "flight_dump" request (each main's handler calls dump()).
//
// Dumps land in $JG_FLIGHT_DIR (the fleet runner points this at its
// per-run log dir) else $JG_TRACE_DIR else results/trace, as
// <proc>-<pid>.flight.jsonl — meta line first, then events oldest-first.
#pragma once

#include <signal.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

namespace mapd {

constexpr size_t kFlightCapacity = 4096;

class FlightRec {
 public:
  static FlightRec& instance() {
    static FlightRec r;
    return r;
  }

  void init(const char* proc) { proc_ = proc; }
  const std::string& proc() const { return proc_; }

  // line: one rendered JSON object, no trailing newline (events.hpp
  // renders; the ring stores strings so a crash dump never allocates)
  void record(std::string line) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ring_.size() >= kFlightCapacity) ring_.pop_front();
    ring_.push_back(std::move(line));
  }

  size_t size() {
    std::lock_guard<std::mutex> lk(mu_);
    return ring_.size();
  }

  std::string default_path() const {
    const char* dir = getenv("JG_FLIGHT_DIR");
    if (!dir || !*dir) dir = getenv("JG_TRACE_DIR");
    std::string d = dir && *dir ? dir : "results/trace";
    return d + "/" + proc_ + "-" + std::to_string(getpid()) +
           ".flight.jsonl";
  }

  // Safe from fatal-signal handlers: try_lock only (a thread crashed
  // mid-record must not deadlock the dump; reading the deque unlocked in
  // that one doomed-process case is an accepted best-effort risk).
  bool dump(const char* reason, const std::string& path_override = "") {
    const bool locked = mu_.try_lock();
    std::string path = path_override.empty() ? default_path() : path_override;
    size_t slash = path.rfind('/');
    if (slash != std::string::npos) mkdirs(path.substr(0, slash));
    FILE* f = fopen(path.c_str(), "w");
    bool ok = false;
    if (f) {
      fprintf(f,
              "{\"meta\":\"flight\",\"proc\":\"%s\",\"pid\":%d,"
              "\"reason\":\"%s\",\"events\":%zu}\n",
              proc_.c_str(), getpid(), reason, ring_.size());
      for (const auto& line : ring_) fprintf(f, "%s\n", line.c_str());
      fclose(f);
      ok = true;
    }
    if (locked) mu_.unlock();
    return ok;
  }

  ~FlightRec() { dump("exit"); }

 private:
  FlightRec() = default;

  static void mkdirs(const std::string& dir) {
    std::string cur;
    for (size_t i = 0; i < dir.size(); ++i) {
      cur += dir[i];
      if (dir[i] == '/' || i + 1 == dir.size())
        mkdir(cur.c_str(), 0755);  // EEXIST is fine
    }
  }

  std::string proc_ = "cpp";
  std::deque<std::string> ring_;
  std::mutex mu_;
};

namespace flight_detail {
inline void fatal_handler(int sig) {
  FlightRec::instance().dump("fatal_signal");
  signal(sig, SIG_DFL);
  raise(sig);
}
inline void usr2_handler(int) { FlightRec::instance().dump("sigusr2"); }
}  // namespace flight_detail

// Call once at process entry (after the role name is known).  Arms
// SIGUSR2 + fatal-signal dumps; the exit dump rides the static
// destructor either way.
inline void flightrec_install(const char* proc) {
  FlightRec::instance().init(proc);
  signal(SIGUSR2, flight_detail::usr2_handler);
  signal(SIGSEGV, flight_detail::fatal_handler);
  signal(SIGABRT, flight_detail::fatal_handler);
  signal(SIGBUS, flight_detail::fatal_handler);
  signal(SIGFPE, flight_detail::fatal_handler);
}

inline void flight_record(std::string line) {
  FlightRec::instance().record(std::move(line));
}

inline bool flight_dump(const char* reason = "manual") {
  return FlightRec::instance().dump(reason);
}

}  // namespace mapd
