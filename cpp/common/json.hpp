// Minimal JSON value, parser, and writer for the host runtime's wire
// protocol (the ~15 ad-hoc message types of SURVEY C10).  No external
// dependencies; numbers are stored as double (all protocol numbers — cell
// coordinates, ids, unix-ms timestamps — fit exactly in a double's 53-bit
// mantissa) and written back as integers when integral.
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <optional>
#include <sstream>
#include <string>
#include <vector>

namespace mapd {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(double n) : type_(Type::Number), num_(n) {}
  Json(int n) : type_(Type::Number), num_(n) {}
  Json(int64_t n) : type_(Type::Number), num_(static_cast<double>(n)) {}
  Json(uint64_t n) : type_(Type::Number), num_(static_cast<double>(n)) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_num(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_str() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const JsonArray& as_array() const {
    static const JsonArray empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  const JsonObject& as_object() const {
    static const JsonObject empty;
    return type_ == Type::Object ? obj_ : empty;
  }

  // object field access; returns Null json for missing keys
  const Json& operator[](const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  Json& set(const std::string& key, Json v) {
    type_ = Type::Object;
    obj_[key] = std::move(v);
    return *this;
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_.count(key) > 0;
  }

  void push_back(Json v) {
    type_ = Type::Array;
    arr_.push_back(std::move(v));
  }

  std::string dump() const {
    std::ostringstream out;
    write(out);
    return out.str();
  }

  void write(std::ostream& out) const {
    switch (type_) {
      case Type::Null: out << "null"; break;
      case Type::Bool: out << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 9.0e15) {
          out << static_cast<int64_t>(num_);
        } else {
          out << num_;
        }
        break;
      }
      case Type::String: write_escaped(out, str_); break;
      case Type::Array: {
        out << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) out << ',';
          arr_[i].write(out);
        }
        out << ']';
        break;
      }
      case Type::Object: {
        out << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) out << ',';
          first = false;
          write_escaped(out, k);
          out << ':';
          v.write(out);
        }
        out << '}';
        break;
      }
    }
  }

  // Parse; returns nullopt on malformed input (protocol handlers must treat
  // garbage frames as ignorable, like the reference's serde_json fallbacks).
  static std::optional<Json> parse(const std::string& text) {
    Parser p{text, 0};
    auto v = p.parse_value();
    if (!v) return std::nullopt;
    p.skip_ws();
    if (p.pos != text.size()) return std::nullopt;
    return v;
  }

 private:
  struct Parser {
    const std::string& s;
    size_t pos;

    void skip_ws() {
      while (pos < s.size() && (s[pos] == ' ' || s[pos] == '\t' ||
                                s[pos] == '\n' || s[pos] == '\r'))
        ++pos;
    }
    bool eat(char c) {
      skip_ws();
      if (pos < s.size() && s[pos] == c) {
        ++pos;
        return true;
      }
      return false;
    }
    bool lit(const char* word) {
      size_t n = std::string(word).size();
      if (s.compare(pos, n, word) == 0) {
        pos += n;
        return true;
      }
      return false;
    }
    std::optional<Json> parse_value() {
      skip_ws();
      if (pos >= s.size()) return std::nullopt;
      char c = s[pos];
      if (c == 'n') return lit("null") ? std::optional<Json>(Json()) : std::nullopt;
      if (c == 't') return lit("true") ? std::optional<Json>(Json(true)) : std::nullopt;
      if (c == 'f') return lit("false") ? std::optional<Json>(Json(false)) : std::nullopt;
      if (c == '"') return parse_string();
      if (c == '[') return parse_array();
      if (c == '{') return parse_object();
      return parse_number();
    }
    std::optional<Json> parse_string() {
      if (!eat('"')) return std::nullopt;
      std::string out;
      while (pos < s.size()) {
        char c = s[pos++];
        if (c == '"') return Json(out);
        if (c == '\\') {
          if (pos >= s.size()) return std::nullopt;
          char e = s[pos++];
          switch (e) {
            case '"': out += '"'; break;
            case '\\': out += '\\'; break;
            case '/': out += '/'; break;
            case 'b': out += '\b'; break;
            case 'f': out += '\f'; break;
            case 'n': out += '\n'; break;
            case 'r': out += '\r'; break;
            case 't': out += '\t'; break;
            case 'u': {
              if (pos + 4 > s.size()) return std::nullopt;
              unsigned code = 0;
              for (int i = 0; i < 4; ++i) {
                char h = s[pos++];
                code <<= 4;
                if (h >= '0' && h <= '9') code |= h - '0';
                else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
                else return std::nullopt;
              }
              // utf-8 encode the BMP code point (surrogate pairs unneeded
              // for this protocol, which is ASCII-heavy)
              if (code < 0x80) {
                out += static_cast<char>(code);
              } else if (code < 0x800) {
                out += static_cast<char>(0xC0 | (code >> 6));
                out += static_cast<char>(0x80 | (code & 0x3F));
              } else {
                out += static_cast<char>(0xE0 | (code >> 12));
                out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
                out += static_cast<char>(0x80 | (code & 0x3F));
              }
              break;
            }
            default: return std::nullopt;
          }
        } else {
          out += c;
        }
      }
      return std::nullopt;
    }
    std::optional<Json> parse_number() {
      size_t start = pos;
      if (pos < s.size() && (s[pos] == '-' || s[pos] == '+')) ++pos;
      while (pos < s.size() &&
             (isdigit(static_cast<unsigned char>(s[pos])) || s[pos] == '.' ||
              s[pos] == 'e' || s[pos] == 'E' || s[pos] == '-' || s[pos] == '+'))
        ++pos;
      if (pos == start) return std::nullopt;
      try {
        return Json(std::stod(s.substr(start, pos - start)));
      } catch (...) {
        return std::nullopt;
      }
    }
    std::optional<Json> parse_array() {
      if (!eat('[')) return std::nullopt;
      JsonArray out;
      skip_ws();
      if (eat(']')) return Json(std::move(out));
      while (true) {
        auto v = parse_value();
        if (!v) return std::nullopt;
        out.push_back(std::move(*v));
        if (eat(']')) return Json(std::move(out));
        if (!eat(',')) return std::nullopt;
      }
    }
    std::optional<Json> parse_object() {
      if (!eat('{')) return std::nullopt;
      JsonObject out;
      skip_ws();
      if (eat('}')) return Json(std::move(out));
      while (true) {
        skip_ws();
        auto k = parse_string();
        if (!k) return std::nullopt;
        if (!eat(':')) return std::nullopt;
        auto v = parse_value();
        if (!v) return std::nullopt;
        out[k->as_str()] = std::move(*v);
        if (eat('}')) return Json(std::move(out));
        if (!eat(',')) return std::nullopt;
      }
    }
  };

  static void write_escaped(std::ostream& out, const std::string& s) {
    out << '"';
    for (char c : s) {
      switch (c) {
        case '"': out << "\\\""; break;
        case '\\': out << "\\\\"; break;
        case '\b': out << "\\b"; break;
        case '\f': out << "\\f"; break;
        case '\n': out << "\\n"; break;
        case '\r': out << "\\r"; break;
        case '\t': out << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out << buf;
          } else {
            out << c;
          }
      }
    }
    out << '"';
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace mapd
