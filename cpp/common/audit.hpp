// Fleet audit plane (ISSUE 10) — native mirror of
// p2p_distributed_tswap_tpu/obs/audit.py: FNV-1a-64 state digests over
// canonically packed tuples, the audit1 beacon blob, and the range-hash
// helpers the drill responder uses.  BYTE-IDENTICAL to the Python side
// (golden-tested via cpp/probes/codec_golden.cpp --audit-encode /
// --audit-decode / --audit-digest, fuzzed by scripts/codec_fuzz.py) —
// keep every packing rule in lockstep.
//
// Digest canon:
//   lane digest:   active (lane,pos,goal) triples sorted by lane, each
//                  packed little-endian i32 x3 (12 bytes);
//   ledger digest: (task_id i64, state u8, pickup i32, delivery i32)
//                  tuples sorted by (task_id, state), 17 bytes each;
//   view digest:   sorted in-flight task ids, i64 each;
//   cells digest:  sorted i32 cells.
//
// audit1 blob (little-endian):
//   u32 magic "AUD1"  u8 version=1  u8 flags=0  u16 n_entries
//   per entry: u8 section  u32 count  i64 seq  i64 epoch  u64 digest
//
// Sections (never renumber): 1 shadow, 2 mirror, 3 device, 4 fields,
// 5 ledger, 6 view.  Digests cross the JSON drill wire as 16-char
// lowercase hex (a u64 would round through the double-typed Json).
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

namespace mapd {
namespace audit {

constexpr const char* kAuditTopic = "mapd.audit";
constexpr const char* kAuditCap = "audit1";
constexpr uint32_t kAuditMagic = 0x31445541;  // b"AUD1"
constexpr uint8_t kAuditVersion = 1;

constexpr uint8_t kSecShadow = 1;
constexpr uint8_t kSecMirror = 2;
constexpr uint8_t kSecDevice = 3;
constexpr uint8_t kSecFields = 4;
constexpr uint8_t kSecLedger = 5;
constexpr uint8_t kSecView = 6;

constexpr uint8_t kTaskPending = 0;
constexpr uint8_t kTaskToPickup = 1;
constexpr uint8_t kTaskToDelivery = 2;

constexpr uint64_t kFnv64Offset = 0xCBF29CE484222325ull;
constexpr uint64_t kFnv64Prime = 0x100000001B3ull;

inline uint64_t fnv1a64(const uint8_t* data, size_t n,
                        uint64_t h = kFnv64Offset) {
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= kFnv64Prime;
  }
  return h;
}

namespace detail {
inline void put_i32(std::string& b, int32_t v) {
  uint32_t u = static_cast<uint32_t>(v);
  for (int k = 0; k < 4; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline void put_i64(std::string& b, int64_t v) {
  uint64_t u = static_cast<uint64_t>(v);
  for (int k = 0; k < 8; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline void put_u64(std::string& b, uint64_t u) {
  for (int k = 0; k < 8; ++k) b += static_cast<char>((u >> (8 * k)) & 0xFF);
}
inline uint64_t hash_str(const std::string& b) {
  return fnv1a64(reinterpret_cast<const uint8_t*>(b.data()), b.size());
}
}  // namespace detail

struct Entry {
  uint8_t section = 0;
  uint32_t count = 0;
  int64_t seq = 0;
  int64_t epoch = 0;
  uint64_t digest = 0;
};

// Sorted-by-lane (lane,pos,goal) triples -> (digest, count).  The caller
// provides triples ALREADY sorted ascending by lane (std::map iteration
// order); packing is little-endian i32 x3, matching audit.py lane_digest.
struct LaneDigest {
  std::string buf;
  uint32_t count = 0;
  void add(int32_t lane, int32_t pos, int32_t goal) {
    detail::put_i32(buf, lane);
    detail::put_i32(buf, pos);
    detail::put_i32(buf, goal);
    ++count;
  }
  uint64_t digest() const { return detail::hash_str(buf); }
};

// Sorted-by-(task_id,state) ledger tuples -> (digest, count).
struct LedgerDigest {
  std::string buf;
  uint32_t count = 0;
  void add(int64_t task_id, uint8_t state, int32_t pickup,
           int32_t delivery) {
    detail::put_i64(buf, task_id);
    buf += static_cast<char>(state);
    detail::put_i32(buf, pickup);
    detail::put_i32(buf, delivery);
    ++count;
  }
  uint64_t digest() const { return detail::hash_str(buf); }
};

// Sorted in-flight task ids -> (digest, count).
inline uint64_t view_digest(const std::vector<int64_t>& sorted_ids) {
  std::string buf;
  for (int64_t t : sorted_ids) detail::put_i64(buf, t);
  return detail::hash_str(buf);
}

// Sorted cells -> (digest, count).
inline uint64_t cells_digest(const std::vector<int32_t>& sorted_cells) {
  std::string buf;
  for (int32_t c : sorted_cells) detail::put_i32(buf, c);
  return detail::hash_str(buf);
}

inline std::string encode_audit(const std::vector<Entry>& entries) {
  std::string out;
  out.reserve(8 + entries.size() * 29);
  detail::put_i32(out, static_cast<int32_t>(kAuditMagic));
  out += static_cast<char>(kAuditVersion);
  out += static_cast<char>(0);  // flags
  out += static_cast<char>(entries.size() & 0xFF);
  out += static_cast<char>((entries.size() >> 8) & 0xFF);
  for (const Entry& e : entries) {
    out += static_cast<char>(e.section);
    detail::put_i32(out, static_cast<int32_t>(e.count));
    detail::put_i64(out, e.seq);
    detail::put_i64(out, e.epoch);
    detail::put_u64(out, e.digest);
  }
  return out;
}

inline bool decode_audit(const std::string& buf,
                         std::vector<Entry>* out) {
  if (buf.size() < 8) return false;
  const uint8_t* b = reinterpret_cast<const uint8_t*>(buf.data());
  uint32_t magic = static_cast<uint32_t>(b[0]) |
                   (static_cast<uint32_t>(b[1]) << 8) |
                   (static_cast<uint32_t>(b[2]) << 16) |
                   (static_cast<uint32_t>(b[3]) << 24);
  if (magic != kAuditMagic || b[4] != kAuditVersion) return false;
  uint16_t n = static_cast<uint16_t>(b[6] | (b[7] << 8));
  if (buf.size() != 8 + static_cast<size_t>(n) * 29) return false;
  out->clear();
  const uint8_t* q = b + 8;
  auto get_u32 = [](const uint8_t* p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
  };
  auto get_u64 = [](const uint8_t* p) {
    uint64_t v = 0;
    for (int k = 7; k >= 0; --k) v = (v << 8) | p[k];
    return v;
  };
  for (uint16_t k = 0; k < n; ++k, q += 29) {
    Entry e;
    e.section = q[0];
    e.count = get_u32(q + 1);
    e.seq = static_cast<int64_t>(get_u64(q + 5));
    e.epoch = static_cast<int64_t>(get_u64(q + 13));
    e.digest = get_u64(q + 21);
    out->push_back(e);
  }
  return true;
}

// 16-char lowercase hex — the JSON-wire spelling of a digest.
inline std::string digest_hex(uint64_t d) {
  char buf[17];
  snprintf(buf, sizeof(buf), "%016llx",
           static_cast<unsigned long long>(d));
  return std::string(buf);
}

// The audit plane is ON unless JG_AUDIT=0 (kill switch: wire
// byte-identical to the pre-audit build).
inline bool audit_enabled() {
  const char* v = getenv("JG_AUDIT");
  return !(v && v[0] == '0' && v[1] == '\0');
}

}  // namespace audit
}  // namespace mapd
