// Leveled, timestamped logging for the host-runtime binaries.
//
// The reference initializes `tracing` only in its demo binaries and uses
// raw println! everywhere else (SURVEY §5 "tracing/profiling").  Here every
// binary logs through one leveled sink: ISO-ish wall time + level tag +
// message, level settable per process via --log-level / MAPD_LOG_LEVEL
// (error | warn | info | debug; default info).  Per-decision chatter (goal
// swap traffic, neighbor cache events) sits at debug so production fleets
// stay quiet without losing the lifecycle narrative.

#pragma once

#include <sys/time.h>

#include <cstdarg>
#include <ctime>
#include <cstdio>
#include <cstring>
#include <string>

#include "knobs.hpp"

namespace mapd {

enum class LogLevel { Error = 0, Warn = 1, Info = 2, Debug = 3 };

inline LogLevel& log_level() {
  static LogLevel level = LogLevel::Info;
  return level;
}

inline void set_log_level(const Knobs& knobs) {
  std::string s = knobs.get_str("--log-level", "MAPD_LOG_LEVEL", "info");
  if (s == "error") log_level() = LogLevel::Error;
  else if (s == "warn") log_level() = LogLevel::Warn;
  else if (s == "info") log_level() = LogLevel::Info;
  else if (s == "debug") log_level() = LogLevel::Debug;
  else fprintf(stderr, "log: unknown level \"%s\", keeping info\n", s.c_str());
}

inline void vlog_at(LogLevel lv, const char* fmt, va_list ap) {
  if (lv > log_level()) return;
  timeval tv;
  gettimeofday(&tv, nullptr);
  tm t;
  localtime_r(&tv.tv_sec, &t);
  static const char* tags[] = {"E", "W", "I", "D"};
  // Error/Warn go to stderr so failures reach harnesses watching stderr and
  // never interleave with machine-readable stdout (probe/trace output).
  FILE* out = lv <= LogLevel::Warn ? stderr : stdout;
  fprintf(out, "%02d:%02d:%02d.%03d %s ", t.tm_hour, t.tm_min, t.tm_sec,
          static_cast<int>(tv.tv_usec / 1000), tags[static_cast<int>(lv)]);
  vfprintf(out, fmt, ap);
  fflush(out);
}

inline void log_info(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vlog_at(LogLevel::Info, fmt, ap);
  va_end(ap);
}

inline void log_debug(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vlog_at(LogLevel::Debug, fmt, ap);
  va_end(ap);
}

inline void log_warn(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vlog_at(LogLevel::Warn, fmt, ap);
  va_end(ap);
}

inline void log_error(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vlog_at(LogLevel::Error, fmt, ap);
  va_end(ap);
}

}  // namespace mapd
