// Deterministic topic→shard map for the federated bus pool — native
// mirror of p2p_distributed_tswap_tpu/runtime/shardmap.py (ISSUE 6
// tentpole; kept choice-identical, golden-tested via
// cpp/probes/codec_golden.cpp --shardmap).
//
// Ownership rules (every topic owned by EXACTLY ONE shard):
// - region position topics "mapd.pos.<rx>.<ry>" spread across ALL
//   shards by the region indices: (rx*7919 + ry*104729) % n;
// - a position topic with a non-numeric suffix falls back to FNV-1a
//   over the topic string;
// - everything else (control plane: "mapd", "mapd.path",
//   "mapd.metrics", the "solver" plan wire) lives on the HOME shard
//   (index 0) and reaches the rest over busd↔busd peering.
// Subscriptions: exact topic → its owner; a ".*" wildcard that can
// match position topics → ALL shards; any other wildcard → home.
// JG_BUS_SHARDS=1 (default): everything is shard 0 — the kill switch
// that keeps the single-hub wire verbatim.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "region.hpp"  // kPosTopicPrefix

namespace mapd {
namespace shardmap {

constexpr int kHomeShard = 0;
constexpr const char* kShardPortsEnv = "JG_BUS_SHARD_PORTS";

// Tenant namespaces (ISSUE 8, runtime/busns.py): a namespaced wire
// topic "<ns>:<topic>" is CLASSIFIED by its logical topic — region
// spread, span wildcards and droppable-beacon shedding are per-tenant
// identical to the un-namespaced fleet — while the FNV fallback hashes
// the full wire topic (choice-identical to the Python mirror).
inline std::string strip_ns(const std::string& topic) {
  const size_t colon = topic.find(':');
  if (colon == std::string::npos || colon == 0) return topic;
  if (topic.find(' ') < colon) return topic;  // not a namespace prefix
  return topic.substr(colon + 1);
}

inline uint32_t fnv1a32(const std::string& s) {
  uint32_t h = 2166136261u;
  for (unsigned char b : s) {
    h ^= b;
    h *= 16777619u;
  }
  return h;
}

inline bool all_digits(const std::string& s) {
  if (s.empty()) return false;
  for (char c : s)
    if (c < '0' || c > '9') return false;
  return true;
}

// The single owning shard of `topic` in an `num_shards` pool.
inline int shard_of(const std::string& topic, int num_shards) {
  if (num_shards <= 1) return kHomeShard;
  const std::string logical = strip_ns(topic);
  const size_t plen = strlen(kPosTopicPrefix);
  if (logical.compare(0, plen, kPosTopicPrefix) == 0 &&
      (logical.empty() || logical.back() != '*')) {
    const std::string suffix = logical.substr(plen);
    const size_t dot = suffix.find('.');
    if (dot != std::string::npos && all_digits(suffix.substr(0, dot)) &&
        all_digits(suffix.substr(dot + 1))) {
      // the region math IS the shard map (identical to shardmap.py)
      const long long rx = atoll(suffix.substr(0, dot).c_str());
      const long long ry = atoll(suffix.substr(dot + 1).c_str());
      return static_cast<int>((rx * 7919 + ry * 104729) % num_shards);
    }
    return static_cast<int>(fnv1a32(topic) % num_shards);
  }
  return kHomeShard;
}

// Every shard a subscription must reach (see shardmap.py).
inline std::vector<int> shards_for_subscription(const std::string& topic,
                                                int num_shards) {
  if (num_shards <= 1) return {kHomeShard};
  if (topic.size() >= 2 &&
      topic.compare(topic.size() - 2, 2, ".*") == 0) {
    const std::string logical = strip_ns(topic);
    const std::string prefix = logical.substr(0, logical.size() - 1);
    const std::string pos_prefix = kPosTopicPrefix;
    const bool spans =
        prefix.compare(0, pos_prefix.size(), pos_prefix) == 0 ||
        pos_prefix.compare(0, prefix.size(), prefix) == 0;
    if (spans) {
      std::vector<int> all(static_cast<size_t>(num_shards));
      for (int i = 0; i < num_shards; ++i) all[static_cast<size_t>(i)] = i;
      return all;
    }
    return {kHomeShard};
  }
  return {shard_of(topic, num_shards)};
}

// Parse a JG_BUS_SHARD_PORTS value ("7450,7451") into the ordered shard
// port list; returns empty on a malformed entry (callers treat that as a
// fatal misconfiguration, never a silent fallback).
inline std::vector<uint16_t> parse_shard_ports(const std::string& spec) {
  std::vector<uint16_t> ports;
  size_t start = 0;
  while (start <= spec.size()) {
    size_t comma = spec.find(',', start);
    std::string tok = spec.substr(
        start, comma == std::string::npos ? std::string::npos
                                          : comma - start);
    // trim spaces
    while (!tok.empty() && tok.front() == ' ') tok.erase(tok.begin());
    while (!tok.empty() && tok.back() == ' ') tok.pop_back();
    if (!tok.empty()) {
      if (!all_digits(tok)) return {};
      long v = atol(tok.c_str());
      if (v <= 0 || v > 65535) return {};
      ports.push_back(static_cast<uint16_t>(v));
    }
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  return ports;
}

// The shard port list the environment advertises, else the single
// `default_port` (legacy single-hub wire).  A malformed value is FATAL,
// matching the Python mirror: a half-parsed pool map must never route
// silently (a quiet single-hub fallback would misroute every region
// publish through home while the rest of the fleet shards).
inline std::vector<uint16_t> shard_ports_from_env(uint16_t default_port) {
  const char* spec = getenv(kShardPortsEnv);
  if (spec && *spec) {
    auto ports = parse_shard_ports(spec);
    if (ports.empty()) {
      fprintf(stderr, "shardmap: malformed %s=\"%s\"\n", kShardPortsEnv,
              spec);
      exit(2);
    }
    return ports;
  }
  return {default_port};
}

}  // namespace shardmap
}  // namespace mapd
