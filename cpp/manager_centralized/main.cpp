// mapd_manager_centralized — "all pathfinding done centrally" (SURVEY C5).
//
// Native rebuild of src/bin/centralized/manager.rs: tracks per-peer
// AgentState {current_pos, goal_pos, task, task_phase} from position_update
// messages, runs a planning tick every 500 ms (one sequential TSWAP step over
// all tracked agents), emits a move_instruction per agent, flips
// pickup -> delivery goals when agents reach pickups, assigns tasks to idle
// agents with a pending queue drained on position updates and completions,
// auto-reassigns a fresh task on completion, bounded-cache cleanup every
// 30 s, --clean to ignore re-discovered peers, and the stdin operator CLI.
//
// Planning backends:
//   --solver=cpu  (default) native sequential TSWAP (common/tswap.hpp)
//   --solver=tpu  delegate each tick to the JAX solver daemon
//                 (runtime/solverd.py) over bus topic "solver" — the
//                 BASELINE.json north-star deployment shape.
//
// Usage: mapd_manager_centralized [--port P] [--map FILE] [--seed S]
//                                 [--clean] [--solver cpu|tpu]

#include <poll.h>
#include <signal.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <optional>
#include <random>
#include <set>
#include <sstream>
#include <string>
#include <tuple>
#include <vector>

#include "../common/audit.hpp"
#include "../common/bus.hpp"
#include "../common/events.hpp"
#include "../common/grid.hpp"
#include "../common/ha.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/plan_codec.hpp"
#include "../common/region.hpp"
#include "../common/trace.hpp"
#include "../common/tswap.hpp"

using namespace mapd;

namespace {

volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

enum class Phase { None, ToPickup, ToDelivery };

struct AgentInfo {
  Cell pos = 0;
  Cell goal = 0;
  std::optional<Json> task;
  Phase phase = Phase::None;
  int64_t last_seen_ms = 0;
  int64_t dispatched_ms = 0;  // when .task was (re-)sent, for resend grace
};

}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  const std::string bus_host = knobs.get_str("--host", "MAPD_BUS_HOST",
                                             "127.0.0.1");
  const uint16_t port = static_cast<uint16_t>(
      knobs.get_int("--port", "MAPD_BUS_PORT", 7400));
  const std::string map_file = knobs.get_str("--map", "MAPD_MAP", "");
  const std::string solver = knobs.get_str("--solver", "MAPD_SOLVER", "cpu");
  const bool clean = knobs.get_bool("--clean", "MAPD_CLEAN");
  // open-loop mode (ISSUE 11): no auto-refill on completion — the load
  // is exactly what the operator injects (task/tasks/taskat).  Replay
  // (fleetsim --replay) requires this, or every done would mint a fresh
  // rng task the captured window never contained.
  const bool open_loop = knobs.get_bool("--open-loop", "MAPD_OPEN_LOOP");
  const uint64_t seed = static_cast<uint64_t>(knobs.get_int(
      "--seed", "MAPD_SEED",
      static_cast<int64_t>(std::random_device{}())));
  // RuntimeConfig knobs, reference-parity defaults (core/config.py).
  const int64_t planning_ms =
      knobs.get_int("--planning-interval-ms", "MAPD_PLANNING_INTERVAL_MS",
                    500);                                      // ref :567
  const int64_t cleanup_ms =
      knobs.get_int("--cleanup-interval-ms", "MAPD_CLEANUP_INTERVAL_MS",
                    30000);                                    // ref :727
  const size_t max_agents = static_cast<size_t>(
      knobs.get_int("--max-tracked-agents", "MAPD_MAX_TRACKED_AGENTS",
                    500));                                     // ref :734
  const size_t max_known_peers = static_cast<size_t>(
      knobs.get_int("--max-known-peers", "MAPD_MAX_KNOWN_PEERS",
                    1000));                                    // ref :752
  const int64_t agent_stale_ms =
      knobs.get_int("--agent-stale-ms", "MAPD_AGENT_STALE_MS", 60000);
  // --solver=tpu resilience: plan natively while the solver daemon has
  // been silent this long (the fleet must not stall if solverd dies).
  const int64_t solver_failover_ms =
      knobs.get_int("--solver-failover-ms", "MAPD_SOLVER_FAILOVER_MS", 5000);
  // Plan-wire codec for --solver=tpu: "packed" (default fast path —
  // base64 int32 snapshot/delta packets, see common/plan_codec.hpp) or
  // "json" (the legacy per-agent object wire; solverd always accepts it).
  const std::string plan_codec =
      knobs.get_str("--plan-codec", "JG_PLAN_CODEC", "packed");
  // an agent that keeps reporting idle this long past dispatch never got
  // its task (delivery lost in a bus outage) — re-send the same task
  const int64_t task_resend_ms =
      knobs.get_int("--task-resend-ms", "MAPD_TASK_RESEND_MS", 5000);
  // region-sharded heartbeats (ISSUE 4): agents beacon packed pos1 on
  // mapd.pos.<rx>.<ry>; the manager subscribes the wildcard so agent
  // heartbeats stop fanning out to every other agent.  JG_REGION_GOSSIP=0
  // falls back to flat position_update.
  const bool region_gossip =
      knobs.get_int("--region-gossip", "JG_REGION_GOSSIP", 1) != 0;
  // dynamic worlds (ISSUE 9): accept world_update_request toggles, mutate
  // the grid, and broadcast caps-negotiated world_update frames.
  // JG_DYNAMIC_WORLD=0 is the kill switch — requests are counted and
  // DROPPED, the world1 cap never rides plan_request, and the wire stays
  // byte-identical to the static build.  A NAMESPACED manager (JG_BUS_NS
  // set — a tenant on a multi-tenant solverd) defaults OFF: the solverd
  // grid is shared across tenants and drops tenant-plane world frames,
  // so accepting toggles here would diverge this fleet's grid from its
  // planner's (agents walled in by a phantom wall).  An explicit
  // --dynamic-world/JG_DYNAMIC_WORLD=1 still overrides for
  // single-tenant-behind-a-namespace setups.
  const char* ns_env = getenv("JG_BUS_NS");
  const bool dynamic_world =
      knobs.get_int("--dynamic-world", "JG_DYNAMIC_WORLD",
                    (ns_env && *ns_env) ? 0 : 1) != 0;
  // audit plane (ISSUE 10): periodic state-consistency digest beacons on
  // mapd.audit (task-ledger FNV chain + packed-encoder shadow ring keyed
  // by plan seq and world epoch) plus the bisect drill responder.
  // JG_AUDIT=0 is the kill switch: no subscription, no frames — the
  // wire stays byte-identical to the pre-audit build.
  const bool audit_on =
      knobs.get_int("--audit", "JG_AUDIT", 1) != 0;
  const int64_t audit_interval_ms =
      knobs.get_int("--audit-interval-ms", "JG_AUDIT_INTERVAL_MS", 2000);
  // federated world regions (ISSUE 14): --regions CxR partitions the
  // world into rectangular regions, each owned by its own
  // (manager, solverd) pair; THIS manager owns --region-id.  Ownership,
  // hysteresis and the border-mirror strip follow the canon in
  // common/region.hpp ≡ runtime/region.py (golden-tested).  Unset /
  // "1" is the kill switch: no subscription, no frames, no filters —
  // the single-manager wire stays byte-identical.
  const std::string regions_spec =
      knobs.get_str("--regions", "JG_REGIONS", "1");
  const int region_id = static_cast<int>(
      knobs.get_int("--region-id", "JG_REGION_ID", 0));
  const int fed_hyst = static_cast<int>(knobs.get_int(
      "--fed-hysteresis", "JG_FED_HYSTERESIS", kDefaultFedHysteresis));
  const int fed_border = static_cast<int>(knobs.get_int(
      "--fed-border", "JG_FED_BORDER", kDefaultFedBorder));
  const int64_t handoff_retry_ms = knobs.get_int(
      "--handoff-retry-ms", "JG_HANDOFF_RETRY_MS", 1000);
  // a federated fleet runs one plan wire per region ("solver.r<id>");
  // the default keeps the legacy single-plane topic
  const std::string solver_topic =
      knobs.get_str("--solver-topic", "JG_SOLVER_TOPIC", "solver");
  // audit-pairing namespace: the auditor joins manager↔solverd digests
  // by ns, so each region pair gets a label (e.g. "r0") WITHOUT bus
  // namespacing; defaults to the tenant ns for namespaced fleets
  const std::string audit_ns = knobs.get_str(
      "--audit-ns", "JG_AUDIT_NS", (ns_env && *ns_env) ? ns_env : "");
  // control-plane HA (ISSUE 15): with --ha/JG_HA=1 the active manager
  // continuously ships its task ledger + dispatch watermarks as
  // ledger1 records on raw topic mapd.ha and renews a liveness lease;
  // --standby tails that stream as a warm replica, promotes on lease
  // expiry inside one claim window, and an old-incarnation active that
  // resumes DEMOTES instead of dual-dispatching.  JG_HA unset/0 keeps
  // the single-manager wire byte-identical: nothing published or
  // subscribed on mapd.ha (raw-socket pin test in tests/test_ha.py).
  const bool ha_standby_boot = knobs.get_bool("--standby",
                                              "JG_HA_STANDBY");
  const bool ha_on =
      ha_standby_boot || knobs.get_int("--ha", "JG_HA", 0) != 0;
  const int64_t ha_lease_ms = knobs.get_int(
      "--ha-lease-ms", "JG_HA_LEASE_MS", ha::kDefaultLeaseMs);
  // the takeover sweep-hold (PR 4's post-outage hold, reused): a
  // promoted standby waits this long for an in-flight task's agent to
  // report before re-queueing it — an agent already claiming the task
  // must never be double-dispatched.  Defaults to one claim window
  // (the idle-but-dispatched re-send grace).
  const int64_t ha_hold_ms = knobs.get_int(
      "--ha-hold-ms", "JG_HA_HOLD_MS", task_resend_ms);
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);
  // span tracing (JG_TRACE=1 or --trace): same schema as the Python
  // tracer; analysis/trace_report.py merges this file with solverd's
  trace_init("manager_centralized", knobs.get_bool("--trace", nullptr));
  // lifecycle events + flight recorder (ISSUE 5): always-on black box,
  // trace-context propagation gated by JG_TRACE_CTX
  events_init("manager_centralized");
  const bool tctx = trace_ctx_enabled();
  // trace_id = run-epoch | task_id: the epoch salt keeps ids unique
  // across manager restarts sharing one log dir (task ids restart at 1).
  // 20 epoch bits keep every id under 2^53 — the JSON wire carries
  // numbers as doubles, and a larger id would round on the way through
  const int64_t trace_epoch = (unix_ms() & 0xFFFFF) << 32;

  Grid grid = Grid::default_grid();
  if (!map_file.empty()) {
    auto g = Grid::from_file(map_file);
    if (!g) {
      fprintf(stderr, "cannot load map %s\n", map_file.c_str());
      return 1;
    }
    grid = *g;
  }
  DistanceCache dc(grid);
  std::mt19937_64 rng(seed);

  // federation canon (ISSUE 14): parse + validate before any wire I/O —
  // a half-parsed world partition must never route silently
  FedMap fed = FedMap::parse(regions_spec);
  if (!fed.valid()) {
    fprintf(stderr, "bad --regions spec %s (want N or CxR)\n",
            regions_spec.c_str());
    return 2;
  }
  const bool fed_on = fed.total() > 1;
  if (fed_on && (region_id < 0 || region_id >= fed.total())) {
    fprintf(stderr, "--region-id %d out of range for %s\n", region_id,
            regions_spec.c_str());
    return 2;
  }
  const FedRect my_rect =
      fed_on ? fed.rect_of(grid.width, grid.height, region_id) : FedRect{};
  if (fed_on && (my_rect.x0 >= my_rect.x1 || my_rect.y0 >= my_rect.y1)) {
    // ceil-width slabs can leave trailing regions EMPTY on narrow
    // grids (e.g. 4x1 on a 9-wide map): a manager owning no cells
    // would strand every task injected into it — fail loudly
    fprintf(stderr,
            "--regions %s leaves region %d empty on a %dx%d grid\n",
            regions_spec.c_str(), region_id, grid.width, grid.height);
    return 2;
  }

  BusClient bus;
  std::string my_id = random_peer_id();
  if (!bus.connect(bus_host, port, my_id)) {
    fprintf(stderr, "cannot connect to bus on port %u\n", port);
    return 1;
  }
  // the active-role subscription set, shared by normal startup and a
  // standby's promotion (a warm standby subscribes ONLY mapd.ha until
  // it takes over — it must not ingest fleet traffic it cannot act on)
  auto subscribe_active = [&]() {
    bus.subscribe("mapd");
    if (region_gossip) {
      if (fed_on) {
      // interest-scoped gossip (ISSUE 14): a region manager needs only
      // the beacon topics covering ITS rectangle expanded by the
      // handoff/mirror margin — subscribing the fleet-wide wildcard
      // would make every manager process every beacon and the message
      // plane would not scale with region count at all.  Coverage: an
      // agent we track can stand at most `hyst` cells outside the rect
      // before the escape handoff fires, and mirrors live within
      // `border` cells — the +1 guard band covers the crossing beat.
      // The expanded-rect topics also spread across the SHARDED bus
      // pool by the region indices (runtime/shardmap.py), so each
      // manager's gossip load lands on its regions' shards.
      const int gcells = static_cast<int>(knobs.get_int(
          "--region-cells", "JG_REGION_CELLS", kDefaultRegionCells));
      const int exp = std::max(fed_border, fed_hyst) + 1;
      const int x0 = std::max(0, my_rect.x0 - exp);
      const int y0 = std::max(0, my_rect.y0 - exp);
      const int x1 = std::min(grid.width - 1, my_rect.x1 - 1 + exp);
      const int y1 = std::min(grid.height - 1, my_rect.y1 - 1 + exp);
      int n_topics = 0;
      for (int gy = y0 / gcells; gy <= y1 / gcells; ++gy)
        for (int gx = x0 / gcells; gx <= x1 / gcells; ++gx) {
          bus.subscribe(std::string(kPosTopicPrefix) +
                        std::to_string(gx) + "." + std::to_string(gy));
          ++n_topics;
        }
      log_info("🗺️  region %d gossip scope: %d topic(s) over "
               "[%d,%d)x[%d,%d)+%d\n", region_id, n_topics, my_rect.x0,
               my_rect.x1, my_rect.y0, my_rect.y1, exp);
      } else {
        bus.subscribe(kPosTopicWildcard);
      }
    }
    if (solver == "tpu") bus.subscribe(solver_topic);
    // cross-region handoffs arrive on this region's own fed topic
    if (fed_on) bus.subscribe(FedMap::fed_topic(region_id));
    // audit plane rides the un-namespaced operator topic (raw): a
    // tenant manager's digests must reach the cross-tenant auditor
    if (audit_on) bus.subscribe(audit::kAuditTopic, /*raw=*/true);
  };
  if (!ha_standby_boot) subscribe_active();
  // the HA plane rides its own raw topic — active (replication source,
  // rival-claim detection) and standby (the replica tail) both join
  if (ha_on) bus.subscribe(ha::kHaTopic, /*raw=*/true);
  // survive a bus restart (reconnect + resubscribe inside BusClient);
  // agents re-announce themselves on their own reconnect, so tracking
  // repopulates within a heartbeat
  bus.set_reconnect([]() {});
  // live-metrics beacon: registry snapshot on mapd.metrics every ~2 s
  // (fleet_top / obs.fleet_aggregator merge it with the Python peers')
  bus.enable_metrics_beacon("manager_centralized");
  // world-epoch tracking (ISSUE 10 satellite): the epoch + dynamic-world
  // gauges are ALWAYS present, so fleet_top's WORLD line shows a
  // 0-epoch (or dynamic-OFF) manager instead of omitting it — the PR 9
  // caveat (namespaced managers silently diverging from a toggling
  // operator plane) becomes visible instead of folklore
  metrics_gauge("manager.world_seq", 0.0);
  metrics_gauge("manager.dynamic_world", dynamic_world ? 1.0 : 0.0);
  if (fed_on) {
    // federation gauges are the aggregator's REGIONS-section evidence
    metrics_gauge("manager.region", static_cast<double>(region_id));
    metrics_gauge("manager.regions", static_cast<double>(fed.total()));
    metrics_gauge("manager.fed_pending_handoffs", 0.0);
  }
  log_info("🧠 centralized manager %s up (grid %dx%d, solver=%s%s)\n",
           my_id.c_str(), grid.width, grid.height, solver.c_str(),
           clean ? ", clean" : "");
  log_info("Commands: task | tasks N | metrics | save <file> | "
           "save path <file> | reset | quit\n");

  std::map<std::string, AgentInfo> agents;
  std::set<std::string> known_left;
  // ---- federation state (ISSUE 14) ----
  // border-strip foreign agents, fed into the move-emission guard (the
  // boundary-planning-correctness contract; see emit_moves).
  // cell_since tracks how long the body has HELD its current cell: a
  // freshly arrived mirror is presumed transiting and blocks the cell;
  // one parked past the block window becomes pass-through — a foreign
  // idle agent may sit on a border cell indefinitely, and a permanent
  // block there starves every crossing route (found live: the 2x1
  // ladder's crossing throughput collapsed ~4x under an unconditional
  // guard).
  struct Mirror {
    Cell cell = 0;
    int64_t last_seen = 0;
    int64_t cell_since = 0;
  };
  std::map<std::string, Mirror> mirrors;
  const int64_t mirror_block_ms = knobs.get_int(
      "--fed-mirror-block-ms", "JG_FED_MIRROR_BLOCK_MS", 3000);
  // mirror EXPIRY must outlive the block window (and slow heartbeats):
  // evicting a parked foreign body between its beacons would drop the
  // very mirror_cells entry the boundary guard reads
  const int64_t mirror_expire_ms = std::max<int64_t>(
      knobs.get_int("--fed-mirror-expire-ms", "JG_FED_MIRROR_EXPIRE_MS",
                    6000),
      2 * mirror_block_ms);
  auto mirror_touch = [&](const std::string& peer, Cell c) {
    const int64_t now2 = mono_ms();
    auto [mit, fresh] = mirrors.try_emplace(peer);
    if (fresh || mit->second.cell != c) mit->second.cell_since = now2;
    mit->second.cell = c;
    mit->second.last_seen = now2;
  };
  // outbound handoffs: seq-chained per destination region, retransmitted
  // until acked; a peer with an unacked record is in transfer limbo and
  // is never re-adopted from its beacons (handing_off)
  struct OutHandoff {
    Json frame;
    std::string peer;
    int dst = 0;
    int64_t first_send_ms = 0;  // creation order (eviction key —
                                // retransmits refresh last_send_ms
                                // even for a dead neighbor's backlog)
    int64_t last_send_ms = 0;
    // the replication-stream view of this record (ISSUE 15): a warm
    // standby receives the full unacked outbox and, on takeover,
    // RESUMES the retransmit with the original seq + epoch
    ha::HandoffOut ho;
  };
  std::map<std::pair<int, int64_t>, OutHandoff> handoff_unacked;
  std::map<int, int64_t> handoff_next_seq;
  // sender incarnation: a RESTARTED manager reuses seq numbers from 1,
  // and a receiver whose dedup set remembered the old incarnation
  // would ack-without-applying — silently losing the lane and its
  // task.  Every handoff frame carries this epoch.
  const int64_t fed_epoch = unix_ms();
  // receiver dedup: per source region, PER SENDER EPOCH applied-seq
  // sets (bounded) — a replayed/retransmitted handoff can never
  // double-admit an agent (or double-dispatch its task).  Per-epoch
  // (not newest-epoch-only, ISSUE 15): a promoted standby legitimately
  // retransmits its dead active's old-epoch records while sending new
  // ones under its own epoch, and BOTH chains must stay exactly-once.
  std::map<int, std::map<int64_t, std::set<int64_t>>> handoff_applied;
  std::set<std::string> handing_off;
  // peers recently adopted via handoff (peer -> flag expiry): shipped
  // as "handoff_peers" on plan_requests so solverd attributes the
  // fresh lanes (solverd.lanes_admitted{cause=handoff}).  STICKY for a
  // few seconds rather than cleared on first send — the flagged
  // request can be lost to a seq gap, and the recovery snapshot that
  // re-declares the lane must still carry the attribution (solverd
  // only counts NEWLY named lanes, so repeat flags never double-count)
  std::map<std::string, int64_t> handoff_admitted;
  int64_t last_handoff_retry = 0;
  // CLAIM-AWARE adoption (the double-tracking guard): a beacon inside
  // our rect but still within a NEIGHBOR's hysteresis reach may belong
  // to that neighbor (its escape check has not fired yet) — adopting
  // it immediately puts two managers on one body, which wedges both
  // ledgers (found live by the 2x2 ladder: border-hovering agents
  // collected conflicting tasks from two planners).  Such candidates
  // wait: either the neighbor's handoff arrives and claims them, or
  // the grace period expires and they were genuinely unclaimed (a
  // fresh agent spawned in the band) and we adopt.
  const int64_t claim_grace_ms = knobs.get_int(
      "--fed-claim-grace-ms", "JG_FED_CLAIM_GRACE_MS", 4000);
  std::map<std::string, int64_t> claim_candidates;  // peer -> first seen
  std::vector<FedRect> fed_rects;
  if (fed_on)
    for (int rid = 0; rid < fed.total(); ++rid)
      fed_rects.push_back(fed.rect_of(grid.width, grid.height, rid));
  // claimable = outside EVERY other region's hysteresis reach: any
  // neighbor that still owned the agent there would already have
  // escaped-and-handed-off (the thresholds are the same geometry)
  auto fed_claimable = [&](int x, int y) {
    for (int rid = 0; rid < fed.total(); ++rid) {
      if (rid == region_id) continue;
      if (!FedMap::escaped(x, y, fed_rects[rid], fed_hyst)) return false;
    }
    return true;
  };
  // cells targeted by move_instructions of the last two planning ticks:
  // a world toggle must not close a cell an agent is currently walking
  // into (its position_update lands a beat after the instruction) —
  // protected alongside positions/goals/task endpoints below
  std::set<Cell> recent_move_targets, prev_move_targets;
  std::deque<Json> pending_tasks;  // pending_task_requests (ref :367-436)
  // Task ids that were re-queued from a dead/stale agent (at-least-once
  // hazard: the original agent may still be alive and complete the task).
  // A later `done` for such an id cancels the pending duplicate, or — if
  // already re-dispatched — is counted once and never double-refilled.
  std::set<long long> requeued_ids;
  std::set<long long> completed_ids;
  // HA write-ahead (ISSUE 15): fresh Task dispatches are deferred here
  // until the ledger record covering them has shipped on the
  // replication stream — an agent must never hold a task no shipped
  // record knows, or a takeover loses it (found live by the failover
  // chaos row: a dispatch landing between 500 ms replication beats
  // died with the active).  Flushed once per main-loop iteration,
  // AFTER ha_replicate — sub-tick added latency, zero-loss ordering.
  std::deque<Json> ha_task_outbox;
  TaskMetricsCollector task_metrics;
  PathComputationMetrics path_metrics;
  // task-id allocation: federated managers mint from DISJOINT residue
  // classes (ids ≡ region_id mod region count) — colliding ids across
  // regions would poison every dedup/ownership filter keyed by task id
  // (two regions each "owning" a task 56; found live by the 2x2 ladder)
  const uint64_t task_id_stride = fed_on ? fed.total() : 1;
  uint64_t next_task_id = fed_on ? 1 + region_id : 1;
  // advance past an externally minted id (taskat / handed-in ledger
  // entries) while PRESERVING this region's residue class
  auto bump_task_id_past = [&](uint64_t id) {
    if (next_task_id > id) return;
    // O(1): a replay can inject ids in the billions, and a unit-step
    // loop here would stall the single bus-processing thread
    next_task_id += ((id - next_task_id) / task_id_stride + 1)
                    * task_id_stride;
  };
  int64_t plan_seq = 0;
  // per-task wire-hop ledger (common/events.hpp: send advances, receive
  // max-merges, bounded by oldest-id eviction)
  TaskHopLedger hops(trace_epoch);

  auto free_cells = grid.free_cells();
  auto gen_point = [&]() { return free_cells[rng() % free_cells.size()]; };
  // federated task sampling: pickups come from OUR region's free cells
  // (each region manager generates its own load), deliveries stay
  // global — cross-region tasks arise naturally and exercise the
  // handoff path exactly like a world-spanning workload would
  std::vector<Cell> rect_free;
  auto rebuild_rect_free = [&]() {
    rect_free.clear();
    if (!fed_on) return;
    for (Cell c : free_cells) {
      const int x = grid.x_of(c), y = grid.y_of(c);
      if (x >= my_rect.x0 && x < my_rect.x1 && y >= my_rect.y0 &&
          y < my_rect.y1)
        rect_free.push_back(c);
    }
  };
  rebuild_rect_free();
  auto gen_pickup = [&]() {
    return (fed_on && !rect_free.empty())
               ? rect_free[rng() % rect_free.size()]
               : gen_point();
  };

  auto point_json = [&](Cell c) {
    Json p;
    p.push_back(Json(grid.x_of(c)));
    p.push_back(Json(grid.y_of(c)));
    return p;
  };
  auto parse_point = [&](const Json& j) -> std::optional<Cell> {
    const auto& arr = j.as_array();
    if (arr.size() != 2) return std::nullopt;
    int x = static_cast<int>(arr[0].as_int());
    int y = static_cast<int>(arr[1].as_int());
    if (!grid.in_bounds(x, y)) return std::nullopt;
    return grid.cell(x, y);
  };

  auto make_task = [&]() {
    Cell pickup = gen_pickup(), delivery = gen_point();
    while (delivery == pickup) delivery = gen_point();
    Json t;
    t.set("pickup", point_json(pickup))
        .set("delivery", point_json(delivery))
        .set("peer_id", Json())
        .set("task_id", static_cast<int64_t>(next_task_id));
    next_task_id += task_id_stride;
    return t;
  };

  // Future-goal hints for the packed solver wire: the delivery cell of a
  // freshly assigned task becomes a goal only at the pickup flip, many
  // ticks later — shipping it as a hint lets solverd pre-sweep the field
  // in its idle window instead of stalling the tick it goes live.
  std::vector<int32_t> plan_hints;

  // hop 0 = task creation: the trace root every later hop counts from
  auto queue_task = [&]() {
    Json t = make_task();
    long long id = t["task_id"].as_int();
    if (tctx) {
      codec::TraceCtx t0{trace_epoch | id, 0, unix_ms()};
      event_emit("task.queue", &t0, id);
    }
    pending_tasks.push_back(std::move(t));
  };

  auto assign_task = [&](const std::string& peer, Json task) {
    task.set("peer_id", peer);
    uint64_t id = static_cast<uint64_t>(task["task_id"].as_int());
    TaskMetric m;
    m.task_id = id;
    m.peer_id = peer;
    m.sent_time = unix_ms();
    task_metrics.add_metric(m);
    AgentInfo& a = agents[peer];
    a.task = task;
    a.phase = Phase::ToPickup;
    a.dispatched_ms = mono_ms();
    if (auto p = parse_point(task["pickup"])) a.goal = *p;
    if (solver == "tpu" && plan_codec != "json")
      if (auto dl = parse_point(task["delivery"]))
        if (plan_hints.size() < 4096)
          plan_hints.push_back(static_cast<int32_t>(*dl));
    if (tctx) {
      auto t = hops.next(static_cast<long long>(id));
      task.set("tc", tc_json(t));
      a.task = task;  // the stored copy carries the context for re-sends
      event_emit("task.dispatch", &t, static_cast<long long>(id), peer);
    }
    if (ha_on)
      ha_task_outbox.push_back(task);  // write-ahead: record ships first
    else
      bus.publish("mapd", task);
    // live dispatch counter: the fleet rollup derives tasks/s and the
    // completion ratio from the dispatched/completed counter pair
    metrics_count("manager.tasks_dispatched");
    log_info("📤 Task %llu -> %s\n", static_cast<unsigned long long>(id),
             peer.c_str());
  };

  // Push an agent's in-flight task back onto the pending queue (front: it
  // was already dispatched once and should not starve behind fresh tasks).
  // Used when an agent dies (peer_left) or ages out silently — the
  // reference loses such tasks (decentralized/manager.rs:185-189).
  auto requeue_task = [&](const std::string& peer, const AgentInfo& a,
                          const char* why) {
    if (!a.task) return;
    Json t = *a.task;
    long long id = t["task_id"].as_int();
    log_info("♻️  %s %s, re-queueing task %lld\n", why, peer.c_str(), id);
    t.set("peer_id", Json());
    requeued_ids.insert(id);  // at-least-once: dedupe a late done (see below)
    if (tctx) {
      codec::TraceCtx t0 = hops.current(id);
      event_emit("task.requeue", &t0, id, peer);
    }
    pending_tasks.push_front(std::move(t));
  };

  // ---- cross-region handoff, outbound (ISSUE 14) ----
  // The agent lane AND its in-flight task-ledger entry move to the
  // neighbor manager in one packed handoff1 record: seq-chained per
  // (src, dst) pair, retransmitted until handoff_ack, dedup-guarded on
  // the receiver.  The agent leaves OUR tracking immediately (its lane
  // vanishes from the next plan delta; solverd drops it), and beacons
  // from it are ignored while the record is unacked (handing_off) so a
  // quick return can never make two managers plan one body.
  auto send_handoff = [&](const std::string& peer, const AgentInfo& a,
                          int dst) {
    codec::HandoffRec r;
    const int64_t hseq = ++handoff_next_seq[dst];
    r.seq = hseq;
    r.src_region = region_id;
    r.peer = peer;
    r.pos = static_cast<int32_t>(a.pos);
    r.goal = static_cast<int32_t>(a.goal);
    r.phase = a.phase == Phase::ToDelivery ? 2
              : (a.phase == Phase::ToPickup ? 1 : 0);
    if (a.task) {
      r.has_task = true;
      r.task_id = (*a.task)["task_id"].as_int();
      if (auto p = parse_point((*a.task)["pickup"]))
        r.pickup = static_cast<int32_t>(*p);
      if (auto p = parse_point((*a.task)["delivery"]))
        r.delivery = static_cast<int32_t>(*p);
      // the ledger entry LEAVES this region with the lane: surrender
      // any local at-least-once claim on its future done, or a task
      // that was displacement-requeued here earlier would be counted
      // by BOTH regions when it completes (found by the smoke's
      // mgr_completed <= injected bound)
      requeued_ids.erase(r.task_id);
    }
    Json f;
    f.set("type", "handoff1")
        .set("src", static_cast<int64_t>(region_id))
        .set("dst", static_cast<int64_t>(dst))
        .set("seq", hseq)
        .set("epoch", fed_epoch)
        .set("peer_id", peer)
        .set("data", codec::encode_b64(codec::encode_handoff(r)));
    bus.publish(FedMap::fed_topic(dst), f);
    ha::HandoffOut ho;
    ho.dst = dst;
    ho.seq = hseq;
    ho.epoch = fed_epoch;
    ho.peer = peer;
    ho.pos = r.pos;
    ho.goal = r.goal;
    ho.phase = static_cast<uint8_t>(r.phase);
    ho.has_task = r.has_task;
    ho.task_id = r.task_id;
    ho.pickup = r.pickup;
    ho.delivery = r.delivery;
    const int64_t send_ms = mono_ms();
    handoff_unacked[{dst, hseq}] =
        OutHandoff{f, peer, dst, send_ms, send_ms, ho};
    handing_off.insert(peer);
    metrics_count("manager.handoffs_sent");
    metrics_gauge("manager.fed_pending_handoffs",
                  static_cast<double>(handoff_unacked.size()));
    log_info("🛫 handoff %lld: %s -> region %d%s\n",
             static_cast<long long>(hseq), peer.c_str(), dst,
             r.has_task ? " (with task)" : "");
    // bounded outbox: with a dead neighbor the chain never acks — past
    // the cap the oldest record's task re-queues LOCALLY (at-least-once;
    // the done path dedups by task id like every other requeue)
    while (handoff_unacked.size() > 1024) {
      // evict the OLDEST record by creation time (a dead neighbor's
      // backlog), never map-order begin() — that would cancel a LIVE
      // destination's fresh in-flight handoff first
      auto oldest = handoff_unacked.begin();
      for (auto it2 = handoff_unacked.begin();
           it2 != handoff_unacked.end(); ++it2)
        if (it2->second.first_send_ms < oldest->second.first_send_ms)
          oldest = it2;
      auto pkt = codec::decode_b64(oldest->second.frame["data"].as_str());
      if (pkt) {
        if (auto rec = codec::decode_handoff(*pkt); rec && rec->has_task) {
          Json t;
          t.set("pickup", point_json(static_cast<Cell>(rec->pickup)))
              .set("delivery", point_json(static_cast<Cell>(rec->delivery)))
              .set("peer_id", Json())
              .set("task_id", rec->task_id);
          requeued_ids.insert(rec->task_id);
          pending_tasks.push_front(std::move(t));
        }
      }
      handing_off.erase(oldest->second.peer);
      metrics_count("manager.handoff_outbox_overflow");
      handoff_unacked.erase(oldest);
      // the pending gauge is the operator's dead-neighbor evidence —
      // it must track evictions, not just sends/acks
      metrics_gauge("manager.fed_pending_handoffs",
                    static_cast<double>(handoff_unacked.size()));
    }
  };

  // drain the pending queue onto idle tracked agents (ref :367-436)
  auto try_assign_pending = [&]() {
    while (!pending_tasks.empty()) {
      std::string idle_peer;
      for (auto& [peer, a] : agents)
        if (!a.task) {
          idle_peer = peer;
          break;
        }
      if (idle_peer.empty()) return;
      Json t = pending_tasks.front();
      pending_tasks.pop_front();
      assign_task(idle_peer, std::move(t));
    }
  };

  auto emit_moves = [&](const std::vector<std::string>& ids,
                        const std::vector<Cell>& next) {
    Span sp("manager.emit_moves");
    // border-mirror guard (ISSUE 14): cells currently occupied by
    // FOREIGN agents (the neighbor's border-strip beacons we mirror).
    // The planner does not know those bodies — including them as
    // immovable lanes deadlocks TSWAP's rotation resolution (found
    // live: the 2x2 ladder froze at the four-border crossing) — so
    // boundary correctness is enforced HERE instead, exactly like
    // moves_blocked_world: never instruct an agent into an occupied
    // border cell; the lane waits a tick and routes on once the
    // foreign agent moves.
    std::set<Cell> mirror_cells;
    if (fed_on) {
      const int64_t now2 = mono_ms();
      for (const auto& [mp, mc] : mirrors)
        if (now2 - mc.cell_since < mirror_block_ms)
          mirror_cells.insert(mc.cell);
    }
    for (size_t k = 0; k < ids.size(); ++k) {
      auto it = agents.find(ids[k]);
      if (it == agents.end()) continue;
      if (next[k] == it->second.pos) continue;  // no-op moves not sent
      if (fed_on && mirror_cells.count(next[k])) {
        metrics_count("manager.moves_blocked_mirror");
        continue;
      }
      if (!grid.is_free(next[k])) {
        // dynamic worlds (ISSUE 9): a plan computed against the
        // pre-toggle mask may still point into a freshly closed cell
        // until solverd's repair lands — the manager, as the system of
        // record for the world, never instructs an agent into a wall
        // (the lane waits a tick; the repaired field routes it around)
        metrics_count("manager.moves_blocked_world");
        continue;
      }
      recent_move_targets.insert(next[k]);
      Json mi;
      mi.set("type", "move_instruction")
          .set("peer_id", ids[k])
          .set("next_pos", point_json(next[k]))
          .set("timestamp", unix_ms());
      // the steered agent's task context rides its instructions, so the
      // execution leg correlates on the receive side (task.exec)
      if (tctx && it->second.task) {
        long long tid = (*it->second.task)["task_id"].as_int();
        auto t = hops.next(tid);
        mi.set("tc", tc_json(t));
      }
      bus.publish("mapd", mi);
      trace_count("manager.moves_emitted");
    }
  };

  // TSWAP goal exchanges ARE task re-assignments: when a step hands agent
  // k the goal agent j held, the task (and phase) follows the goal, and
  // the manager — the system of record for assignments — re-broadcasts
  // the exchanged Tasks so agent-side positional completion tracks the
  // NEW task.  (The decentralized agents do exactly this on the wire with
  // swap_request/response; this is the centralized equivalent.)
  //
  // History: round 4 instead RESET goals from tasks every tick ("never
  // persist swapped goals") — that fixed the wrong-delivery freeze but
  // created a rarer head-on LIVELOCK: two agents meeting at even
  // separation trigger a Rule-4 rotation, retreat one cell, have their
  // goals snapped back, and repeat forever (observed as fleets frozen
  // right after a pickup flip in the round-4/5 flaky e2e runs).  With
  // tasks following goals, the rotation is real progress exactly like
  // the offline kernel (solver/step.py slot permutation).
  //
  // Push-extension goals (a parked blocker retargeted at the mover's
  // CELL, not at any agent's goal) match no donor: the movement pass
  // already resolved the pair this tick, and the blocker keeps its task
  // state — its goal resets next tick.  An agent whose task was donated
  // away and who received none becomes idle (task_withdrawn tells it to
  // drop its stale copy); try_assign_pending refills it.  Exchanged-task
  // re-broadcasts make the receiving agent re-emit received/started
  // metrics, which update the original record's timestamps — accepted
  // (the task is genuinely being re-assigned).
  auto adopt_goal_exchanges = [&](const std::vector<std::string>& ids,
                                  const std::vector<Cell>& old_goals,
                                  const std::vector<Cell>& new_goals) {
    Span sp("manager.adopt_goal_exchanges");
    struct Incoming {
      std::optional<Json> task;
      Phase phase = Phase::None;
      bool set = false;
    };
    std::multimap<Cell, size_t> donors;  // old goal cell -> index
    for (size_t k = 0; k < ids.size(); ++k)
      if (new_goals[k] != old_goals[k]) donors.insert({old_goals[k], k});
    if (donors.empty()) return;
    std::vector<Incoming> incoming(ids.size());
    std::vector<char> donated(ids.size(), 0);
    for (size_t k = 0; k < ids.size(); ++k) {
      if (new_goals[k] == old_goals[k]) continue;
      auto range = donors.equal_range(new_goals[k]);
      for (auto it = range.first; it != range.second; ++it) {
        size_t j = it->second;
        if (donated[j] || j == k) continue;  // each donor gives once
        AgentInfo& aj = agents[ids[j]];
        incoming[k] = Incoming{aj.task, aj.phase, true};
        donated[j] = 1;
        break;
      }
    }
    auto withdraw = [&](const std::string& peer, const Json& task) {
      Json w;
      w.set("type", "task_withdrawn")
          .set("task_id", task["task_id"])
          .set("peer_id", peer);
      if (tctx) {
        auto t = hops.next(task["task_id"].as_int());
        w.set("tc", tc_json(t));
      }
      bus.publish("mapd", w);
      trace_count("manager.goal_exchanges");
      log_info("🔁 task %lld exchanged away from %s\n",
               task["task_id"].as_int(), peer.c_str());
    };
    for (size_t k = 0; k < ids.size(); ++k) {
      if (!donated[k] && !incoming[k].set) continue;
      AgentInfo& a = agents[ids[k]];
      if (donated[k] && !incoming[k].set) {
        // task handed away, nothing received: now idle
        if (a.task) withdraw(ids[k], *a.task);
        a.task.reset();
        a.phase = Phase::None;
        a.goal = a.pos;
      } else if (incoming[k].set) {
        // received an IDLE donor's positional goal while donating a task
        // away: the agent must drop its stale copy too
        if (donated[k] && a.task && !incoming[k].task)
          withdraw(ids[k], *a.task);
        if (!donated[k] && a.task) {
          // the receiver's own task was claimed by NOBODY (its new goal
          // came from a push-extension coincidence): never drop a live
          // task — back onto the pending queue it goes.  The agent must
          // also hear task_withdrawn, or its live stale copy could
          // positionally double-done the re-dispatched task.
          withdraw(ids[k], *a.task);
          requeue_task(ids[k], a, "exchange displaced");
        }
        a.task = incoming[k].task;
        a.phase = incoming[k].phase;
        if (a.task) {
          a.task->set("peer_id", ids[k]);
          auto cell = parse_point((*a.task)[
              a.phase == Phase::ToDelivery ? "delivery" : "pickup"]);
          if (cell) a.goal = *cell;
          a.dispatched_ms = mono_ms();
          if (tctx) {
            long long tid = (*a.task)["task_id"].as_int();
            auto t = hops.next(tid);
            a.task->set("tc", tc_json(t));
            event_emit("task.exchange", &t, tid, ids[k]);
          }
          bus.publish("mapd", *a.task);  // the re-assignment, on the wire
          log_info("🔁 task %lld exchanged to %s\n",
                   (*a.task)["task_id"].as_int(), ids[k].c_str());
        } else {
          a.phase = Phase::None;
          a.goal = a.pos;
        }
      }
    }
    try_assign_pending();  // displaced tasks go straight back out
  };

  // pickup-arrival phase transitions (ref :695-709): the MANAGER flips the
  // goal to delivery in centralized mode
  auto pickup_transitions = [&]() {
    for (auto& [peer, a] : agents) {
      if (a.phase == Phase::ToPickup && a.task) {
        auto pk = parse_point((*a.task)["pickup"]);
        if (pk && a.pos == *pk) {
          if (auto dl = parse_point((*a.task)["delivery"])) {
            a.goal = *dl;
            a.phase = Phase::ToDelivery;
            if (tctx) {
              // centralized mode: the MANAGER knows the pickup flip (the
              // agent is a dumb body) — the pickup hop comes from here
              long long tid = (*a.task)["task_id"].as_int();
              codec::TraceCtx t0 = hops.current(tid);
              event_emit("task.pickup", &t0, tid, peer);
            }
            log_info("📍 %s reached pickup, now -> delivery\n", peer.c_str());
          }
        }
      }
    }
  };

  auto plan_native = [&]() {
    Span sp("manager.plan_native");
    std::vector<std::string> ids;
    std::vector<Cell> old_goals;
    std::vector<TswapAgent> ta;
    for (auto& [peer, a] : agents) {
      ids.push_back(peer);
      old_goals.push_back(a.goal);
      ta.push_back(TswapAgent{static_cast<int>(ta.size()), a.pos, a.goal});
    }
    if (ids.empty()) return;
    auto t0 = std::chrono::steady_clock::now();
    {
      Span sp("manager.tswap_step",
              "\"agents\":" + std::to_string(ta.size()));
      tswap_step(ta, dc);
    }
    int64_t us = std::chrono::duration_cast<std::chrono::microseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
    path_metrics.record_micros(us, unix_ms());
    std::vector<Cell> next(ids.size()), new_goals(ids.size());
    for (size_t k = 0; k < ids.size(); ++k) {
      next[k] = ta[k].v;
      new_goals[k] = ta[k].g;
    }
    emit_moves(ids, next);
    // swapped/rotated goals carry their tasks with them (see
    // adopt_goal_exchanges: the round-4 reset-every-tick alternative
    // livelocks head-on pairs)
    adopt_goal_exchanges(ids, old_goals, new_goals);
  };

  // goals as they were SENT for the in-flight plan_seq: the daemon's
  // returned goals are relative to these, and any goal mutation between
  // request and response (completion, fresh assignment, idle reset) must
  // not be misread as an exchange
  std::map<std::string, Cell> sent_goals;
  // packed fast path: delta tracking against the state as sent (shadow),
  // periodic snapshot resync, and the seq-gap recovery trigger
  const bool use_packed = (plan_codec != "json");
  codec::PackedFleetEncoder plan_enc;
  int64_t plan_sent_ms = 0;  // fresh-response RTT (manager.plan_rtt_ms)
  // world epoch (monotone, bumped per accepted world_update batch):
  // every audit digest carries it as the second watermark
  int64_t world_seq = 0;
  // accumulated accepted toggles (cell -> blocked, last state wins):
  // replayed to a resyncing solverd so a daemon restarted mid-run
  // re-learns every wall instead of silently planning on the original
  // map (the stale_epoch divergence the audit plane exposes)
  std::map<int32_t, int> world_state;
  // audit shadow ring (ISSUE 10): per-tick digests of the fleet state AS
  // SENT, keyed by plan seq — shipped inside every audit beacon so the
  // auditor joins solverd's post-apply mirror digest at the exact same
  // watermark despite the 2 s beacon cadence vs the 500 ms tick
  std::deque<audit::Entry> audit_ring;

  // ---- control-plane HA state (ISSUE 15) ----
  bool ha_role_standby = ha_standby_boot;   // current role (can flip)
  // incarnation epoch: every HA frame carries it; a takeover bumps it
  // past the dead active's, and the lower (incarnation, peer) of two
  // claimants always demotes (split-brain guard, ha::should_demote)
  int64_t ha_incarnation = unix_ms();
  ha::LedgerEncoder ha_enc(ha_incarnation);
  ha::LedgerReplica ha_rep;
  // the active's lease as the standby sees it (auditor silent-peer rule)
  std::string ha_active_peer;
  int64_t ha_active_inc = 0;
  int64_t ha_lease_last = 0;
  int64_t ha_lease_interval = ha_lease_ms;
  int64_t ha_active_repl_seq = 0;
  // the last record's shipped digests — the takeover announcement
  // proves digest equality against exactly these
  uint64_t ha_active_ld = 0, ha_active_vd = 0;
  bool ha_have_active_digests = false;
  // a fresh standby knows nothing: ask for a snapshot immediately (the
  // plan wire's snapshot-resync path, reused) instead of waiting for
  // the active's next organic delta — which would gap anyway
  bool ha_need_resync = ha_standby_boot;
  int64_t ha_last_resync_req = 0;
  // post-takeover restore set: in-flight replica entries wait here for
  // their agent's next beacon (sweep-hold) instead of being re-queued
  // into a double dispatch; the hold expiry re-queues survivors
  std::map<std::string, Json> ha_restore_task;
  std::map<std::string, Phase> ha_restore_phase;
  int64_t ha_hold_until = 0;
  bool ha_promoted = false;  // this process took over at least once
  const int64_t ha_started = mono_ms();
  // operator lines (taskat under replay) arriving while still standby
  // are deferred and drained at promotion, never dropped
  std::deque<std::string> ha_deferred_cmds;
  bool ha_drain_cmds = false;
  int64_t last_ha_lease = 0, last_ha_repl = 0;
  auto ha_role_gauges = [&]() {
    metrics_gauge("manager.ha_role", ha_role_standby ? 0.0 : 1.0,
                  "role=\"active\"");
    metrics_gauge("manager.ha_role", ha_role_standby ? 1.0 : 0.0,
                  "role=\"standby\"");
  };
  if (ha_on) {
    ha_role_gauges();
    metrics_gauge("manager.ha_replica_lag_entries", 0.0);
  }

  // one world_update fan-out for all three broadcast sites (operator
  // toggles, snapshot-resync replay, HA takeover replay — a wire
  // change applied to one site but not the others would silently
  // desynchronize world state): JSON [x,y,b] on "mapd" for
  // agents/harnesses, packed world1 (or [cell,b] JSON on a JSON plan
  // wire) on the solver topic.  Frames carry the CURRENT world_seq.
  auto publish_world_update = [&](const std::vector<int32_t>& cells,
                                  const std::vector<int32_t>& blocked,
                                  bool to_mapd) {
    if (to_mapd) {
      Json fleet_toggles;
      for (size_t k = 0; k < cells.size(); ++k) {
        Json t;
        t.push_back(Json(static_cast<int64_t>(grid.x_of(cells[k]))));
        t.push_back(Json(static_cast<int64_t>(grid.y_of(cells[k]))));
        t.push_back(Json(static_cast<int64_t>(blocked[k])));
        fleet_toggles.push_back(t);
      }
      Json wu;
      wu.set("type", "world_update")
          .set("world_seq", world_seq)
          .set("toggles", fleet_toggles);
      bus.publish("mapd", wu);
    }
    if (solver == "tpu") {
      Json su;
      su.set("type", "world_update").set("world_seq", world_seq);
      if (use_packed) {
        su.set("codec", codec::kCodecName)
            .set("data", codec::encode_b64(
                     codec::encode_world(world_seq, cells, blocked)));
      } else {
        Json st;
        for (size_t k = 0; k < cells.size(); ++k) {
          Json t;
          t.push_back(Json(static_cast<int64_t>(cells[k])));
          t.push_back(Json(static_cast<int64_t>(blocked[k])));
          st.push_back(t);
        }
        su.set("toggles", st);
      }
      bus.publish(solver_topic, su);
    }
  };

  auto plan_request_tpu = [&]() {
    Span sp("manager.plan_request_encode");
    if (use_packed) {
      std::vector<std::tuple<std::string, int32_t, int32_t>> fleet;
      fleet.reserve(agents.size());
      for (auto& [peer, a] : agents)
        fleet.emplace_back(peer, static_cast<int32_t>(a.pos),
                           static_cast<int32_t>(a.goal));
      if (fleet.empty()) return;
      codec::Packet pkt = plan_enc.encode_tick(++plan_seq, fleet);
      if (tctx) {
        // plan-chain trace: its own id namespace (bit 31 salt) so plan
        // frames never collide with task traces in the timeline
        pkt.has_trace = true;
        pkt.trace = codec::TraceCtx{
            trace_epoch | 0x80000000LL | (plan_seq & 0x7FFFFFFF), 1,
            unix_ms()};
      }
      if (audit_on) {
        // digest the post-encode shadow: exactly the state solverd's
        // mirror must hold after applying THIS packet (same canon:
        // sorted-by-lane (lane,pos,goal) i32 triples, obs/audit.py)
        audit::LaneDigest ld;
        for (const auto& [lane, pg] : plan_enc.shadow_map())
          ld.add(lane, pg.first, pg.second);
        audit::Entry e;
        e.section = audit::kSecShadow;
        e.count = ld.count;
        e.seq = plan_seq;
        e.epoch = world_seq;
        e.digest = ld.digest();
        audit_ring.push_back(e);
        while (audit_ring.size() > 8) audit_ring.pop_front();
      }
      if (pkt.kind == codec::kSnapshot)
        metrics_count("manager.plan_snapshots");
      else
        // snapshots carry the whole fleet by design; only genuine deltas
        // feed the O(churn) evidence counter
        metrics_count("manager.delta_agents",
                      static_cast<double>(pkt.idx.size()));
      Json caps;
      caps.push_back(Json(codec::kCodecName));
      // trace1 cap: this peer reads trace blocks on packed responses
      if (tctx) caps.push_back(Json("trace1"));
      // world1 cap (ISSUE 9): this manager may emit world_update frames;
      // gated so JG_DYNAMIC_WORLD=0 keeps the request bytes identical
      if (dynamic_world) caps.push_back(Json(codec::kWorldCap));
      Json req;
      req.set("type", "plan_request")
          .set("seq", plan_seq)
          .set("codec", codec::kCodecName)
          .set("caps", caps)
          .set("base_seq", pkt.base_seq)
          .set("data", codec::encode_b64(pkt));
      if (!plan_hints.empty()) {
        Json hints;
        for (int32_t c : plan_hints) hints.push_back(Json(c));
        req.set("hints", hints);
        plan_hints.clear();
      }
      if (fed_on && !handoff_admitted.empty()) {
        // recently handed-off lanes: solverd attributes their
        // admission (lanes_admitted{cause=handoff}); expired flags
        // prune here
        const int64_t now2 = mono_ms();
        Json hp;
        for (auto hit2 = handoff_admitted.begin();
             hit2 != handoff_admitted.end();) {
          if (now2 > hit2->second) {
            hit2 = handoff_admitted.erase(hit2);
          } else {
            hp.push_back(Json(hit2->first));
            ++hit2;
          }
        }
        if (!hp.is_null()) req.set("handoff_peers", hp);
      }
      plan_sent_ms = mono_ms();
      bus.publish(solver_topic, req);
      return;
    }
    Json req;
    Json arr;
    std::map<std::string, Cell> snap;
    for (auto& [peer, a] : agents) {
      Json e;
      e.set("peer_id", peer)
          .set("pos", point_json(a.pos))
          .set("goal", point_json(a.goal));
      arr.push_back(e);
      snap[peer] = a.goal;
    }
    if (arr.is_null()) return;
    req.set("type", "plan_request").set("seq", ++plan_seq).set("agents", arr);
    if (tctx)
      req.set("tc", tc_json(trace_epoch | 0x80000000LL |
                                (plan_seq & 0x7FFFFFFF), 1));
    sent_goals = std::move(snap);
    plan_sent_ms = mono_ms();
    bus.publish(solver_topic, req);
  };

  // ---- dynamic worlds (ISSUE 9) ----
  // An operator/harness asks for obstacle toggles with
  //   {"type":"world_update_request","toggles":[[x,y,blocked01],...]}
  // on "mapd".  The manager is the system of record for the world: it
  // VALIDATES each toggle (in bounds; a closing cell must be free,
  // unoccupied, and not a live goal or any task's pickup/delivery — a
  // wall through a task endpoint would strand the task forever), mutates
  // its grid, resets the native distance cache, and broadcasts the
  // accepted batch as caps-negotiated world_update frames: JSON
  // [x,y,blocked] on "mapd" for agents/harnesses, and packed world1 (or
  // [cell,blocked] JSON when the plan wire is JSON) on "solver" so the
  // daemon repairs its cached fields.  The requester gets a
  // world_update_applied ack with per-toggle rejection reasons.
  auto handle_world_request = [&](const Json& d) {
    if (!dynamic_world) {
      metrics_count("manager.world_updates_ignored");
      return;
    }
    std::set<Cell> protected_cells;
    auto protect_task = [&](const Json& t) {
      if (auto p = parse_point(t["pickup"])) protected_cells.insert(*p);
      if (auto p = parse_point(t["delivery"])) protected_cells.insert(*p);
    };
    for (auto& [peer, a] : agents) {
      protected_cells.insert(a.pos);
      protected_cells.insert(a.goal);
      if (a.task) protect_task(*a.task);
    }
    for (const auto& t : pending_tasks) protect_task(t);
    // in-flight moves: instructions already published may not have
    // echoed back as position_updates yet — closing their target would
    // wall the walking agent in
    protected_cells.insert(recent_move_targets.begin(),
                           recent_move_targets.end());
    protected_cells.insert(prev_move_targets.begin(),
                           prev_move_targets.end());
    std::vector<int32_t> cells, blocked;
    Json rejected;
    for (const auto& e : d["toggles"].as_array()) {
      const auto& arr = e.as_array();
      if (arr.size() != 3) {
        // malformed entries must still show in the ack — the requester
        // reconciles accepted + rejected against what it submitted
        Json r;
        r.push_back(Json(static_cast<int64_t>(-1)));
        r.push_back(Json(static_cast<int64_t>(-1)));
        r.push_back(Json(std::string("malformed")));
        rejected.push_back(r);
        continue;
      }
      const int x = static_cast<int>(arr[0].as_int());
      const int y = static_cast<int>(arr[1].as_int());
      const bool blk = arr[2].as_int() != 0;
      auto reject = [&](const char* why) {
        Json r;
        r.push_back(Json(static_cast<int64_t>(x)));
        r.push_back(Json(static_cast<int64_t>(y)));
        r.push_back(Json(std::string(why)));
        rejected.push_back(r);
      };
      if (!grid.in_bounds(x, y)) {
        reject("out_of_bounds");
        continue;
      }
      const Cell c = grid.cell(x, y);
      if ((grid.free[c] != 0) == !blk) {
        reject("noop");
        continue;
      }
      if (blk && protected_cells.count(c)) {
        reject("occupied");
        continue;
      }
      grid.free[c] = blk ? 0 : 1;
      cells.push_back(static_cast<int32_t>(c));
      blocked.push_back(blk ? 1 : 0);
      world_state[static_cast<int32_t>(c)] = blk ? 1 : 0;
    }
    if (!cells.empty()) {
      ++world_seq;
      dc.clear();  // native fields rebuild against the new mask on demand
      free_cells = grid.free_cells();
      rebuild_rect_free();  // region task sampling tracks the new mask
      metrics_count("manager.world_updates");
      metrics_count("manager.world_toggles",
                    static_cast<double>(cells.size()));
      metrics_gauge("manager.world_seq", static_cast<double>(world_seq));
      publish_world_update(cells, blocked, /*to_mapd=*/true);
      log_info("🌍 world update %lld: %zu toggle(s) applied, %zu free "
               "cell(s) remain\n",
               static_cast<long long>(world_seq), cells.size(),
               free_cells.size());
    }
    if (rejected.is_null()) rejected = Json(JsonArray{});
    Json ack;
    ack.set("type", "world_update_applied")
        .set("world_seq", world_seq)
        .set("accepted", static_cast<int64_t>(cells.size()))
        .set("rejected", rejected);
    bus.publish("mapd", ack);
  };

  // ---- the ledger, enumerated once (ISSUE 10 + ISSUE 15) ----
  // Pending queue + every in-flight assignment + post-takeover hold
  // entries, in deterministic order (pending, agents, restore).  BOTH
  // the audit digests (ledger_tuples below) and the HA replication
  // stream derive from this ONE enumeration — the takeover
  // digest-equality acceptance holds only while they enumerate
  // identical state, so there is exactly one source.
  auto ha_ledger_tasks = [&]() {
    std::vector<ha::LedgerTask> out;
    auto cells_of = [&](const Json& t, int32_t* pk, int32_t* dl) {
      auto p = parse_point(t["pickup"]);
      auto d2 = parse_point(t["delivery"]);
      *pk = p ? static_cast<int32_t>(*p) : -1;
      *dl = d2 ? static_cast<int32_t>(*d2) : -1;
    };
    for (const auto& t : pending_tasks) {
      ha::LedgerTask lt;
      lt.task_id = t["task_id"].as_int();
      lt.state = audit::kTaskPending;
      cells_of(t, &lt.pickup, &lt.delivery);
      out.push_back(std::move(lt));
    }
    for (auto& [peer, a] : agents) {
      if (!a.task) continue;
      ha::LedgerTask lt;
      lt.task_id = (*a.task)["task_id"].as_int();
      lt.state = a.phase == Phase::ToDelivery ? audit::kTaskToDelivery
                                              : audit::kTaskToPickup;
      cells_of(*a.task, &lt.pickup, &lt.delivery);
      lt.peer = peer;
      out.push_back(std::move(lt));
    }
    // post-takeover hold entries (ISSUE 15): an in-flight task waiting
    // for its agent to report is STILL in this ledger — dropping it
    // would read as a lost task at the very watermark the takeover is
    // judged on
    for (auto& [peer, tj] : ha_restore_task) {
      ha::LedgerTask lt;
      lt.task_id = tj["task_id"].as_int();
      auto ph = ha_restore_phase.find(peer);
      lt.state = (ph != ha_restore_phase.end()
                  && ph->second == Phase::ToDelivery)
                     ? audit::kTaskToDelivery
                     : audit::kTaskToPickup;
      cells_of(tj, &lt.pickup, &lt.delivery);
      lt.peer = peer;
      out.push_back(std::move(lt));
    }
    return out;
  };

  // ---- audit plane (ISSUE 10): ledger digests, beacon, drill ----
  // (task_id, state, pickup, delivery) tuples sorted by (id, state) —
  // the ledger canon of obs/audit.py, derived from the one enumeration
  auto ledger_tuples = [&]() {
    std::vector<std::tuple<int64_t, uint8_t, int32_t, int32_t>> tup;
    for (const auto& t : ha_ledger_tasks())
      tup.emplace_back(t.task_id, t.state, t.pickup, t.delivery);
    std::sort(tup.begin(), tup.end());
    return tup;
  };

  auto publish_audit_beacon = [&]() {
    // a warm standby (ISSUE 15) beacons its REPLICA's digests at the
    // replicated watermarks — the auditor sees replica convergence
    // live, and the takeover digest-equality is externally checkable
    const bool stby = ha_on && ha_role_standby;
    std::vector<audit::Entry> entries;
    if (!stby)
      entries.assign(audit_ring.begin(), audit_ring.end());
    std::vector<std::tuple<int64_t, uint8_t, int32_t, int32_t>> tup;
    if (stby) {
      for (const auto& [tid, t] : ha_rep.tasks)
        tup.emplace_back(tid, t.state, t.pickup, t.delivery);
      std::sort(tup.begin(), tup.end());
    } else {
      tup = ledger_tuples();
    }
    const int64_t wm_seq = stby ? ha_rep.plan_seq : plan_seq;
    const int64_t wm_epoch = stby ? ha_rep.world_seq : world_seq;
    audit::LedgerDigest ld;
    int64_t pending = 0, to_pickup = 0, to_delivery = 0;
    std::vector<int64_t> inflight;
    for (const auto& [id, st, pk, dl] : tup) {
      ld.add(id, st, pk, dl);
      if (st == audit::kTaskPending) ++pending;
      else if (st == audit::kTaskToPickup) ++to_pickup;
      else ++to_delivery;
      if (st != audit::kTaskPending) inflight.push_back(id);
    }
    audit::Entry el;
    el.section = audit::kSecLedger;
    el.count = ld.count;
    el.seq = wm_seq;
    el.epoch = wm_epoch;
    el.digest = ld.digest();
    entries.push_back(el);
    std::sort(inflight.begin(), inflight.end());
    audit::Entry ev;
    ev.section = audit::kSecView;
    ev.count = static_cast<uint32_t>(inflight.size());
    ev.seq = wm_seq;
    ev.epoch = wm_epoch;
    ev.digest = audit::view_digest(inflight);
    entries.push_back(ev);
    Json caps;
    caps.push_back(Json(std::string(audit::kAuditCap)));
    Json buckets;
    buckets.set("pending", pending)
        .set("to_pickup", to_pickup)
        .set("to_delivery", to_delivery);
    Json b;
    b.set("type", "audit_beacon")
        .set("peer_id", my_id)
        .set("proc", stby ? "manager_standby" : "manager_centralized")
        .set("ns", audit_ns)
        .set("ts_ms", unix_ms())
        .set("interval_s", audit_interval_ms / 1000.0)
        .set("caps", caps)
        .set("dynamic_world", dynamic_world)
        .set("buckets", buckets)
        .set("data", codec::b64_encode(audit::encode_audit(entries)));
    bus.publish(audit::kAuditTopic, b, /*raw=*/true);
  };

  // Bisect drill responder: range digests over lane halves ("shadow")
  // or task-id halves ("ledger"), rows at the leaf — the auditor
  // recurses to the first divergent lane without any full-state ship.
  auto handle_drill = [&](const Json& d) {
    if (!audit_on) return;
    const std::string target = d["target"].as_str();
    if (target != "manager_centralized" && target != my_id) return;
    const std::string view = d["view"].as_str();
    const int64_t lo = d["lo"].as_int();
    const int64_t hi = d["hi"].as_int();
    const bool want_rows = d["rows"].as_bool() || hi - lo <= 4;
    Json resp;
    resp.set("type", "audit_drill_response")
        .set("req_id", d["req_id"])
        .set("peer_id", my_id)
        .set("target", target)
        .set("view", view)
        .set("lo", lo)
        .set("hi", hi);
    if (view == "ledger") {
      audit::LedgerDigest ld;
      for (const auto& [id, st, pk, dl] : ledger_tuples()) {
        if (id < lo || id >= hi) continue;
        ld.add(id, st, pk, dl);
      }
      resp.set("digest", audit::digest_hex(ld.digest()))
          .set("count", static_cast<int64_t>(ld.count));
    } else {  // "shadow"
      audit::LaneDigest ldg;
      Json rows;
      for (const auto& [lane, pg] : plan_enc.shadow_map()) {
        if (lane < lo || lane >= hi) continue;
        ldg.add(lane, pg.first, pg.second);
        if (want_rows) {
          Json r;
          r.push_back(Json(static_cast<int64_t>(lane)));
          r.push_back(Json(static_cast<int64_t>(pg.first)));
          r.push_back(Json(static_cast<int64_t>(pg.second)));
          r.push_back(Json(static_cast<int64_t>(1)));
          r.push_back(Json(plan_enc.peer_of(lane)));
          rows.push_back(r);
        }
      }
      resp.set("digest", audit::digest_hex(ldg.digest()))
          .set("count", static_cast<int64_t>(ldg.count));
      if (want_rows) {
        if (rows.is_null()) rows = Json(JsonArray{});
        resp.set("rows", rows);
      }
    }
    bus.publish(audit::kAuditTopic, resp, /*raw=*/true);
  };

  // --solver=tpu liveness (declared before the HA lambdas: a
  // promotion must reset the failover clock, or a standby's whole
  // pre-takeover uptime reads as daemon silence)
  int64_t last_plan_response = mono_ms();
  bool failed_over = false;

  // ---- control-plane HA lambdas (ISSUE 15) ----
  auto ha_replicate = [&]() {
    // the unacked cross-region handoff outbox rides every record
    // wholesale: a promoted standby RESUMES the retransmit-until-ack
    // loop instead of losing a mid-transfer task
    std::vector<ha::HandoffOut> hovec;
    hovec.reserve(handoff_unacked.size());
    for (const auto& [hk, out] : handoff_unacked) {
      (void)hk;
      hovec.push_back(out.ho);
    }
    auto rec = ha_enc.encode_tick(plan_seq, world_seq,
                                  static_cast<int64_t>(next_task_id),
                                  ha_ledger_tasks(), world_state, hovec);
    if (!rec) return;
    const std::string blob = codec::b64_encode(ha::encode_ledger(*rec));
    Json f;
    f.set("type", "ledger1")
        .set("ns", audit_ns)
        .set("peer_id", my_id)
        .set("incarnation", ha_incarnation)
        .set("seq", rec->seq)
        .set("data", blob);
    bus.publish(ha::kHaTopic, f, /*raw=*/true);
    metrics_count("manager.ha_repl_records");
    metrics_count("manager.ha_repl_bytes",
                  static_cast<double>(blob.size()));
    metrics_gauge("manager.ha_repl_seq",
                  static_cast<double>(ha_enc.last_seq()));
  };

  // the write-ahead flush: ship the record covering every deferred
  // dispatch, THEN release the Task frames to the agents
  auto ha_flush = [&]() {
    if (!ha_on || ha_role_standby) return;
    ha_replicate();
    while (!ha_task_outbox.empty()) {
      bus.publish("mapd", ha_task_outbox.front());
      ha_task_outbox.pop_front();
    }
  };

  auto ha_publish_lease = [&]() {
    Json f;
    f.set("type", "ha_lease")
        .set("ns", audit_ns)
        .set("peer_id", my_id)
        .set("incarnation", ha_incarnation)
        .set("interval_ms", ha_lease_ms)
        .set("repl_seq", ha_enc.last_seq());
    bus.publish(ha::kHaTopic, f, /*raw=*/true);
  };

  // takeover: become the region's system of record inside one claim
  // window — seed the ledger from the replica, replay the accumulated
  // world toggles at the replicated epoch, announce the bumped
  // incarnation WITH the digest-equal watermark proof, and hold
  // in-flight entries for their agents (the sweep-hold) so a task an
  // agent already claims is never double-dispatched.
  auto ha_promote = [&](const char* why) {
    ha_role_standby = false;
    ha_promoted = true;
    ha_incarnation = std::max(unix_ms(), ha_active_inc + 1);
    ha_enc = ha::LedgerEncoder(ha_incarnation);
    metrics_count("manager.ha_takeovers");
    ha_role_gauges();
    subscribe_active();
    // seed the ledger: pending entries go straight to the queue,
    // in-flight ones wait in the restore set for their agent's beacon
    for (const auto& [tid, t] : ha_rep.tasks) {
      Json tj;
      tj.set("pickup", point_json(static_cast<Cell>(t.pickup)))
          .set("delivery", point_json(static_cast<Cell>(t.delivery)))
          .set("peer_id", Json())
          .set("task_id", tid);
      if (t.state == audit::kTaskPending || t.peer.empty()) {
        pending_tasks.push_back(std::move(tj));
      } else {
        tj.set("peer_id", t.peer);
        ha_restore_task[t.peer] = std::move(tj);
        ha_restore_phase[t.peer] = t.state == audit::kTaskToDelivery
                                       ? Phase::ToDelivery
                                       : Phase::ToPickup;
      }
    }
    if (ha_rep.next_task_id > 0)
      bump_task_id_past(static_cast<uint64_t>(ha_rep.next_task_id));
    // resume the dead active's unacked cross-region handoffs (ISSUE
    // 15): rebuild each original frame (same seq + ORIGINAL epoch, so
    // the receiver's per-epoch dedup keeps working — already-applied
    // records re-ack, lost ones apply) and let the retransmit loop
    // drive them; their tasks are NOT in our ledger (they left with
    // the record) and must not be re-queued locally — that would
    // double-dispatch against a receiver that did apply.
    if (fed_on) {
      for (const auto& h : ha_rep.handoffs) {
        codec::HandoffRec r;
        r.seq = h.seq;
        r.src_region = region_id;
        r.peer = h.peer;
        r.pos = h.pos;
        r.goal = h.goal;
        r.phase = h.phase;
        r.has_task = h.has_task;
        r.task_id = h.task_id;
        r.pickup = h.pickup;
        r.delivery = h.delivery;
        Json f;
        f.set("type", "handoff1")
            .set("src", static_cast<int64_t>(region_id))
            .set("dst", static_cast<int64_t>(h.dst))
            .set("seq", h.seq)
            .set("epoch", h.epoch)
            .set("peer_id", h.peer)
            .set("data", codec::encode_b64(codec::encode_handoff(r)));
        handoff_unacked[{h.dst, h.seq}] =
            OutHandoff{f, h.peer, h.dst, mono_ms(), 0, h};
        handing_off.insert(h.peer);
        auto& nxt = handoff_next_seq[h.dst];
        nxt = std::max(nxt, h.seq);
        metrics_count("manager.ha_restored_handoffs");
      }
      if (!handoff_unacked.empty())
        metrics_gauge("manager.fed_pending_handoffs",
                      static_cast<double>(handoff_unacked.size()));
    }
    // world replay: adopt the replicated toggle state at the
    // replicated epoch, then re-broadcast it exactly like the
    // snapshot-resync world replay — agents and solverd re-learn every
    // wall from the NEW system of record
    if (!ha_rep.world.empty() || ha_rep.world_seq > world_seq) {
      const Cell cells_total = static_cast<Cell>(grid.free.size());
      for (const auto& [c, bl] : ha_rep.world) {
        if (c < 0 || c >= cells_total) continue;
        grid.free[c] = bl ? 0 : 1;
        world_state[c] = bl ? 1 : 0;
      }
      world_seq = std::max(world_seq, ha_rep.world_seq);
      dc.clear();
      free_cells = grid.free_cells();
      rebuild_rect_free();
      metrics_gauge("manager.world_seq", static_cast<double>(world_seq));
      if (dynamic_world && !world_state.empty()) {
        std::vector<int32_t> cells, blocked;
        for (const auto& [c, b2] : world_state) {
          cells.push_back(c);
          blocked.push_back(b2);
        }
        publish_world_update(cells, blocked, /*to_mapd=*/true);
      }
    }
    ha_hold_until = mono_ms() + ha_hold_ms;
    // the solver-failover clock starts NOW: the standby's whole
    // pre-takeover uptime must not read as solverd silence
    last_plan_response = mono_ms();
    failed_over = false;
    // the takeover announcement: self-computed audit-canon digests
    // over the seeded ledger MUST equal the failed active's last
    // shipped digests — the acceptance equality, on the wire for any
    // judge (ha_smoke, chaos_gate, fleet_top)
    auto [ld, vd] = ha::ledger_view_digests(ha_ledger_tasks());
    Json t;
    t.set("type", "ha_takeover")
        .set("ns", audit_ns)
        .set("peer_id", my_id)
        .set("incarnation", ha_incarnation)
        .set("why", std::string(why))
        .set("repl_seq", ha_rep.seq)
        .set("plan_seq", ha_rep.plan_seq)
        .set("world_seq", ha_rep.world_seq)
        .set("ledger_digest", audit::digest_hex(ld))
        .set("view_digest", audit::digest_hex(vd))
        .set("active_ledger_digest",
             ha_have_active_digests ? audit::digest_hex(ha_active_ld)
                                    : std::string(""))
        .set("active_view_digest",
             ha_have_active_digests ? audit::digest_hex(ha_active_vd)
                                    : std::string(""))
        .set("active_peer", ha_active_peer)
        .set("pending", static_cast<int64_t>(pending_tasks.size()))
        .set("inflight", static_cast<int64_t>(ha_restore_task.size()));
    bus.publish(ha::kHaTopic, t, /*raw=*/true);
    last_ha_lease = 0;  // start leasing immediately
    ha_drain_cmds = !ha_deferred_cmds.empty();
    ha_replicate();  // a rival standby can tail US from this moment
    log_info("👑 HA takeover (%s): incarnation %lld, %zu pending + %zu "
             "in-flight restored @ repl seq %lld (ledger %s)\n",
             why, static_cast<long long>(ha_incarnation),
             pending_tasks.size(), ha_restore_task.size(),
             static_cast<long long>(ha_rep.seq),
             audit::digest_hex(ld).c_str());
    try_assign_pending();
  };

  // the split-brain guard's losing side: surrender the ledger to the
  // higher-incarnation claimant and become ITS warm standby — an
  // old-incarnation active that resumes must never dual-dispatch
  auto ha_demote = [&](int64_t inc, const std::string& peer) {
    log_warn("⚠️  HA demote: %s claims incarnation %lld > mine %lld; "
             "surrendering the active role\n", peer.c_str(),
             static_cast<long long>(inc),
             static_cast<long long>(ha_incarnation));
    ha_role_standby = true;
    metrics_count("manager.ha_demotions");
    ha_role_gauges();
    pending_tasks.clear();
    agents.clear();
    ha_restore_task.clear();
    ha_restore_phase.clear();
    ha_hold_until = 0;
    handoff_unacked.clear();
    handing_off.clear();
    requeued_ids.clear();
    ha_task_outbox.clear();
    ha_rep = ha::LedgerReplica();
    ha_have_active_digests = false;
    ha_need_resync = true;
    ha_active_peer = peer;
    ha_active_inc = inc;
    ha_lease_last = mono_ms();
  };

  // one entry point for every mapd.ha frame (both roles).  Returns
  // true when the frame was an HA frame (handled or filtered).
  auto ha_handle_frame = [&](const Json& d) -> bool {
    const std::string& type = d["type"].as_str();
    if (type != "ha_lease" && type != "ledger1" &&
        type != "ha_takeover" && type != "ha_resync_request")
      return false;
    if (d["ns"].as_str() != audit_ns) return true;  // another pair's
    const std::string peer = d["peer_id"].as_str();
    if (peer == my_id) return true;  // own frame echoed back
    const int64_t inc = d["incarnation"].as_int();
    if (type == "ha_resync_request") {
      if (!ha_role_standby) {
        metrics_count("manager.ha_resync_requests");
        ha_enc.request_snapshot();
        ha_replicate();
      }
      return true;
    }
    // an active-claiming frame: while active ourselves, the lower
    // (incarnation, peer) demotes — deterministic on both sides
    if (!ha_role_standby) {
      if (ha::should_demote(ha_incarnation, my_id, inc, peer))
        ha_demote(inc, peer);
      return true;
    }
    // standby: any claimant frame renews the lease (a zombie with a
    // LOWER incarnation than the freshest seen never does)
    if (inc >= ha_active_inc) {
      if (inc > ha_active_inc) {
        // a NEW active incarnation announced itself: our chain (if
        // any) is from the old one — resync against the new stream
        ha_active_inc = inc;
        ha_need_resync = true;
      }
      ha_active_peer = peer;
      ha_lease_last = mono_ms();
      if (type == "ha_lease") {
        const int64_t iv = d["interval_ms"].as_int();
        if (iv > 0) ha_lease_interval = iv;
        ha_active_repl_seq = d["repl_seq"].as_int();
        metrics_gauge("manager.ha_replica_lag_entries",
                      static_cast<double>(std::max<int64_t>(
                          0, ha_active_repl_seq - ha_rep.seq)));
      }
    }
    if (type == "ledger1") {
      auto raw = codec::b64_decode(d["data"].as_str());
      std::optional<ha::LedgerRec> rec;
      if (raw) rec = ha::decode_ledger(*raw);
      if (!rec) {
        metrics_count("manager.ha_bad_records");
        return true;
      }
      switch (ha_rep.apply(*rec)) {
        case ha::ApplyResult::kApplied:
          ha_active_ld = rec->ledger_digest;
          ha_active_vd = rec->view_digest;
          ha_have_active_digests = true;
          ha_need_resync = false;
          metrics_gauge("manager.ha_replica_lag_entries", 0.0);
          break;
        case ha::ApplyResult::kDivergent:
          // applied but the recomputed digests disagree: this replica
          // must RESYNC, never promote on bad state
          metrics_count("manager.ha_replica_divergence");
          ha_have_active_digests = false;
          ha_need_resync = true;
          break;
        case ha::ApplyResult::kGap:
          metrics_count("manager.ha_replica_gaps");
          ha_need_resync = true;
          // the last-known active digests describe a PRE-GAP ledger: a
          // takeover forced before the resync lands must not claim
          // equality against them — the proof is honestly unavailable
          ha_have_active_digests = false;
          break;
        case ha::ApplyResult::kStale:
          metrics_count("manager.ha_stale_records");
          break;
      }
      metrics_gauge("manager.ha_repl_seq",
                    static_cast<double>(ha_rep.seq));
    }
    return true;
  };

  auto handle_plan_response = [&](const Json& d) {
    // one-way solverd->manager latency (trace ctx echoed by the daemon;
    // JSON wire carries "tc", the packed response its trace1 block)
    if (auto t = tc_parse(d))
      event_emit("plan.response", &*t, d["seq"].as_int(), "solverd",
                 t->send_ms);
    if (d["seq"].as_int() != plan_seq) {
      trace_count("manager.stale_plan_responses");
      return;  // stale tick
    }
    Span sp("manager.plan_response_apply");
    // Only FRESH (applied) responses prove the daemon useful: a daemon
    // whose latency always exceeds the planning tick produces nothing but
    // stale responses, and counting those as liveness would suppress the
    // failover while no plan of its ever lands.
    last_plan_response = mono_ms();
    if (failed_over) {
      failed_over = false;
      log_info("🔌 solver daemon responding again; leaving native "
               "failover\n");
      // this tick's moves were already planned natively — applying the
      // daemon's plan too would send agents two conflicting instructions
      return;
    }
    int64_t us = d["duration_micros"].as_int();
    path_metrics.record_micros(us, unix_ms());
    // end-to-end planning latency as the fleet pays it: request publish ->
    // fresh response applied (the crossover harness compares this against
    // the native path's tick_ms)
    metrics_observe("manager.plan_rtt_ms",
                    static_cast<double>(mono_ms() - plan_sent_ms));
    std::vector<std::string> ids;
    std::vector<Cell> next, old_goals, new_goals;
    if (d["codec"].as_str() == codec::kCodecName) {
      // packed response: int32 (lane, next_cell, goal_cell) triplets for
      // lanes that moved or changed goal; lanes resolve through the
      // encoder's roster, sent-state through its shadow
      auto pkt = codec::decode_b64(d["data"].as_str());
      if (!pkt || pkt->kind != codec::kResponse) {
        metrics_count("manager.bad_plan_packets");
        return;
      }
      if (pkt->has_trace)
        event_emit("plan.response", &pkt->trace, d["seq"].as_int(),
                   "solverd", pkt->trace.send_ms);
      const Cell cells = static_cast<Cell>(grid.width * grid.height);
      for (size_t k = 0; k < pkt->idx.size(); ++k) {
        Cell np = static_cast<Cell>(pkt->pos[k]);
        if (np < 0 || np >= cells) continue;
        const std::string& peer = plan_enc.peer_of(pkt->idx[k]);
        if (peer.empty()) continue;
        auto it = agents.find(peer);
        if (it == agents.end()) continue;
        ids.push_back(peer);
        next.push_back(np);
        // same phantom-exchange guard as the JSON path: judged against
        // the goal the request carried (the encoder's shadow)
        auto sh = plan_enc.shadow_of(pkt->idx[k]);
        const bool unchanged = sh && sh->second == it->second.goal;
        Cell ng = static_cast<Cell>(pkt->goal[k]);
        old_goals.push_back(it->second.goal);
        new_goals.push_back(unchanged && ng >= 0 && ng < cells
                                ? ng : it->second.goal);
      }
    } else {
      for (const auto& mv : d["moves"].as_array()) {
        auto np = parse_point(mv["next_pos"]);
        if (!np) continue;
        const std::string& peer = mv["peer_id"].as_str();
        auto it = agents.find(peer);
        if (it == agents.end()) continue;
        ids.push_back(peer);
        next.push_back(*np);
        // exchanges are judged against the goal THE REQUEST carried, and
        // only for agents whose goal is unchanged since — a completion or
        // fresh assignment in flight must not fabricate a phantom exchange
        auto sg = sent_goals.find(peer);
        const bool unchanged = sg != sent_goals.end()
                               && sg->second == it->second.goal;
        old_goals.push_back(it->second.goal);
        auto ng = parse_point(mv["goal"]);
        new_goals.push_back(ng && unchanged ? *ng : it->second.goal);
      }
    }
    emit_moves(ids, next);
    // the daemon's returned post-swap goals re-assign tasks exactly like
    // the native path (adopt_goal_exchanges)
    adopt_goal_exchanges(ids, old_goals, new_goals);
  };

  auto save_csv = [&](const std::string& path, const std::string& content) {
    std::ofstream out(path);
    if (!out) {
      log_warn("⚠️  cannot write %s\n", path.c_str());
      return;
    }
    out << content;
    log_info("💾 saved %s\n", path.c_str());
  };

  auto handle_command = [&](const std::string& line) -> bool {
    std::istringstream in(line);
    std::string cmd;
    in >> cmd;
    if (cmd == "quit" || cmd == "exit") return false;
    if (ha_on && ha_role_standby
        && (cmd == "task" || cmd == "tasks" || cmd == "taskat")) {
      // operator load arriving at a warm standby (a replay driver
      // re-routing around a dead active): deferred, drained at
      // promotion — a standby must never mint or queue tasks itself.
      // Past the cap the line is DROPPED — loudly: the counter + log
      // are the only way a judge's "missing task" traces back here.
      if (ha_deferred_cmds.size() < 10000) {
        ha_deferred_cmds.push_back(line);
      } else {
        metrics_count("manager.ha_deferred_dropped");
        log_warn("⚠️  standby deferred-command queue full; dropping "
                 "operator line: %s\n", line.c_str());
      }
      return true;
    }
    if (cmd == "task") {
      queue_task();
      try_assign_pending();
    } else if (cmd == "tasks") {
      size_t n = 0;
      in >> n;
      if (!n) n = agents.size();
      for (size_t k = 0; k < n; ++k) queue_task();
      try_assign_pending();
      log_info("📦 queued %zu tasks (%zu pending)\n", n, pending_tasks.size());
    } else if (cmd == "taskat") {
      // replay injection (ISSUE 11): queue a task with EXPLICIT
      // endpoints and (optionally) an explicit id, so a captured
      // window re-drives as a deterministic load instead of a fresh
      // rng sample.  fleetsim --replay writes these lines.
      long long px = -1, py = -1, dx = -1, dy = -1, id = -1;
      in >> px >> py >> dx >> dy;
      if (!(in >> id)) id = -1;
      if (!grid.in_bounds(static_cast<int>(px), static_cast<int>(py)) ||
          !grid.in_bounds(static_cast<int>(dx), static_cast<int>(dy))) {
        log_warn("⚠️  taskat: out-of-bounds (%lld,%lld)->(%lld,%lld)\n",
                 px, py, dx, dy);
        metrics_count("manager.taskat_rejected");
      } else {
        if (id >= 0)
          bump_task_id_past(static_cast<uint64_t>(id));
        uint64_t tid;
        if (id >= 0) {
          tid = static_cast<uint64_t>(id);
        } else {
          tid = next_task_id;
          next_task_id += task_id_stride;
        }
        Json t;
        t.set("pickup", point_json(grid.cell(static_cast<int>(px),
                                             static_cast<int>(py))))
            .set("delivery", point_json(grid.cell(static_cast<int>(dx),
                                                  static_cast<int>(dy))))
            .set("peer_id", Json())
            .set("task_id", static_cast<int64_t>(tid));
        if (tctx) {
          codec::TraceCtx t0{trace_epoch | static_cast<long long>(tid), 0,
                             unix_ms()};
          event_emit("task.queue", &t0, static_cast<long long>(tid));
        }
        pending_tasks.push_back(std::move(t));
        try_assign_pending();
      }
    } else if (cmd == "metrics") {
      log_info("%s\n", task_metrics.statistics().to_string().c_str());
      if (auto ps = path_metrics.statistics())
        log_info("%s\n", ps->to_string().c_str());
      log_info("%s\n",
               MetricsRegistry::instance().network_summary_string().c_str());
      // live registry dump (Prometheus text): ticks, cache, per-topic bytes
      log_info("%s", MetricsRegistry::instance().expose_text().c_str());
    } else if (cmd == "save") {
      std::string a, b;
      in >> a >> b;
      if (a == "path")
        save_csv(b.empty() ? "path_metrics.csv" : b,
                 path_metrics.to_csv_string());
      else
        save_csv(a.empty() ? "task_metrics.csv" : a,
                 task_metrics.to_csv_string());
    } else if (cmd == "reset") {
      task_metrics.clear();
      path_metrics.clear();
      pending_tasks.clear();
      for (auto& [peer, a] : agents) {
        a.task.reset();
        a.phase = Phase::None;
        a.goal = a.pos;
      }
      log_info("🔄 state reset\n");
    } else if (!cmd.empty()) {
      Json raw;
      raw.set("raw", line);
      bus.publish("mapd", raw);
    }
      return true;
  };

  int64_t last_plan = 0, last_cleanup = mono_ms(), last_audit = 0;
  std::string stdin_buf;
  bool running = true;

  while (running && !g_stop && bus.connected()) {
    // poll every shard link plus stdin (stdin stays LAST in the vector)
    std::vector<pollfd> pfds;
    bus.append_pollfds(pfds);
    pfds.push_back({STDIN_FILENO, POLLIN, 0});
    poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 100);

    if (pfds.back().revents & POLLIN) {
      char buf[4096];
      ssize_t n = read(STDIN_FILENO, buf, sizeof(buf));
      if (n > 0) {
        stdin_buf.append(buf, static_cast<size_t>(n));
        size_t nl;
        while ((nl = stdin_buf.find('\n')) != std::string::npos) {
          std::string line = stdin_buf.substr(0, nl);
          stdin_buf.erase(0, nl + 1);
          if (!handle_command(line)) {
            running = false;
            break;
          }
        }
      } else if (n == 0) {
        running = false;
      }
    }

    bool alive = bus.pump(
        [&](const BusClient::Msg& m) {
          const Json& d = m.data;
          const std::string& type = d["type"].as_str();
          // HA plane first (ISSUE 15): ha frames are handled in either
          // role; everything else is IGNORED while standby — a warm
          // replica must never ingest fleet traffic it cannot act on
          // (its subscriptions are ha-only anyway; this also covers
          // the demoted-active case, whose old subscriptions remain)
          if (ha_on && ha_handle_frame(d)) return;
          if (ha_on && ha_role_standby) return;
          if (type == "position_update" || type == "pos1") {
            // one heartbeat ingestion for both wires: flat JSON
            // position_update and the packed pos1 region beacon (which is
            // addressed by the bus frame's own `from`).  A MULTIPLEXED
            // client (analysis/fleetsim.py simulates thousands of agents
            // over one connection) puts the agent identity in an optional
            // envelope `peer_id` instead — it wins over `from` when
            // present; real per-process agents never set it.
            std::string peer;
            std::optional<Cell> p;
            bool has_busy = false;
            long long busy_tid = 0;
            if (type == "pos1") {
              auto p1 = codec::decode_pos1_b64(d["data"].as_str());
              if (!p1) return;
              peer = d.has("peer_id") ? d["peer_id"].as_str() : m.from;
              if (p1->pos >= 0 &&
                  p1->pos < static_cast<Cell>(grid.free.size()))
                p = p1->pos;
              has_busy = p1->has_task;
              busy_tid = p1->task_id;
              // busy-claim heartbeats carry their task's trace1 block:
              // per-hop one-way latency only (no event — beacon rate)
              if (tctx && p1->has_trace)
                hop_latency_ms(p1->trace.send_ms, "task.claim_hb");
            } else {
              peer = d["peer_id"].as_str();
              p = parse_point(d["position"]);
              has_busy = d.has("busy_task");
              busy_tid = d["busy_task"].as_int();
              if (tctx)
                if (auto t = tc_parse(d))
                  hop_latency_ms(t->send_ms, "task.claim_hb");
            }
            if (clean && known_left.count(peer)) return;
            if (!p) return;
            auto it = agents.find(peer);
            if (it == agents.end()) {
              // post-takeover restore (ISSUE 15): this agent's
              // in-flight task rode the replication stream — reattach
              // it instead of adopting the agent idle, which would
              // hand it a SECOND task while it works the first.  The
              // normal idle-but-busy reconciliation then re-sends the
              // task if the agent actually lost its copy.
              auto rst = ha_restore_task.find(peer);
              if (rst != ha_restore_task.end()) {
                AgentInfo a;
                a.pos = *p;
                a.last_seen_ms = mono_ms();
                a.dispatched_ms = mono_ms();
                a.task = rst->second;
                auto ph = ha_restore_phase.find(peer);
                a.phase = (ph != ha_restore_phase.end())
                              ? ph->second : Phase::ToPickup;
                auto cell = parse_point((*a.task)[
                    a.phase == Phase::ToDelivery ? "delivery"
                                                 : "pickup"]);
                a.goal = cell ? *cell : *p;
                agents[peer] = a;
                ha_restore_task.erase(rst);
                ha_restore_phase.erase(peer);
                metrics_count("manager.ha_restored_lanes");
                log_info("🔗 HA restore: %s re-attached to task %lld\n",
                         peer.c_str(),
                         (*agents[peer].task)["task_id"].as_int());
                try_assign_pending();
                return;
              }
              if (fed_on) {
                // ownership (ISSUE 14): adopt only agents standing in
                // OUR region; a foreign agent in the border strip
                // becomes a stationary mirror lane instead (boundary
                // planning correctness), anything further is not ours.
                // A peer in transfer limbo (unacked outbound handoff)
                // is never re-adopted — the neighbor owns it the moment
                // the record applies.
                if (handing_off.count(peer)) return;
                const int x = grid.x_of(*p), y = grid.y_of(*p);
                if (fed.region_of(grid.width, grid.height, x, y)
                    != region_id) {
                  claim_candidates.erase(peer);
                  if (FedMap::in_border(x, y, my_rect, fed_border))
                    mirror_touch(peer, *p);
                  else
                    mirrors.erase(peer);
                  return;
                }
                if (!fed_claimable(x, y)) {
                  // inside our rect but within a neighbor's hysteresis
                  // reach: possibly still the neighbor's.  Wait for its
                  // handoff — or the unclaimed grace — before adopting
                  // (mirror it meanwhile so planning routes around it).
                  const int64_t now2 = mono_ms();
                  auto [cit, fresh] =
                      claim_candidates.emplace(peer, now2);
                  if (fresh || now2 - cit->second < claim_grace_ms) {
                    mirror_touch(peer, *p);
                    return;
                  }
                  metrics_count("manager.fed_grace_adoptions");
                }
                claim_candidates.erase(peer);
                mirrors.erase(peer);
              }
              AgentInfo a;
              a.pos = a.goal = *p;
              a.last_seen_ms = mono_ms();
              agents[peer] = a;
              log_info("🔍 tracking agent %s (%zu)\n", peer.c_str(),
                       agents.size());
              try_assign_pending();
            } else {
              AgentInfo& a = it->second;
              a.pos = *p;
              a.last_seen_ms = mono_ms();
              if (fed_on) {
                // hysteresis escape: hand the lane (and its task) to
                // the region the agent now stands in — only once it is
                // MORE than the margin outside ours, so border
                // oscillation never thrashes ownership
                const int x = grid.x_of(*p), y = grid.y_of(*p);
                if (FedMap::escaped(x, y, my_rect, fed_hyst)) {
                  const int dst =
                      fed.region_of(grid.width, grid.height, x, y);
                  if (dst != region_id) {
                    send_handoff(peer, a, dst);
                    agents.erase(it);
                    return;
                  }
                }
              }
              if (!a.task) a.goal = *p;
              // idle-but-marked-busy reconciliation: the heartbeat carries
              // a busy_task field while the agent holds a task; still-idle
              // (or still on a DIFFERENT task — an exchanged-task
              // re-broadcast can be lost too) well past dispatch means the
              // Task publish was dropped — re-send the SAME task.  A lost
              // DONE instead heals via the agent's retransmit (which also
              // refuses this duplicate by task id).
              bool stale_assignment =
                  a.task
                  && (!has_busy
                      || busy_tid != (*a.task)["task_id"].as_int());
              if (stale_assignment
                  && mono_ms() - a.dispatched_ms > task_resend_ms) {
                log_info("↻ %s reports idle but task %lld is in flight; "
                         "re-sending\n", peer.c_str(),
                         static_cast<long long>(
                             (*a.task)["task_id"].as_int()));
                if (tctx) {
                  long long tid = (*a.task)["task_id"].as_int();
                  auto t = hops.next(tid);
                  a.task->set("tc", tc_json(t));
                  event_emit("task.resend", &t, tid, peer);
                }
                bus.publish("mapd", *a.task);
                a.dispatched_ms = mono_ms();
              }
            }
          } else if (type == "plan_response") {
            handle_plan_response(d);
          } else if (type == "plan_snapshot_request") {
            // solverd lost the delta chain (restart, dropped packet): the
            // next planning tick re-sends the full fleet state
            plan_enc.request_snapshot();
            metrics_count("manager.plan_snapshot_requests");
            log_info("🔁 solver daemon requested a plan snapshot "
                     "(its chain ends at seq %lld)\n",
                     static_cast<long long>(d["have_seq"].as_int()));
            if (dynamic_world && !world_state.empty()) {
              // world replay (ISSUE 10): a resyncing daemon may have
              // restarted with the ORIGINAL map — re-send every
              // accumulated toggle at the current epoch so its grid
              // (and world_seq, which it adopts from the frame)
              // reconverges with the planner of record
              std::vector<int32_t> cells, blocked;
              for (const auto& [c, b] : world_state) {
                cells.push_back(c);
                blocked.push_back(b);
              }
              publish_world_update(cells, blocked, /*to_mapd=*/false);
              metrics_count("manager.world_replays");
              log_info("🌍 replayed %zu accumulated world toggle(s) at "
                       "epoch %lld with the snapshot\n",
                       cells.size(), static_cast<long long>(world_seq));
            }
          } else if (type == "task_metric_received") {
            task_metrics.update_received(
                static_cast<uint64_t>(d["task_id"].as_int()),
                d["timestamp_ms"].as_int());
          } else if (type == "task_metric_started") {
            task_metrics.update_started(
                static_cast<uint64_t>(d["task_id"].as_int()),
                d["timestamp_ms"].as_int());
          } else if (type == "task_metric_completed") {
            const uint64_t tid = static_cast<uint64_t>(d["task_id"].as_int());
            task_metrics.update_completed(tid, d["timestamp_ms"].as_int());
            // live task-latency histogram for the fleet rollup (beacons)
            auto itm = task_metrics.metrics.find(tid);
            if (itm != task_metrics.metrics.end())
              if (auto t = itm->second.total_time())
                metrics_observe("task.total_time_ms",
                                static_cast<double>(*t));
          } else if (type == "world_update_request") {
            handle_world_request(d);
          } else if (type == "audit_drill_request") {
            handle_drill(d);
          } else if (type == "flight_dump") {
            // black-box query: dump the ring and answer with the path
            bus.publish("mapd",
                        flight_dump_answer("manager_centralized", my_id));
          } else if (fed_on && type.empty() && d.has("task_id")
                     && d.has("pickup") && d.has("delivery")
                     && !d["peer_id"].as_str().empty()) {
            // ownership-conflict arbitration (ISSUE 14): every manager
            // hears every task dispatch on "mapd".  One naming an
            // agent WE track, carrying a task our ledger cannot
            // explain, means another region claimed the agent (grace
            // adoption of a band-dweller, beacon races).  The agent's
            // POSITION arbitrates — both sides apply the same rule to
            // the same beacons, so exactly one yields: if it stands
            // outside our rectangle we RELEASE it (our in-flight task
            // re-queues locally; at-least-once, the done path dedups),
            // if inside we keep it and the dispatcher releases when it
            // hears OUR next dispatch/re-send.  Without this a
            // band-dwelling agent collects conflicting tasks from two
            // planners and both ledgers wedge (found live by the 2x2
            // ladder).
            const std::string peer = d["peer_id"].as_str();
            auto it = agents.find(peer);
            if (it == agents.end()) return;
            const long long tid = d["task_id"].as_int();
            bool known =
                it->second.task
                && (*it->second.task)["task_id"].as_int() == tid;
            if (!known)
              for (const auto& q : pending_tasks)
                if (q["task_id"].as_int() == tid) {
                  known = true;
                  break;
                }
            if (known || requeued_ids.count(tid)
                || completed_ids.count(tid))
              return;
            const int x = grid.x_of(it->second.pos);
            const int y = grid.y_of(it->second.pos);
            if (x >= my_rect.x0 && x < my_rect.x1 && y >= my_rect.y0 &&
                y < my_rect.y1)
              return;  // standing in OUR rect: the other side yields
            requeue_task(peer, it->second,
                         "ownership conflict, releasing");
            metrics_count("manager.fed_conflict_releases");
            agents.erase(it);
            try_assign_pending();
          } else if (type == "handoff1") {
            // ---- cross-region handoff, inbound (ISSUE 14) ----
            if (!fed_on || static_cast<int>(d["dst"].as_int()) != region_id)
              return;
            const int src = static_cast<int>(d["src"].as_int());
            const int64_t hseq = d["seq"].as_int();
            const int64_t hepoch = d["epoch"].as_int();
            Json ack;
            ack.set("type", "handoff_ack")
                .set("src", static_cast<int64_t>(src))
                .set("dst", static_cast<int64_t>(region_id))
                .set("seq", hseq)
                .set("epoch", hepoch)  // sender matches its own epoch
                .set("peer_id", d["peer_id"]);
            // per-epoch dedup sets (ISSUE 15): each sender incarnation
            // owns its own seq chain.  A promoted standby retransmits
            // its dead active's OLD-epoch records while minting new
            // ones under its own epoch — both must dedup against their
            // own chain (the old reset-on-newer-epoch rule would
            // strand the restored retransmits: dropped as stale, never
            // acked, agent in limbo forever).
            auto& epochs_seen = handoff_applied[src];
            auto& seen = epochs_seen[hepoch];
            while (epochs_seen.size() > 4) {
              // bound: keep the newest epochs, never evicting the one
              // this frame just landed in
              auto oldest = epochs_seen.begin();
              if (oldest->first == hepoch) ++oldest;
              epochs_seen.erase(oldest);
            }
            if (seen.count(hseq)) {
              // replayed/retransmitted record: ack again (its ack was
              // lost), NEVER re-apply — a duplicate handoff must not
              // double-admit the lane or double-dispatch its task
              metrics_count("manager.handoffs_dup_dropped");
              bus.publish(FedMap::fed_topic(src), ack);
              return;
            }
            auto pkt = codec::decode_b64(d["data"].as_str());
            std::optional<codec::HandoffRec> rec;
            if (pkt) rec = codec::decode_handoff(*pkt);
            const Cell cells = static_cast<Cell>(grid.free.size());
            if (!rec || rec->pos < 0 || rec->pos >= cells ||
                (rec->has_task &&
                 (rec->pickup < 0 || rec->pickup >= cells ||
                  rec->delivery < 0 || rec->delivery >= cells))) {
              // malformed record: counted, NOT acked — the sender keeps
              // retransmitting and the counter names the problem
              metrics_count("manager.bad_handoffs");
              return;
            }
            seen.insert(hseq);
            while (seen.size() > 8192) seen.erase(seen.begin());
            const std::string hpeer = rec->peer;
            AgentInfo a;
            a.pos = static_cast<Cell>(rec->pos);
            a.goal = (rec->goal >= 0 && rec->goal < cells)
                         ? static_cast<Cell>(rec->goal) : a.pos;
            a.last_seen_ms = mono_ms();
            a.dispatched_ms = mono_ms();
            if (rec->has_task) {
              Json t;
              t.set("pickup", point_json(static_cast<Cell>(rec->pickup)))
                  .set("delivery",
                       point_json(static_cast<Cell>(rec->delivery)))
                  .set("peer_id", hpeer)
                  .set("task_id", rec->task_id);
              a.task = t;
              a.phase = rec->phase == 2 ? Phase::ToDelivery
                                        : Phase::ToPickup;
              // the ledger entry moves WITH the lane: metrics and the
              // audit ledger digest now account for it here
              TaskMetric m;
              m.task_id = static_cast<uint64_t>(rec->task_id);
              m.peer_id = hpeer;
              m.sent_time = unix_ms();
              task_metrics.add_metric(m);
              if (rec->task_id >= 0)
                bump_task_id_past(static_cast<uint64_t>(rec->task_id));
            }
            known_left.erase(hpeer);  // --clean must re-track a handoff
            mirrors.erase(hpeer);
            claim_candidates.erase(hpeer);
            // ownership-race merge: we may already track this agent
            // (adopted from its beacons before the neighbor's record
            // arrived).  The LEDGER ENTRY is what must never be lost
            // or doubled:
            //  - incoming carries a DIFFERENT task: our local
            //    assignment RE-QUEUES (never silently clobbered — that
            //    loses it from every ledger; found by the smoke's
            //    exact-once accounting) and the neighbor's state wins;
            //  - incoming carries the SAME task, or NO task while we
            //    hold one: our record is fresher (pickup flips and
            //    goal exchanges happened HERE) — keep it, just ack.
            auto prev = agents.find(hpeer);
            if (prev != agents.end() && prev->second.task) {
              const long long ptid =
                  (*prev->second.task)["task_id"].as_int();
              if (rec->has_task && ptid != rec->task_id) {
                requeue_task(hpeer, prev->second, "handoff displaced");
              } else {
                prev->second.last_seen_ms = mono_ms();
                metrics_count("manager.handoffs_received");
                bus.publish(FedMap::fed_topic(src), ack);
                return;
              }
            }
            agents[hpeer] = a;
            handoff_admitted[hpeer] = mono_ms() + 10000;
            metrics_count("manager.handoffs_received");
            bus.publish(FedMap::fed_topic(src), ack);
            log_info("🛬 handoff %lld from region %d: adopted %s%s\n",
                     static_cast<long long>(hseq), src, hpeer.c_str(),
                     rec->has_task ? " (with task)" : "");
            try_assign_pending();
          } else if (type == "handoff_ack") {
            if (!fed_on || static_cast<int>(d["src"].as_int()) != region_id)
              return;
            auto key = std::make_pair(
                static_cast<int>(d["dst"].as_int()), d["seq"].as_int());
            auto hit = handoff_unacked.find(key);
            // the ack must echo the RECORD's own epoch — an ack for
            // another incarnation's record (same seq, different
            // lane/task) must not cancel this one.  Judged per record,
            // not against the process-global fed_epoch: a promoted
            // standby's restored outbox entries keep their ORIGINAL
            // epoch (ISSUE 15) and their acks must still land.
            if (hit != handoff_unacked.end()
                && d["epoch"].as_int()
                       != hit->second.frame["epoch"].as_int())
              return;
            if (hit != handoff_unacked.end()) {
              handing_off.erase(hit->second.peer);
              handoff_unacked.erase(hit);
              metrics_count("manager.handoffs_acked");
              metrics_gauge("manager.fed_pending_handoffs",
                            static_cast<double>(handoff_unacked.size()));
            }
          } else if (d["status"].as_str() == "done") {
            // same multiplexed-client accommodation as the heartbeat
            // path: an explicit payload peer_id outranks the frame from
            const std::string peer =
                d.has("peer_id") ? d["peer_id"].as_str() : m.from;
            const long long tid = d["task_id"].as_int();
            // post-takeover hold entries (ISSUE 15): a done for a task
            // still in the restore set completes it — the agent
            // finished during the outage without re-beaconing first.
            // The entry must leave the hold set (the hold expiry would
            // otherwise re-queue a completed task) but still counts as
            // ledger-known below.
            bool ha_restore_known = false;
            for (auto rit = ha_restore_task.begin();
                 rit != ha_restore_task.end(); ++rit) {
              if (rit->second["task_id"].as_int() == tid) {
                ha_restore_known = true;
                ha_restore_phase.erase(rit->first);
                ha_restore_task.erase(rit);
                break;
              }
            }
            if (fed_on) {
              // ownership (ISSUE 14): every region manager hears
              // "mapd", so only the region whose LEDGER knows the task
              // may count a done — anything else either acks without
              // counting (we track the reporter: quiet its retransmit;
              // the region of record dedups and counts) or ignores the
              // frame outright.  An agent mid-handoff keeps
              // retransmitting until the new owner applies the record
              // and answers — the retransmit heals the limbo window.
              // owner-first short-circuit: the common case is the
              // reporter's own region hearing its own done — one map
              // lookup.  The linear fallbacks below run only on
              // foreign frames and are bounded by max_agents (500) and
              // the pending deque; fine at done rates, and an
              // inflight-id index is the scaling follow-up if a
              // many-region profile ever shows them.
              auto rit = agents.find(peer);
              bool task_known = ha_restore_known
                  || (rit != agents.end() && rit->second.task
                      && (*rit->second.task)["task_id"].as_int() == tid)
                  || completed_ids.count(tid) || requeued_ids.count(tid);
              if (!task_known)
                for (const auto& q : pending_tasks)
                  if (q["task_id"].as_int() == tid) {
                    task_known = true;
                    break;
                  }
              if (!task_known)
                for (const auto& [ap, aa] : agents)
                  if (aa.task && (*aa.task)["task_id"].as_int() == tid) {
                    task_known = true;
                    break;
                  }
              // unknown task: IGNORE outright — even when we track the
              // reporter.  Acking here would clear the agent's
              // unacked_done and silence the retransmit that is the
              // region of record's only heal if ITS copy of the frame
              // was dropped (per-subscriber slow-consumer eviction);
              // the owner hears a later retransmit and acks it itself.
              if (!task_known) return;
            } else if (ha_promoted) {
              // exact-once across a takeover (ISSUE 15): only
              // ledger-known ids count.  An unknown id is a
              // pre-takeover completion whose ack died with the old
              // active — ACK it (quieting the agent's retransmit; we
              // ARE the region of record now, nobody else will) but
              // never count it, or the system-of-record completion
              // counter would read a double completion.
              bool known = ha_restore_known
                  || completed_ids.count(tid)
                  || requeued_ids.count(tid);
              if (!known) {
                auto kit = agents.find(peer);
                known = kit != agents.end() && kit->second.task
                    && (*kit->second.task)["task_id"].as_int() == tid;
              }
              if (!known)
                for (const auto& q : pending_tasks)
                  if (q["task_id"].as_int() == tid) {
                    known = true;
                    break;
                  }
              if (!known)
                for (const auto& [ap, aa] : agents)
                  if (aa.task
                      && (*aa.task)["task_id"].as_int() == tid) {
                    known = true;
                    break;
                  }
              if (!known) {
                Json ack;
                ack.set("type", "done_ack").set("peer_id", peer)
                    .set("task_id", Json(static_cast<int64_t>(tid)));
                bus.publish("mapd", ack);
                metrics_count("manager.ha_unknown_done_acked");
                return;
              }
            }
            auto done_tc = tc_parse(d);
            if (done_tc) {
              hops.seen(tid, *done_tc);
              event_emit("task.done", &*done_tc, tid, peer,
                         done_tc->send_ms);
            }
            // ack unconditionally: agents retransmit done until acked, and
            // a duplicate (its ack was lost) must still be acked
            Json ack;
            ack.set("type", "done_ack").set("peer_id", peer)
                .set("task_id", Json(static_cast<int64_t>(tid)));
            if (tctx && done_tc) {
              auto t = hops.next(tid);
              ack.set("tc", tc_json(t));
            }
            bus.publish("mapd", ack);
            auto it = agents.find(peer);
            if (it != agents.end() && it->second.task
                && (*it->second.task)["task_id"].as_int() == tid) {
              it->second.task.reset();
              it->second.phase = Phase::None;
              it->second.goal = it->second.pos;
            }
            if (completed_ids.count(tid)) {
              // second completion of a re-dispatched task (at-least-once
              // re-queue): counted once already — free the reporter and
              // keep it in the work loop, but don't count the duplicate
              log_warn("⚠️  duplicate done for task %lld (%s) ignored\n",
                       tid, peer.c_str());
              // only refill a task-FREE reporter: a late done for an old
              // task (original agent of a requeued task reporting after
              // re-dispatch) must not overwrite an in-flight assignment
              if (it != agents.end() && !it->second.task
                  && pending_tasks.empty() && !open_loop)
                assign_task(peer, make_task());
              try_assign_pending();
            } else {
              if (requeued_ids.erase(tid)) {
                // the presumed-dead agent finished after all: cancel the
                // queued duplicate if it is still pending.  The id goes
                // into completed_ids EITHER WAY — the task may have been
                // re-queued more than once (another copy already
                // dispatched, or re-queued again later), and any
                // subsequent done for it must dedupe.
                completed_ids.insert(tid);
                for (auto q = pending_tasks.begin();
                     q != pending_tasks.end(); ++q)
                  if ((*q)["task_id"].as_int() == tid) {
                    pending_tasks.erase(q);
                    log_info("♻️  task %lld done by its original agent; "
                             "queued duplicate cancelled\n", tid);
                    break;
                  }
              }
              log_info("🎉 %s finished task %lld\n", peer.c_str(), tid);
              // counted on the DEDUPED path only: a retransmitted or
              // double-completed done never inflates the fleet tasks/s
              metrics_count("manager.tasks_completed");
              // auto-reassign on completion (ref :908-950): queued tasks
              // (incl. ones re-queued from dead agents) drain before a fresh
              // task is generated, so orphans cannot starve behind auto-refill
              // guarded on !task for the same late-duplicate-done reason
              // as the branch above: never clobber an in-flight assignment
              if (it != agents.end() && !it->second.task
                  && pending_tasks.empty() && !open_loop)
                assign_task(peer, make_task());
              try_assign_pending();
            }
          }
                },
        [&](const Json& ev) {
          if (ev["op"].as_str() == "peer_left") {
            const std::string& peer = ev["peer_id"].as_str();
            known_left.insert(peer);
            mirrors.erase(peer);  // a dead foreign agent stops mirroring
            auto it = agents.find(peer);
            if (it != agents.end()) {
              // The task restarts from pickup on the next idle agent.
              requeue_task(peer, it->second, "agent died:");
              agents.erase(it);
              try_assign_pending();
                        }
          }
        });
    if (!alive) break;

    int64_t now = mono_ms();
    if (now - last_plan >= planning_ms) {  // planning tick (ref :675-724)
      Span sp("manager.plan_tick",
              "\"agents\":" + std::to_string(agents.size()));
      trace_count("manager.plan_ticks");
      auto tick_t0 = std::chrono::steady_clock::now();
      last_plan = now;
      // roll the move-target protection window (last two ticks)
      prev_move_targets = std::move(recent_move_targets);
      recent_move_targets.clear();
      if (fed_on) {
        // expire border mirrors whose beacons stopped (the agent left
        // the strip, died, or crossed in and got adopted)
        for (auto mit = mirrors.begin(); mit != mirrors.end();) {
          if (now - mit->second.last_seen > mirror_expire_ms ||
              agents.count(mit->first) || handing_off.count(mit->first))
            mit = mirrors.erase(mit);
          else
            ++mit;
        }
        metrics_gauge("manager.fed_mirrors",
                      static_cast<double>(mirrors.size()));
      }
      pickup_transitions();
      if (!agents.empty()) {
        if (solver == "tpu") {
          // keep requesting so a revived daemon ends the failover, but
          // plan natively while it is silent — the fleet must keep moving
          // (the reference has no comparable resilience path)
          plan_request_tpu();
          if (now - last_plan_response > solver_failover_ms) {
            if (!failed_over) {
              failed_over = true;
              trace_count("manager.solver_failovers");
              trace_instant("manager.solver_failover");
              log_warn("⚠️  solver daemon silent for %lld ms; planning "
                       "natively until it responds\n",
                       static_cast<long long>(now - last_plan_response));
            }
            plan_native();
          }
        } else {
          plan_native();
        }
      }
      // live tick accounting (registry, always on): p50/p95 vs the
      // planning budget in the fleet rollup.  In tpu mode this covers
      // only the host-side encode — the daemon's own tick_ms rides its
      // beacon — so the number is honest either way.
      double tick_ms_taken =
          std::chrono::duration<double, std::milli>(
              std::chrono::steady_clock::now() - tick_t0)
              .count();
      metrics_observe("tick_ms", tick_ms_taken);
      if (tick_ms_taken > static_cast<double>(planning_ms))
        metrics_count("tick.over_budget");
      metrics_gauge("tick.agents", static_cast<double>(agents.size()));
      // queue-depth gauge (ISSUE 16): dispatch is capacity-gated (a
      // task leaves pending_tasks only when an agent frees up), so the
      // dispatched/completed counter pair can never show an overload —
      // the backlog here is the fleet's actual pressure signal, the
      // one the health plane forecasts over
      metrics_gauge("manager.tasks_pending",
                    static_cast<double>(pending_tasks.size()));
    }
    if (audit_on && now - last_audit >= audit_interval_ms) {
      last_audit = now;
      publish_audit_beacon();
    }
    if (fed_on && now - last_handoff_retry >= handoff_retry_ms) {
      // retransmit-until-ack: a lost handoff (or lost ack) heals here;
      // the receiver's dedup guard makes the replay harmless
      last_handoff_retry = now;
      for (auto& [key, out] : handoff_unacked) {
        if (now - out.last_send_ms >= handoff_retry_ms) {
          bus.publish(FedMap::fed_topic(out.dst), out.frame);
          out.last_send_ms = now;
          metrics_count("manager.handoff_retransmits");
        }
      }
    }
    if (ha_on) {
      if (!ha_role_standby) {
        // active: renew the lease and ship the replication stream
        if (now - last_ha_lease >= ha_lease_ms) {
          last_ha_lease = now;
          ha_publish_lease();
        }
        // per-iteration write-ahead flush: a pending dispatch forces
        // it immediately; otherwise the (cheap, diff-only) replication
        // check runs on a short cadence
        if (!ha_task_outbox.empty() || now - last_ha_repl >= 50) {
          last_ha_repl = now;
          ha_flush();  // record first, then the deferred Task frames
        }
        if (ha_hold_until && now >= ha_hold_until) {
          // sweep-hold expiry: an in-flight entry whose agent never
          // reported inside one claim window re-queues AT-LEAST-ONCE
          // (its agent may still finish; the done path dedups by id)
          for (auto& [peer, tj] : ha_restore_task) {
            const long long tid = tj["task_id"].as_int();
            Json t = tj;
            t.set("peer_id", Json());
            requeued_ids.insert(tid);
            pending_tasks.push_front(std::move(t));
            metrics_count("manager.ha_hold_requeues");
            log_info("♻️  HA hold expired: task %lld of silent agent "
                     "%s re-queued\n", tid, peer.c_str());
          }
          ha_restore_task.clear();
          ha_restore_phase.clear();
          ha_hold_until = 0;
          try_assign_pending();
        }
        if (ha_drain_cmds) {
          // operator lines deferred while standby (replay taskat) run
          // now that we ARE the system of record
          ha_drain_cmds = false;
          std::deque<std::string> lines;
          lines.swap(ha_deferred_cmds);
          for (const auto& line : lines) handle_command(line);
        }
      } else {
        // standby: judge the active's lease by the auditor's
        // silent-peer rule; a cold start with NO active ever heard
        // promotes after a longer grace (nobody owns the region)
        if (ha_lease_last
            && ha::lease_expired(now, ha_lease_last,
                                 ha_lease_interval)) {
          metrics_count("manager.ha_lease_expiries");
          log_warn("⚠️  HA lease expired: active %s (incarnation %lld) "
                   "silent %lld ms — taking over\n",
                   ha_active_peer.c_str(),
                   static_cast<long long>(ha_active_inc),
                   static_cast<long long>(now - ha_lease_last));
          ha_promote("lease_expired");
        } else if (!ha_lease_last
                   && now - ha_started > 6 * ha_lease_ms + 3000) {
          log_warn("⚠️  HA cold start: no active ever announced — "
                   "claiming the region\n");
          ha_promote("cold_start");
        }
        if (ha_role_standby && ha_need_resync
            && now - ha_last_resync_req > 1000) {
          ha_last_resync_req = now;
          Json f;
          f.set("type", "ha_resync_request")
              .set("ns", audit_ns)
              .set("peer_id", my_id)
              .set("incarnation", ha_incarnation)
              .set("have_seq", ha_rep.seq);
          bus.publish(ha::kHaTopic, f, /*raw=*/true);
        }
      }
    }
    if (now - last_cleanup > cleanup_ms) {
      last_cleanup = now;
      // Stale age-out re-queues in-flight tasks just like peer_left does: a
      // live-but-silent agent never emits peer_left, and its task must not
      // be lost on this path either.  This is AT-LEAST-ONCE delivery: the
      // silent agent may still be alive (e.g. a transient bus stall) and
      // finish the task anyway — the done handler dedupes by task_id
      // (requeued_ids/completed_ids), cancelling the queued duplicate or
      // counting a double completion once.  The cap trim below deliberately
      // does NOT re-queue — it evicts agents that are still live and
      // working, so re-dispatching their task would run it twice.
      for (auto it = agents.begin(); it != agents.end();) {
        if (now - it->second.last_seen_ms > agent_stale_ms) {
          requeue_task(it->first, it->second, "evicting stale agent");
          it = agents.erase(it);
        } else {
          ++it;
        }
      }
      // Cap trim: only IDLE agents are eligible — evicting a live busy
      // agent would either lose its task (it re-registers task-less on the
      // next heartbeat and gets a second assignment) or duplicate it (if
      // re-queued while the agent keeps working).  If everyone is busy the
      // cap is soft: warn and keep them until tasks complete.
      while (agents.size() > max_agents) {
        auto oldest = agents.end();
        for (auto it = agents.begin(); it != agents.end(); ++it)
          if (!it->second.task
              && (oldest == agents.end()
                  || it->second.last_seen_ms < oldest->second.last_seen_ms))
            oldest = it;
        if (oldest == agents.end()) {
          log_warn("⚠️  %zu agents exceed cap %zu but all are busy; "
                   "deferring trim\n", agents.size(), max_agents);
          break;
        }
        agents.erase(oldest);
      }
      while (known_left.size() > max_known_peers)
        known_left.erase(known_left.begin());
      try_assign_pending();
      dc.trim(512);
      trace_flush();  // bounded ring: the 30 s cleanup cadence drains it
      log_info("🧹 [CLEANUP] agents=%zu pending=%zu\n", agents.size(),
               pending_tasks.size());
        }
  }

  if (const char* p = getenv("TASK_CSV_PATH"))
    save_csv(p, task_metrics.to_csv_string());
  if (const char* p = getenv("PATH_CSV_PATH"))
    save_csv(p, path_metrics.to_csv_string());
  log_info("%s\n", task_metrics.statistics().to_string().c_str());
  trace_flush();
  log_info("manager: bye\n");
  bus.close();
  return 0;
}
