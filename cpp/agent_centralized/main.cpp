// mapd_agent_centralized — "dumb telepresence body" (SURVEY C6).
//
// Native rebuild of src/bin/centralized/agent.rs: picks a random free cell,
// broadcasts position_update three times at startup then at least every
// second, obeys move_instruction messages addressed to its peer id (moves
// and re-broadcasts immediately), accepts Tasks addressed to it with
// task_metric_received/started emissions, and detects completion
// positionally (current_pos == task.delivery) with task_metric_completed +
// {"status":"done"}.
//
// Usage: mapd_agent_centralized [--port P] [--map FILE] [--seed S]

#include <poll.h>
#include <signal.h>

#include <cstdio>
#include <cstring>
#include <optional>
#include <random>
#include <string>

#include "../common/bus.hpp"
#include "../common/events.hpp"
#include "../common/grid.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/plan_codec.hpp"
#include "../common/region.hpp"

using namespace mapd;

namespace {
volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  const std::string bus_host = knobs.get_str("--host", "MAPD_BUS_HOST",
                                             "127.0.0.1");
  const uint16_t port = static_cast<uint16_t>(
      knobs.get_int("--port", "MAPD_BUS_PORT", 7400));
  const std::string map_file = knobs.get_str("--map", "MAPD_MAP", "");
  const uint64_t seed = static_cast<uint64_t>(knobs.get_int(
      "--seed", "MAPD_SEED",
      static_cast<int64_t>(std::random_device{}())));
  // >=1 s position heartbeat (ref :285-291), settable like every knob.
  const int64_t heartbeat_ms =
      knobs.get_int("--heartbeat-ms", "MAPD_HEARTBEAT_MS", 1000);
  // done retransmit cadence until the manager acks (lost-done desync fix)
  const int64_t done_retry_ms =
      knobs.get_int("--done-retry-ms", "MAPD_DONE_RETRY_MS", 2000);
  // Region-sharded heartbeats (ISSUE 4): the manager is the only consumer
  // of a centralized agent's position, yet the flat "mapd" broadcast fans
  // every heartbeat to every OTHER agent too.  With region gossip on the
  // heartbeat is a packed pos1 beacon on mapd.pos.<rx>.<ry>, which only
  // the wildcard-subscribed manager receives — fanout N, not N².
  // JG_REGION_GOSSIP=0 restores the flat JSON wire.
  const bool region_gossip =
      knobs.get_int("--region-gossip", "JG_REGION_GOSSIP", 1) != 0;
  const RegionMap regions(static_cast<int>(
      knobs.get_int("--region-cells", "JG_REGION_CELLS",
                    kDefaultRegionCells)));
  // slow JSON heartbeat cadence under region gossip, so a flat-wire
  // manager (kill-switched or reference-wire) still tracks this agent
  const int64_t legacy_pos_ms =
      knobs.get_int("--legacy-pos-ms", "JG_LEGACY_POS_MS", 2000);
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);
  // lifecycle events + flight recorder (ISSUE 5); trace-context
  // propagation gated by JG_TRACE_CTX
  events_init("agent_centralized");
  const bool tctx = trace_ctx_enabled();

  Grid grid = Grid::default_grid();
  if (!map_file.empty()) {
    auto g = Grid::from_file(map_file);
    if (!g) {
      fprintf(stderr, "cannot load map %s\n", map_file.c_str());
      return 1;
    }
    grid = *g;
  }
  std::mt19937_64 rng(seed);

  BusClient bus;
  std::string my_id = random_peer_id();
  if (!bus.connect(bus_host, port, my_id)) {
    fprintf(stderr, "cannot connect to bus on port %u\n", port);
    return 1;
  }
  bus.subscribe("mapd");
  bus.enable_metrics_beacon("agent_centralized");

  Cell my_pos = grid.random_free_cell(rng);
  std::optional<Json> my_task;
  // trace context of the held task (rode in on the Task message); every
  // SEND that references the task advances the hop, heartbeats repeat
  // the current hop (a claim is not causal progress)
  std::optional<codec::TraceCtx> my_tc;
  bool exec_emitted = false;  // first obeyed move_instruction per task
  auto my_tc_next = [&]() {
    my_tc->hop += 1;
    my_tc->send_ms = unix_ms();
    return *my_tc;
  };

  // Done retransmit-until-ack (lost-done desync fix): a done published
  // into a bus outage is silently dropped (bus.hpp: lossy medium), which
  // would leave the manager steering a taskless agent forever.  The
  // completed metric is stored verbatim so retransmits carry the ORIGINAL
  // completion timestamp.
  std::optional<Json> unacked_done;
  Json unacked_done_metric;
  long long unacked_done_id = -1;
  int64_t done_last_sent_ms = 0;
  std::optional<codec::TraceCtx> unacked_tc;  // refreshed per retransmit

  auto point_json = [&](Cell c) {
    Json p;
    p.push_back(Json(grid.x_of(c)));
    p.push_back(Json(grid.y_of(c)));
    return p;
  };
  auto parse_point = [&](const Json& j) -> std::optional<Cell> {
    const auto& arr = j.as_array();
    if (arr.size() != 2) return std::nullopt;
    int x = static_cast<int>(arr[0].as_int());
    int y = static_cast<int>(arr[1].as_int());
    if (!grid.in_bounds(x, y)) return std::nullopt;
    return grid.cell(x, y);
  };

  int64_t last_legacy_pos_ms = 0;
  auto broadcast_position = [&]() {
    if (region_gossip) {
      // packed heartbeat on the region topic (goal = pos: the centralized
      // agent has no local goal; the manager steers it by instruction)
      Json b;
      codec::TraceCtx hb_tc;
      bool with_tc = tctx && my_task.has_value() && my_tc.has_value();
      if (with_tc) {
        hb_tc = *my_tc;  // current hop, fresh stamp: a repeated claim
        hb_tc.send_ms = unix_ms();
      }
      b.set("type", "pos1")
          .set("data", codec::encode_pos1_b64(
                           my_pos, my_pos, my_task.has_value(),
                           my_task ? (*my_task)["task_id"].as_int() : 0,
                           with_tc ? &hb_tc : nullptr));
      bus.publish(regions.topic_for(grid, my_pos), b);
      // a slow JSON heartbeat rides along so a flat-wire manager (the
      // kill switch set on its side, or a reference-wire build) still
      // gets liveness + busy tracking
      const int64_t now = mono_ms();
      if (legacy_pos_ms <= 0 || now - last_legacy_pos_ms < legacy_pos_ms)
        return;
      last_legacy_pos_ms = now;
    }
    Json upd;
    upd.set("type", "position_update")
        .set("peer_id", my_id)
        .set("position", point_json(my_pos));
    // busy/idle status rides the heartbeat so the manager can detect a
    // Task whose delivery was lost in an outage (idle-but-marked-busy)
    if (my_task) {
      upd.set("busy_task", (*my_task)["task_id"]);
      if (tctx && my_tc) {
        codec::TraceCtx t = *my_tc;
        t.send_ms = unix_ms();
        upd.set("tc", tc_json(t));
      }
    }
    bus.publish("mapd", upd);
  };

  // Builds, publishes, and RETURNS the metric payload (the completed
  // metric is also held for retransmit-until-ack, original timestamp).
  auto task_metric = [&](const char* type) -> Json {
    Json m;
    if (!my_task || (*my_task)["task_id"].is_null()) return m;
    m.set("type", type)
        .set("task_id", (*my_task)["task_id"])
        .set("peer_id", my_id)
        .set("timestamp_ms", unix_ms());
    bus.publish("mapd", m);
    return m;
  };

  auto completion_check = [&]() {  // positional done detection (ref :379-410)
    if (!my_task) return;  // my_task.reset() below prevents duplicate done
    auto dl = parse_point((*my_task)["delivery"]);
    if (dl && my_pos == *dl) {
      Json metric = task_metric("task_metric_completed");
      Json done;
      done.set("status", "done").set("task_id", (*my_task)["task_id"]);
      if (tctx && my_tc) {
        event_emit("task.delivery", &*my_tc,
                   (*my_task)["task_id"].as_int(), my_id);
        done.set("tc", tc_json(my_tc_next()));
      }
      bus.publish("mapd", done);
      log_info("✅ Task %lld DONE\n",
               static_cast<long long>((*my_task)["task_id"].as_int()));
      // hold both payloads for retransmit until the manager acks
      unacked_done = done;
      unacked_done_metric = metric;
      unacked_done_id = (*my_task)["task_id"].as_int();
      unacked_tc = my_tc;
      done_last_sent_ms = mono_ms();
      my_task.reset();
      my_tc.reset();
    }
  };

  // retransmitted dones carry a FRESH context stamp (hop advances too:
  // each retransmit is a new wire crossing)
  auto refresh_unacked_tc = [&]() {
    if (!(tctx && unacked_tc && unacked_done)) return;
    unacked_tc->hop += 1;
    unacked_tc->send_ms = unix_ms();
    unacked_done->set("tc", tc_json(*unacked_tc));
  };

  log_info("🤖 centralized agent %s at (%d, %d)\n", my_id.c_str(),
           grid.x_of(my_pos), grid.y_of(my_pos));

  // 3x initial broadcast for startup robustness (ref :232-269)
  for (int i = 0; i < 3; ++i) broadcast_position();

  // survive a bus restart: resubscribe happens inside BusClient; the agent
  // re-announces its position so the manager re-tracks it immediately
  bus.set_reconnect([&]() {
    for (int i = 0; i < 3; ++i) broadcast_position();
  });

  int64_t last_broadcast = mono_ms();
  while (!g_stop && bus.connected()) {
    // poll every shard link (a pool spreads region beacons across fds)
    std::vector<pollfd> pfds;
    bus.append_pollfds(pfds);
    poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 200);

    bool alive = bus.pump([&](const BusClient::Msg& m) {
      const Json& d = m.data;
      const std::string& type = d["type"].as_str();
      if (type == "move_instruction") {
        if (d["peer_id"].as_str() != my_id) return;
        if (auto np = parse_point(d["next_pos"])) {
          if (auto t = tc_parse(d)) {
            if (my_tc && t->trace_id == my_tc->trace_id) {
              if (t->hop > my_tc->hop) my_tc->hop = t->hop;  // max-merge
              if (!exec_emitted && my_task) {
                // first obeyed instruction: the execution leg has begun
                // (claim -> exec is the planning wait)
                exec_emitted = true;
                event_emit("task.exec", &*t,
                           (*my_task)["task_id"].as_int(), my_id,
                           t->send_ms);
              }
            }
          }
          my_pos = *np;  // obey and re-broadcast immediately (ref :312-330)
          broadcast_position();
          last_broadcast = mono_ms();
          completion_check();
        }
      } else if (type == "done_ack") {
        if (d["peer_id"].as_str() == my_id
            && d["task_id"].as_int() == unacked_done_id) {
          if (auto t = tc_parse(d))
            event_emit("task.done_ack", &*t, unacked_done_id, my_id,
                       t->send_ms);
          unacked_done.reset();
          unacked_tc.reset();
          unacked_done_id = -1;
        }
      } else if (type == "flight_dump") {
        bus.publish("mapd", flight_dump_answer("agent_centralized", my_id));
      } else if (type == "task_withdrawn") {
        // a TSWAP goal exchange moved this task to another agent; drop
        // the stale copy so positional completion can't double-fire
        if (d["peer_id"].as_str() == my_id && my_task
            && (*my_task)["task_id"].as_int() == d["task_id"].as_int()) {
          log_info("🔁 task %lld withdrawn (exchanged away)\n",
                   d["task_id"].as_int());
          if (auto t = tc_parse(d))
            event_emit("task.withdrawn", &*t, d["task_id"].as_int(),
                       my_id, t->send_ms);
          my_task.reset();
          my_tc.reset();
        }
      } else if (type.empty() && d.has("pickup") && d.has("delivery")) {
        if (d["peer_id"].as_str() != my_id) return;
        const long long tid = d["task_id"].as_int();
        if (unacked_done && tid == unacked_done_id) {
          // the manager re-sent a task we already completed (its done was
          // lost): refuse the duplicate and heal by retransmitting now
          refresh_unacked_tc();
          bus.publish("mapd", unacked_done_metric);
          bus.publish("mapd", *unacked_done);
          done_last_sent_ms = mono_ms();
          return;
        }
        if (my_task && (*my_task)["task_id"].as_int() == tid)
          return;  // duplicate delivery of the task we are working on
        my_task = d;
        my_tc = tc_parse(d);
        exec_emitted = false;
        if (my_tc)
          event_emit("task.claim", &*my_tc, tid, my_id, my_tc->send_ms);
        task_metric("task_metric_received");
        task_metric("task_metric_started");
        log_info("📦 [TASK RECEIVED] Task ID: %lld\n",
                 static_cast<long long>(d["task_id"].as_int()));
        broadcast_position();
        last_broadcast = mono_ms();
        completion_check();  // degenerate tasks can complete in place
      }
        });
    if (!alive) break;

    int64_t now = mono_ms();
    if (now - last_broadcast >= heartbeat_ms) {  // ref :285-291
      broadcast_position();
      last_broadcast = now;
    }
    // done retransmit: no ack yet (lost in an outage, or the ack itself
    // was lost) — re-publish on the retry cadence until acked
    if (unacked_done && now - done_last_sent_ms >= done_retry_ms) {
      log_info("🔁 retransmitting done for task %lld (no ack yet)\n",
               unacked_done_id);
      refresh_unacked_tc();
      bus.publish("mapd", unacked_done_metric);
      bus.publish("mapd", *unacked_done);
      done_last_sent_ms = now;
    }
  }

  log_info("agent %s: shutting down\n", my_id.c_str());
  bus.close();
  return 0;
}
