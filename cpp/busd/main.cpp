// mapd_bus — the pub/sub message bus daemon.
//
// Host-runtime equivalent of the reference's libp2p gossipsub mesh + mDNS
// discovery (SURVEY C9): roles connect over loopback TCP, subscribe to
// topics, and published payloads fan out to every other subscriber (the
// reference's flood-publish semantics, src/bin/*/: everything is physically
// broadcast on topic "mapd").  peer_joined / peer_left events give managers
// the discovered/expired capability of mDNS.
//
// Usage: mapd_bus [port]           (default 7400)

#include <poll.h>
#include <signal.h>

#include <cstdio>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "../common/bus.hpp"  // unix_ms/mono_ms helpers
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/metrics.hpp"
#include "../common/net.hpp"

using namespace mapd;

namespace {

struct Client {
  LineConn conn;
  std::string peer_id;
  std::set<std::string> topics;
  explicit Client(int fd) : conn(fd) {}
};

volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  uint16_t port = (argc > 1 && argv[1][0] != '-')
                      ? static_cast<uint16_t>(atoi(argv[1]))
                      : 7400;
  // cross-host fleets: bind a routable interface ("0.0.0.0" for all) so
  // agents on other hosts can reach the hub (RUN_INSTRUCTIONS cross-host)
  const std::string bind_addr =
      knobs.get_str("--bind", "MAPD_BUS_BIND", "127.0.0.1");
  // Fault injection for protocol tests: silently drop the first
  // `drop_count` published frames whose data `type` equals `drop_type`
  // (e.g. sever the swap_response of a task exchange to prove the
  // manager's unclaimed-task sweep rescues the stranded task).  The bus
  // is a deliberately lossy medium — this makes a SPECIFIC loss
  // reproducible instead of waiting for an outage race.
  const std::string drop_type =
      knobs.get_str("--drop-type", "MAPD_BUS_DROP_TYPE", "");
  int64_t drop_left = knobs.get_int("--drop-count", "MAPD_BUS_DROP_COUNT",
                                    drop_type.empty() ? 0 : 1);
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);

  int listen_fd = tcp_listen(port, bind_addr);
  if (listen_fd < 0) {
    fprintf(stderr, "mapd_bus: cannot listen on %s:%u\n", bind_addr.c_str(),
            port);
    return 1;
  }
  set_nonblocking(listen_fd);
  log_info("mapd_bus listening on %s:%u\n", bind_addr.c_str(), port);

  std::map<int, std::unique_ptr<Client>> clients;

  auto broadcast = [&](const Json& frame, const std::string& topic,
                       int except_fd) {
    std::string line = frame.dump();
    int fanout = 0;
    for (auto& [fd, c] : clients) {
      if (fd == except_fd) continue;
      if (!topic.empty() && !c->topics.count(topic)) continue;
      if (c->peer_id.empty()) continue;  // not yet hello'd
      c->conn.send_line(line);
      ++fanout;
    }
    // hub-side fan-out accounting (wire bytes incl. framing newline);
    // rides the busd metrics beacon into the fleet rollup
    if (fanout) {
      std::string labels = "topic=\"" + topic + "\"";
      metrics_count("bus.fanout_msgs", fanout, labels);
      metrics_count("bus.fanout_bytes",
                    static_cast<double>(fanout * (line.size() + 1)), labels);
    }
  };

  // The hub beacons its own registry too (same schema as every BusClient):
  // fan-out volume per topic + connected-client gauge, as peer "busd".
  int64_t next_beacon_ms = 0;
  auto maybe_beacon = [&]() {
    int64_t now = mono_ms();
    if (now < next_beacon_ms) return;
    next_beacon_ms = now + 2000;
    metrics_gauge("bus.clients", static_cast<double>(clients.size()));
    Json msg;
    msg.set("op", "msg")
        .set("topic", "mapd.metrics")
        .set("from", "busd")
        .set("data", make_metrics_beacon("busd", "busd", 2.0));
    broadcast(msg, "mapd.metrics", -1);
  };

  while (!g_stop) {
    std::vector<pollfd> pfds;
    pfds.push_back({listen_fd, POLLIN, 0});
    for (auto& [fd, c] : clients) {
      short ev = POLLIN;
      if (c->conn.wants_write()) ev |= POLLOUT;
      pfds.push_back({fd, ev, 0});
    }
    int rc = poll(pfds.data(), pfds.size(), 1000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    maybe_beacon();

    // accept new connections
    if (pfds[0].revents & POLLIN) {
      while (true) {
        int cfd = accept(listen_fd, nullptr, nullptr);
        if (cfd < 0) break;
        set_nonblocking(cfd);
        clients.emplace(cfd, std::make_unique<Client>(cfd));
      }
    }

    std::vector<int> dead;
    for (size_t k = 1; k < pfds.size(); ++k) {
      int fd = pfds[k].fd;
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      Client& c = *it->second;
      bool ok = true;
      const char* why = "";
      if (pfds[k].revents & (POLLERR | POLLHUP)) {
        ok = false;
        why = "pollerr/hup";
        // poll() sets no errno for revents; fetch the socket's own error
        // so the drop diagnostic doesn't print a stale one
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        errno = soerr;
      }
      if (ok && (pfds[k].revents & POLLIN)) {
        ok = c.conn.on_readable();
        if (!ok) why = "read-eof/err";
      }
      while (ok) {
        auto line = c.conn.next_line();
        if (!line) break;
        auto parsed = Json::parse(*line);
        if (!parsed || !parsed->is_object()) continue;
        const Json& j = *parsed;
        const std::string& op = j["op"].as_str();
        if (op == "hello") {
          c.peer_id = j["peer_id"].as_str();
          Json welcome;
          welcome.set("op", "welcome").set("peer_id", c.peer_id);
          c.conn.send_line(welcome.dump());
        } else if (op == "sub") {
          const std::string& topic = j["topic"].as_str();
          c.topics.insert(topic);
          Json joined;  // discovery event, like an mDNS "discovered"
          joined.set("op", "peer_joined")
              .set("peer_id", c.peer_id)
              .set("topic", topic);
          broadcast(joined, topic, fd);
        } else if (op == "unsub") {
          c.topics.erase(j["topic"].as_str());
        } else if (op == "pub") {
          const std::string& topic = j["topic"].as_str();
          if (drop_left > 0 && !drop_type.empty()
              && j["data"]["type"].as_str() == drop_type) {
            --drop_left;
            log_warn("💉 fault injection: dropped %s frame from %s "
                     "(%lld more)\n", drop_type.c_str(), c.peer_id.c_str(),
                     static_cast<long long>(drop_left));
            continue;
          }
          Json msg;
          msg.set("op", "msg")
              .set("topic", topic)
              .set("from", c.peer_id)
              .set("data", j["data"]);
          broadcast(msg, topic, fd);
        } else if (op == "peers") {
          const std::string& topic = j["topic"].as_str();
          Json peers;
          for (auto& [ofd, oc] : clients)
            if (ofd != fd && oc->topics.count(topic) &&
                !oc->peer_id.empty())
              peers.push_back(Json(oc->peer_id));
          if (peers.is_null()) peers = Json(JsonArray{});
          Json reply;
          reply.set("op", "peers").set("topic", topic).set("peers", peers);
          c.conn.send_line(reply.dump());
        }
      }
      if (ok && (c.conn.wants_write())) {
        ok = c.conn.on_writable();
        if (!ok) why = "write-err";
      }
      if (!ok) {
        log_debug("dropping client fd=%d peer=%s (%s, errno=%d)\n", fd,
                  c.peer_id.c_str(), why, errno);
        dead.push_back(fd);
      }
    }

    for (int fd : dead) {
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      std::string peer = it->second->peer_id;
      it->second->conn.close_fd();
      clients.erase(it);
      if (!peer.empty()) {
        Json left;  // discovery event, like an mDNS "expired"
        left.set("op", "peer_left").set("peer_id", peer);
        broadcast(left, "", -1);
      }
    }
  }

  for (auto& [fd, c] : clients) c->conn.close_fd();
  close(listen_fd);
  log_info("mapd_bus: shut down\n");
  return 0;
}
