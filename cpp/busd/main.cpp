// mapd_bus — the pub/sub message bus daemon.
//
// Host-runtime equivalent of the reference's libp2p gossipsub mesh + mDNS
// discovery (SURVEY C9): roles connect over loopback TCP, subscribe to
// topics, and published payloads fan out to every other subscriber (the
// reference's flood-publish semantics, src/bin/*/: everything is physically
// broadcast on topic "mapd").  peer_joined / peer_left events give managers
// the discovered/expired capability of mDNS.
//
// Relay fast path (ISSUE 4): the hub is the fleet's measured ceiling, so
// the hot path avoids ALL JSON work:
//
// - Topic-prefix framing.  Clients that advertise `caps:["relay1"]` in
//   hello publish `P<topic> <payload>\n` and receive
//   `M<topic> <from> <payload>\n`; the hub peeks the topic with one
//   memchr and splices relays without parsing the payload (legacy JSON
//   peers keep the `{"op":"pub"...}` / `{"op":"msg"...}` wire — both
//   renderings are built at most once per publish and byte-shared across
//   the fanout).
// - Coalesced writes.  Per-client outbound queues hold refcounted frames;
//   each wakeup flushes everything queued with one writev batch instead
//   of a syscall (and a buffer copy) per message per client.
// - Bounded queues / slow-consumer policy.  A consumer that stops reading
//   first loses its queued position/metrics beacons oldest-first
//   (`bus.slow_consumer_drops` / `_dropped_bytes` counters — beacons are
//   superseded by the next one anyway), and is evicted outright past the
//   hard limit (`bus.slow_consumer_evictions`, emits peer_left) so one
//   stalled peer can never head-of-line-block the fleet.
// - Wildcard subscriptions.  A topic ending in `.*` subscribes by prefix
//   (managers use `mapd.pos.*` to see every region beacon without
//   enumerating regions).
//
// Usage: mapd_bus [port]           (default 7400)

#include <limits.h>
#include <poll.h>
#include <signal.h>
#include <sys/uio.h>

#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "../common/bus.hpp"  // unix_ms/mono_ms helpers
#include "../common/events.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/metrics.hpp"
#include "../common/net.hpp"
#include "../common/region.hpp"  // kPosTopicPrefix (droppable beacons)

using namespace mapd;

namespace {

struct OutFrame {
  std::shared_ptr<const std::string> data;  // framed line incl. '\n'
  bool droppable;
};

struct Client {
  LineConn conn;  // input framing only; output goes through the queue
  std::string peer_id;
  bool fast = false;  // advertised caps:["relay1"] in hello
  std::set<std::string> topics;
  std::set<std::string> prefixes;  // from "<prefix>.*" subscriptions
  std::deque<OutFrame> outq;
  size_t out_bytes = 0;   // total queued
  size_t front_off = 0;   // bytes of outq.front() already written
  explicit Client(int fd) : conn(fd) {}
};

volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

// Position beacons, metrics beacons, and per-decision path-metric
// samples are periodic/sampled streams a consumer can afford to lose —
// the only frames the slow-consumer policy may shed.
bool droppable_topic(const std::string& topic) {
  return topic.compare(0, strlen(kPosTopicPrefix), kPosTopicPrefix) == 0 ||
         topic == "mapd.metrics" || topic == "mapd.path";
}

std::string json_quote(const std::string& s) { return Json(s).dump(); }

}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  uint16_t port = (argc > 1 && argv[1][0] != '-')
                      ? static_cast<uint16_t>(atoi(argv[1]))
                      : 7400;
  // cross-host fleets: bind a routable interface ("0.0.0.0" for all) so
  // agents on other hosts can reach the hub (RUN_INSTRUCTIONS cross-host)
  const std::string bind_addr =
      knobs.get_str("--bind", "MAPD_BUS_BIND", "127.0.0.1");
  // Fault injection for protocol tests: silently drop the first
  // `drop_count` published frames whose data `type` equals `drop_type`
  // (e.g. sever the swap_response of a task exchange to prove the
  // manager's unclaimed-task sweep rescues the stranded task).  The bus
  // is a deliberately lossy medium — this makes a SPECIFIC loss
  // reproducible instead of waiting for an outage race.  (The filter
  // needs the payload's `type`, so configuring it re-enables a JSON parse
  // per published frame — test mode only.)
  const std::string drop_type =
      knobs.get_str("--drop-type", "MAPD_BUS_DROP_TYPE", "");
  int64_t drop_left = knobs.get_int("--drop-count", "MAPD_BUS_DROP_COUNT",
                                    drop_type.empty() ? 0 : 1);
  // Slow-consumer queue limits: past `soft` the client's queued BEACONS
  // drop oldest-first; past `hard` the client is evicted.
  const size_t queue_soft = static_cast<size_t>(
      knobs.get_int("--queue-soft-kb", "JG_BUS_QUEUE_SOFT_KB", 256)) * 1024;
  const size_t queue_hard = static_cast<size_t>(
      knobs.get_int("--queue-hard-kb", "JG_BUS_QUEUE_HARD_KB", 4096)) * 1024;
  // Per-client kernel send buffer (KB; 0 = kernel default).  The kernel
  // buffer sits IN FRONT of the user-space queue the limits above govern,
  // so backpressure tests shrink it to hit the policy deterministically.
  const int sndbuf_kb = static_cast<int>(
      knobs.get_int("--sndbuf-kb", "JG_BUS_SNDBUF_KB", 0));
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);
  // flight recorder (ISSUE 5): the hub's black box records membership
  // churn and slow-consumer actions — the fleet-side context for any
  // incident blackbox.py reconstructs
  events_init("busd");

  int listen_fd = tcp_listen(port, bind_addr);
  if (listen_fd < 0) {
    fprintf(stderr, "mapd_bus: cannot listen on %s:%u\n", bind_addr.c_str(),
            port);
    return 1;
  }
  set_nonblocking(listen_fd);
  log_info("mapd_bus listening on %s:%u\n", bind_addr.c_str(), port);

  std::map<int, std::unique_ptr<Client>> clients;
  std::map<std::string, std::set<int>> subs_exact;  // topic -> fds
  std::vector<std::pair<std::string, int>> subs_prefix;  // (prefix, fd)
  std::set<int> evict;  // hard-limit overflows, reaped with the dead list

  auto enqueue = [&](Client& c, int fd,
                     const std::shared_ptr<const std::string>& frame,
                     bool droppable) {
    if (evict.count(fd)) return;
    c.outq.push_back(OutFrame{frame, droppable});
    c.out_bytes += frame->size();
    if (c.out_bytes <= queue_soft) return;
    // drop-oldest policy: shed queued beacons (never the partially
    // written front frame) until back under the soft limit
    size_t k = c.front_off ? 1 : 0;
    size_t dropped = 0, dropped_bytes = 0;
    while (c.out_bytes > queue_soft && k < c.outq.size()) {
      if (!c.outq[k].droppable) {
        ++k;
        continue;
      }
      dropped_bytes += c.outq[k].data->size();
      c.out_bytes -= c.outq[k].data->size();
      c.outq.erase(c.outq.begin() + static_cast<long>(k));
      ++dropped;
    }
    if (dropped) {
      metrics_count("bus.slow_consumer_drops", static_cast<double>(dropped));
      metrics_count("bus.slow_consumer_dropped_bytes",
                    static_cast<double>(dropped_bytes));
    }
    if (c.out_bytes > queue_hard) {
      metrics_count("bus.slow_consumer_evictions");
      event_emit("bus.slow_consumer_evict", nullptr, -1, c.peer_id);
      log_warn("🐌 evicting slow consumer fd=%d peer=%s (%zu bytes "
               "queued > %zu hard limit)\n", fd, c.peer_id.c_str(),
               c.out_bytes, queue_hard);
      evict.insert(fd);
    }
  };

  // One writev batch of everything queued; returns false on write error.
  auto flush_client = [&](Client& c) -> bool {
    while (!c.outq.empty()) {
      iovec iov[64];
      int n = 0;
      size_t first = c.front_off;
      for (const auto& f : c.outq) {
        if (n == 64) break;
        iov[n].iov_base = const_cast<char*>(f.data->data()) +
                          (n == 0 ? first : 0);
        iov[n].iov_len = f.data->size() - (n == 0 ? first : 0);
        ++n;
      }
      ssize_t wrote = writev(c.conn.fd(), iov, n);
      if (wrote < 0)
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      size_t left = static_cast<size_t>(wrote);
      c.out_bytes -= left;
      while (left > 0) {
        size_t avail = c.outq.front().data->size() - c.front_off;
        if (left >= avail) {
          left -= avail;
          c.front_off = 0;
          c.outq.pop_front();
        } else {
          c.front_off += left;
          left = 0;
        }
      }
    }
    return true;
  };

  // Fan a payload out to `topic`'s subscribers.  `raw` is the payload
  // text (valid JSON from well-behaved peers) — NEVER parsed here; the
  // two wire renderings are built lazily, at most once each, and the
  // same buffer is shared by every recipient's queue.
  auto relay_payload = [&](const std::string& topic, const std::string& from,
                           const std::string& raw, int except_fd) {
    std::shared_ptr<const std::string> fast, legacy;
    const bool droppable = droppable_topic(topic);
    int fanout = 0;
    double fanout_bytes = 0;
    auto deliver = [&](int fd) {
      auto it = clients.find(fd);
      if (it == clients.end()) return;
      Client& c = *it->second;
      if (fd == except_fd || c.peer_id.empty()) return;
      const auto& frame = c.fast
          ? (fast ? fast
                  : (fast = std::make_shared<const std::string>(
                         "M" + topic + " " + from + " " + raw + "\n")))
          : (legacy ? legacy
                    : (legacy = std::make_shared<const std::string>(
                           "{\"op\":\"msg\",\"topic\":" +
                           json_quote(topic) + ",\"from\":" +
                           json_quote(from) + ",\"data\":" + raw + "}\n")));
      enqueue(c, fd, frame, droppable);
      ++fanout;
      fanout_bytes += static_cast<double>(frame->size());
    };
    auto ex = subs_exact.find(topic);
    if (ex != subs_exact.end())
      for (int fd : ex->second) deliver(fd);
    std::set<int> seen;  // exact + overlapping prefixes: one frame per fd
    for (const auto& [prefix, fd] : subs_prefix)
      if (topic.compare(0, prefix.size(), prefix) == 0 &&
          (ex == subs_exact.end() || !ex->second.count(fd)) &&
          seen.insert(fd).second)
        deliver(fd);
    // hub-side fan-out accounting (actual wire bytes incl. framing);
    // rides the busd metrics beacon into the fleet rollup
    if (fanout) {
      std::string labels = "topic=\"" + topic + "\"";
      metrics_count("bus.fanout_msgs", fanout, labels);
      metrics_count("bus.fanout_bytes", fanout_bytes, labels);
    }
  };

  // Control frames (welcome / peers / peer_joined / peer_left) stay JSON
  // on both wires; `topic` routes them ("" = every client).
  auto broadcast_control = [&](const Json& frame, const std::string& topic,
                               int except_fd) {
    auto line = std::make_shared<const std::string>(frame.dump() + "\n");
    for (auto& [fd, c] : clients) {
      if (fd == except_fd || c->peer_id.empty()) continue;
      if (!topic.empty() && !c->topics.count(topic)) continue;
      enqueue(*c, fd, line, false);
    }
  };

  // The hub beacons its own registry too (same schema as every BusClient):
  // fan-out volume per topic + connected-client gauge, as peer "busd".
  int64_t next_beacon_ms = 0;
  auto maybe_beacon = [&]() {
    int64_t now = mono_ms();
    if (now < next_beacon_ms) return;
    next_beacon_ms = now + 2000;
    metrics_gauge("bus.clients", static_cast<double>(clients.size()));
    relay_payload("mapd.metrics", "busd",
                  make_metrics_beacon("busd", "busd", 2.0).dump(), -1);
  };

  auto drop_subs = [&](int fd, Client& c) {
    for (const auto& t : c.topics) {
      auto it = subs_exact.find(t);
      if (it != subs_exact.end()) {
        it->second.erase(fd);
        if (it->second.empty()) subs_exact.erase(it);
      }
    }
    for (auto it = subs_prefix.begin(); it != subs_prefix.end();)
      it = (it->second == fd) ? subs_prefix.erase(it) : std::next(it);
  };

  while (!g_stop) {
    std::vector<pollfd> pfds;
    pfds.push_back({listen_fd, POLLIN, 0});
    for (auto& [fd, c] : clients) {
      short ev = POLLIN;
      if (c->out_bytes > 0) ev |= POLLOUT;
      pfds.push_back({fd, ev, 0});
    }
    int rc = poll(pfds.data(), pfds.size(), 1000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    maybe_beacon();

    // accept new connections
    if (pfds[0].revents & POLLIN) {
      while (true) {
        int cfd = accept(listen_fd, nullptr, nullptr);
        if (cfd < 0) break;
        set_nonblocking(cfd);
        if (sndbuf_kb > 0) {
          int v = sndbuf_kb * 1024;
          setsockopt(cfd, SOL_SOCKET, SO_SNDBUF, &v, sizeof(v));
        }
        clients.emplace(cfd, std::make_unique<Client>(cfd));
      }
    }

    std::vector<int> dead;
    for (size_t k = 1; k < pfds.size(); ++k) {
      int fd = pfds[k].fd;
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      Client& c = *it->second;
      bool ok = true;
      bool closing = false;  // disconnect AFTER draining buffered lines
      const char* why = "";
      if (pfds[k].revents & (POLLERR | POLLHUP)) {
        closing = true;
        why = "pollerr/hup";
        // poll() sets no errno for revents; fetch the socket's own error
        // so the drop diagnostic doesn't print a stale one
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        errno = soerr;
      }
      if (pfds[k].revents & POLLIN) {
        if (!c.conn.on_readable()) {
          closing = true;
          why = "read-eof/err";
        }
      }
      // A publish-then-close burst lands data and FIN in one read: the
      // complete lines already buffered are valid frames and MUST relay
      // before the disconnect is honored (a quitting chat peer's last
      // message used to vanish when the hub saw the EOF in the same
      // wakeup — the pub-then-close race, now deterministic in tests).
      while (ok) {
        auto line = c.conn.next_line();
        if (!line) break;
        if (!line->empty() && (*line)[0] == 'P') {
          // fast publish: `P<topic> <payload>` — topic peek, no parse
          size_t sp = line->find(' ');
          if (sp == std::string::npos || sp < 2) continue;
          const std::string topic = line->substr(1, sp - 1);
          const std::string raw = line->substr(sp + 1);
          if (drop_left > 0 && !drop_type.empty()) {
            auto parsed = Json::parse(raw);  // fault-injection test mode
            if (parsed && (*parsed)["type"].as_str() == drop_type) {
              --drop_left;
              log_warn("💉 fault injection: dropped %s frame from %s "
                       "(%lld more)\n", drop_type.c_str(),
                       c.peer_id.c_str(),
                       static_cast<long long>(drop_left));
              continue;
            }
          }
          metrics_count("bus.relay_fast_frames");
          relay_payload(topic, c.peer_id, raw, fd);
          continue;
        }
        auto parsed = Json::parse(*line);
        if (!parsed || !parsed->is_object()) continue;  // ignore garbage
        const Json& j = *parsed;
        const std::string& op = j["op"].as_str();
        if (op == "hello") {
          c.peer_id = j["peer_id"].as_str();
          event_emit("bus.peer_joined", nullptr, -1, c.peer_id);
          for (const auto& cap : j["caps"].as_array())
            if (cap.as_str() == "relay1") c.fast = true;
          Json caps;
          caps.push_back(Json("relay1"));
          Json welcome;
          welcome.set("op", "welcome")
              .set("peer_id", c.peer_id)
              .set("caps", caps);
          enqueue(c, fd, std::make_shared<const std::string>(
                             welcome.dump() + "\n"), false);
        } else if (op == "sub") {
          const std::string& topic = j["topic"].as_str();
          if (topic.size() > 2 &&
              topic.compare(topic.size() - 2, 2, ".*") == 0) {
            // wildcard: subscribe every topic under the prefix (managers'
            // "mapd.pos.*"); no peer_joined — prefix consumers are
            // infrastructure, not discoverable fleet members
            const std::string prefix = topic.substr(0, topic.size() - 1);
            if (c.prefixes.insert(prefix).second)
              subs_prefix.emplace_back(prefix, fd);
          } else if (c.topics.insert(topic).second) {
            subs_exact[topic].insert(fd);
            Json joined;  // discovery event, like an mDNS "discovered"
            joined.set("op", "peer_joined")
                .set("peer_id", c.peer_id)
                .set("topic", topic);
            broadcast_control(joined, topic, fd);
          }
        } else if (op == "unsub") {
          const std::string& topic = j["topic"].as_str();
          if (topic.size() > 2 &&
              topic.compare(topic.size() - 2, 2, ".*") == 0) {
            const std::string prefix = topic.substr(0, topic.size() - 1);
            c.prefixes.erase(prefix);
            for (auto pit = subs_prefix.begin(); pit != subs_prefix.end();)
              pit = (pit->second == fd && pit->first == prefix)
                        ? subs_prefix.erase(pit)
                        : std::next(pit);
          } else if (c.topics.erase(topic)) {
            auto ex = subs_exact.find(topic);
            if (ex != subs_exact.end()) {
              ex->second.erase(fd);
              if (ex->second.empty()) subs_exact.erase(ex);
            }
          }
        } else if (op == "pub") {
          const std::string& topic = j["topic"].as_str();
          if (drop_left > 0 && !drop_type.empty()
              && j["data"]["type"].as_str() == drop_type) {
            --drop_left;
            log_warn("💉 fault injection: dropped %s frame from %s "
                     "(%lld more)\n", drop_type.c_str(), c.peer_id.c_str(),
                     static_cast<long long>(drop_left));
            continue;
          }
          metrics_count("bus.relay_json_frames");
          relay_payload(topic, c.peer_id, j["data"].dump(), fd);
        } else if (op == "peers") {
          const std::string& topic = j["topic"].as_str();
          Json peers;
          for (auto& [ofd, oc] : clients)
            if (ofd != fd && oc->topics.count(topic) &&
                !oc->peer_id.empty())
              peers.push_back(Json(oc->peer_id));
          if (peers.is_null()) peers = Json(JsonArray{});
          Json reply;
          reply.set("op", "peers").set("topic", topic).set("peers", peers);
          enqueue(c, fd, std::make_shared<const std::string>(
                             reply.dump() + "\n"), false);
        }
      }
      if (closing) ok = false;
      if (ok && c.out_bytes > 0) {
        ok = flush_client(c);
        if (!ok) why = "write-err";
      }
      if (!ok) {
        log_debug("dropping client fd=%d peer=%s (%s, errno=%d)\n", fd,
                  c.peer_id.c_str(), why, errno);
        dead.push_back(fd);
      }
    }

    for (int fd : evict) dead.push_back(fd);
    evict.clear();
    for (int fd : dead) {
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      std::string peer = it->second->peer_id;
      if (!peer.empty()) event_emit("bus.peer_left", nullptr, -1, peer);
      drop_subs(fd, *it->second);
      it->second->conn.close_fd();
      clients.erase(it);
      if (!peer.empty()) {
        Json left;  // discovery event, like an mDNS "expired"
        left.set("op", "peer_left").set("peer_id", peer);
        broadcast_control(left, "", -1);
      }
    }
  }

  for (auto& [fd, c] : clients) c->conn.close_fd();
  close(listen_fd);
  log_info("mapd_bus: shut down\n");
  return 0;
}
