// mapd_bus — the pub/sub message bus daemon.
//
// Host-runtime equivalent of the reference's libp2p gossipsub mesh + mDNS
// discovery (SURVEY C9): roles connect over loopback TCP, subscribe to
// topics, and published payloads fan out to every other subscriber (the
// reference's flood-publish semantics, src/bin/*/: everything is physically
// broadcast on topic "mapd").  peer_joined / peer_left events give managers
// the discovered/expired capability of mDNS.
//
// Relay fast path (ISSUE 4): the hub is the fleet's measured ceiling, so
// the hot path avoids ALL JSON work:
//
// - Topic-prefix framing.  Clients that advertise `caps:["relay1"]` in
//   hello publish `P<topic> <payload>\n` and receive
//   `M<topic> <from> <payload>\n`; the hub peeks the topic with one
//   memchr and splices relays without parsing the payload (legacy JSON
//   peers keep the `{"op":"pub"...}` / `{"op":"msg"...}` wire — both
//   renderings are built at most once per publish and byte-shared across
//   the fanout).
// - Coalesced writes.  Per-client outbound queues hold refcounted frames;
//   each wakeup flushes everything queued with one writev batch instead
//   of a syscall (and a buffer copy) per message per client.
// - Bounded queues / slow-consumer policy.  A consumer that stops reading
//   first loses its queued position/metrics beacons oldest-first
//   (`bus.slow_consumer_drops` / `_dropped_bytes` counters — beacons are
//   superseded by the next one anyway), and is evicted outright past the
//   hard limit (`bus.slow_consumer_evictions`, emits peer_left) so one
//   stalled peer can never head-of-line-block the fleet.
// - Wildcard subscriptions.  A topic ending in `.*` subscribes by prefix
//   (managers use `mapd.pos.*` to see every region beacon without
//   enumerating regions).
//
// Federated shard pool (ISSUE 6): one busd remains the fleet's throughput
// ceiling and single point of failure, so the bus itself shards.  A pool
// member runs with `--shard i --shards n --peers <port,port,...>` (the
// full pool port list, index = shard id; runtime/buspool.py spawns it):
//
// - Topic ownership is the deterministic shardmap
//   (cpp/common/shardmap.hpp ≡ runtime/shardmap.py): region position
//   topics spread across all shards, the control plane lives on the HOME
//   shard (0).  Shard-aware clients (caps `shard1`) route subs and
//   publishes to the owning shard themselves.
// - busd↔busd peering.  The higher-index shard initiates one TCP link to
//   every lower-index shard (hello caps `["relay1","peer1"]`); links ride
//   the relay fast path (M-frames, refcounted renderings, writev).
//   Peering is interest-scoped: a shard subscribes a topic over its links
//   only while it has >= 1 LOCAL subscriber for it, so cross-shard
//   traffic is bounded by actual interest, not the pool size.
// - Loop prevention: a frame that ARRIVED over a peer link is delivered
//   to local clients only — never re-forwarded to another peer link.
//   Every pair of shards has a direct link and subscriptions propagate on
//   all links, so one hop always suffices; a frame can never loop or
//   duplicate.  (Shard-aware clients whose wildcard subscription spans
//   every shard are also skipped for peer-forwarded frames — they already
//   saw the frame at its origin shard.)
// - `JG_BUS_SHARDS=1` (the default) is the kill switch: no peers, no new
//   caps, byte-identical single-hub wire.
//
// Zero-copy same-host lanes + beacon aggregation (ISSUE 18):
//
// - shm1: a client whose hello carries caps:["shm1"] and a
//   `"shm":{"path":...,"v":1}` block offers a mapped SPSC ring pair
//   (common/shmlane.hpp ≡ runtime/shmlane.py) it created under the run
//   dir.  The hub attaches, echoes "shm1" in welcome, and from then on
//   the DROPPABLE topic class (beacons/metrics/path — the measured
//   dominant traffic) moves through the rings as the exact relay frames:
//   client publishes ride the c2s ring, deliveries the s2c ring.  TCP
//   stays the control channel, carries oversized/overflow frames
//   (`bus.shm_fallbacks` — never a stall), and remains the only
//   transport cross-host.  A dead client's lane is reaped with its TCP
//   session.  `JG_BUS_SHM` unset/0 keeps the wire byte-identical.
// - agg1 (`--agg-ms` / JG_BUS_AGG_MS, default 0 = off): pos1 beacons of
//   one region topic arriving within the window coalesce into a single
//   agg1 frame (plan_codec.hpp, packed1 family) delivered once per
//   agg1-capable subscriber — O(agents)→O(regions) fanout on the
//   dominant topic class.  Legacy subscribers keep receiving singles;
//   peer links always carry singles (the remote shard re-aggregates for
//   its own subscribers), so aggregation composes across the pool.
//
// Usage: mapd_bus [port]           (default 7400)

#include <limits.h>
#include <poll.h>
#include <signal.h>
#include <sys/uio.h>

#include <chrono>
#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "../common/bus.hpp"  // unix_ms/mono_ms helpers
#include "../common/events.hpp"
#include "../common/json.hpp"
#include "../common/knobs.hpp"
#include "../common/log.hpp"
#include "../common/metrics.hpp"
#include "../common/net.hpp"
#include "../common/plan_codec.hpp"  // agg1 beacon aggregate (ISSUE 18)
#include "../common/region.hpp"  // kPosTopicPrefix (droppable beacons)
#include "../common/shardmap.hpp"
#include "../common/shmlane.hpp"  // same-host ring lanes (ISSUE 18)

using namespace mapd;

namespace {

struct OutFrame {
  std::shared_ptr<const std::string> data;  // framed line incl. '\n'
  bool droppable;
};

// relay fanout scoping for beacon aggregation: singles go to everyone
// minus the agg1 subscribers; the coalesced agg1 frame goes to ONLY them
enum class Fanout { kAll, kSkipAgg, kOnlyAgg };

struct Client {
  LineConn conn;  // input framing only; output goes through the queue
  std::string peer_id;
  bool fast = false;   // advertised caps:["relay1"] in hello
  bool shard1 = false;  // shard-aware client: routes its own subs/pubs
  bool is_peer = false;  // busd↔busd peering link (caps:["peer1"])
  bool agg1 = false;   // advertised caps:["agg1"] AND window active:
                       // receives coalesced region beacons, not singles
  shm::Lane lane;      // attached shm ring pair (valid() if negotiated)
  // shm spin-then-park state: last instant the lane had frames (the
  // idle-spin budget counts from here) and whether the reader is
  // currently parked (bus.shm_parks counts busy->parked transitions
  // only, so a long park is one event, not one per poll iteration)
  int64_t lane_busy_us = 0;
  bool lane_parked = false;
  int peer_shard = -1;   // shard index of the remote busd (peer links)
  std::set<std::string> topics;
  std::set<std::string> prefixes;  // from "<prefix>.*" subscriptions
  // prefix subs by a shard1 client that span EVERY shard (e.g. the
  // manager's "mapd.pos.*"): peer-forwarded frames skip these — the
  // client already receives them at the origin shard
  std::set<std::string> span_prefixes;
  std::deque<OutFrame> outq;
  size_t out_bytes = 0;   // total queued
  size_t front_off = 0;   // bytes of outq.front() already written
  explicit Client(int fd) : conn(fd) {}
};

// One outbound peer link slot (this shard initiates to every lower
// shard index); reconnects with backoff like a BusClient.  Dials are
// NONBLOCKING (EINPROGRESS + POLLOUT): the relay loop must never stall
// behind a SYN-dropping dead peer host.
struct PeerSlot {
  int shard = -1;
  uint16_t port = 0;
  int fd = -1;          // live Client in the clients map, or -1
  int pending_fd = -1;  // nonblocking connect in flight, or -1
  int64_t pending_since_ms = 0;
  int64_t backoff_ms = 0;
  int64_t next_attempt_ms = 0;
};

volatile sig_atomic_t g_stop = 0;
void handle_stop(int) { g_stop = 1; }

// Position beacons, metrics beacons, and per-decision path-metric
// samples are periodic/sampled streams a consumer can afford to lose —
// the only frames the slow-consumer policy may shed.  Classified by the
// LOGICAL topic (shardmap::strip_ns): a tenant's beacons shed like the
// un-namespaced fleet's (ISSUE 8) — busd stays otherwise topic-opaque.
bool droppable_topic(const std::string& wire_topic) {
  const std::string topic = shardmap::strip_ns(wire_topic);
  return topic.compare(0, strlen(kPosTopicPrefix), kPosTopicPrefix) == 0 ||
         topic == "mapd.metrics" || topic == "mapd.path";
}

std::string json_quote(const std::string& s) { return Json(s).dump(); }

}  // namespace

int main(int argc, char** argv) {
  Knobs knobs(argc, argv);
  set_log_level(knobs);
  uint16_t port = (argc > 1 && argv[1][0] != '-')
                      ? static_cast<uint16_t>(atoi(argv[1]))
                      : 7400;
  // cross-host fleets: bind a routable interface ("0.0.0.0" for all) so
  // agents on other hosts can reach the hub (RUN_INSTRUCTIONS cross-host)
  const std::string bind_addr =
      knobs.get_str("--bind", "MAPD_BUS_BIND", "127.0.0.1");
  // Federated pool membership (ISSUE 6): my shard index, the pool size,
  // and the full pool port list for the peering links.
  const int my_shard = static_cast<int>(
      knobs.get_int("--shard", "JG_BUS_SHARD_INDEX", 0));
  const int num_shards = static_cast<int>(
      knobs.get_int("--shards", "JG_BUS_SHARDS", 1));
  const std::string peers_spec =
      knobs.get_str("--peers", shardmap::kShardPortsEnv, "");
  const std::string peer_host =
      knobs.get_str("--peer-host", "JG_BUS_PEER_HOST", "127.0.0.1");
  const std::string my_peer_id =
      num_shards > 1 ? "busd-s" + std::to_string(my_shard) : "busd";
  std::vector<uint16_t> pool_ports;
  if (num_shards > 1) {
    if (my_shard < 0 || my_shard >= num_shards) {
      fprintf(stderr, "mapd_bus: --shard %d out of range for --shards %d\n",
              my_shard, num_shards);
      return 1;
    }
    pool_ports = shardmap::parse_shard_ports(peers_spec);
    if (static_cast<int>(pool_ports.size()) != num_shards) {
      fprintf(stderr,
              "mapd_bus: --shards %d but --peers lists %zu port(s)\n",
              num_shards, pool_ports.size());
      return 1;
    }
  }
  // Fault injection for protocol tests: silently drop the first
  // `drop_count` published frames whose data `type` equals `drop_type`
  // (e.g. sever the swap_response of a task exchange to prove the
  // manager's unclaimed-task sweep rescues the stranded task).  The bus
  // is a deliberately lossy medium — this makes a SPECIFIC loss
  // reproducible instead of waiting for an outage race.  (The filter
  // needs the payload's `type`, so configuring it re-enables a JSON parse
  // per published frame — test mode only.)
  const std::string drop_type =
      knobs.get_str("--drop-type", "MAPD_BUS_DROP_TYPE", "");
  int64_t drop_left = knobs.get_int("--drop-count", "MAPD_BUS_DROP_COUNT",
                                    drop_type.empty() ? 0 : 1);
  // Slow-consumer queue limits: past `soft` the client's queued BEACONS
  // drop oldest-first; past `hard` the client is evicted.
  const size_t queue_soft = static_cast<size_t>(
      knobs.get_int("--queue-soft-kb", "JG_BUS_QUEUE_SOFT_KB", 256)) * 1024;
  const size_t queue_hard = static_cast<size_t>(
      knobs.get_int("--queue-hard-kb", "JG_BUS_QUEUE_HARD_KB", 4096)) * 1024;
  // Per-client kernel send buffer (KB; 0 = kernel default).  The kernel
  // buffer sits IN FRONT of the user-space queue the limits above govern,
  // so backpressure tests shrink it to hit the policy deterministically.
  const int sndbuf_kb = static_cast<int>(
      knobs.get_int("--sndbuf-kb", "JG_BUS_SNDBUF_KB", 0));
  // shm lanes (ISSUE 18): accept client lane offers unless explicitly
  // disabled (clients only offer when JG_BUS_SHM is set truthy, so the
  // unset default keeps the wire byte-identical end to end)
  const bool shm_ok = knobs.get_int("--shm", "JG_BUS_SHM", 1) != 0;
  // shm idle-spin budget (µs): after a lane's last frame, keep the poll
  // loop hot (zero-timeout) this long before parking on the doorbell
  // FIFO.  0 (default) parks immediately — the pre-knob behavior.  A
  // bursty publisher that resumes within the budget skips the
  // park/doorbell syscall round trip at the cost of busd CPU.
  const int64_t shm_spin_us =
      knobs.get_int("--shm-spin-us", "JG_BUS_SHM_SPIN_US", 0);
  // beacon aggregation window (ms); 0 = off (byte-identical wire)
  const int64_t agg_ms = knobs.get_int("--agg-ms", "JG_BUS_AGG_MS", 0);
  signal(SIGINT, handle_stop);
  signal(SIGTERM, handle_stop);
  signal(SIGPIPE, SIG_IGN);
  // flight recorder (ISSUE 5): the hub's black box records membership
  // churn and slow-consumer actions — the fleet-side context for any
  // incident blackbox.py reconstructs
  events_init(my_peer_id.c_str());

  int listen_fd = tcp_listen(port, bind_addr);
  if (listen_fd < 0) {
    fprintf(stderr, "mapd_bus: cannot listen on %s:%u\n", bind_addr.c_str(),
            port);
    return 1;
  }
  set_nonblocking(listen_fd);
  log_info("mapd_bus listening on %s:%u%s\n", bind_addr.c_str(), port,
           num_shards > 1
               ? (" (shard " + std::to_string(my_shard) + "/" +
                  std::to_string(num_shards) + ")").c_str()
               : "");

  std::map<int, std::unique_ptr<Client>> clients;
  std::map<std::string, std::set<int>> subs_exact;  // topic -> fds
  std::vector<std::pair<std::string, int>> subs_prefix;  // (prefix, fd)
  std::set<int> evict;  // hard-limit overflows, reaped with the dead list

  // Interest-scoped peering: refcounts of LOCAL (non-peer) subscribers
  // per exact topic / prefix.  A topic is subscribed over the peer links
  // exactly while some local client wants it, so cross-shard traffic is
  // bounded by interest, not pool size.  (Prefixes propagate in their
  // wildcard form "<prefix>*".)
  std::map<std::string, int> local_exact_refs;
  std::map<std::string, int> local_prefix_refs;

  // Outbound peer links: this shard initiates to every LOWER shard index
  // (one TCP per pair pool-wide); inbound links arrive from higher ones.
  std::vector<PeerSlot> peer_slots;
  for (int j = 0; num_shards > 1 && j < my_shard; ++j) {
    PeerSlot slot;  // field defaults (fd/pending_fd = -1) are the truth
    slot.shard = j;
    slot.port = pool_ports[static_cast<size_t>(j)];
    peer_slots.push_back(slot);
  }

  auto enqueue = [&](Client& c, int fd,
                     const std::shared_ptr<const std::string>& frame,
                     bool droppable) {
    if (evict.count(fd)) return;
    c.outq.push_back(OutFrame{frame, droppable});
    c.out_bytes += frame->size();
    if (c.out_bytes <= queue_soft) return;
    // drop-oldest policy: shed queued beacons (never the partially
    // written front frame) until back under the soft limit
    size_t k = c.front_off ? 1 : 0;
    size_t dropped = 0, dropped_bytes = 0;
    while (c.out_bytes > queue_soft && k < c.outq.size()) {
      if (!c.outq[k].droppable) {
        ++k;
        continue;
      }
      dropped_bytes += c.outq[k].data->size();
      c.out_bytes -= c.outq[k].data->size();
      c.outq.erase(c.outq.begin() + static_cast<long>(k));
      ++dropped;
    }
    if (dropped) {
      metrics_count("bus.slow_consumer_drops", static_cast<double>(dropped));
      metrics_count("bus.slow_consumer_dropped_bytes",
                    static_cast<double>(dropped_bytes));
    }
    if (c.out_bytes > queue_hard) {
      metrics_count("bus.slow_consumer_evictions");
      event_emit("bus.slow_consumer_evict", nullptr, -1, c.peer_id);
      log_warn("🐌 evicting slow consumer fd=%d peer=%s (%zu bytes "
               "queued > %zu hard limit)\n", fd, c.peer_id.c_str(),
               c.out_bytes, queue_hard);
      evict.insert(fd);
    }
  };

  // One writev batch of everything queued; returns false on write error.
  auto flush_client = [&](Client& c) -> bool {
    while (!c.outq.empty()) {
      iovec iov[64];
      int n = 0;
      size_t first = c.front_off;
      for (const auto& f : c.outq) {
        if (n == 64) break;
        iov[n].iov_base = const_cast<char*>(f.data->data()) +
                          (n == 0 ? first : 0);
        iov[n].iov_len = f.data->size() - (n == 0 ? first : 0);
        ++n;
      }
      ssize_t wrote = writev(c.conn.fd(), iov, n);
      if (wrote < 0)
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      size_t left = static_cast<size_t>(wrote);
      c.out_bytes -= left;
      while (left > 0) {
        size_t avail = c.outq.front().data->size() - c.front_off;
        if (left >= avail) {
          left -= avail;
          c.front_off = 0;
          c.outq.pop_front();
        } else {
          c.front_off += left;
          left = 0;
        }
      }
    }
    return true;
  };

  // Send a control line (sub/unsub/hello) on a peer link.
  auto peer_send = [&](Client& c, int fd, const std::string& line) {
    enqueue(c, fd, std::make_shared<const std::string>(line + "\n"), false);
  };

  // Propagate a local-interest change to every live peer link.
  auto peers_sub = [&](const std::string& wire_topic, bool sub) {
    if (num_shards <= 1) return;
    Json j;
    j.set("op", sub ? "sub" : "unsub").set("topic", wire_topic);
    const std::string line = j.dump();
    for (auto& [fd, c] : clients)
      if (c->is_peer) peer_send(*c, fd, line);
  };

  // Fan a payload out to `topic`'s subscribers.  `raw` is the payload
  // text (valid JSON from well-behaved peers) — NEVER parsed here; the
  // two wire renderings are built lazily, at most once each, and the
  // same buffer is shared by every recipient's queue.
  // `from_peer`: the frame arrived over a busd↔busd link — deliver to
  // LOCAL clients only (never re-forward to another peer link: one hop
  // always suffices in the full mesh, and this is what makes loops
  // impossible), and skip shard-aware clients whose matching wildcard
  // spans every shard (they already saw it at the origin shard).
  auto relay_payload = [&](const std::string& topic, const std::string& from,
                           const std::string& raw, int except_fd,
                           bool from_peer, Fanout mode = Fanout::kAll) {
    std::shared_ptr<const std::string> fast, legacy;
    const bool droppable = droppable_topic(topic);
    int fanout = 0;
    double fanout_bytes = 0;
    auto deliver = [&](int fd, bool via_span_prefix) {
      auto it = clients.find(fd);
      if (it == clients.end()) return;
      Client& c = *it->second;
      if (fd == except_fd || c.peer_id.empty()) return;
      if (from_peer && (c.is_peer || (c.shard1 && via_span_prefix))) return;
      if (mode == Fanout::kSkipAgg && c.agg1) return;
      if (mode == Fanout::kOnlyAgg && !c.agg1) return;
      const auto& frame = c.fast
          ? (fast ? fast
                  : (fast = std::make_shared<const std::string>(
                         "M" + topic + " " + from + " " + raw + "\n")))
          : (legacy ? legacy
                    : (legacy = std::make_shared<const std::string>(
                           "{\"op\":\"msg\",\"topic\":" +
                           json_quote(topic) + ",\"from\":" +
                           json_quote(from) + ",\"data\":" + raw + "}\n")));
      ++fanout;
      fanout_bytes += static_cast<double>(frame->size());
      // shm fast path: droppable frames to a lane-attached relay1 client
      // ride the s2c ring (frame minus the trailing '\n').  A full ring
      // or torn-down lane falls back to the TCP queue — never a stall.
      if (droppable && c.fast && c.lane.valid()) {
        if (c.lane.send(frame->data(), frame->size() - 1)) {
          metrics_count("bus.shm_tx_frames");
          return;
        }
        metrics_count("bus.shm_fallbacks");
      }
      enqueue(c, fd, frame, droppable);
      if (c.is_peer) {
        metrics_count("bus.peer_tx_msgs");
        metrics_count("bus.peer_tx_bytes",
                      static_cast<double>(frame->size()));
      }
    };
    auto ex = subs_exact.find(topic);
    if (ex != subs_exact.end())
      for (int fd : ex->second) deliver(fd, false);
    std::set<int> seen;  // exact + overlapping prefixes: one frame per fd
    for (const auto& [prefix, fd] : subs_prefix)
      if (topic.compare(0, prefix.size(), prefix) == 0 &&
          (ex == subs_exact.end() || !ex->second.count(fd)) &&
          seen.insert(fd).second) {
        auto it = clients.find(fd);
        const bool span = it != clients.end() &&
                          it->second->span_prefixes.count(prefix) > 0;
        deliver(fd, span);
      }
    // hub-side fan-out accounting (actual wire bytes incl. framing);
    // rides the busd metrics beacon into the fleet rollup
    if (fanout) {
      std::string labels = "topic=\"" + topic + "\"";
      metrics_count("bus.fanout_msgs", fanout, labels);
      metrics_count("bus.fanout_bytes", fanout_bytes, labels);
    }
  };

  // Beacon aggregation (ISSUE 18): pos1 beacons of one region topic
  // buffered within the agg window, flushed as ONE agg1 frame to the
  // agg1-capable subscribers.  Singles still go out immediately to
  // everyone else (legacy interop) — the agg1 crowd is simply excluded
  // from the per-beacon fanout.  Peer links carry singles; the remote
  // shard re-aggregates for its own subscribers.
  struct AggPending {
    std::vector<codec::Agg1Entry> entries;
    int64_t first_ms = 0;
  };
  std::map<std::string, AggPending> agg_pending;  // wire topic -> window
  int agg1_subs = 0;  // live agg1-capable clients (skip work when none)

  // Publish ingress: every published payload (fast P, legacy pub, peer M)
  // funnels through here so aggregation sees one stream.
  auto ingress_pub = [&](const std::string& topic, const std::string& from,
                         const std::string& raw, int except_fd,
                         bool from_peer) {
    if (agg_ms > 0 && agg1_subs > 0) {
      const std::string logical = shardmap::strip_ns(topic);
      if (logical.compare(0, strlen(kPosTopicPrefix), kPosTopicPrefix) ==
          0) {
        // coalescing needs the pos1 blob, so this (opt-in) path pays one
        // JSON parse per beacon — bought back many times over by the
        // O(agents)→O(regions) fanout cut
        auto parsed = Json::parse(raw);
        if (parsed && parsed->is_object() &&
            (*parsed)["type"].as_str() == "pos1") {
          auto blob = codec::b64_decode((*parsed)["data"].as_str());
          if (blob) {
            auto& p = agg_pending[topic];
            if (p.entries.empty()) p.first_ms = mono_ms();
            p.entries.push_back({from, std::move(*blob)});
            metrics_count("bus.agg_coalesced");
            relay_payload(topic, from, raw, except_fd, from_peer,
                          Fanout::kSkipAgg);
            return;
          }
        }
      }
    }
    relay_payload(topic, from, raw, except_fd, from_peer);
  };

  auto flush_aggs = [&]() {
    if (agg_pending.empty()) return;
    const int64_t now = mono_ms();
    // agg frames must ride the rings too: chunk each window so the framed
    // fast-path line fits the smallest attached agg1 lane slot, else every
    // flush TCP-falls-back and bus.shm_fallbacks becomes steady-state
    // noise instead of an anomaly signal.  No lane-attached agg1 subs =>
    // one frame per window as before.
    size_t min_slot = 0;
    for (auto& [cfd, cc] : clients) {
      (void)cfd;
      if (cc->agg1 && cc->lane.valid() &&
          (min_slot == 0 || cc->lane.slot_size < min_slot))
        min_slot = cc->lane.slot_size;
    }
    for (auto it = agg_pending.begin(); it != agg_pending.end();) {
      AggPending& p = it->second;
      if (now - p.first_ms < agg_ms && p.entries.size() < 4096) {
        ++it;
        continue;
      }
      size_t raw_budget = SIZE_MAX;  // unlimited when no lanes listen
      if (min_slot) {
        // fast frame: "M<topic> <from> {"type":"agg1","data":"<b64>"}"
        const size_t overhead =
            1 + it->first.size() + 1 + my_peer_id.size() + 1 +
            sizeof("{\"type\":\"agg1\",\"data\":\"\"}") - 1;
        raw_budget =
            min_slot > overhead ? (min_slot - overhead) / 4 * 3 : 0;
      }
      size_t i = 0;
      while (i < p.entries.size()) {
        std::vector<codec::Agg1Entry> chunk;
        size_t sz = 8;  // agg1 fixed header
        while (i < p.entries.size()) {
          const size_t esz =
              4 + p.entries[i].name.size() + p.entries[i].blob.size();
          if (!chunk.empty() && sz + esz > raw_budget) break;
          sz += esz;
          chunk.push_back(std::move(p.entries[i]));
          ++i;
        }
        const std::string payload = "{\"type\":\"agg1\",\"data\":\"" +
                                    codec::encode_agg1_b64(chunk) +
                                    "\"}";
        metrics_count("bus.agg_flushes");
        metrics_count("bus.agg_entries",
                      static_cast<double>(chunk.size()));
        relay_payload(it->first, my_peer_id, payload, -1, false,
                      Fanout::kOnlyAgg);
      }
      it = agg_pending.erase(it);
    }
  };

  // Control frames (welcome / peers / peer_joined / peer_left) stay JSON
  // on both wires; `topic` routes them ("" = every client).  Peer links
  // never receive them — discovery is per-shard (the control plane meets
  // on the home shard, where every fleet member subscribes).
  auto broadcast_control = [&](const Json& frame, const std::string& topic,
                               int except_fd) {
    auto line = std::make_shared<const std::string>(frame.dump() + "\n");
    for (auto& [fd, c] : clients) {
      if (fd == except_fd || c->peer_id.empty() || c->is_peer) continue;
      if (!topic.empty() && !c->topics.count(topic)) continue;
      enqueue(*c, fd, line, false);
    }
  };

  // One fast publish (`P<topic> <payload>`), whether it arrived on the
  // TCP link or through the client's c2s shm ring — topic peek, no parse.
  auto handle_fast_pub = [&](Client& c, int fd, const std::string& line,
                             bool via_shm) {
    size_t sp = line.find(' ');
    if (sp == std::string::npos || sp < 2) return;
    const std::string topic = line.substr(1, sp - 1);
    const std::string raw = line.substr(sp + 1);
    if (drop_left > 0 && !drop_type.empty()) {
      auto parsed = Json::parse(raw);  // fault-injection test mode
      if (parsed && (*parsed)["type"].as_str() == drop_type) {
        --drop_left;
        log_warn("💉 fault injection: dropped %s frame from %s "
                 "(%lld more)\n", drop_type.c_str(), c.peer_id.c_str(),
                 static_cast<long long>(drop_left));
        return;
      }
    }
    metrics_count(via_shm ? "bus.shm_rx_frames" : "bus.relay_fast_frames");
    ingress_pub(topic, c.peer_id, raw, fd, false);
  };

  // The hub beacons its own registry too (same schema as every BusClient):
  // fan-out volume per topic + connected-client gauge, as peer "busd"
  // (single hub) / "busd-s<i>" (pool member, `shard` field on the payload
  // so the fleet aggregator renders per-shard rows).
  int64_t next_beacon_ms = 0;
  auto maybe_beacon = [&]() {
    int64_t now = mono_ms();
    if (now < next_beacon_ms) return;
    next_beacon_ms = now + 2000;
    size_t queued = 0;
    size_t live_peers = 0;
    size_t shm_lanes = 0;
    for (auto& [fd, c] : clients) {
      queued += c->out_bytes;
      if (c->is_peer) ++live_peers;
      if (c->lane.valid()) ++shm_lanes;
    }
    metrics_gauge("bus.clients",
                  static_cast<double>(clients.size() - live_peers));
    metrics_gauge("bus.queued_bytes", static_cast<double>(queued));
    if (shm_lanes) metrics_gauge("bus.shm_lanes",
                                 static_cast<double>(shm_lanes));
    if (num_shards > 1)
      metrics_gauge("bus.peer_links", static_cast<double>(live_peers));
    Json b = make_metrics_beacon(my_peer_id, "busd", 2.0);
    if (num_shards > 1)
      b.set("shard", static_cast<int64_t>(my_shard))
          .set("shards", static_cast<int64_t>(num_shards));
    relay_payload("mapd.metrics", my_peer_id, b.dump(), -1, false);
  };

  auto drop_subs = [&](int fd, Client& c) {
    for (const auto& t : c.topics) {
      auto it = subs_exact.find(t);
      if (it != subs_exact.end()) {
        it->second.erase(fd);
        if (it->second.empty()) subs_exact.erase(it);
      }
      if (!c.is_peer && --local_exact_refs[t] <= 0) {
        local_exact_refs.erase(t);
        peers_sub(t, false);
      }
    }
    for (auto it = subs_prefix.begin(); it != subs_prefix.end();)
      it = (it->second == fd) ? subs_prefix.erase(it) : std::next(it);
    if (!c.is_peer)
      for (const auto& p : c.prefixes) {
        if (c.span_prefixes.count(p)) continue;  // never counted
        if (--local_prefix_refs[p] <= 0) {
          local_prefix_refs.erase(p);
          peers_sub(p + "*", false);
        }
      }
  };

  // Register an ESTABLISHED outbound peer link: hello + replay of every
  // current local interest (the interest-scoped subscriptions).
  auto arm_peer_link = [&](PeerSlot& slot, int fd) {
    set_nonblocking(fd);
    if (sndbuf_kb > 0) {
      int v = sndbuf_kb * 1024;
      setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &v, sizeof(v));
    }
    auto c = std::make_unique<Client>(fd);
    c->peer_id = "busd-s" + std::to_string(slot.shard);
    c->is_peer = true;
    c->fast = true;  // both ends are relay1 by construction
    c->peer_shard = slot.shard;
    Client& ref = *c;
    clients.emplace(fd, std::move(c));
    slot.fd = fd;
    slot.backoff_ms = 0;
    Json hello;
    Json caps;
    caps.push_back(Json("relay1"));
    caps.push_back(Json("peer1"));
    hello.set("op", "hello")
        .set("peer_id", my_peer_id)
        .set("caps", caps)
        .set("shard", static_cast<int64_t>(my_shard));
    peer_send(ref, fd, hello.dump());
    for (const auto& [t, refs] : local_exact_refs)
      if (refs > 0) {
        Json j;
        j.set("op", "sub").set("topic", t);
        peer_send(ref, fd, j.dump());
      }
    for (const auto& [p, refs] : local_prefix_refs)
      if (refs > 0) {
        Json j;
        j.set("op", "sub").set("topic", p + "*");
        peer_send(ref, fd, j.dump());
      }
    metrics_count("bus.peer_connects");
    log_info("🔗 peer link up to shard %d (port %u)\n", slot.shard,
             slot.port);
  };

  // Backoff-paced outbound peering maintenance.  Dials are nonblocking
  // — connect() returns EINPROGRESS and completion is observed via
  // POLLOUT + SO_ERROR on later wakeups (the pending fd rides the main
  // poll set), so an unreachable peer host can never freeze the relay
  // loop; a dead shard degrades its topics, not the pool.
  auto peer_dial_failed = [&](PeerSlot& slot, int64_t now) {
    slot.backoff_ms = slot.backoff_ms
                          ? std::min<int64_t>(slot.backoff_ms * 2, 4000)
                          : 250;
    slot.next_attempt_ms = now + slot.backoff_ms;
  };
  auto maintain_peer_links = [&]() {
    int64_t now = mono_ms();
    for (auto& slot : peer_slots) {
      if (slot.fd >= 0) continue;
      if (slot.pending_fd >= 0) {
        // connect in flight: zero-timeout progress check
        pollfd p{slot.pending_fd, POLLOUT, 0};
        if (poll(&p, 1, 0) > 0 &&
            (p.revents & (POLLOUT | POLLERR | POLLHUP))) {
          int err = 0;
          socklen_t len = sizeof(err);
          getsockopt(slot.pending_fd, SOL_SOCKET, SO_ERROR, &err, &len);
          const int fd = slot.pending_fd;
          slot.pending_fd = -1;
          if (err == 0) {
            int one = 1;
            setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            arm_peer_link(slot, fd);
          } else {
            close(fd);
            peer_dial_failed(slot, now);
          }
        } else if (now - slot.pending_since_ms > 1000) {
          close(slot.pending_fd);
          slot.pending_fd = -1;
          peer_dial_failed(slot, now);
        }
        continue;
      }
      if (now < slot.next_attempt_ms) continue;
      int fd = socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) {
        peer_dial_failed(slot, now);
        continue;
      }
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(slot.port);
      if (inet_pton(AF_INET, peer_host.c_str(), &addr.sin_addr) != 1) {
        close(fd);
        peer_dial_failed(slot, now);
        continue;
      }
      set_nonblocking(fd);
      int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr),
                       sizeof(addr));
      if (rc == 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        arm_peer_link(slot, fd);
      } else if (errno == EINPROGRESS) {
        slot.pending_fd = fd;
        slot.pending_since_ms = now;
      } else {
        close(fd);
        peer_dial_failed(slot, now);
      }
    }
  };

  while (!g_stop) {
    maintain_peer_links();
    std::vector<pollfd> pfds;
    pfds.push_back({listen_fd, POLLIN, 0});
    for (auto& [fd, c] : clients) {
      short ev = POLLIN;
      if (c->out_bytes > 0) ev |= POLLOUT;
      pfds.push_back({fd, ev, 0});
    }
    // in-flight peer dials: their completion wakes the loop (the
    // per-client processing below skips fds not in the clients map)
    for (const auto& slot : peer_slots)
      if (slot.pending_fd >= 0)
        pfds.push_back({slot.pending_fd, POLLOUT, 0});
    // shm lanes: spin-then-park.  A lane with frames already waiting
    // forces a zero-timeout poll (spin); an idle lane keeps spinning
    // within --shm-spin-us of its last frame; past the budget we park —
    // set the ring's parked flag (re-checking for the race) and let the
    // client's doorbell FIFO wake us through the poll set.
    int timeout_ms = 1000;
    const int64_t now_us = std::chrono::duration_cast<std::chrono::microseconds>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
    for (auto& [fd, c] : clients) {
      if (!c->lane.valid()) continue;
      if (c->lane.rx_pending()) {
        c->lane_busy_us = now_us;
        c->lane_parked = false;
        timeout_ms = 0;
      } else if (shm_spin_us > 0 &&
                 now_us - c->lane_busy_us < shm_spin_us) {
        // idle-spin budget not yet spent: stay hot, no park flag
        c->lane_parked = false;
        timeout_ms = 0;
      } else if (!c->lane.rx.reader_park()) {
        // a writer slipped a frame in during the park race: stay hot
        c->lane_busy_us = now_us;
        c->lane_parked = false;
        timeout_ms = 0;
      } else {
        if (!c->lane_parked) {
          c->lane_parked = true;
          metrics_count("bus.shm_parks");
        }
        if (c->lane.bell_rx_fd >= 0)
          pfds.push_back({c->lane.bell_rx_fd, POLLIN, 0});
      }
    }
    // a pending agg window bounds the sleep to its flush deadline
    if (timeout_ms > 0 && !agg_pending.empty()) {
      int64_t next = INT64_MAX;
      for (const auto& [t, p] : agg_pending)
        next = std::min(next, p.first_ms + agg_ms);
      const int64_t wait = next - mono_ms();
      timeout_ms = static_cast<int>(
          std::max<int64_t>(0, std::min<int64_t>(wait, timeout_ms)));
    }
    int rc = poll(pfds.data(), pfds.size(), timeout_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    maybe_beacon();

    // drain the client->hub rings (unpark first so writers stop ringing;
    // per-lane budget so one firehose lane cannot starve the rest)
    for (auto& [fd, c] : clients) {
      if (!c->lane.valid()) continue;
      c->lane.rx.reader_unpark();
      c->lane.drain_bell();
      std::string frame;
      int budget = 4096;
      for (; budget > 0 && c->lane.recv(&frame); --budget)
        if (!frame.empty() && frame[0] == 'P')
          handle_fast_pub(*c, fd, frame, true);
      if (budget < 4096) {
        // frames arrived: restart the idle-spin budget from now
        c->lane_busy_us = now_us;
        c->lane_parked = false;
      }
    }
    flush_aggs();

    // accept new connections
    if (pfds[0].revents & POLLIN) {
      while (true) {
        int cfd = accept(listen_fd, nullptr, nullptr);
        if (cfd < 0) break;
        set_nonblocking(cfd);
        if (sndbuf_kb > 0) {
          int v = sndbuf_kb * 1024;
          setsockopt(cfd, SOL_SOCKET, SO_SNDBUF, &v, sizeof(v));
        }
        clients.emplace(cfd, std::make_unique<Client>(cfd));
      }
    }

    std::vector<int> dead;
    for (size_t k = 1; k < pfds.size(); ++k) {
      int fd = pfds[k].fd;
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      Client& c = *it->second;
      bool ok = true;
      bool closing = false;  // disconnect AFTER draining buffered lines
      const char* why = "";
      if (pfds[k].revents & (POLLERR | POLLHUP)) {
        closing = true;
        why = "pollerr/hup";
        // poll() sets no errno for revents; fetch the socket's own error
        // so the drop diagnostic doesn't print a stale one
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        errno = soerr;
      }
      if (pfds[k].revents & POLLIN) {
        if (!c.conn.on_readable()) {
          closing = true;
          why = "read-eof/err";
        }
      }
      // A publish-then-close burst lands data and FIN in one read: the
      // complete lines already buffered are valid frames and MUST relay
      // before the disconnect is honored (a quitting chat peer's last
      // message used to vanish when the hub saw the EOF in the same
      // wakeup — the pub-then-close race, now deterministic in tests).
      while (ok) {
        auto line = c.conn.next_line();
        if (!line) break;
        if (!line->empty() && (*line)[0] == 'P') {
          handle_fast_pub(c, fd, *line, false);
          continue;
        }
        if (!line->empty() && (*line)[0] == 'M' && c.is_peer) {
          // peer-forwarded frame: `M<topic> <from> <payload>` — the
          // remote shard's delivery of a frame some local client here
          // subscribed to.  The ORIGINAL sender rides in <from>; relay
          // to LOCAL clients only (the one-hop loop-prevention rule).
          size_t s1 = line->find(' ');
          size_t s2 = s1 == std::string::npos ? std::string::npos
                                              : line->find(' ', s1 + 1);
          if (s2 == std::string::npos || s1 < 2) continue;
          const std::string topic = line->substr(1, s1 - 1);
          const std::string from = line->substr(s1 + 1, s2 - s1 - 1);
          const std::string raw = line->substr(s2 + 1);
          metrics_count("bus.peer_rx_msgs");
          metrics_count("bus.peer_rx_bytes",
                        static_cast<double>(line->size() + 1));
          ingress_pub(topic, from, raw, fd, true);
          continue;
        }
        auto parsed = Json::parse(*line);
        if (!parsed || !parsed->is_object()) continue;  // ignore garbage
        const Json& j = *parsed;
        const std::string& op = j["op"].as_str();
        if (op == "hello") {
          c.peer_id = j["peer_id"].as_str();
          bool wants_shm = false;
          for (const auto& cap : j["caps"].as_array()) {
            if (cap.as_str() == "relay1") c.fast = true;
            if (cap.as_str() == "shard1") c.shard1 = true;
            if (cap.as_str() == "shm1") wants_shm = true;
            if (cap.as_str() == "agg1") c.agg1 = agg_ms > 0;
            if (cap.as_str() == "peer1" && num_shards > 1) {
              // inbound peering link from a higher-index shard
              c.is_peer = true;
              c.peer_shard = static_cast<int>(j["shard"].as_int());
            }
          }
          if (c.agg1) ++agg1_subs;
          // shm lane offer: attach the client-created ring file; the
          // "shm1" welcome echo is the client's signal the lane is live.
          // Any malformed offer is refused (logged), never fatal.
          if (wants_shm && shm_ok && !c.is_peer && c.fast) {
            const std::string lane_path = j["shm"]["path"].as_str();
            std::string err;
            if (!lane_path.empty()) c.lane = shm::Lane::attach(lane_path, &err);
            if (c.lane.valid()) {
              // the idle-spin budget counts from attach, so a fresh
              // lane is not charged a park before its first frame
              c.lane_busy_us =
                  std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::steady_clock::now().time_since_epoch())
                      .count();
              metrics_count("bus.shm_attaches");
              log_info("🧵 shm lane up for %s (%s)\n", c.peer_id.c_str(),
                       lane_path.c_str());
            } else {
              log_warn("shm lane refused for %s: %s\n", c.peer_id.c_str(),
                       err.c_str());
            }
          }
          event_emit(c.is_peer ? "bus.peer_link_joined" : "bus.peer_joined",
                     nullptr, -1, c.peer_id);
          Json caps;
          caps.push_back(Json("relay1"));
          if (num_shards > 1) caps.push_back(Json("peer1"));
          if (c.lane.valid()) caps.push_back(Json("shm1"));
          if (c.agg1) caps.push_back(Json("agg1"));
          Json welcome;
          welcome.set("op", "welcome")
              .set("peer_id", c.peer_id)
              .set("caps", caps);
          if (num_shards > 1)
            welcome.set("shard", static_cast<int64_t>(my_shard))
                .set("shards", static_cast<int64_t>(num_shards));
          enqueue(c, fd, std::make_shared<const std::string>(
                             welcome.dump() + "\n"), false);
          if (c.is_peer) {
            // the responder side never initiates, so it replays ITS
            // local interests over the new link right away (the mirror
            // of arm_peer_link on the initiator side)
            metrics_count("bus.peer_accepts");
            for (const auto& [t, refs] : local_exact_refs)
              if (refs > 0) {
                Json s;
                s.set("op", "sub").set("topic", t);
                peer_send(c, fd, s.dump());
              }
            for (const auto& [p, refs] : local_prefix_refs)
              if (refs > 0) {
                Json s;
                s.set("op", "sub").set("topic", p + "*");
                peer_send(c, fd, s.dump());
              }
          }
        } else if (op == "sub") {
          const std::string& topic = j["topic"].as_str();
          if (topic.size() > 2 &&
              topic.compare(topic.size() - 2, 2, ".*") == 0) {
            // wildcard: subscribe every topic under the prefix (managers'
            // "mapd.pos.*"); no peer_joined — prefix consumers are
            // infrastructure, not discoverable fleet members
            const std::string prefix = topic.substr(0, topic.size() - 1);
            if (c.prefixes.insert(prefix).second) {
              subs_prefix.emplace_back(prefix, fd);
              const bool span =
                  c.shard1 && num_shards > 1 &&
                  shardmap::shards_for_subscription(topic, num_shards)
                          .size() > 1;
              if (span) c.span_prefixes.insert(prefix);
              // span subscribers receive at every SOURCE shard (they
              // subscribed there themselves), so they are NOT local
              // interest for peering — counting them would pull the
              // whole cross-shard stream here just to discard it at
              // delivery (the span-suppression rule)
              if (!c.is_peer && !span && ++local_prefix_refs[prefix] == 1)
                peers_sub(topic, true);
            }
          } else if (c.topics.insert(topic).second) {
            subs_exact[topic].insert(fd);
            if (!c.is_peer) {
              if (++local_exact_refs[topic] == 1) peers_sub(topic, true);
              Json joined;  // discovery event, like an mDNS "discovered"
              joined.set("op", "peer_joined")
                  .set("peer_id", c.peer_id)
                  .set("topic", topic);
              broadcast_control(joined, topic, fd);
            }
          }
        } else if (op == "unsub") {
          const std::string& topic = j["topic"].as_str();
          if (topic.size() > 2 &&
              topic.compare(topic.size() - 2, 2, ".*") == 0) {
            const std::string prefix = topic.substr(0, topic.size() - 1);
            if (c.prefixes.erase(prefix)) {
              const bool was_span = c.span_prefixes.erase(prefix) > 0;
              if (!c.is_peer && !was_span
                  && --local_prefix_refs[prefix] <= 0) {
                local_prefix_refs.erase(prefix);
                peers_sub(topic, false);
              }
            }
            for (auto pit = subs_prefix.begin(); pit != subs_prefix.end();)
              pit = (pit->second == fd && pit->first == prefix)
                        ? subs_prefix.erase(pit)
                        : std::next(pit);
          } else if (c.topics.erase(topic)) {
            auto ex = subs_exact.find(topic);
            if (ex != subs_exact.end()) {
              ex->second.erase(fd);
              if (ex->second.empty()) subs_exact.erase(ex);
            }
            if (!c.is_peer && --local_exact_refs[topic] <= 0) {
              local_exact_refs.erase(topic);
              peers_sub(topic, false);
            }
          }
        } else if (op == "pub") {
          const std::string& topic = j["topic"].as_str();
          if (drop_left > 0 && !drop_type.empty()
              && j["data"]["type"].as_str() == drop_type) {
            --drop_left;
            log_warn("💉 fault injection: dropped %s frame from %s "
                     "(%lld more)\n", drop_type.c_str(), c.peer_id.c_str(),
                     static_cast<long long>(drop_left));
            continue;
          }
          metrics_count("bus.relay_json_frames");
          ingress_pub(topic, c.peer_id, j["data"].dump(), fd, false);
        } else if (op == "peers") {
          const std::string& topic = j["topic"].as_str();
          Json peers;
          for (auto& [ofd, oc] : clients)
            if (ofd != fd && !oc->is_peer && oc->topics.count(topic) &&
                !oc->peer_id.empty())
              peers.push_back(Json(oc->peer_id));
          if (peers.is_null()) peers = Json(JsonArray{});
          Json reply;
          reply.set("op", "peers").set("topic", topic).set("peers", peers);
          enqueue(c, fd, std::make_shared<const std::string>(
                             reply.dump() + "\n"), false);
        }
      }
      if (closing) ok = false;
      if (ok && c.out_bytes > 0) {
        ok = flush_client(c);
        if (!ok) why = "write-err";
      }
      if (!ok) {
        log_debug("dropping client fd=%d peer=%s (%s, errno=%d)\n", fd,
                  c.peer_id.c_str(), why, errno);
        dead.push_back(fd);
      }
    }

    for (int fd : evict) dead.push_back(fd);
    evict.clear();
    for (int fd : dead) {
      auto it = clients.find(fd);
      if (it == clients.end()) continue;
      std::string peer = it->second->peer_id;
      const bool was_peer_link = it->second->is_peer;
      if (!peer.empty())
        event_emit(was_peer_link ? "bus.peer_link_left" : "bus.peer_left",
                   nullptr, -1, peer);
      drop_subs(fd, *it->second);
      if (it->second->agg1) --agg1_subs;
      if (it->second->lane.valid()) {
        // reap the dead client's ring: mark it torn down (a half-dead
        // writer sharing the mapping stops immediately) and unlink the
        // file + bells so nothing stale survives the session
        it->second->lane.mark_detached();
        it->second->lane.close_lane(true);
        log_info("🧵 shm lane reaped for %s\n", peer.c_str());
      }
      it->second->conn.close_fd();
      clients.erase(it);
      if (was_peer_link) {
        // outbound slot: re-arm the backoff so the link self-heals
        for (auto& slot : peer_slots)
          if (slot.fd == fd) {
            slot.fd = -1;
            slot.backoff_ms = slot.backoff_ms
                                  ? std::min<int64_t>(slot.backoff_ms * 2,
                                                      4000)
                                  : 250;
            slot.next_attempt_ms = mono_ms() + slot.backoff_ms;
          }
        log_warn("🔗 peer link down (%s)\n", peer.c_str());
        continue;  // infrastructure: no peer_left discovery event
      }
      if (!peer.empty()) {
        Json left;  // discovery event, like an mDNS "expired"
        left.set("op", "peer_left").set("peer_id", peer);
        broadcast_control(left, "", -1);
      }
    }
  }

  for (auto& [fd, c] : clients) c->conn.close_fd();
  close(listen_fd);
  log_info("mapd_bus: shut down\n");
  return 0;
}
